"""Multi-replica serving fleet (ISSUE 11): replica death is routine.

PR 10 made *training* recovery a supervised, continuously-fault-injected
subsystem; this module applies the same doctrine to serving. A
:class:`ServingFleet` runs N :class:`ReplicaWorker`\\ s — each one a
:class:`~paddle_tpu.serve.engine.DecodeEngine` +
:class:`~paddle_tpu.serve.scheduler.ContinuousBatchingScheduler` pair —
behind a :class:`~paddle_tpu.serve.router.FleetRouter`, and guarantees
that EVERY submitted request reaches a terminal ``finish_reason``
(``"length"|"eos"|"timeout"|"shed"``) no matter which replica dies,
stalls, or drains mid-flight.

The recovery contract, and how each piece is honest about what a
distributed deployment could actually know:

- **Death is observed, not announced.** A killed replica simply stops
  ticking and heartbeating; the router declares it dead only when its
  heartbeat FILE (the PR-10 ``parallel/multihost`` machinery) goes stale
  past the timeout. Until then its requests wait — exactly the
  detection latency a real fleet pays.
- **Resubmission is a reconcile sweep, keyed by request id.** The fleet
  keeps the assignment table (rid → replica). Every tick it verifies
  each non-terminal request is still held by a live replica that
  actually KNOWS it; orphans (dead/released replica, or a delivery the
  ``drop_submit`` fault ate) are resubmitted to a survivor with the
  GLOBAL rid, the ORIGINAL submit timestamp (deadlines never reset),
  and a bumped ``retries`` count. The abandoned attempt emits a
  ``finish_reason="retried"`` request record — the lineage is in the
  telemetry stream, one terminal record per rid, always.
- **Resubmit is idempotent.** A duplicate delivery (the
  ``duplicate_submit`` fault — an RPC retry racing its original) is
  dropped at the replica boundary because the rid is already known
  there; a completion for a superseded attempt is dropped at collection
  because the fleet request is already terminal or re-homed
  (``stale_completions`` counts both, asserting zero surprise).
- **A stalled replica self-fences.** A replica that stops beating long
  enough to be declared dead (a GC pause, a network partition) finds,
  on waking, that its lease is gone: it evicts every slot, frees its
  blocks, and stays out of service — it never completes a request the
  fleet already re-homed (the Bamboo [R2] zombie rule).
- **Drain is the elastic scale-down path.** ``drain(replica)`` stops
  admission, re-routes the replica's QUEUED requests to survivors,
  lets RUNNING slots finish in place, then releases the replica with
  every block back in its pool — scale-down loses zero requests.

Fault injection rides the PR-10 :class:`~paddle_tpu.train.faults.
FaultSchedule` (``kill_replica_at_tick``, ``stall_replica_at_tick``,
``drop_submit_at``, ``duplicate_submit_at``), so the whole fleet path is
deterministically drilled in CI (``bench.py --fleet-child``) the same
way ``run_resilient`` is.

**Process isolation (ISSUE 13).** ``ServingFleet(replica_mode=
"process")`` promotes each replica to a real child process
(:class:`ProcReplicaWorker`): the engine+scheduler pair lives in
``serve/replica_proc.py``, submit/complete ride the length-prefixed
:mod:`~paddle_tpu.serve.transport` frames, and the child beats the same
PR-10 heartbeat files. The parent's ENTIRE view of a process replica is
files + transport — a SIGKILL, a hang, or a corrupt reply is contained
in the child, observed via heartbeat staleness / per-message timeout /
classified parse errors, and healed by the exact reconcile path the
in-process drills already pin. The in-process SimClock fleet stays the
default and is behaviorally unchanged; elastic capacity on top of
``drain()`` and :meth:`ServingFleet.spawn_replica` is the
:class:`~paddle_tpu.serve.autoscaler.Autoscaler`'s policy loop.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import logging
import os
import random
import signal
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional

from ..obs.fleet_trace import merge_fleet_trace
from ..obs.fleet_trace import save_fleet_trace as _save_fleet_trace
from ..obs.metrics import MetricsHub
from ..obs.slo import SLOMonitor
from ..obs.trace import Tracer
from ..parallel import multihost
from . import transport as transport_lib
from .engine import AdmitProbe
from .kv_cache import blobs_to_pages, pages_to_blobs
from .router import FleetRouter
from .scheduler import ContinuousBatchingScheduler, Request

__all__ = ["ReplicaWorker", "ProcReplicaWorker", "RemoteRequest",
           "FleetRequest", "ServingFleet", "build_proc_spec"]

_log = logging.getLogger("paddle_tpu.serve.fleet")


class ReplicaWorker:
    """One serving replica: engine + scheduler + heartbeat + lifecycle.

    ``state`` machine: ``"live"`` → (``drain``) → ``"draining"`` →
    ``"released"``; any non-released state → ``"dead"`` (set ONLY by the
    router's heartbeat verdict). ``killed`` and ``stall`` are fault-
    injection flags beneath the state machine — they change what the
    replica *does* (nothing), not what the fleet *knows* (that takes a
    stale heartbeat)."""

    def __init__(self, replica_id: int, engine, scheduler, root: str,
                 role: str = "both"):
        self.replica_id = int(replica_id)
        self.engine = engine
        self.scheduler = scheduler
        self.root = root
        # disaggregation role (ISSUE 18): "prefill"|"decode"|"both".
        # The router filters placement on it; "both" is the colocated
        # default and serves everything.
        self.role = role
        self.state = "live"
        self.killed = False
        self._stall_until: Optional[int] = None
        self._fenced = False
        self.known: set = set()           # rids actually delivered here
        self._collected = 0               # scheduler.completed cursor
        self._hb_seq = 0
        # per-replica Tracer (ISSUE 17): the fleet installs one when
        # tracing is on; spans drain into the merged fleet trace each
        # tick — the in-process twin of the child's span-batch shipping
        self.tracer = None

    # -- fault hooks -------------------------------------------------------

    def kill(self) -> None:
        """Process death: no more ticks, no more beats. The engine's
        blocks die with it (a real process loses its HBM); survivors'
        pools are untouched."""
        self.killed = True

    def stall(self, until_tick: int) -> None:
        """Hang (GC pause / partition) until the fleet tick index
        ``until_tick``: no work, no beats — but unlike ``kill``, the
        replica may wake, and must then self-fence if its lease died."""
        self._stall_until = int(until_tick)

    def stalled(self, tick: int) -> bool:
        return self._stall_until is not None and tick < self._stall_until

    def sigkill(self) -> None:
        """The process-level kill point (``sigkill_replica_at_tick``)
        degrades to the abstract kill for an in-process worker — the
        same schedule drills both replica modes."""
        self.kill()

    # -- the worker seam (shared with ProcReplicaWorker) -------------------

    def join(self, now: float) -> None:
        """Join the fleet: first heartbeat (the process worker's
        blocking hello handshake lands here)."""
        self.beat(now)

    def deliver(self, fr: "FleetRequest",
                now: float) -> Optional[Request]:
        """Hand one fleet request to this replica's scheduler; returns
        the replica-side attempt (None = delivery failed, the reconcile
        sweep re-homes it — in-process delivery cannot fail)."""
        return self.scheduler.submit(
            fr.prompt, fr.max_new_tokens, eos_id=fr.eos_id,
            deadline_s=fr.deadline_s, priority=fr.priority, rid=fr.rid,
            submit_ts=fr.submit_ts, retries=fr.retries)

    def begin_drain(self, now: float) -> List[int]:
        """Stop admitting and surrender the QUEUED (never-admitted)
        requests: returns their rids for the fleet to resubmit (their
        ``local`` attempts stay referenced for the retried-lineage
        record). Running slots finish in place."""
        rids = []
        for local in list(self.scheduler.queue):
            self.scheduler.queue.remove(local)
            self.known.discard(local.rid)
            rids.append(local.rid)
        return rids

    def cancel_drain(self) -> None:
        """Drain cancelled (the raced-capacity yield): nothing to undo
        in-process — admission gating lives in the router's state
        check."""

    def idle(self) -> bool:
        """Nothing queued, running, or prefilling — the drain-release
        condition."""
        return not (self.scheduler.running or self.scheduler.prefilling
                    or self.scheduler.queue)

    def orphan_count(self) -> int:
        return (len(self.scheduler.queue) + len(self.scheduler.running)
                + len(self.scheduler.prefilling))

    def on_declared_dead(self) -> None:
        """Hook run when the router's heartbeat verdict lands. The
        in-process zombie fence stays in :meth:`tick` (a stalled worker
        must fence itself on WAKE); process workers fence by kill."""

    def shutdown(self) -> None:
        """Release-path teardown (a no-op for an in-process object)."""

    def transport_stats(self) -> Optional[Dict[str, int]]:
        return None

    def pop_handoffs(self) -> List[Dict[str, Any]]:
        """Drain finished prefills awaiting transfer, SERIALIZED to the
        wire format even in-process — the wire-byte accounting (and the
        bit-identity claim: decode adopts exactly the bytes that would
        cross a socket) must not depend on replica mode."""
        out = []
        for req, meta, kpages, vpages in self.scheduler.pop_handoffs():
            blobs = pages_to_blobs(kpages, vpages)
            out.append({"rid": req.rid, "meta": meta, "blobs": blobs})
        return out

    def adopt(self, fr: "FleetRequest", pkg: Dict[str, Any],
              now: float) -> Optional[Request]:
        """Decode-side adoption of a streamed prefill package; None =
        can't take it yet (no slot / pool backpressure)."""
        cache = self.engine.cache
        kpages, vpages = blobs_to_pages(
            pkg["blobs"], num_layers=cache.num_layers,
            block_size=cache.block_size, num_heads=cache.num_heads,
            head_dim=cache.head_dim, quantized=cache.quantized,
            dtype=cache.dtype)
        return self.scheduler.adopt(pkg["meta"], kpages, vpages)

    def drain_spans(self) -> List[Dict[str, Any]]:
        """Pop this replica's buffered trace events for the fleet-level
        merge (empty when tracing is off)."""
        if self.tracer is None:
            return []
        return self.tracer.drain_events()

    def drain_metrics(self) -> List[Dict[str, Any]]:
        """Registry deltas to absorb at fleet level — always empty
        in-process: the engine/scheduler write the parent hub directly
        through their ``replica=<i>``-scoped handles (ISSUE 19)."""
        return []

    # -- liveness ----------------------------------------------------------

    def beat(self, now: float) -> None:
        self._hb_seq += 1
        multihost.write_heartbeat(
            self.root, host_id=self.replica_id, seq=self._hb_seq, now=now,
            extra={"role": "serving-replica",
                   # the shared load payload (scheduler.load_report) +
                   # the tick-time EMA: the autoscaler's sensors, and
                   # the same schema a process replica's child beats —
                   # a cross-process router balances on the exact
                   # evidence it health-checks
                   **self.scheduler.load_report(),
                   "est_tick_s": self.scheduler.est_tick_s,
                   "free_blocks": self.engine.cache.free_blocks,
                   "free_slots": len(self.engine.free_slots()),
                   # the prefix-locality payoff rides the beat too
                   "prefix_hit_blocks": self.engine.cache.prefix_hit_blocks})

    def reset(self) -> None:
        """Self-fence: evict every slot (blocks back to the pool), drop
        all bookkeeping. Run by a replica that wakes from a stall to
        find itself declared dead — its requests live elsewhere now."""
        for slot in list(self.scheduler.running):
            self.engine.evict(slot)
        for slot in list(self.scheduler.prefilling):
            self.engine.evict(slot)
        self.scheduler.running.clear()
        self.scheduler.prefilling.clear()
        self.scheduler.queue.clear()
        self.scheduler.handoffs.clear()
        self.known.clear()

    def tick(self, now: float, tick_idx: int) -> None:
        """One replica tick: step the scheduler, then beat. Killed,
        released and stalled replicas do nothing; a dead one that can
        still run (a woken zombie) fences itself exactly once."""
        if self.killed or self.state == "released":
            return
        if self.state == "dead":
            if not self._fenced and not self.stalled(tick_idx):
                _log.warning("replica %d woke fenced (lease lost): "
                             "resetting", self.replica_id)
                self.reset()
                self._fenced = True
            return
        if self.stalled(tick_idx):
            return
        self.scheduler.step()
        self.beat(now)


@dataclasses.dataclass
class FleetRequest:
    """One fleet-level request: the global identity (``rid``), the SLO
    fields, the current assignment, and the resubmission lineage. The
    terminal request record (the one non-"retried" telemetry record for
    this rid) lands in ``record``."""
    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int]
    deadline_s: Optional[float]
    priority: int
    session_id: Optional[int]
    submit_ts: float
    replica: Optional[int] = None
    retries: int = 0
    attempts: List[int] = dataclasses.field(default_factory=list)
    local: Optional[Request] = None       # current replica-side attempt
    record: Optional[Dict[str, Any]] = None

    @property
    def done(self) -> bool:
        return self.record is not None

    @property
    def finish_reason(self) -> Optional[str]:
        return self.record["finish_reason"] if self.record else None

    @property
    def tokens(self) -> List[int]:
        return list(self.local.tokens) if self.local is not None else []


@dataclasses.dataclass
class RemoteRequest(Request):
    """Parent-side mirror of a request delivered to a subprocess
    replica: identity + SLO fields are enough for the retried-lineage
    record (the fleet stamps ``finish_reason="retried"`` and emits
    :meth:`record` exactly as in-process); once the child's completion
    arrives, the CHILD's terminal record is returned verbatim — one
    schema, authored where the work actually ran."""
    child_record: Optional[Dict[str, Any]] = None

    def record(self) -> Dict[str, Any]:
        if (self.child_record is not None
                and self.finish_reason != "retried"):
            return dict(self.child_record)
        return super().record()


class _RemoteSchedulerView:
    """The router/fleet-facing load view of a subprocess replica's
    scheduler. The parent never holds the child's real queue — only the
    evidence the child last reported (heartbeat payloads and tick
    replies), which is exactly what a cross-host router could know."""

    def __init__(self):
        self.max_slots = 1
        self.est_tick_s: Optional[float] = None
        self._pending = 0
        self._prefill_backlog = 0
        self.queue: List[int] = []          # rids, as last reported
        self.running: List[int] = []
        self.prefilling: List[int] = []
        self.completed: List[RemoteRequest] = []
        self.by_rid: Dict[int, RemoteRequest] = {}

    def update(self, load: Dict[str, Any]) -> None:
        self._pending = int(load.get("pending_new_tokens") or 0)
        self._prefill_backlog = int(load.get("prefill_backlog") or 0)
        self.queue = list(load.get("queued_rids") or ())
        self.running = list(load.get("running_rids") or ())
        self.prefilling = list(load.get("prefilling_rids") or ())
        if load.get("est_tick_s") is not None:
            self.est_tick_s = float(load["est_tick_s"])

    def pending_new_tokens(self) -> int:
        return self._pending

    def prefill_backlog(self) -> int:
        return self._prefill_backlog

    def predicted_completion_s(self, max_new_tokens: int
                               ) -> Optional[float]:
        # the ContinuousBatchingScheduler model, over reported evidence
        if self.est_tick_s is None:
            return None
        ticks = (self._pending / max(1, self.max_slots)
                 + max_new_tokens)
        return ticks * self.est_tick_s


class _RemoteEngineView:
    """Engine facade over hello/heartbeat/tick-reply evidence: geometry
    is static (the hello handshake), occupancy is the last report. The
    router's ``admit_probe`` runs the real probe's never-clears-first
    rules against that evidence."""

    def __init__(self):
        self.cache = self       # the fleet reads w.engine.cache.<field>
        self.context_width = 0
        self.max_slots = 1
        self.block_size = 1
        self.num_blocks = 2
        self.free_blocks = 1
        self.free_slots_reported = 1
        self.prefix_hit_blocks = 0
        self.cow_forks = 0
        self.ticks = 0
        self._compile_counts: Dict[str, int] = {}

    def set_geometry(self, hello: Dict[str, Any]) -> None:
        self.context_width = int(hello["context_width"])
        self.max_slots = int(hello["max_slots"])
        self.block_size = int(hello["block_size"])
        self.num_blocks = int(hello["num_blocks"])
        self.free_blocks = self.num_blocks - 1      # null block reserved
        self.free_slots_reported = self.max_slots

    def update(self, load: Dict[str, Any]) -> None:
        if load.get("free_blocks") is not None:
            self.free_blocks = int(load["free_blocks"])
        if load.get("free_slots") is not None:
            self.free_slots_reported = int(load["free_slots"])
        self.ticks = int(load.get("engine_ticks") or self.ticks)
        self.prefix_hit_blocks = int(load.get("prefix_hit_blocks")
                                     or self.prefix_hit_blocks)
        self.cow_forks = int(load.get("cow_forks") or self.cow_forks)
        if load.get("compile_counts"):
            self._compile_counts = dict(load["compile_counts"])

    def blocks_needed(self, length: int) -> int:
        return max(1, -(-int(length) // self.block_size))

    def compile_counts(self) -> Dict[str, int]:
        return dict(self._compile_counts)

    def admit_probe(self, total_len: int,
                    include_slots: bool = True) -> AdmitProbe:
        need = self.blocks_needed(total_len)
        if total_len > self.context_width:
            reason = "width"
        elif include_slots and self.free_slots_reported == 0:
            reason = "slots"
        elif need > self.free_blocks:
            reason = "blocks"
        else:
            reason = None
        return AdmitProbe(ok=reason is None, reason=reason,
                          blocks_needed=need,
                          free_blocks=self.free_blocks,
                          free_slots=self.free_slots_reported)


class ProcReplicaWorker:
    """One serving replica living in its OWN process (ISSUE 13).

    The parent's entire view of this replica is heartbeat FILES plus the
    seq-numbered submit/complete transport — the same worker seam
    :class:`ReplicaWorker` implements in-process, so the router, the
    reconcile sweep, drain, and the autoscaler are mode-blind:

    - a SIGKILL/OOM/segfault in the child stops the beats; the router
      observes staleness and the fleet re-homes the requests — the
      router process never crashes;
    - a hung child (or a lost reply) surfaces as the per-message
      timeout; bounded retransmits recover a lost REPLY from the
      child's seq cache, and exhausted retries quarantine the transport
      (``transport_down``) while the heartbeat verdict decides;
    - a garbled reply is a CLASSIFIED :class:`~paddle_tpu.serve.
      transport.TransportCorrupt`, counted and retried, never an
      exception through the fleet tick;
    - declared-dead process replicas are fenced BY KILL — the
      definitive form of the PR-11 zombie self-fence (a process that
      no longer exists cannot complete a re-homed request).
    """

    is_process = True

    def __init__(self, replica_id: int, spec: Dict[str, Any], root: str,
                 *, faults=None, telemetry=None, timeout_s: float = 2.0,
                 spawn_timeout_s: float = 300.0, stderr=None,
                 mode: str = "process", role: str = "both",
                 chaos=None):
        self.replica_id = int(replica_id)
        self.root = root
        self.state = "live"
        self.killed = False
        self.role = role
        self._stall_until: Optional[int] = None
        self.known: set = set()
        self._collected = 0
        self.faults = faults
        self.telemetry = telemetry
        self.scheduler = _RemoteSchedulerView()
        self.engine = _RemoteEngineView()
        self.transport_down = False
        self.transport_errors = 0
        self._mode = mode
        # the epoch lease (ISSUE 20): granted by the fleet before the
        # hello, bumped on declare-dead. Every op is stamped with it;
        # every reply from a different epoch is discarded wholesale.
        self.lease_epoch = 0
        self.revoked_epoch: Optional[int] = None
        self.fence_reply: Optional[Dict[str, Any]] = None
        self.readmit_info: Optional[Dict[str, Any]] = None
        self.stale_epoch_replies = 0
        self.stale_metric_deltas = 0
        self.readmits = 0
        # readmit probing state (socket mode): capped exponential tick
        # backoff with seeded jitter, so a healed partition doesn't see
        # every fenced replica probed on the same tick
        self._fenced_tick: Optional[int] = None
        self._fenced_at: Optional[float] = None
        self._readmit_attempts = 0
        self._next_readmit_tick = 0
        self._readmit_rng = random.Random(0xFE0CE + self.replica_id)
        # trace events shipped piggybacked on tick replies (ISSUE 17),
        # buffered here until the fleet's per-tick span drain
        self._spans: List[Dict[str, Any]] = []
        # registry deltas shipped the same way (ISSUE 19), buffered
        # until the fleet's per-tick absorb sweep
        self._metrics_deltas: List[Dict[str, Any]] = []
        # KV-page handoff packages shipped on tick replies (ISSUE 18),
        # buffered until the fleet's per-tick handoff sweep
        self._handoffs: List[Dict[str, Any]] = []
        self._spawn_timeout_s = float(spawn_timeout_s)
        spec = dict(spec, replica_id=self.replica_id, root=root)
        if role != "both":
            spec["role"] = role
        if mode == "socket":
            # socket transport (ISSUE 18): listen first, THEN spawn —
            # the child dials on startup. Loopback here; a remote host
            # runs the same child by hand against a routable listener.
            srv = transport_lib.listen()
            host, port = srv.getsockname()
            proc = transport_lib.spawn_replica_process(
                spec, stderr=stderr, connect=f"{host}:{port}")
            try:
                sock, _ = transport_lib.accept_connection(
                    srv, timeout_s=self._spawn_timeout_s)
            except transport_lib.TransportError:
                if proc.poll() is None:
                    proc.kill()
                raise
            finally:
                srv.close()
            reader: Any = transport_lib.SocketFrameReader(sock)
            writer: Any = transport_lib.SocketWriter(sock)
            if chaos is not None and chaos.link(self.replica_id) \
                    is not None:
                # the chaos plane (ISSUE 20) sits at the frame seam:
                # impairments are enacted on real wire bytes, so every
                # pathology surfaces through the real timeout →
                # retransmit → transport_down → heartbeat chain
                from .chaos import ChaosFrameReader
                reader = ChaosFrameReader(sock, chaos, self.replica_id)
                writer = chaos.wrap_writer(self.replica_id, writer)
            self.transport = transport_lib.ReplicaTransport(
                reader, writer, proc=proc, timeout_s=timeout_s,
                backoff_seed=self.replica_id)
        else:
            proc = transport_lib.spawn_replica_process(spec,
                                                       stderr=stderr)
            self.transport = transport_lib.ReplicaTransport(
                proc.stdout, proc.stdin, proc=proc, timeout_s=timeout_s,
                backoff_seed=self.replica_id)

    @property
    def pid(self) -> Optional[int]:
        return self.transport.pid

    def _emit(self, rec: Dict[str, Any]) -> None:
        if self.telemetry is not None:
            self.telemetry.emit_event(rec)

    def _transport_error(self, op: str, err) -> None:
        self.transport_errors += 1
        m = self.transport.metrics
        if m is not None:
            # same site as the attribute counter, so the registry and
            # fleet.stats() totals agree by construction (satellite 2)
            m.counter("transport_errors",
                      "exhausted-retry transport failures").inc()
        kind = getattr(err, "kind", "error")
        _log.warning("replica %d transport %s on %s: %s",
                     self.replica_id, kind, op, err)
        self._emit({"kind": "transport", "event": kind,
                    "replica": self.replica_id, "op": op})
        # every retransmit already failed by the time we get here: stop
        # talking to this replica (no per-tick timeout stalls while a
        # corpse rots) and let the heartbeat verdict make the call
        self.transport_down = True

    def _request(self, op: str, **kw) -> Dict[str, Any]:
        """Every op stamped with this worker's lease epoch (ISSUE 20) —
        the wire half of the fence. A worker never granted an epoch
        (legacy drivers) sends unstamped, unchanged."""
        if self.lease_epoch:
            kw.setdefault("epoch", self.lease_epoch)
        return self.transport.request(op, **kw)

    # -- lifecycle ---------------------------------------------------------

    def join(self, now: float) -> None:
        """Blocking hello handshake: waits for the child to finish its
        jax bring-up, records the engine geometry, and confirms the
        first heartbeat landed (the child beats on hello). The hello is
        also the lease GRANT: it carries the epoch the fleet issued at
        spawn."""
        reply = self._request(
            "hello", now=now, timeout_s=self._spawn_timeout_s,
            max_attempts=1)
        self.engine.set_geometry(reply)
        self.scheduler.max_slots = self.engine.max_slots
        load = reply.get("load") or {}
        self.scheduler.update(load)
        self.engine.update(load)

    def _terminate(self, sig=signal.SIGKILL) -> None:
        proc = self.transport.proc
        if proc is not None and proc.poll() is None:
            try:
                os.kill(proc.pid, sig)
            except (ProcessLookupError, OSError):
                pass
        self.transport.close()
        if proc is not None:
            try:
                proc.wait(timeout=5.0)
            except Exception:               # still dying; reaped later
                pass

    def kill(self) -> None:
        """REAL process death: SIGKILL. The beats stop on their own —
        the fleet learns nothing until the heartbeat goes stale."""
        self.killed = True
        self._terminate(signal.SIGKILL)

    sigkill = kill

    def stall(self, until_tick: int) -> None:
        """Simulated hang from the FLEET's side of the seam: no tick
        traffic (so no work and no beats) until ``until_tick`` — the
        evidence trail of a hung child, with the child itself healthy."""
        self._stall_until = int(until_tick)

    def stalled(self, tick: int) -> bool:
        return self._stall_until is not None and tick < self._stall_until

    def on_declared_dead(self) -> None:
        """Fence-by-kill: the process analog of the PR-11 zombie
        self-fence. A declared-dead replica whose process still runs (a
        stall, a partition) must never complete a re-homed request —
        SIGKILL makes that structural. This is the PIPE-mode fence
        (same host, so the signal always lands — the strongest fence
        available); socket-mode workers are fenced BY EPOCH instead
        (:meth:`fence`), because a kill signal cannot cross hosts."""
        self._terminate(signal.SIGKILL)

    def fence(self, new_epoch: int, now: float,
              tick_idx: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Epoch fence (ISSUE 20): revoke this worker's lease. The OLD
        epoch becomes invalid the moment the parent adopts the new one
        — every subsequent reply, handoff or metric delta stamped with
        it is discarded, and the child itself rejects ops carrying it —
        so the fence holds even if the revocation NOTICE below never
        arrives (the point of fencing by epoch, not by reachability).
        The notice is one best-effort short-timeout attempt: when the
        send direction is up (asymmetric partition) the child evicts
        its slots immediately instead of at first rejected op."""
        self.revoked_epoch = self.lease_epoch or None
        self.lease_epoch = int(new_epoch)
        self.fence_reply = None
        self.readmit_info = None
        self._fenced_tick = tick_idx
        self._fenced_at = now
        self._readmit_attempts = 0
        self._next_readmit_tick = (tick_idx or 0) + 1
        if (self.transport.closed or self.killed
                or self.transport.proc is None
                or self.transport.proc.poll() is not None):
            return None
        try:
            reply = self._request(
                "fence", now=now, max_attempts=1,
                timeout_s=min(self.transport.timeout_s, 0.5))
        except transport_lib.TransportError:
            return None             # unreachable: the epoch IS the fence
        if reply.get("ok"):
            self.fence_reply = reply.get("fence")
        return self.fence_reply

    def try_readmit(self, new_epoch: int, now: float) -> bool:
        """One readmit probe (partition heal): offer the fenced child a
        FRESH lease (strictly newer than the fence epoch — the child
        rejects a readmit that does not outrank what it holds). On
        success the worker rejoins as an EMPTY live replica —
        parent-side rid bookkeeping is reset, the child already evicted
        everything at fence time, and the reply's fence report
        (tokens_while_fenced, stale_epoch_rejects) is kept as drill
        evidence. A failed probe burns its epoch; the counter is
        monotone, not dense."""
        if (self.transport.closed or self.killed
                or self.transport.proc is None
                or self.transport.proc.poll() is not None):
            return False
        self._readmit_attempts += 1
        try:
            reply = self.transport.request(
                "readmit", epoch=int(new_epoch), now=now,
                max_attempts=1,
                timeout_s=min(self.transport.timeout_s, 0.5))
        except transport_lib.TransportError:
            return False
        if not reply.get("ok"):
            return False
        self.lease_epoch = int(new_epoch)
        self.readmits += 1
        self.readmit_info = {
            "epoch": self.lease_epoch,
            "fence": reply.get("fence"),
            "tokens_while_fenced": reply.get("tokens_while_fenced"),
            "stale_epoch_rejects": reply.get("stale_epoch_rejects")}
        if self.fence_reply is None:
            self.fence_reply = reply.get("fence")
        # clean slate on BOTH sides: the child cleared its rid/dedupe
        # state at fence; any rid we still track for it lives elsewhere
        # now (resubmitted when it was declared dead)
        self.known.clear()
        self.scheduler.by_rid.clear()
        self.state = "live"
        self.transport_down = False
        load = reply.get("load") or {}
        self.scheduler.update(load)
        self.engine.update(load)
        return True

    def shutdown(self) -> None:
        """Graceful stop (release path / fleet teardown): ask the child
        to exit, then make sure."""
        proc = self.transport.proc
        if (not self.transport.closed and not self.transport_down
                and proc is not None and proc.poll() is None):
            try:
                self.transport.request("stop", max_attempts=1)
            except transport_lib.TransportError:
                pass
        self._terminate(signal.SIGKILL)

    # -- the worker seam ---------------------------------------------------

    def deliver(self, fr: "FleetRequest",
                now: float) -> Optional[Request]:
        if self.transport_down:
            return None                 # don't pay timeouts to a corpse
        try:
            reply = self._request(
                "submit", rid=fr.rid, prompt=list(fr.prompt),
                max_new_tokens=fr.max_new_tokens, eos_id=fr.eos_id,
                deadline_s=fr.deadline_s, priority=fr.priority,
                submit_ts=fr.submit_ts, retries=fr.retries, now=now)
        except transport_lib.TransportError as e:
            self._transport_error("submit", e)
            return None
        if not reply.get("ok"):
            return None                 # refused (draining child)
        req = RemoteRequest(
            rid=fr.rid, prompt=list(fr.prompt),
            max_new_tokens=fr.max_new_tokens, eos_id=fr.eos_id,
            deadline_s=fr.deadline_s, priority=fr.priority,
            retries=fr.retries, submit_ts=fr.submit_ts)
        self.scheduler.by_rid[fr.rid] = req
        # optimistic load accounting: the child's shadow view otherwise
        # refreshes only on tick replies, so a burst of submits between
        # ticks would all read this replica at its pre-burst load and
        # pile onto one worker (in-process workers account admission
        # immediately — this keeps socket placement consistent with
        # that). The next real report overwrites the estimate.
        self.scheduler._pending += fr.max_new_tokens
        self.scheduler._prefill_backlog += len(fr.prompt)
        return req

    def tick(self, now: float, tick_idx: int) -> None:
        """One replica tick over the wire: the child steps its
        scheduler, beats, and ships completions + telemetry + load in
        the reply. Transport faults are classified and contained."""
        if (self.killed or self.state in ("released", "dead")
                or self.transport_down or self.stalled(tick_idx)):
            return
        flags = {}
        if self.faults is not None:
            if self.faults.should_hang_transport(tick_idx,
                                                 self.replica_id):
                flags["inject_drop_reply"] = True
            if self.faults.should_corrupt_reply(tick_idx,
                                                self.replica_id):
                flags["inject_corrupt_reply"] = True
        try:
            reply = self._request("tick", now=now,
                                  tick=tick_idx, **flags)
        except transport_lib.TransportError as e:
            self._transport_error("tick", e)
            return
        self._absorb(reply)

    def _absorb(self, reply: Dict[str, Any]) -> None:
        rep_ep = reply.get("epoch")
        if (rep_ep is not None and self.lease_epoch
                and int(rep_ep) != self.lease_epoch):
            # a reply stamped with a revoked lease (ISSUE 20): a
            # fenced-then-superseded child's late work. Discard it
            # WHOLESALE — its completions were resubmitted elsewhere,
            # its load view is of an evicted scheduler, its metric
            # deltas would double-count against the readmitted epoch.
            self.stale_epoch_replies += 1
            return
        load = reply.get("load") or {}
        self.scheduler.update(load)
        self.engine.update(load)
        for ev in reply.get("events") or ():
            self._emit(ev)              # the fleet's ONE telemetry stream
        sp = reply.get("spans")
        if sp:
            self._spans.extend(sp)
        md = reply.get("metrics")
        if md:
            # tagged with the epoch they arrived under: a fence between
            # absorb and the fleet's drain sweep must still kill them
            self._metrics_deltas.append((self.lease_epoch, md))
        for item in reply.get("completed") or ():
            rec = item.get("record") or {}
            rid = rec.get("rid")
            req = self.scheduler.by_rid.pop(rid, None)
            if req is None:             # superseded/unknown: _collect
                req = RemoteRequest(rid=rid, prompt=[],
                                    max_new_tokens=1)
            req.child_record = rec
            req.tokens = list(item.get("tokens") or ())
            req.finish_reason = rec.get("finish_reason")
            req.finish_ts = req.submit_ts   # done marker; truth in rec
            self.scheduler.completed.append(req)
        # KV handoff packages (ISSUE 18): the framed binary payloads
        # landed in reply["blobs"]; each handoff header says how many
        # belong to it. A package only exists here because the WHOLE
        # reply (header + every blob) was absorbed — a child killed
        # mid-transfer never surfaces a partial handoff.
        hoffs = reply.get("handoffs") or ()
        if hoffs:
            blobs = reply.get("blobs") or []
            off = 0
            for h in hoffs:
                nb = int(h.get("nblobs") or 0)
                rid = int(h["rid"])
                self._handoffs.append({
                    "rid": rid, "meta": h["meta"],
                    "blobs": blobs[off:off + nb],
                    # the epoch this package arrived under: the fleet's
                    # handoff sweep discards it if the lease was revoked
                    # before placement (a stale prefill must not be
                    # adopted alongside its resubmitted twin)
                    "epoch": self.lease_epoch})
                off += nb
                # the request now lives between replicas; the child
                # forgot it too, so a later re-delivery must not dedupe
                self.scheduler.by_rid.pop(rid, None)

    def begin_drain(self, now: float) -> List[int]:
        try:
            reply = self._request("drain", now=now)
        except transport_lib.TransportError as e:
            self._transport_error("drain", e)
            return []
        rids = [int(r) for r in reply.get("queued_rids") or ()]
        for rid in rids:
            self.known.discard(rid)
            self.scheduler.by_rid.pop(rid, None)
        self.scheduler.update(reply.get("load") or {})
        return rids

    def cancel_drain(self) -> None:
        """The child refuses submissions while draining; a cancelled
        drain must tell it to admit again."""
        if self.transport_down:
            return
        try:
            self._request("resume")
        except transport_lib.TransportError as e:
            self._transport_error("resume", e)

    def pop_handoffs(self) -> List[Dict[str, Any]]:
        out, self._handoffs = self._handoffs, []
        return out

    def adopt(self, fr: "FleetRequest", pkg: Dict[str, Any],
              now: float) -> Optional[Request]:
        """Ship a finished-prefill package to this (decode) child: one
        "adopt" round with the KV pages as framed binary payloads."""
        if self.transport_down:
            return None
        try:
            reply = self._request(
                "adopt", rid=fr.rid, meta=pkg["meta"],
                blobs=pkg["blobs"], now=now)
        except transport_lib.TransportError as e:
            self._transport_error("adopt", e)
            return None
        if not reply.get("ok"):
            return None                 # refused (capacity/draining)
        meta = pkg["meta"]
        req = RemoteRequest(
            rid=fr.rid, prompt=list(meta["prompt"]),
            max_new_tokens=int(meta["max_new_tokens"]),
            eos_id=meta.get("eos_id"),
            deadline_s=meta.get("deadline_s"),
            priority=int(meta.get("priority") or 0),
            retries=int(meta.get("retries") or 0),
            submit_ts=meta.get("submit_ts"))
        self.scheduler.by_rid[fr.rid] = req
        return req

    def idle(self) -> bool:
        return not (self.scheduler.running or self.scheduler.prefilling
                    or self.scheduler.queue)

    def orphan_count(self) -> int:
        return (len(self.scheduler.queue) + len(self.scheduler.running)
                + len(self.scheduler.prefilling))

    def stats_probe(self, now: float) -> Optional[Dict[str, Any]]:
        """One stats round-trip (the drills' leak/retrace evidence:
        free blocks and compile counts straight from the child)."""
        if (self.transport_down or self.transport.closed
                or self.killed or self.state in ("dead", "released")):
            return None
        try:
            return self._request("stats", now=now)
        except transport_lib.TransportError as e:
            self._transport_error("stats", e)
            return None

    def transport_stats(self) -> Dict[str, int]:
        return {"errors": self.transport_errors,
                "retransmits": self.transport.retransmits,
                "timeouts": self.transport.timeouts,
                "corrupt_replies": self.transport.corrupt_replies}

    def drain_spans(self) -> List[Dict[str, Any]]:
        """Pop the child's shipped trace events (no transport round —
        the spans already rode the tick replies)."""
        sp, self._spans = self._spans, []
        return sp

    def drain_metrics(self) -> List[Dict[str, Any]]:
        """Pop the child's shipped registry deltas (no transport round
        — they already rode the tick replies; deltas a SIGKILL ate
        simply never land here). Deltas tagged with a revoked epoch are
        discarded, not merged (ISSUE 20) — a fenced replica's late
        counters must not pollute the fleet registry."""
        tagged, self._metrics_deltas = self._metrics_deltas, []
        out: List[Dict[str, Any]] = []
        for ep, md in tagged:
            if ep == self.lease_epoch:
                out.extend(md)
            else:
                self.stale_metric_deltas += 1
        return out

    def scrape_metrics(self, now: float) -> Optional[str]:
        """One ``metrics`` op round-trip: the child's full registry as
        Prometheus text exposition. A READ, not a drain — the tick-
        reply delta watermarks are untouched. None when the link is
        down or the child has no registry."""
        if (self.transport_down or self.transport.closed
                or self.killed or self.state in ("dead", "released")):
            return None
        try:
            reply = self._request("metrics", now=now)
        except transport_lib.TransportError as e:
            self._transport_error("metrics", e)
            return None
        if not reply.get("ok"):
            return None
        return reply.get("exposition")


class ServingFleet:
    """N replica workers + a router + the recovery loop (see module
    docstring).

    Args:
      make_engine: ``callable(replica_id) -> DecodeEngine`` — one engine
        per replica (homogeneous capacity assumed for validation).
      n_replicas: fleet width.
      telemetry: shared :class:`~paddle_tpu.obs.Telemetry`; every
        replica's request/evict records and the fleet's shed/replica
        events land in one stream (records carry the GLOBAL rid).
      root: heartbeat directory (a fresh tempdir by default).
      clock: shared injectable clock — heartbeats, deadlines, arrival
        replay and predictions all read it (``SimClock`` for CI).
      heartbeat_timeout_s: staleness after which a replica is dead.
      order / shed / est_tick_s: scheduler admission policy, router
        shedding, and the cold-start tick-time prior (see
        :class:`ContinuousBatchingScheduler`).
      faults: a :class:`~paddle_tpu.train.faults.FaultSchedule` with the
        serving points armed.
      replica_mode: ``"inprocess"`` (default — behaviorally identical
        to PR 11) or ``"process"`` — each replica is a real child
        process behind the submit/complete transport (needs
        ``proc_spec``; use :meth:`from_model`).
      proc_spec: the child-process build spec (:func:`build_proc_spec`):
        model config, engine kwargs, variables npz path.
      transport_timeout_s / spawn_timeout_s: per-message reply timeout
        and the hello-handshake budget (a child pays jax bring-up
        once).
      autoscaler: an :class:`~paddle_tpu.serve.autoscaler.Autoscaler`
        to bind; its policy loop runs inside every fleet tick.
      trace: distributed request tracing (ISSUE 17). The fleet gets a
        router-lane :class:`~paddle_tpu.obs.Tracer` and every replica
        gets its own (a process replica builds one in the child and
        ships span batches back on tick replies); all of them stamp the
        SHARED fleet clock, and :meth:`fleet_trace` merges the lanes
        into one Chrome/Perfetto timeline with ``s``/``t``/``f`` flow
        events linking each rid across processes. Default off —
        tracing off is the byte-identical pre-trace fleet.
      slo: streaming SLO monitoring — ``True`` for a default
        :class:`~paddle_tpu.obs.SLOMonitor`, or a configured instance.
        Every terminal record feeds it; :meth:`slo_report` and the
        ``"slo"`` key of :meth:`stats` surface rolling p50/p95/p99
        TTFT/TPOT and the error-budget burn rate.
      anomaly: a :class:`~paddle_tpu.obs.ServingAnomalyDetector`; the
        fleet feeds it per-tick replica views, terminal records and
        transport counters, and binds fleet evidence sources (live
        heartbeats, the trace tail, transport totals) into its
        forensic bundles.
    """

    def __init__(self, make_engine: Optional[Callable[[int], Any]],
                 n_replicas: int, *, telemetry=None, root: Optional[str]
                 = None, clock=None, heartbeat_timeout_s: float = 3.0,
                 order: str = "fcfs", shed: bool = True,
                 affinity: bool = True,
                 est_tick_s: Optional[float] = None, faults=None,
                 replica_mode: str = "inprocess",
                 proc_spec: Optional[Dict[str, Any]] = None,
                 transport_timeout_s: float = 2.0,
                 spawn_timeout_s: float = 300.0,
                 autoscaler=None, trace: bool = False, slo=None,
                 anomaly=None, roles: Optional[List[str]] = None,
                 metrics=None, chaos=None,
                 death_confirmations: int = 2,
                 lease_timeout_s: Optional[float] = None,
                 degrade_grace_s: Optional[float] = None,
                 readmit_grace_s: Optional[float] = None):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        if replica_mode not in ("inprocess", "process", "socket"):
            raise ValueError(
                f"replica_mode must be 'inprocess'|'process'|'socket', "
                f"got {replica_mode!r}")
        if chaos is not None and replica_mode != "socket":
            # the chaos plane impairs WIRE frames at the socket seam;
            # pipes/in-process have no link to impair — fail loudly
            # rather than run a drill with the chaos silently off
            raise ValueError("chaos requires replica_mode='socket'")
        if replica_mode in ("process", "socket") and proc_spec is None:
            raise ValueError(
                f"replica_mode={replica_mode!r} needs proc_spec — use "
                "ServingFleet.from_model(...) or build_proc_spec()")
        # prefill/decode disaggregation (ISSUE 18): roles[i] is replica
        # i's role; None = every replica serves "both" (the byte-
        # identical colocated fleet). A mixed fleet needs at least one
        # decode-capable replica or handoffs would have nowhere to land.
        if roles is not None:
            roles = list(roles)
            if len(roles) != n_replicas:
                raise ValueError(
                    f"roles has {len(roles)} entries for "
                    f"{n_replicas} replicas")
            bad = [r for r in roles
                   if r not in ("both", "prefill", "decode")]
            if bad:
                raise ValueError(f"invalid role(s) {bad!r}: must be "
                                 f"'both'|'prefill'|'decode'")
            if (any(r == "prefill" for r in roles)
                    and not any(r in ("decode", "both") for r in roles)):
                raise ValueError("a fleet with prefill replicas needs "
                                 "at least one decode-capable replica")
        self._roles = roles
        self.disagg = bool(roles) and any(r == "prefill" for r in roles)
        self.replica_mode = replica_mode
        self.telemetry = telemetry
        self.clock = clock if clock is not None else time.perf_counter
        self.root = root or tempfile.mkdtemp(prefix="paddle_tpu_fleet_")
        self.faults = faults
        self.make_engine = make_engine
        self.order = order
        self.est_tick_s = est_tick_s
        self._proc_spec = dict(proc_spec or {})
        self._transport_timeout_s = float(transport_timeout_s)
        self._spawn_timeout_s = float(spawn_timeout_s)
        # observability (ISSUE 17) — all default-off; the tracer must
        # exist BEFORE the spawn loop (process replicas read the spec's
        # "trace" key at build, transport observers hook at construction)
        self.tracer = Tracer(clock=self.clock) if trace else None
        self._replica_spans: Dict[int, List[Dict[str, Any]]] = \
            collections.defaultdict(list)
        if self.tracer is not None and replica_mode in ("process",
                                                        "socket"):
            self._proc_spec["trace"] = True
        # metrics registry (ISSUE 19) — same doctrine as the tracer:
        # built BEFORE the spawn loop (process replicas read the spec's
        # "metrics" key at build; in-process workers take scoped
        # handles at construction), default-off, byte-identical dark.
        self.metrics = (MetricsHub(clock=self.clock) if metrics is True
                        else (metrics or None))
        if (self.metrics is not None
                and replica_mode in ("process", "socket")):
            self._proc_spec["metrics"] = True
        self.slo = SLOMonitor() if slo is True else (slo or None)
        if self.slo is not None and self.metrics is not None:
            # the SLO monitor publishes its rolling percentiles and
            # burn rate as gauges into the same registry (satellite 3)
            self.slo.metrics = self.metrics
        self.anomaly = anomaly
        # the network chaos plane (ISSUE 20): bound to the fleet clock
        # so partition/flap windows are SimClock-deterministic; wired
        # per link inside _spawn_worker. None = stock reader/writer —
        # byte-identical to the pre-chaos transport.
        self.chaos = chaos
        if chaos is not None:
            chaos.bind(self.clock)
        # epoch leases (ISSUE 20): one fleet-global monotone counter —
        # every grant (spawn, readmit) is a fresh epoch, so "newer
        # epoch" is a total order across the whole membership history.
        # Must exist BEFORE the spawn loop (spawn grants the first
        # epoch; the hello delivers it).
        self._epochs = itertools.count(1)
        if lease_timeout_s is not None:
            # the child-side half of the lease: absent from the spec
            # (and from child behavior) unless explicitly armed
            self._proc_spec["lease_timeout_s"] = float(lease_timeout_s)
        self.workers: List[Any] = []
        for i in range(n_replicas):       # Popen-spawn (or build) all…
            self._spawn_worker(roles[i] if roles else "both")
        self.router = FleetRouter(
            self.workers, self.root,
            heartbeat_timeout_s=heartbeat_timeout_s, clock=self.clock,
            affinity=affinity, shed=shed, tracer=self.tracer,
            death_confirmations=death_confirmations,
            metrics=self.metrics)
        now = self.clock()
        for w in self.workers:            # …then join: children paid
            w.join(now)                   # their jax bring-up in parallel
        self.autoscaler = autoscaler
        if autoscaler is not None:
            autoscaler.bind(self)
        self.requests: Dict[int, FleetRequest] = {}
        # the non-terminal subset, kept separately so the per-tick
        # reconcile/outstanding sweeps are O(in-flight), not
        # O(everything ever submitted); `requests` is the full ledger
        # (prune_terminal() bounds it for long-lived fleets)
        self._active: Dict[int, FleetRequest] = {}
        self._rid = itertools.count()
        self._unplaced: List[FleetRequest] = []
        self.ticks = 0
        self.resubmits = 0
        self.shed_count = 0
        self.duplicates_dropped = 0
        self.stale_completions = 0
        self.arrived_prompt_tokens = 0
        self.arrived_new_tokens = 0
        # handoff ledger (ISSUE 18): rid -> in-flight KV package. A rid
        # here is owned by the FLEET — no replica holds it, so the
        # reconcile sweep must not resubmit it (fr.replica is None).
        self._pending_handoffs: Dict[int, Dict[str, Any]] = {}
        self.handoff_count = 0
        self.handoff_wire_bytes = 0
        self.handoff_blocks = 0
        self.stale_handoffs = 0
        # membership accounting (ISSUE 20)
        self.fences = 0
        self.readmitted = 0
        self.stale_epoch_handoffs = 0
        # partition degradation (ISSUE 20): when a disagg fleet loses
        # every prefill-capable replica for longer than the grace
        # window, decode replicas temporarily serve colocated prefill
        # (slower, not stuck); heal releases it. Grace defaults to two
        # heartbeat timeouts — long enough that an ordinary death +
        # replacement never engages it.
        self.degraded = False
        self.degrade_grace_s = (float(degrade_grace_s)
                                if degrade_grace_s is not None
                                else 2.0 * float(heartbeat_timeout_s))
        self.readmit_grace_s = (float(readmit_grace_s)
                                if readmit_grace_s is not None
                                else 8.0 * float(heartbeat_timeout_s))
        self._prefill_lost_at: Optional[float] = None
        self.degradations = 0
        self.degrade_releases = 0
        # host-side router/reconcile cost (satellite 1): wall seconds
        # (perf_counter, NEVER the injectable clock — SimClock would
        # report zero) accumulated around placement work, bucketed per
        # fleet tick. Submit-path routing lands in the next tick's
        # bucket.
        self._router_cur_s = 0.0
        self._router_tick_s: List[float] = []
        if self.anomaly is not None:
            # bundles capture fleet-level evidence at trigger time:
            # live heartbeats, the merged-trace tail, transport totals
            self.anomaly.bind(tracer=self.tracer)
            self.anomaly.bind_fleet(
                heartbeats=lambda: multihost.read_heartbeats(self.root),
                trace_tail=((lambda: self.fleet_trace(tail=128))
                            if self.tracer is not None else None),
                transport=self._transport_totals)

    # -- replica lifecycle -------------------------------------------------

    def _spawn_worker(self, role: str = "both"):
        """Construct (but do not yet join) replica ``len(workers)`` in
        the active mode. Ids are append-only — a dead/released worker
        stays as a tombstone — so replica id == list index forever."""
        i = len(self.workers)
        if self.replica_mode in ("process", "socket"):
            w = ProcReplicaWorker(
                i, self._proc_spec, self.root, faults=self.faults,
                telemetry=self.telemetry,
                timeout_s=self._transport_timeout_s,
                spawn_timeout_s=self._spawn_timeout_s,
                mode=self.replica_mode, role=role,
                chaos=self.chaos)
            # the lease grant: the hello (join) carries this epoch to
            # the child, every later op is stamped with it
            w.lease_epoch = next(self._epochs)
            if self.tracer is not None:
                # retransmit/timeout/corrupt verdicts land as instants
                # on the ROUTER lane — the child can't see them (a lost
                # reply is invisible to the process that sent it)
                w.transport.on_event = (
                    lambda event, op, _r=i: self.tracer.instant(
                        f"transport_{event}", replica=_r, op=op))
            if self.metrics is not None:
                # per-LINK wire health (bytes/frames/RTT/failures) is a
                # parent-side property of the connection — the child
                # can't measure its own reply loss any more than it can
                # see its own SIGKILL
                w.transport.metrics = self.metrics.scoped(link=str(i))
        else:
            eng = self.make_engine(i)
            wtr = (Tracer(clock=self.clock)
                   if self.tracer is not None else None)
            mets = (self.metrics.scoped(replica=str(i))
                    if self.metrics is not None else None)
            sched = ContinuousBatchingScheduler(
                eng, telemetry=self.telemetry, order=self.order,
                shed=False, est_tick_s=self.est_tick_s, clock=self.clock,
                tracer=wtr, role=role, metrics=mets)
            w = ReplicaWorker(i, eng, sched, self.root, role=role)
            if wtr is not None:
                eng.tracer = wtr
                w.tracer = wtr
            if mets is not None:
                # in-process replicas write the parent hub directly
                # through a replica=<i>-scoped view — the same label
                # namespace absorb_delta gives a process replica
                eng.metrics = mets
        self.workers.append(w)
        return w

    def spawn_replica(self, role: Optional[str] = None) -> int:
        """Add one replica to the live fleet — the autoscaler's
        scale-up / cold-replacement primitive. Blocks until the
        newcomer is serving and has beaten once (a process replica pays
        its jax bring-up here); the router (shared worker list) can
        place onto it immediately. Returns the new replica id."""
        w = self._spawn_worker(role or "both")
        w.join(self.clock())
        self._replica_event("spawned", w)
        return w.replica_id

    def shutdown(self) -> None:
        """Stop every replica (process replicas get a stop op, then
        SIGKILL). Drills and tests call this; a production fleet runs
        until its supervisor does."""
        for w in self.workers:
            w.shutdown()

    # -- helpers -----------------------------------------------------------

    def _worker(self, replica_id: int) -> ReplicaWorker:
        return self.workers[replica_id]

    def _emit(self, rec: Dict[str, Any]) -> None:
        if self.telemetry is not None:
            self.telemetry.emit_event(rec)

    def _replica_event(self, event: str, worker: ReplicaWorker,
                       **extra) -> None:
        self._emit({"kind": "replica", "event": event,
                    "replica": worker.replica_id, "tick": self.ticks,
                    **extra})

    def _finalize(self, fr: FleetRequest, emit: bool = True) -> None:
        """A request reached its terminal record: drop it from the
        in-flight index (and emit the record when the fleet built it —
        replica-side completions were already emitted by the
        scheduler)."""
        if emit:
            self._emit(fr.record)
        self._active.pop(fr.rid, None)
        if self.tracer is not None:
            # phase "f": the rid's flow ENDS at its terminal record —
            # whichever path produced it (completion, shed, parked
            # timeout), every flow closes exactly once
            self.tracer.complete(
                "terminal", self.tracer.now_us(), flow_end=fr.rid,
                rid=fr.rid, reason=fr.record.get("finish_reason"),
                retries=fr.retries)
        if self.slo is not None:
            self.slo.observe(fr.record)
        if self.anomaly is not None and fr.replica is not None:
            self.anomaly.observe_serving(fr.replica, fr.record)

    def _terminal_record(self, fr: FleetRequest, reason: str, now: float,
                         **extra) -> Dict[str, Any]:
        """Fleet-side terminal record (shed / parked-timeout — requests
        no replica ever ran) built through ``Request.record()`` so the
        schema lives in exactly one place."""
        req = Request(rid=fr.rid, prompt=fr.prompt,
                      max_new_tokens=fr.max_new_tokens, eos_id=fr.eos_id,
                      deadline_s=fr.deadline_s, priority=fr.priority,
                      retries=fr.retries, submit_ts=fr.submit_ts,
                      finish_ts=now, finish_reason=reason)
        rec = req.record()
        rec.update(extra)
        return rec

    def _route_role(self) -> Optional[str]:
        """The submit-path role filter: prefill-first in a disagg
        fleet, EXCEPT while degraded (every prefill replica unreachable
        past the grace window) — then requests place on decode-capable
        replicas, which serve colocated prefill until the heal."""
        return "prefill" if (self.disagg and not self.degraded) else None

    # -- submission --------------------------------------------------------

    def submit(self, prompt: List[int], max_new_tokens: int,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None, priority: int = 0,
               session_id: Optional[int] = None) -> FleetRequest:
        """Route one request into the fleet. Returns a
        :class:`FleetRequest` immediately — possibly already terminal
        (``"shed"``)."""
        width = self.workers[0].engine.context_width
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens > width:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"exceeds slot capacity {width}")
        now = self.clock()
        fr = FleetRequest(rid=next(self._rid), prompt=list(prompt),
                          max_new_tokens=max_new_tokens, eos_id=eos_id,
                          deadline_s=deadline_s, priority=priority,
                          session_id=session_id, submit_ts=now)
        self.requests[fr.rid] = fr
        self._active[fr.rid] = fr
        # monotone arrival-work counters (never pruned): the
        # autoscaler's M/M/c arrival-rate estimator diffs these per
        # step — prompt tokens are prefill work, new tokens decode work
        self.arrived_prompt_tokens += len(fr.prompt)
        self.arrived_new_tokens += max_new_tokens
        if self.metrics is not None:
            m = self.metrics
            m.counter("fleet_requests_submitted",
                      "requests routed into the fleet").inc()
            m.counter("fleet_arrived_prompt_tokens",
                      "prefill work arrived").inc(len(fr.prompt))
            m.counter("fleet_arrived_new_tokens",
                      "decode work arrived").inc(max_new_tokens)
        t0 = self.tracer.now_us() if self.tracer is not None else None
        _w0 = time.perf_counter()
        dec = self.router.route(
            prompt_len=len(fr.prompt), max_new_tokens=max_new_tokens,
            deadline_s=deadline_s, session_id=session_id,
            submit_ts=now, now=now, role=self._route_role())
        self._router_cur_s += time.perf_counter() - _w0
        if self.tracer is not None:
            # the rid's flow BEGINS here (phase "s"); every later hop —
            # replica-side queue_wait/decode, a resubmit, the terminal —
            # carries the same id, so the merged trace draws one arrow
            # through every process the request touched
            outcome = ("shed" if dec.shed else "parked"
                       if dec.worker is None
                       else f"replica{dec.worker.replica_id}")
            self.tracer.complete(
                "submit", t0, self.tracer.now_us(), flow_start=fr.rid,
                rid=fr.rid, outcome=outcome)
        if dec.shed:
            self._shed(fr, dec)
            return fr
        if dec.worker is None:
            self._unplaced.append(fr)     # no healthy capacity: park
            return fr
        self._deliver(fr, dec.worker)
        if (self.faults is not None
                and self.faults.should_duplicate_submit(fr.rid)):
            # RPC-retry duplicate: same request delivered again — the
            # replica-boundary rid check must drop it
            self._deliver(fr, dec.worker)
        return fr

    def _deliver(self, fr: FleetRequest, worker: ReplicaWorker) -> None:
        if fr.rid in worker.known:
            self.duplicates_dropped += 1
            if self.tracer is not None:
                self.tracer.instant("dup_dropped", rid=fr.rid,
                                    replica=worker.replica_id)
            return
        fr.replica = worker.replica_id
        fr.attempts.append(worker.replica_id)
        if (self.faults is not None
                and self.faults.should_drop_submit(fr.rid)):
            # delivery lost after assignment: the replica never learns
            # of the rid — the reconcile sweep must notice and resubmit
            fr.local = None
            return
        fr.local = worker.deliver(fr, self.clock())
        if fr.local is None:
            # a real delivery failure (transport error, draining child):
            # same evidence shape as the drop_submit fault — the
            # reconcile sweep re-homes it
            return
        worker.known.add(fr.rid)

    def _shed(self, fr: FleetRequest, dec) -> None:
        self.shed_count += 1
        if self.metrics is not None:
            self.metrics.counter("fleet_shed",
                                 "requests shed at submit").inc()
        fr.record = self._terminal_record(
            fr, "shed", fr.submit_ts,        # shed at submit: wall 0
            shed_reason=dec.shed_reason,
            predicted_completion_s=dec.predicted_completion_s)
        self._finalize(fr)

    # -- recovery ----------------------------------------------------------

    def _resubmit(self, fr: FleetRequest, now: float,
                  reason: str) -> None:
        if fr.local is not None:
            # abandon the old attempt with visible lineage: one
            # "retried" record per abandoned attempt, never terminal
            fr.local.finish_ts = now
            fr.local.finish_reason = "retried"
            self._emit(fr.local.record())
        self.resubmits += 1
        if self.metrics is not None:
            self.metrics.counter(
                "fleet_resubmits",
                "orphaned requests re-homed by the reconcile sweep",
                reason=reason).inc()
        fr.retries += 1
        fr.local, fr.replica = None, None
        if self.tracer is not None:
            # phase "t": the SAME flow id continues — the kill-and-
            # resubmit drill renders as one connected arrow, not two
            # disjoint request lifetimes
            self.tracer.complete(
                "resubmit", now * 1e6, self.tracer.now_us(),
                flow_step=fr.rid, rid=fr.rid, reason=reason,
                retry=fr.retries)
        _log.warning("resubmitting rid=%d (%s), retry %d",
                     fr.rid, reason, fr.retries)
        dec = self.router.route(
            prompt_len=len(fr.prompt),
            max_new_tokens=fr.max_new_tokens, deadline_s=fr.deadline_s,
            session_id=fr.session_id, submit_ts=fr.submit_ts, now=now,
            allow_shed=False, role=self._route_role())
        if dec.worker is None:
            self._unplaced.append(fr)
        else:
            self._deliver(fr, dec.worker)

    def _reconcile(self, now: float) -> None:
        """The anti-entropy sweep: every non-terminal request must be
        held by a live replica that knows its rid. Parked requests
        retry placement first (capacity may have appeared)."""
        self._place_parked(now)
        for fr in list(self._active.values()):
            if fr.record is not None or fr.replica is None:
                continue
            w = self._worker(fr.replica)
            if w.state in ("dead", "released"):
                self._resubmit(fr, now, f"replica-{w.state}")
            elif fr.local is None and w.state in ("live", "draining"):
                self._resubmit(fr, now, "lost-submit")
        if self._unplaced and not self.router.candidates():
            # capacity emergency: parked work and zero live replicas.
            # The drain guard can be raced (a replica killed just before
            # the drain is only OBSERVED dead later), so scale-down
            # yields: cancel a drain rather than strand requests. This
            # check runs AFTER the orphan sweep above: a death verdict
            # (K-confirmed, so one refresh later than it used to be)
            # may park its orphans in this very tick, and the drainer
            # must be recalled before it goes idle and is released.
            w = next((w for w in self.workers if w.state == "draining"),
                     None)
            if w is not None:
                w.state = "live"
                w.cancel_drain()
                _log.warning("drain of replica %d cancelled: no other "
                             "live capacity for %d parked request(s)",
                             w.replica_id, len(self._unplaced))
                self._replica_event("drain-cancelled", w,
                                    parked=len(self._unplaced))
                self._place_parked(now)

    def _place_parked(self, now: float) -> None:
        for fr in list(self._unplaced):
            # a parked request still owns its deadline: no replica will
            # ever run the scheduler's expiry sweep for it, so the fleet
            # does — parked-forever must not exist
            if (fr.deadline_s is not None
                    and now - fr.submit_ts > fr.deadline_s):
                self._unplaced.remove(fr)
                fr.record = self._terminal_record(fr, "timeout", now)
                self._finalize(fr)
                continue
            dec = self.router.route(
                prompt_len=len(fr.prompt),
                max_new_tokens=fr.max_new_tokens,
                deadline_s=fr.deadline_s, session_id=fr.session_id,
                submit_ts=fr.submit_ts, now=now, allow_shed=False,
                role=self._route_role())
            if dec.worker is not None:
                self._unplaced.remove(fr)
                self._deliver(fr, dec.worker)

    def _collect(self) -> None:
        """Drain newly completed replica-side requests into fleet
        terminal records. Completions from superseded attempts (the rid
        was re-homed) or already-terminal rids are counted and dropped —
        the idempotency boundary."""
        for w in self.workers:
            if w.killed or w.state in ("dead", "released"):
                continue
            comp = w.scheduler.completed
            while w._collected < len(comp):
                req = comp[w._collected]
                w._collected += 1
                fr = self.requests.get(req.rid)
                if fr is None:
                    continue
                if fr.record is not None or fr.local is not req:
                    self.stale_completions += 1
                    continue
                fr.record = req.record()
                self._finalize(fr, emit=False)   # scheduler emitted it
                w.known.discard(req.rid)

    # -- prefill→decode handoff (ISSUE 18) ---------------------------------

    def _collect_handoffs(self, now: float) -> None:
        """Sweep finished-prefill KV packages out of the prefill
        replicas into the fleet's handoff ledger. A package is only
        visible once its WHOLE tick reply (header + every framed page
        payload) was absorbed, so a prefill replica killed mid-transfer
        simply never surfaces it — the request still points at the dead
        replica and the ordinary reconcile resubmit re-homes it."""
        for w in self.workers:
            if w.killed or w.state in ("dead", "released"):
                continue
            pop = getattr(w, "pop_handoffs", None)
            if pop is None:
                continue
            for pkg in pop():
                rid = int(pkg["rid"])
                pkg_ep = pkg.get("epoch")
                if (pkg_ep is not None
                        and getattr(w, "lease_epoch", 0)
                        and pkg_ep != w.lease_epoch):
                    # the package arrived under a lease that has since
                    # been revoked (ISSUE 20): its rid was resubmitted
                    # — adopting it would race the retry's own prefill
                    self.stale_epoch_handoffs += 1
                    continue
                fr = self.requests.get(rid)
                if (fr is None or fr.record is not None
                        or fr.replica != w.replica_id):
                    # superseded attempt (the rid was already re-homed
                    # or went terminal): the package is stale evidence
                    self.stale_handoffs += 1
                    continue
                # the request now lives BETWEEN replicas: fleet-owned.
                # reconcile skips replica-None rids; the ledger entry
                # is the liveness obligation instead (deadline-swept
                # in _place_handoffs).
                fr.local, fr.replica = None, None
                w.known.discard(rid)
                self._pending_handoffs[rid] = {
                    "pkg": pkg, "src": w.replica_id,
                    "t0_pc": time.perf_counter(),
                    "t0_us": (self.tracer.now_us()
                              if self.tracer is not None else None)}

    def _place_handoffs(self, now: float) -> None:
        """Adopt every ledgered KV package onto a decode replica:
        least ``pending_new_tokens`` first, try-each until one admits.
        All refused → retry next tick (capacity may appear); zero
        decode-capable replicas → the pages are worthless (their pool
        is gone), drop the package and resubmit through prefill."""
        for rid in list(self._pending_handoffs):
            ho = self._pending_handoffs[rid]
            fr = self.requests[rid]
            if (fr.deadline_s is not None
                    and now - fr.submit_ts > fr.deadline_s):
                del self._pending_handoffs[rid]
                fr.record = self._terminal_record(fr, "timeout", now)
                self._finalize(fr)
                continue
            cands = self.router.candidates("decode")
            if not cands:
                del self._pending_handoffs[rid]
                self._resubmit(fr, now, "handoff-lost")
                continue
            cands.sort(key=lambda w: self.router.load_key(w, None))
            placed = False
            for w in cands:
                req = w.adopt(fr, ho["pkg"], now)
                if req is None:
                    continue
                fr.local, fr.replica = req, w.replica_id
                fr.attempts.append(w.replica_id)
                w.known.add(rid)
                del self._pending_handoffs[rid]
                self._emit_handoff(fr, ho, w, now)
                placed = True
                break
            if not placed and rid in self._pending_handoffs:
                _log.debug("handoff rid=%d found no admitting decode "
                           "replica this tick; retrying", rid)

    def _emit_handoff(self, fr: FleetRequest, ho: Dict[str, Any],
                      dst, now: float) -> None:
        pkg = ho["pkg"]
        meta = pkg["meta"]
        wire = sum(len(b) for b in pkg["blobs"])
        blocks = int(meta.get("blocks") or len(pkg["blobs"]))
        ms = (time.perf_counter() - ho["t0_pc"]) * 1000.0
        self.handoff_count += 1
        self.handoff_wire_bytes += wire
        self.handoff_blocks += blocks
        self._emit({"kind": "kv_handoff", "rid": fr.rid,
                    "blocks": blocks, "wire_bytes": wire,
                    "quant": meta.get("quant"), "transfer_ms": ms,
                    "src_replica": ho["src"],
                    "dst_replica": dst.replica_id, "tick": self.ticks})
        if self.tracer is not None:
            # phase "t": the rid's flow steps THROUGH the handoff span
            # — the merged trace draws prefill-lane → router-lane
            # handoff → decode-lane as one connected arrow
            self.tracer.complete(
                "kv_handoff", ho["t0_us"], self.tracer.now_us(),
                flow_step=fr.rid, rid=fr.rid, blocks=blocks,
                wire_bytes=wire, src=ho["src"], dst=dst.replica_id)

    # -- elastic scale-down ------------------------------------------------

    def drain(self, replica_id: int) -> str:
        """Graceful drain: stop admitting to ``replica_id``, re-route
        its queued (never-admitted) requests, let running slots finish,
        then release. Returns the replica's state."""
        w = self._worker(replica_id)
        if w.state != "live":
            return w.state
        if not any(o.state == "live" for o in self.workers if o is not w):
            raise ValueError(
                f"cannot drain replica {replica_id}: it is the last live "
                f"replica (scale-down below 1 would strand every "
                f"outstanding request)")
        w.state = "draining"
        now = self.clock()
        self._replica_event("draining", w)
        for rid in w.begin_drain(now):
            fr = self.requests.get(rid)
            if fr is not None and fr.record is None:
                self._resubmit(fr, now, "drain")
        return w.state

    # -- partition tolerance (ISSUE 20) ------------------------------------

    def readmit_pending(self) -> List[Any]:
        """Fenced socket workers whose process is still alive and whose
        fence is recent enough (``readmit_grace_s``) that a readmit may
        rescue them. The autoscaler counts these toward role fill —
        fenced is NOT just dead for capacity math, or a heal would land
        a readmitted replica on top of its own replacement."""
        if self.replica_mode != "socket":
            return []
        now = self.clock()
        out = []
        for w in self.workers:
            if (w.state == "dead" and getattr(w, "is_process", False)
                    and not w.killed and not w.transport.closed):
                proc = w.transport.proc
                if (proc is not None and proc.poll() is None
                        and (w._fenced_at is None
                             or now - w._fenced_at
                             <= self.readmit_grace_s)):
                    out.append(w)
        return out

    def _probe_readmits(self, now: float) -> None:
        """Offer every readmit-eligible fenced worker a fresh lease,
        on a capped exponential tick backoff with seeded jitter (a
        healed partition must not see every fenced replica probed on
        the same tick). One short-timeout attempt per probe — cheap
        while the partition holds, immediate once it heals."""
        t = self.ticks
        for w in self.readmit_pending():
            if t < w._next_readmit_tick:
                continue
            if w.try_readmit(next(self._epochs), now):
                self.readmitted += 1
                info = w.readmit_info or {}
                self._replica_event(
                    "readmitted", w, epoch=w.lease_epoch,
                    tokens_while_fenced=info.get("tokens_while_fenced"),
                    stale_epoch_rejects=info.get("stale_epoch_rejects"))
                if self.metrics is not None:
                    self.metrics.counter(
                        "fleet_readmitted_total",
                        "fenced replicas re-admitted after heal").inc()
                if self.tracer is not None:
                    self.tracer.instant("replica_readmitted",
                                        replica=w.replica_id,
                                        epoch=w.lease_epoch)
                # the death verdict is spent: a fresh staleness streak
                # must start from zero for the new incarnation
                self.router._stale_streak.pop(w.replica_id, None)
            else:
                step = 1 << min(w._readmit_attempts, 4)
                w._next_readmit_tick = (
                    t + step + w._readmit_rng.randrange(0, step + 1))

    def _update_degradation(self, now: float) -> None:
        """Disagg partition degradation: zero reachable prefill-capable
        replicas past the grace window flips the fleet to degraded —
        the submit path routes to decode-capable replicas, whose
        schedulers serve colocated prefill (slower, not stuck). Any
        prefill candidate reappearing (heal, readmit, autoscaler
        replacement) releases it immediately."""
        if not self.disagg:
            return
        if self.router.candidates("prefill"):
            self._prefill_lost_at = None
            if self.degraded:
                self.degraded = False
                self.degrade_releases += 1
                self._emit({"kind": "degrade", "event": "released",
                            "t": now, "tick": self.ticks})
                if self.tracer is not None:
                    self.tracer.instant("degrade_released")
            return
        if self._prefill_lost_at is None:
            self._prefill_lost_at = now
            return
        if (not self.degraded
                and now - self._prefill_lost_at >= self.degrade_grace_s):
            self.degraded = True
            self.degradations += 1
            _log.warning("no reachable prefill replica for %.2fs: "
                         "degrading to colocated prefill on decode "
                         "replicas", now - self._prefill_lost_at)
            self._emit({"kind": "degrade", "event": "engaged",
                        "t": now, "tick": self.ticks,
                        "grace_s": self.degrade_grace_s})
            if self.metrics is not None:
                self.metrics.counter(
                    "fleet_degraded_total",
                    "disagg→colocated degradation engagements").inc()
            if self.tracer is not None:
                self.tracer.instant("degrade_engaged")

    # -- the fleet tick ----------------------------------------------------

    def tick(self) -> None:
        """One fleet heartbeat: fire scheduled faults, observe health,
        reconcile assignments, tick every replica, collect completions,
        finalize drains."""
        now = self.clock()
        t = self.ticks
        if self.faults is not None:
            k = self.faults.kill_replica_for_tick(t)
            if k is not None:
                self._worker(k).kill()
            sk = self.faults.sigkill_replica_for_tick(t)
            if sk is not None:
                self._worker(sk).sigkill()
            s = self.faults.stall_replica_for_tick(t)
            if s is not None:
                rep, n = s
                self._worker(rep).stall(t + n)
        for w in self.router.refresh_health(now):
            self._replica_event("dead", w, orphans=w.orphan_count())
            if (getattr(w, "is_process", False)
                    and getattr(w, "_mode", None) == "socket"):
                # fence BY EPOCH (ISSUE 20): a socket replica may live
                # on a host our signals cannot reach — revoke its lease
                # instead of killing. The revocation holds even if the
                # notice below never arrives: every op/reply/handoff/
                # metric-delta of the old epoch is now discarded on
                # both sides of the wire.
                old_ep = w.lease_epoch
                info = w.fence(next(self._epochs), now, tick_idx=t)
                self.fences += 1
                rec = {"kind": "fence", "replica": w.replica_id,
                       "t": now, "tick": t, "reason": "declared-dead",
                       "epoch": old_ep, "new_epoch": w.lease_epoch,
                       "acked": info is not None}
                if info:
                    rec["slots_evicted"] = info.get("slots_evicted")
                    rec["blocks_freed"] = info.get("blocks_freed")
                self._emit(rec)
                if self.metrics is not None:
                    self.metrics.counter(
                        "fleet_fence_total",
                        "lease revocations on declare-dead").inc()
                if self.tracer is not None:
                    self.tracer.instant("replica_fenced",
                                        replica=w.replica_id,
                                        epoch=old_ep)
            else:
                w.on_declared_dead()     # pipe/in-process: fence by
                #                          kill (same host — stronger)
            # retire the ghost's beat (quarantine rename, never delete):
            # watchdogs scanning the root must not re-report it forever
            multihost.retire_heartbeat(self.root, w.replica_id)
        self._probe_readmits(now)
        self._update_degradation(now)
        if self.autoscaler is not None:
            # policy BEFORE reconcile: a cold-spawned replacement is
            # placeable in the same tick that needs it
            self.autoscaler.step(now)
        _w0 = time.perf_counter()
        self._reconcile(now)
        self._router_cur_s += time.perf_counter() - _w0
        for w in self.workers:
            w.tick(now, t)
        _w0 = time.perf_counter()
        self._collect_handoffs(now)
        self._place_handoffs(now)
        self._router_cur_s += time.perf_counter() - _w0
        self._collect()
        if self.tracer is not None:
            for w in self.workers:
                sp = w.drain_spans()
                if sp:
                    self._replica_spans[w.replica_id].extend(sp)
        if self.metrics is not None:
            # absorb the registry deltas that rode this tick's replies,
            # namespaced per replica — the metrics twin of the span
            # drain above (in-process workers return [] here; they
            # already wrote the hub directly)
            for w in self.workers:
                d = w.drain_metrics()
                if d:
                    self.metrics.absorb_delta(
                        d, replica=str(w.replica_id))
        if self.anomaly is not None:
            for w in self.workers:
                if w.killed or w.state in ("dead", "released"):
                    continue
                busy = bool(w.scheduler.running
                            or w.scheduler.prefilling)
                self.anomaly.observe_fleet_tick(
                    w.replica_id, tick=t,
                    engine_ticks=w.engine.ticks,
                    queued=len(w.scheduler.queue), busy=busy)
                ts = w.transport_stats()
                if ts is not None:
                    self.anomaly.observe_transport(w.replica_id, ts)
        for w in self.workers:
            if w.state == "draining" and w.idle():
                w.state = "released"
                w.shutdown()
                multihost.retire_heartbeat(self.root, w.replica_id)
                self._replica_event(
                    "released", w,
                    free_blocks=w.engine.cache.free_blocks)
        self._router_tick_s.append(self._router_cur_s)
        self._router_cur_s = 0.0
        if self.metrics is not None:
            m = self.metrics
            m.counter("fleet_ticks", "fleet heartbeats").inc()
            m.gauge("fleet_active_requests",
                    "non-terminal requests in flight"
                    ).set(len(self._active))
            m.gauge("fleet_unplaced",
                    "parked requests awaiting capacity"
                    ).set(len(self._unplaced))
            m.gauge("fleet_pending_handoffs",
                    "KV packages in the fleet-owned handoff ledger"
                    ).set(len(self._pending_handoffs))
            m.histogram("fleet_router_ms",
                        "host-side placement cost per fleet tick (ms)"
                        ).observe(self._router_tick_s[-1] * 1000.0)
            m.gauge("fleet_degraded",
                    "1 while serving colocated prefill on decode "
                    "replicas (disagg partition degradation)"
                    ).set(1 if self.degraded else 0)
            if self.chaos is not None:
                cs = self.chaos.stats()
                m.gauge("chaos_frames_dropped",
                        "frames discarded by the chaos plane"
                        ).set(cs["frames_dropped"])
                m.gauge("chaos_frames_delayed",
                        "frames held by the chaos plane"
                        ).set(cs["frames_delayed"])
                m.gauge("chaos_bytes_dropped",
                        "wire bytes discarded by the chaos plane"
                        ).set(cs["bytes_dropped"])
                m.gauge("chaos_delay_injected_s",
                        "cumulative injected delay (s)"
                        ).set(cs["delay_injected_s"])
        self.ticks += 1

    def outstanding(self) -> bool:
        return (bool(self._active) or bool(self._pending_handoffs)
                or any(w.state == "draining" for w in self.workers))

    def prune_terminal(self) -> int:
        """Drop terminal requests from the ledger (a long-lived fleet's
        memory bound — the telemetry stream is the durable record).
        Returns how many were pruned."""
        dead = [rid for rid, fr in self.requests.items()
                if fr.record is not None]
        for rid in dead:
            del self.requests[rid]
        return len(dead)

    # -- workload replay ---------------------------------------------------

    def play(self, workload, *, dt_s: Optional[float] = None,
             drain_at_tick: Optional[Dict[int, int]] = None,
             max_ticks: int = 100000) -> List[FleetRequest]:
        """Replay a :func:`~paddle_tpu.serve.loadgen.make_workload`
        trace: submit every arrival whose ``at_s`` has passed, tick,
        advance the clock (``SimClock`` + ``dt_s``; a real clock just
        flows). Arrival times are relative to the START of the replay —
        the clock's epoch (perf_counter's arbitrary origin, a SimClock
        mid-run) must not collapse the trace into one burst.
        ``drain_at_tick`` maps fleet tick index → replica id for
        scripted elastic scale-down. Returns every
        :class:`FleetRequest` in rid order, all terminal."""
        pending = collections.deque(
            sorted(workload, key=lambda g: g.at_s))
        drains = dict(drain_at_tick or {})
        t0 = self.clock()
        for _ in range(max_ticks):
            now = self.clock() - t0
            while pending and pending[0].at_s <= now:
                g = pending.popleft()
                self.submit(g.prompt, g.max_new_tokens, eos_id=g.eos_id,
                            deadline_s=g.deadline_s, priority=g.priority,
                            session_id=g.session_id)
            if self.ticks in drains:
                self.drain(drains.pop(self.ticks))
            if not pending and not drains and not self.outstanding():
                return [self.requests[r] for r in sorted(self.requests)]
            self.tick()
            adv = getattr(self.clock, "advance", None)
            if adv is not None and dt_s is not None:
                adv(dt_s)
        raise RuntimeError(f"fleet did not drain in {max_ticks} ticks "
                           f"({sum(1 for f in self.requests.values() if not f.done)} "
                           f"requests outstanding)")

    # -- fleet observability (ISSUE 17) ------------------------------------

    def fleet_trace(self, tail: Optional[int] = None
                    ) -> Optional[Dict[str, Any]]:
        """Merge the router lane and every replica's shipped spans into
        ONE Chrome/Perfetto trace (``None`` when tracing is off). All
        lanes share the fleet clock, so a rid's ``s``/``t``/``f`` flow
        events connect across processes. ``tail`` keeps only the most
        recent N non-metadata events (the forensic-bundle window)."""
        if self.tracer is None:
            return None
        for w in self.workers:          # sweep spans a tick hasn't yet
            sp = w.drain_spans()
            if sp:
                self._replica_spans[w.replica_id].extend(sp)
        return merge_fleet_trace(self.tracer.events(),
                                 dict(self._replica_spans), tail=tail)

    def save_fleet_trace(self, path: str) -> str:
        """Write the merged fleet trace JSON (open in ui.perfetto.dev).
        Raises when tracing is off — there is nothing to save."""
        tr = self.fleet_trace()
        if tr is None:
            raise ValueError("tracing is off: construct the fleet with "
                             "trace=True")
        return _save_fleet_trace(tr, path)

    def slo_report(self) -> Optional[Dict[str, Any]]:
        """The streaming SLO monitor's snapshot (rolling percentiles,
        goodput, burn rate) — ``None`` when SLO monitoring is off."""
        return self.slo.report() if self.slo is not None else None

    def _transport_totals(self) -> Dict[str, int]:
        """Fleet-wide transport failure counters summed over process
        replicas (all zeros for an in-process fleet). With the registry
        on, the totals READ THROUGH it (satellite 2) — the per-link
        counters are incremented at the exact sites the attribute
        counters are, so both paths agree; the attribute fallback stays
        the dark-mode source of truth."""
        tot = {"errors": 0, "retransmits": 0, "timeouts": 0,
               "corrupt_replies": 0}
        if self.metrics is not None:
            for row in self.metrics.snapshot():
                name = row["name"]
                if (name.startswith("transport_")
                        and row["type"] == "counter"
                        and name[len("transport_"):] in tot):
                    tot[name[len("transport_"):]] += int(row["value"])
            return tot
        for w in self.workers:
            ts = w.transport_stats()
            if ts:
                for k in tot:
                    tot[k] += int(ts.get(k) or 0)
        return tot

    def _membership_stats(self) -> Dict[str, Any]:
        """The epoch-lease membership counters (ISSUE 20): fences
        issued, zombies re-admitted, stale-epoch traffic discarded at
        each merge seam, flap verdicts averted, and the degradation
        state — one dict shared by ``stats()`` and the fleet record."""
        return {
            "fences": self.fences,
            "readmitted": self.readmitted,
            "false_deaths_averted": self.router.false_deaths_averted,
            "stale_epoch_replies": sum(
                getattr(w, "stale_epoch_replies", 0)
                for w in self.workers),
            "stale_epoch_handoffs": self.stale_epoch_handoffs,
            "stale_metric_deltas": sum(
                getattr(w, "stale_metric_deltas", 0)
                for w in self.workers),
            "readmit_pending": len(self.readmit_pending()),
            "degraded": self.degraded,
            "degradations": self.degradations,
            "degrade_releases": self.degrade_releases,
        }

    def emit_stats(self) -> Dict[str, Any]:
        """Emit one ``kind="fleet"`` summary record into the telemetry
        stream (transport totals, recovery counters, the SLO snapshot
        when monitoring is on) — the record ``obs.report`` surfaces as
        the serving transport/SLO blocks. Returns the record."""
        rec: Dict[str, Any] = {
            "kind": "fleet", "tick": self.ticks,
            "resubmits": self.resubmits, "shed": self.shed_count,
            "duplicates_dropped": self.duplicates_dropped,
            "stale_completions": self.stale_completions,
            "transport": self._transport_totals(),
            "membership": self._membership_stats()}
        if self.chaos is not None:
            rec["chaos"] = self.chaos.stats()
        if self.slo is not None:
            rec["slo"] = self.slo.report()
        self._emit(rec)
        if self.metrics is not None:
            # the registry rides the telemetry stream as its own record
            # kind — obs.report/obs.top read it back offline without a
            # live hub (the fleet record's schema is untouched)
            self._emit({"kind": "metrics", "tick": self.ticks,
                        "metrics": self.metrics.snapshot()})
        return rec

    # -- reporting ---------------------------------------------------------

    def _router_ms(self) -> Dict[str, Any]:
        """Host-side placement cost (route + reconcile + handoff
        sweeps) in wall milliseconds, bucketed per fleet tick — the
        hostile-scale loadgen's router-overhead evidence."""
        buckets = self._router_tick_s
        total = sum(buckets) + self._router_cur_s
        return {"total": total * 1000.0,
                "per_tick_mean": ((sum(buckets) / len(buckets)) * 1000.0
                                  if buckets else 0.0),
                "per_tick_max": (max(buckets) * 1000.0
                                 if buckets else 0.0),
                "ticks": len(buckets)}

    def stats(self) -> Dict[str, Any]:
        reasons = collections.Counter(
            fr.record["finish_reason"]
            for fr in self.requests.values() if fr.record)
        per_replica = {}
        for w in self.workers:
            row = {"state": w.state, "killed": w.killed,
                   "role": getattr(w, "role", "both"),
                   "engine_ticks": w.engine.ticks,
                   "free_blocks": w.engine.cache.free_blocks,
                   "prefix_hit_blocks": w.engine.cache.prefix_hit_blocks,
                   "compile_counts": w.engine.compile_counts()}
            ts = w.transport_stats()
            if ts is not None:
                row["transport"] = ts
            if getattr(w, "lease_epoch", 0):
                row["epoch"] = w.lease_epoch
                if getattr(w, "revoked_epoch", None) is not None:
                    row["revoked_epoch"] = w.revoked_epoch
                if getattr(w, "readmits", 0):
                    row["readmits"] = w.readmits
            per_replica[w.replica_id] = row
        scale = ({"scale_events": len(self.autoscaler.events),
                  "desired_replicas": self.autoscaler.desired,
                  "replacements": self.autoscaler.replacements}
                 if self.autoscaler is not None else {})
        out = {
            **scale,
            "submitted": len(self.requests),
            "terminal": sum(1 for fr in self.requests.values()
                            if fr.record is not None),
            "finish_reasons": dict(reasons),
            "resubmits": self.resubmits,
            "shed": self.shed_count,
            "duplicates_dropped": self.duplicates_dropped,
            "stale_completions": self.stale_completions,
            "unplaced": len(self._unplaced),
            "ticks": self.ticks,
            "replica_mode": self.replica_mode,
            "prefix_hit_blocks": sum(
                w.engine.cache.prefix_hit_blocks for w in self.workers),
            "cow_forks": sum(
                w.engine.cache.cow_forks for w in self.workers),
            "transport": self._transport_totals(),
            "replicas": per_replica,
            "handoffs": self.handoff_count,
            "handoff_wire_bytes": self.handoff_wire_bytes,
            "handoff_blocks": self.handoff_blocks,
            "stale_handoffs": self.stale_handoffs,
            "pending_handoffs": len(self._pending_handoffs),
            "router_ms": self._router_ms(),
            # the membership block is UNCONDITIONAL: a dark twin with
            # chaos off must expose the same key set (bench leg 4 pins
            # instrumented-vs-dark stats symmetry) — only "chaos" below
            # is gated on the plane actually being attached
            "membership": self._membership_stats(),
        }
        if self.chaos is not None:
            out["chaos"] = self.chaos.stats()
        if self.slo is not None:
            # burn rate and the rolling percentiles ride the stats dict
            # (ISSUE 17) — the dashboard's one-call snapshot
            out["slo"] = self.slo.report()
        if self.anomaly is not None:
            out["anomalies"] = [v.kind for v in self.anomaly.verdicts]
        return out

    @classmethod
    def from_model(cls, model, variables, n_replicas: int, *,
                   engine_kwargs: Optional[Dict[str, Any]] = None,
                   replica_mode: str = "inprocess",
                   model_spec: Optional[Dict[str, Any]] = None,
                   **kw) -> "ServingFleet":
        """Convenience constructor: N identical engines over one
        checkpoint (the common homogeneous fleet). With
        ``replica_mode="process"`` the model CONFIG plus the variables
        (saved once as an npz under the fleet root) ship to each child
        process, which rebuilds its own engine — the parent never
        shares python objects with a replica. ``model_spec`` overrides
        the introspected TransformerLM constructor kwargs (custom
        models)."""
        from .engine import DecodeEngine
        ek = dict(engine_kwargs or {})
        if replica_mode in ("process", "socket"):
            root = kw.pop("root", None) or tempfile.mkdtemp(
                prefix="paddle_tpu_fleet_")
            spec = build_proc_spec(
                model, variables, root, engine_kwargs=ek,
                model_spec=model_spec, order=kw.get("order", "fcfs"),
                est_tick_s=kw.get("est_tick_s"),
                warmup=kw.pop("warmup", None),
                compile_cache_dir=kw.pop("compile_cache_dir", None),
                autotune_cache_dir=kw.pop("autotune_cache_dir", None),
                telemetry_dir=kw.pop("telemetry_dir", None))
            return cls(None, n_replicas, replica_mode=replica_mode,
                       proc_spec=spec, root=root, **kw)

        def mk(_i):
            return DecodeEngine(model, variables, **ek)

        return cls(mk, n_replicas, **kw)


def _introspect_lm(model) -> Dict[str, Any]:
    """Recover the :class:`~paddle_tpu.models.TransformerLM` constructor
    config a child process needs (dense homogeneous blocks — the
    serving contract)."""
    blk = model.blocks[0]
    return {"vocab": model.emb.vocab, "dim": model.emb.dim,
            "num_layers": len(model.blocks),
            "num_heads": blk.attn.num_heads,
            "ffn_hidden": blk.ffn1.features,
            "max_len": model.max_len}


def build_proc_spec(model, variables, root: str, *,
                    engine_kwargs: Optional[Dict[str, Any]] = None,
                    model_spec: Optional[Dict[str, Any]] = None,
                    order: str = "fcfs",
                    est_tick_s: Optional[float] = None,
                    mesh_axes: Optional[Dict[str, int]] = None,
                    warmup: Optional[bool] = None,
                    compile_cache_dir: Optional[str] = None,
                    autotune_cache_dir: Optional[str] = None,
                    telemetry_dir: Optional[str] = None
                    ) -> Dict[str, Any]:
    """The child-process build spec: model constructor kwargs, engine
    kwargs, scheduler policy, and the variables npz (written once under
    ``root``; every replica loads the same file — a training checkpoint
    serves unmodified, just across a process boundary).

    ``mesh_axes`` (ISSUE 15): an optional ``{axis_name: size}`` dict —
    e.g. ``{"model": 2}`` — shipped as ``spec["mesh"]`` so a
    process-mode replica builds its engine TENSOR-PARALLEL over its own
    local devices (a Mesh object cannot cross the JSON wire; the axis
    layout can). Deliberately ABSENT from the spec when None, so a
    single-device spec is byte-identical to the pre-tp schema —
    replicas on old and new code agree on the frame bytes.

    ``warmup`` / ``compile_cache_dir`` / ``autotune_cache_dir``
    (ISSUE 16): the cold-start trio — the child executes both engine
    programs before its hello reply, against a persistent XLA compile
    cache and kernel-autotune cache shared across spawns, so autoscaler
    cold-spawns and supervisor restarts come up warm. Same
    schema-stability rule as ``mesh``: each key is ABSENT when unset.

    ``telemetry_dir`` (ISSUE 17): a directory where each child replica
    line-flushes its telemetry records to ``replica_<id>.jsonl`` AS
    WELL AS shipping them on tick replies — a SIGKILLed child's records
    up to the kill survive for post-mortem forensics, where the
    reply-shipped copies die with the pipe. ABSENT when unset, like
    every optional key."""
    from .replica_proc import save_variables_npz
    npz = os.path.join(root, "variables.npz")
    save_variables_npz(npz, variables)
    spec = {"model": dict(model_spec or _introspect_lm(model)),
            "engine": dict(engine_kwargs or {}),
            "variables_npz": npz, "order": order,
            "est_tick_s": est_tick_s, "root": root}
    if mesh_axes:
        spec["mesh"] = dict(mesh_axes)
    if warmup is not None:
        spec["warmup"] = bool(warmup)
    if compile_cache_dir:
        spec["compile_cache_dir"] = str(compile_cache_dir)
    if autotune_cache_dir:
        spec["autotune_cache_dir"] = str(autotune_cache_dir)
    if telemetry_dir:
        spec["telemetry_dir"] = str(telemetry_dir)
    return spec
