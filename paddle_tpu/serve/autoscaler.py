"""Supervised autoscaler for the serving fleet (ISSUE 13, upgraded to
a per-role queueing-model controller in ISSUE 18): elastic capacity as
a POLICY LOOP over evidence the fleet already publishes.

The mechanics existed before this module: ``drain()`` is lossless
scale-down (PR 11), :meth:`~paddle_tpu.serve.fleet.ServingFleet.
spawn_replica` is scale-up, and every replica's load rides its PR-10
heartbeat file. The autoscaler adds only the decisions, and the
discipline that keeps decisions from flapping:

- **Sense from the files.** Load is read from the heartbeat payloads
  (``pending_new_tokens`` / ``prefill_backlog`` per live replica, the
  child-reported tick-time EMA) — the same evidence a watchdog on
  another host would have, not a private pointer into a scheduler.
- **Model the queue, don't threshold it.** Each ROLE GROUP (a
  disaggregated fleet has separate prefill and decode groups; a plain
  fleet is one ``"both"`` group) is modeled as an M/M/c queue: c =
  live replicas × slots (the decode lanes), service rate μ = 1 /
  tick-time EMA, arrival rate λ = an EMA over the fleet's monotone
  arrival-work counters (prompt tokens for prefill, new tokens for
  decode) diffed per step. Predicted delay is the MAX of the Erlang-C
  expected wait Wq and the PR-11 deterministic backlog model
  (``backlog / lanes × tick_s``) — the queueing term sees load that
  hasn't queued yet (λ near saturation), the backlog term sees load
  that already has.
- **Scale up on predicted-delay breach** (``up_delay_s``), gated on
  the delay DERIVATIVE: capacity is added only while the breach is not
  already improving — a just-spawned replica gets one cooldown to bend
  the curve before the policy piles on. The spawned replica takes the
  breaching group's role.
- **Scale down on sustained idle** (``idle_grace_ticks`` consecutive
  ticks with zero backlog AND zero in-flight requests), always through
  ``drain()`` — zero lost requests — and never below one live replica
  per role (a prefill group with no decode peer would strand every
  handoff).
- **Hysteresis** (``cooldown_ticks``): after ANY up/down decision the
  policy holds still, so bursty traffic produces a BOUNDED number of
  scale events (the CI test pins this).
- **Cold-spawn replacement under a restart budget**: a replica the
  router declared dead is replaced (``action="replace"``, same role)
  outside the up/down cooldown — healing is not scaling — but under
  ``max_replacements``; when the budget is exhausted the autoscaler
  GIVES UP LOUD (:class:`AutoscalerGaveUp` with the full event
  ledger).

Every decision emits a ``kind="scale"`` telemetry event (action,
reason, replica counts before/after, the evidence) — aggregated by
``obs.report``'s serving block via
:func:`~paddle_tpu.obs.percentiles.summarize_scale`.
"""

from __future__ import annotations

import logging
import math
from typing import Any, Dict, List, Optional

from ..parallel import multihost

__all__ = ["Autoscaler", "AutoscalerGaveUp", "erlang_c_wait"]

_log = logging.getLogger("paddle_tpu.serve.autoscaler")


class AutoscalerGaveUp(RuntimeError):
    """The replacement budget is exhausted: replicas keep dying faster
    than the policy may heal. Carries the event ledger so the operator
    sees every decision that led here."""

    def __init__(self, msg: str, events: List[Dict[str, Any]]):
        super().__init__(msg)
        self.events = list(events)


def erlang_c_wait(lam: float, mu: float, c: int) -> float:
    """Expected M/M/c queue wait Wq (seconds): λ arrivals/s, μ per-
    server service rate, c servers. Uses the numerically stable
    Erlang-B recurrence ``B_k = a·B_{k-1} / (k + a·B_{k-1})`` then
    ``C = B_c / (1 − ρ + ρ·B_c)`` and ``Wq = C / (cμ − λ)``. Returns
    0 for an empty or degenerate system and ``inf`` at or past
    saturation (ρ ≥ 1) — an unstable queue's wait is unbounded."""
    if lam <= 0.0 or mu <= 0.0 or c < 1:
        return 0.0
    a = lam / mu                       # offered load (erlangs)
    rho = a / c
    if rho >= 1.0:
        return float("inf")
    b = 1.0
    for k in range(1, int(c) + 1):
        b = a * b / (k + a * b)
    cq = b / (1.0 - rho + rho * b)     # P(wait) — Erlang C
    return cq / (c * mu - lam)


class Autoscaler:
    """The policy loop (see module docstring). Construct with policy
    knobs, pass to ``ServingFleet(autoscaler=...)`` (or call
    :meth:`bind` yourself); :meth:`step` runs inside every fleet tick.

    Args:
      min_replicas / max_replicas: the live-capacity envelope (fleet
        TOTAL — roles share it). Scale down never goes below
        ``min_replicas`` (and ``drain()`` itself refuses below 1);
        scale up and replacement never exceed ``max_replicas``.
      up_delay_s: predicted-queue-delay breach that triggers scale-up.
        Needs tick-time evidence (heartbeat-reported EMA or the fleet's
        ``est_tick_s`` prior); with neither, ``up_pending_per_slot``
        is the fallback trigger.
      up_pending_per_slot: backlog-per-decode-lane fallback threshold.
      idle_grace_ticks: consecutive fully-idle ticks before scale-down.
      cooldown_ticks: minimum fleet ticks between scale up/down events
        (the hysteresis that bounds flapping).
      max_replacements: cold-spawn budget for replacing dead replicas;
        exhausted → :class:`AutoscalerGaveUp`.
    """

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 8,
                 up_delay_s: Optional[float] = None,
                 up_pending_per_slot: float = 8.0,
                 idle_grace_ticks: int = 20, cooldown_ticks: int = 10,
                 max_replacements: int = 3):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_delay_s = up_delay_s
        self.up_pending_per_slot = float(up_pending_per_slot)
        self.idle_grace_ticks = int(idle_grace_ticks)
        self.cooldown_ticks = int(cooldown_ticks)
        self.max_replacements = int(max_replacements)
        self.fleet = None
        self.desired: Optional[int] = None   # fleet total (legacy API)
        self.desired_by_role: Dict[str, int] = {}
        self.events: List[Dict[str, Any]] = []
        self.replacements = 0
        self._idle_ticks = 0
        self._last_scale_tick: Optional[int] = None
        # queueing-model state (ISSUE 18): per-role arrival-rate EMA
        # over the fleet's monotone work counters, and the previous
        # step's predicted delay (the derivative gate's memory)
        self._arrival_ema: Dict[str, float] = {}
        self._prev_delay: Dict[str, Optional[float]] = {}
        self._prev_lam: Dict[str, float] = {}
        self._prev_now: Optional[float] = None
        self._prev_work: Dict[str, int] = {}

    # -- wiring ------------------------------------------------------------

    def bind(self, fleet) -> "Autoscaler":
        self.fleet = fleet
        live = [w for w in fleet.workers if w.state == "live"]
        self.desired = min(self.max_replicas,
                           max(self.min_replicas, len(live)))
        self.desired_by_role = {}
        for w in live:
            r = getattr(w, "role", "both")
            self.desired_by_role[r] = self.desired_by_role.get(r, 0) + 1
        return self

    def _emit(self, action: str, reason: str, before: int, after: int,
              **extra) -> None:
        ev = {"kind": "scale", "action": action, "reason": reason,
              "replicas_before": before, "replicas_after": after,
              "tick": self.fleet.ticks, "desired": self.desired,
              **extra}
        self.events.append(ev)
        if self.fleet.telemetry is not None:
            self.fleet.telemetry.emit_event(ev)
        _log.warning("autoscaler %s (%s): %d -> %d replicas",
                     action, reason, before, after)

    # -- sensing -----------------------------------------------------------

    def _arrival_work(self, role: str) -> int:
        """The fleet's cumulative arrival work for one role's unit of
        service: prompt tokens feed prefill groups, new tokens feed
        decode (and colocated "both") groups."""
        if role == "prefill":
            return int(getattr(self.fleet, "arrived_prompt_tokens", 0))
        return int(getattr(self.fleet, "arrived_new_tokens", 0))

    def _update_arrivals(self, roles, now: Optional[float]) -> None:
        """Diff the monotone work counters since the previous step into
        per-role arrival-rate EMAs (0.7/0.3 — the repo's tick-time
        smoothing). dt ≤ 0 (SimClock not advanced, first step) leaves
        the EMA untouched rather than dividing by zero."""
        if now is None:
            self._prev_now = None
            return
        prev_now = self._prev_now
        self._prev_now = now
        work = {r: self._arrival_work(r) for r in roles}
        prev = self._prev_work
        self._prev_work = dict(prev, **work)
        if prev_now is None:
            return
        dt = now - prev_now
        if dt <= 0:
            return
        for r in roles:
            if r not in prev:
                continue
            rate = max(0.0, (work[r] - prev[r]) / dt)
            old = self._arrival_ema.get(r)
            self._arrival_ema[r] = (rate if old is None
                                    else 0.7 * old + 0.3 * rate)

    def _sense_role(self, role: str, group, beats) -> Dict[str, Any]:
        """One role group's load evidence from the heartbeat FILES (the
        cross-process sensor): backlog in the role's work unit, the
        slowest member's tick-time EMA, and the M/M/c predicted delay
        = max(Erlang-C Wq, the deterministic backlog model)."""
        backlog_key = ("prefill_backlog" if role == "prefill"
                       else "pending_new_tokens")
        pending = 0
        est = None
        for w in group:
            b = beats.get(w.replica_id) or {}
            pending += int(b.get(backlog_key) or 0)
            if b.get("est_tick_s") is not None:
                e = float(b["est_tick_s"])
                est = e if est is None else max(est, e)
        if est is None:
            est = self.fleet.est_tick_s
        max_slots = max((getattr(w.engine, "max_slots", 1)
                         for w in group), default=1)
        lanes = max(1, len(group) * max_slots)
        lam = self._arrival_ema.get(role) or 0.0
        if est is not None:
            mu = 1.0 / est if est > 0 else 0.0
            wq = erlang_c_wait(lam, mu, lanes)
            backlog_delay = (pending / lanes) * est
            delay: Optional[float] = max(wq, backlog_delay)
        else:
            delay = None
        return {"role": role,
                "pending": pending,
                "pending_per_slot": pending / lanes,
                "lanes": lanes,
                "arrival_rate": lam,
                "predicted_delay_s": delay}

    def _sense(self, live, now: Optional[float] = None
               ) -> Dict[str, Any]:
        """Fleet-wide evidence: per-role queue models plus the
        in-flight ledger (a parked request with zero backlog still
        means the fleet is not idle)."""
        groups: Dict[str, list] = {}
        for w in live:
            groups.setdefault(getattr(w, "role", "both"), []).append(w)
        if not groups:
            groups = {"both": []}
        self._update_arrivals(
            sorted(groups), self.fleet.clock() if now is None else now)
        beats = multihost.read_heartbeats(self.fleet.root)
        by_role = {r: self._sense_role(r, g, beats)
                   for r, g in sorted(groups.items())}
        pending = sum(s["pending"] for s in by_role.values())
        delays = [s["predicted_delay_s"] for s in by_role.values()
                  if s["predicted_delay_s"] is not None]
        return {"pending_new_tokens": pending,
                "predicted_delay_s": max(delays) if delays else None,
                "pending_per_slot": max(s["pending_per_slot"]
                                        for s in by_role.values()),
                "in_flight": len(self.fleet._active),
                "by_role": by_role}

    # -- the policy step ---------------------------------------------------

    def _cooled_down(self, tick: int) -> bool:
        return (self._last_scale_tick is None
                or tick - self._last_scale_tick >= self.cooldown_ticks)

    def step(self, now: Optional[float] = None) -> None:
        """One policy decision, run per fleet tick (after the health
        refresh, before reconcile — a replacement spawned here receives
        the dead replica's orphans in the same tick)."""
        fleet = self.fleet
        assert fleet is not None, "bind() the autoscaler to a fleet first"
        tick = fleet.ticks
        live = [w for w in fleet.workers if w.state == "live"]
        draining = [w for w in fleet.workers if w.state == "draining"]
        sense = self._sense(live, now)
        evidence = {k: v for k, v in sense.items() if k != "by_role"}
        m = getattr(fleet, "metrics", None)
        if m is not None:
            # the controller's sensor readings, published per role
            # (ISSUE 19): what the policy SAW when it decided — the
            # dashboard's answer to "why did it scale"
            for role, s in sense["by_role"].items():
                sm = m.scoped(role=role)
                sm.gauge("autoscaler_arrival_rate",
                         "per-role arrival-work EMA (units/s)"
                         ).set(s["arrival_rate"])
                sm.gauge("autoscaler_pending_per_slot",
                         "backlog per decode lane"
                         ).set(s["pending_per_slot"])
                if (s["predicted_delay_s"] is not None
                        and math.isfinite(s["predicted_delay_s"])):
                    sm.gauge("autoscaler_predicted_delay_s",
                             "max(Erlang-C Wq, backlog model)"
                             ).set(s["predicted_delay_s"])
            m.gauge("autoscaler_desired_replicas",
                    "policy's desired fleet total"
                    ).set(self.desired or 0)

        # 1) replacement: heal the envelope before judging load. Healing
        # is not scaling — it ignores the up/down cooldown but pays from
        # its own bounded budget, loud when exhausted. A dead replica
        # is replaced IN ITS ROLE — a disaggregated fleet that lost its
        # prefill replica needs a prefill replica back, not a spare
        # decoder.
        fenced = getattr(fleet, "readmit_pending", lambda: [])()
        deficit_role = self._role_deficit(live, draining, fenced)
        if (deficit_role is not None
                and len(live) + len(draining) < self.max_replicas):
            if self.replacements >= self.max_replacements:
                raise AutoscalerGaveUp(
                    f"replacement budget exhausted "
                    f"({self.replacements}/{self.max_replacements} "
                    f"cold spawns): replicas are dying faster than "
                    f"policy may heal — fix the fleet, not the budget",
                    self.events)
            self.replacements += 1
            before = len(live)
            rid = fleet.spawn_replica(
                deficit_role if deficit_role != "both" else None)
            self._emit("replace", "replica-dead", before, before + 1,
                       replica=rid, role=deficit_role,
                       replacements=self.replacements, **evidence)
            return

        # 2) idle bookkeeping for the scale-down grace window
        idle = (sense["pending_new_tokens"] == 0
                and sense["in_flight"] == 0)
        self._idle_ticks = self._idle_ticks + 1 if idle else 0

        # the derivative gate's memory updates EVERY step (cooldown
        # included) — a stale previous delay would misread a cooling
        # queue as a fresh breach the moment the cooldown lifts
        prev_delay = dict(self._prev_delay)
        prev_lam = dict(self._prev_lam)
        for r, s in sense["by_role"].items():
            self._prev_delay[r] = s["predicted_delay_s"]
            self._prev_lam[r] = s["arrival_rate"]

        if not self._cooled_down(tick):
            return

        # 3) scale up on the first role whose predicted delay breaches
        # (fallback: raw backlog-per-lane when no tick-time evidence
        # exists yet), gated on the delay derivative: a breach that is
        # already IMPROVING (previous step's delay was higher) gets no
        # more capacity — the last spawn is still absorbing it. The
        # capacity envelope counts DRAINING replicas too — their
        # engines still hold memory/processes until released.
        if len(live) + len(draining) < self.max_replicas:
            for role, s in sense["by_role"].items():
                delay = s["predicted_delay_s"]
                if delay is not None and self.up_delay_s is not None:
                    breach = delay > self.up_delay_s
                    up_reason = "predicted-delay-breach"
                else:
                    breach = (s["pending_per_slot"]
                              > self.up_pending_per_slot)
                    up_reason = "backlog-threshold"
                if not breach:
                    continue
                pd = prev_delay.get(role)
                if delay is not None and pd is not None:
                    if delay < pd:
                        continue        # improving: let it drain
                    if (math.isinf(delay) and math.isinf(pd)
                            and s["arrival_rate"]
                            < prev_lam.get(role, math.inf)):
                        # both reads saturated (inf < inf is useless)
                        # — judge the breach by the arrival-rate
                        # derivative instead: a decaying λ EMA means
                        # the burst has passed and the last spawn is
                        # still absorbing it
                        continue
                before = len(live)
                self.desired = min(self.max_replicas, self.desired + 1)
                self.desired_by_role[role] = \
                    self.desired_by_role.get(role, 0) + 1
                rid = fleet.spawn_replica(
                    role if role != "both" else None)
                self._last_scale_tick = tick
                self._emit("up", up_reason, before, before + 1,
                           replica=rid, role=role,
                           predicted_delay_role_s=delay, **evidence)
                return

        # 4) scale down on sustained idle, through drain() — lossless.
        # Never drain a role's LAST live replica: a prefill group with
        # no decode peer (or vice versa) deadlocks the handoff path.
        if (self._idle_ticks >= self.idle_grace_ticks
                and len(live) > self.min_replicas
                and self.desired > self.min_replicas):
            role_counts: Dict[str, int] = {}
            for w in live:
                r = getattr(w, "role", "both")
                role_counts[r] = role_counts.get(r, 0) + 1
            cands = [w for w in live
                     if role_counts[getattr(w, "role", "both")] > 1]
            if not cands:
                return
            victim = min(cands, key=lambda w: (
                w.scheduler.pending_new_tokens(), -w.replica_id))
            vrole = getattr(victim, "role", "both")
            before = len(live)
            self.desired -= 1
            if self.desired_by_role.get(vrole, 0) > 0:
                self.desired_by_role[vrole] -= 1
            fleet.drain(victim.replica_id)
            self._last_scale_tick = tick
            self._idle_ticks = 0
            self._emit("down", "sustained-idle", before, before - 1,
                       replica=victim.replica_id, role=vrole,
                       **evidence)

    def _role_deficit(self, live, draining, fenced=()) -> Optional[str]:
        """The first role short of its desired count (None = envelope
        healthy). Draining replicas still count — the replacement
        branch must not double-heal a scale-down in progress. Fenced
        replicas within their re-admission grace window count too
        (ISSUE 20): fenced ≠ dead for capacity math — a zombie behind a
        partition is expected back, and spawning a replacement AND
        re-admitting the original would over-provision the role."""
        have: Dict[str, int] = {}
        for w in list(live) + list(draining) + list(fenced):
            r = getattr(w, "role", "both")
            have[r] = have.get(r, 0) + 1
        for r in sorted(self.desired_by_role):
            if have.get(r, 0) < self.desired_by_role[r]:
                return r
        # legacy guard: totals disagree without a per-role deficit
        # (e.g. desired bumped externally) — heal with a "both" spawn
        if len(live) + len(draining) + len(fenced) < (self.desired or 0):
            return "both"
        return None
