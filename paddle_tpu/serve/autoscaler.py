"""Supervised autoscaler for the serving fleet (ISSUE 13): elastic
capacity as a POLICY LOOP over evidence the fleet already publishes.

The mechanics existed before this module: ``drain()`` is lossless
scale-down (PR 11), :meth:`~paddle_tpu.serve.fleet.ServingFleet.
spawn_replica` is scale-up, and every replica's load rides its PR-10
heartbeat file. The autoscaler adds only the decisions, and the
discipline that keeps decisions from flapping:

- **Sense from the files.** Load is read from the heartbeat payloads
  (``pending_new_tokens`` per live replica, the child-reported tick-time
  EMA) — the same evidence a watchdog on another host would have, not a
  private pointer into a scheduler. The predicted queue delay is the
  PR-11 shed model fleet-wide: ``backlog / (live · max_slots)`` ticks at
  the observed tick time.
- **Scale up on predicted-delay breach** (``up_delay_s``): capacity is
  added when the backlog's predicted delay says requests queued NOW will
  wait too long — before deadlines start shedding, not after.
- **Scale down on sustained idle** (``idle_grace_ticks`` consecutive
  ticks with zero backlog AND zero in-flight requests): one idle instant
  is a gap between bursts; only a sustained lull pays back a replica.
  Scale-down always routes through ``drain()`` — zero lost requests, by
  the PR-11 contract.
- **Hysteresis** (``cooldown_ticks``): after ANY up/down decision the
  policy holds still, so bursty traffic that would flap a naive
  threshold policy produces a BOUNDED number of scale events (the CI
  test pins this). The grace counter resets on any load.
- **Cold-spawn replacement under a restart budget**: a replica the
  router declared dead is replaced (``action="replace"``) outside the
  up/down cooldown — healing is not scaling — but under
  ``max_replacements``; when the budget is exhausted the autoscaler
  GIVES UP LOUD (:class:`AutoscalerGaveUp` with the full event ledger,
  the PR-10 supervisor rule: a fleet whose replicas keep dying has a
  bug, and respawning forever would hide it).

Every decision emits a ``kind="scale"`` telemetry event (action,
reason, replica counts before/after, the evidence) — aggregated by
``obs.report``'s serving block via
:func:`~paddle_tpu.obs.percentiles.summarize_scale`.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from ..parallel import multihost

__all__ = ["Autoscaler", "AutoscalerGaveUp"]

_log = logging.getLogger("paddle_tpu.serve.autoscaler")


class AutoscalerGaveUp(RuntimeError):
    """The replacement budget is exhausted: replicas keep dying faster
    than the policy may heal. Carries the event ledger so the operator
    sees every decision that led here."""

    def __init__(self, msg: str, events: List[Dict[str, Any]]):
        super().__init__(msg)
        self.events = list(events)


class Autoscaler:
    """The policy loop (see module docstring). Construct with policy
    knobs, pass to ``ServingFleet(autoscaler=...)`` (or call
    :meth:`bind` yourself); :meth:`step` runs inside every fleet tick.

    Args:
      min_replicas / max_replicas: the live-capacity envelope. Scale
        down never goes below ``min_replicas`` (and ``drain()`` itself
        refuses below 1); scale up and replacement never exceed
        ``max_replicas``.
      up_delay_s: predicted-queue-delay breach that triggers scale-up.
        Needs tick-time evidence (heartbeat-reported EMA or the fleet's
        ``est_tick_s`` prior); with neither, ``up_pending_per_slot``
        is the fallback trigger.
      up_pending_per_slot: backlog-per-decode-lane fallback threshold.
      idle_grace_ticks: consecutive fully-idle ticks before scale-down.
      cooldown_ticks: minimum fleet ticks between scale up/down events
        (the hysteresis that bounds flapping).
      max_replacements: cold-spawn budget for replacing dead replicas;
        exhausted → :class:`AutoscalerGaveUp`.
    """

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 8,
                 up_delay_s: Optional[float] = None,
                 up_pending_per_slot: float = 8.0,
                 idle_grace_ticks: int = 20, cooldown_ticks: int = 10,
                 max_replacements: int = 3):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_delay_s = up_delay_s
        self.up_pending_per_slot = float(up_pending_per_slot)
        self.idle_grace_ticks = int(idle_grace_ticks)
        self.cooldown_ticks = int(cooldown_ticks)
        self.max_replacements = int(max_replacements)
        self.fleet = None
        self.desired: Optional[int] = None
        self.events: List[Dict[str, Any]] = []
        self.replacements = 0
        self._idle_ticks = 0
        self._last_scale_tick: Optional[int] = None

    # -- wiring ------------------------------------------------------------

    def bind(self, fleet) -> "Autoscaler":
        self.fleet = fleet
        live = sum(1 for w in fleet.workers if w.state == "live")
        self.desired = min(self.max_replicas,
                           max(self.min_replicas, live))
        return self

    def _emit(self, action: str, reason: str, before: int, after: int,
              **extra) -> None:
        ev = {"kind": "scale", "action": action, "reason": reason,
              "replicas_before": before, "replicas_after": after,
              "tick": self.fleet.ticks, "desired": self.desired,
              **extra}
        self.events.append(ev)
        if self.fleet.telemetry is not None:
            self.fleet.telemetry.emit_event(ev)
        _log.warning("autoscaler %s (%s): %d -> %d replicas",
                     action, reason, before, after)

    # -- sensing -----------------------------------------------------------

    def _sense(self, live) -> Dict[str, Any]:
        """Load evidence from the heartbeat FILES (the cross-process
        sensor), with the in-flight ledger deciding idleness — a parked
        request with zero backlog still means the fleet is not idle."""
        beats = multihost.read_heartbeats(self.fleet.root)
        pending = 0
        est = None
        for w in live:
            b = beats.get(w.replica_id) or {}
            pending += int(b.get("pending_new_tokens") or 0)
            if b.get("est_tick_s") is not None:
                e = float(b["est_tick_s"])
                est = e if est is None else max(est, e)
        if est is None:
            est = self.fleet.est_tick_s
        max_slots = max((getattr(w.engine, "max_slots", 1)
                         for w in live), default=1)
        lanes = max(1, len(live) * max_slots)
        delay = (pending / lanes) * est if est is not None else None
        return {"pending_new_tokens": pending,
                "predicted_delay_s": delay,
                "pending_per_slot": pending / lanes,
                "in_flight": len(self.fleet._active)}

    # -- the policy step ---------------------------------------------------

    def _cooled_down(self, tick: int) -> bool:
        return (self._last_scale_tick is None
                or tick - self._last_scale_tick >= self.cooldown_ticks)

    def step(self, now: Optional[float] = None) -> None:
        """One policy decision, run per fleet tick (after the health
        refresh, before reconcile — a replacement spawned here receives
        the dead replica's orphans in the same tick)."""
        fleet = self.fleet
        assert fleet is not None, "bind() the autoscaler to a fleet first"
        tick = fleet.ticks
        live = [w for w in fleet.workers if w.state == "live"]
        draining = [w for w in fleet.workers if w.state == "draining"]
        sense = self._sense(live)

        # 1) replacement: heal the envelope before judging load. Healing
        # is not scaling — it ignores the up/down cooldown but pays from
        # its own bounded budget, loud when exhausted.
        if (len(live) + len(draining) < self.desired
                and len(live) < self.max_replicas):
            if self.replacements >= self.max_replacements:
                raise AutoscalerGaveUp(
                    f"replacement budget exhausted "
                    f"({self.replacements}/{self.max_replacements} "
                    f"cold spawns): replicas are dying faster than "
                    f"policy may heal — fix the fleet, not the budget",
                    self.events)
            self.replacements += 1
            before = len(live)
            rid = fleet.spawn_replica()
            self._emit("replace", "replica-dead", before, before + 1,
                       replica=rid,
                       replacements=self.replacements, **sense)
            return

        # 2) idle bookkeeping for the scale-down grace window
        idle = (sense["pending_new_tokens"] == 0
                and sense["in_flight"] == 0)
        self._idle_ticks = self._idle_ticks + 1 if idle else 0

        if not self._cooled_down(tick):
            return

        # 3) scale up on predicted-delay breach (fallback: raw
        # backlog-per-lane when no tick-time evidence exists yet). The
        # capacity envelope counts DRAINING replicas too — their
        # engines still hold memory/processes until released, and the
        # replacement branch already counts them.
        delay = sense["predicted_delay_s"]
        if delay is not None and self.up_delay_s is not None:
            breach = delay > self.up_delay_s
            up_reason = "predicted-delay-breach"
        else:
            breach = sense["pending_per_slot"] > self.up_pending_per_slot
            up_reason = "backlog-threshold"
        if breach and len(live) + len(draining) < self.max_replicas:
            before = len(live)
            self.desired = min(self.max_replicas, self.desired + 1)
            rid = fleet.spawn_replica()
            self._last_scale_tick = tick
            self._emit("up", up_reason, before, before + 1,
                       replica=rid, **sense)
            return

        # 4) scale down on sustained idle, through drain() — lossless
        if (self._idle_ticks >= self.idle_grace_ticks
                and len(live) > self.min_replicas
                and self.desired > self.min_replicas):
            victim = min(live, key=lambda w: (
                w.scheduler.pending_new_tokens(), -w.replica_id))
            before = len(live)
            self.desired -= 1
            fleet.drain(victim.replica_id)
            self._last_scale_tick = tick
            self._idle_ticks = 0
            self._emit("down", "sustained-idle", before, before - 1,
                       replica=victim.replica_id, **sense)
