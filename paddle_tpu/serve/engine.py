"""The decode engine — two compiled fixed-shape programs serving any
number of concurrent ragged requests.

The framework's static-shapes contract ("Static shapes everywhere",
DESIGN_DECISIONS) is what makes serving latency predictable: a program
that retraces when a request arrives or finishes pays seconds of XLA
compile mid-traffic. So the engine compiles exactly TWO programs and
reuses them for the whole process lifetime:

- **prefill** at the fixed padded width ``[1, W]`` (``W`` = the cache's
  per-slot context capacity): runs :meth:`TransformerLM.prefill`, writes
  the prompt's per-layer K/V into the slot's pool pages, and returns the
  first greedy token. Every prompt, whatever its length, runs this one
  shape.
- the **decode tick** at the fixed slot count ``[S]``: one
  :meth:`TransformerLM.decode_step` over ALL slots with an ``active``
  mask — empty slots ride along as masked lanes (null-block scatter,
  zero-length attention), so admissions and evictions between ticks are
  pure host-side table edits that never change the compiled shape.

The KV pools are the tick's DONATED carry: the pool buffers flip between
two XLA allocations instead of reallocating per token. Block tables,
lengths, and the token front are small host-authoritative arrays pushed
per call (bytes, not megabytes — the pools never cross the host
boundary).

Sampling is greedy (argmax) — deterministic, which is what lets the serve
tests pin engine output against the training forward bit-for-bit.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .kv_cache import PagedKVCache, scatter_prefill

__all__ = ["DecodeEngine", "AdmitProbe"]


@dataclasses.dataclass
class AdmitProbe:
    """Structured admission verdict (ISSUE 11 satellite): WHY a request
    can't start matters to the router — ``"slots"`` clears at the next
    eviction (queue briefly), ``"blocks"`` is KV-pool saturation that can
    persist for a straggler's whole lifetime (prefer another replica, or
    shed), ``"width"`` can never clear (reject). ``ok`` mirrors the old
    boolean ``can_admit`` answer."""
    ok: bool
    reason: Optional[str]          # None | "width" | "slots" | "blocks"
    blocks_needed: int
    free_blocks: int
    free_slots: int


def _resolve_attention(attention: str) -> str:
    """``"auto"`` picks the Pallas paged kernel on TPU and the bit-exact
    XLA gather path elsewhere (the same auto-select rule as the flash
    kernels' ``interpret=None``)."""
    if attention == "auto":
        return "paged" if jax.default_backend() == "tpu" else "xla"
    if attention not in ("paged", "xla"):
        raise ValueError(f"attention must be 'auto'|'paged'|'xla', "
                         f"got {attention!r}")
    return attention


class DecodeEngine:
    """Compiled serving runtime for a :class:`~paddle_tpu.models.
    TransformerLM` checkpoint.

    Args:
      model: a TransformerLM (homogeneous blocks; any training config —
        the serve path restacks the per-block params at trace time, so
        checkpoints are shape-compatible as-is).
      variables: the model's variables dict (training checkpoint or
        ``load_inference_model`` output).
      max_slots: decode-tick batch width S — the max concurrent
        sequences. Fixed at compile time; empty slots are masked lanes.
      block_size: KV tokens per pool block. Small blocks waste less on
        ragged tails but cost more gather indirection; 16 is the
        conventional sweet spot (DESIGN_DECISIONS PR-9).
      num_blocks: pool size. Default sizes the pool for full residency
        (every slot at full context) — shrink it to test admission
        backpressure.
      max_blocks_per_seq: per-slot table width; the per-slot context
        capacity is ``max_blocks_per_seq * block_size`` (defaults to
        ``model.max_len // block_size``, and must keep the capacity
        within ``model.max_len`` — positions are embedded).
      attention: ``"auto" | "paged" | "xla"`` — see
        :func:`_resolve_attention`.
      telemetry: optional :class:`paddle_tpu.obs.Telemetry`; the engine
        emits one ``kind="decode_tick"`` record per tick (dispatch wall,
        active slots, tokens/sec) and the scheduler adds per-request
        records through the same object.
      dtype: KV pool dtype. f32 default matches the projections' f32
        accumulation under both the f32 and bf16-compute policies.
    """

    def __init__(self, model, variables, *, max_slots: int = 4,
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 max_blocks_per_seq: Optional[int] = None,
                 attention: str = "auto", telemetry=None,
                 dtype=jnp.float32):
        self.model = model
        self.variables = variables
        self.telemetry = telemetry
        self.attention = _resolve_attention(attention)
        num_layers = len(model.blocks)
        num_heads = model.blocks[0].attn.num_heads
        dim = model.emb.dim
        head_dim = model.blocks[0].attn.head_dim or dim // num_heads
        if max_blocks_per_seq is None:
            max_blocks_per_seq = max(1, model.max_len // block_size)
        if max_blocks_per_seq * block_size > model.max_len:
            raise ValueError(
                f"slot capacity {max_blocks_per_seq * block_size} exceeds "
                f"model.max_len={model.max_len} (positions are embedded)")
        if num_blocks is None:
            num_blocks = max_slots * max_blocks_per_seq + 1   # + null block
        self.cache = PagedKVCache(
            num_layers, num_heads, head_dim, num_blocks, block_size,
            max_slots=max_slots, max_blocks_per_seq=max_blocks_per_seq,
            dtype=dtype)
        self.max_slots = max_slots
        # host-authoritative slot state beside the cache's tables/lengths
        self.active = np.zeros((max_slots,), bool)
        self.tokens = np.zeros((max_slots,), np.int32)   # next to decode
        self.ticks = 0
        self.tokens_generated = 0

        W = self.cache.context_width
        attn_impl = self.attention

        def prefill_fn(variables, pages_k, pages_v, ids, length, table):
            # ids [1, W] padded; length [1]; table [1, MB]
            logits, (ks, vs) = model.apply(variables, ids,
                                           method="prefill")
            scat = jax.vmap(scatter_prefill, in_axes=(0, 0, None, None))
            pages_k = scat(pages_k, ks.astype(pages_k.dtype), table, length)
            pages_v = scat(pages_v, vs.astype(pages_v.dtype), table, length)
            last = jnp.take_along_axis(
                logits, (length - 1)[:, None, None], axis=1)[0, 0]
            return pages_k, pages_v, jnp.argmax(last).astype(jnp.int32)

        def tick_fn(variables, pages_k, pages_v, tables, lengths, tokens,
                    active):
            logits, (pages_k, pages_v, _) = model.apply(
                variables, tokens, (pages_k, pages_v, tables), lengths,
                active, attn_impl=attn_impl, method="decode_step")
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return pages_k, pages_v, nxt

        # donate the KV pools: the tick's carry flips between two
        # allocations instead of growing HBM per token
        self._prefill_fn = jax.jit(prefill_fn, donate_argnums=(1, 2))
        self._tick_fn = jax.jit(tick_fn, donate_argnums=(1, 2))
        self._W = W

    # -- introspection -----------------------------------------------------

    @property
    def context_width(self) -> int:
        return self._W

    def compile_counts(self) -> Dict[str, int]:
        """Distinct traced programs per entry point — the no-retrace
        invariant is both == 1 after warmup, across any admit/evict
        churn (the bench serving gate asserts it)."""
        return {"prefill": int(self._prefill_fn._cache_size()),
                "tick": int(self._tick_fn._cache_size())}

    def free_slots(self) -> List[int]:
        return [s for s in range(self.max_slots) if not self.active[s]]

    def admit_probe(self, total_len: int,
                    include_slots: bool = True) -> AdmitProbe:
        """Structured admission check for a sequence that may grow to
        ``total_len`` tokens (prompt + generation budget): the first
        failing constraint, in never-clears-first order — ``"width"``
        (exceeds slot capacity), ``"slots"`` (no free decode lane;
        skipped with ``include_slots=False`` for callers that manage
        slots themselves, like the scheduler), ``"blocks"`` (KV pool
        can't cover the worst-case reservation)."""
        blocks_needed = self.cache.blocks_needed(total_len)
        free_slots = len(self.free_slots())
        if total_len > self._W:
            reason = "width"
        elif include_slots and free_slots == 0:
            reason = "slots"
        elif blocks_needed > self.cache.free_blocks:
            reason = "blocks"
        else:
            reason = None
        return AdmitProbe(ok=reason is None, reason=reason,
                          blocks_needed=blocks_needed,
                          free_blocks=self.cache.free_blocks,
                          free_slots=free_slots)

    def can_admit(self, total_len: int) -> bool:
        """Whether the pool can host a sequence that may grow to
        ``total_len`` tokens (prompt + generation budget). Admission
        reserves the worst case up front so a running request can never
        strand mid-decode without a block (DESIGN_DECISIONS PR-9).
        Boolean view of :meth:`admit_probe` (slot availability excluded —
        the historical contract; the scheduler tracks slots itself)."""
        return self.admit_probe(total_len, include_slots=False).ok

    # -- request lifecycle -------------------------------------------------

    def stage_prompt(self, prompt: List[int]) -> np.ndarray:
        """Pad a prompt to the fixed prefill width ``[1, W]`` — pure host
        work the scheduler runs at SUBMIT time (the PR-3 staging move:
        admission-path host prep happens off the tick's critical path)."""
        P = len(prompt)
        if not 0 < P <= self._W:
            raise ValueError(f"prompt length {P} not in [1, {self._W}]")
        ids = np.zeros((1, self._W), np.int32)
        ids[0, :P] = prompt
        return ids

    def admit(self, slot: int, prompt: List[int],
              reserve_len: Optional[int] = None,
              staged: Optional[np.ndarray] = None) -> int:
        """Prefill ``prompt`` into ``slot`` and return the first greedy
        token. ``reserve_len`` (default: prompt length) eagerly allocates
        blocks for the sequence's full growth target; ``staged`` is an
        already-padded :meth:`stage_prompt` array."""
        assert not self.active[slot], f"slot {slot} is occupied"
        P = len(prompt)
        target = max(P, reserve_len or P)
        if not self.cache.ensure_capacity(slot, target):
            raise RuntimeError(
                f"KV pool exhausted admitting slot {slot} "
                f"(need {self.cache.blocks_needed(target)} blocks, "
                f"{self.cache.free_blocks} free) — gate admissions on "
                f"can_admit()")
        ids = staged if staged is not None else self.stage_prompt(prompt)
        self.cache.k, self.cache.v, tok = self._prefill_fn(
            self.variables, self.cache.k, self.cache.v,
            jnp.asarray(ids), jnp.asarray([P], jnp.int32),
            jnp.asarray(self.cache.tables[slot:slot + 1]))
        self.cache.lengths[slot] = P
        self.active[slot] = True
        self.tokens[slot] = int(tok)
        return int(tok)

    def evict(self, slot: int) -> None:
        """Free ``slot``'s blocks back to the pool; the lane masks off at
        the next tick. Stale pool contents are not wiped (finite, always
        length-masked) — reuse is a table edit."""
        self.cache.free_slot(slot)
        self.active[slot] = False
        self.tokens[slot] = 0

    def decode_tick(self) -> np.ndarray:
        """One compiled decode step over every slot. Appends each active
        slot's pending token to its KV, samples the next greedy token,
        and returns the new token front ``[S]`` (inactive lanes 0)."""
        t0 = time.perf_counter()
        # the new token lands at position lengths[slot]: every active slot
        # must own that block, or the scatter would silently route to the
        # null block / clamp onto live data — fail loud instead
        for slot in np.flatnonzero(self.active):
            need = self.cache.blocks_needed(int(self.cache.lengths[slot]) + 1)
            if need > len(self.cache._owned[slot]):
                raise RuntimeError(
                    f"slot {slot} decoding past its reservation (length "
                    f"{int(self.cache.lengths[slot])} needs block {need}, "
                    f"owns {len(self.cache._owned[slot])}) — admit with a "
                    f"larger reserve_len or call cache.ensure_capacity")
        tables, lengths = self.cache.device_tables()
        self.cache.k, self.cache.v, nxt = self._tick_fn(
            self.variables, self.cache.k, self.cache.v, tables, lengths,
            jnp.asarray(self.tokens), jnp.asarray(self.active))
        # the dispatch is async: host bookkeeping that doesn't need the
        # sampled tokens runs UNDER the in-flight device call (the PR-3
        # overlap move at tick scale); np.asarray(nxt) is the drain
        n_active = int(self.active.sum())
        self.cache.lengths[self.active] += 1
        nxt = np.asarray(nxt)
        self.tokens = np.where(self.active, nxt, 0).astype(np.int32)
        self.ticks += 1
        self.tokens_generated += n_active
        if self.telemetry is not None:
            wall = time.perf_counter() - t0
            self.telemetry.emit_event({
                "kind": "decode_tick", "tick": self.ticks,
                "active_slots": n_active, "wall_ms": round(wall * 1e3, 4),
                "tokens_per_sec": round(n_active / wall, 2) if wall else None,
                "free_blocks": self.cache.free_blocks,
            })
        return self.tokens.copy()

    # -- observability -----------------------------------------------------

    def attribution_report(self, emit: bool = True) -> Dict[str, Any]:
        """MFU-gap attribution of the compiled decode tick (the
        ``Trainer.attribution_report`` recipe: one AOT
        ``lower().compile()``, zero executions). Decode is memory-bound —
        every tick streams the full parameter set and the active KV for
        one token of compute — and the report's ``decode`` block says so
        on the spec-sheet HBM tables (``bound="memory"``)."""
        from ..obs import attribution as attr_lib
        from ..obs import hloprof
        from ..obs.telemetry import lowered_hlo_flops
        tables, lengths = self.cache.device_tables()
        lowered = self._tick_fn.lower(
            self.variables, self.cache.k, self.cache.v, tables, lengths,
            jnp.asarray(self.tokens), jnp.asarray(self.active))
        compiled = lowered.compile()
        analysis = hloprof.parse_module(compiled.as_text())
        report = attr_lib.build_report(
            analysis,
            device_kind=getattr(jax.devices()[0], "device_kind", ""),
            n_devices=1,
            cost_analysis_flops=lowered_hlo_flops(compiled),
            meta={"program": "decode_tick", "max_slots": self.max_slots,
                  "context_width": self._W,
                  "block_size": self.cache.block_size,
                  "attention": self.attention})
        if emit and self.telemetry is not None:
            self.telemetry.emit_event(report)
        return report
