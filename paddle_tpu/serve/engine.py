"""The decode engine — two compiled fixed-shape programs serving any
number of concurrent ragged requests.

The framework's static-shapes contract ("Static shapes everywhere",
DESIGN_DECISIONS) is what makes serving latency predictable: a program
that retraces when a request arrives or finishes pays seconds of XLA
compile mid-traffic. So the engine compiles exactly TWO programs and
reuses them for the whole process lifetime:

- **prefill**: at the fixed padded width ``[1, W]`` (``W`` = the cache's
  per-slot context capacity) by default, or — with
  ``prefill_chunk=C`` — at the fixed CHUNK width ``[1, C]``, so a long
  prompt becomes ``ceil(P/C)`` cheap calls the scheduler interleaves
  between decode ticks instead of one monolithic stall (ISSUE 12:
  chunked prefill bounds running slots' TPOT under long admissions).
- the **decode tick** at the fixed slot count ``[S]`` — or, with
  ``speculative=k``, at ``[S, 1+k]``: every tick carries each slot's
  pending token plus ``k`` n-gram self-drafted guesses, one batched
  dispatch verifies all of them, and the host accepts the longest
  draft prefix the model agrees with plus the model's own next token.
  Greedy output is BIT-IDENTICAL to the non-speculative engine by
  construction (each span row is computed by the exact q_len=1 op
  sequence) — speculation only changes how many tokens one memory-bound
  tick retires, never which tokens. The drafted width is a static
  shape, so ``compile_counts()`` stays pinned at {prefill: 1, tick: 1}.

**Copy-on-write prefix sharing** (``share_prefix=True``): admission
looks the prompt up in the cache-resident prefix index and maps every
full-block hit into the slot's table BY REFERENCE (refcounted — zero
new HBM, zero re-scatter); only the divergent tail allocates and
prefills fresh blocks. An exact-duplicate prompt additionally shares
the partial boundary block and forks it (one-block device copy) at the
first divergent write — the OS COW page move at the divergence point.

The KV pools are the tick's DONATED carry: the pool buffers flip between
two XLA allocations instead of reallocating per token. Block tables,
lengths, and the token front are small host-authoritative arrays pushed
per call (bytes, not megabytes — the pools never cross the host
boundary).

Sampling is greedy (argmax) by default — deterministic, which is what
lets the serve tests pin engine output against the training forward
bit-for-bit. ``sampling=SamplingConfig(...)`` switches the tick to
seeded stochastic sampling (temperature / top-k / top-p with per-slot,
per-tick PRNG keys); it composes with sharing, chunked prefill AND
speculation — stochastic verification uses the Leviathan
rejection-sampling rule (PAPERS.md [S3], ISSUE 14): a drafted token
``d`` with filtered target probability ``p(d)`` is accepted with
probability ``p(d)`` (the draft distribution is a point mass, so the
accept ratio ``min(1, p/q)`` reduces to ``p(d)``); on rejection the
token resamples from the residual ``norm(max(p - q, 0))`` — ``p`` with
``d`` excluded — which preserves the target distribution EXACTLY by
the standard [S3] argument. Acceptance randomness rides the same
per-slot ``fold_in`` key tree as plain sampling, so a fixed seed
replays the identical token stream.

**Int8 KV quantization** (``kv_dtype="int8"``, ISSUE 14): the pools
store int8 values plus per-row-per-head scale pages; scatters quantize,
the attention kernels dequantize in VMEM (the XLA path in the gather).
Roughly 3-4x the resident sequences per HBM byte at a measured logit
drift bound — the serving bench gate pins >= 99% greedy token
agreement vs the f32 pool on its gate set. **Radix retention** rides
the prefix cache (see ``kv_cache``): evicted registered blocks park in
a retained LRU and later same-prefix admissions hit them without any
concurrently-resident sharer.

**Tensor-parallel serving** (``mesh=``, ISSUE 15): the same two
programs run sharded over a tp mesh — params placed by the megatron
rule, KV pools split on the HEAD axis (each shard owns ``H/tp`` heads
of every block; int8 scale pages split identically), per-shard
attention over local heads, and the row-parallel out/ffn2 projections
all-reduced back to the replicated residual so the logits assemble on
the existing tp head path. The host side never learns about shards:
one logical block table drives every device's pool, which is why CoW,
retention, speculation, chunking and the scheduler compose unchanged
and the tp=2 engine is token-identical (greedy, f32) to the
single-device one. Capacity accounting (``kv_bytes_per_token``) turns
per-shard, so resident sequences at equal per-device HBM scale with
the mesh.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .kv_cache import PagedKVCache, scatter_prefill_pages
from ..parallel.sharding import tp_constrain, tp_shard_scope

__all__ = ["DecodeEngine", "AdmitProbe", "SamplingConfig"]


@dataclasses.dataclass
class AdmitProbe:
    """Structured admission verdict (ISSUE 11 satellite): WHY a request
    can't start matters to the router — ``"slots"`` clears at the next
    eviction (queue briefly), ``"blocks"`` is KV-pool saturation that can
    persist for a straggler's whole lifetime (prefer another replica, or
    shed), ``"width"`` can never clear (reject). ``ok`` mirrors the old
    boolean ``can_admit`` answer. ``free_blocks`` counts RECLAIMABLE
    capacity (genuinely free + retained-LRU blocks — ISSUE 14: a probe
    on raw free alone undercounts and sheds spuriously under
    retention); ``raw_free_blocks`` keeps the eager-free number and
    ``retained_blocks`` the difference's provenance."""
    ok: bool
    reason: Optional[str]          # None | "width" | "slots" | "blocks"
    blocks_needed: int
    free_blocks: int
    free_slots: int
    raw_free_blocks: int = 0
    retained_blocks: int = 0


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """Stochastic decoding knobs (ISSUE 12 satellite). Applied inside
    the compiled tick with a per-slot, per-tick PRNG key
    (``fold_in(fold_in(seed, tick), slot)``) so a fixed seed replays the
    exact token stream — seeded-deterministic, not merely "random".
    Filters compose in the conventional order: temperature scaling,
    then top-k truncation, then top-p (nucleus) truncation."""
    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: int = 0

    def validate(self, vocab: int) -> None:
        if not self.temperature > 0:
            raise ValueError(f"temperature must be > 0 (greedy is "
                             f"sampling=None), got {self.temperature}")
        if self.top_k is not None and not 1 <= self.top_k <= vocab:
            raise ValueError(f"top_k must be in [1, {vocab}], "
                             f"got {self.top_k}")
        if self.top_p is not None and not 0 < self.top_p <= 1:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")


def _filter_logits(cfg: SamplingConfig, logits):
    """Temperature -> top-k -> top-p filtering over the LAST axis (any
    leading shape): the filtered logits define the target distribution
    ``p`` both plain sampling and the [S3] accept/resample rule draw
    from. Top-k keeps the k highest logits; top-p keeps the smallest
    descending-probability set whose mass reaches p (the head token
    always survives both)."""
    x = logits.astype(jnp.float32) / cfg.temperature
    if cfg.top_k is not None:
        kth = jnp.sort(x, axis=-1)[..., -cfg.top_k][..., None]
        x = jnp.where(x >= kth, x, -jnp.inf)
    if cfg.top_p is not None:
        sorted_x = jnp.sort(x, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_x, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep entries whose PRECEDING cumulative mass is < p (the
        # first token always survives); find the cutoff logit value
        keep = (cum - probs) < cfg.top_p
        cutoff = jnp.min(jnp.where(keep, sorted_x, jnp.inf), axis=-1)
        x = jnp.where(x >= cutoff[..., None], x, -jnp.inf)
    return x


def _sample_tokens(cfg: SamplingConfig, logits, keys):
    """Traced sampler: ``logits [S, V]``, ``keys [S, 2]`` -> ``[S]``
    int32 draws from the filtered target distribution."""
    x = _filter_logits(cfg, logits)
    return jax.vmap(jax.random.categorical)(keys, x).astype(jnp.int32)


def _resolve_attention(attention: str) -> str:
    """``"auto"`` picks the Pallas paged kernel on TPU and the bit-exact
    XLA gather path elsewhere (the same auto-select rule as the flash
    kernels' ``interpret=None``)."""
    if attention == "auto":
        return "paged" if jax.default_backend() == "tpu" else "xla"
    if attention not in ("paged", "xla"):
        raise ValueError(f"attention must be 'auto'|'paged'|'xla', "
                         f"got {attention!r}")
    return attention


class DecodeEngine:
    """Compiled serving runtime for a :class:`~paddle_tpu.models.
    TransformerLM` checkpoint.

    Args:
      model: a TransformerLM (homogeneous blocks; any training config —
        the serve path restacks the per-block params at trace time, so
        checkpoints are shape-compatible as-is).
      variables: the model's variables dict (training checkpoint or
        ``load_inference_model`` output).
      max_slots: decode-tick batch width S — the max concurrent
        sequences. Fixed at compile time; empty slots are masked lanes.
      block_size: KV tokens per pool block. Small blocks waste less on
        ragged tails but cost more gather indirection; 16 is the
        conventional sweet spot (DESIGN_DECISIONS PR-9).
      num_blocks: pool size. Default sizes the pool for full residency
        (every slot at full context) — shrink it to test admission
        backpressure.
      max_blocks_per_seq: per-slot table width; the per-slot context
        capacity is ``max_blocks_per_seq * block_size`` (defaults to
        ``model.max_len // block_size``, and must keep the capacity
        within ``model.max_len`` — positions are embedded).
      attention: ``"auto" | "paged" | "xla"`` — see
        :func:`_resolve_attention`. The span path (speculation /
        chunked prefill) follows the same choice: the multi-query paged
        kernel on TPU, the bit-exact XLA gather path elsewhere
        (ISSUE 14).
      share_prefix: copy-on-write physical block sharing between
        resident sequences with a common prompt prefix (default ON —
        the PagedAttention production win, ISSUE 12).
      retain_prefix: RadixAttention-style retention (ISSUE 14, needs
        ``share_prefix``): evicted registered blocks park in a
        retained LRU (lazily reclaimed under pool pressure) so
        SEQUENTIAL same-prefix requests hit too, not just
        concurrently-resident ones.
      speculative: number of n-gram self-drafted tokens verified per
        tick (0 = off). Greedy verification is lossless by
        construction; with ``sampling`` the [S3] rejection-sampling
        rule keeps the output distribution exact.
      prefill_chunk: prefill chunk width C (None = legacy one-shot
        full-width prefill). Long prompts prefill in ``ceil(P/C)``
        calls the scheduler interleaves between decode ticks.
      sampling: a :class:`SamplingConfig` for stochastic decoding
        (None = greedy).
      telemetry: optional :class:`paddle_tpu.obs.Telemetry`; the engine
        emits one ``kind="decode_tick"`` record per tick (dispatch wall,
        active slots, tokens/sec, sharing/speculation/retention
        counters, ``kv_bytes_per_token``/``quant_dtype``) and the
        scheduler adds per-request records through the same object.
      dtype: KV pool dtype. f32 default matches the projections' f32
        accumulation under both the f32 and bf16-compute policies.
      kv_dtype: ``None``/``"f32"`` (pools at ``dtype``) or ``"int8"`` —
        quantized pools with per-row-per-head scale pages (ISSUE 14):
        ~4x fewer HBM bytes per resident token, dequantized in-kernel.
      mesh: optional ``jax.sharding.Mesh`` carrying a ``tp_axis`` axis
        (ISSUE 15): the engine's two compiled programs run TENSOR
        PARALLEL over it — params placed by the megatron
        ``param_sharding`` rule, KV pools sharded on the head axis
        (each shard holds ``H/tp`` heads of every block, int8 scale
        pages split identically), attention + MLP as the tp-sharded
        forward with the out/ffn2 all-reduce assembling the replicated
        residual and logits. The HOST side is shard-oblivious: one
        logical block table, so CoW forks, quantized scatters,
        retention, speculation and the scheduler/fleet compose
        unchanged, and ``compile_counts()`` stays {prefill: 1, tick: 1}.
        ``mesh=None`` (default) is the single-device engine, unchanged.
      param_sharding: with ``mesh=``, the parameter placement — a
        :class:`~paddle_tpu.parallel.ShardingRules` or a PartitionSpec
        pytree (default: :func:`~paddle_tpu.parallel.megatron_sp_rules`,
        the same layout the training tp paths use, so tp-trained
        checkpoints serve with zero resharding).
      tp_axis: the mesh axis name carrying the tensor-parallel degree
        (default ``"model"``, the framework's standard axis).
    """

    def __init__(self, model, variables, *, max_slots: int = 4,
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 max_blocks_per_seq: Optional[int] = None,
                 attention: str = "auto", share_prefix: bool = True,
                 retain_prefix: bool = True,
                 speculative: int = 0,
                 prefill_chunk: Optional[int] = None,
                 sampling: Optional[SamplingConfig] = None,
                 telemetry=None, dtype=jnp.float32,
                 kv_dtype: Optional[str] = None,
                 mesh=None, param_sharding=None, tp_axis: str = "model"):
        self.model = model
        self.variables = variables
        self.telemetry = telemetry
        # optional Tracer (ISSUE 17): assigned by the fleet/replica when
        # request tracing is on; None costs one attribute test per tick
        self.tracer = None
        # optional metrics registry handle (ISSUE 19): assigned by the
        # fleet (replica-scoped facade) or the replica child (its local
        # hub) — same contract as tracer, None costs one attribute test
        self.metrics = None
        self._metrics_tick_counters: Dict[str, int] = {}
        self.attention = _resolve_attention(attention)
        if speculative < 0:
            raise ValueError(f"speculative must be >= 0, "
                             f"got {speculative}")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, "
                             f"got {prefill_chunk}")
        if sampling is not None:
            sampling.validate(model.emb.vocab)
        self.speculative = int(speculative)
        self.prefill_chunk = prefill_chunk
        self.sampling = sampling
        num_layers = len(model.blocks)
        num_heads = model.blocks[0].attn.num_heads
        dim = model.emb.dim
        head_dim = model.blocks[0].attn.head_dim or dim // num_heads
        # tensor-parallel mesh (ISSUE 15): resolve the tp degree, place
        # the params by the megatron rule, and shard the pools on the
        # head axis. All of it is PLACEMENT — the traced program bodies
        # below are identical either way (shard-in-scope pins the layout
        # at trace time; the SPMD partitioner inserts the collectives).
        self.mesh = mesh
        self.tp_axis = tp_axis
        if mesh is not None:
            sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
            if tp_axis not in sizes:
                raise ValueError(f"mesh has no {tp_axis!r} axis "
                                 f"(axes: {list(sizes)})")
            self.tp_degree = int(sizes[tp_axis])
            if num_heads % self.tp_degree:
                raise ValueError(
                    f"num_heads {num_heads} must divide by tp degree "
                    f"{self.tp_degree} (head-axis KV sharding)")
            from ..parallel.sharding import shard_tree
            if param_sharding is None:
                from ..parallel.megatron import megatron_sp_rules
                # thread tp_axis through: a mesh whose tp axis is not
                # named "model" must get matching default specs
                param_sharding = megatron_sp_rules(model_axis=tp_axis)
            self.variables = shard_tree(mesh, variables, param_sharding)
        else:
            self.tp_degree = 1
        if max_blocks_per_seq is None:
            max_blocks_per_seq = max(1, model.max_len // block_size)
        if max_blocks_per_seq * block_size > model.max_len:
            raise ValueError(
                f"slot capacity {max_blocks_per_seq * block_size} exceeds "
                f"model.max_len={model.max_len} (positions are embedded)")
        if num_blocks is None:
            num_blocks = max_slots * max_blocks_per_seq + 1   # + null block
        self.cache = PagedKVCache(
            num_layers, num_heads, head_dim, num_blocks, block_size,
            max_slots=max_slots, max_blocks_per_seq=max_blocks_per_seq,
            dtype=dtype, share_prefix=share_prefix, kv_dtype=kv_dtype,
            retain_prefix=retain_prefix, tp_degree=self.tp_degree)
        if mesh is not None:
            self.cache.shard_pools(mesh, tp_axis)
        self.max_slots = max_slots
        # host-authoritative slot state beside the cache's tables/lengths
        self.active = np.zeros((max_slots,), bool)
        self.tokens = np.zeros((max_slots,), np.int32)   # next to decode
        # per-slot token history (prompt + accepted generations): the
        # n-gram self-drafter's corpus — tiny host lists, always kept.
        # The drafter's lookup is incremental: per-slot maps of bigram/
        # token -> (latest index, previous-latest index), maintained on
        # append, so each proposal is O(k) instead of rescanning the
        # history per tick
        self.history: List[List[int]] = [[] for _ in range(max_slots)]
        self._bigram_idx: List[Dict] = [{} for _ in range(max_slots)]
        self._unigram_idx: List[Dict] = [{} for _ in range(max_slots)]
        self._tick_counters: Dict[str, int] = {}
        # chunked-prefill cursors: slot -> (prompt, cursor, shared_len)
        self._prefilling: Dict[int, Dict[str, Any]] = {}
        self.ticks = 0
        self.tokens_generated = 0
        self.prefill_chunks = 0          # cumulative chunk calls
        self.draft_proposed = 0          # cumulative drafted tokens
        self.draft_accepted = 0          # cumulative accepted drafts
        # per-slot attribution for request-level telemetry
        self.slot_stats: List[Dict[str, int]] = [
            {} for _ in range(max_slots)]
        # what the last tick retired per slot (list of accepted tokens;
        # [tok] for the non-speculative tick) — the scheduler's view
        self.last_accepted: Dict[int, List[int]] = {}

        W = self.cache.context_width
        attn_impl = self.attention
        K1 = 1 + self.speculative
        cfg = self.sampling

        def first_token(last_logits, key):
            if cfg is None:
                return jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
            return _sample_tokens(cfg, last_logits[None], key[None])[0]

        if prefill_chunk is None:
            def prefill_fn(variables, pages_k, pages_v, ids, length,
                           start, table, key):
                # ids [1, W] padded; length/start [1]; table [1, MB]
                logits, (ks, vs) = model.apply(variables, ids,
                                               method="prefill")
                scat = jax.vmap(scatter_prefill_pages,
                                in_axes=(0, 0, None, None, None))
                pages_k = scat(pages_k, ks, table, length, start)
                pages_v = scat(pages_v, vs, table, length, start)
                last = jnp.take_along_axis(
                    logits, (length - 1)[:, None, None], axis=1)[0, 0]
                return pages_k, pages_v, first_token(last, key)
        else:
            C = prefill_chunk

            def prefill_fn(variables, pages_k, pages_v, ids, start, n,
                           write_from, table, key):
                # ids [1, C]: tokens at positions start..start+n-1;
                # rows >= n are padding; scatter floored at write_from
                # (shared-prefix rows are co-owned — never rewritten)
                logits, (pages_k, pages_v, _) = model.apply(
                    variables, ids, (pages_k, pages_v, table), start, n,
                    jnp.ones((1,), bool), attn_impl=attn_impl,
                    write_from=write_from, method="decode_span")
                last = jnp.take_along_axis(
                    logits, (n - 1)[:, None, None], axis=1)[0, 0]
                return pages_k, pages_v, first_token(last, key)

        if self.speculative == 0:
            def tick_fn(variables, pages_k, pages_v, tables, lengths,
                        tokens, active, keys):
                logits, (pages_k, pages_v, _) = model.apply(
                    variables, tokens, (pages_k, pages_v, tables), lengths,
                    active, attn_impl=attn_impl, method="decode_step")
                if cfg is None:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                else:
                    nxt = _sample_tokens(cfg, logits, keys)
                return pages_k, pages_v, nxt[:, None]
        elif cfg is None:
            def tick_fn(variables, pages_k, pages_v, tables, lengths,
                        tokens, n, active):
                # tokens [S, 1+k]: pending + drafts; ONE span dispatch
                # verifies every draft (greedy argmax per row)
                logits, (pages_k, pages_v, _) = model.apply(
                    variables, tokens, (pages_k, pages_v, tables),
                    lengths, n, active, attn_impl=attn_impl,
                    method="decode_span")
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return pages_k, pages_v, nxt        # [S, 1+k]
        else:
            def tick_fn(variables, pages_k, pages_v, tables, lengths,
                        tokens, n, active, keys):
                # stochastic speculation, the [S3] rejection rule: for
                # draft row j the proposal distribution is a point mass
                # at tokens[:, j+1], so accept with prob p_j(draft) and
                # resample rejections from p_j with the draft excluded
                # (= norm(max(p - q, 0))) — distribution-preserving by
                # construction. All three verdict arrays are computed in
                # ONE dispatch; the host walks the accept prefix.
                logits, (pages_k, pages_v, _) = model.apply(
                    variables, tokens, (pages_k, pages_v, tables),
                    lengths, n, active, attn_impl=attn_impl,
                    method="decode_span")
                x = _filter_logits(cfg, logits)     # [S, 1+k, V]
                p = jax.nn.softmax(x, axis=-1)
                Q = x.shape[1]
                # per-row keys: fold the row index into the slot key,
                # then a role constant (0 = accept-u, 1 = resample,
                # 2 = bonus sample) — seeded-deterministic replay
                rows = jnp.arange(Q)
                rk = jax.vmap(lambda key: jax.vmap(
                    lambda r: jax.random.fold_in(key, r))(rows))(keys)
                role = lambda c: jax.vmap(jax.vmap(
                    lambda kk: jax.random.fold_in(kk, c)))(rk)
                u = jax.vmap(jax.vmap(jax.random.uniform))(role(0))
                drafts = tokens[:, 1:]              # [S, k]
                p_draft = jnp.take_along_axis(
                    p[:, :-1], drafts[..., None], axis=-1)[..., 0]
                accept = u[:, :-1] < p_draft        # [S, k]
                res_x = jnp.where(
                    jax.nn.one_hot(drafts, x.shape[-1], dtype=bool),
                    -jnp.inf, x[:, :-1])
                resample = jax.vmap(jax.vmap(jax.random.categorical))(
                    role(1)[:, :-1], res_x).astype(jnp.int32)
                bonus = jax.vmap(jax.vmap(jax.random.categorical))(
                    role(2), x).astype(jnp.int32)   # [S, 1+k]
                return pages_k, pages_v, accept, resample, bonus

        # shard-in-scope wrapping (ISSUE 15): with a mesh, every traced
        # body runs inside tp_shard_scope (the attention layer pins
        # head-sharded projections/pools, the model pins replicated
        # residual/logits). _in_scope is the ONE place scope entry
        # happens; without a mesh it is the identity and every
        # tp_constrain below no-ops, so the single-device trace is
        # byte-identical.
        def _in_scope(fn):
            if self.mesh is None:
                return fn

            def wrapped(*args):
                with tp_shard_scope(self.mesh, self.tp_axis):
                    return fn(*args)
            return wrapped

        # The compiled programs' RETURNED pools are constrained back to
        # the head-sharded input placement — without the output pin the
        # partitioner may pick a different pool layout, which both
        # breaks donation and retraces the next call on the changed
        # input sharding (the no-retrace invariant would die quietly).
        def _pin_pools(fn, pool_outs=(0, 1)):
            def pinned(*args):
                out = fn(*args)
                return tuple(tp_constrain(o, 3) if i in pool_outs else o
                             for i, o in enumerate(out))
            return pinned

        # donate the KV pools: the tick's carry flips between two
        # allocations instead of growing HBM per token
        self._prefill_fn = jax.jit(_in_scope(_pin_pools(prefill_fn)),
                                   donate_argnums=(1, 2))
        self._tick_fn = jax.jit(_in_scope(_pin_pools(tick_fn)),
                                donate_argnums=(1, 2))
        # COW block copy: [L, bs, H, hd] pages move pool-internally, one
        # tiny donated program (not an engine entry point — not counted
        # in compile_counts, traced once for the process lifetime).
        # tree_map covers the quantized (values, scales) tuple pools —
        # a fork copies the scale page with its value page. Sharded
        # pools copy shard-locally (the block axis is unsharded, the
        # head axis untouched) — the output pin keeps the carry layout.
        def _cow(pages, src, dst):
            out = jax.tree_util.tree_map(
                lambda p: p.at[:, dst].set(p[:, src]), pages)
            return tp_constrain(out, 3)

        self._cow_fn = jax.jit(_in_scope(_cow), donate_argnums=(0,))
        self._zero_keys = jnp.zeros((max_slots, 2), jnp.uint32)
        seed = sampling.seed if sampling is not None else 0
        self._tick_keys = jax.jit(lambda t: jax.vmap(
            lambda s: jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(seed), t), s))(
                    jnp.arange(max_slots)))
        self._W = W
        self._K1 = K1

    # -- introspection -----------------------------------------------------

    @property
    def context_width(self) -> int:
        return self._W

    def compile_counts(self) -> Dict[str, int]:
        """Distinct traced programs per entry point — the no-retrace
        invariant is both == 1 after warmup, across any admit/evict
        churn AND with speculation/chunking/sharing on (drafted width
        and chunk width are static shapes; the bench serving gate
        asserts it)."""
        return {"prefill": int(self._prefill_fn._cache_size()),
                "tick": int(self._tick_fn._cache_size())}

    # -- warmup (ISSUE 16) -------------------------------------------------

    def warmup(self) -> Dict[str, Any]:
        """Pay both programs' compiles NOW, before the first request.

        Executes each compiled entry point once with all-inactive dummy
        operands — every slot masked off, lengths 0, zero ids — built
        with the exact aval construction of the real call sites, so the
        jit cache ends at ``{prefill: 1, tick: 1}`` and the first real
        request retraces nothing. Executing (rather than AOT
        ``lower().compile()``) is what populates the jit cache AND the
        persistent compilation cache in one move; it is numerically
        harmless because pool contents only matter where an active
        slot's table+length mark them valid (the eviction rule: stale
        pool contents are finite and always length-masked — warmup's
        stray writes land in block 0, which the first real prefill
        rewrites before any read), and it consumes no entropy — the
        PRNG keys fold stateless counters that warmup leaves untouched,
        so warmed and unwarmed engines emit identical token streams.

        With :mod:`~paddle_tpu.nn.autotune` enabled, each program's
        timed warmup registers under the engine's shape key (the
        program-level analog of a kernel's block entry — this is where
        the paged/span programs' grids get their cache row): a restarted
        replica with a populated cache reports the hit and pays zero
        trials. Returns the startup breakdown the replica child ships in
        its hello/heartbeat payloads."""
        assert not self.active.any() and not self._prefilling, \
            "warmup() must run before any admission (fresh engine)"
        from ..nn import autotune
        from ..obs import xla_cache
        t0 = time.perf_counter()
        xla_before = xla_cache.cache_entry_count()
        trials_before = autotune.stats()["trials"]
        timings: Dict[str, float] = {}

        def _prefill_once():
            table = jnp.asarray(self.cache.tables[0:1])
            key = self._prefill_key()
            if self.prefill_chunk is None:
                out = self._prefill_fn(
                    self.variables, self.cache.k, self.cache.v,
                    jnp.zeros((1, self._W), jnp.int32),
                    jnp.asarray([1], jnp.int32),
                    jnp.asarray([0], jnp.int32), table, key)
            else:
                out = self._prefill_fn(
                    self.variables, self.cache.k, self.cache.v,
                    jnp.zeros((1, self.prefill_chunk), jnp.int32),
                    jnp.asarray([0], jnp.int32),
                    jnp.asarray([1], jnp.int32),
                    jnp.asarray([0], jnp.int32), table, key)
            # donated pools: the engine's carry is the returned pair
            self.cache.k, self.cache.v = out[0], out[1]
            return out[2]

        def _tick_once():
            tables, lengths = self.cache.device_tables()
            if self.speculative == 0:
                keys = (self._zero_keys if self.sampling is None
                        else self._tick_keys(self.ticks))
                out = self._tick_fn(
                    self.variables, self.cache.k, self.cache.v, tables,
                    lengths, jnp.asarray(self.tokens),
                    jnp.asarray(self.active), keys)
            elif self.sampling is not None:
                out = self._tick_fn(
                    self.variables, self.cache.k, self.cache.v, tables,
                    lengths, jnp.zeros((self.max_slots, self._K1),
                                       jnp.int32),
                    jnp.zeros((self.max_slots,), jnp.int32),
                    jnp.asarray(self.active), self._tick_keys(self.ticks))
            else:
                out = self._tick_fn(
                    self.variables, self.cache.k, self.cache.v, tables,
                    lengths, jnp.zeros((self.max_slots, self._K1),
                                       jnp.int32),
                    jnp.zeros((self.max_slots,), jnp.int32),
                    jnp.asarray(self.active))
            self.cache.k, self.cache.v = out[0], out[1]
            return out[2]

        def _measured(name, fn):
            t = time.perf_counter()
            if autotune.is_enabled():
                key = autotune.make_key(
                    f"serve_{name}",
                    shape=(self.max_slots, self._W, self._K1,
                           self.cache.block_size, self.cache.num_blocks),
                    dtype=self.cache.quant_dtype,
                    extra=(self.speculative,
                           int(self.sampling is not None),
                           self.prefill_chunk, self.attention))
                before = autotune.stats()["trials"]
                autotune.choose(f"serve_{name}", key=key,
                                candidates=[{}], runner=fn, default={})
                if autotune.stats()["trials"] == before:
                    fn()    # cache hit skipped the timed trial — still
                    #         warm this process's jit cache
            else:
                fn()
            jax.block_until_ready((self.cache.k, self.cache.v))
            timings[name] = time.perf_counter() - t

        _measured("prefill", _prefill_once)
        _measured("tick", _tick_once)
        wall = time.perf_counter() - t0
        trials = autotune.stats()["trials"] - trials_before
        added = xla_cache.cache_entry_count() - xla_before
        xla_hit = (None if xla_cache.active_dir() is None
                   else added == 0)
        report = {
            "prefill_s": round(timings["prefill"], 6),
            "tick_s": round(timings["tick"], 6),
            "wall_s": round(wall, 6),
            "autotune_trials": trials,
            "autotune_cache_hit": (None if not autotune.is_enabled()
                                   else trials == 0),
            "xla_cache_entries_added": added,
            "xla_cache_hit": xla_hit,
            "compile_counts": self.compile_counts(),
        }
        if self.telemetry is not None:
            self.telemetry.record_compile(
                "serve_warmup", wall, cache_hit=xla_hit,
                autotune_trials=trials,
                meta={"warmup": True,
                      "prefill_s": report["prefill_s"],
                      "tick_s": report["tick_s"]})
        return report

    def free_slots(self) -> List[int]:
        return [s for s in range(self.max_slots)
                if not self.active[s] and s not in self._prefilling]

    def admit_probe(self, total_len: int,
                    include_slots: bool = True) -> AdmitProbe:
        """Structured admission check for a sequence that may grow to
        ``total_len`` tokens (prompt + generation budget): the first
        failing constraint, in never-clears-first order — ``"width"``
        (exceeds slot capacity), ``"slots"`` (no free decode lane;
        skipped with ``include_slots=False`` for callers that manage
        slots themselves, like the scheduler), ``"blocks"`` (KV pool
        can't cover the worst-case reservation). Deliberately ignores
        prefix-cache hits: the probe is the conservative no-sharing
        bound, so an admitted request can never strand mid-decode even
        if every co-owner forks. The blocks check runs against
        RECLAIMABLE capacity — free plus retained-LRU blocks (ISSUE 14:
        retained blocks are one lazy reclaim away from free; probing
        raw ``num_free`` alone would report ``"blocks"`` backpressure,
        and shed, against capacity the pool actually has)."""
        blocks_needed = self.cache.blocks_needed(total_len)
        free_slots = len(self.free_slots())
        reclaimable = self.cache.free_blocks      # free + retained
        if total_len > self._W:
            reason = "width"
        elif include_slots and free_slots == 0:
            reason = "slots"
        elif blocks_needed > reclaimable:
            reason = "blocks"
        else:
            reason = None
        return AdmitProbe(ok=reason is None, reason=reason,
                          blocks_needed=blocks_needed,
                          free_blocks=reclaimable,
                          free_slots=free_slots,
                          raw_free_blocks=self.cache.allocator.num_free,
                          retained_blocks=self.cache.retained_blocks)

    def can_admit(self, total_len: int) -> bool:
        """Whether the pool can host a sequence that may grow to
        ``total_len`` tokens (prompt + generation budget). Admission
        reserves the worst case up front so a running request can never
        strand mid-decode without a block (DESIGN_DECISIONS PR-9).
        Boolean view of :meth:`admit_probe` (slot availability excluded —
        the historical contract; the scheduler tracks slots itself)."""
        return self.admit_probe(total_len, include_slots=False).ok

    # -- request lifecycle -------------------------------------------------

    def stage_prompt(self, prompt: List[int]) -> np.ndarray:
        """Pad a prompt to the fixed prefill width — pure host work the
        scheduler runs at SUBMIT time (the PR-3 staging move:
        admission-path host prep happens off the tick's critical
        path). Chunked engines stage per-chunk at prefill time (the
        arrays are C-sized — already cheap)."""
        P = len(prompt)
        if not 0 < P <= self._W:
            raise ValueError(f"prompt length {P} not in [1, {self._W}]")
        if self.prefill_chunk is not None:
            return np.asarray([prompt], np.int32)    # chunked: raw ids
        ids = np.zeros((1, self._W), np.int32)
        ids[0, :P] = prompt
        return ids

    def _reserve(self, slot: int, prompt: List[int],
                 reserve_len: Optional[int]) -> Dict[str, int]:
        """Shared admission prologue: prefix-cache adopt + worst-case
        block reservation. Returns the slot's sharing stats."""
        P = len(prompt)
        target = max(P, reserve_len or P)
        match = self.cache.match_prefix(prompt)
        shared_len, hit_blocks = 0, 0
        if match is not None and match.blocks:
            self.cache.adopt_prefix(slot, match)
            shared_len, hit_blocks = match.length, match.hit_blocks
        if not self.cache.ensure_capacity(slot, target):
            self.cache.free_slot(slot)     # roll back the adoption
            raise RuntimeError(
                f"KV pool exhausted admitting slot {slot} "
                f"(need {self.cache.blocks_needed(target)} blocks, "
                f"{self.cache.free_blocks} free) — gate admissions on "
                f"can_admit()")
        stats = {"prefix_hit_blocks": hit_blocks,
                 "shared_len": shared_len,
                 "blocks_reserved": self.cache.owned_count(slot),
                 "cow_forks": 0, "prefill_chunks": 0,
                 "draft_proposed": 0, "draft_accepted": 0}
        self.slot_stats[slot] = stats
        return stats

    def _prefill_key(self) -> jnp.ndarray:
        """Per-admission PRNG key for a sampled first token (greedy
        engines trace the same operand but never use it)."""
        seed = self.sampling.seed if self.sampling is not None else 0
        return jax.random.fold_in(jax.random.PRNGKey(seed),
                                  1 + self.prefill_chunks + self.ticks)

    def admit(self, slot: int, prompt: List[int],
              reserve_len: Optional[int] = None,
              staged: Optional[np.ndarray] = None) -> int:
        """Prefill ``prompt`` into ``slot`` and return the first
        token. ``reserve_len`` (default: prompt length) eagerly
        allocates blocks for the sequence's full growth target;
        ``staged`` is an already-padded :meth:`stage_prompt` array. On
        a chunked engine this drives :meth:`begin_prefill` /
        :meth:`prefill_step` to completion in one call — schedulers
        interleave the steps instead."""
        self.begin_prefill(slot, prompt, reserve_len=reserve_len,
                           staged=staged)
        while True:
            tok = self.prefill_step(slot)
            if tok is not None:
                return tok

    def begin_prefill(self, slot: int, prompt: List[int],
                      reserve_len: Optional[int] = None,
                      staged: Optional[np.ndarray] = None) -> None:
        """Reserve ``slot`` for ``prompt`` (prefix-cache adoption +
        worst-case block reservation) and queue its prefill work.
        :meth:`prefill_step` runs one compiled prefill call at a time —
        the whole prompt for a legacy engine, one C-token chunk for a
        chunked one — and returns the first token when done."""
        assert not self.active[slot], f"slot {slot} is occupied"
        assert slot not in self._prefilling, f"slot {slot} is prefilling"
        P = len(prompt)
        if not 0 < P <= self._W:
            raise ValueError(f"prompt length {P} not in [1, {self._W}]")
        stats = self._reserve(slot, prompt, reserve_len)
        shared = stats["shared_len"]
        # an exact-duplicate prompt shares every block; still re-attend
        # the final position (writes masked) for the first-token logits
        cursor = min(shared, P - 1)
        self._prefilling[slot] = {
            "prompt": list(prompt), "cursor": cursor,
            "shared_len": shared, "staged": staged}

    def prefill_step(self, slot: int) -> Optional[int]:
        """Run ONE compiled prefill call for a :meth:`begin_prefill`'d
        slot. Returns the first generated token when the prompt is fully
        processed (the slot is then live for decode ticks), else None —
        call again, ideally with decode ticks in between (that
        interleaving is chunked prefill's whole point)."""
        st = self._prefilling[slot]
        prompt, P = st["prompt"], len(st["prompt"])
        stats = self.slot_stats[slot]
        tr0 = self.tracer.now_us() if self.tracer is not None else None
        if self.prefill_chunk is None:
            ids = st["staged"] if st["staged"] is not None \
                else self.stage_prompt(prompt)
            self.cache.k, self.cache.v, tok = self._prefill_fn(
                self.variables, self.cache.k, self.cache.v,
                jnp.asarray(ids), jnp.asarray([P], jnp.int32),
                jnp.asarray([st["shared_len"]], jnp.int32),
                jnp.asarray(self.cache.tables[slot:slot + 1]),
                self._prefill_key())
            stats["prefill_chunks"] += 1
            self.prefill_chunks += 1
            done = True
        else:
            C = self.prefill_chunk
            cur = st["cursor"]
            n = min(C, P - cur)
            ids = np.zeros((1, C), np.int32)
            ids[0, :n] = prompt[cur:cur + n]
            self.cache.k, self.cache.v, tok = self._prefill_fn(
                self.variables, self.cache.k, self.cache.v,
                jnp.asarray(ids), jnp.asarray([cur], jnp.int32),
                jnp.asarray([n], jnp.int32),
                jnp.asarray([st["shared_len"]], jnp.int32),
                jnp.asarray(self.cache.tables[slot:slot + 1]),
                self._prefill_key())
            st["cursor"] = cur + n
            stats["prefill_chunks"] += 1
            self.prefill_chunks += 1
            done = st["cursor"] >= P
        if tr0 is not None:
            self.tracer.complete("prefill_dispatch", tr0,
                                 self.tracer.now_us(), slot=slot,
                                 done=done)
        if not done:
            return None
        del self._prefilling[slot]
        self.cache.lengths[slot] = P
        self.active[slot] = True
        tok = int(tok)
        self.tokens[slot] = tok
        self.history[slot] = []
        self._bigram_idx[slot] = {}
        self._unigram_idx[slot] = {}
        self._history_append(slot, list(prompt) + [tok])
        self.cache.register_prefix(slot, prompt)
        return tok

    def evict(self, slot: int) -> None:
        """Free ``slot``'s blocks back to the pool (shared blocks
        survive until their LAST owner lets go); the lane masks off at
        the next tick. Stale pool contents are not wiped (finite,
        always length-masked) — reuse is a table edit."""
        self.cache.free_slot(slot)
        self.active[slot] = False
        self.tokens[slot] = 0
        self.history[slot] = []
        self._bigram_idx[slot] = {}
        self._unigram_idx[slot] = {}
        self._prefilling.pop(slot, None)

    # -- prefill/decode disaggregation (ISSUE 18) --------------------------

    def export_slot(self, slot: int):
        """Package a live slot's state for a prefill→decode handoff:
        ``(meta, kpages, vpages)`` where meta carries the KV length and
        block count and the pages are host numpy in table order (see
        :meth:`PagedKVCache.export_pages`). Exported at the moment the
        first token exists but no decode tick has run, the pages cover
        exactly the prompt — the pending first token's KV is written by
        the ADOPTING replica's first tick, so nothing transient is
        lost in flight."""
        assert self.active[slot], f"slot {slot} is not live"
        P = int(self.cache.lengths[slot])
        ids, kpages, vpages = self.cache.export_pages(slot)
        meta = {"length": P, "blocks": len(ids),
                "quant": self.cache.quant_dtype}
        return meta, kpages, vpages

    def adopt_slot(self, slot: int, prompt: List[int], first_token: int,
                   kpages, vpages,
                   reserve_len: Optional[int] = None) -> bool:
        """Adopt a handed-off sequence into a free slot: import the
        streamed pages at this pool's own block ids, then rebuild the
        host lane state exactly as :meth:`prefill_step`'s completion
        would have — pending token, history (prompt + first token),
        drafter indices, prefix registration — so the first decode tick
        here is bit-identical to the tick a colocated replica would
        have run. Returns False on pool backpressure (nothing
        changed)."""
        assert not self.active[slot], f"slot {slot} is occupied"
        assert slot not in self._prefilling, f"slot {slot} is prefilling"
        P = len(prompt)
        if not 0 < P <= self._W:
            raise ValueError(f"prompt length {P} not in [1, {self._W}]")
        if not self.cache.import_pages(slot, kpages, vpages, P,
                                       reserve_len=reserve_len):
            return False
        tok = int(first_token)
        self.active[slot] = True
        self.tokens[slot] = tok
        self.history[slot] = []
        self._bigram_idx[slot] = {}
        self._unigram_idx[slot] = {}
        self._history_append(slot, list(prompt) + [tok])
        self.cache.register_prefix(slot, prompt)
        self.slot_stats[slot] = {
            "prefix_hit_blocks": 0, "shared_len": 0,
            "blocks_reserved": self.cache.owned_count(slot),
            "cow_forks": 0, "prefill_chunks": 0,
            "draft_proposed": 0, "draft_accepted": 0}
        return True

    # -- speculation -------------------------------------------------------

    def _history_append(self, slot: int, toks: List[int]) -> None:
        """Append accepted tokens to the slot's history and keep the
        drafter's bigram/unigram occurrence maps current (each key holds
        the latest and previous-latest index — exactly what "most
        recent EARLIER occurrence of the tail" needs)."""
        h = self.history[slot]
        big, uni = self._bigram_idx[slot], self._unigram_idx[slot]
        for t in toks:
            h.append(t)
            j = len(h) - 1
            if j >= 1:
                key = (h[j - 1], t)
                big[key] = (j - 1, big.get(key, (None,))[0])
            uni[t] = (j, uni.get(t, (None,))[0])

    def _propose_drafts(self, slot: int) -> List[int]:
        """N-gram self-drafting (prompt-lookup decoding): find the most
        recent earlier occurrence of the history's tail bigram (then
        unigram) and propose its continuation; pad with the last
        proposed/known token (greedy tiny-model generations converge to
        short cycles, which is exactly what this predicts). Wrong drafts
        cost nothing but masked verify lanes — acceptance never drops
        below the non-speculative one token per tick. O(k) per call:
        the occurrence maps are maintained on append."""
        k = self.speculative
        h = self.history[slot]
        cont: List[int] = []
        if len(h) >= 2:
            cur, *prev = self._bigram_idx[slot].get((h[-2], h[-1]),
                                                    (None, None))
            i = prev[0] if cur == len(h) - 2 else cur
            if i is not None:
                cont = h[i + 2:i + 2 + k]
        if not cont and h:
            cur, *prev = self._unigram_idx[slot].get(h[-1], (None, None))
            i = prev[0] if cur == len(h) - 1 else cur
            if i is not None:
                cont = h[i + 1:i + 1 + k]
        pad = cont[-1] if cont else h[-1]
        return (cont + [pad] * k)[:k]

    # -- the tick ----------------------------------------------------------

    def _pre_tick_guard(self) -> np.ndarray:
        """Host guard before every tick: each active slot must own the
        block(s) its writes land in (fail loud, never a silent
        null-block scatter), and any ADOPTED shared block in the write
        range forks first — the copy-on-write point. Returns the live
        token count per slot ``n [S]`` (1 + accepted-capacity-clamped
        drafts)."""
        n = np.zeros((self.max_slots,), np.int32)
        for slot in np.flatnonzero(self.active):
            p = int(self.cache.lengths[slot])
            need = self.cache.blocks_needed(p + 1)
            if need > len(self.cache._owned[slot]):
                raise RuntimeError(
                    f"slot {slot} decoding past its reservation (length "
                    f"{p} needs block {need}, owns "
                    f"{len(self.cache._owned[slot])}) — admit with a "
                    f"larger reserve_len or call cache.ensure_capacity")
            cap = len(self.cache._owned[slot]) * self.cache.block_size - p
            n[slot] = max(1, min(self._K1, cap))
            for idx in self.cache.cow_targets(slot, p, p + int(n[slot])
                                              - 1):
                src, dst = self.cache.fork_block(slot, idx)
                src_i = jnp.asarray(src, jnp.int32)
                dst_i = jnp.asarray(dst, jnp.int32)
                self.cache.k = self._cow_fn(self.cache.k, src_i, dst_i)
                self.cache.v = self._cow_fn(self.cache.v, src_i, dst_i)
                self.slot_stats[slot]["cow_forks"] = \
                    self.slot_stats[slot].get("cow_forks", 0) + 1
        return n

    def decode_tick(self) -> np.ndarray:
        """One compiled decode step over every slot. Appends each active
        slot's pending token (plus, with ``speculative=k``, its drafted
        guesses) to its KV, verifies/samples, and returns the new token
        front ``[S]`` (inactive lanes 0). ``last_accepted`` maps each
        active slot to the list of tokens it retired this tick — one for
        the plain tick, up to ``k+1`` under speculation."""
        t0 = time.perf_counter()
        tr0 = self.tracer.now_us() if self.tracer is not None else None
        n = self._pre_tick_guard()
        tables, lengths = self.cache.device_tables()
        drafted_tick, accepted_tick = 0, 0
        stochastic = self.speculative > 0 and self.sampling is not None
        if self.speculative == 0:
            if self.sampling is None:
                keys = self._zero_keys      # greedy: unused operand
            else:
                keys = self._tick_keys(self.ticks)
            self.cache.k, self.cache.v, nxt = self._tick_fn(
                self.variables, self.cache.k, self.cache.v, tables,
                lengths, jnp.asarray(self.tokens),
                jnp.asarray(self.active), keys)
        else:
            toks = np.zeros((self.max_slots, self._K1), np.int32)
            for slot in np.flatnonzero(self.active):
                drafts = self._propose_drafts(slot)
                toks[slot, 0] = self.tokens[slot]
                toks[slot, 1:] = drafts
                drafted_tick += int(n[slot]) - 1
            if stochastic:
                self.cache.k, self.cache.v, acc_d, res_d, bon_d = \
                    self._tick_fn(
                        self.variables, self.cache.k, self.cache.v,
                        tables, lengths, jnp.asarray(toks),
                        jnp.asarray(n), jnp.asarray(self.active),
                        self._tick_keys(self.ticks))
            else:
                self.cache.k, self.cache.v, nxt = self._tick_fn(
                    self.variables, self.cache.k, self.cache.v, tables,
                    lengths, jnp.asarray(toks), jnp.asarray(n),
                    jnp.asarray(self.active))
        # the dispatch is async: host bookkeeping that doesn't need the
        # sampled tokens runs UNDER the in-flight device call (the PR-3
        # overlap move at tick scale) — the plain tick advances every
        # active slot by exactly one, so its length bump overlaps;
        # speculative lengths depend on acceptance and must wait.
        # np.asarray(nxt) is the drain.
        n_active = int(self.active.sum())
        if self.speculative == 0:
            self.cache.lengths[self.active] += 1
        if stochastic:
            acc_d, res_d, bon_d = (np.asarray(acc_d), np.asarray(res_d),
                                   np.asarray(bon_d))
        else:
            nxt = np.asarray(nxt)                # [S, 1] or [S, 1+k]
        self.last_accepted = {}
        front = np.zeros((self.max_slots,), np.int32)
        tokens_tick = 0
        for slot in np.flatnonzero(self.active):
            if self.speculative == 0:
                accepted = [int(nxt[slot, 0])]
            elif stochastic:
                # [S3] walk: accept drafts while the per-row coin lands
                # under p(draft); the stopping row's token is the
                # residual resample, or the bonus sample from the last
                # live row when every draft survived
                live = int(n[slot])
                take = 0
                while take < live - 1 and bool(acc_d[slot, take]):
                    take += 1
                accepted = [int(toks[slot, j + 1]) for j in range(take)]
                if take < live - 1:
                    accepted.append(int(res_d[slot, take]))
                else:
                    accepted.append(int(bon_d[slot, live - 1]))
                accepted_tick += take
                self.cache.lengths[slot] += len(accepted)
            else:
                # accept the longest draft prefix the model reproduced,
                # plus the model's own token after it — identical to
                # the sequential greedy stream by induction
                take = 1
                while (take < int(n[slot])
                       and int(toks[slot, take]) == int(nxt[slot,
                                                            take - 1])):
                    take += 1
                accepted = [int(t) for t in nxt[slot, :take]]
                accepted_tick += take - 1
                self.cache.lengths[slot] += len(accepted)
            self.last_accepted[slot] = accepted
            front[slot] = accepted[-1]
            self._history_append(slot, accepted)
            tokens_tick += len(accepted)
            st = self.slot_stats[slot]
            st["draft_proposed"] = st.get("draft_proposed", 0) \
                + (int(n[slot]) - 1 if self.speculative else 0)
            st["draft_accepted"] = st.get("draft_accepted", 0) \
                + len(accepted) - 1
        self.tokens = front
        self.ticks += 1
        self.tokens_generated += tokens_tick
        self.draft_proposed += drafted_tick
        self.draft_accepted += accepted_tick
        if tr0 is not None:
            self.tracer.complete("engine_tick", tr0,
                                 self.tracer.now_us(), tick=self.ticks,
                                 active=n_active, tokens=tokens_tick,
                                 accepted_drafts=accepted_tick)
        if self.telemetry is not None:
            wall = time.perf_counter() - t0
            # sharing/chunk counters are emitted as PER-TICK DELTAS
            # (admissions land between ticks, so their hits show up on
            # the next record): every decode_tick field aggregates the
            # same way — sum over records — with no cumulative mix-ins
            snap = {"prefix_hit_blocks": self.cache.prefix_hit_blocks,
                    "cow_forks": self.cache.cow_forks,
                    "prefill_chunks": self.prefill_chunks,
                    "retained_hits": self.cache.retained_hits}
            delta = {key: val - self._tick_counters.get(key, 0)
                     for key, val in snap.items()}
            self._tick_counters = snap
            self.telemetry.emit_event({
                "kind": "decode_tick", "tick": self.ticks,
                "active_slots": n_active, "wall_ms": round(wall * 1e3, 4),
                "tokens": tokens_tick,
                "tokens_per_sec": round(tokens_tick / wall, 2)
                if wall else None,
                "free_blocks": self.cache.free_blocks,
                "draft_accept_rate": round(accepted_tick / drafted_tick,
                                           4) if drafted_tick else None,
                # gauges, not per-tick deltas: the retained-LRU size and
                # the pool's capacity accounting (ISSUE 14); with a tp
                # mesh kv_bytes_per_token is PER SHARD and tp_degree
                # carries the mesh width (ISSUE 15)
                "retained_blocks": self.cache.retained_blocks,
                "kv_bytes_per_token": self.cache.kv_bytes_per_token,
                "quant_dtype": self.cache.quant_dtype,
                "tp_degree": self.tp_degree,
                **delta,
            })
        if self.metrics is not None:
            m = self.metrics
            m.histogram("engine_tick_ms",
                        "compiled decode tick wall time (ms)").observe(
                (time.perf_counter() - t0) * 1e3)
            m.counter("engine_ticks", "decode ticks executed").inc()
            m.counter("engine_tokens",
                      "tokens retired across all slots").inc(tokens_tick)
            m.gauge("engine_active_slots",
                    "slots decoding this tick").set(n_active)
            # KV pool occupancy: reserved fraction of the paged pool
            m.gauge("engine_kv_free_blocks",
                    "free blocks in the paged KV pool").set(
                self.cache.free_blocks)
            m.gauge("engine_kv_occupancy",
                    "reserved fraction of the paged KV pool").set(
                1.0 - self.cache.free_blocks / self.cache.num_blocks)
            # sharing/speculation counters as per-tick increments, via
            # a snapshot diff SEPARATE from telemetry's (each consumer
            # owns its own baseline; sharing one would starve whichever
            # reads second)
            snap = {"engine_prefix_hit_blocks":
                    self.cache.prefix_hit_blocks,
                    "engine_cow_forks": self.cache.cow_forks,
                    "engine_prefill_chunks": self.prefill_chunks,
                    "engine_draft_proposed": self.draft_proposed,
                    "engine_draft_accepted": self.draft_accepted}
            for key, val in snap.items():
                d = val - self._metrics_tick_counters.get(key, 0)
                if d:
                    m.counter(key, "cumulative engine counter").inc(d)
            self._metrics_tick_counters = snap
        return self.tokens.copy()

    # -- observability -----------------------------------------------------

    def attribution_report(self, emit: bool = True) -> Dict[str, Any]:
        """MFU-gap attribution of the compiled decode tick (the
        ``Trainer.attribution_report`` recipe: one AOT
        ``lower().compile()``, zero executions). Decode is memory-bound —
        every tick streams the full parameter set and the active KV for
        one token of compute — and the report's ``decode`` block says so
        on the spec-sheet HBM tables (``bound="memory"``)."""
        from ..obs import attribution as attr_lib
        from ..obs import hloprof
        from ..obs.telemetry import lowered_hlo_flops
        tables, lengths = self.cache.device_tables()
        if self.speculative == 0:
            keys = jnp.zeros((self.max_slots, 2), jnp.uint32)
            lowered = self._tick_fn.lower(
                self.variables, self.cache.k, self.cache.v, tables,
                lengths, jnp.asarray(self.tokens),
                jnp.asarray(self.active), keys)
        else:
            span_args = (self.variables, self.cache.k, self.cache.v,
                         tables, lengths,
                         jnp.zeros((self.max_slots, self._K1), jnp.int32),
                         jnp.ones((self.max_slots,), jnp.int32),
                         jnp.asarray(self.active))
            if self.sampling is not None:   # stochastic verify: + keys
                span_args += (jnp.zeros((self.max_slots, 2),
                                        jnp.uint32),)
            lowered = self._tick_fn.lower(*span_args)
        compiled = lowered.compile()
        analysis = hloprof.parse_module(compiled.as_text())
        report = attr_lib.build_report(
            analysis,
            device_kind=getattr(jax.devices()[0], "device_kind", ""),
            n_devices=self.tp_degree,
            cost_analysis_flops=lowered_hlo_flops(compiled),
            meta={"program": "decode_tick", "max_slots": self.max_slots,
                  "context_width": self._W,
                  "block_size": self.cache.block_size,
                  "attention": self.attention,
                  "speculative": self.speculative,
                  "tp_degree": self.tp_degree})
        if emit and self.telemetry is not None:
            self.telemetry.emit_event(report)
        return report
