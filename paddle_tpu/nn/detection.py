"""SSD-style detection family — prior boxes, multibox loss, NMS output, ROI
pooling.

Reference: ``paddle/gserver/layers/PriorBox.cpp``, ``MultiBoxLossLayer.cpp``,
``DetectionOutputLayer.cpp``, ``DetectionUtil.cpp``, ``ROIPoolLayer.cpp``.

TPU-first design notes:
- Prior boxes depend only on static shapes, so they are generated host-side
  (numpy) at module-build/trace time and baked into the program as constants —
  no per-step device work at all.
- Matching, hard-negative mining, and NMS are the classically "dynamic" parts
  of SSD. Here they are all static-shape and jit-safe: ground truth arrives
  padded ([B, G, 4] with a -1 label for padding), bipartite matching is a
  ``lax.fori_loop`` of G global-argmax steps over a [P, G] overlap matrix
  (exactly the reference's greedy bipartite phase, DetectionUtil.cpp:234),
  negative mining uses the rank-of-rank trick instead of a host sort, and NMS
  is a fixed-K ``fori_loop`` of select-max-then-suppress. Everything batches
  over images with ``vmap``.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.module import Module
from paddle_tpu.nn import costs

__all__ = ["prior_box", "iou_matrix", "encode_boxes", "decode_boxes",
           "match_priors", "MultiBoxLoss", "nms", "DetectionOutput",
           "ROIPool"]


def prior_box(feature_shape: Tuple[int, int],
              image_shape: Tuple[int, int],
              min_sizes: Sequence[float],
              max_sizes: Sequence[float] = (),
              aspect_ratios: Sequence[float] = (),
              variance: Sequence[float] = (0.1, 0.1, 0.2, 0.2),
              clip: bool = True,
              flip: bool = True) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Generate SSD prior boxes for one feature map.

    Returns ``(boxes, variances)``, each ``[H*W*num_priors, 4]`` with boxes as
    normalized ``(xmin, ymin, xmax, ymax)``. Per-cell ordering matches the
    reference (``PriorBox.cpp`` forward): for each min_size — the ar=1 box,
    then the ``sqrt(min*max)`` box, then the remaining aspect ratios (with
    reciprocals appended when ``flip``).
    """
    fh, fw = feature_shape
    ih, iw = image_shape
    step_w = iw / fw
    step_h = ih / fh
    ars = [1.0]
    for ar in aspect_ratios:
        ars.append(float(ar))
        if flip:
            ars.append(1.0 / float(ar))
    if max_sizes:
        assert len(max_sizes) == len(min_sizes)
    boxes = []
    for y in range(fh):
        for x in range(fw):
            cx = (x + 0.5) * step_w
            cy = (y + 0.5) * step_h
            for s, mn in enumerate(min_sizes):
                bw = bh = float(mn)
                boxes.append((cx - bw / 2, cy - bh / 2,
                              cx + bw / 2, cy + bh / 2))
                if max_sizes:
                    bw = bh = math.sqrt(mn * max_sizes[s])
                    boxes.append((cx - bw / 2, cy - bh / 2,
                                  cx + bw / 2, cy + bh / 2))
                for ar in ars:
                    if abs(ar - 1.0) < 1e-6:
                        continue
                    bw = mn * math.sqrt(ar)
                    bh = mn / math.sqrt(ar)
                    boxes.append((cx - bw / 2, cy - bh / 2,
                                  cx + bw / 2, cy + bh / 2))
    b = np.asarray(boxes, np.float32) / np.array([iw, ih, iw, ih], np.float32)
    if clip:
        b = np.clip(b, 0.0, 1.0)
    var = np.tile(np.asarray(variance, np.float32)[None, :], (b.shape[0], 1))
    return jnp.asarray(b), jnp.asarray(var)


def _area(boxes):
    w = jnp.maximum(boxes[..., 2] - boxes[..., 0], 0.0)
    h = jnp.maximum(boxes[..., 3] - boxes[..., 1], 0.0)
    return w * h


def iou_matrix(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Pairwise Jaccard overlap, [N, 4] x [M, 4] -> [N, M] (reference:
    ``DetectionUtil.cpp:91``)."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = _area(a)[:, None] + _area(b)[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _center_size(boxes):
    wh = boxes[..., 2:] - boxes[..., :2]
    c = (boxes[..., :2] + boxes[..., 2:]) / 2
    return c, wh


def encode_boxes(priors, variances, gt):
    """Center-size encode gt against priors (``encodeBBoxWithVar``,
    ``DetectionUtil.cpp:112``)."""
    pc, pwh = _center_size(priors)
    gc, gwh = _center_size(gt)
    pwh = jnp.maximum(pwh, 1e-8)
    d_c = (gc - pc) / pwh / variances[..., :2]
    d_wh = jnp.log(jnp.maximum(jnp.abs(gwh / pwh), 1e-8)) / variances[..., 2:]
    return jnp.concatenate([d_c, d_wh], -1)


def decode_boxes(priors, variances, loc):
    """Inverse of :func:`encode_boxes` (``decodeBBoxWithVar``)."""
    pc, pwh = _center_size(priors)
    c = loc[..., :2] * variances[..., :2] * pwh + pc
    wh = jnp.exp(loc[..., 2:] * variances[..., 2:]) * pwh
    return jnp.concatenate([c - wh / 2, c + wh / 2], -1)


def match_priors(priors: jnp.ndarray, gt_boxes: jnp.ndarray,
                 gt_valid: jnp.ndarray, overlap_threshold: float = 0.5):
    """SSD matching for one image: greedy bipartite then per-prior threshold
    (``matchBBox``, ``DetectionUtil.cpp:234``). Returns ``(match_idx [P]
    int32, -1 = unmatched, overlaps [P])``. ``gt_valid`` is a [G] bool mask
    over padded ground truth rows.
    """
    P = priors.shape[0]
    G = gt_boxes.shape[0]
    ov = iou_matrix(priors, gt_boxes)           # [P, G]
    ov = jnp.where(gt_valid[None, :], ov, 0.0)
    ov = jnp.where(ov > 1e-6, ov, 0.0)
    best_overlap = jnp.max(ov, axis=1)

    def bipartite_step(_, state):
        match, avail = state                    # avail: [P,G] pairs still open
        masked = jnp.where(avail, ov, -1.0)
        flat = jnp.argmax(masked)
        i, j = flat // G, flat % G
        ok = masked[i, j] > 0.0
        match = jnp.where(ok, match.at[i].set(j), match)
        avail = jnp.where(ok, avail.at[i, :].set(False).at[:, j].set(False),
                          jnp.zeros_like(avail))
        return match, avail

    match0 = jnp.full((P,), -1, jnp.int32)
    avail0 = jnp.broadcast_to(gt_valid[None, :], (P, G))
    match, _ = lax.fori_loop(0, G, bipartite_step, (match0, avail0))

    # Per-prediction phase: any still-unmatched prior takes its best gt if
    # the overlap clears the threshold.
    best_gt = jnp.argmax(ov, axis=1).astype(jnp.int32)
    take = (match < 0) & (best_overlap >= overlap_threshold)
    match = jnp.where(take, best_gt, match)
    return match, best_overlap


class MultiBoxLoss(Module):
    """SSD multibox loss: smooth-L1 localisation on matched priors + softmax
    confidence with hard negative mining (reference:
    ``MultiBoxLossLayer.cpp``; knobs at ``:31-34``).

    ``forward(loc_preds [B,P,4], conf_preds [B,P,C], gt_boxes [B,G,4],
    gt_labels [B,G] with -1 padding)`` -> scalar loss (sum of loc+conf,
    normalised by the number of matched priors, as the reference does).
    """

    def __init__(self, priors, variances, num_classes: int,
                 overlap_threshold: float = 0.5, neg_pos_ratio: float = 3.0,
                 neg_overlap: float = 0.5, background_id: int = 0,
                 name: str = "multibox_loss"):
        super().__init__(name=name)
        self.priors = priors
        self.variances = variances
        self.num_classes = num_classes
        self.overlap_threshold = overlap_threshold
        self.neg_pos_ratio = neg_pos_ratio
        self.neg_overlap = neg_overlap
        self.background_id = background_id

    def forward(self, loc_preds, conf_preds, gt_boxes, gt_labels):
        def per_image(loc_p, conf_p, g_box, g_lab):
            valid = g_lab >= 0
            match, overlap = match_priors(self.priors, g_box, valid,
                                          self.overlap_threshold)
            pos = match >= 0
            npos = jnp.sum(pos)
            safe_match = jnp.maximum(match, 0)
            # --- localisation: smooth L1 on positives
            gt_for_prior = g_box[safe_match]
            loc_t = encode_boxes(self.priors, self.variances, gt_for_prior)
            sl1 = costs.smooth_l1_elementwise(loc_p, loc_t)
            loc_loss = jnp.sum(jnp.where(pos[:, None], sl1, 0.0))
            # --- confidence: CE vs matched label (background for negatives)
            conf_t = jnp.where(pos, g_lab[safe_match], self.background_id)
            logp = jax.nn.log_softmax(conf_p, -1)
            ce = -jnp.take_along_axis(logp, conf_t[:, None], 1)[:, 0]
            # hard negative mining: candidates are unmatched priors whose
            # best overlap is below neg_overlap; keep the highest-loss
            # neg_pos_ratio * npos of them (rank-of-rank, no host sort)
            neg_cand = (~pos) & (overlap < self.neg_overlap)
            neg_loss = jnp.where(neg_cand, ce, -jnp.inf)
            order = jnp.argsort(-neg_loss)
            rank = jnp.argsort(order)
            num_neg = jnp.minimum((self.neg_pos_ratio * npos).astype(jnp.int32),
                                  jnp.sum(neg_cand))
            neg = neg_cand & (rank < num_neg)
            conf_loss = jnp.sum(jnp.where(pos | neg, ce, 0.0))
            return loc_loss, conf_loss, npos

        loc_l, conf_l, npos = jax.vmap(per_image)(
            loc_preds, conf_preds, gt_boxes, gt_labels)
        denom = jnp.maximum(jnp.sum(npos), 1).astype(loc_preds.dtype)
        return (jnp.sum(loc_l) + jnp.sum(conf_l)) / denom


def nms(boxes: jnp.ndarray, scores: jnp.ndarray, max_out: int,
        iou_threshold: float = 0.45,
        score_threshold: float = 0.01) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Jit-safe greedy NMS (``applyNMSFast``, ``DetectionUtil.cpp:432``).

    Fixed ``max_out`` iterations of select-highest-then-suppress over static
    shapes. Returns ``(indices [max_out], keep_mask [max_out])``; slots past
    the survivor count have ``keep=False``.
    """
    alive = scores > score_threshold

    def step(_, state):
        alive, idxs, keep, k = state
        s = jnp.where(alive, scores, -jnp.inf)
        i = jnp.argmax(s)
        ok = s[i] > -jnp.inf
        overl = iou_matrix(boxes[i][None, :], boxes)[0]
        alive = alive & (overl <= iou_threshold)
        alive = alive.at[i].set(False)
        idxs = jnp.where(ok, idxs.at[k].set(i.astype(jnp.int32)), idxs)
        keep = jnp.where(ok, keep.at[k].set(True), keep)
        return alive, idxs, keep, k + jnp.where(ok, 1, 0)

    idxs0 = jnp.zeros((max_out,), jnp.int32)
    keep0 = jnp.zeros((max_out,), bool)
    _, idxs, keep, _ = lax.fori_loop(0, max_out, step,
                                     (alive, idxs0, keep0, 0))
    return idxs, keep


class DetectionOutput(Module):
    """Decode + per-class NMS + cross-class top-k (reference:
    ``DetectionOutputLayer.cpp``; ``getDetectionIndices`` at
    ``DetectionUtil.cpp:466``).

    ``forward(loc_preds [B,P,4], conf_preds [B,P,C])`` ->
    ``[B, keep_top_k, 6]`` rows of ``(label, score, xmin, ymin, xmax, ymax)``
    with ``label = -1`` padding. Fixed output shape keeps the whole decode
    path inside one XLA program.
    """

    def __init__(self, priors, variances, num_classes: int,
                 background_id: int = 0, nms_threshold: float = 0.45,
                 nms_top_k: int = 64, keep_top_k: int = 32,
                 confidence_threshold: float = 0.01,
                 name: str = "detection_output"):
        super().__init__(name=name)
        self.priors = priors
        self.variances = variances
        self.num_classes = num_classes
        self.background_id = background_id
        self.nms_threshold = nms_threshold
        self.nms_top_k = nms_top_k
        self.keep_top_k = keep_top_k
        self.confidence_threshold = confidence_threshold

    def forward(self, loc_preds, conf_preds):
        classes = [c for c in range(self.num_classes)
                   if c != self.background_id]

        def per_image(loc_p, conf_p):
            boxes = decode_boxes(self.priors, self.variances, loc_p)
            probs = jax.nn.softmax(conf_p, -1)
            rows = []
            for c in classes:
                idxs, keep = nms(boxes, probs[:, c], self.nms_top_k,
                                 self.nms_threshold,
                                 self.confidence_threshold)
                sc = jnp.where(keep, probs[idxs, c], -1.0)
                lab = jnp.where(keep, c, -1).astype(jnp.float32)
                rows.append(jnp.concatenate(
                    [lab[:, None], sc[:, None], boxes[idxs]], -1))
            allrows = jnp.concatenate(rows, 0)      # [(C-1)*nms_top_k, 6]
            if allrows.shape[0] < self.keep_top_k:
                # keep the output shape at the documented keep_top_k even
                # when few classes/candidates exist
                fill = jnp.full((self.keep_top_k - allrows.shape[0], 6), -1.0,
                                allrows.dtype)
                allrows = jnp.concatenate([allrows, fill], 0)
            top = jnp.argsort(-allrows[:, 1])[:self.keep_top_k]
            out = allrows[top]
            # blank out slots whose score fell below threshold / padding
            good = out[:, 1] > 0
            return jnp.where(good[:, None], out,
                             jnp.full_like(out, -1.0))

        return jax.vmap(per_image)(loc_preds, conf_preds)


class ROIPool(Module):
    """Max ROI pooling (reference: ``ROIPoolLayer.cpp`` — rounded roi corners
    at ``:97-100``, floor/ceil bin edges at ``:114-117``, empty bins -> 0).

    ``forward(features [B,H,W,C], rois [R,5])`` with roi rows
    ``(batch_idx, x1, y1, x2, y2)`` in image coordinates ->
    ``[R, ph, pw, C]``. Bins are realised as boolean masks over the feature
    map (one fused masked-max per bin) — static shapes, no gather scatter.
    """

    def __init__(self, pooled_height: int, pooled_width: int,
                 spatial_scale: float, name: str = "roi_pool"):
        super().__init__(name=name)
        self.ph = pooled_height
        self.pw = pooled_width
        self.spatial_scale = spatial_scale

    def forward(self, features, rois):
        B, H, W, C = features.shape
        hh = jnp.arange(H)
        ww = jnp.arange(W)

        def per_roi(roi):
            b = roi[0].astype(jnp.int32)
            x1 = jnp.round(roi[1] * self.spatial_scale)
            y1 = jnp.round(roi[2] * self.spatial_scale)
            x2 = jnp.round(roi[3] * self.spatial_scale)
            y2 = jnp.round(roi[4] * self.spatial_scale)
            rw = jnp.maximum(x2 - x1 + 1, 1.0)
            rh = jnp.maximum(y2 - y1 + 1, 1.0)
            bin_h = rh / self.ph
            bin_w = rw / self.pw
            fmap = features[b]                   # [H, W, C]
            ph_i = jnp.arange(self.ph)
            pw_i = jnp.arange(self.pw)
            hstart = jnp.clip(jnp.floor(ph_i * bin_h) + y1, 0, H)
            hend = jnp.clip(jnp.ceil((ph_i + 1) * bin_h) + y1, 0, H)
            wstart = jnp.clip(jnp.floor(pw_i * bin_w) + x1, 0, W)
            wend = jnp.clip(jnp.ceil((pw_i + 1) * bin_w) + x1, 0, W)
            hmask = (hh[None, :] >= hstart[:, None]) & \
                    (hh[None, :] < hend[:, None])         # [ph, H]
            wmask = (ww[None, :] >= wstart[:, None]) & \
                    (ww[None, :] < wend[:, None])         # [pw, W]
            mask = hmask[:, None, :, None] & wmask[None, :, None, :]
            vals = jnp.where(mask[..., None], fmap[None, None], -jnp.inf)
            out = jnp.max(vals, axis=(2, 3))              # [ph, pw, C]
            return jnp.where(jnp.isfinite(out), out, 0.0)

        return jax.vmap(per_roi)(rois)
