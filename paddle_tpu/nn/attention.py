"""Attention layers.

Reference: ``simple_attention`` (``/root/reference/python/paddle/
trainer_config_helpers/networks.py:1320`` — additive/concat attention over
encoder states inside the recurrent group) and ``dot_product_attention``
(``networks.py:1400``+). Multi-head scaled-dot-product attention is the
transformer-era generalization (beyond the 2017 reference, required for the
long-context axis; the sequence-parallel ring variant lives in
``paddle_tpu.parallel.ring_attention``).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core import initializers as I
from ..core.dtypes import current_policy
from ..core.module import Module
from .layers import Linear

__all__ = ["AdditiveAttention", "DotProductAttention", "MultiHeadAttention",
           "dot_product_attention_weights"]


def _tp_paged_kernel(kernel, q, pages_k, pages_v, *rest, head_dim: int):
    """Run a paged Pallas kernel PER SHARD over the active tp scope's
    head groups (ISSUE 15): the kernel is head-parallel by construction
    (its grid iterates heads independently), so a ``shard_map`` over the
    model axis hands each device its ``H/tp`` local heads of the query
    and of every pool block — block tables and lengths replicate. With
    no scope active the kernel runs whole, unchanged. ``head_dim`` is
    the axis of ``q`` (and of the kernel's output) carrying heads; pool
    leaves always carry heads on axis 2 (``[N, bs, H, hd]`` values,
    ``[N, bs, H]`` scale pages)."""
    from ..parallel.sharding import current_tp_shard
    scope = current_tp_shard()
    if scope is None:
        return kernel(q, pages_k, pages_v, *rest)
    from jax.sharding import PartitionSpec as P
    from ..parallel.overlap import shard_map_compat
    mesh, axis = scope
    qspec = P(*[axis if i == head_dim else None for i in range(q.ndim)])

    def pool_spec(pool):
        return jax.tree_util.tree_map(
            lambda leaf: P(*[axis if i == 2 else None
                             for i in range(leaf.ndim)]), pool)

    sharded = shard_map_compat(
        kernel, mesh=mesh,
        in_specs=(qspec, pool_spec(pages_k), pool_spec(pages_v))
        + tuple(P() for _ in rest),
        out_specs=qspec)
    return sharded(q, pages_k, pages_v, *rest)


def dot_product_attention_weights(q, k, mask=None, scale: Optional[float] = None):
    """softmax(q·kᵀ/√d) with additive masking; q [B, Tq, D], k [B, Tk, D]."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    logits = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    if mask is not None:
        logits = jnp.where(mask > 0, logits, -1e9)
    w = jax.nn.softmax(logits, axis=-1)
    if mask is not None:
        w = w * (mask > 0)
    return w


class AdditiveAttention(Module):
    """Bahdanau / the reference's ``simple_attention``: score = vᵀ tanh(W_d d +
    W_e e). ``__call__(decoder_state [B, D], enc [B, T, E], enc_mask [B, T])``
    returns the context vector [B, E]."""

    def __init__(self, hidden: int, name=None):
        super().__init__(name=name)
        self.hidden = hidden
        self.proj_d = Linear(hidden, use_bias=False, name="proj_decoder")
        self.proj_e = Linear(hidden, use_bias=False, name="proj_encoder")
        self.v = Linear(1, use_bias=False, name="score")

    def forward(self, decoder_state, enc, enc_mask=None, enc_proj=None):
        # enc_proj may be precomputed once per sequence (the reference caches
        # the encoder projection outside the recurrent group).
        if enc_proj is None:
            enc_proj = self.proj_e(enc)
        s = jnp.tanh(enc_proj + self.proj_d(decoder_state)[:, None, :])
        scores = self.v(s)[..., 0]                       # [B, T]
        from .activations import sequence_softmax
        w = sequence_softmax(scores, mask=enc_mask)
        return jnp.einsum("bt,bte->be", w, enc), w


class DotProductAttention(Module):
    """The reference's ``dot_product_attention`` (networks.py): context =
    softmax(d·Eᵀ)·E for a single query state."""

    def __init__(self, scale: Optional[float] = None, name=None):
        super().__init__(name=name)
        self.scale = scale

    def forward(self, decoder_state, enc, enc_mask=None):
        w = dot_product_attention_weights(
            decoder_state[:, None, :], enc,
            mask=None if enc_mask is None else enc_mask[:, None, :],
            scale=self.scale)[:, 0]                      # [B, T]
        return jnp.einsum("bt,bte->be", w, enc), w


class MultiHeadAttention(Module):
    """Scaled-dot-product multi-head attention, bf16-friendly, with optional
    causal + segment masking (packed sequences). Self- or cross-attention.

    ``attention_impl`` selects the self-attention compute path:

    - ``"xla"``: materialized-scores einsum path; supports arbitrary
      ``mask=`` and cross-attention. The oracle path.
    - ``"flash"``: fused Pallas blockwise kernel
      (:mod:`paddle_tpu.nn.pallas_attention`) — linear HBM traffic forward
      AND backward (both are fully blockwise; nothing [T, T]-shaped in
      HBM). Supports ``causal=`` and packed-sequence ``segments=``.
    - ``"ring"``: sequence-parallel ring attention over the mesh's ``seq``
      axis (:mod:`paddle_tpu.parallel.ring`); needs ``seq_mesh=``.
    - ``"seq"``/``"ulysses"``: all-to-all sequence parallelism
      (:mod:`paddle_tpu.parallel.ulysses`); needs ``seq_mesh=``.

    All fast paths consume the framework's variable-length contract
    (``core.sequence`` packing: ``segments`` [B, T], 1-based, 0 = pad) —
    the successor of the reference's never-padded
    ``Argument::sequenceStartPositions`` ragged batches
    (``paddle/parameter/Argument.h:84-93``). Arbitrary dense ``mask=`` is
    XLA-path only. ``use_flash=True`` is an alias for
    ``attention_impl="flash"``."""

    def __init__(self, num_heads: int, head_dim: Optional[int] = None,
                 out_dim: Optional[int] = None, use_flash: bool = False,
                 attention_impl: Optional[str] = None, seq_mesh=None,
                 seq_axis: str = "seq", batch_axis: Optional[str] = None,
                 name=None):
        super().__init__(name=name)
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.out_dim = out_dim
        impl = attention_impl or ("flash" if use_flash else "xla")
        if impl == "ulysses":
            impl = "seq"
        if impl not in ("xla", "flash", "ring", "seq"):
            raise ValueError(f"unknown attention_impl {impl!r}")
        if impl in ("ring", "seq") and seq_mesh is None:
            raise ValueError(f"attention_impl={impl!r} needs seq_mesh=")
        self.attention_impl = impl
        self.use_flash = impl == "flash"
        self.seq_mesh = seq_mesh
        self.seq_axis = seq_axis
        self.batch_axis = batch_axis

    def _fast_path_checks(self, q_in, kv_in, mask):
        if mask is not None:
            raise ValueError(
                f"attention_impl={self.attention_impl!r} supports causal= "
                "and segments= (packed sequences), not arbitrary mask=; "
                "use the default XLA path for dense masks")
        if kv_in is not q_in:
            raise ValueError(
                f"attention_impl={self.attention_impl!r} is self-attention "
                "only; pass kv_in=None or use the XLA path")

    def forward(self, q_in, kv_in=None, mask=None, causal: bool = False,
                segments=None, return_kv: bool = False):
        """q_in [B, Tq, D]; kv_in defaults to q_in (self-attention);
        mask [B, Tq, Tk] (1 = attend); segments [B, T] packed-sequence ids
        (1-based, 0 = padding — ``core.sequence.pack_sequences``).
        ``return_kv``: also return the projected ``(k, v)`` ([B, Tk, H,
        hd] each, pre-attention) — the serving prefill captures them into
        the paged KV cache (``paddle_tpu.serve``)."""
        kv_in = q_in if kv_in is None else kv_in
        pol = current_policy()
        d_model = q_in.shape[-1]
        h = self.num_heads
        hd = self.head_dim or d_model // h
        out_d = self.out_dim or d_model

        def proj(name, x, feats):
            w = self.param(name, I.xavier_uniform, (x.shape[-1], feats))
            return jnp.dot(pol.cast_compute(x), pol.cast_compute(w),
                           preferred_element_type=pol.accum_dtype)

        # named_scope annotations: profiler traces resolve the projections
        # and the attention core by name instead of anonymous fusions.
        with jax.named_scope("qkv_proj"):
            q = proj("wq", q_in, h * hd).reshape(*q_in.shape[:2], h, hd)
            k = proj("wk", kv_in, h * hd).reshape(*kv_in.shape[:2], h, hd)
            v = proj("wv", kv_in, h * hd).reshape(*kv_in.shape[:2], h, hd)
        impl = self.attention_impl
        if impl == "flash":
            self._fast_path_checks(q_in, kv_in, mask)
            from .pallas_attention import flash_attention
            T = q.shape[1]
            if next((b for b in (128, 64, 32, 16, 8) if T % b == 0),
                    None) is None:
                raise ValueError(
                    f"flash path needs seq len divisible by 8; pad T={T}")
            # block sizes auto-select in the kernel (large blocks: the
            # per-grid-step overhead dominated at the old fixed 128 —
            # measured 5x per-layer, experiments/profile_transformer.py)
            with jax.named_scope("flash_attention"):
                ctx = flash_attention(jnp.moveaxis(q, 2, 1),
                                      jnp.moveaxis(k, 2, 1),
                                      jnp.moveaxis(v, 2, 1),
                                      segments, causal)
                ctx = jnp.moveaxis(ctx, 1, 2).astype(pol.compute_dtype)
        elif impl in ("ring", "seq"):
            self._fast_path_checks(q_in, kv_in, mask)
            if impl == "ring":
                from ..parallel.ring import make_ring_attention as make
            else:
                from ..parallel.ulysses import make_ulysses_attention as make
            attn = make(self.seq_mesh, seq_axis=self.seq_axis,
                        batch_axis=self.batch_axis, causal=causal,
                        with_segments=segments is not None)
            with jax.named_scope(f"{impl}_attention"):
                ctx = (attn(q, k, v, segments) if segments is not None
                       else attn(q, k, v)).astype(pol.compute_dtype)
        else:
            with jax.named_scope("sdpa_xla"):
                logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
                logits = logits.astype(jnp.float32)
                if causal:
                    Tq, Tk = logits.shape[-2:]
                    cm = jnp.tril(jnp.ones((Tq, Tk), bool))
                    logits = jnp.where(cm[None, None], logits, -1e9)
                if segments is not None:
                    sm = (segments[:, :, None] == segments[:, None, :]) \
                        & (segments[:, :, None] > 0)
                    logits = jnp.where(sm[:, None], logits, -1e9)
                if mask is not None:
                    logits = jnp.where(mask[:, None, :, :] > 0, logits, -1e9)
                w = jax.nn.softmax(logits, axis=-1).astype(pol.compute_dtype)
                ctx = jnp.einsum("bhqk,bkhd->bqhd", w, v)
        ctx = ctx.reshape(*q_in.shape[:2], h * hd)
        with jax.named_scope("out_proj"):
            out = proj("wo", ctx, out_d)
        if return_kv:
            # serving prefill captures (k, v) into the paged pools; under
            # a tp_shard_scope (ISSUE 15) pin them head-sharded so the
            # engine's scatter lands on the sharded pools reshard-free
            from ..parallel.sharding import tp_constrain
            return out, (tp_constrain(k, 2), tp_constrain(v, 2))
        return out

    def decode(self, q_in, pages_k, pages_v, tables, positions, active,
               impl: str = "xla"):
        """One decode step (q_len = 1) against a paged KV cache: project
        the new token, scatter its K/V into this layer's pool pages, and
        attend over the slot's whole ragged context.

        Args: ``q_in`` [S, 1, D] (one token per serving slot);
        ``pages_k``/``pages_v`` [N, bs, H, hd] (this layer's pool);
        ``tables`` [S, MB] block tables; ``positions`` [S] the incoming
        token's 0-based position (== the pre-step sequence length);
        ``active`` [S] bool slot mask (inactive slots scatter to the null
        block and output zeros). ``impl``: ``"paged"`` = the Pallas
        decode kernel (:func:`~paddle_tpu.nn.pallas_attention.
        paged_decode_attention`); ``"xla"`` = the gather + masked-softmax
        reference path, bit-exact (f32) with the training forward at the
        same padded width. Returns ``(out [S, 1, out_d], pages_k,
        pages_v)`` with the updated pools.

        Callable outside forward (the ``scope()`` helper-method pattern):
        the serving engine reaches it via
        ``model.apply(..., method="decode_step")``. Quantized pools (the
        ``(int8, scales)`` tuples, ISSUE 14) flow through transparently:
        the scatter quantizes, the kernel/gather dequantizes. Under an
        active ``tp_shard_scope`` (ISSUE 15) the projections and pools
        are constrained head-sharded — qkv column-parallel, attention on
        local heads, the out projection's row-parallel partial sums
        all-reduced — the Megatron tp recipe with the partitioner
        inserting the collectives; the paged kernel path runs per shard
        via :func:`_tp_paged_kernel`."""
        from ..serve.kv_cache import gather_pages, scatter_token_pages
        from ..parallel.sharding import tp_constrain
        with self.scope():
            pol = current_policy()
            d_model = q_in.shape[-1]
            h = self.num_heads
            hd = self.head_dim or d_model // h
            out_d = self.out_dim or d_model
            S = q_in.shape[0]

            def proj(name, x, feats):
                w = self.param(name, I.xavier_uniform, (x.shape[-1], feats))
                return jnp.dot(pol.cast_compute(x), pol.cast_compute(w),
                               preferred_element_type=pol.accum_dtype)

            with jax.named_scope("qkv_proj"):
                q = tp_constrain(
                    proj("wq", q_in, h * hd).reshape(S, 1, h, hd), 2)
                k = tp_constrain(
                    proj("wk", q_in, h * hd).reshape(S, 1, h, hd), 2)
                v = tp_constrain(
                    proj("wv", q_in, h * hd).reshape(S, 1, h, hd), 2)
            with jax.named_scope("kv_scatter"):
                pages_k = tp_constrain(
                    scatter_token_pages(pages_k, k[:, 0], tables,
                                        positions, active), 2)
                pages_v = tp_constrain(
                    scatter_token_pages(pages_v, v[:, 0], tables,
                                        positions, active), 2)
            # the new token sees itself: effective length = position + 1
            eff_len = jnp.where(active, positions + 1, 0)
            if impl == "paged":
                from .pallas_attention import paged_decode_attention
                with jax.named_scope("paged_attention"):
                    ctx = _tp_paged_kernel(
                        paged_decode_attention, q[:, 0], pages_k,
                        pages_v, tables, eff_len, head_dim=1)
                    ctx = ctx.reshape(S, 1, h, hd).astype(pol.compute_dtype)
            else:
                with jax.named_scope("sdpa_xla"):
                    kg = gather_pages(pages_k, tables)      # [S, W, h, hd]
                    vg = gather_pages(pages_v, tables)
                    ctx = self._sdpa_row(q, kg, vg, eff_len, pol, hd)
            ctx = tp_constrain(ctx, 2).reshape(S, 1, h * hd)
            with jax.named_scope("out_proj"):
                out = tp_constrain(proj("wo", ctx, out_d))
            return out, pages_k, pages_v

    @staticmethod
    def _sdpa_row(q, kg, vg, eff_len, pol, hd):
        """ONE query row against the gathered paged context ``kg``/``vg``
        ``[S, W, h, hd]`` -> ``ctx [S, 1, h, hd]`` — the single op chain
        BOTH :meth:`decode` (q_len=1) and every :meth:`decode_span` row
        share, so their bit-equality lock-step is structural: an edit
        here changes the tick and the verify/chunk span together, never
        one without the other.

        Mirrors the forward "sdpa_xla" branch op for op — WITH the
        single query row broadcast to all W rows, so every op in the
        chain has the training forward's exact shape. XLA's CPU gemm is
        row-stable across row counts but the q_len=1 PV contraction
        lowers with a DIFFERENT k-accumulation order (measured: ~1 ulp
        drift), so shape-matching is what makes decode logits bit-equal
        (f32) to the full-sequence forward's row. O(W^2) — this is the
        correctness-oracle path; the paged Pallas kernel is the
        decode-shaped production path."""
        S, W = kg.shape[:2]
        h = q.shape[2]
        qb = jnp.broadcast_to(q, (S, W, h, hd))
        logits = jnp.einsum("bqhd,bkhd->bhqk", qb, kg) / np.sqrt(hd)
        logits = logits.astype(jnp.float32)
        mask = jnp.arange(W)[None, :] < eff_len[:, None]
        logits = jnp.where(mask[:, None, None, :], logits, -1e9)
        w = jax.nn.softmax(logits, axis=-1).astype(pol.compute_dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", w, vg)[:, :1]
        # a length-0 lane's softmax is uniform over -1e9 logits (an
        # average of stale pages, not zeros) — zero it to match the
        # paged kernel's convention; live lanes pass through unchanged
        return jnp.where((eff_len > 0)[:, None, None, None], ctx, 0.0)

    def decode_span(self, q_in, pages_k, pages_v, tables, start, n,
                    active, impl: str = "xla", write_from=None):
        """A SPAN of consecutive new tokens per slot against the paged
        KV cache — the multi-query generalization of :meth:`decode`
        shared by the speculative verify tick (``Q = 1 + draft_k``) and
        chunked prefill (``Q = chunk``), ISSUE 12.

        Args: ``q_in`` [S, Q, D] (token ``j`` of slot ``s`` sits at
        position ``start[s] + j``); ``n`` [S] live token count per slot
        (rows ``>= n`` are padding: null-block scatter, garbage logits
        the host ignores); ``active`` [S]; ``write_from`` [S] optional
        absolute position below which the scatter is masked (a chunk
        re-attending a shared prefix must not write co-owned pages).
        Returns ``(out [S, Q, out_d], pages_k, pages_v)``.

        ``impl="xla"``: each row is computed by the EXACT q_len=1
        broadcast-to-W op sequence (an unrolled loop over the static
        ``Q``), so every position's output is bit-equal (f32) to what a
        sequence of single-token :meth:`decode` ticks would have
        produced — the lossless-speculation and chunked-prefill
        bit-equality guarantees are structural, not tolerances.
        ``impl="paged"``: the multi-query paged Pallas kernel
        (:func:`~paddle_tpu.nn.pallas_attention.paged_span_attention`,
        ISSUE 14) — streams only the slot's own pages instead of the
        O(W)-per-row gather; tolerance-accurate vs the oracle, bit-equal
        to the q_len=1 kernel at Q=1. Quantized pools flow through both
        (scatter quantizes, kernel/gather dequantizes). Under an active
        ``tp_shard_scope`` (ISSUE 15) the span runs tp-sharded exactly
        like :meth:`decode` — head-sharded projections/pools/kernel,
        all-reduced out projection."""
        from ..serve.kv_cache import gather_pages, scatter_span_pages
        from ..parallel.sharding import tp_constrain
        if impl not in ("xla", "paged"):
            raise ValueError(
                f"decode_span supports impl='xla'|'paged', got {impl!r}")
        with self.scope():
            pol = current_policy()
            d_model = q_in.shape[-1]
            h = self.num_heads
            hd = self.head_dim or d_model // h
            out_d = self.out_dim or d_model
            S, Q = q_in.shape[:2]

            def proj(name, x, feats):
                w = self.param(name, I.xavier_uniform, (x.shape[-1], feats))
                return jnp.dot(pol.cast_compute(x), pol.cast_compute(w),
                               preferred_element_type=pol.accum_dtype)

            with jax.named_scope("qkv_proj"):
                q = tp_constrain(
                    proj("wq", q_in, h * hd).reshape(S, Q, h, hd), 2)
                k = tp_constrain(
                    proj("wk", q_in, h * hd).reshape(S, Q, h, hd), 2)
                v = tp_constrain(
                    proj("wv", q_in, h * hd).reshape(S, Q, h, hd), 2)
            n_eff = jnp.where(active, n, 0)
            with jax.named_scope("kv_scatter"):
                pages_k = tp_constrain(
                    scatter_span_pages(pages_k, k, tables, start,
                                       n_eff, write_from), 2)
                pages_v = tp_constrain(
                    scatter_span_pages(pages_v, v, tables, start,
                                       n_eff, write_from), 2)
            if impl == "paged":
                from .pallas_attention import paged_span_attention
                with jax.named_scope("paged_span_attention"):
                    ctx = _tp_paged_kernel(
                        paged_span_attention, q, pages_k, pages_v,
                        tables, start, n_eff, head_dim=2)
                    ctx = ctx.astype(pol.compute_dtype)
            else:
                with jax.named_scope("sdpa_xla"):
                    kg = gather_pages(pages_k, tables)  # [S, W, h, hd]
                    vg = gather_pages(pages_v, tables)
                    ctxs = []
                    for j in range(Q):
                        # row j sees context start+j+1 (itself
                        # included); later span rows sit beyond the
                        # mask, and masked logits are the constant -1e9
                        # regardless of page content — identical to the
                        # sequential tick's view
                        eff_len = jnp.where(active & (j < n_eff),
                                            start + j + 1, 0)
                        ctxs.append(self._sdpa_row(q[:, j:j + 1], kg,
                                                   vg, eff_len, pol,
                                                   hd))
                    ctx = jnp.concatenate(ctxs, axis=1)  # [S, Q, h, hd]
            ctx = tp_constrain(ctx, 2).reshape(S, Q, h * hd)
            with jax.named_scope("out_proj"):
                out = tp_constrain(proj("wo", ctx, out_d))
            return out, pages_k, pages_v
