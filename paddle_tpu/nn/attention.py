"""Attention layers.

Reference: ``simple_attention`` (``/root/reference/python/paddle/
trainer_config_helpers/networks.py:1320`` — additive/concat attention over
encoder states inside the recurrent group) and ``dot_product_attention``
(``networks.py:1400``+). Multi-head scaled-dot-product attention is the
transformer-era generalization (beyond the 2017 reference, required for the
long-context axis; the sequence-parallel ring variant lives in
``paddle_tpu.parallel.ring_attention``).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core import initializers as I
from ..core.dtypes import current_policy
from ..core.module import Module
from .layers import Linear

__all__ = ["AdditiveAttention", "DotProductAttention", "MultiHeadAttention",
           "dot_product_attention_weights"]


def dot_product_attention_weights(q, k, mask=None, scale: Optional[float] = None):
    """softmax(q·kᵀ/√d) with additive masking; q [B, Tq, D], k [B, Tk, D]."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    logits = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    if mask is not None:
        logits = jnp.where(mask > 0, logits, -1e9)
    w = jax.nn.softmax(logits, axis=-1)
    if mask is not None:
        w = w * (mask > 0)
    return w


class AdditiveAttention(Module):
    """Bahdanau / the reference's ``simple_attention``: score = vᵀ tanh(W_d d +
    W_e e). ``__call__(decoder_state [B, D], enc [B, T, E], enc_mask [B, T])``
    returns the context vector [B, E]."""

    def __init__(self, hidden: int, name=None):
        super().__init__(name=name)
        self.hidden = hidden
        self.proj_d = Linear(hidden, use_bias=False, name="proj_decoder")
        self.proj_e = Linear(hidden, use_bias=False, name="proj_encoder")
        self.v = Linear(1, use_bias=False, name="score")

    def forward(self, decoder_state, enc, enc_mask=None, enc_proj=None):
        # enc_proj may be precomputed once per sequence (the reference caches
        # the encoder projection outside the recurrent group).
        if enc_proj is None:
            enc_proj = self.proj_e(enc)
        s = jnp.tanh(enc_proj + self.proj_d(decoder_state)[:, None, :])
        scores = self.v(s)[..., 0]                       # [B, T]
        from .activations import sequence_softmax
        w = sequence_softmax(scores, mask=enc_mask)
        return jnp.einsum("bt,bte->be", w, enc), w


class DotProductAttention(Module):
    """The reference's ``dot_product_attention`` (networks.py): context =
    softmax(d·Eᵀ)·E for a single query state."""

    def __init__(self, scale: Optional[float] = None, name=None):
        super().__init__(name=name)
        self.scale = scale

    def forward(self, decoder_state, enc, enc_mask=None):
        w = dot_product_attention_weights(
            decoder_state[:, None, :], enc,
            mask=None if enc_mask is None else enc_mask[:, None, :],
            scale=self.scale)[:, 0]                      # [B, T]
        return jnp.einsum("bt,bte->be", w, enc), w


class MultiHeadAttention(Module):
    """Scaled-dot-product multi-head attention, bf16-friendly, with optional
    causal + segment masking (packed sequences). Self- or cross-attention.

    ``use_flash=True`` routes self-attention through the fused Pallas kernel
    (:mod:`paddle_tpu.nn.pallas_attention`) — linear HBM traffic in the
    forward pass (the backward currently rematerialises full attention, see
    the kernel module docstring). The flash path supports ``causal=`` but
    not arbitrary ``mask=`` (flash + mask raises; use packing-aware masks on
    the XLA path)."""

    def __init__(self, num_heads: int, head_dim: Optional[int] = None,
                 out_dim: Optional[int] = None, use_flash: bool = False,
                 name=None):
        super().__init__(name=name)
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.out_dim = out_dim
        self.use_flash = use_flash

    def forward(self, q_in, kv_in=None, mask=None, causal: bool = False):
        """q_in [B, Tq, D]; kv_in defaults to q_in (self-attention);
        mask [B, Tq, Tk] (1 = attend)."""
        kv_in = q_in if kv_in is None else kv_in
        pol = current_policy()
        d_model = q_in.shape[-1]
        h = self.num_heads
        hd = self.head_dim or d_model // h
        out_d = self.out_dim or d_model

        def proj(name, x, feats):
            w = self.param(name, I.xavier_uniform, (x.shape[-1], feats))
            return jnp.dot(pol.cast_compute(x), pol.cast_compute(w),
                           preferred_element_type=pol.accum_dtype)

        q = proj("wq", q_in, h * hd).reshape(*q_in.shape[:2], h, hd)
        k = proj("wk", kv_in, h * hd).reshape(*kv_in.shape[:2], h, hd)
        v = proj("wv", kv_in, h * hd).reshape(*kv_in.shape[:2], h, hd)
        if self.use_flash:
            if mask is not None:
                raise ValueError(
                    "flash path supports causal=, not arbitrary mask=")
            if kv_in is not q_in:
                raise ValueError("flash path is self-attention only; pass "
                                 "kv_in=None or use use_flash=False")
            from .pallas_attention import flash_attention
            T = q.shape[1]
            # largest divisor of T up to 128 keeps VMEM blocks bounded; a T
            # with no reasonable divisor must be padded upstream
            bq = next((b for b in (128, 64, 32, 16, 8) if T % b == 0), None)
            if bq is None:
                raise ValueError(
                    f"flash path needs seq len divisible by 8; pad T={T}")
            ctx = flash_attention(jnp.moveaxis(q, 2, 1),
                                  jnp.moveaxis(k, 2, 1),
                                  jnp.moveaxis(v, 2, 1),
                                  causal, None, bq, bq)
            ctx = jnp.moveaxis(ctx, 1, 2).astype(pol.compute_dtype)
        else:
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
            logits = logits.astype(jnp.float32)
            if causal:
                Tq, Tk = logits.shape[-2:]
                cm = jnp.tril(jnp.ones((Tq, Tk), bool))
                logits = jnp.where(cm[None, None], logits, -1e9)
            if mask is not None:
                logits = jnp.where(mask[:, None, :, :] > 0, logits, -1e9)
            w = jax.nn.softmax(logits, axis=-1).astype(pol.compute_dtype)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", w, v)
        ctx = ctx.reshape(*q_in.shape[:2], h * hd)
        return proj("wo", ctx, out_d)
