"""Linear-chain CRF — loss (forward algorithm) and Viterbi decode as scans.

Reference: ``/root/reference/paddle/gserver/layers/LinearChainCRF.cpp`` (forward
recursion with start/stop transition rows, ``CRFLayer.cpp`` the cost layer,
``CRFDecodingLayer.cpp`` the Viterbi decoder; fluid ``linear_chain_crf_op``).
Parameterization matches the reference: a ``[L+2, L]`` weight matrix whose row 0
is start transitions ``a``, row 1 stop transitions ``b``, rows 2.. the ``w``
transition matrix (``LinearChainCRF.cpp:23-29`` comment block).

Log-space throughout; recursions are ``lax.scan`` over time (XLA-friendly, no
dynamic shapes); masking freezes alpha past each sequence's end.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core import initializers as I
from ..core.module import Module
from ..core.sequence import length_mask

__all__ = ["CRF", "crf_log_likelihood", "crf_decode"]


def _logsumexp(x, axis=-1):
    return jax.scipy.special.logsumexp(x, axis=axis)


def crf_forward(emissions, lengths, start, stop, trans):
    """log Z via the forward recursion (LinearChainCRF::forward analog).

    emissions: [B, T, L] unary scores; lengths: [B]; start/stop: [L];
    trans: [L, L] (trans[i, j] = score of i -> j). Returns [B] log partition.
    """
    b, t, L = emissions.shape
    alpha0 = start[None, :] + emissions[:, 0]          # [B, L]
    mask = length_mask(lengths, t)                      # [B, T]

    def body(alpha, inp):
        emit_t, m_t = inp                               # [B, L], [B]
        # alpha'[j] = logsumexp_i(alpha[i] + trans[i,j]) + emit[j]
        scores = alpha[:, :, None] + trans[None, :, :]  # [B, L, L]
        new = _logsumexp(scores, axis=1) + emit_t
        keep = m_t[:, None]
        return keep * new + (1 - keep) * alpha, None

    xs = (jnp.swapaxes(emissions, 0, 1)[1:], jnp.swapaxes(mask, 0, 1)[1:])
    alpha, _ = lax.scan(body, alpha0, xs)
    return _logsumexp(alpha + stop[None, :], axis=1)


def crf_score(emissions, tags, lengths, start, stop, trans):
    """Score of a given tag path (gold score)."""
    b, t, L = emissions.shape
    mask = length_mask(lengths, t)
    # unary terms
    unary = jnp.take_along_axis(emissions, tags[..., None], axis=-1)[..., 0]
    unary = (unary * mask).sum(1)
    # start / stop terms
    first = jnp.take(start, tags[:, 0])
    last_idx = jnp.maximum(lengths - 1, 0)
    last_tag = jnp.take_along_axis(tags, last_idx[:, None], 1)[:, 0]
    final = jnp.take(stop, last_tag)
    # transitions
    pair = trans[tags[:, :-1], tags[:, 1:]]            # [B, T-1]
    pair = (pair * mask[:, 1:]).sum(1)
    valid = (lengths > 0).astype(emissions.dtype)
    return (unary + pair + first + final) * valid


def crf_log_likelihood(emissions, tags, lengths, weights):
    """Per-sequence negative log likelihood (the reference ``CRFLayer`` cost).
    ``weights``: the [L+2, L] parameter block (start/stop/trans packed)."""
    start, stop, trans = weights[0], weights[1], weights[2:]
    logz = crf_forward(emissions, lengths, start, stop, trans)
    gold = crf_score(emissions, tags, lengths, start, stop, trans)
    return logz - gold


def crf_decode(emissions, lengths, weights):
    """Viterbi decode (reference: ``CRFDecodingLayer`` /
    ``LinearChainCRF::decode``): returns best tags [B, T] (0 past lengths)."""
    start, stop, trans = weights[0], weights[1], weights[2:]
    b, t, L = emissions.shape
    mask = length_mask(lengths, t)
    alpha0 = start[None, :] + emissions[:, 0]

    def body(alpha, inp):
        emit_t, m_t = inp
        scores = alpha[:, :, None] + trans[None, :, :]  # [B, i, j]
        best_prev = jnp.argmax(scores, axis=1)          # [B, L]
        new = jnp.max(scores, axis=1) + emit_t
        keep = m_t[:, None]
        new_alpha = keep * new + (1 - keep) * alpha
        # frozen steps keep identity backpointer so backtrace passes through
        bp = jnp.where(m_t[:, None] > 0, best_prev,
                       jnp.arange(L)[None, :])
        return new_alpha, bp

    xs = (jnp.swapaxes(emissions, 0, 1)[1:], jnp.swapaxes(mask, 0, 1)[1:])
    alpha, bps = lax.scan(body, alpha0, xs)             # bps: [T-1, B, L]
    last = jnp.argmax(alpha + stop[None, :], axis=-1)   # [B]

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    first_tag, tags_rev = lax.scan(back, last, bps, reverse=True)
    tags = jnp.concatenate([first_tag[None], tags_rev], 0)  # [T, B]
    tags = jnp.swapaxes(tags, 0, 1)
    return (tags * mask.astype(tags.dtype)).astype(jnp.int32)


class CRF(Module):
    """CRF layer holding the packed [L+2, L] weights (reference param layout)."""

    def __init__(self, num_tags: int, name=None):
        super().__init__(name=name)
        self.num_tags = num_tags

    def weights(self):
        with self.scope():
            return self.param("w", I.normal(0.01),
                              (self.num_tags + 2, self.num_tags))

    def forward(self, emissions, tags, lengths):
        return crf_log_likelihood(emissions, tags, lengths, self.weights())

    def decode(self, emissions, lengths):
        return crf_decode(emissions, lengths, self.weights())
