"""1x1 convolution as matmul + Pallas dW — the ResNet bottleneck hot path.

PERF.md (round 3) traced the ResNet-50 residual to XLA's conv kernels: the
dW convs for [1,1,Cin,Cout] kernels reduce a ~10^5-element contraction into
a tiny output and run at ~13% MXU efficiency; dx convs output-fused with
BN-backward reductions run at 5-11%. A 1x1 stride-1 conv IS a matmul
(``[B*H*W, Cin] @ [Cin, Cout]``), so this module provides:

- :func:`conv1x1` — the matmul form with a ``jax.custom_vjp``: forward and
  dx go through XLA's *matmul* path (tiled very differently from its conv
  path), and dW runs a dedicated Pallas reduction-matmul kernel that
  streams M-chunks of x/dy through VMEM and accumulates the [Cin, Cout]
  tile in f32 across the sequential TPU grid.
- :func:`conv1x1_strided` — the stride-s variant (the bottleneck shortcut):
  slice then matmul; the slice VJP is a scatter XLA handles well.

``experiments/conv1x1_backward.py`` measures this form against
``lax.conv_general_dilated`` per bottleneck shape; ``nn.layers.Conv2D``
routes 1x1 convs here when ``set_conv1x1_impl`` selects it.

Reference lineage: the reference's 1x1 convs run as cuDNN GEMMs
(``gserver/layers/ExpandConvLayer.cpp`` im2col+GEMM path) — the GEMM view
is the original form; the TPU twist is owning the dW tiling.

``interpret=None`` auto-selects the Pallas interpreter off-TPU (same
convention as :mod:`.pallas_attention`).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["conv1x1", "conv1x1_strided", "dw_pallas"]


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _dw_kernel(x_ref, dy_ref, out_ref):
    """One M-chunk's contribution: out += x_chunk^T @ dy_chunk (f32)."""
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    out_ref[...] += jax.lax.dot_general(
        x_ref[...], dy_ref[...],
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _chunk_rows(m: int, cap: int = 2048) -> int:
    """Largest divisor of m that is a multiple of 16 (bf16 sublane tile)
    and <= cap; falls back to m itself (single chunk)."""
    best = m
    for mc in range(min(cap, m), 15, -16):
        if m % mc == 0 and mc % 16 == 0:
            best = mc
            break
    return best


@functools.partial(jax.jit, static_argnames=("interpret",))
def dw_pallas(x2d, dy2d, interpret: Optional[bool] = None):
    """dW = x2d^T @ dy2d with f32 accumulation. x2d [M, Cin], dy2d
    [M, Cout] -> [Cin, Cout] f32. Grid streams M-chunks; the output tile is
    revisited every step (sequential TPU grid) and accumulated in place."""
    m, cin = x2d.shape
    cout = dy2d.shape[1]
    mc = _chunk_rows(m)
    interp = _interpret() if interpret is None else interpret
    return pl.pallas_call(
        _dw_kernel,
        grid=(m // mc,),
        in_specs=[
            pl.BlockSpec((mc, cin), lambda i: (i, 0)),
            pl.BlockSpec((mc, cout), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((cin, cout), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((cin, cout), jnp.float32),
        interpret=interp,
    )(x2d, dy2d)


@jax.custom_vjp
def conv1x1(x, w):
    """y[b,h,w,:] = x[b,h,w,:] @ w. x [B,H,W,Cin], w [Cin,Cout]."""
    b, h, ww, cin = x.shape
    y = x.reshape(b * h * ww, cin) @ w
    return y.reshape(b, h, ww, w.shape[1])


def _conv1x1_fwd(x, w):
    return conv1x1(x, w), (x, w)


def _conv1x1_bwd(res, dy):
    x, w = res
    b, h, ww, cin = x.shape
    cout = w.shape[1]
    dy2 = dy.reshape(b * h * ww, cout)
    dx = (dy2 @ w.T).reshape(x.shape)
    dw = dw_pallas(x.reshape(b * h * ww, cin), dy2).astype(w.dtype)
    return dx, dw


conv1x1.defvjp(_conv1x1_fwd, _conv1x1_bwd)


def conv1x1_strided(x, w, stride=(1, 1)):
    """Stride-s 1x1 conv (the bottleneck/shortcut downsample): slicing
    commutes with a pointwise conv, and the slice VJP (zero-scatter) is
    cheap — so the strided case reuses the dense-matmul kernel."""
    sh, sw = stride
    if (sh, sw) != (1, 1):
        x = x[:, ::sh, ::sw, :]
    return conv1x1(x, w)
