"""Fused blockwise (flash-style) attention in Pallas — the long-context hot
op where XLA's generic fusion loses: materialising the [T, T] score matrix in
HBM is O(T^2) bandwidth, while these kernels stream K/V blocks through VMEM
with an online softmax, keeping HBM traffic linear in T.

Reference-lineage note: the 2017 reference has no attention kernel at all
(SURVEY §5 long-context row — this is one of the deliberate "exceeds" items);
its closest machinery is the RNN-era ``ContextProjection``. The algorithm is
the public flash-attention recipe; the kernels follow the Pallas TPU playbook
(`/opt/skills/guides/pallas_guide.md`): 2-D grid over (batch*heads, row
blocks), the streamed operand resident in VMEM, ``fori_loop`` over the other
axis' blocks.

Training is fully blockwise: the forward saves only O and the per-row
log-sum-exp L; the backward runs two Pallas kernels (dq over query blocks;
dk/dv over key blocks) that rebuild each probability tile as
``exp(s - L)`` — nothing [T, T]-shaped ever exists in HBM, forward or
backward.

``interpret=None`` auto-selects the Pallas interpreter off-TPU, so the same
tests run on the CPU harness and the kernels compile on real chips.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["flash_attention", "reference_attention"]

_NEG = -1e30


def reference_attention(q, k, v, causal: bool = False,
                        scale: Optional[float] = None):
    """Plain softmax attention — the numeric oracle. [B, H, T, D] inputs."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _causal_mask(qi, bq, kb, bk):
    q_idx = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_idx = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return k_idx <= q_idx


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                 block_k):
    # q_ref: [BQ, D]; k_ref/v_ref: [T, D]; o_ref: [BQ, D]; lse_ref: [BQ]
    bq, d = q_ref.shape
    t = k_ref.shape[0]
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale

    def body(kb, carry):
        m, l, acc = carry
        ks = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vs = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, ks, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = jnp.where(_causal_mask(qi, bq, kb, block_k), s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, vs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    num_kb = t // block_k
    if causal:
        # key blocks strictly after this query block never contribute:
        # highest visible key is (qi+1)*bq - 1 -> ceil((qi+1)*bq / block_k)
        num_kb = jnp.minimum(num_kb,
                             ((qi + 1) * bq + block_k - 1) // block_k)
    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l).astype(o_ref.dtype)
    lse_ref[:] = m + jnp.log(l)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               scale, causal, block_k):
    # per-query-block dq: loop over key blocks, rebuilding P = exp(s - lse)
    bq, d = q_ref.shape
    t = k_ref.shape[0]
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:]                                 # [BQ, 1]
    delta = delta_ref[:]                             # [BQ, 1]

    def body(kb, dq):
        ks = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vs = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, ks, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = jnp.where(_causal_mask(qi, bq, kb, block_k), s, _NEG)
        p = jnp.exp(s - lse)                         # [BQ, BK]
        dp = jax.lax.dot_general(do, vs, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(ds, ks, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    num_kb = t // block_k
    if causal:
        num_kb = jnp.minimum(num_kb,
                             ((qi + 1) * bq + block_k - 1) // block_k)
    dq = jax.lax.fori_loop(0, num_kb, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, causal, block_q):
    # per-key-block dk/dv: loop over query blocks
    bk, d = k_ref.shape
    t = q_ref.shape[0]
    ki = pl.program_id(1)
    ks = k_ref[:].astype(jnp.float32)
    vs = v_ref[:].astype(jnp.float32)

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[pl.ds(qb * block_q, block_q), :].astype(jnp.float32) * scale
        do = do_ref[pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(qb * block_q, block_q), :]   # [BQ, 1]
        delta = delta_ref[pl.ds(qb * block_q, block_q), :]
        s = jax.lax.dot_general(q, ks, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = jnp.where(_causal_mask(qb, block_q, ki, bk), s, _NEG)
        p = jnp.exp(s - lse)                          # [BQ, BK]
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vs, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                         # [BQ, BK]
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    num_qb = t // block_q
    start = jnp.int32(0)
    if causal:
        # query blocks strictly before this key block never see it:
        # first visible query is ki*bk -> floor(ki*bk / block_q)
        start = (ki * bk) // block_q
    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, num_qb, body, (dk0, dv0))
    # dk accumulated against q*scale, so the scale is already applied
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    B, H, T, D = q.shape
    bq = min(block_q, T)
    bk = min(block_k, T)
    assert T % bq == 0 and T % bk == 0, \
        f"seq len {T} must be a multiple of block sizes ({bq}, {bk})"
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    kern = functools.partial(_attn_kernel, scale=scale, causal=causal,
                             block_k=bk)
    out, lse = pl.pallas_call(
        kern,
        grid=(B * H, T // bq),
        in_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i: (b, i, 0)),
            # trailing unit dim keeps the block 2-D (TPU tiling rejects
            # rank-1 blocks)
            pl.BlockSpec((None, bq, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, T, D), lse.reshape(B, H, T)


def _flash_backward(q, k, v, out, lse, g, causal, scale, block_q, block_k,
                    interpret):
    B, H, T, D = q.shape
    bq = min(block_q, T)
    bk = min(block_k, T)
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    gf = g.reshape(B * H, T, D)
    lsef = lse.reshape(B * H, T, 1)
    # delta = rowsum(dO * O) — O(T*D) elementwise, fine outside the kernel
    delta = jnp.sum(gf.astype(jnp.float32)
                    * out.reshape(B * H, T, D).astype(jnp.float32),
                    axis=-1, keepdims=True)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, block_k=bk),
        grid=(B * H, T // bq),
        in_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, bq, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, bq, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, gf, lsef, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq),
        grid=(B * H, T // bk),
        in_specs=[
            pl.BlockSpec((None, bk, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, T, D), v.dtype),
        ],
        interpret=interpret,
    )(kf, vf, qf, gf, lsef, delta)

    return (dq.reshape(B, H, T, D), dk.reshape(B, H, T, D),
            dv.reshape(B, H, T, D))



def _resolve_defaults(q, scale, interpret):
    """One place for the default scale / interpreter-mode decision so the
    forward, fwd-rule, and bwd-rule can never drift apart."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return scale, interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, interpret: Optional[bool] = None):
    """Fused attention over [B, H, T, D]. ``T`` must divide by the block
    sizes (pack/pad upstream — static shapes are the framework contract).
    ``interpret`` defaults to True off-TPU so the CPU test harness runs the
    same kernels through the Pallas interpreter."""
    scale, interpret = _resolve_defaults(q, scale, interpret)
    out, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                            interpret)
    return out


def _fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    scale, interpret = _resolve_defaults(q, scale, interpret)
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              interpret)
    return out, (q, k, v, out, lse)


def _bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    scale, interpret = _resolve_defaults(q, scale, interpret)
    return _flash_backward(q, k, v, out, lse, g, causal, scale, block_q,
                           block_k, interpret)


flash_attention.defvjp(_fwd, _bwd)
