"""Fused blockwise (flash-style) attention in Pallas — the long-context hot
op where XLA's generic fusion loses: materialising the [T, T] score matrix in
HBM is O(T^2) bandwidth, while these kernels stream K/V blocks through VMEM
with an online softmax, keeping HBM traffic linear in T.

Reference-lineage note: the 2017 reference has no attention kernel at all
(SURVEY §5 long-context row — this is one of the deliberate "exceeds" items);
its closest machinery is the RNN-era ``ContextProjection``, and its
variable-length contract is ``Argument::sequenceStartPositions``
(``paddle/parameter/Argument.h:84-93``) — never-padded ragged batches. The
TPU-native successor of that contract is packing + segment ids
(``core/sequence.py``), and these kernels consume it natively: pass
``segments`` ([B, T] int32, 1-based, 0 = padding, the ``pack_sequences``
layout) and attention is confined within each packed sub-sequence. Blocks
whose segment-id ranges cannot intersect are skipped with ``pl.when``
(FLOPs and VPU work skipped; the DMA still runs since index maps cannot
depend on data), and intersecting blocks mask per-element. The algorithm is
the public flash-attention recipe; the kernels follow the Pallas TPU
playbook (`/opt/skills/guides/pallas_guide.md`).

Structure: 3-D grids ``(batch*heads, row blocks, streamed blocks)`` with the
online-softmax state carried in VMEM scratch across the innermost grid axis
(sequential on TPU) — so VMEM holds only one q/k/v BLOCK at a time and the
kernels scale to arbitrary T (a full-K/V-resident design caps out around
T=8k on a 16 MB-VMEM chip). Causal upper-triangle blocks are skipped with
``pl.when`` (no FLOPs; the grid step still retires).

Training is fully blockwise: the forward saves only O and the per-row
log-sum-exp L; the backward runs two Pallas kernels (dq over query blocks;
dk/dv over key blocks) that rebuild each probability tile as
``exp(s - L)`` — nothing [T, T]-shaped ever exists in HBM, forward or
backward.

Rows with no visible key (segment id 0 = padding) produce an unspecified
finite output (uniform average of the streamed v blocks) — identical to the
convention of other public TPU flash kernels; mask padding rows downstream.

``interpret=None`` auto-selects the Pallas interpreter off-TPU, so the same
tests run on the CPU harness and the kernels compile on real chips.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import autotune

__all__ = ["flash_attention", "reference_attention",
           "paged_decode_attention", "paged_reference_attention",
           "paged_span_attention", "paged_span_reference_attention"]

_NEG = -1e30


def reference_attention(q, k, v, causal: bool = False,
                        scale: Optional[float] = None, segments=None):
    """Plain softmax attention — the numeric oracle. [B, H, T, D] inputs;
    ``segments`` [B, T] confines attention within equal non-zero ids."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    T = q.shape[2]
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    if segments is not None:
        seg = (segments[:, :, None] == segments[:, None, :]) \
            & (segments[:, :, None] > 0) & (segments[:, None, :] > 0)
        s = jnp.where(seg[:, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    if segments is not None:
        p = jnp.where(jnp.isnan(p), 0.0, p)     # fully-masked padding rows
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _causal_mask(qi, bq, kb, bk):
    q_idx = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_idx = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return k_idx <= q_idx


def _block_needed(qi, bq, ki, bk, causal):
    """Whether key block ki intersects the causal cone of query block qi."""
    if not causal:
        return True
    return ki * bk <= (qi + 1) * bq - 1


def _seg_block_mask(sq, sk):
    """[bq,1], [bk,1] id blocks -> [bq, bk] visibility mask (0 = padding)."""
    return (sq == sk.reshape(1, -1)) & (sq > 0) & (sk.reshape(1, -1) > 0)


def _seg_block_relevant(sq, sk):
    """Sound skip test: packed ids in the two blocks can only match if
    their value ranges intersect (exact for any id layout) and neither
    block is all-padding."""
    return ((jnp.min(sq) <= jnp.max(sk)) & (jnp.max(sq) >= jnp.min(sk))
            & (jnp.max(sq) > 0) & (jnp.max(sk) > 0))


def _attn_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, segs):
    if segs:
        sq_ref, sk_ref, o_ref, lse_ref, m_s, l_s, acc_s = rest
    else:
        o_ref, lse_ref, m_s, l_s, acc_s = rest
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nkb = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        m_s[:] = jnp.full((bq, 1), _NEG, jnp.float32)
        l_s[:] = jnp.zeros((bq, 1), jnp.float32)
        acc_s[:] = jnp.zeros((bq, d), jnp.float32)

    needed = _block_needed(qi, bq, ki, bk, causal)
    if segs:
        needed = needed & _seg_block_relevant(sq_ref[:], sk_ref[:])

    @pl.when(needed)
    def _():
        # Matmuls take the operands in their NATIVE dtype with an f32
        # accumulator: for bf16 inputs the MXU multiplies bf16 pairs into
        # f32 at full rate (upcasting first halves throughput and changes
        # nothing numerically — bf16 values are exact in f32). The scale is
        # applied to the f32 scores instead of the q operand for the same
        # reason. The probability tile is cast back to the value dtype
        # before the PV matmul (the standard flash recipe; softmax stats
        # m/l/LSE stay f32).
        s = jax.lax.dot_general(q_ref[:], k_ref[:], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = jnp.where(_causal_mask(qi, bq, ki, bk), s, _NEG)
        if segs:
            s = jnp.where(_seg_block_mask(sq_ref[:], sk_ref[:]), s, _NEG)
        m = m_s[:]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_s[:] = l_s[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_s[:] = acc_s[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[:] = m_new

    @pl.when(ki == nkb - 1)
    def _():
        l = jnp.maximum(l_s[:], 1e-30)
        o_ref[:] = (acc_s[:] / l).astype(o_ref.dtype)
        lse_ref[:] = m_s[:] + jnp.log(l)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
               scale, causal, segs):
    if segs:
        sq_ref, sk_ref, dq_ref, dq_s = rest
    else:
        dq_ref, dq_s = rest
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nkb = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        dq_s[:] = jnp.zeros((bq, d), jnp.float32)

    needed = _block_needed(qi, bq, ki, bk, causal)
    if segs:
        needed = needed & _seg_block_relevant(sq_ref[:], sk_ref[:])

    @pl.when(needed)
    def _():
        # Native-dtype matmul operands + f32 accumulate (see _attn_kernel);
        # ds is cast to the k dtype before the dq matmul.
        lse = lse_ref[:]
        delta = delta_ref[:]
        s = jax.lax.dot_general(q_ref[:], k_ref[:], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = jnp.where(_causal_mask(qi, bq, ki, bk), s, _NEG)
        if segs:
            s = jnp.where(_seg_block_mask(sq_ref[:], sk_ref[:]), s, _NEG)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do_ref[:], v_ref[:], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_s[:] = dq_s[:] + jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nkb - 1)
    def _():
        dq_ref[:] = (dq_s[:] * scale).astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref, *rest,
                scale, causal, segs):
    if segs:
        sk_ref, sq_ref, dk_ref, dv_ref, dk_s, dv_s = rest
    else:
        dk_ref, dv_ref, dk_s, dv_s = rest
    bk, d = k_ref.shape
    bq = q_ref.shape[0]
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nqb = pl.num_programs(2)

    @pl.when(qi == 0)
    def _():
        dk_s[:] = jnp.zeros((bk, d), jnp.float32)
        dv_s[:] = jnp.zeros((bk, d), jnp.float32)

    needed = _block_needed(qi, bq, ki, bk, causal)
    if segs:
        needed = needed & _seg_block_relevant(sq_ref[:], sk_ref[:])

    @pl.when(needed)
    def _():
        # Native-dtype matmul operands + f32 accumulate (see _attn_kernel).
        # dk accumulates against the UNSCALED q; the scale lands once at
        # the final write.
        lse = lse_ref[:]
        delta = delta_ref[:]
        s = jax.lax.dot_general(q_ref[:], k_ref[:], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = jnp.where(_causal_mask(qi, bq, ki, bk), s, _NEG)
        if segs:
            s = jnp.where(_seg_block_mask(sq_ref[:], sk_ref[:]), s, _NEG)
        p = jnp.exp(s - lse)
        dv_s[:] = dv_s[:] + jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[:], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do_ref[:], v_ref[:], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_s[:] = dk_s[:] + jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[:], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nqb - 1)
    def _():
        dk_ref[:] = (dk_s[:] * scale).astype(dk_ref.dtype)
        dv_ref[:] = dv_s[:].astype(dv_ref.dtype)


def _auto_block(T, cap):
    """Largest block <= cap dividing T, preferring lane-friendly multiples
    of 128. Measured on v5e (experiments/profile_transformer.py, T=2048
    d64): per-layer fwd+bwd cost falls 76.6 ms -> 14.9 ms going from
    128x128 to 512x1024 blocks — the per-grid-step overhead dominates at
    small blocks, so default as large as VMEM comfortably allows."""
    for b in range(min(cap, T) // 128 * 128, 127, -128):
        if T % b == 0:
            return b
    # no 128-multiple divides T: fall back to the largest sublane-aligned
    # (multiple-of-8) divisor — odd blocks mis-tile on the TPU
    for b in range(min(cap, T) // 8 * 8, 7, -8):
        if T % b == 0:
            return b
    # T < 8 or not 8-divisible (interpreter-scale shapes): any divisor
    for b in range(min(cap, T), 0, -1):
        if T % b == 0:
            return b
    return min(cap, T)


def _blocks(block_q, block_k, T):
    bq = _auto_block(T, 512) if block_q is None else min(block_q, T)
    bk = _auto_block(T, 1024) if block_k is None else min(block_k, T)
    assert T % bq == 0 and T % bk == 0, \
        f"seq len {T} must be a multiple of block sizes ({bq}, {bk})"
    return bq, bk


def _flash_candidates(T):
    """Candidate ``(block_q, block_k)`` grid for the flash kernels: the
    lane-friendly 128-multiples dividing T, capped at 6 configurations —
    the trial budget is priced against replica spawn latency
    (DESIGN_DECISIONS), and past 6 the remaining combinations are the
    small-block corner ``_auto_block`` already measured as dominated.
    When T has no 128-multiple divisor (interpreter-scale shapes) the
    heuristic block is the single candidate: one trial, and the timing
    still lands in the cache so the next process pays zero."""
    qs = [b for b in (512, 256, 128) if T % b == 0]
    ks = [b for b in (1024, 512, 256, 128) if T % b == 0]
    if not qs:
        qs = [_auto_block(T, 512)]
    if not ks:
        ks = [_auto_block(T, 1024)]
    return [{"block_q": a, "block_k": b} for a in qs for b in ks][:6]


def _tuned_blocks(kernel, q, segments, causal, block_q, block_k,
                  interpret):
    """Block selection with the autotuner as the default path
    (ISSUE 16). Explicit ``block_q``/``block_k`` bypass the tuner
    entirely (bit-identical to the pre-tuner resolution); with the tuner
    disabled the ``_auto_block`` heuristic answers untimed, with zero
    trials and zero disk I/O. Enabled, each candidate runs the REAL
    kernel once on zero operands with its blocks passed explicitly —
    which is what terminates the recursion — as plain concrete
    execution, legal even while this call sits inside an outer trace
    (a concrete eager call during tracing is ordinary Python)."""
    T = q.shape[2]
    if block_q is not None or block_k is not None:
        return _blocks(block_q, block_k, T)
    default_bq, default_bk = _blocks(None, None, T)
    if not autotune.is_enabled():
        return default_bq, default_bk
    B, H, _, D = q.shape
    segmented = segments is not None
    key = autotune.make_key(kernel, shape=(B, H, T, D), dtype=q.dtype,
                            extra=(int(bool(causal)), int(segmented)))

    def runner(block_q, block_k):
        z = jnp.zeros((B, H, T, D), q.dtype)
        seg = jnp.ones((B, T), jnp.int32) if segmented else None
        if kernel == "flash_bwd":
            return jax.grad(lambda a: flash_attention(
                a, z, z, seg, causal, None, block_q, block_k,
                interpret).astype(jnp.float32).sum())(z)
        return flash_attention(z, z, z, seg, causal, None, block_q,
                               block_k, interpret)

    cfg = autotune.choose(kernel, key=key,
                          candidates=_flash_candidates(T),
                          runner=runner,
                          default={"block_q": default_bq,
                                   "block_k": default_bk})
    try:
        return _blocks(cfg.get("block_q"), cfg.get("block_k"), T)
    except AssertionError:
        # a cache entry with non-dividing blocks (hand-edited or from
        # another build) must not crash the model — heuristic fallback
        return default_bq, default_bk


def _kv_index_map(causal, bq, bk, H=1):
    """K/V block index map for q-major kernels. Under causal masking the
    skipped upper-triangle steps clamp to the row's last needed key block,
    so the pipeline re-references the resident block instead of fetching
    one that pl.when will discard (skipping FLOPs alone still paid the
    DMA). ``H``: grid axis 0 is batch*heads; head-invariant operands
    (segment ids) use ``H > 1`` to index by batch row."""
    if not causal:
        return lambda b, i, j: (b // H, j, 0)
    return lambda b, i, j: (b // H, jnp.minimum(j, ((i + 1) * bq - 1) // bk), 0)


def _q_index_map(causal, bq, bk, H=1):
    """Q-side map for the key-major dk/dv kernel: clamp the skipped
    before-the-diagonal steps up to the first query block that sees this
    key block."""
    if not causal:
        return lambda b, i, j: (b // H, j, 0)
    return lambda b, i, j: (b // H, jnp.maximum(j, (i * bk) // bq), 0)


def _row_map(H=1):
    return lambda b, i, j: (b // H, i, 0)


def _key_row_map(H=1):
    return lambda b, i, j: (b // H, i, 0)


def _flash_forward(q, k, v, segments, causal, scale, block_q, block_k,
                   interpret):
    B, H, T, D = q.shape
    bq, bk = _tuned_blocks("flash_fwd", q, segments, causal, block_q,
                           block_k, interpret)
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    kvmap = _kv_index_map(causal, bq, bk)
    segs = segments is not None
    in_specs = [
        pl.BlockSpec((None, bq, D), _row_map()),
        pl.BlockSpec((None, bk, D), kvmap),
        pl.BlockSpec((None, bk, D), kvmap),
    ]
    operands = [qf, kf, vf]
    if segs:
        segf = segments.reshape(B, T, 1).astype(jnp.int32)
        in_specs += [
            pl.BlockSpec((None, bq, 1), _row_map(H)),
            pl.BlockSpec((None, bk, 1), _kv_index_map(causal, bq, bk, H)),
        ]
        operands += [segf, segf]
    out, lse = pl.pallas_call(
        functools.partial(_attn_kernel, scale=scale, causal=causal,
                          segs=segs),
        grid=(B * H, T // bq, T // bk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, bq, D), _row_map()),
            # trailing unit dim keeps the block 2-D (TPU tiling rejects
            # rank-1 blocks)
            pl.BlockSpec((None, bq, 1), _row_map()),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    return out.reshape(B, H, T, D), lse.reshape(B, H, T)


def _flash_backward(q, k, v, segments, out, lse, g, causal, scale, block_q,
                    block_k, interpret):
    B, H, T, D = q.shape
    bq, bk = _tuned_blocks("flash_bwd", q, segments, causal, block_q,
                           block_k, interpret)
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    gf = g.reshape(B * H, T, D)
    lsef = lse.reshape(B * H, T, 1)
    segs = segments is not None
    segf = (segments.reshape(B, T, 1).astype(jnp.int32) if segs else None)
    # delta = rowsum(dO * O) — O(T*D) elementwise, fine outside the kernel
    delta = jnp.sum(gf.astype(jnp.float32)
                    * out.reshape(B * H, T, D).astype(jnp.float32),
                    axis=-1, keepdims=True)

    kvmap = _kv_index_map(causal, bq, bk)
    in_specs = [
        pl.BlockSpec((None, bq, D), _row_map()),
        pl.BlockSpec((None, bk, D), kvmap),
        pl.BlockSpec((None, bk, D), kvmap),
        pl.BlockSpec((None, bq, D), _row_map()),
        pl.BlockSpec((None, bq, 1), _row_map()),
        pl.BlockSpec((None, bq, 1), _row_map()),
    ]
    operands = [qf, kf, vf, gf, lsef, delta]
    if segs:
        in_specs += [
            pl.BlockSpec((None, bq, 1), _row_map(H)),
            pl.BlockSpec((None, bk, 1), _kv_index_map(causal, bq, bk, H)),
        ]
        operands += [segf, segf]
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, segs=segs),
        grid=(B * H, T // bq, T // bk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, bq, D), _row_map()),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(*operands)

    qmap = _q_index_map(causal, bq, bk)
    in_specs = [
        pl.BlockSpec((None, bk, D), _key_row_map()),
        pl.BlockSpec((None, bk, D), _key_row_map()),
        pl.BlockSpec((None, bq, D), qmap),
        pl.BlockSpec((None, bq, D), qmap),
        pl.BlockSpec((None, bq, 1), qmap),
        pl.BlockSpec((None, bq, 1), qmap),
    ]
    operands = [kf, vf, qf, gf, lsef, delta]
    if segs:
        in_specs += [
            pl.BlockSpec((None, bk, 1), _key_row_map(H)),
            pl.BlockSpec((None, bq, 1), _q_index_map(causal, bq, bk, H)),
        ]
        operands += [segf, segf]
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          segs=segs),
        grid=(B * H, T // bk, T // bq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, bk, D), _key_row_map()),
            pl.BlockSpec((None, bk, D), _key_row_map()),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, T, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
    )(*operands)

    return (dq.reshape(B, H, T, D), dk.reshape(B, H, T, D),
            dv.reshape(B, H, T, D))


def _resolve_defaults(q, scale, interpret):
    """One place for the default scale / interpreter-mode decision so the
    forward, fwd-rule, and bwd-rule can never drift apart."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return scale, interpret


# ---------------------------------------------------------------------------
# decode-shaped attention: q_len = 1 over a paged KV cache (serving path)
# ---------------------------------------------------------------------------

def _unpack_pages(pages):
    """``(values, scales)`` for a quantized pool (``serve.kv_cache``'s
    int8 tuple convention), ``(values, None)`` for a plain one."""
    if isinstance(pages, tuple):
        return pages[0], pages[1]
    return pages, None


def _gathered(pages, tables):
    """Dequantized position-order gather for the reference oracles —
    the serving pool's own gather, so the oracles can never drift from
    the XLA serving path's dequant convention."""
    from ..serve.kv_cache import gather_pages
    return gather_pages(pages, tables)


def paged_reference_attention(q, pages_k, pages_v, tables, lengths,
                              scale: Optional[float] = None):
    """Numeric oracle for :func:`paged_decode_attention` — gather the
    block-table pages into position order (dequantized for int8 pools)
    and run masked softmax attention for the single query token. ``q``
    ``[S, H, D]``; pages ``[N, bs, H, D]`` or the quantized
    ``(int8, scales)`` tuple; ``tables`` ``[S, MB]``; ``lengths``
    ``[S]`` (0 = inactive slot -> zero output)."""
    S, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    k = _gathered(pages_k, tables)
    v = _gathered(pages_v, tables)
    W = k.shape[1]
    s = jnp.einsum("shd,skhd->shk", q, k) * scale
    mask = jnp.arange(W)[None] < lengths[:, None]
    s = jnp.where(mask[:, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.isnan(p), 0.0, p)       # length-0 (inactive) rows
    return jnp.einsum("shk,skhd->shd", p, v)


def paged_span_reference_attention(q, pages_k, pages_v, tables, start, n,
                                   scale: Optional[float] = None):
    """Numeric oracle for :func:`paged_span_attention` — per-row masked
    softmax over the gathered (dequantized) context. ``q``
    ``[S, Q, H, D]`` (row ``j`` of slot ``s`` sits at position
    ``start[s] + j``); rows ``>= n[s]`` are padding whose output is
    unspecified (compare live rows only); ``n == 0`` marks an inactive
    slot (zero output on every row)."""
    S, Q, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    k = _gathered(pages_k, tables)            # [S, W, H, D]
    v = _gathered(pages_v, tables)
    W = k.shape[1]
    s = jnp.einsum("sqhd,skhd->sqhk", q, k) * scale
    k_idx = jnp.arange(W)[None, None, :]
    # causal within the span: row j sees positions <= start + j
    vis = (k_idx <= (start[:, None] + jnp.arange(Q)[None, :])[..., None]) \
        & (n[:, None, None] > 0)
    s = jnp.where(vis[:, :, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.isnan(p), 0.0, p)       # inactive slots
    return jnp.einsum("sqhk,skhd->sqhd", p, v)


def _paged_decode_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                         scale, bs, quant):
    """One (slot, head) row's online softmax over its block table. Grid
    ``(S, H, MB)``: the innermost axis streams the slot's KV blocks
    (sequential on TPU — the m/l/acc scratch carries across it), with the
    pool block resolved by the PREFETCHED block table in the index map,
    so the DMA fetches exactly the pages the sequence owns. With
    ``quant`` the K/V blocks arrive int8 with per-row scale pages and
    are dequantized IN VMEM (never in HBM — the whole point of the int8
    pool is HBM bytes)."""
    if quant:
        sk_ref, sv_ref, o_ref, m_s, l_s, acc_s = rest
    else:
        o_ref, m_s, l_s, acc_s = rest
    s_idx = pl.program_id(0)
    j = pl.program_id(2)
    nkb = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        m_s[:] = jnp.full(m_s.shape, _NEG, jnp.float32)
        l_s[:] = jnp.zeros(l_s.shape, jnp.float32)
        acc_s[:] = jnp.zeros(acc_s.shape, jnp.float32)

    length = len_ref[s_idx]

    # blocks past the sequence length are skipped entirely (an inactive
    # slot — length 0 — skips every block and writes zeros)
    @pl.when(j * bs < length)
    def _():
        # native-dtype matmul operands + f32 accumulate (see _attn_kernel)
        if quant:
            kb = k_ref[:].astype(jnp.float32) * sk_ref[:]
            vb = v_ref[:].astype(jnp.float32) * sv_ref[:]
        else:
            kb, vb = k_ref[:], v_ref[:]
        s = jax.lax.dot_general(q_ref[:], kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        k_idx = j * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        s = jnp.where(k_idx < length, s, _NEG)
        m = m_s[:]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_s[:] = l_s[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_s[:] = acc_s[:] * corr + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[:] = m_new

    @pl.when(j == nkb - 1)
    def _():
        l = jnp.maximum(l_s[:], 1e-30)
        o_ref[:] = (acc_s[:] / l).astype(o_ref.dtype)


def paged_decode_attention(q, pages_k, pages_v, tables, lengths,
                           scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    """Decode-shaped (q_len = 1) flash attention over a paged KV cache.

    The serving hot op: each active slot attends its single new-token
    query against the KV blocks its block table names, streaming block by
    block with the online softmax (lse-correct across the slot's ragged
    length; within-block tail positions masked). The block table and
    lengths are SCALAR-PREFETCHED (``pltpu.PrefetchScalarGridSpec``) so
    the K/V index maps resolve pool pages before each grid step's DMA —
    the kernel never touches blocks the sequence does not own, which is
    what makes the pool's ragged sharing free.

    Args: ``q`` ``[S, H, D]`` (slot-major, one token per slot);
    ``pages_k``/``pages_v`` ``[N, bs, H, D]`` (one layer's pool), or the
    quantized ``(int8 values, scales [N, bs, H])`` tuple — scale pages
    stream beside the value blocks and dequantization happens in VMEM;
    ``tables`` ``[S, MB]`` int32; ``lengths`` ``[S]`` int32 — the number
    of valid tokens INCLUDING the one just scattered; 0 marks an
    inactive slot (zero output). ``interpret`` defaults to True off-TPU
    (same contract as :func:`flash_attention`)."""
    S, H, D = q.shape
    pages_k, scale_k = _unpack_pages(pages_k)
    pages_v, scale_v = _unpack_pages(pages_v)
    quant = scale_k is not None
    N, bs, Hk, Dk = pages_k.shape
    assert (H, D) == (Hk, Dk), f"q heads {(H, D)} != pages {(Hk, Dk)}"
    MB = tables.shape[1]
    scale, interpret = _resolve_defaults(q, scale, interpret)
    q4 = q.reshape(S, H, 1, D)

    def q_map(s, h, j, tbl, lens):
        return (s, h, 0, 0)

    def kv_map(s, h, j, tbl, lens):
        return (tbl[s, j], 0, h, 0)

    in_specs = [
        pl.BlockSpec((None, None, 1, D), q_map),
        pl.BlockSpec((None, bs, None, D), kv_map),
        pl.BlockSpec((None, bs, None, D), kv_map),
    ]
    operands = [q4, pages_k, pages_v]
    if quant:
        # trailing unit dim keeps the scale block 2-D ([bs, 1])
        in_specs += [pl.BlockSpec((None, bs, None, 1), kv_map),
                     pl.BlockSpec((None, bs, None, 1), kv_map)]
        operands += [scale_k[..., None], scale_v[..., None]]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, H, MB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, None, 1, D), q_map),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, scale=scale, bs=bs,
                          quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, 1, D), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32), *operands)
    return out.reshape(S, H, D)


def _paged_span_kernel(tbl_ref, start_ref, n_ref, q_ref, k_ref, v_ref,
                       *rest, scale, bs, quant):
    """One (slot, head) SPAN's online softmax over its block table — the
    q_len = 1+k generalization of :func:`_paged_decode_kernel` (ISSUE
    14). Grid ``(S, H, MB)`` with the span's ``Q`` rows resident in one
    VMEM block and per-row online-softmax state ``[Q, 1]``/``[Q, D]``;
    causality WITHIN the span is a per-element mask (row ``j`` sees
    positions ``<= start + j``), so the speculative verify tick and
    chunked prefill stream exactly the pages the slot owns instead of
    materializing an O(W)-per-row XLA gather."""
    if quant:
        sk_ref, sv_ref, o_ref, m_s, l_s, acc_s = rest
    else:
        o_ref, m_s, l_s, acc_s = rest
    Q, d = q_ref.shape
    s_idx = pl.program_id(0)
    j = pl.program_id(2)
    nkb = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        m_s[:] = jnp.full(m_s.shape, _NEG, jnp.float32)
        l_s[:] = jnp.zeros(l_s.shape, jnp.float32)
        acc_s[:] = jnp.zeros(acc_s.shape, jnp.float32)

    start = start_ref[s_idx]
    n = n_ref[s_idx]

    # blocks past the span's last live position are skipped entirely,
    # and an inactive slot — n == 0 — skips every block regardless of
    # a stale start and writes zeros (the oracle's convention); block 0
    # always runs for a live slot, so every live row's softmax state
    # lifts off the _NEG floor there (row j's own position
    # start+j >= 0 is always visible)
    @pl.when((n > 0) & (j * bs < start + n))
    def _():
        if quant:
            kb = k_ref[:].astype(jnp.float32) * sk_ref[:]
            vb = v_ref[:].astype(jnp.float32) * sv_ref[:]
        else:
            kb, vb = k_ref[:], v_ref[:]
        s = jax.lax.dot_general(q_ref[:], kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        k_idx = j * bs + jax.lax.broadcasted_iota(jnp.int32, (Q, bs), 1)
        q_idx = jax.lax.broadcasted_iota(jnp.int32, (Q, bs), 0)
        s = jnp.where(k_idx <= start + q_idx, s, _NEG)
        m = m_s[:]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_s[:] = l_s[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_s[:] = acc_s[:] * corr + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[:] = m_new

    @pl.when(j == nkb - 1)
    def _():
        l = jnp.maximum(l_s[:], 1e-30)
        o_ref[:] = (acc_s[:] / l).astype(o_ref.dtype)


def paged_span_attention(q, pages_k, pages_v, tables, start, n,
                         scale: Optional[float] = None,
                         interpret: Optional[bool] = None):
    """Multi-query (q_len = 1+k) flash attention over a paged KV cache —
    the span-tick hot op (ISSUE 14). Each slot's span of ``Q``
    consecutive new-token queries attends its block-table pages with one
    streamed online softmax per row, causal within the span; the
    speculative verify tick and chunked prefill ride this instead of the
    gather-everything XLA path on TPU.

    Args: ``q`` ``[S, Q, H, D]`` (row ``j`` of slot ``s`` sits at
    position ``start[s] + j``); ``pages_k``/``pages_v`` one layer's pool
    (plain or the quantized ``(int8, scales)`` tuple — dequantized in
    VMEM); ``tables`` ``[S, MB]``; ``start``/``n`` ``[S]`` int32 — rows
    ``>= n[s]`` are padding (finite garbage output the host ignores),
    ``n == 0`` marks an inactive slot (zero output). At ``Q = 1`` the
    kernel runs the exact op sequence of
    :func:`paged_decode_attention` (bit-equal — the greedy-path
    contract). ``interpret`` defaults to True off-TPU."""
    S, Q, H, D = q.shape
    pages_k, scale_k = _unpack_pages(pages_k)
    pages_v, scale_v = _unpack_pages(pages_v)
    quant = scale_k is not None
    N, bs, Hk, Dk = pages_k.shape
    assert (H, D) == (Hk, Dk), f"q heads {(H, D)} != pages {(Hk, Dk)}"
    MB = tables.shape[1]
    scale, interpret = _resolve_defaults(q, scale, interpret)
    qt = jnp.swapaxes(q, 1, 2)               # [S, H, Q, D]

    def q_map(s, h, j, tbl, st, nn):
        return (s, h, 0, 0)

    def kv_map(s, h, j, tbl, st, nn):
        return (tbl[s, j], 0, h, 0)

    in_specs = [
        pl.BlockSpec((None, None, Q, D), q_map),
        pl.BlockSpec((None, bs, None, D), kv_map),
        pl.BlockSpec((None, bs, None, D), kv_map),
    ]
    operands = [qt, pages_k, pages_v]
    if quant:
        in_specs += [pl.BlockSpec((None, bs, None, 1), kv_map),
                     pl.BlockSpec((None, bs, None, 1), kv_map)]
        operands += [scale_k[..., None], scale_v[..., None]]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(S, H, MB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, None, Q, D), q_map),
        scratch_shapes=[
            pltpu.VMEM((Q, 1), jnp.float32),
            pltpu.VMEM((Q, 1), jnp.float32),
            pltpu.VMEM((Q, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_span_kernel, scale=scale, bs=bs,
                          quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, Q, D), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), start.astype(jnp.int32),
      n.astype(jnp.int32), *operands)
    return jnp.swapaxes(out, 1, 2)           # [S, Q, H, D]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention(q, k, v, segments=None, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Fused attention over [B, H, T, D]. ``T`` must divide by the block
    sizes (pack/pad upstream — static shapes are the framework contract).
    ``block_q``/``block_k`` default to the largest T-dividing blocks up to
    512/1024 — measured ~5x faster than 128x128 on v5e at T=2048
    (``_auto_block``); pass explicit sizes to override (e.g. tighter VMEM).
    ``segments``: optional [B, T] packed-sequence ids (``core.sequence``
    convention: 1-based, 0 = padding) confining attention within each
    sub-sequence — shared across heads. ``interpret`` defaults to True
    off-TPU so the CPU test harness runs the same kernels through the
    Pallas interpreter."""
    scale, interpret = _resolve_defaults(q, scale, interpret)
    out, _ = _flash_forward(q, k, v, segments, causal, scale, block_q,
                            block_k, interpret)
    return out


def _fwd(q, k, v, segments, causal, scale, block_q, block_k, interpret):
    scale, interpret = _resolve_defaults(q, scale, interpret)
    out, lse = _flash_forward(q, k, v, segments, causal, scale, block_q,
                              block_k, interpret)
    return out, (q, k, v, segments, out, lse)


def _bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, segments, out, lse = res
    scale, interpret = _resolve_defaults(q, scale, interpret)
    dq, dk, dv = _flash_backward(q, k, v, segments, out, lse, g, causal,
                                 scale, block_q, block_k, interpret)
    return dq, dk, dv, None


flash_attention.defvjp(_fwd, _bwd)
