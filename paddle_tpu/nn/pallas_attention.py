"""Fused blockwise (flash-style) attention in Pallas — the long-context hot
op where XLA's generic fusion loses: materialising the [T, T] score matrix in
HBM is O(T^2) bandwidth, while this kernel streams K/V blocks through VMEM
with an online softmax, keeping HBM traffic linear in T.

Reference-lineage note: the 2017 reference has no attention kernel at all
(SURVEY §5 long-context row — this is one of the deliberate "exceeds" items);
its closest machinery is the RNN-era ``ContextProjection``. The algorithm is
the public flash-attention online-softmax recurrence; the kernel follows the
Pallas TPU playbook (`/opt/skills/guides/pallas_guide.md`): 2-D grid over
(batch*heads, query blocks), K/V resident in VMEM, ``fori_loop`` over key
blocks carrying (running max, denominator, accumulator).

Autodiff: the kernel is forward-only; a ``jax.custom_vjp`` recomputes
attention for the backward pass. Nothing [T, T]-shaped is SAVED between
forward and backward, but the recomputation itself is the plain XLA
attention, so the backward pass still materialises [T, T] scores
transiently — training memory/bandwidth is O(T^2) in the backward. The
linear-HBM win currently applies to inference and to forward-dominated
uses; a blockwise Pallas backward is the known follow-up.

``interpret=None`` auto-selects the Pallas interpreter off-TPU, so the same
tests run on the CPU harness and the kernel compiles on real chips.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["flash_attention", "reference_attention"]


def reference_attention(q, k, v, causal: bool = False,
                        scale: Optional[float] = None):
    """Plain softmax attention — the numeric oracle and the backward-pass
    recomputation target. [B, H, T, D] inputs."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_k):
    # q_ref: [BQ, D]; k_ref/v_ref: [T, D]; o_ref: [BQ, D]
    bq, d = q_ref.shape
    t = k_ref.shape[0]
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale

    def body(kb, carry):
        m, l, acc = carry
        ks = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vs = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, ks, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_idx = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_idx = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_idx <= q_idx, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # exp(-inf - -inf) guards: rows with no visible keys keep m = -inf
        p = jnp.exp(s - m_new)
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.exp(m - m_new)
        corr = jnp.where(jnp.isfinite(m), corr, 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, vs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    num_kb = t // block_k
    if causal:
        # key blocks strictly after this query block never contribute:
        # highest visible key is (qi+1)*bq - 1 -> ceil((qi+1)*bq / block_k)
        num_kb = jnp.minimum(num_kb,
                             ((qi + 1) * bq + block_k - 1) // block_k)
    _, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    B, H, T, D = q.shape
    bq = min(block_q, T)
    bk = min(block_k, T)
    assert T % bq == 0 and T % bk == 0, \
        f"seq len {T} must be a multiple of block sizes ({bq}, {bk})"
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    kern = functools.partial(_attn_kernel, scale=scale, causal=causal,
                             block_k=bk)
    out = pl.pallas_call(
        kern,
        grid=(B * H, T // bq),
        in_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, T, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, interpret: Optional[bool] = None):
    """Fused attention over [B, H, T, D]. ``T`` must divide by the block
    sizes (pack/pad upstream — static shapes are the framework contract).
    ``interpret`` defaults to True off-TPU so the CPU test harness runs the
    same kernel through the Pallas interpreter."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)


def _fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    # flash-style rematerialisation: recompute attention under vjp instead of
    # saving the [T, T] probabilities
    _, vjp = jax.vjp(lambda q, k, v: reference_attention(q, k, v, causal,
                                                         scale), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
