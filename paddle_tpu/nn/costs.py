"""Cost (loss) functions — the reference's cost-layer family.

Reference: ``/root/reference/paddle/gserver/layers/CostLayer.cpp`` (multi-class
cross-entropy, soft CE, SVM, Huber, rank cost, lambda rank, smooth-L1, MSE,
multi-binary-label CE) plus ``NCELayer.cpp`` and ``HierarchicalSigmoidLayer.cpp``.
All are pure functions ``(logits/outputs, labels, ...) -> per-example loss`` with
an optional ``weight``; reductions happen in the trainer so data-parallel psum
averages correctly. Losses compute in float32 regardless of activation dtype.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = [
    "softmax_cross_entropy", "cross_entropy_with_probs", "soft_binary_ce",
    "binary_logistic", "multi_binary_ce", "mse", "smooth_l1",
    "huber_regression",
    "huber_classification", "hinge", "rank_cost", "lambda_rank_ndcg",
    "sum_cost", "nce_loss", "hsigmoid_loss", "reduce",
]


def _weight(loss, weight):
    return loss if weight is None else loss * weight


def _softplus(x):
    """Stable log(1+exp(x)) (jax.nn.softplus) in float32."""
    return jax.nn.softplus(x.astype(jnp.float32))


def reduce(per_example, mask=None, how: str = "mean"):
    """Masked reduction to a scalar; use inside train steps."""
    x = per_example.astype(jnp.float32)
    if mask is not None:
        m = mask.astype(jnp.float32)
        x = x * m
        if how == "mean":
            return x.sum() / jnp.maximum(m.sum(), 1.0)
    if how == "mean":
        return x.mean()
    if how == "sum":
        return x.sum()
    raise ValueError(how)


def softmax_cross_entropy(logits, labels, weight=None):
    """Multi-class CE from logits, int labels (reference:
    ``MultiClassCrossEntropy``, CostLayer.cpp; ``oneHotCrossEntropy`` in
    paddle/math/Matrix.cpp). Stable log-softmax; label -1 masks the example."""
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    valid = labels >= 0
    safe = jnp.clip(labels, 0, logits.shape[-1] - 1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return _weight(nll * valid.astype(nll.dtype), weight)


def cross_entropy_with_probs(logits, target_probs, weight=None):
    """Soft-label CE (reference: ``SoftBinaryClassCrossEntropy`` /
    soft_cross_entropy)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return _weight(-(target_probs * logp).sum(-1), weight)


def soft_binary_ce(probs, targets, weight=None, eps=1e-7):
    """Binary CE on probabilities (post-sigmoid)."""
    p = jnp.clip(probs.astype(jnp.float32), eps, 1 - eps)
    l = -(targets * jnp.log(p) + (1 - targets) * jnp.log1p(-p))
    return _weight(l.sum(-1) if l.ndim > 1 else l, weight)


def binary_logistic(logits, labels, weight=None):
    """Per-example binary cross-entropy on logits [B] with 0/1 labels [B]
    (reference: the quick_start LR demo's classification cost — sigmoid +
    binary CE)."""
    z = logits.astype(jnp.float32)
    y = labels.astype(jnp.float32)
    return _weight(_softplus(z) - y * z, weight)


def multi_binary_ce(logits, targets, weight=None):
    """Multi-label binary CE from logits (reference:
    ``MultiBinaryLabelCrossEntropy``, CostLayer.cpp)."""
    x = logits.astype(jnp.float32)
    l = _softplus(x) - x * targets
    return _weight(l.sum(-1), weight)


def mse(output, target, weight=None):
    """Sum-of-squares cost (reference: ``SumOfSquaresCostLayer``)."""
    d = (output - target).astype(jnp.float32)
    return _weight(0.5 * (d * d).sum(-1), weight)


def smooth_l1_elementwise(output, target, delta: float = 1.0):
    """Per-element smooth-L1 (shared by :func:`smooth_l1` and the SSD
    multibox loss)."""
    d = jnp.abs((output - target).astype(jnp.float32))
    return jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)


def smooth_l1(output, target, weight=None, delta: float = 1.0):
    """Smooth-L1 (reference: ``SmoothL1CostLayer``; fluid smooth_l1_op)."""
    return _weight(smooth_l1_elementwise(output, target, delta).sum(-1),
                   weight)


def huber_regression(output, target, weight=None, delta: float = 1.0):
    """Huber regression cost (reference: ``HuberRegressionLoss``)."""
    d = jnp.abs((output - target).astype(jnp.float32))
    l = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
    return _weight(l.sum(-1), weight)


def huber_classification(score, label01, weight=None):
    """Huberized hinge for binary classification, y in {0,1}
    (reference: ``HuberTwoClassification``, CostLayer.cpp)."""
    y = (2.0 * label01 - 1.0).astype(jnp.float32)
    z = y * score[..., 0].astype(jnp.float32)
    l = jnp.where(z < -1, -4.0 * z, jnp.where(z < 1, (1 - z) ** 2, 0.0))
    return _weight(l, weight)


def hinge(score, label01, weight=None):
    """Two-class SVM hinge (reference: ``MultiClassHingeLoss`` binary case)."""
    y = 2.0 * label01 - 1.0
    return _weight(jnp.maximum(0.0, 1.0 - y * score[..., 0]), weight)


def rank_cost(left, right, label, weight=None):
    """Pairwise rank cost (RankNet; reference: ``RankingCost``,
    CostLayer.cpp): -o*t + log(1+exp(o)), o = left-right, t in [0,1]."""
    o = (left - right).astype(jnp.float32)[..., 0]
    l = _softplus(o) - o * label
    return _weight(l, weight)


def lambda_rank_ndcg(scores, relevance, lengths=None, sigma: float = 1.0,
                     ndcg_k: int = 5):
    """ListWise LambdaRank gradient-compatible cost (reference:
    ``LambdaCost``, CostLayer.cpp — NDCG-weighted pairwise logistic).
    ``scores``/``relevance``: [B, T]; returns per-list loss [B]."""
    s = scores.astype(jnp.float32)
    r = relevance.astype(jnp.float32)
    t = s.shape[1]
    if lengths is not None:
        valid = (jnp.arange(t)[None, :] < lengths[:, None])
    else:
        valid = jnp.ones_like(s, bool)
    diff_s = s[:, :, None] - s[:, None, :]
    gain = (2.0 ** r - 1.0)
    # ideal DCG for normalization
    sorted_r = jnp.sort(jnp.where(valid, r, -jnp.inf), axis=1)[:, ::-1]
    disc = 1.0 / jnp.log2(jnp.arange(t) + 2.0)
    topk = (jnp.arange(t) < ndcg_k)
    idcg = ((2.0 ** jnp.where(jnp.isfinite(sorted_r), sorted_r, 0.0) - 1.0)
            * disc * topk).sum(1)
    # rank by current scores for discounts
    order = jnp.argsort(-jnp.where(valid, s, -jnp.inf), axis=1)
    ranks = jnp.argsort(order, axis=1)
    d = jnp.take(disc, jnp.clip(ranks, 0, t - 1))
    delta = jnp.abs(gain[:, :, None] - gain[:, None, :]) * \
        jnp.abs(d[:, :, None] - d[:, None, :]) / \
        jnp.maximum(idcg, 1e-9)[:, None, None]
    pair_valid = valid[:, :, None] & valid[:, None, :] & \
        (r[:, :, None] > r[:, None, :])
    logistic = _softplus(-sigma * diff_s)
    return (delta * logistic * pair_valid).sum((1, 2))


def sum_cost(output, weight=None):
    """Sum of outputs as a cost (reference: ``SumCostLayer`` — used to expose
    arbitrary expressions as objectives)."""
    return _weight(output.astype(jnp.float32).sum(-1), weight)


def nce_loss(hidden, labels, table_w, table_b, noise_ids, noise_logprob=None):
    """Noise-contrastive estimation (reference: ``NCELayer.cpp``) — binary
    logistic on the true class vs K sampled noise classes.

    hidden: [B, D]; labels: [B]; table_w: [V, D]; table_b: [V];
    noise_ids: [B, K] pre-sampled noise class ids. ``noise_logprob`` is
    log(k·q(class)) per vocabulary entry, [V]; when given, logits are corrected
    by subtracting it (the consistency correction matching the reference's
    sampling-weighted multinomial in NCELayer)."""
    h = hidden.astype(jnp.float32)
    pos_w = jnp.take(table_w, labels, axis=0)          # [B, D]
    pos_b = jnp.take(table_b, labels)
    pos_logit = jnp.einsum("bd,bd->b", h, pos_w) + pos_b
    neg_w = jnp.take(table_w, noise_ids, axis=0)       # [B, K, D]
    neg_b = jnp.take(table_b, noise_ids)
    neg_logit = jnp.einsum("bd,bkd->bk", h, neg_w) + neg_b
    if noise_logprob is not None:
        pos_logit = pos_logit - jnp.take(noise_logprob, labels)
        neg_logit = neg_logit - jnp.take(noise_logprob, noise_ids)
    pos_l = _softplus(-pos_logit)
    neg_l = _softplus(neg_logit).sum(-1)
    return pos_l + neg_l


def hsigmoid_loss(hidden, labels, codes, signs, node_w, node_b):
    """Hierarchical sigmoid (reference: ``HierarchicalSigmoidLayer.cpp``,
    ``paddle/math/MatrixBitCode.cpp``) with a *complete binary tree* over
    classes, matching the reference's bit-code addressing.

    hidden: [B, D]; codes: [B, L] int node ids (-1 pad); signs: [B, L] ±1/0;
    node_w: [num_nodes, D]; node_b: [num_nodes].
    Use :func:`build_hsigmoid_codes` to derive codes/signs from labels.
    """
    h = hidden.astype(jnp.float32)
    safe = jnp.maximum(codes, 0)
    w = jnp.take(node_w, safe, axis=0)                 # [B, L, D]
    b = jnp.take(node_b, safe)
    logit = jnp.einsum("bd,bld->bl", h, w) + b
    l = _softplus(-signs * logit)
    return (l * (codes >= 0)).sum(-1)


def build_hsigmoid_codes(labels, num_classes: int):
    """Host/jit helper: complete-binary-tree path codes for each label.

    Mirrors the reference's ``SimpleCode`` (``paddle/math/MatrixBitCode.cpp``):
    code(c) = c + num_classes maps the label into heap order; internal nodes are
    indices [1, num_classes); sign is +1 when the path goes left (bit 0).
    Returns (codes [B, L], signs [B, L]) with -1/0 padding; L = ceil(log2(C)).
    """
    depth = max(1, int(jnp.ceil(jnp.log2(num_classes))))
    c = labels + num_classes
    codes, signs = [], []
    for _ in range(depth):
        parent = c // 2
        bit = c % 2
        valid = parent >= 1
        codes.append(jnp.where(valid, parent - 1, -1))
        signs.append(jnp.where(valid, 1.0 - 2.0 * bit, 0.0))
        c = parent
    return jnp.stack(codes, -1), jnp.stack(signs, -1)


def cross_entropy_over_beam(path_scores, gold_idx, gold_score=None,
                            valid_mask=None):
    """Cross-entropy over beam-search candidate paths (reference:
    ``CrossEntropyOverBeamLayer.cpp`` / ``CrossEntropyOverBeam.h`` — softmax
    over all candidate paths of the beam tree; when the gold sequence fell
    off the beam it is appended as an extra path, ``goldAsExtraPath_``).

    ``path_scores [B, N]``: final scores of the N candidate paths per
    sequence. ``gold_idx [B]``: index of the gold path among candidates, or
    -1 if gold fell off the beam — in which case ``gold_score [B]`` (the
    model's score of the gold path) is appended as an N+1-th candidate.
    ``valid_mask [B, N]`` masks out padding candidates. Returns the mean
    negative log-probability of the gold path.
    """
    B, N = path_scores.shape
    if gold_score is None:
        gold_score = jnp.zeros((B,), path_scores.dtype)
    if valid_mask is None:
        valid_mask = jnp.ones((B, N), bool)
    off_beam = gold_idx < 0
    # static shape: always append the extra column; it only participates
    # (and is the target) when gold is off-beam
    extra = jnp.where(off_beam, gold_score, -jnp.inf)
    scores = jnp.concatenate([jnp.where(valid_mask, path_scores, -jnp.inf),
                              extra[:, None]], axis=1)
    target = jnp.where(off_beam, N, jnp.maximum(gold_idx, 0))
    logp = jax.nn.log_softmax(scores, axis=-1)
    nll = -jnp.take_along_axis(logp, target[:, None], 1)[:, 0]
    return jnp.mean(nll)
