"""Mixture-of-experts FFN with expert parallelism over the ``expert`` mesh
axis — a forward-looking capability (the 2017 reference has no MoE; the
mesh declares the axis, ``core/mesh.py``, and this layer is what uses it).

TPU-native shape: the classic static dispatch/combine einsum formulation —
top-1 routing with a fixed per-expert capacity, dispatch as a one-hot
[tokens, experts, capacity] tensor, expert FFNs batched over the expert
dimension. Everything is dense matmuls with static shapes (MXU-friendly, no
sorting/gathering), and sharding the expert-major weights/activations over
the ``expert`` axis (see :func:`moe_sharding_rules`) makes XLA insert the
token all-to-alls over ICI.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializers as I
from paddle_tpu.core.module import Module

__all__ = ["MoEFFN", "moe_sharding_rules"]


class MoEFFN(Module):
    """Top-1 routed expert FFN: ``x [B, T, D] -> [B, T, D]``.

    ``capacity_factor`` sizes each expert's token buffer
    (``C = ceil(tokens/experts * factor)``); overflowing tokens are dropped
    (contribute zero — the standard static-capacity trade).
    ``forward(x, return_aux=True)`` also returns the Switch-style
    load-balancing auxiliary loss to add to the training objective."""

    def __init__(self, num_experts: int, hidden: int,
                 capacity_factor: float = 1.25, act: str = "gelu",
                 name=None):
        super().__init__(name=name)
        self.num_experts = num_experts
        self.hidden = hidden
        self.capacity_factor = capacity_factor
        self.act_name = act

    def forward(self, x, return_aux: bool = False):
        from . import activations
        B, T, D = x.shape
        E = self.num_experts
        N = B * T
        C = max(1, math.ceil(N / E * self.capacity_factor))
        act = activations.get(self.act_name)

        wg = self.param("wg", I.xavier_uniform, (D, E))
        w1 = self.param("w1", I.fan_in_uniform, (E, D, self.hidden))
        b1 = self.param("b1", I.zeros, (E, self.hidden))
        w2 = self.param("w2", I.fan_in_uniform, (E, self.hidden, D))
        b2 = self.param("b2", I.zeros, (E, D))

        xf = x.reshape(N, D)
        logits = xf @ wg                                    # [N, E]
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)                 # [N]
        gate = jnp.max(probs, axis=-1)                      # [N]
        # Routing bookkeeping stays int32 regardless of x.dtype: a bf16
        # cumsum only counts exactly to 256, which would collide capacity
        # slots on real batch sizes.
        onehot_i = jax.nn.one_hot(expert, E, dtype=jnp.int32)  # [N, E]
        pos = jnp.cumsum(onehot_i, axis=0) * onehot_i - 1      # [N, E]
        kept = (pos < C) & (onehot_i > 0)
        pos_c = jnp.clip(pos, 0, C - 1)
        pos_onehot = jax.nn.one_hot(pos_c, C, dtype=x.dtype)   # [N, E, C]
        dispatch = pos_onehot * kept.astype(x.dtype)[..., None]
        combine = dispatch * gate.astype(x.dtype)[:, None, None]
        onehot = onehot_i.astype(jnp.float32)

        # [E, C, D] expert inputs; batched expert FFN; combine back
        expert_in = jnp.einsum("nd,nec->ecd", xf, dispatch)
        h = act(jnp.einsum("ecd,edh->ech", expert_in, w1) + b1[:, None, :])
        expert_out = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
        out = jnp.einsum("ecd,nec->nd", expert_out, combine)

        out = out.reshape(B, T, D)
        if not return_aux:
            return out
        # Switch-style load-balance aux: E * sum_e (frac_tokens_e * mean_prob_e)
        frac = jnp.mean(onehot, axis=0)
        mean_prob = jnp.mean(probs.astype(jnp.float32), axis=0)
        return out, E * jnp.sum(frac * mean_prob)


def moe_sharding_rules(expert_axis: str = "expert"):
    """fnmatch-style ``(pattern, PartitionSpec)`` rules sharding the
    expert-major MoE weights over the expert mesh axis (feed to
    :class:`paddle_tpu.parallel.ShardingRules`, composable with other
    rules)."""
    from jax.sharding import PartitionSpec as P
    return [
        ("*/w1", P(expert_axis, None, None)),
        ("*/b1", P(expert_axis, None)),
        ("*/w2", P(expert_axis, None, None)),
        ("*/b2", P(expert_axis, None)),
    ]
