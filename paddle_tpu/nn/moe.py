"""Mixture-of-experts FFN with expert parallelism over the ``expert`` mesh
axis — a forward-looking capability (the 2017 reference has no MoE; the
mesh declares the axis, ``core/mesh.py``, and this layer is what uses it).

TPU-native shape: the classic static dispatch/combine einsum formulation —
top-k routing with a fixed per-expert capacity, dispatch as a one-hot
[tokens, experts, capacity] tensor, expert FFNs batched over the expert
dimension. Everything is dense matmuls with static shapes (MXU-friendly, no
sorting/gathering), and sharding the expert-major weights/activations over
the ``expert`` axis (see :func:`moe_sharding_rules`) makes XLA insert the
token all-to-alls over ICI.

Routing is top-k (k static; k=1 is the Switch formulation, k=2 the classic
GShard/expert-choice-free variant): each token's k expert choices claim
capacity slots in choice-major order (first choices of all tokens beat
second choices — the standard priority), gates optionally renormalized over
the kept choices. Overflowing (token, choice) pairs are dropped
(contribute zero), and the layer REPORTS the drop rate instead of hiding it
(``return_stats=True``).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializers as I
from paddle_tpu.core.module import Module

__all__ = ["MoEFFN", "moe_sharding_rules"]


class MoEFFN(Module):
    """Top-k routed expert FFN: ``x [B, T, D] -> [B, T, D]``.

    ``capacity_factor`` sizes each expert's token buffer
    (``C = ceil(tokens*k/experts * factor)``); overflowing (token, choice)
    pairs are dropped (contribute zero — the standard static-capacity
    trade). ``forward(x, return_aux=True)`` also returns the Switch-style
    load-balancing auxiliary loss; ``return_stats=True`` additionally
    returns routing telemetry: ``drop_rate`` (fraction of token-choices
    that overflowed) and ``expert_fraction`` (per-expert token share).
    """

    def __init__(self, num_experts: int, hidden: int,
                 capacity_factor: float = 1.25, act: str = "gelu",
                 top_k: int = 1, renormalize: bool = True, name=None):
        super().__init__(name=name)
        assert 1 <= top_k <= num_experts
        self.num_experts = num_experts
        self.hidden = hidden
        self.capacity_factor = capacity_factor
        self.act_name = act
        self.top_k = top_k
        self.renormalize = renormalize

    def forward(self, x, return_aux: bool = False,
                return_stats: bool = False):
        from . import activations
        B, T, D = x.shape
        E = self.num_experts
        K = self.top_k
        N = B * T
        C = max(1, math.ceil(N * K / E * self.capacity_factor))
        act = activations.get(self.act_name)

        wg = self.param("wg", I.xavier_uniform, (D, E))
        w1 = self.param("w1", I.fan_in_uniform, (E, D, self.hidden))
        b1 = self.param("b1", I.zeros, (E, self.hidden))
        w2 = self.param("w2", I.fan_in_uniform, (E, self.hidden, D))
        b2 = self.param("b2", I.zeros, (E, D))

        xf = x.reshape(N, D)
        logits = xf @ wg                                    # [N, E]
        probs = jax.nn.softmax(logits, axis=-1)
        top_gates, top_idx = jax.lax.top_k(probs, K)        # [N, K]
        if self.renormalize and K > 1:
            top_gates = top_gates / jnp.maximum(
                jnp.sum(top_gates, axis=-1, keepdims=True), 1e-9)

        # Capacity assignment, choice-major priority: all first choices
        # claim slots before any second choice. Routing bookkeeping stays
        # int32 regardless of x.dtype: a bf16 cumsum only counts exactly to
        # 256, which would collide capacity slots on real batch sizes.
        counts = jnp.zeros((E,), jnp.int32)                 # slots used
        dispatch = jnp.zeros((N, E, C), x.dtype)
        combine = jnp.zeros((N, E, C), x.dtype)
        kept_total = jnp.zeros((), jnp.int32)
        for j in range(K):                                  # K is static
            onehot_j = jax.nn.one_hot(top_idx[:, j], E, dtype=jnp.int32)
            pos_j = (jnp.cumsum(onehot_j, axis=0) - 1
                     + counts[None, :]) * onehot_j          # [N, E]
            kept = (pos_j < C) & (onehot_j > 0)
            pos_c = jnp.clip(pos_j, 0, C - 1)
            pos_onehot = jax.nn.one_hot(pos_c, C, dtype=x.dtype)  # [N, E, C]
            disp_j = pos_onehot * kept.astype(x.dtype)[..., None]
            dispatch = dispatch + disp_j
            combine = combine + disp_j * top_gates[:, j, None, None].astype(
                x.dtype)
            counts = counts + jnp.sum(onehot_j * kept.astype(jnp.int32),
                                      axis=0)
            kept_total = kept_total + jnp.sum(kept.astype(jnp.int32))

        # [E, C, D] expert inputs; batched expert FFN; combine back
        expert_in = jnp.einsum("nd,nec->ecd", xf, dispatch)
        h = act(jnp.einsum("ecd,edh->ech", expert_in, w1) + b1[:, None, :])
        expert_out = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
        out = jnp.einsum("ecd,nec->nd", expert_out, combine)

        out = out.reshape(B, T, D)
        if not (return_aux or return_stats):
            return out
        # Switch-style load-balance aux over FIRST choices:
        # E * sum_e (frac_tokens_e * mean_prob_e)
        onehot1 = jax.nn.one_hot(top_idx[:, 0], E, dtype=jnp.float32)
        frac = jnp.mean(onehot1, axis=0)
        mean_prob = jnp.mean(probs.astype(jnp.float32), axis=0)
        aux = E * jnp.sum(frac * mean_prob)
        if not return_stats:
            return out, aux
        stats = {
            "drop_rate": 1.0 - kept_total.astype(jnp.float32) / (N * K),
            "expert_fraction": frac,
            "capacity": jnp.asarray(C, jnp.int32),
        }
        if not return_aux:
            return out, stats
        return out, aux, stats


def moe_sharding_rules(expert_axis: str = "expert"):
    """fnmatch-style ``(pattern, PartitionSpec)`` rules sharding the
    expert-major MoE weights over the expert mesh axis (feed to
    :class:`paddle_tpu.parallel.ShardingRules`, composable with other
    rules)."""
    from jax.sharding import PartitionSpec as P
    return [
        ("*/w1", P(expert_axis, None, None)),
        ("*/b1", P(expert_axis, None)),
        ("*/w2", P(expert_axis, None, None)),
        ("*/b2", P(expert_axis, None)),
    ]
