"""Fused LayerNorm + matmul epilogue kernel (ROADMAP item, ISSUE 16).

The transformer block's pre-projection pattern ``Linear(LayerNorm(x))``
costs an extra HBM round trip when XLA materializes the normalized
activations between the two ops; this Pallas kernel computes the row
statistics in VMEM and feeds the normalized tile straight into the MXU
dot — the LN is an *epilogue of the matmul's operand load*, never a
stored tensor. Each ``(block_m, block_n)`` output tile loads its
``(block_m, K)`` x rows once, normalizes in f32 (the ``LayerNorm``
module's exact recipe: f32 mean/var, ``rsqrt(var + eps)``), applies the
optional scale/bias, casts back to the input dtype and runs one
``jnp.dot`` with ``preferred_element_type=jnp.float32`` — matching
:func:`ln_matmul_reference` to f32 roundoff (the kernel body compiles
as ONE fused computation, so its FMA-fused rounding can differ from the
op-at-a-time oracle in the last ulp; K is never split, so the dot's
accumulation order is identical).

The row statistics recompute once per N-tile — the standard epilogue
trade: recomputing a [bm, 1] mean/var in VMEM is cheaper than an HBM
round trip of the [M, K] normalized tensor for every realistic K.

This is the first *autotuned citizen* beyond the flash kernels: with
:mod:`~paddle_tpu.nn.autotune` enabled, ``(block_m, block_n)`` come from
timed trials persisted per ``(shape, dtype, platform)``; disabled, the
``_auto_block`` heuristic answers untimed, and explicit blocks bypass
selection entirely — the same three-tier contract as
``flash_attention``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from . import autotune
from .pallas_attention import _auto_block

__all__ = ["fused_ln_matmul", "ln_matmul_reference"]


def ln_matmul_reference(x, w, scale=None, bias=None, eps: float = 1e-6):
    """Unfused oracle: ``LayerNorm(x) @ w`` with the ``LayerNorm``
    module's numerics (f32 statistics, cast back to ``x.dtype`` before
    the dot, f32 accumulation)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * lax.rsqrt(var + eps)
    if scale is not None:
        y = y * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    y = y.astype(x.dtype)
    return jnp.dot(y, w, preferred_element_type=jnp.float32
                   ).astype(x.dtype)


def _ln_matmul_kernel(x_ref, w_ref, *refs, eps, has_scale, has_bias):
    o_ref = refs[-1]
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    i = 0
    if has_scale:
        y = y * refs[i][...].astype(jnp.float32)
        i += 1
    if has_bias:
        y = y + refs[i][...].astype(jnp.float32)
    y = y.astype(x_ref.dtype)
    o_ref[...] = jnp.dot(y, w_ref[...],
                         preferred_element_type=jnp.float32
                         ).astype(o_ref.dtype)


def _ln_candidates(M, N):
    """Candidate tile grid: MXU-friendly blocks dividing M/N, capped at
    6 trials (the flash kernels' budget rule)."""
    ms = [b for b in (256, 128, 64) if M % b == 0]
    ns = [b for b in (512, 256, 128) if N % b == 0]
    if not ms:
        ms = [_auto_block(M, 128)]
    if not ns:
        ns = [_auto_block(N, 512)]
    return [{"block_m": a, "block_n": b} for a in ms for b in ns][:6]


def fused_ln_matmul(x, w, scale=None, bias=None, *, eps: float = 1e-6,
                    block_m: Optional[int] = None,
                    block_n: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """``LayerNorm(x) @ w`` in one Pallas kernel.

    Args:
      x: ``[M, K]`` activations (leading dims: flatten upstream — the
        framework's static-shape packing already does).
      w: ``[K, N]`` projection weight.
      scale, bias: optional ``[K]`` LN affine params (the ``LayerNorm``
        module's ``scale``/``bias``).
      eps: LN epsilon (module default 1e-6).
      block_m, block_n: explicit tile sizes (must divide M/N); None =
        autotuned when the tuner is enabled, else the ``_auto_block``
        heuristic.
      interpret: Pallas interpreter toggle; defaults to True off-TPU
        (same auto-select rule as the flash kernels).
    """
    M, K = x.shape
    K2, N = w.shape
    assert K == K2, f"x [{M},{K}] @ w [{K2},{N}]: contraction mismatch"
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    explicit = block_m is not None or block_n is not None
    bm = _auto_block(M, 128) if block_m is None else min(block_m, M)
    bn = _auto_block(N, 512) if block_n is None else min(block_n, N)
    if not explicit and autotune.is_enabled():
        key = autotune.make_key("ln_matmul", shape=(M, K, N),
                                dtype=x.dtype,
                                extra=(int(scale is not None),
                                       int(bias is not None)))

        def runner(block_m, block_n):
            zx = jnp.zeros((M, K), x.dtype)
            zw = jnp.zeros((K, N), w.dtype)
            zs = jnp.zeros((K,), x.dtype) if scale is not None else None
            zb = jnp.zeros((K,), x.dtype) if bias is not None else None
            return fused_ln_matmul(zx, zw, zs, zb, eps=eps,
                                   block_m=block_m, block_n=block_n,
                                   interpret=interpret)

        cfg = autotune.choose("ln_matmul", key=key,
                              candidates=_ln_candidates(M, N),
                              runner=runner,
                              default={"block_m": bm, "block_n": bn})
        cm, cn = cfg.get("block_m", bm), cfg.get("block_n", bn)
        if M % cm == 0 and N % cn == 0:
            bm, bn = cm, cn
    assert M % bm == 0 and N % bn == 0, \
        f"[{M},{N}] must tile by blocks ({bm}, {bn})"
    in_specs = [
        pl.BlockSpec((bm, K), lambda i, j: (i, 0)),
        pl.BlockSpec((K, bn), lambda i, j: (0, j)),
    ]
    operands = [x, w]
    for p in (scale, bias):
        if p is not None:
            # rank-2 block: TPU tiling rejects rank-1
            in_specs.append(pl.BlockSpec((1, K), lambda i, j: (0, 0)))
            operands.append(p.reshape(1, K))
    return pl.pallas_call(
        functools.partial(_ln_matmul_kernel, eps=eps,
                          has_scale=scale is not None,
                          has_bias=bias is not None),
        grid=(M // bm, N // bn),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        interpret=interpret,
    )(*operands)
