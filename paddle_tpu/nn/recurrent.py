"""Recurrent layers — lax.scan successors of the reference's RNN machinery.

Reference: ``/root/reference/paddle/gserver/layers/LstmLayer.cpp`` (LSTM with
peephole connections, reversed mode), ``GatedRecurrentLayer.cpp`` (GRU),
``RecurrentLayer.cpp`` (vanilla), and the ``SequenceToBatch`` batch-scheduling
trick (``SequenceToBatch.h``) that packs variable-length sequences for step-wise
kernels. On TPU the scheduling disappears: one ``lax.scan`` over the padded time
axis with per-step validity masks (state freezes past each sequence's end), and
optional segment-reset for packed rows. The gate matmuls are fused into one
``[D, 4H]`` projection so the MXU sees large GEMMs.

Step cells are exposed separately (``LSTMCell.step``) for the decoder-side
"recurrent group" pattern (the reference's ``LstmStepLayer``/``GruStepLayer``
used inside ``RecurrentGradientMachine`` unrolls).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core import initializers as I
from ..core.module import Module
from . import activations

__all__ = ["LSTMCell", "GRUCell", "SimpleRNNCell", "RNN", "BiRNN"]


class LSTMCell(Module):
    """LSTM cell with optional peepholes (reference: ``LstmLayer.cpp`` — gates
    i,f,o with W_ic/W_fc/W_oc diagonal peephole weights; ``hl_lstm.h``)."""

    def __init__(self, hidden: int, use_peepholes: bool = True,
                 act="tanh", gate_act="sigmoid", name=None):
        super().__init__(name=name)
        self.hidden = hidden
        self.use_peepholes = use_peepholes
        self.act = activations.get(act)
        self.gate_act = activations.get(gate_act)

    def initial_state(self, batch: int):
        return (jnp.zeros((batch, self.hidden)),
                jnp.zeros((batch, self.hidden)))

    def step(self, state, x):
        with self.scope():
            return self._step(state, x)

    def _step(self, state, x):
        h_prev, c_prev = state
        hd = self.hidden
        wx = self.param("wx", I.xavier_uniform, (x.shape[-1], 4 * hd))
        wh = self.param("wh", I.orthogonal(), (hd, 4 * hd))
        b = self.param("b", I.zeros, (4 * hd,))
        z = x @ wx + h_prev @ wh + b
        zi, zf, zg, zo = jnp.split(z, 4, axis=-1)
        if self.use_peepholes:
            w_ic = self.param("w_ic", I.zeros, (hd,))
            w_fc = self.param("w_fc", I.zeros, (hd,))
            zi = zi + c_prev * w_ic
            zf = zf + c_prev * w_fc
        i = self.gate_act(zi)
        f = self.gate_act(zf)
        c = f * c_prev + i * self.act(zg)
        zo_ = zo
        if self.use_peepholes:
            w_oc = self.param("w_oc", I.zeros, (hd,))
            zo_ = zo + c * w_oc
        o = self.gate_act(zo_)
        h = o * self.act(c)
        return (h, c), h

    def forward(self, state, x):
        return self._step(state, x)


class GRUCell(Module):
    """GRU cell (reference: ``GatedRecurrentLayer.cpp``, ``hl_gpu_gru.cuh``)."""

    def __init__(self, hidden: int, act="tanh", gate_act="sigmoid", name=None):
        super().__init__(name=name)
        self.hidden = hidden
        self.act = activations.get(act)
        self.gate_act = activations.get(gate_act)

    def initial_state(self, batch: int):
        return jnp.zeros((batch, self.hidden))

    def step(self, state, x):
        with self.scope():
            return self._step(state, x)

    def _step(self, state, x):
        h_prev = state
        hd = self.hidden
        wx = self.param("wx", I.xavier_uniform, (x.shape[-1], 3 * hd))
        wh = self.param("wh", I.orthogonal(), (hd, 2 * hd))
        wc = self.param("wc", I.orthogonal(), (hd, hd))
        b = self.param("b", I.zeros, (3 * hd,))
        zx = x @ wx + b
        zu, zr, zc = jnp.split(zx, 3, axis=-1)
        hu, hr = jnp.split(h_prev @ wh, 2, axis=-1)
        u = self.gate_act(zu + hu)
        r = self.gate_act(zr + hr)
        cand = self.act(zc + (r * h_prev) @ wc)
        h = u * h_prev + (1 - u) * cand
        return h, h

    def forward(self, state, x):
        return self._step(state, x)


class SimpleRNNCell(Module):
    """Vanilla RNN (reference: ``RecurrentLayer.cpp``)."""

    def __init__(self, hidden: int, act="tanh", name=None):
        super().__init__(name=name)
        self.hidden = hidden
        self.act = activations.get(act)

    def initial_state(self, batch: int):
        return jnp.zeros((batch, self.hidden))

    def step(self, state, x):
        with self.scope():
            return self._step(state, x)

    def _step(self, state, x):
        wx = self.param("wx", I.xavier_uniform, (x.shape[-1], self.hidden))
        wh = self.param("wh", I.orthogonal(), (self.hidden, self.hidden))
        b = self.param("b", I.zeros, (self.hidden,))
        h = self.act(x @ wx + state @ wh + b)
        return h, h

    def forward(self, state, x):
        return self._step(state, x)


class RNN(Module):
    """Run a cell over the time axis of ``x [B, T, D]`` with lax.scan.

    - ``mask [B, T]``: state freezes where mask==0 (padded steps) — replaces
      the reference's SequenceToBatch scheduling.
    - ``segment_starts [B, T]``: 1 where a new packed segment begins — state
      resets, enabling packed-row training (SURVEY.md §5).
    - ``reverse``: the reference's reversed-LSTM mode.
    - ``initial_state``: boot state (the RecurrentGradientMachine boot layer).
    Returns ``(outputs [B, T, H], final_state)``.
    """

    def __init__(self, cell, reverse: bool = False, name=None):
        super().__init__(name=name)
        self.cell = cell
        self.reverse = reverse

    def forward(self, x, mask=None, segment_starts=None, initial_state=None):
        b, t = x.shape[0], x.shape[1]
        state0 = (initial_state if initial_state is not None
                  else self.cell.initial_state(b))

        # Materialize cell params once (outside scan) by tracing one step at
        # fixed path; scan then reuses them via closure.
        cell = self.cell

        def one_step(state, inputs):
            xt, mt, st = inputs
            if st is not None:
                # reset state where a new segment starts
                state = jax.tree_util.tree_map(
                    lambda s0, s: jnp.where(st[:, None] > 0, s0, s),
                    state0, state)
            new_state, out = cell.step(state, xt)
            if mt is not None:
                keep = mt[:, None]
                new_state = jax.tree_util.tree_map(
                    lambda n, o: keep * n + (1 - keep) * o, new_state, state)
                out = out * keep
            return new_state, out

        if self.reverse and segment_starts is not None:
            # The reversed scan enters each packed segment at its END, so the
            # reset flags must fire there: end[t] = start[t+1] (and the last
            # position always ends a segment), computed in original order and
            # reversed with the rest of the inputs below.
            segment_starts = jnp.concatenate(
                [segment_starts[:, 1:],
                 jnp.ones_like(segment_starts[:, :1])], axis=1)

        xs = jnp.swapaxes(x, 0, 1)                      # [T, B, D]
        ms = None if mask is None else jnp.swapaxes(mask, 0, 1)
        ss = None if segment_starts is None else jnp.swapaxes(segment_starts,
                                                              0, 1)
        if self.reverse:
            xs = xs[::-1]
            ms = None if ms is None else ms[::-1]
            ss = None if ss is None else ss[::-1]

        # Pre-create params: run one step eagerly so scan's trace finds them.
        _ = one_step(state0, (xs[0], None if ms is None else ms[0],
                              None if ss is None else ss[0]))

        def scan_body(state, inp):
            if ms is None and ss is None:
                xt = inp
                return one_step(state, (xt, None, None))
            if ss is None:
                xt, mt = inp
                return one_step(state, (xt, mt, None))
            if ms is None:
                xt, st = inp
                return one_step(state, (xt, None, st))
            xt, mt, st = inp
            return one_step(state, (xt, mt, st))

        if ms is None and ss is None:
            inputs = xs
        elif ss is None:
            inputs = (xs, ms)
        elif ms is None:
            inputs = (xs, ss)
        else:
            inputs = (xs, ms, ss)
        final, outs = lax.scan(scan_body, state0, inputs)
        outs = jnp.swapaxes(outs, 0, 1)                 # [B, T, H]
        if self.reverse:
            outs = outs[:, ::-1]
        return outs, final


class BiRNN(Module):
    """Bidirectional wrapper (reference: ``networks.py bidirectional_lstm``):
    concat of forward and reverse passes with independent cells."""

    def __init__(self, fwd_cell, bwd_cell, name=None):
        super().__init__(name=name)
        self.fwd = RNN(fwd_cell, reverse=False, name="fwd")
        self.bwd = RNN(bwd_cell, reverse=True, name="bwd")

    def forward(self, x, mask=None, segment_starts=None):
        of, _ = self.fwd(x, mask=mask, segment_starts=segment_starts)
        ob, _ = self.bwd(x, mask=mask, segment_starts=segment_starts)
        return jnp.concatenate([of, ob], axis=-1)
