"""Recurrent layers — lax.scan successors of the reference's RNN machinery.

Reference: ``/root/reference/paddle/gserver/layers/LstmLayer.cpp`` (LSTM with
peephole connections, reversed mode), ``GatedRecurrentLayer.cpp`` (GRU),
``RecurrentLayer.cpp`` (vanilla), and the ``SequenceToBatch`` batch-scheduling
trick (``SequenceToBatch.h``) that packs variable-length sequences for step-wise
kernels. On TPU the scheduling disappears: one ``lax.scan`` over the padded time
axis with per-step validity masks (state freezes past each sequence's end), and
optional segment-reset for packed rows. The gate matmuls are fused into one
``[D, 4H]`` projection so the MXU sees large GEMMs.

Step cells are exposed separately (``LSTMCell.step``) for the decoder-side
"recurrent group" pattern (the reference's ``LstmStepLayer``/``GruStepLayer``
used inside ``RecurrentGradientMachine`` unrolls).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core import initializers as I
from ..core.module import Module
from . import activations

__all__ = ["LSTMCell", "GRUCell", "SimpleRNNCell", "RNN", "BiRNN"]


class LSTMCell(Module):
    """LSTM cell with optional peepholes (reference: ``LstmLayer.cpp`` — gates
    i,f,o with W_ic/W_fc/W_oc diagonal peephole weights; ``hl_lstm.h``)."""

    def __init__(self, hidden: int, use_peepholes: bool = True,
                 act="tanh", gate_act="sigmoid", name=None):
        super().__init__(name=name)
        self.hidden = hidden
        self.use_peepholes = use_peepholes
        self.act = activations.get(act)
        self.gate_act = activations.get(gate_act)

    def initial_state(self, batch: int):
        return (jnp.zeros((batch, self.hidden)),
                jnp.zeros((batch, self.hidden)))

    def step(self, state, x):
        with self.scope():
            return self._step(state, x)

    def input_proj(self, x):
        """Input-to-hidden half of the gates for a WHOLE sequence
        [..., T, D] in one MXU-shaped matmul — hoisted out of the scan by
        :class:`RNN` (pair with :meth:`step_proj`, which adds the serial
        hidden-to-hidden half). Declares every cell param in the same order
        as :meth:`step`, so init is identical whichever path runs first."""
        with self.scope():
            hd = self.hidden
            wx = self.param("wx", I.xavier_uniform, (x.shape[-1], 4 * hd))
            self.param("wh", I.orthogonal(), (hd, 4 * hd))
            b = self.param("b", I.zeros, (4 * hd,))
            return x @ wx + b

    def step_proj(self, state, zx):
        """One step from a precomputed input projection (see input_proj)."""
        with self.scope():
            h_prev, c_prev = state
            hd = self.hidden
            wh = self.param("wh", I.orthogonal(), (hd, 4 * hd))
            return self._gates(h_prev, c_prev, zx + h_prev @ wh)

    def _step(self, state, x):
        h_prev, c_prev = state
        hd = self.hidden
        wx = self.param("wx", I.xavier_uniform, (x.shape[-1], 4 * hd))
        wh = self.param("wh", I.orthogonal(), (hd, 4 * hd))
        b = self.param("b", I.zeros, (4 * hd,))
        return self._gates(h_prev, c_prev, x @ wx + h_prev @ wh + b)

    def _gates(self, h_prev, c_prev, z):
        hd = self.hidden
        zi, zf, zg, zo = jnp.split(z, 4, axis=-1)
        if self.use_peepholes:
            w_ic = self.param("w_ic", I.zeros, (hd,))
            w_fc = self.param("w_fc", I.zeros, (hd,))
            zi = zi + c_prev * w_ic
            zf = zf + c_prev * w_fc
        i = self.gate_act(zi)
        f = self.gate_act(zf)
        c = f * c_prev + i * self.act(zg)
        zo_ = zo
        if self.use_peepholes:
            w_oc = self.param("w_oc", I.zeros, (hd,))
            zo_ = zo + c * w_oc
        o = self.gate_act(zo_)
        h = o * self.act(c)
        return (h, c), h

    def forward(self, state, x):
        return self._step(state, x)


class GRUCell(Module):
    """GRU cell (reference: ``GatedRecurrentLayer.cpp``, ``hl_gpu_gru.cuh``)."""

    def __init__(self, hidden: int, act="tanh", gate_act="sigmoid", name=None):
        super().__init__(name=name)
        self.hidden = hidden
        self.act = activations.get(act)
        self.gate_act = activations.get(gate_act)

    def initial_state(self, batch: int):
        return jnp.zeros((batch, self.hidden))

    def step(self, state, x):
        with self.scope():
            return self._step(state, x)

    def input_proj(self, x):
        """Input half of the gates for a whole sequence (see
        ``LSTMCell.input_proj``); declares params in :meth:`step`'s order."""
        with self.scope():
            hd = self.hidden
            wx = self.param("wx", I.xavier_uniform, (x.shape[-1], 3 * hd))
            self.param("wh", I.orthogonal(), (hd, 2 * hd))
            self.param("wc", I.orthogonal(), (hd, hd))
            b = self.param("b", I.zeros, (3 * hd,))
            return x @ wx + b

    def step_proj(self, state, zx):
        with self.scope():
            hd = self.hidden
            wh = self.param("wh", I.orthogonal(), (hd, 2 * hd))
            wc = self.param("wc", I.orthogonal(), (hd, hd))
            return self._gates(state, zx, wh, wc)

    def _step(self, state, x):
        hd = self.hidden
        wx = self.param("wx", I.xavier_uniform, (x.shape[-1], 3 * hd))
        wh = self.param("wh", I.orthogonal(), (hd, 2 * hd))
        wc = self.param("wc", I.orthogonal(), (hd, hd))
        b = self.param("b", I.zeros, (3 * hd,))
        return self._gates(state, x @ wx + b, wh, wc)

    def _gates(self, h_prev, zx, wh, wc):
        zu, zr, zc = jnp.split(zx, 3, axis=-1)
        hu, hr = jnp.split(h_prev @ wh, 2, axis=-1)
        u = self.gate_act(zu + hu)
        r = self.gate_act(zr + hr)
        cand = self.act(zc + (r * h_prev) @ wc)
        h = u * h_prev + (1 - u) * cand
        return h, h

    def forward(self, state, x):
        return self._step(state, x)


class SimpleRNNCell(Module):
    """Vanilla RNN (reference: ``RecurrentLayer.cpp``)."""

    def __init__(self, hidden: int, act="tanh", name=None):
        super().__init__(name=name)
        self.hidden = hidden
        self.act = activations.get(act)

    def initial_state(self, batch: int):
        return jnp.zeros((batch, self.hidden))

    def step(self, state, x):
        with self.scope():
            return self._step(state, x)

    def input_proj(self, x):
        with self.scope():
            wx = self.param("wx", I.xavier_uniform,
                            (x.shape[-1], self.hidden))
            self.param("wh", I.orthogonal(), (self.hidden, self.hidden))
            b = self.param("b", I.zeros, (self.hidden,))
            return x @ wx + b

    def step_proj(self, state, zx):
        with self.scope():
            wh = self.param("wh", I.orthogonal(),
                            (self.hidden, self.hidden))
            h = self.act(zx + state @ wh)
            return h, h

    def _step(self, state, x):
        wx = self.param("wx", I.xavier_uniform, (x.shape[-1], self.hidden))
        wh = self.param("wh", I.orthogonal(), (self.hidden, self.hidden))
        b = self.param("b", I.zeros, (self.hidden,))
        h = self.act(x @ wx + state @ wh + b)
        return h, h

    def forward(self, state, x):
        return self._step(state, x)


class RNN(Module):
    """Run a cell over the time axis of ``x [B, T, D]`` with lax.scan.

    - ``mask [B, T]``: state freezes where mask==0 (padded steps) — replaces
      the reference's SequenceToBatch scheduling.
    - ``segment_starts [B, T]``: 1 where a new packed segment begins — state
      resets, enabling packed-row training (SURVEY.md §5).
    - ``reverse``: the reference's reversed-LSTM mode.
    - ``initial_state``: boot state (the RecurrentGradientMachine boot layer).
    Returns ``(outputs [B, T, H], final_state)``.
    """

    def __init__(self, cell, reverse: bool = False, unroll: int = 1,
                 name=None):
        super().__init__(name=name)
        self.cell = cell
        self.reverse = reverse
        # lax.scan unroll factor: an RNN step is a SMALL matmul, so the
        # while-loop iteration overhead (~10 us on TPU) can dominate;
        # unrolling amortizes it and lets XLA fuse across steps at the cost
        # of compile time (measured in experiments/PERF.md "Round 5")
        self.unroll = unroll

    def forward(self, x, mask=None, segment_starts=None, initial_state=None):
        b, t = x.shape[0], x.shape[1]
        state0 = (initial_state if initial_state is not None
                  else self.cell.initial_state(b))

        # Materialize cell params once (outside scan) by tracing one step at
        # fixed path; scan then reuses them via closure.
        cell = self.cell

        # Input-projection hoist: cells exposing input_proj/step_proj get
        # their input-to-hidden gate matmul computed for the WHOLE sequence
        # in one MXU-shaped [B*T, D] @ [D, G] before the scan; only the
        # serial hidden-to-hidden half stays inside (halves LSTM scan FLOPs
        # — experiments/PERF.md "Round 5").
        use_proj = hasattr(cell, "input_proj")
        if use_proj:
            x = cell.input_proj(x)
        cell_step = cell.step_proj if use_proj else cell.step

        def one_step(state, inputs):
            xt, mt, st = inputs
            if st is not None:
                # reset state where a new segment starts
                state = jax.tree_util.tree_map(
                    lambda s0, s: jnp.where(st[:, None] > 0, s0, s),
                    state0, state)
            new_state, out = cell_step(state, xt)
            if mt is not None:
                keep = mt[:, None]
                new_state = jax.tree_util.tree_map(
                    lambda n, o: keep * n + (1 - keep) * o, new_state, state)
                out = out * keep
            return new_state, out

        if self.reverse and segment_starts is not None:
            # The reversed scan enters each packed segment at its END, so the
            # reset flags must fire there: end[t] = start[t+1] (and the last
            # position always ends a segment), computed in original order and
            # reversed with the rest of the inputs below.
            segment_starts = jnp.concatenate(
                [segment_starts[:, 1:],
                 jnp.ones_like(segment_starts[:, :1])], axis=1)

        xs = jnp.swapaxes(x, 0, 1)                      # [T, B, D]
        ms = None if mask is None else jnp.swapaxes(mask, 0, 1)
        ss = None if segment_starts is None else jnp.swapaxes(segment_starts,
                                                              0, 1)
        if self.reverse:
            xs = xs[::-1]
            ms = None if ms is None else ms[::-1]
            ss = None if ss is None else ss[::-1]

        # Pre-create params: run one step eagerly so scan's trace finds them.
        _ = one_step(state0, (xs[0], None if ms is None else ms[0],
                              None if ss is None else ss[0]))

        def scan_body(state, inp):
            if ms is None and ss is None:
                xt = inp
                return one_step(state, (xt, None, None))
            if ss is None:
                xt, mt = inp
                return one_step(state, (xt, mt, None))
            if ms is None:
                xt, st = inp
                return one_step(state, (xt, None, st))
            xt, mt, st = inp
            return one_step(state, (xt, mt, st))

        if ms is None and ss is None:
            inputs = xs
        elif ss is None:
            inputs = (xs, ms)
        elif ms is None:
            inputs = (xs, ss)
        else:
            inputs = (xs, ms, ss)
        final, outs = lax.scan(scan_body, state0, inputs,
                               unroll=self.unroll)
        outs = jnp.swapaxes(outs, 0, 1)                 # [B, T, H]
        if self.reverse:
            outs = outs[:, ::-1]
        return outs, final


class BiRNN(Module):
    """Bidirectional wrapper (reference: ``networks.py bidirectional_lstm``):
    concat of forward and reverse passes with independent cells."""

    def __init__(self, fwd_cell, bwd_cell, unroll: int = 1, name=None):
        super().__init__(name=name)
        self.fwd = RNN(fwd_cell, reverse=False, unroll=unroll, name="fwd")
        self.bwd = RNN(bwd_cell, reverse=True, unroll=unroll, name="bwd")

    def forward(self, x, mask=None, segment_starts=None):
        of, _ = self.fwd(x, mask=mask, segment_starts=segment_starts)
        ob, _ = self.bwd(x, mask=mask, segment_starts=segment_starts)
        return jnp.concatenate([of, ob], axis=-1)


class MDLstm(Module):
    """Two-dimensional multi-directional LSTM over an image grid (reference:
    ``MDLstmLayer.cpp`` — Graves-style MDLSTM: each cell (i, j) receives
    recurrent input from its top (i-1, j) and left (i, j-1) neighbours, with
    one forget gate per direction).

    ``forward(x [B, H, W, D]) -> h [B, H, W, hidden]``. Implemented as a
    ``lax.scan`` over rows whose carry is the previous row's (h, c)
    [B, W, hidden], with an inner scan over columns carrying (h_left,
    c_left) — the same O(H*W) sequential dependency the recurrence itself
    has. Set ``reverse_h``/``reverse_w`` for the other three scan
    directions (the reference instantiates 4 directions for full MD-LSTM).
    """

    def __init__(self, hidden: int, act="tanh", gate_act="sigmoid",
                 reverse_h: bool = False, reverse_w: bool = False, name=None):
        super().__init__(name=name)
        self.hidden = hidden
        self.act = activations.get(act)
        self.gate_act = activations.get(gate_act)
        self.reverse_h = reverse_h
        self.reverse_w = reverse_w

    def forward(self, x):
        B, H, W, D = x.shape
        hd = self.hidden
        wx = self.param("wx", I.xavier_uniform, (D, 5 * hd))
        wh_up = self.param("wh_up", I.orthogonal(), (hd, 5 * hd))
        wh_left = self.param("wh_left", I.orthogonal(), (hd, 5 * hd))
        b = self.param("b", I.zeros, (5 * hd,))

        if self.reverse_h:
            x = x[:, ::-1]
        if self.reverse_w:
            x = x[:, :, ::-1]
        # precompute the input contribution for every cell in one matmul
        zx = jnp.einsum("bhwd,dk->bhwk", x, wx) + b

        def cell(h_up, c_up, h_left, c_left, z_in):
            z = z_in + h_up @ wh_up + h_left @ wh_left
            zi, zf1, zf2, zg, zo = jnp.split(z, 5, axis=-1)
            i = self.gate_act(zi)
            f_up = self.gate_act(zf1)
            f_left = self.gate_act(zf2)
            c = f_up * c_up + f_left * c_left + i * self.act(zg)
            h = self.gate_act(zo) * self.act(c)
            return h, c

        def row_step(carry_row, z_row):
            # carry_row: (h, c) of the row above, each [B, W, hd]
            h_above, c_above = carry_row

            def col_step(carry_col, inputs):
                h_left, c_left = carry_col
                z_in, h_up, c_up = inputs
                h, c = cell(h_up, c_up, h_left, c_left, z_in)
                return (h, c), (h, c)

            zeros = jnp.zeros((B, hd), zx.dtype)
            (_, _), (h_row, c_row) = jax.lax.scan(
                col_step, (zeros, zeros),
                (jnp.swapaxes(z_row, 0, 1),
                 jnp.swapaxes(h_above, 0, 1),
                 jnp.swapaxes(c_above, 0, 1)))
            h_row = jnp.swapaxes(h_row, 0, 1)     # [B, W, hd]
            c_row = jnp.swapaxes(c_row, 0, 1)
            return (h_row, c_row), h_row

        zeros_row = jnp.zeros((B, W, hd), zx.dtype)
        _, h_all = jax.lax.scan(row_step, (zeros_row, zeros_row),
                                jnp.swapaxes(zx, 0, 1))
        h = jnp.swapaxes(h_all, 0, 1)             # [B, H, W, hd]
        if self.reverse_h:
            h = h[:, ::-1]
        if self.reverse_w:
            h = h[:, :, ::-1]
        return h


class HierarchicalRNN(Module):
    """Two-level recurrence over nested sequences (reference: nested
    ``RecurrentGradientMachine`` — an outer recurrent group stepping over
    subsequences with an inner RNN per subsequence,
    ``gserver/gradientmachines/RecurrentGradientMachine.h:428``; equivalence
    fixture ``gserver/tests/sequence_nest_rnn.conf``).

    ``forward(data [B, S, T, D], sub_lengths [B, S], num_subseqs [B])``:
    the inner cell runs over each subsequence's tokens (state reset per
    subsequence — the nested frame boundary), its last state is the
    subsequence summary; the outer cell then runs over the S summaries.
    Returns ``(inner_out [B, S, T, Hi], outer_out [B, S, Ho])``. Inner runs
    batched over B*S (one scan, full MXU batch), outer over S.
    """

    def __init__(self, inner_cell, outer_cell, name=None):
        super().__init__(name=name)
        self.inner = RNN(inner_cell)
        self.outer = RNN(outer_cell)

    def forward(self, data, sub_lengths, num_subseqs):
        B, S, T = data.shape[:3]
        flat = data.reshape((B * S, T) + data.shape[3:])
        flat_len = sub_lengths.reshape(B * S)
        from ..core.sequence import length_mask
        inner_out, _ = self.inner(flat, mask=length_mask(flat_len, T))
        inner_out = inner_out.reshape((B, S, T) + inner_out.shape[2:])
        # subsequence summary = last valid inner state
        from .sequence_ops import sub_seq_last
        summaries = sub_seq_last(inner_out, sub_lengths)     # [B, S, Hi]
        outer_out, _ = self.outer(summaries,
                                  mask=length_mask(num_subseqs, S))
        return inner_out, outer_out
