"""Per-shape kernel autotuner with on-disk persistence (ISSUE 16).

The Pallas kernels' block sizes were hand-picked on one chip
(``_auto_block``'s v5e measurement); this module makes the selection
empirical and *remembered*: candidate configurations are timed once per
``(kernel, shape, dtype, platform)`` key and the winner lands in a JSON
cache file, so the second process ever to see a shape pays **zero
trials**. The same machinery hosts program-level entries — the serving
engine's warmup registers its prefill/tick timings under its shape key,
which is what lets a supervisor-restarted replica prove it came up warm
(0 trials, cache hit) instead of re-measuring.

Contract (the zero-overhead pin, PR-2/4 style):

- **Disabled by default.** With no cache directory configured —
  :func:`enable` not called and ``PADDLE_TPU_AUTOTUNE_CACHE`` unset —
  :func:`choose` returns the caller's default config untimed, with zero
  trials and zero disk I/O. Callers' dispatch behavior is byte-identical
  to the pre-autotune heuristic path.
- **Explicit overrides bypass everything.** A caller that passes
  explicit ``block_q``/``block_k`` never reaches :func:`choose` at all
  (the kernels resolve explicit blocks before consulting the tuner).
- **Corrupt caches degrade silently.** A truncated, unparseable, or
  schema-stale cache file reads as empty and the key re-tunes; the
  atomic-rename write (merge-with-disk, tmp + ``os.replace``, the
  ``save_variables_npz`` pattern) keeps the file a complete JSON
  document under concurrent writers — last writer wins per key, never a
  torn read. A cache is advice, not state: losing it costs trials, not
  correctness.

Trial timing goes through :func:`time_kernel`, which fences with
``jax.block_until_ready`` and discards the first (compile) iteration —
timing the enqueue or the compile instead of the kernel was the bug the
shared util exists to delete (bench.py's steady-state loops use it too).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

SCHEMA_VERSION = 1
ENV_VAR = "PADDLE_TPU_AUTOTUNE_CACHE"
CACHE_BASENAME = "autotune.json"

# tri-state: None = follow the environment variable; "" = forced off
# (disable() beats an inherited env var); non-empty = enable()'d dir
_dir_override: Optional[str] = None

_stats = {"trials": 0, "hits": 0, "misses": 0}

_AUTO = object()          # time_kernel fence sentinel: default jax fence


# -- enable / disable ------------------------------------------------------

def enable(cache_dir: str) -> None:
    """Turn autotuning on with ``cache_dir`` holding the JSON cache."""
    global _dir_override
    _dir_override = str(cache_dir)


def disable() -> None:
    """Force autotuning off (wins over the environment variable)."""
    global _dir_override
    _dir_override = ""


def reset() -> None:
    """Back to environment-variable control (test hygiene)."""
    global _dir_override
    _dir_override = None


def cache_dir() -> Optional[str]:
    """The active cache directory, or None when tuning is off."""
    if _dir_override is not None:
        return _dir_override or None
    return os.environ.get(ENV_VAR) or None


def is_enabled() -> bool:
    return cache_dir() is not None


def cache_file() -> Optional[str]:
    d = cache_dir()
    return os.path.join(d, CACHE_BASENAME) if d else None


# -- stats (the telemetry satellite reads these) ---------------------------

def stats() -> Dict[str, int]:
    """``{"trials", "hits", "misses"}`` counters for this process."""
    return dict(_stats)


def reset_stats() -> None:
    for k in _stats:
        _stats[k] = 0


# -- cache file ------------------------------------------------------------

def _load(path: str) -> Dict[str, Any]:
    """Read the cache's entries. Missing, unparseable, truncated, or
    schema-stale files all read as empty — the silent-re-tune rule."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(data, dict) or data.get("schema") != SCHEMA_VERSION:
        return {}
    entries = data.get("entries")
    return entries if isinstance(entries, dict) else {}


def _store(path: str, key: str, entry: Dict[str, Any]) -> None:
    """Merge ``{key: entry}`` with whatever is on disk and atomically
    replace the file. Two concurrent writers each produce a complete
    document; the loser's *other* keys survive in the winner's merge
    unless both tuned in the same instant — worst case a key re-tunes."""
    entries = _load(path)
    entries[key] = entry
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"schema": SCHEMA_VERSION, "entries": entries}, f,
                  indent=0, sort_keys=True)
    os.replace(tmp, path)


def make_key(kernel: str, *, shape: Sequence[int], dtype: Any,
             platform: Optional[str] = None,
             extra: Sequence[Any] = ()) -> str:
    """Canonical cache key: kernel name, operand shape, dtype, platform
    (the pluggable-backend seam — a CPU-tuned block is not a TPU-tuned
    block), plus kernel-specific flags (causal, segmented, ...)."""
    if platform is None:
        import jax
        platform = jax.default_backend()
    parts = [str(kernel), "x".join(str(int(s)) for s in shape),
             str(dtype), str(platform)]
    parts += [str(e) for e in extra]
    return "|".join(parts)


# -- timing ----------------------------------------------------------------

def time_kernel(fn: Callable[..., Any], *args, warmup: int = 1,
                iters: int = 1, fence: Any = _AUTO,
                **kwargs) -> Tuple[float, Any]:
    """Steady-state timing of ``fn(*args, **kwargs)``: run ``warmup``
    discarded iterations first (the first call pays tracing +
    compilation — including it was the classic autotune bug), then time
    ``iters`` iterations, fencing the last result so async dispatch
    can't make the enqueue look like the kernel. Returns
    ``(total_seconds, last_result)`` for the timed iterations.

    ``fence`` defaults to ``jax.block_until_ready``; pass ``fence=None``
    for callables that drain internally (``DecodeEngine.decode_tick``
    ends on a host ``np.asarray``)."""
    if fence is _AUTO:
        import jax
        fence = jax.block_until_ready
    out = None
    for _ in range(max(0, int(warmup))):
        out = fn(*args, **kwargs)
        if fence is not None:
            fence(out)
    iters = max(1, int(iters))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args, **kwargs)
    if fence is not None:
        fence(out)
    return time.perf_counter() - t0, out


# -- selection -------------------------------------------------------------

def choose(kernel: str, *, key: str,
           candidates: Sequence[Dict[str, Any]],
           runner: Callable[..., Any],
           default: Dict[str, Any]) -> Dict[str, Any]:
    """Pick a config for ``kernel`` at cache key ``key``.

    Disabled → ``default``, untimed, no I/O (the zero-overhead pin).
    Cache hit → the stored config, zero trials. Miss → every candidate
    runs once through :func:`time_kernel` via ``runner(**config)`` (one
    discarded compile iteration + one timed), the winner is persisted,
    and candidates that raise (mis-tiled on this backend) are skipped.
    If every candidate fails, ``default`` is returned and nothing is
    stored — a transient failure must not poison the cache."""
    if not is_enabled():
        return dict(default)
    path = cache_file()
    entry = _load(path).get(key)
    if isinstance(entry, dict) and isinstance(entry.get("config"), dict):
        _stats["hits"] += 1
        return dict(entry["config"])
    _stats["misses"] += 1
    best: Optional[Dict[str, Any]] = None
    best_t = float("inf")
    tried = 0
    for cand in (list(candidates) or [dict(default)]):
        try:
            t, _ = time_kernel(lambda: runner(**cand))
        except Exception:
            continue
        tried += 1
        _stats["trials"] += 1
        if t < best_t:
            best, best_t = dict(cand), t
    if best is None:
        return dict(default)
    _store(path, key, {"config": best, "best_s": best_t,
                       "trials": tried, "kernel": kernel})
    return best
