"""Activation functions.

The reference registers 17 activations by name
(``/root/reference/paddle/gserver/activations/ActivationFunction.cpp:97-472``):
sigmoid, softmax, sequence_softmax, relu, brelu, tanh, stanh, softrelu, abs,
square, exp, log, sqrt, reciprocal, softsign (+ identity/linear). All are pure
jnp functions here — XLA fuses them into adjacent matmuls on TPU, so there is no
kernel registry to mirror; the name->fn map keeps the reference's string-config
surface for the model-IR frontend.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["get", "ACTIVATIONS", "sequence_softmax"]


def identity(x):
    return x


def sigmoid(x):
    return jax.nn.sigmoid(x)


def softmax(x):
    return jax.nn.softmax(x, axis=-1)


def relu(x):
    return jax.nn.relu(x)


def brelu(x, t_min=0.0, t_max=24.0):
    # bounded relu (ActivationFunction.cpp BRelu: clip to [0, 24])
    return jnp.clip(x, t_min, t_max)


def tanh(x):
    return jnp.tanh(x)


def stanh(x, a=1.7159, b=2.0 / 3.0):
    # scaled tanh (LeCun): a * tanh(b * x)
    return a * jnp.tanh(b * x)


def softrelu(x, threshold=40.0):
    # log(1 + exp(x)) with overflow clamp, as the reference does
    return jnp.log1p(jnp.exp(jnp.clip(x, -threshold, threshold)))


def abs_act(x):
    return jnp.abs(x)


def square(x):
    return x * x


def exp(x):
    return jnp.exp(x)


def log_act(x):
    return jnp.log(x)


def sqrt_act(x):
    return jnp.sqrt(x)


def reciprocal(x):
    return 1.0 / x


def softsign(x):
    return x / (1.0 + jnp.abs(x))


def gelu(x):  # beyond the reference set; standard for transformer models
    return jax.nn.gelu(x)


def silu(x):
    return jax.nn.silu(x)


def leaky_relu(x, alpha=0.01):
    return jax.nn.leaky_relu(x, alpha)


def elu(x):
    return jax.nn.elu(x)


def sequence_softmax(x, lengths=None, mask=None):
    """Softmax over the time axis of [B, T] (or [B, T, 1]) scores honoring
    sequence validity — the reference's ``sequence_softmax`` activation
    (ActivationFunction.cpp SequenceSoftmax) used by attention weights."""
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    if squeeze:
        x = x[..., 0]
    if mask is None and lengths is not None:
        t = x.shape[1]
        mask = (jnp.arange(t)[None, :] < lengths[:, None]).astype(x.dtype)
    if mask is not None:
        x = jnp.where(mask > 0, x, -1e9)
    out = jax.nn.softmax(x, axis=1)
    if mask is not None:
        out = out * mask
        out = out / jnp.maximum(out.sum(axis=1, keepdims=True), 1e-9)
    return out[..., None] if squeeze else out


ACTIVATIONS = {
    "": identity,
    "linear": identity,
    "identity": identity,
    "sigmoid": sigmoid,
    "softmax": softmax,
    "relu": relu,
    "brelu": brelu,
    "tanh": tanh,
    "stanh": stanh,
    "softrelu": softrelu,
    "abs": abs_act,
    "square": square,
    "exp": exp,
    "log": log_act,
    "sqrt": sqrt_act,
    "reciprocal": reciprocal,
    "softsign": softsign,
    "gelu": gelu,
    "silu": silu,
    "leaky_relu": leaky_relu,
    "elu": elu,
}


def get(name):
    """Resolve an activation by name (the config-string surface) or pass through."""
    if callable(name):
        return name
    if name not in ACTIVATIONS:
        raise KeyError(f"unknown activation '{name}'; have {sorted(ACTIVATIONS)}")
    return ACTIVATIONS[name]
