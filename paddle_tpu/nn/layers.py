"""Core layer library — TPU-native equivalents of the reference's gserver layers.

The reference implements ~110 C++ ``Layer`` classes
(``/root/reference/paddle/gserver/layers/``; Python surface
``python/paddle/trainer_config_helpers/layers.py``). Here each layer is a thin
:class:`~paddle_tpu.core.module.Module` emitting jax.numpy/lax ops; XLA handles
fusion and MXU tiling, so layers carry no device-specific code (the analog of the
reference's CPU/GPU kernel pairs collapsing into one implementation).

Conventions:
  - Images are NHWC (TPU-native layout; the reference is NCHW — transposed at
    the data boundary). Conv kernels are HWIO.
  - Dense compute may run in bf16 per the active dtype policy; params stay f32.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core import initializers as I
from ..core.dtypes import current_policy
from ..core.module import Module, current_rng
from . import activations

__all__ = [
    "Linear", "Embedding", "Conv2D", "Conv2DTranspose", "DepthwiseConv2D",
    "Pool2D", "GlobalPool", "BatchNorm", "LayerNorm", "GroupNorm", "Dropout",
    "Maxout", "Bias", "ScaleShift", "CrossChannelNorm", "SpatialPyramidPool",
    "FeatureMapExpand", "BlockExpand", "Interpolation", "Multiplex", "RowL2Norm",
    "SumToOneNorm", "DataNorm", "L2Distance", "CosSim", "OuterProd", "ConvShift",
    "SlopeIntercept", "Pad2D", "Crop2D", "Resize", "Rotate", "Addto", "Concat",
    "MixedLayer", "FullMatrixProjection", "TableProjection", "IdentityProjection",
    "DotMulProjection", "ContextProjection", "CrossMapNormal", "RowConv",
    "Conv3D", "Conv3DTranspose", "Pool3D", "SelectiveFC", "SamplingId",
    "ScaleSubRegion", "Power", "Scaling", "DotProd", "ConvexCombination",
    "CosSimVecMat", "BilinearInterp", "EosIdCheck", "PRelu",
    "ScalingProjection", "SliceProjection", "TransposedFullMatrixProjection",
    "SwitchOrder", "MaxPoolWithMask",
]

Pair = Union[int, Tuple[int, int]]


def _pair(v: Pair) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def _conv_padding(padding):
    """Normalize padding: "SAME"/"VALID", int p, (pad_h, pad_w), or explicit
    [(lo,hi),(lo,hi)] — matching the kernel/stride (h, w) convention."""
    if isinstance(padding, str):
        return padding
    if isinstance(padding, int):
        return [(padding, padding), (padding, padding)]
    padding = list(padding)
    if all(isinstance(p, (list, tuple)) for p in padding):
        return [tuple(p) for p in padding]
    ph, pw = padding
    return [(ph, ph), (pw, pw)]


class Linear(Module):
    """Fully-connected layer (reference: ``FullyConnectedLayer``,
    ``gserver/layers/FullyConnectedLayer.cpp``; fluid ``mul_op`` + bias)."""

    def __init__(self, features: int, act="", use_bias: bool = True,
                 w_init=I.fan_in_uniform, b_init=I.zeros, name=None):
        super().__init__(name=name)
        self.features = features
        self.act = activations.get(act)
        self.use_bias = use_bias
        self.w_init = w_init
        self.b_init = b_init

    def forward(self, x):
        pol = current_policy()
        w = self.param("w", self.w_init, (x.shape[-1], self.features))
        y = jnp.dot(pol.cast_compute(x), pol.cast_compute(w),
                    preferred_element_type=pol.accum_dtype)
        if self.use_bias:
            b = self.param("b", self.b_init, (self.features,))
            y = y + b
        return self.act(y)


class Embedding(Module):
    """Embedding lookup (reference: ``TableProjection``,
    ``gserver/layers/TableProjection.cpp``; fluid ``lookup_table_op``).
    ``ids`` may be any-int shape; output appends the embedding dim.
    Out-of-range ids (e.g. padding = -1) return zeros."""

    def __init__(self, vocab: int, dim: int, w_init=None, name=None):
        super().__init__(name=name)
        self.vocab = vocab
        self.dim = dim
        self.w_init = w_init or I.normal(1.0 / np.sqrt(dim))

    def table(self):
        """Fetch the table from within this module's own scope (callable from a
        parent's forward — pushes this module's path so the param is shared
        with lookups, enabling tied softmax weights)."""
        with self.scope():
            return self.param("w", self.w_init, (self.vocab, self.dim))

    def forward(self, ids):
        w = self.param("w", self.w_init, (self.vocab, self.dim))
        valid = (ids >= 0) & (ids < self.vocab)
        safe = jnp.clip(ids, 0, self.vocab - 1)
        out = jnp.take(w, safe, axis=0)
        return out * valid[..., None].astype(out.dtype)

    def attend(self, x):
        """Project activations back onto the table (tied softmax weights)."""
        return jnp.dot(x, self.table().T)


# How 1x1 convs lower: "conv" = lax.conv_general_dilated; "matmul" =
# reshape + dot (XLA's matmul path — different tiling than its conv path);
# "pallas" = matmul forward + Pallas dW reduction kernel
# (nn/pallas_conv.py). Measured per-shape in experiments/conv1x1_backward.py.
_CONV1X1_IMPL = "conv"


def set_conv1x1_impl(impl: str) -> str:
    """Select the 1x1-conv lowering globally; returns the previous value.

    TRACE-TIME semantics: the global is read when a step is traced, and jit
    caches do NOT key on it — any function already jitted keeps the lowering
    it was traced with. Call this BEFORE building/jitting the step (the bench
    children set it via ``BENCH_CONV1X1_IMPL`` at process start); toggling
    after compilation silently has no effect on cached executables."""
    global _CONV1X1_IMPL
    assert impl in ("conv", "matmul", "pallas"), impl
    prev, _CONV1X1_IMPL = _CONV1X1_IMPL, impl
    return prev


class Conv2D(Module):
    """2-D convolution, NHWC/HWIO (reference: ``ExpandConvLayer`` /
    ``CudnnConvLayer``, ``gserver/layers/ExpandConvLayer.cpp``; function-layer
    ``GemmConvOp``). XLA lowers this onto the MXU directly; 1x1 convs can
    route through the matmul/Pallas path (:func:`set_conv1x1_impl`)."""

    def __init__(self, features: int, kernel: Pair, stride: Pair = 1,
                 padding="SAME", dilation: Pair = 1, groups: int = 1, act="",
                 use_bias: bool = True, w_init=I.msra_normal, name=None):
        super().__init__(name=name)
        self.features = features
        self.kernel = _pair(kernel)
        self.stride = _pair(stride)
        self.padding = _conv_padding(padding)
        self.dilation = _pair(dilation)
        self.groups = groups
        self.act = activations.get(act)
        self.use_bias = use_bias
        self.w_init = w_init

    def forward(self, x):
        pol = current_policy()
        kh, kw = self.kernel
        cin = x.shape[-1]
        w = self.param("w", self.w_init,
                       (kh, kw, cin // self.groups, self.features))
        # Output stays in compute dtype (the MXU accumulates f32 internally
        # for bf16 operands); upcasting via preferred_element_type would break
        # the conv rhs-transpose rule, which requires operand dtypes to match.
        # for a 1x1 kernel SAME == VALID == zero padding; only explicit
        # nonzero padding keeps the conv path
        pad_free = (self.padding in ("SAME", "VALID")
                    or all(p == (0, 0) for p in self.padding))
        if ((kh, kw) == (1, 1) and self.dilation == (1, 1)
                and self.groups == 1 and pad_free
                and _CONV1X1_IMPL != "conv"):
            from . import pallas_conv
            xc = pol.cast_compute(x)
            wc = pol.cast_compute(w).reshape(cin, self.features)
            if _CONV1X1_IMPL == "pallas":
                y = pallas_conv.conv1x1_strided(xc, wc, self.stride)
            else:
                sh, sw = self.stride
                if (sh, sw) != (1, 1):
                    xc = xc[:, ::sh, ::sw, :]
                b_, h_, w_, _ = xc.shape
                y = (xc.reshape(b_ * h_ * w_, cin) @ wc).reshape(
                    b_, h_, w_, self.features)
        elif ((kh, kw) == (7, 7) and self.stride == (2, 2)
                and self.padding == "SAME" and self.dilation == (1, 1)
                and self.groups == 1 and cin <= 4
                and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0):
            # Tiny-C_in strided stem (the classic 7x7/2 ImageNet stem): the
            # MXU pads 3 input channels to a full tile and runs at ~12%.
            # EXACT space-to-depth rewrite (input 2x2 patches -> channels,
            # end-zero-padded weights re-indexed w2[a,b,(dy,dx,c)] =
            # w[2a+dy, 2b+dx, c], conv 4x4/1 pad (1,2)): same math to f32
            # roundoff, 1.9x faster measured (experiments/PERF.md "Round
            # 5: 3x3 campaign"; the MLPerf-ResNet TPU trick, done
            # weight-compatibly).
            xc, wc = pol.cast_compute(x), pol.cast_compute(w)
            n, h, ww_, c = xc.shape
            x2 = xc.reshape(n, h // 2, 2, ww_ // 2, 2, c)
            x2 = x2.transpose(0, 1, 3, 2, 4, 5).reshape(
                n, h // 2, ww_ // 2, 4 * c)
            wp = jnp.pad(wc, ((0, 1), (0, 1), (0, 0), (0, 0)))
            w2 = wp.reshape(4, 2, 4, 2, c, self.features)
            w2 = w2.transpose(0, 2, 1, 3, 4, 5).reshape(
                4, 4, 4 * c, self.features)
            y = lax.conv_general_dilated(
                x2, w2, window_strides=(1, 1), padding=[(1, 2), (1, 2)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        else:
            y = lax.conv_general_dilated(
                pol.cast_compute(x), pol.cast_compute(w),
                window_strides=self.stride, padding=self.padding,
                rhs_dilation=self.dilation, feature_group_count=self.groups,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            y = y + self.param("b", I.zeros, (self.features,)).astype(y.dtype)
        return self.act(y)


class DepthwiseConv2D(Conv2D):
    """Depthwise conv (reference: ``DepthwiseConvOp``, function layer)."""

    def __init__(self, multiplier: int, kernel: Pair, stride: Pair = 1,
                 padding="SAME", act="", use_bias=True, name=None):
        # features resolved at call time: cin * multiplier, groups = cin
        super().__init__(features=multiplier, kernel=kernel, stride=stride,
                         padding=padding, act=act, use_bias=use_bias, name=name)
        self.multiplier = multiplier

    def forward(self, x):
        pol = current_policy()
        kh, kw = self.kernel
        cin = x.shape[-1]
        features = cin * self.multiplier
        w = self.param("w", self.w_init, (kh, kw, 1, features))
        y = lax.conv_general_dilated(
            pol.cast_compute(x), pol.cast_compute(w),
            window_strides=self.stride, padding=self.padding,
            rhs_dilation=self.dilation, feature_group_count=cin,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            y = y + self.param("b", I.zeros, (features,)).astype(y.dtype)
        return self.act(y)


class Conv2DTranspose(Module):
    """Transposed conv (reference: ``ExpandConvTransLayer``, ``DeConv3DLayer``)."""

    def __init__(self, features: int, kernel: Pair, stride: Pair = 1,
                 padding="SAME", act="", use_bias=True,
                 w_init=I.msra_normal, name=None):
        super().__init__(name=name)
        self.features = features
        self.kernel = _pair(kernel)
        self.stride = _pair(stride)
        self.padding = _conv_padding(padding)
        self.act = activations.get(act)
        self.use_bias = use_bias
        self.w_init = w_init

    def forward(self, x):
        pol = current_policy()
        kh, kw = self.kernel
        w = self.param("w", self.w_init, (kh, kw, x.shape[-1], self.features))
        y = lax.conv_transpose(
            pol.cast_compute(x), pol.cast_compute(w),
            strides=self.stride, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            y = y + self.param("b", I.zeros, (self.features,))
        return self.act(pol.cast_accum(y))


class Pool2D(Module):
    """Max/avg pooling (reference: ``PoolLayer``/``CudnnPoolLayer``,
    ``gserver/layers/PoolLayer.cpp``; function ``Pool2DOp``)."""

    def __init__(self, kind: str, window: Pair, stride: Optional[Pair] = None,
                 padding="VALID", name=None):
        super().__init__(name=name)
        assert kind in ("max", "avg")
        self.kind = kind
        self.window = _pair(window)
        self.stride = _pair(stride if stride is not None else window)
        self.padding = padding

    def forward(self, x):
        wh, ww = self.window
        sh, sw = self.stride
        dims = (1, wh, ww, 1)
        strides = (1, sh, sw, 1)
        if self.kind == "max":
            return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides,
                                     self.padding)
        s = lax.reduce_window(x, 0.0, lax.add, dims, strides, self.padding)
        if self.padding == "VALID":
            return s / (wh * ww)
        ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
        cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides, self.padding)
        return s / jnp.maximum(cnt, 1.0)


class GlobalPool(Module):
    """Global spatial pooling to [N, C]."""

    def __init__(self, kind: str = "avg", name=None):
        super().__init__(name=name)
        assert kind in ("max", "avg"), kind
        self.kind = kind

    def forward(self, x):
        return (jnp.max if self.kind == "max" else jnp.mean)(x, axis=(1, 2))


@jax.custom_vjp
def _bn_train_norm(x, mean, inv, gamma, beta):
    """Training-mode BN normalization with a hand-written VJP.

    The autodiff backward of the mean/var formulation emits 3-4 reductions
    over the activation per BN layer; the closed-form BN backward needs
    exactly two (sum(dy), sum(dy*xhat)) plus one elementwise pass:

        dx = gamma*inv * (dy - sum(dy)/n - xhat*sum(dy*xhat)/n)

    This is the *total* derivative (the mean/inv dependence on x is folded
    in), so the bwd returns zero cotangents for mean/inv and the upstream
    stats-backward graph dead-code-eliminates. (On the ResNet-50 step XLA's
    fusion already absorbed most of the difference — measured perf-neutral,
    experiments/ round 3 — but the backward HLO is structurally minimal and
    numerically pinned by test_batchnorm_custom_vjp_matches_autodiff.) Do
    not differentiate through mean/inv from elsewhere — they are treated as
    x-derived here.
    """
    xhat = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
    return xhat * gamma.astype(x.dtype) + beta.astype(x.dtype)


def _bn_train_norm_fwd(x, mean, inv, gamma, beta):
    return _bn_train_norm(x, mean, inv, gamma, beta), (x, mean, inv, gamma)


def _bn_train_norm_bwd(res, dy):
    x, mean, inv, gamma = res
    axes = tuple(range(x.ndim - 1))
    n = x.size // x.shape[-1]
    xhat = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
    dbeta = jnp.sum(dy, axis=axes, dtype=jnp.float32)
    dgamma = jnp.sum(dy * xhat, axis=axes, dtype=jnp.float32)
    scale = (gamma * inv).astype(x.dtype)
    dx = scale * (dy - (dbeta / n).astype(x.dtype)
                  - xhat * (dgamma / n).astype(x.dtype))
    return (dx, jnp.zeros_like(mean), jnp.zeros_like(inv),
            dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype))


_bn_train_norm.defvjp(_bn_train_norm_fwd, _bn_train_norm_bwd)


class BatchNorm(Module):
    """Batch normalization with running stats (reference:
    ``BatchNormalizationLayer``/``CudnnBatchNormLayer``,
    ``gserver/layers/BatchNormalizationLayer.cpp``; running mean/var kept as
    non-trainable state, the analog of PARAMETER_VALUE-typed stat buffers)."""

    def __init__(self, momentum: float = 0.9, eps: float = 1e-5,
                 use_scale_shift: bool = True, name=None):
        super().__init__(name=name)
        self.momentum = momentum
        self.eps = eps
        self.use_scale_shift = use_scale_shift

    def forward(self, x, train: bool = False):
        c = x.shape[-1]
        axes = tuple(range(x.ndim - 1))
        mean_s = self.state("mean", I.zeros, (c,))
        var_s = self.state("var", I.ones, (c,))
        # Moment statistics in float32 regardless of the compute policy
        # (bf16 batch moments are too coarse); the normalization itself runs
        # in the activation dtype — see below. Moments use the one-pass
        # E[x^2]-E[x]^2 form: sum and sum-of-squares are independent
        # reductions XLA multi-output-fuses into a single read of x, where
        # mean-then-var would read the activation twice (measured ~2x BN
        # stat cost on the ResNet-50 step, experiments/profile_resnet50.py).
        xf = x.astype(jnp.float32)
        if train:
            n = x.size // c
            s1 = jnp.sum(xf, axis=axes)
            s2 = jnp.sum(xf * xf, axis=axes)
            mean = s1 / n
            var = jnp.maximum(s2 / n - mean * mean, 0.0)
            m = self.momentum
            self.update_state("mean", m * mean_s + (1 - m) * mean)
            self.update_state("var", m * var_s + (1 - m) * var)
        else:
            mean, var = mean_s, var_s
        # Normalization itself rides the activation dtype (halves the HBM
        # traffic of the fused elementwise under bf16); only the moment
        # reductions above need f32.
        inv = lax.rsqrt(var + self.eps)
        if train and self.use_scale_shift:
            # custom-VJP path: closed-form BN backward (2 reductions
            # instead of autodiff's 3-4 — see _bn_train_norm)
            return _bn_train_norm(x, mean, inv,
                                  self.param("scale", I.ones, (c,)),
                                  self.param("shift", I.zeros, (c,)))
        y = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
        if self.use_scale_shift:
            y = y * self.param("scale", I.ones, (c,)).astype(x.dtype) + \
                self.param("shift", I.zeros, (c,)).astype(x.dtype)
        return y


class LayerNorm(Module):
    """Layer normalization (beyond the reference's set; required by the modern
    attention stack — SURVEY.md §5 notes transformer-era additions)."""

    def __init__(self, eps: float = 1e-6, use_scale: bool = True,
                 use_bias: bool = True, name=None):
        super().__init__(name=name)
        self.eps = eps
        self.use_scale = use_scale
        self.use_bias = use_bias

    def forward(self, x):
        dtype = x.dtype
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - mean) * lax.rsqrt(var + self.eps)
        c = x.shape[-1]
        if self.use_scale:
            y = y * self.param("scale", I.ones, (c,))
        if self.use_bias:
            y = y + self.param("bias", I.zeros, (c,))
        return y.astype(dtype)


class GroupNorm(Module):
    def __init__(self, groups: int = 32, eps: float = 1e-5, name=None):
        super().__init__(name=name)
        self.groups = groups
        self.eps = eps

    def forward(self, x):
        c = x.shape[-1]
        g = min(self.groups, c)
        if c % g:
            raise ValueError(f"GroupNorm: {c} channels not divisible by "
                             f"{g} groups")
        shape = x.shape[:-1] + (g, c // g)
        xg = x.reshape(shape)
        axes = tuple(range(1, x.ndim - 1)) + (x.ndim,)
        mean = jnp.mean(xg, axis=axes, keepdims=True)
        var = jnp.var(xg, axis=axes, keepdims=True)
        y = ((xg - mean) * lax.rsqrt(var + self.eps)).reshape(x.shape)
        return y * self.param("scale", I.ones, (c,)) + \
            self.param("bias", I.zeros, (c,))


class Dropout(Module):
    """Inverted dropout (reference: ``drop_rate`` layer attr applied via
    ``Layer::forwardDropOut``, ``gserver/layers/Layer.cpp``)."""

    def __init__(self, rate: float, name=None):
        super().__init__(name=name)
        self.rate = rate

    def forward(self, x, train: bool = False):
        if not train or self.rate <= 0.0:
            return x
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(current_rng("dropout"), keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)


class Maxout(Module):
    """Maxout over channel groups (reference: ``MaxOutLayer``)."""

    def __init__(self, groups: int, name=None):
        super().__init__(name=name)
        self.groups = groups

    def forward(self, x):
        c = x.shape[-1]
        return jnp.max(x.reshape(x.shape[:-1] + (c // self.groups, self.groups)),
                       axis=-1)


class Bias(Module):
    """Standalone bias (reference: ``BiasLayer`` / shared biases)."""

    def forward(self, x):
        return x + self.param("b", I.zeros, (x.shape[-1],))


class ScaleShift(Module):
    """Per-channel learned scale+shift (reference: ``ScaleShiftLayer``)."""

    def forward(self, x):
        return x * self.param("scale", I.ones, (x.shape[-1],)) + \
            self.param("shift", I.zeros, (x.shape[-1],))


class CrossChannelNorm(Module):
    """L2 norm across channels with learned per-channel scale
    (reference: ``CrossChannelNormLayer``, SSD's Norm layer)."""

    def forward(self, x):
        scale = self.param("scale", I.constant(20.0), (x.shape[-1],))
        norm = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True) + 1e-12)
        return x / norm * scale


class SpatialPyramidPool(Module):
    """SPP (reference: ``SpatialPyramidPoolLayer.cpp``) — concat of pyramid
    max-pools to a fixed-size vector regardless of input HW."""

    def __init__(self, levels: int = 3, kind: str = "max", name=None):
        super().__init__(name=name)
        self.levels = levels
        self.kind = kind

    def forward(self, x):
        n, h, w, c = x.shape
        outs = []
        for lvl in range(self.levels):
            bins = 2 ** lvl
            # Static pyramid: split into bins x bins cells (requires h, w >= bins)
            hs = [h * i // bins for i in range(bins + 1)]
            ws = [w * i // bins for i in range(bins + 1)]
            for i in range(bins):
                for j in range(bins):
                    cell = x[:, hs[i]:hs[i + 1], ws[j]:ws[j + 1], :]
                    red = jnp.max if self.kind == "max" else jnp.mean
                    outs.append(red(cell, axis=(1, 2)))
        return jnp.concatenate(outs, axis=-1)


class FeatureMapExpand(Module):
    """Expand [N, C] vector across spatial dims of a reference map
    (reference: ``FeatureMapExpandLayer``)."""

    def __init__(self, as_map_of=None, name=None):
        super().__init__(name=name)

    def forward(self, x, like):
        return jnp.broadcast_to(x[:, None, None, :],
                                like.shape[:3] + (x.shape[-1],))


class BlockExpand(Module):
    """im2col as a layer (reference: ``BlockExpandLayer`` — conv patches to
    sequence, used for OCR)."""

    def __init__(self, block: Pair, stride: Pair, padding="VALID", name=None):
        super().__init__(name=name)
        self.block = _pair(block)
        self.stride = _pair(stride)
        self.padding = padding

    def forward(self, x):
        bh, bw = self.block
        patches = lax.conv_general_dilated_patches(
            x, filter_shape=(bh, bw), window_strides=self.stride,
            padding=self.padding, dimension_numbers=("NHWC", "HWIO", "NHWC"))
        n, oh, ow, d = patches.shape
        return patches.reshape(n, oh * ow, d)


class Interpolation(Module):
    """out = w*a + (1-w)*b with per-sample weight (reference:
    ``InterpolationLayer``)."""

    def forward(self, w, a, b):
        w = w.reshape(w.shape[0], *([1] * (a.ndim - 1)))
        return w * a + (1.0 - w) * b


class Multiplex(Module):
    """Row-wise select among K inputs by index (reference: ``MultiplexLayer``)."""

    def forward(self, index, *xs):
        stacked = jnp.stack(xs, axis=0)          # [K, N, ...]
        return jnp.take_along_axis(
            stacked, index.reshape(1, -1, *([1] * (stacked.ndim - 2))),
            axis=0)[0]


class RowL2Norm(Module):
    """Row-wise L2 normalize (reference: ``RowL2NormLayer``)."""

    def forward(self, x):
        return x / jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True) + 1e-12)


class SumToOneNorm(Module):
    """Row-wise sum-to-one normalize (reference: ``SumToOneNormLayer``)."""

    def forward(self, x):
        return x / jnp.maximum(jnp.sum(x, axis=-1, keepdims=True), 1e-12)


class DataNorm(Module):
    """Input feature normalization from precomputed stats (reference:
    ``DataNormLayer`` — z-score / min-max / decimal scaling)."""

    def __init__(self, strategy: str = "z-score", name=None):
        super().__init__(name=name)
        self.strategy = strategy

    def forward(self, x):
        c = x.shape[-1]
        if self.strategy == "z-score":
            mean = self.state("mean", I.zeros, (c,))
            std = self.state("std", I.ones, (c,))
            return (x - mean) / jnp.maximum(std, 1e-12)
        if self.strategy == "min-max":
            mn = self.state("min", I.zeros, (c,))
            mx = self.state("max", I.ones, (c,))
            return (x - mn) / jnp.maximum(mx - mn, 1e-12)
        raise ValueError(self.strategy)


class L2Distance(Module):
    """Row-wise L2 distance between two inputs (reference: ``L2DistanceLayer``)."""

    def forward(self, a, b):
        return jnp.sqrt(jnp.sum((a - b) ** 2, axis=-1, keepdims=True) + 1e-12)


class CosSim(Module):
    """Row-wise cosine similarity * scale (reference: ``CosSimLayer``,
    function ``CosSimOp``)."""

    def __init__(self, scale: float = 1.0, name=None):
        super().__init__(name=name)
        self.scale = scale

    def forward(self, a, b):
        na = jnp.sqrt(jnp.sum(a * a, axis=-1) + 1e-12)
        nb = jnp.sqrt(jnp.sum(b * b, axis=-1) + 1e-12)
        return (self.scale * jnp.sum(a * b, axis=-1) / (na * nb))[..., None]


class OuterProd(Module):
    """Row-wise outer product flattened (reference: ``OuterProdLayer``)."""

    def forward(self, a, b):
        return (a[:, :, None] * b[:, None, :]).reshape(a.shape[0], -1)


class ConvShift(Module):
    """Circular 1-D correlation of rows (reference: ``ConvShiftLayer`` — NTM
    shift addressing)."""

    def forward(self, a, b):
        n, m = a.shape
        k = b.shape[-1]
        half = k // 2
        idx = (jnp.arange(m)[:, None] + jnp.arange(-half, k - half)[None, :]) % m
        gathered = a[:, idx]                     # [N, M, K]
        return jnp.einsum("nmk,nk->nm", gathered, b)


class SlopeIntercept(Module):
    """y = slope*x + intercept, fixed scalars (reference:
    ``SlopeInterceptLayer``)."""

    def __init__(self, slope: float = 1.0, intercept: float = 0.0, name=None):
        super().__init__(name=name)
        self.slope = slope
        self.intercept = intercept

    def forward(self, x):
        return self.slope * x + self.intercept


class Pad2D(Module):
    """Zero-pad NHWC (reference: ``PadLayer``, function ``PadOp``)."""

    def __init__(self, pad: Sequence[int], name=None):
        super().__init__(name=name)
        self.pad = pad  # (top, bottom, left, right)

    def forward(self, x):
        t, b, l, r = self.pad
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)))


class Crop2D(Module):
    """Static crop NHWC (reference: ``CropLayer``, function ``CropOp``)."""

    def __init__(self, offset: Tuple[int, int], size: Tuple[int, int], name=None):
        super().__init__(name=name)
        self.offset = offset
        self.size = size

    def forward(self, x):
        (oh, ow), (h, w) = self.offset, self.size
        return x[:, oh:oh + h, ow:ow + w, :]


class Resize(Module):
    """Reshape rows to a new width (reference: ``ResizeLayer``)."""

    def __init__(self, size: int, name=None):
        super().__init__(name=name)
        self.size = size

    def forward(self, x):
        return x.reshape(-1, self.size)


class Rotate(Module):
    """Rotate feature maps 90° (reference: ``RotateLayer``)."""

    def forward(self, x):
        return jnp.rot90(x, k=1, axes=(1, 2))


class Addto(Module):
    """Elementwise sum of inputs + optional bias/activation (reference:
    ``AddtoLayer``)."""

    def __init__(self, act="", use_bias: bool = False, name=None):
        super().__init__(name=name)
        self.act = activations.get(act)
        self.use_bias = use_bias

    def forward(self, *xs):
        y = xs[0]
        for x in xs[1:]:
            y = y + x
        if self.use_bias:
            y = y + self.param("b", I.zeros, (y.shape[-1],))
        return self.act(y)


class Concat(Module):
    """Feature concat (reference: ``ConcatenateLayer``)."""

    def __init__(self, axis: int = -1, act="", name=None):
        super().__init__(name=name)
        self.axis = axis
        self.act = activations.get(act)

    def forward(self, *xs):
        return self.act(jnp.concatenate(xs, axis=self.axis))


# ---------------------------------------------------------------------------
# MixedLayer & projections — the reference's composable projection system
# (``gserver/layers/MixedLayer.cpp`` + projections; config surface
# ``trainer_config_helpers/layers.py mixed_layer``). A MixedLayer sums the
# outputs of K projections, then bias + activation.
# ---------------------------------------------------------------------------

class FullMatrixProjection(Module):
    """Dense projection (reference: ``FullMatrixProjection.cpp``)."""

    def __init__(self, features: int, w_init=I.fan_in_uniform, name=None):
        super().__init__(name=name)
        self.features = features
        self.w_init = w_init

    def forward(self, x):
        w = self.param("w", self.w_init, (x.shape[-1], self.features))
        return jnp.dot(x, w)


class TableProjection(Module):
    """Embedding projection (reference: ``TableProjection.cpp``)."""

    def __init__(self, vocab: int, dim: int, name=None):
        super().__init__(name=name)
        self.emb = Embedding(vocab, dim, name="table")

    def forward(self, ids):
        return self.emb(ids)


class IdentityProjection(Module):
    """Identity / scaled identity (reference: ``IdentityProjection.cpp``)."""

    def __init__(self, scale: float = 1.0, offset: int = 0, size=None, name=None):
        super().__init__(name=name)
        self.scale = scale
        self.offset = offset
        self.size = size

    def forward(self, x):
        if self.size is not None:
            x = x[..., self.offset:self.offset + self.size]
        return self.scale * x


class DotMulProjection(Module):
    """Elementwise learned-weight product (reference: ``DotMulProjection.cpp``)."""

    def forward(self, x):
        w = self.param("w", I.uniform(1.0), (x.shape[-1],))
        return x * w


class ContextProjection(Module):
    """Sliding context window concat over time (reference:
    ``ContextProjection.cpp``; function ``ContextProjectionOp``) — concatenates
    [t+start, t+start+len) frames per step; out-of-range frames are zero (or
    trainable boundary vectors when ``trainable_pads``)."""

    def __init__(self, context_len: int, context_start: Optional[int] = None,
                 trainable_pads: bool = False, name=None):
        super().__init__(name=name)
        self.len = context_len
        self.start = -(context_len // 2) if context_start is None else context_start
        self.trainable_pads = trainable_pads

    def forward(self, x):  # x: [B, T, D]
        b, t, d = x.shape
        n_left = max(-self.start, 0)
        n_right = max(self.start + self.len - 1, 0)
        idx = jnp.arange(t)
        cols = []
        for k in range(self.len):
            off = self.start + k
            shifted = jnp.roll(x, -off, axis=1)
            valid = ((idx + off >= 0) & (idx + off < t))[None, :, None]
            if self.trainable_pads and off < 0:
                # missing frame t+off ∈ [-n_left, -1] maps to begin-pad row
                # n_left + (t+off), varying per timestep (reference:
                # ContextProjection begin_pad semantics).
                rows = jnp.clip(n_left + idx + off, 0, n_left - 1)
                fill = self.param("pad_l", I.zeros, (n_left, d))[rows]
                cols.append(jnp.where(valid, shifted, fill[None, :, :]))
            elif self.trainable_pads and off > 0:
                # missing frame t+off ∈ [T, T+n_right-1] maps to end-pad row
                # t+off-T, varying per timestep.
                rows = jnp.clip(idx + off - t, 0, n_right - 1)
                fill = self.param("pad_r", I.zeros, (n_right, d))[rows]
                cols.append(jnp.where(valid, shifted, fill[None, :, :]))
            else:
                cols.append(jnp.where(valid, shifted, 0.0))
        return jnp.concatenate(cols, axis=-1)


class MixedLayer(Module):
    """Sum of projections + bias + activation (reference: ``MixedLayer.cpp``)."""

    def __init__(self, projections: Sequence[Module], act="", use_bias=True,
                 name=None):
        super().__init__(name=name)
        self.projections = list(projections)
        self.act = activations.get(act)
        self.use_bias = use_bias

    def forward(self, *inputs):
        assert len(inputs) == len(self.projections)
        y = None
        for proj, x in zip(self.projections, inputs):
            o = proj(x)
            y = o if y is None else y + o
        if self.use_bias:
            y = y + self.param("b", I.zeros, (y.shape[-1],))
        return self.act(y)


class CrossMapNormal(Module):
    """Local response normalisation across channel maps (reference:
    ``function/CrossMapNormalOp.cpp`` — ``f(x) = x * (1 + scale *
    SUM_window(x^2))^(-pow)`` with the window of ``size`` maps centred at
    each channel; layer wrapper ``CMRProjectionNormLayer``). NHWC.

    The config-helper surface (``img_cmrnorm_layer``) passes
    ``scale = alpha / size``; this module takes ``scale``/``power`` directly
    like the function layer does.
    """

    def __init__(self, size: int = 5, scale: float = 0.0001,
                 power: float = 0.75, name=None):
        super().__init__(name=name)
        self.size = size
        self.scale = scale
        self.power = power

    def forward(self, x):
        half = (self.size - 1) // 2
        sq = x * x
        # sum over a channel window: pad C then window-sum via cumsum diff
        pad = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) +
                      [(half, self.size - 1 - half)])
        csum = jnp.cumsum(pad, axis=-1)
        csum = jnp.pad(csum, [(0, 0)] * (x.ndim - 1) + [(1, 0)])
        win = csum[..., self.size:] - csum[..., :-self.size]
        denom = (1.0 + self.scale * win) ** (-self.power)
        return x * denom


class RowConv(Module):
    """Lookahead row convolution over packed sequences (reference:
    ``function/RowConvOp.cpp`` — ``out[t] = sum_k filter[k] * in[t+k]``
    elementwise per feature, truncated at each sequence end; from the
    DeepSpeech2 architecture).

    ``forward(x [B, T, D], lengths [B])``; context rows beyond a sequence's
    length contribute zero, matching the reference's per-sequence truncation.
    """

    def __init__(self, context: int, w_init=I.zeros, name=None):
        super().__init__(name=name)
        self.context = context
        self.w_init = w_init

    def forward(self, x, lengths=None):
        B, T, D = x.shape
        if lengths is None:
            lengths = jnp.full((B,), T)
        w = self.param("w", self.w_init, (self.context, D))
        idx = jnp.arange(T)
        out = jnp.zeros_like(x)
        for k in range(self.context):
            shifted = jnp.roll(x, -k, axis=1)
            valid = (idx + k < lengths[:, None])[..., None]
            out = out + jnp.where(valid, shifted, 0.0) * w[k]
        return out


class Conv3D(Module):
    """3-D convolution, NDHWC/DHWIO (reference: ``Conv3DLayer.cpp``). One
    ``lax.conv_general_dilated`` call — XLA tiles it onto the MXU the same
    way as 2-D convs."""

    def __init__(self, features: int, kernel, stride=1, padding="SAME",
                 act="", use_bias=True, w_init=I.fan_in_uniform,
                 b_init=I.zeros, name=None):
        super().__init__(name=name)
        self.features = features
        self.kernel = (kernel,) * 3 if isinstance(kernel, int) else tuple(kernel)
        self.stride = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
        self.padding = padding
        self.act = activations.get(act)
        self.use_bias = use_bias
        self.w_init = w_init
        self.b_init = b_init

    def forward(self, x):
        pol = current_policy()
        w = self.param("w", self.w_init,
                       self.kernel + (x.shape[-1], self.features))
        pad = self.padding
        if isinstance(pad, int):
            pad = [(pad, pad)] * 3
        # No preferred_element_type on convs: the rhs-transpose rule in the
        # conv gradient requires operand dtypes to match (same constraint as
        # Conv2D above).
        y = lax.conv_general_dilated(
            pol.cast_compute(x), pol.cast_compute(w),
            window_strides=self.stride, padding=pad,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.use_bias:
            y = y + self.param("b", self.b_init,
                               (self.features,)).astype(y.dtype)
        return self.act(y)


class Conv3DTranspose(Module):
    """3-D transposed convolution (reference: ``DeConv3DLayer.cpp``)."""

    def __init__(self, features: int, kernel, stride=1, padding="SAME",
                 act="", use_bias=True, w_init=I.fan_in_uniform,
                 b_init=I.zeros, name=None):
        super().__init__(name=name)
        self.features = features
        self.kernel = (kernel,) * 3 if isinstance(kernel, int) else tuple(kernel)
        self.stride = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
        self.padding = padding
        self.act = activations.get(act)
        self.use_bias = use_bias
        self.w_init = w_init
        self.b_init = b_init

    def forward(self, x):
        pol = current_policy()
        w = self.param("w", self.w_init,
                       self.kernel + (x.shape[-1], self.features))
        pad = self.padding
        if isinstance(pad, int):
            pad = [(pad, pad)] * 3
        y = lax.conv_transpose(
            pol.cast_compute(x), pol.cast_compute(w),
            strides=self.stride, padding=pad,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.use_bias:
            y = y + self.param("b", self.b_init,
                               (self.features,)).astype(y.dtype)
        return self.act(y)


class Pool3D(Module):
    """3-D max/avg pooling, NDHWC (reference: ``Pool3DLayer.cpp``)."""

    def __init__(self, kind: str, window, stride=None, padding="VALID",
                 name=None):
        super().__init__(name=name)
        assert kind in ("max", "avg")
        self.kind = kind
        self.window = (window,) * 3 if isinstance(window, int) else tuple(window)
        stride = stride if stride is not None else window
        self.stride = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
        self.padding = padding

    def forward(self, x):
        dims = (1,) + self.window + (1,)
        strides = (1,) + self.stride + (1,)
        if self.kind == "max":
            return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides,
                                     self.padding)
        s = lax.reduce_window(x, 0.0, lax.add, dims, strides, self.padding)
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides,
                                self.padding)
        return s / cnt


class SelectiveFC(Module):
    """Fully-connected over a per-sample subset of output columns (reference:
    ``SelectiveFullyConnectedLayer.cpp`` — used for large-vocab softmax where
    only sampled columns are computed).

    ``forward(x [B, D], sel [B, K])`` computes ``x @ W[:, sel[b]] + b[sel[b]]``
    per sample — a gather of weight columns followed by a batched matvec
    (einsum), instead of the reference's sparse-matrix product. ``sel`` ids
    < 0 yield zeros. ``forward(x)`` without ``sel`` is a plain Linear (the
    reference's full-matrix mode at inference)."""

    def __init__(self, features: int, act="", use_bias=True,
                 w_init=I.fan_in_uniform, b_init=I.zeros, name=None):
        super().__init__(name=name)
        self.features = features
        self.act = activations.get(act)
        self.use_bias = use_bias
        self.w_init = w_init
        self.b_init = b_init

    def forward(self, x, sel=None):
        pol = current_policy()
        w = self.param("w", self.w_init, (x.shape[-1], self.features))
        b = self.param("b", self.b_init, (self.features,)) \
            if self.use_bias else None
        if sel is None:
            y = jnp.dot(pol.cast_compute(x), pol.cast_compute(w),
                        preferred_element_type=pol.accum_dtype)
            if b is not None:
                y = y + b
            return self.act(y)
        valid = sel >= 0
        safe = jnp.clip(sel, 0, self.features - 1)
        w_sel = jnp.take(w, safe, axis=1)          # [D, B, K]
        w_sel = jnp.moveaxis(w_sel, 1, 0)          # [B, D, K]
        y = jnp.einsum("bd,bdk->bk", pol.cast_compute(x),
                       pol.cast_compute(w_sel),
                       preferred_element_type=pol.accum_dtype)
        if b is not None:
            y = y + jnp.take(b, safe)
        return jnp.where(valid, self.act(y), 0.0)


class SamplingId(Module):
    """Sample an id per row from a (softmax) distribution (reference:
    ``SamplingIdLayer.cpp`` + ``MultinomialSampler``). Input is logits by
    default (``from_logits=False`` for probabilities). Needs an ``rngs=
    {'sample': key}`` stream under apply."""

    def __init__(self, from_logits: bool = True, name=None):
        super().__init__(name=name)
        self.from_logits = from_logits

    def forward(self, x):
        logits = x if self.from_logits else jnp.log(jnp.maximum(x, 1e-30))
        key = current_rng("sample")
        return jax.random.categorical(key, logits, axis=-1)


class ScaleSubRegion(Module):
    """Scale a per-sample sub-region of an image by a constant (reference:
    ``function/ScaleSubRegionOp.cpp`` — 1-based inclusive region indices
    ``[c1, c2, h1, h2, w1, w2]`` per sample, forward multiplies the region
    by ``value``). NHWC here; region built as a boolean mask so the op (and
    its gradient, which scales only in-region, ``:73``) stays jit-safe."""

    def __init__(self, value: float, name=None):
        super().__init__(name=name)
        self.value = value

    def forward(self, x, indices):
        B, H, W, C = x.shape
        idx = indices.astype(jnp.int32)          # [B, 6], 1-based inclusive
        cc = jnp.arange(C)[None, :]
        hh = jnp.arange(H)[None, :]
        ww = jnp.arange(W)[None, :]
        cm = (cc >= idx[:, 0:1] - 1) & (cc <= idx[:, 1:2] - 1)   # [B, C]
        hm = (hh >= idx[:, 2:3] - 1) & (hh <= idx[:, 3:4] - 1)   # [B, H]
        wm = (ww >= idx[:, 4:5] - 1) & (ww <= idx[:, 5:6] - 1)   # [B, W]
        mask = hm[:, :, None, None] & wm[:, None, :, None] & cm[:, None, None, :]
        return jnp.where(mask, x * self.value, x)


class Power(Module):
    """Per-sample power: ``y[b] = x[b] ** w[b]`` with the exponent coming
    from another layer (reference: ``PowerLayer.cpp`` — two inputs, scalar
    exponent per sample)."""

    def forward(self, exponent, x):
        e = exponent.reshape(exponent.shape[0], *([1] * (x.ndim - 1)))
        return jnp.power(x, e)


class Scaling(Module):
    """Per-sample scaling: ``y[b] = w[b] * x[b]`` with the scale from
    another layer (reference: ``ScalingLayer.cpp``)."""

    def forward(self, weight, x):
        w = weight.reshape(weight.shape[0], *([1] * (x.ndim - 1)))
        return w * x


class DotProd(Module):
    """Row-wise dot product of two inputs -> [B, 1] (reference:
    ``DotProdLayer.cpp``)."""

    def forward(self, a, b):
        return jnp.sum(a * b, axis=-1, keepdims=True)


class ConvexCombination(Module):
    """Weighted sum of K stacked rows: weights [B, K], data [B, K, D] (or
    flat [B, K*D]) -> [B, D] (reference: ``ConvexCombinationLayer`` in
    ``LinearChainCRF``-era naming, a.k.a. ``linear_comb_layer``)."""

    def __init__(self, size: Optional[int] = None, name=None):
        super().__init__(name=name)
        self.size = size

    def forward(self, weights, data):
        B, K = weights.shape
        if data.ndim == 2:
            data = data.reshape(B, K, -1)
        return jnp.einsum("bk,bkd->bd", weights, data)


class CosSimVecMat(Module):
    """Cosine similarity of a vector against each of K stacked rows:
    vec [B, D], mat [B, K, D] (or flat [B, K*D]) -> [B, K] (reference:
    ``CosSimVecMatLayer.cpp``)."""

    def __init__(self, scale: float = 1.0, name=None):
        super().__init__(name=name)
        self.scale = scale

    def forward(self, vec, mat):
        B = vec.shape[0]
        if mat.ndim == 2:
            mat = mat.reshape(B, -1, vec.shape[-1])
        num = jnp.einsum("bd,bkd->bk", vec, mat)
        den = (jnp.linalg.norm(vec, axis=-1, keepdims=True)
               * jnp.linalg.norm(mat, axis=-1) + 1e-12)
        return self.scale * num / den


class BilinearInterp(Module):
    """Bilinear up/down-sampling of NHWC feature maps (reference:
    ``BilinearInterpLayer.cpp``). Deviation: uses half-pixel sampling
    (``jax.image.resize``) rather than the reference's align-corners
    ratios — border pixels differ slightly from the legacy layer."""

    def __init__(self, out_h: int, out_w: int, name=None):
        super().__init__(name=name)
        self.out_h = out_h
        self.out_w = out_w

    def forward(self, x):
        B, H, W, C = x.shape
        return jax.image.resize(x, (B, self.out_h, self.out_w, C),
                                method="bilinear")


class EosIdCheck(Module):
    """1 where the id equals ``eos_id`` (reference: ``EosIdCheckLayer.cpp``
    — the stop signal inside generation groups)."""

    def __init__(self, eos_id: int, name=None):
        super().__init__(name=name)
        self.eos_id = eos_id

    def forward(self, ids):
        return (ids == self.eos_id).astype(jnp.float32)


class PRelu(Module):
    """Parametric ReLU with learned negative slope (reference:
    ``ParameterReluLayer.cpp``; ``partial_sum`` groups channels sharing one
    slope — ``channels`` slopes here, 1 = fully shared)."""

    def __init__(self, channels: int = 1, init_slope: float = 0.25,
                 name=None):
        super().__init__(name=name)
        self.channels = channels
        self.init_slope = init_slope

    def forward(self, x):
        a = self.param("a", I.constant(self.init_slope), (self.channels,))
        if self.channels > 1:
            assert x.shape[-1] % self.channels == 0
            a = jnp.repeat(a, x.shape[-1] // self.channels)
        return jnp.where(x >= 0, x, a * x)


class ScalingProjection(Module):
    """One learned scalar times the input (reference:
    ``ScalingProjection.cpp``)."""

    def forward(self, x):
        w = self.param("w", I.ones, (1,))
        return w * x


class SliceProjection(Module):
    """Column slice [start, end) of the input (reference:
    ``SliceProjection.cpp``)."""

    def __init__(self, start: int, end: int, name=None):
        super().__init__(name=name)
        self.start = start
        self.end = end

    def forward(self, x):
        return x[..., self.start:self.end]


class TransposedFullMatrixProjection(Module):
    """``y = x @ W.T`` (reference: ``TransposedFullMatrixProjection.cpp`` —
    weight shared transposed with another projection). The weight is stored
    ``(features, in)`` so it can be shared with a forward projection; the
    init scales by the true fan-in (``in``, shape[1]) — the generic
    fan-in initializer would read shape[0]."""

    def __init__(self, features: int, w_init=None, name=None):
        super().__init__(name=name)
        self.features = features
        self.w_init = w_init

    def forward(self, x):
        fan_in = x.shape[-1]

        def default_init(rng, shape, dtype=jnp.float32):
            bound = 1.0 / np.sqrt(fan_in)
            return jax.random.uniform(rng, shape, dtype, -bound, bound)

        w = self.param("w", self.w_init or default_init,
                       (self.features, fan_in))
        return x @ w.T


class SwitchOrder(Module):
    """NCHW <-> NHWC layout switch (reference: function-layer ``SwitchOp``
    / ``SwitchOrderLayer.cpp``). The package is NHWC-native; this exists
    for interop at data boundaries."""

    def __init__(self, to: str = "NHWC", name=None):
        super().__init__(name=name)
        assert to in ("NHWC", "NCHW")
        self.to = to

    def forward(self, x):
        if self.to == "NHWC":
            return jnp.transpose(x, (0, 2, 3, 1))
        return jnp.transpose(x, (0, 3, 1, 2))


class MaxPoolWithMask(Module):
    """Max pooling that also returns the argmax mask (reference:
    ``MaxPoolWithMaskLayer.cpp`` — the mask holds each output's flat input
    index, consumed by unpooling). Non-overlapping windows
    (stride == window), NHWC; mask indices are flat over (H, W) per channel,
    matching the reference's row-major convention."""

    def __init__(self, window: int, name=None):
        super().__init__(name=name)
        self.window = window

    def forward(self, x):
        B, H, W, C = x.shape
        w = self.window
        assert H % w == 0 and W % w == 0, "window must tile the input"
        Ho, Wo = H // w, W // w
        t = x.reshape(B, Ho, w, Wo, w, C)
        t = jnp.moveaxis(t, 2, 3).reshape(B, Ho, Wo, w * w, C)
        pooled = jnp.max(t, axis=3)
        local = jnp.argmax(t, axis=3).astype(jnp.int32)   # [B,Ho,Wo,C]
        # local window index -> flat (H, W) input index
        ly, lx = local // w, local % w
        gy = jnp.arange(Ho)[None, :, None, None] * w + ly
        gx = jnp.arange(Wo)[None, None, :, None] * w + lx
        return pooled, gy * W + gx
