"""CTC loss — the forward (alpha) recursion as a lax.scan.

Reference: ``/root/reference/paddle/gserver/layers/LinearChainCTC.cpp`` (the
classic alpha-beta recursion over the blank-extended label sequence; ``CTCLayer
.cpp`` cost layer, ``WarpCTCLayer.cpp`` the warp-ctc binding). Blank id = 0 by
default, matching the reference's ``blank_`` convention (norm_by_times flag too).

Log-space alpha recursion over the extended sequence z of length 2U+1 (blanks
interleaved); all shapes static, masking handles variable input/label lengths.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.sequence import length_mask

__all__ = ["ctc_loss", "ctc_greedy_decode"]

# Large-negative sentinel instead of -inf: keeps gradients finite for
# infeasible alignments (e.g. label longer than input).
_NEG = -1e30

_log_add = jnp.logaddexp


def ctc_loss(log_probs, input_lengths, labels, label_lengths, blank: int = 0,
             norm_by_times: bool = False):
    """Per-example CTC negative log likelihood.

    log_probs: [B, T, V] log-softmax outputs; labels: [B, U] (no blanks);
    input_lengths: [B]; label_lengths: [B]. Returns [B] losses.
    """
    b, t, v = log_probs.shape
    u = labels.shape[1]
    s = 2 * u + 1

    # extended sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full((b, s), blank, labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    ext_valid = jnp.arange(s)[None, :] < (2 * label_lengths + 1)[:, None]

    # can skip from s-2 to s when ext[s] != blank and ext[s] != ext[s-2]
    ext_prev2 = jnp.concatenate(
        [jnp.full((b, 2), -1, labels.dtype), ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != ext_prev2)

    # alpha_0: positions 0 (blank) and 1 (first label)
    emit0 = jnp.take_along_axis(log_probs[:, 0], ext, axis=-1)  # [B, S]
    alpha0 = jnp.where(jnp.arange(s)[None, :] <= 1, emit0, _NEG)
    alpha0 = jnp.where(ext_valid, alpha0, _NEG)

    time_mask = length_mask(input_lengths, t)

    def body(alpha, inp):
        lp_t, m_t = inp                                  # [B, V], [B]
        emit = jnp.take_along_axis(lp_t, ext, axis=-1)   # [B, S]
        shift1 = jnp.concatenate(
            [jnp.full((b, 1), _NEG), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate(
            [jnp.full((b, 2), _NEG), alpha[:, :-2]], axis=1)
        acc = _log_add(alpha, shift1)
        acc = jnp.where(can_skip, _log_add(acc, shift2), acc)
        new = jnp.where(ext_valid, acc + emit, _NEG)
        keep = m_t[:, None]
        return jnp.where(keep > 0, new, alpha), None

    xs = (jnp.swapaxes(log_probs, 0, 1)[1:],
          jnp.swapaxes(time_mask.astype(log_probs.dtype), 0, 1)[1:])
    alpha, _ = lax.scan(body, alpha0, xs)

    # final: last blank or last label position (the latter only exists for
    # non-empty targets — clamping would double-count alpha[0]).
    end1 = 2 * label_lengths                             # final blank
    end2 = jnp.maximum(2 * label_lengths - 1, 0)         # final label
    a1 = jnp.take_along_axis(alpha, end1[:, None], 1)[:, 0]
    a2 = jnp.take_along_axis(alpha, end2[:, None], 1)[:, 0]
    a2 = jnp.where(label_lengths > 0, a2, _NEG)
    ll = _log_add(a1, a2)
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.maximum(input_lengths.astype(loss.dtype), 1.0)
    return loss


def ctc_greedy_decode(log_probs, input_lengths, blank: int = 0):
    """Best-path decode: argmax per frame, collapse repeats, strip blanks.
    Returns (decoded [B, T] padded with -1, lengths [B])."""
    b, t, v = log_probs.shape
    ids = jnp.argmax(log_probs, axis=-1)                # [B, T]
    valid = length_mask(input_lengths, t) > 0
    prev = jnp.concatenate([jnp.full((b, 1), -1, ids.dtype), ids[:, :-1]], 1)
    keep = valid & (ids != blank) & (ids != prev)

    # stable compaction: sort by (not keep, position)
    order = jnp.argsort(jnp.where(keep, jnp.arange(t)[None, :], t + 1), axis=1)
    gathered = jnp.take_along_axis(jnp.where(keep, ids, -1), order, axis=1)
    lengths = keep.sum(1)
    pos = jnp.arange(t)[None, :]
    return jnp.where(pos < lengths[:, None], gathered, -1), lengths
