"""Sequence manipulation layers over padded [B, T, ...] batches with masks.

Reference: the gserver sequence family — ``SequencePoolLayer.cpp`` (avg/max/sum
pooling), ``SequenceLastInstanceLayer.cpp`` (last/first), ``ExpandLayer.cpp``,
``SequenceConcatLayer.cpp``, ``SequenceReshapeLayer.cpp``, ``SequenceSliceLayer
.cpp``, ``KmaxSeqScoreLayer.cpp``, ``MaxIdLayer.cpp``. The reference works on
ragged Arguments; here every op takes ``lengths [B]`` (or a mask) against padded
data — all static shapes for XLA.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.sequence import length_mask

__all__ = ["seq_pool", "seq_last", "seq_first", "seq_expand", "seq_concat",
           "seq_reshape", "seq_slice", "kmax_scores", "max_id", "seq_softmax_pool",
           "starts_from_segments", "sub_seq_pool", "sub_seq_last",
           "select_sub_sequences"]


def seq_pool(x, lengths, kind: str = "average"):
    """Pool over time honoring lengths (reference: ``SequencePoolLayer`` —
    average/sum/max/sqrt)."""
    t = x.shape[1]
    m = length_mask(lengths, t)[..., None]
    if kind == "sum":
        return (x * m).sum(1)
    if kind == "average":
        return (x * m).sum(1) / jnp.maximum(
            lengths[:, None].astype(x.dtype), 1.0)
    if kind == "sqrt":
        return (x * m).sum(1) / jnp.sqrt(
            jnp.maximum(lengths[:, None].astype(x.dtype), 1.0))
    if kind == "max":
        neg = jnp.where(m > 0, x, -jnp.inf)
        out = neg.max(1)
        return jnp.where(lengths[:, None] > 0, out, 0.0)
    raise ValueError(kind)


def seq_last(x, lengths):
    """Last valid frame (reference: ``SequenceLastInstanceLayer``)."""
    idx = jnp.maximum(lengths - 1, 0)
    out = jnp.take_along_axis(
        x, idx[:, None, None].astype(jnp.int32).repeat(x.shape[-1], -1),
        axis=1)[:, 0]
    return jnp.where(lengths[:, None] > 0, out, 0.0)


def seq_first(x, lengths):
    return jnp.where(lengths[:, None] > 0, x[:, 0], 0.0)


def seq_expand(vec, like_lengths, max_len: int):
    """Broadcast a per-sequence vector across time (reference: ``ExpandLayer``)."""
    out = jnp.broadcast_to(vec[:, None, :],
                           (vec.shape[0], max_len, vec.shape[-1]))
    return out * length_mask(like_lengths, max_len)[..., None]


def seq_concat(a, a_len, b, b_len):
    """Concatenate two padded sequence batches along time, compacting padding
    (reference: ``SequenceConcatLayer``). Output T = Ta + Tb."""
    ta, tb = a.shape[1], b.shape[1]
    t_out = ta + tb
    bsz = a.shape[0]
    pos = jnp.arange(t_out)[None, :]
    from_a = pos < a_len[:, None]
    idx_a = jnp.clip(pos, 0, ta - 1)
    idx_b = jnp.clip(pos - a_len[:, None], 0, tb - 1)
    ga = jnp.take_along_axis(a, idx_a[..., None].repeat(a.shape[-1], -1), 1)
    gb = jnp.take_along_axis(b, idx_b[..., None].repeat(b.shape[-1], -1), 1)
    out = jnp.where(from_a[..., None], ga, gb)
    new_len = a_len + b_len
    return out * length_mask(new_len, t_out)[..., None], new_len


def seq_reshape(x, lengths, new_width: int):
    """Reshape each sequence's flat values to a new frame width (reference:
    ``SequenceReshapeLayer``): [B, T, D] -> [B, T*D//W, W] with adjusted
    lengths."""
    b, t, d = x.shape
    assert (t * d) % new_width == 0
    new_t = t * d // new_width
    out = x.reshape(b, new_t, new_width)
    new_len = (lengths * d) // new_width
    return out * length_mask(new_len, new_t)[..., None], new_len


def seq_slice(x, lengths, offsets, sizes):
    """Per-sequence subsequence extraction (reference: ``SequenceSliceLayer``):
    gather ``sizes`` frames starting at ``offsets`` (clamped to valid range)."""
    b, t, d = x.shape
    pos = jnp.arange(t)[None, :]
    idx = jnp.clip(offsets[:, None] + pos, 0, t - 1)
    gathered = jnp.take_along_axis(x, idx[..., None].repeat(d, -1), 1)
    new_len = jnp.minimum(sizes, jnp.maximum(lengths - offsets, 0))
    return gathered * length_mask(new_len, t)[..., None], new_len


def kmax_scores(scores, lengths, k: int):
    """Indices of the top-k scores per sequence (reference:
    ``KmaxSeqScoreLayer``)."""
    t = scores.shape[1]
    masked = jnp.where(length_mask(lengths, t) > 0, scores, -jnp.inf)
    _, idx = jax.lax.top_k(masked, k)
    return idx


def max_id(x):
    """Argmax over features (reference: ``MaxIdLayer`` — the prediction op)."""
    return jnp.argmax(x, axis=-1)


def seq_softmax_pool(x, scores, lengths):
    """Attention-style weighted pool: softmax(scores over valid steps) · x."""
    from .activations import sequence_softmax
    w = sequence_softmax(scores, lengths=lengths)
    if w.ndim == 2:
        w = w[..., None]
    return (x * w).sum(1)


def starts_from_segments(segment_ids):
    """[B, T] segment ids -> [B, T] 1/0 flags marking where a new (non-pad)
    segment begins — the form :class:`~paddle_tpu.nn.recurrent.RNN` takes as
    ``segment_starts``. Works for either segment level (pass
    ``sub_segment_ids`` for inner-recurrence resets)."""
    prev = jnp.concatenate([jnp.full_like(segment_ids[:, :1], -1),
                            segment_ids[:, :-1]], axis=1)
    return ((segment_ids != prev) & (segment_ids > 0)).astype(jnp.float32)


# ---------------------------------------------------------- nested (sub-)seq

def sub_seq_pool(x, sub_lengths, kind: str = "average"):
    """Pool each subsequence of a nested batch: ``x [B, S, T, D]``,
    ``sub_lengths [B, S]`` -> ``[B, S, D]`` (reference:
    ``SequencePoolLayer`` applied at the sub-sequence level of a nested
    Argument, ``Argument.h:84-93``)."""
    B, S = x.shape[:2]
    flatx = x.reshape((B * S,) + x.shape[2:])
    out = seq_pool(flatx, sub_lengths.reshape(B * S), kind)
    return out.reshape((B, S) + out.shape[1:])


def sub_seq_last(x, sub_lengths):
    """Last valid token of each subsequence: [B, S, T, D] -> [B, S, D]
    (reference: ``SequenceLastInstanceLayer`` on nested input)."""
    B, S = x.shape[:2]
    flatx = x.reshape((B * S,) + x.shape[2:])
    out = seq_last(flatx, sub_lengths.reshape(B * S))
    return out.reshape((B, S) + out.shape[1:])


def select_sub_sequences(x, sub_lengths, indices):
    """Gather chosen subsequences from a nested batch (reference:
    ``SubNestedSequenceLayer.cpp`` — selects sub-sequences by the ids
    produced e.g. by ``KmaxSeqScoreLayer``).

    ``x [B, S, T, D]``, ``indices [B, K]`` (ids into the S axis; -1 pads) ->
    ``(x' [B, K, T, D], sub_lengths' [B, K])``; padded picks give zeros.
    """
    valid = indices >= 0
    safe = jnp.maximum(indices, 0)
    gx = jnp.take_along_axis(
        x, safe[:, :, None, None].astype(jnp.int32), axis=1)
    gl = jnp.take_along_axis(sub_lengths, safe, axis=1)
    gx = jnp.where(valid[:, :, None, None], gx, 0)
    gl = jnp.where(valid, gl, 0)
    return gx, gl
