"""Layer library: core layers, activations, costs, sequence ops, recurrent nets,
attention — the TPU-native successor of paddle/gserver/layers (+ fluid operators)."""

from . import activations, costs
from .layers import *  # noqa: F401,F403
from .layers import __all__ as _layers_all

__all__ = list(_layers_all) + ["activations", "costs"]
