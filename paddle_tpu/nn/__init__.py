"""Layer library: core layers, activations, costs, sequence ops, recurrent nets,
attention — the TPU-native successor of paddle/gserver/layers (+ fluid operators)."""

from . import activations, autotune, costs, ctc, detection, moe, sequence_ops
from .fused_ln import fused_ln_matmul, ln_matmul_reference
from .attention import (AdditiveAttention, DotProductAttention,
                        MultiHeadAttention)
from .crf import CRF, crf_decode, crf_log_likelihood
from .moe import MoEFFN, moe_sharding_rules
from .detection import (DetectionOutput, MultiBoxLoss, ROIPool,
                        decode_boxes, encode_boxes, iou_matrix, nms,
                        prior_box)
from .ctc import ctc_greedy_decode, ctc_loss
from .layers import *  # noqa: F401,F403
from .layers import __all__ as _layers_all
from .recurrent import (RNN, BiRNN, GRUCell, HierarchicalRNN,
                        LSTMCell, MDLstm, SimpleRNNCell)

__all__ = list(_layers_all) + [
    "activations", "costs", "sequence_ops", "RNN", "BiRNN", "GRUCell",
    "HierarchicalRNN", "LSTMCell", "MDLstm", "SimpleRNNCell", "CRF", "crf_decode", "crf_log_likelihood",
    "ctc_loss", "ctc_greedy_decode", "AdditiveAttention", "DotProductAttention",
    "MultiHeadAttention", "detection", "DetectionOutput", "MultiBoxLoss",
    "ROIPool", "prior_box", "nms", "iou_matrix", "encode_boxes", "decode_boxes",
    "MoEFFN", "moe_sharding_rules", "moe",
    "autotune", "fused_ln_matmul", "ln_matmul_reference",
]
