"""MNIST models — the reference's first demo family.

Reference: ``/root/reference/v1_api_demo/mnist/light_mnist.py`` (LeNet-style
conv-pool×2 + fc) and ``mnist/vgg_16_mnist.py``; the fluid analogs are
``fluid/tests/book/test_recognize_digits_{mlp,conv}.py``.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.module import Module
from .. import nn

__all__ = ["LeNet", "MnistMLP"]


class LeNet(Module):
    """conv(20,5)-pool2-conv(50,5)-pool2-fc(500)-fc(10), the light_mnist
    topology (``v1_api_demo/mnist/light_mnist.py`` conv_pool groups)."""

    def __init__(self, num_classes: int = 10, use_batchnorm: bool = False):
        super().__init__()
        self.c1 = nn.Conv2D(20, 5, act="relu", padding="VALID")
        self.p1 = nn.Pool2D("max", 2)
        self.c2 = nn.Conv2D(50, 5, act="relu", padding="VALID")
        self.p2 = nn.Pool2D("max", 2)
        self.bn = nn.BatchNorm() if use_batchnorm else None
        self.fc1 = nn.Linear(500, act="relu")
        self.fc2 = nn.Linear(num_classes)

    def forward(self, x, train: bool = False):
        h = self.p1(self.c1(x))
        h = self.p2(self.c2(h))
        if self.bn is not None:
            h = self.bn(h, train=train)
        h = h.reshape(h.shape[0], -1)
        return self.fc2(self.fc1(h))


class MnistMLP(Module):
    """128-64-10 MLP (``fluid/tests/book/test_recognize_digits_mlp.py``)."""

    def __init__(self, num_classes: int = 10, hidden=(128, 64)):
        super().__init__()
        self.fcs = [nn.Linear(h, act="relu") for h in hidden]
        self.out = nn.Linear(num_classes)

    def forward(self, x, train: bool = False):
        h = x.reshape(x.shape[0], -1)
        for fc in self.fcs:
            h = fc(h)
        return self.out(h)
