"""VAE demo (reference: ``v1_api_demo/vae/vae_conf.py`` — MLP encoder to
(mu, logvar), reparameterization, MLP decoder to Bernoulli probs; losses
``reconstruct_error`` (BCE) + ``KL_loss`` at ``vae_conf.py:94-103``).

TPU-native: one Module; the reparameterization noise comes from the module
RNG stream ('sample'), so the whole ELBO step jits cleanly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module, current_rng
from paddle_tpu.nn.layers import Linear

__all__ = ["VAE", "elbo_loss"]


class VAE(Module):
    """x [B, D] -> (recon_logits [B, D], mu [B, Z], logvar [B, Z])."""

    def __init__(self, input_dim: int, latent: int = 16, hidden: int = 128,
                 name="vae"):
        super().__init__(name=name)
        self.enc = Linear(hidden, act="relu")
        self.mu = Linear(latent)
        self.logvar = Linear(latent)
        self.dec1 = Linear(hidden, act="relu")
        self.dec_out = Linear(input_dim)

    def encode(self, x):
        h = self.enc(x)
        return self.mu(h), self.logvar(h)

    def decode(self, z):
        return self.dec_out(self.dec1(z))

    def forward(self, x, train: bool = True):
        mu, logvar = self.encode(x)
        if train:
            eps = jax.random.normal(current_rng("sample"), mu.shape)
            z = mu + jnp.exp(0.5 * logvar) * eps    # vae_conf reparam (:27)
        else:
            z = mu
        return self.decode(z), mu, logvar


def elbo_loss(recon_logits, x, mu, logvar):
    """Negative ELBO: Bernoulli BCE reconstruction + analytic KL to N(0, I)
    (``vae_conf.py:94`` reconstruct_error, ``:99`` KL_loss)."""
    # stable BCE-with-logits: max(l,0) - l*x + log(1 + exp(-|l|))
    bce = jnp.sum(jnp.maximum(recon_logits, 0) - recon_logits * x
                  + jnp.log1p(jnp.exp(-jnp.abs(recon_logits))), axis=-1)
    kl = 0.5 * jnp.sum(jnp.exp(logvar) + mu ** 2 - 1.0 - logvar, axis=-1)
    return jnp.mean(bce + kl)
