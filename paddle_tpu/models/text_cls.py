"""LSTM text classification — the reference's RNN benchmark workload.

Reference: ``/root/reference/benchmark/paddle/rnn/rnn.py`` (embedding ->
2 x lstm -> fc over the last step; the published anchor is 184 ms/batch at
bs64 h512 seq100 vocab30k on 1xK40m, BASELINE.md). Library model so the
benchmark (``bench.py --metric lstm``) measures the same code users train —
benchmark-only model definitions are how perf regressions hide.
"""

from __future__ import annotations

from ..core.module import Module
from .. import nn
from ..nn.recurrent import LSTMCell, RNN

__all__ = ["LSTMTextClassifier"]


class LSTMTextClassifier(Module):
    """``ids [B, T] -> logits [B, num_classes]`` via embedding -> stacked
    LSTMs -> fc on the final state."""

    def __init__(self, vocab: int, hidden: int = 512, num_layers: int = 2,
                 num_classes: int = 2, name=None):
        super().__init__(name=name)
        self.emb = nn.Embedding(vocab, hidden)
        # unroll measured NEUTRAL-to-worse under the bench's
        # steps-per-call fori_loop (XLA pipelines the rolled loop better);
        # see experiments/PERF.md "Round 5"
        self.layers = [RNN(LSTMCell(hidden), name=f"lstm{i}")
                       for i in range(num_layers)]
        self.fc = nn.Linear(num_classes, name="fc")

    def forward(self, ids, train: bool = False):
        h = self.emb(ids)
        for layer in self.layers:
            h, _ = layer(h)
        return self.fc(h[:, -1])
