"""Decoder-only transformer LM — the flagship long-context showcase tying
the modern additions together: multi-head attention with the optional Pallas
flash path, pre-LN residual blocks, and optional mixture-of-experts FFNs.

The 2017 reference predates transformers entirely (SURVEY §5 records the
absence of any attention-era machinery) — this model family is a deliberate
"exceeds" item, built from the same Module/IR system as everything else, so
it exports, shards (ring/Ulysses for the seq axis, expert axis for MoE), and
trains under the standard Trainer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core import initializers as I
from paddle_tpu.core.module import Module, is_initializing
from paddle_tpu.nn.attention import MultiHeadAttention
from paddle_tpu.nn.layers import Dropout, Embedding, LayerNorm, Linear
from paddle_tpu.nn.moe import MoEFFN

__all__ = ["TransformerBlock", "TransformerLM", "remat_policy"]


def remat_policy(name):
    """Map a remat knob value to a ``jax.checkpoint`` policy.

    - ``"dots"`` (or ``True``): save matmul outputs, rematerialize the
      cheap elementwise/norm tail (``dots_saveable`` — the standard
      transformer trade: activation memory drops to the dot products while
      the backward recompute stays a small fraction of step FLOPs).
    - ``"full"``: save nothing between layer boundaries — maximum memory
      saving, one extra full forward in the backward.
    """
    if name in (True, "dots"):
        return jax.checkpoint_policies.dots_saveable
    if name == "full":
        return None
    raise ValueError(f"remat must be None, 'dots', or 'full'; got {name!r}")


class TransformerBlock(Module):
    """Pre-LN block: ``x + MHA(LN(x))`` then ``x + FFN(LN(x))``; the FFN is
    a dense two-layer gelu MLP or an :class:`MoEFFN` when
    ``moe_experts > 0``."""

    def __init__(self, dim: int, num_heads: int, ffn_hidden: int,
                 use_flash: bool = False, moe_experts: int = 0,
                 dropout: float = 0.0, attention_impl=None, seq_mesh=None,
                 seq_axis: str = "seq", batch_axis=None,
                 residual_sharding=None, name=None):
        super().__init__(name=name)
        # Optional ``x -> x`` callable (typically a with_sharding_constraint
        # closure) applied to the residual stream after each sublayer add.
        # Constraining residuals to a seq-sharded spec (e.g.
        # P("data", "model", None)) turns Megatron tensor-parallel's
        # activation all-reduces into reduce-scatter/all-gather pairs —
        # sequence-parallel residuals, halving tp wire bytes
        # (experiments/scaling_projection.py quantifies it).
        self.residual_sharding = residual_sharding
        self.ln1 = LayerNorm()
        self.attn = MultiHeadAttention(num_heads, use_flash=use_flash,
                                       attention_impl=attention_impl,
                                       seq_mesh=seq_mesh, seq_axis=seq_axis,
                                       batch_axis=batch_axis)
        self.ln2 = LayerNorm()
        self.moe_experts = moe_experts
        if moe_experts > 0:
            self.ffn = MoEFFN(moe_experts, ffn_hidden)
        else:
            self.ffn1 = Linear(ffn_hidden, act="gelu")
            self.ffn2 = Linear(dim)
        self.dropout = Dropout(dropout) if dropout else None

    def forward(self, x, train: bool = False, segments=None,
                return_kv: bool = False):
        # named_scope: profiler traces (utils/stats.py:profile_trace) show
        # model structure instead of anonymous fusions — trace-time
        # metadata only, zero runtime effect.
        with jax.named_scope("attn"):
            a = self.attn(self.ln1(x), causal=True, segments=segments,
                          return_kv=return_kv)
            kv = None
            if return_kv:
                a, kv = a
            h = x + self._maybe_drop(a, train)
        if self.residual_sharding is not None:
            h = self.residual_sharding(h)
        with jax.named_scope("ffn"):
            z = self.ln2(h)
            if self.moe_experts > 0:
                y, aux = self.ffn(z, return_aux=True)
            else:
                y = self.ffn2(self.ffn1(z))
                aux = jnp.zeros((), jnp.float32)
            out = h + self._maybe_drop(y, train)
        if self.residual_sharding is not None:
            out = self.residual_sharding(out)
        if return_kv:
            return out, aux, kv
        return out, aux

    def decode_step(self, x, pages_k, pages_v, tables, positions, active,
                    attn_impl: str = "xla"):
        """One serving decode step: the forward block with the attention
        sublayer swapped for :meth:`MultiHeadAttention.decode` (paged KV
        scatter + q_len=1 attention). Returns ``(out, pages_k, pages_v)``
        with this layer's updated pool pages. No dropout — serving is
        inference-only by construction."""
        with jax.named_scope("attn"):
            a, pages_k, pages_v = self.attn.decode(
                self.ln1(x), pages_k, pages_v, tables, positions, active,
                impl=attn_impl)
            h = x + a
        if self.residual_sharding is not None:
            h = self.residual_sharding(h)
        with jax.named_scope("ffn"):
            z = self.ln2(h)
            if self.moe_experts > 0:
                y, _aux = self.ffn(z, return_aux=True)
            else:
                y = self.ffn2(self.ffn1(z))
            out = h + y
        if self.residual_sharding is not None:
            out = self.residual_sharding(out)
        return out, pages_k, pages_v

    def decode_span(self, x, pages_k, pages_v, tables, start, n, active,
                    attn_impl: str = "xla", write_from=None):
        """A span of consecutive new tokens per slot: the forward block
        with the attention sublayer swapped for
        :meth:`MultiHeadAttention.decode_span` (multi-token paged
        scatter + per-row q_len=1-exact attention). Shared by the
        speculative verify tick and chunked prefill (ISSUE 12).
        ``x`` [S, Q, D]; returns ``(out, pages_k, pages_v)``."""
        with jax.named_scope("attn"):
            a, pages_k, pages_v = self.attn.decode_span(
                self.ln1(x), pages_k, pages_v, tables, start, n, active,
                impl=attn_impl, write_from=write_from)
            h = x + a
        if self.residual_sharding is not None:
            h = self.residual_sharding(h)
        with jax.named_scope("ffn"):
            z = self.ln2(h)
            if self.moe_experts > 0:
                y, _aux = self.ffn(z, return_aux=True)
            else:
                y = self.ffn2(self.ffn1(z))
            out = h + y
        if self.residual_sharding is not None:
            out = self.residual_sharding(out)
        return out, pages_k, pages_v

    def _maybe_drop(self, x, train):
        if self.dropout is not None and train:
            return self.dropout(x, train=True)
        return x


class TransformerLM(Module):
    """``ids [B, T] -> logits [B, T, vocab]`` with tied input/output
    embeddings. ``forward(ids, train, return_aux=True)`` also returns the
    summed MoE load-balance loss (zero for dense FFNs)."""

    def __init__(self, vocab: int, dim: int = 128, num_layers: int = 2,
                 num_heads: int = 4, ffn_hidden: int = 256,
                 max_len: int = 512, use_flash: bool = False,
                 moe_experts: int = 0, dropout: float = 0.0,
                 attention_impl=None, seq_mesh=None, seq_axis: str = "seq",
                 batch_axis=None, residual_sharding=None, remat=None,
                 name="transformer_lm"):
        super().__init__(name=name)
        self.max_len = max_len
        self.residual_sharding = residual_sharding
        # remat: None (off), "dots"/True, or "full" — runs the block stack
        # as ONE lax.scan over stacked per-layer params with jax.checkpoint
        # around the body: layer-boundary activations are the only thing
        # saved across the stack (policy-dependent within a layer), turning
        # activation memory from O(L * T * D * blowup) into
        # O(L boundaries + one layer's working set) — the standard
        # scan-over-layers + rematerialization recipe. Requires homogeneous
        # blocks and dropout == 0; the variables tree is UNCHANGED
        # (per-block subtrees are stacked at trace time), so checkpoints
        # move freely between remat and plain configs.
        if remat is not None:
            remat_policy(remat)          # validate eagerly
        self.remat = remat
        self.dropout_rate = dropout
        self.emb = Embedding(vocab, dim)
        self.pos = Embedding(max_len, dim,
                             w_init=I.normal(0.02), name="pos")
        self.blocks = [TransformerBlock(dim, num_heads, ffn_hidden,
                                        use_flash, moe_experts, dropout,
                                        attention_impl=attention_impl,
                                        seq_mesh=seq_mesh, seq_axis=seq_axis,
                                        batch_axis=batch_axis,
                                        residual_sharding=residual_sharding,
                                        name=f"block{i}")
                       for i in range(num_layers)]
        self.ln_f = LayerNorm()

    def embed(self, ids, positions=None):
        """Token + positional embedding only (the pipeline-parallel entry:
        stage 0's input is produced outside the block pipeline)."""
        T = ids.shape[1]
        pos = jnp.arange(T)[None] if positions is None else positions
        return self.emb(ids) + self.pos(pos)

    def head(self, x):
        """Final LN + tied readout (the pipeline-parallel exit)."""
        return self.emb.attend(self.ln_f(x))

    def forward(self, ids, train: bool = False, return_aux: bool = False,
                segments=None, positions=None):
        """``segments``/``positions``: packed-sequence metadata
        (``core.sequence.pack_sequences``) — attention is confined within
        each packed sub-sequence on every attention impl, and positional
        embeddings restart per segment when ``positions`` is given."""
        T = ids.shape[1]
        assert T <= self.max_len, f"T={T} exceeds max_len={self.max_len}"
        pos = jnp.arange(T)[None] if positions is None else positions
        with jax.named_scope("embed"):
            x = self.emb(ids) + self.pos(pos)
        if self.residual_sharding is not None:
            x = self.residual_sharding(x)
        if self.remat is not None and not is_initializing():
            # init must trace the plain loop so every block creates its
            # params; apply takes the scanned/rematerialized stack.
            x, aux_total = self._scan_blocks(x, train, segments)
        else:
            aux_total = jnp.zeros((), jnp.float32)
            for blk in self.blocks:
                with jax.named_scope(blk._name):
                    x, aux = blk(x, train=train, segments=segments)
                aux_total = aux_total + aux
        with jax.named_scope("head"):
            x = self.ln_f(x)
            logits = self.emb.attend(x)      # tied softmax weights
        if return_aux:
            return logits, aux_total
        return logits

    # -- serving entry points (paddle_tpu.serve) ---------------------------
    #
    # All three run the block stack as ONE lax.scan over the per-block
    # param subtrees STACKED AT TRACE TIME (the _scan_blocks recipe, minus
    # checkpoint — no gradients flow here), so the variables tree is the
    # training tree unchanged: any training checkpoint serves as-is.
    #
    # Shard-in-scope (ISSUE 15): the bodies are mesh-oblivious, but when
    # the engine traces them inside `parallel.tp_shard_scope` the
    # attention layer pins its projections/pools head-sharded and the
    # residual stream + logits pin REPLICATED here — classic Megatron tp
    # (not sequence-parallel: decode is one token per slot, so there is
    # no sequence to split; the head axis is the only parallel axis with
    # work on it). The logits assemble on the existing tp head path: the
    # row-parallel out/ffn2 projections all-reduce back to the replicated
    # residual, and the tied readout runs replicated on every shard.

    def _stacked_blocks(self):
        block0 = self.blocks[0]
        subs = [blk.subtree() for blk in self.blocks]
        return block0, jax.tree_util.tree_map(lambda *ls: jnp.stack(ls),
                                              *subs)

    def prefill(self, ids, positions=None):
        """Serving prefill: ``ids [B, W] -> (logits [B, W, vocab],
        (k, v))`` where ``k``/``v`` are the per-layer attention
        projections ``[L, B, W, H, hd]`` — the engine scatters rows
        ``< length`` into the paged KV cache. ``W`` is the engine's FIXED
        padded context width: rows past a sequence's true length produce
        unspecified logits/KV (causal masking keeps them out of every
        valid row), and running every prefill at one width both pins the
        compiled shape (no retraces) and keeps each row's softmax
        reduction width identical to the training forward's — the f32
        bit-equality contract the serve tests pin."""
        from paddle_tpu.parallel.sharding import tp_constrain
        T = ids.shape[1]
        assert T <= self.max_len, f"T={T} exceeds max_len={self.max_len}"
        pos = jnp.arange(T)[None] if positions is None else positions
        with jax.named_scope("decode/prefill"):
            with jax.named_scope("embed"):
                x = tp_constrain(self.emb(ids) + self.pos(pos))
            block0, stacked = self._stacked_blocks()

            def body(h, bp):
                y, _aux, kv = block0.apply(
                    {"params": {block0._name: bp}}, h, train=False,
                    return_kv=True)
                return tp_constrain(y), kv

            with jax.named_scope("block_scan"):
                x, (ks, vs) = lax.scan(body, x, stacked)
            with jax.named_scope("head"):
                logits = tp_constrain(self.emb.attend(self.ln_f(x)))
        return logits, (ks, vs)

    def decode_step(self, token, kv, positions, active=None,
                    attn_impl: str = "xla"):
        """Serving decode tick: one new token per slot against the paged
        KV cache. ``token [S]`` int32; ``kv = (pages_k, pages_v,
        tables)`` with pools ``[L, N, bs, H, hd]`` (the leading layer
        axis feeds the layer scan) and ``tables [S, MB]``; ``positions
        [S]`` the incoming token's 0-based position (== pre-step length);
        ``active [S]`` bool (default: all). Returns ``(logits [S,
        vocab], kv')`` with the updated pools — same structure, so the
        engine's jit carry donates cleanly."""
        from paddle_tpu.parallel.sharding import tp_constrain
        pages_k, pages_v, tables = kv
        S = token.shape[0]
        if active is None:
            active = jnp.ones((S,), bool)
        # inactive slots may carry position 0 forever; the clamp only
        # guards overflow and is the identity for every valid position
        pos_idx = jnp.minimum(positions, self.max_len - 1)
        with jax.named_scope("decode/step"):
            with jax.named_scope("embed"):
                x = tp_constrain(self.emb(token[:, None])
                                 + self.pos(pos_idx[:, None]))
            block0, stacked = self._stacked_blocks()

            def body(h, xs):
                bp, pk, pv = xs
                y, pk, pv = block0.apply(
                    {"params": {block0._name: bp}}, h, pk, pv, tables,
                    positions, active, attn_impl=attn_impl,
                    method="decode_step")
                return tp_constrain(y), (pk, pv)

            with jax.named_scope("block_scan"):
                x, (pages_k, pages_v) = lax.scan(
                    body, x, (stacked, pages_k, pages_v))
            with jax.named_scope("head"):
                logits = tp_constrain(self.emb.attend(self.ln_f(x)))
        return logits[:, 0], (pages_k, pages_v, tables)

    def decode_span(self, tokens, kv, start, n, active=None,
                    attn_impl: str = "xla", write_from=None):
        """Serving span step: ``Q`` consecutive new tokens per slot
        against the paged KV cache — ONE compiled dispatch that the
        speculative verify tick (``Q = 1 + draft_k``) and chunked
        prefill (``Q = chunk``) both ride (ISSUE 12). ``tokens``
        ``[S, Q]`` int32 (token ``j`` of slot ``s`` at position
        ``start[s] + j``); ``n`` ``[S]`` live token counts (rows past
        ``n`` are padding — null-block scatter, garbage logits);
        ``write_from`` ``[S]`` optional scatter floor for shared-prefix
        re-reads. Returns ``(logits [S, Q, vocab], kv')``; row ``j`` of
        a live slot is bit-equal (f32) to what :meth:`decode_step`
        would produce at that position — the structural losslessness
        the serve tests pin."""
        from paddle_tpu.parallel.sharding import tp_constrain
        pages_k, pages_v, tables = kv
        S, Q = tokens.shape
        if active is None:
            active = jnp.ones((S,), bool)
        pos = jnp.minimum(start[:, None]
                          + jnp.arange(Q, dtype=jnp.int32)[None, :],
                          self.max_len - 1)
        with jax.named_scope("decode/span"):
            with jax.named_scope("embed"):
                x = tp_constrain(self.emb(tokens) + self.pos(pos))
            block0, stacked = self._stacked_blocks()

            def body(h, xs):
                bp, pk, pv = xs
                y, pk, pv = block0.apply(
                    {"params": {block0._name: bp}}, h, pk, pv, tables,
                    start, n, active, attn_impl=attn_impl,
                    write_from=write_from, method="decode_span")
                return tp_constrain(y), (pk, pv)

            with jax.named_scope("block_scan"):
                x, (pages_k, pages_v) = lax.scan(
                    body, x, (stacked, pages_k, pages_v))
            with jax.named_scope("head"):
                logits = tp_constrain(self.emb.attend(self.ln_f(x)))
        return logits, (pages_k, pages_v, tables)

    def grad_sync_scan_paths(self):
        """The ``parallel.overlap`` in-scan protocol: fnmatch patterns (over
        slash-joined param paths) of the leaves this model gradient-syncs
        PER LAYER inside its scan-over-layers stack — the Trainer's
        bucketed grad_sync excludes them from its top-level buckets so
        they are never double-synced. Only the remat'd stack scans, so
        without ``remat`` there is nothing to claim."""
        if self.remat is None:
            return ()
        return ("*/block*/*",)

    def _scan_blocks(self, x, train, segments):
        """The rematerialized stack: stack the (homogeneous) per-block param
        subtrees onto a leading [L, ...] layer axis and run ONE
        ``jax.checkpoint``-wrapped block as a ``lax.scan`` over it. Grads
        flow back through the stack's transpose (unstack) onto the
        per-block leaves, so the optimizer/checkpoint view of the params is
        unchanged."""
        assert not (train and self.dropout_rate > 0), \
            "remat scan-over-layers requires dropout == 0 (rngs do not " \
            "thread through the stacked block)"
        block0 = self.blocks[0]
        subs = [blk.subtree() for blk in self.blocks]
        stacked = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *subs)

        def body(carry, bp):
            h, aux = carry
            # Per-layer in-scan gradient sync (no-op outside an active
            # Trainer grad_sync="bucketed" trace): the stacked leaves'
            # gradient only completes when the WHOLE scan transpose
            # finishes, so the bucket marker wraps each layer's param
            # slice HERE — its all-reduce fires inside that layer's
            # backward iteration. Lazy import: parallel imports models.
            from paddle_tpu.parallel import overlap as _overlap
            bp = _overlap.sync_scan_slice(bp, tag="scan_layer")
            with jax.named_scope("block_scan"):
                y, a = block0.apply({"params": {block0._name: bp}}, h,
                                    train=train, segments=segments)
            return (y, aux + a), None

        body = jax.checkpoint(body, policy=remat_policy(self.remat))
        (x, aux_total), _ = lax.scan(
            body, (x, jnp.zeros((), jnp.float32)), stacked)
        return x, aux_total


def make_pipeline_lm_apply(model: "TransformerLM", mesh, microbatches: int,
                           pipe_axis: str = "pipe"):
    """Pipeline-parallel forward for a :class:`TransformerLM`: the block
    stack executes as a GPipe wavefront over the mesh's ``pipe`` axis
    (one block per stage), embeddings/head stay outside the pipeline —
    making pipeline parallelism reachable from the model library rather
    than only from hand-built toys (the integration gap VERDICT r2 called
    out for the sequence-parallel wrappers).

    Returns ``apply_fn(variables, ids, positions=None) -> logits`` that is
    numerically identical to ``model.apply`` (the wavefront is
    differentiable, so ``jax.grad`` through ``apply_fn`` trains embeddings,
    blocks, and head end to end). Requires ``len(model.blocks)`` == the
    ``pipe`` axis size, homogeneous blocks, and ``dropout == 0`` (rngs
    don't cross the shard_map boundary). For the M >> S
    gradient-accumulation regime use
    :func:`paddle_tpu.parallel.make_pipeline_1f1b` directly.
    """
    import jax

    from ..parallel.pipeline import make_pipeline

    S = len(model.blocks)
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    assert sizes.get(pipe_axis) == S, \
        f"pipe axis size {sizes.get(pipe_axis)} != num_layers {S}"
    block0 = model.blocks[0]

    def stage_fn(p_stage, act):
        out, _aux = block0.apply({"params": p_stage}, act)
        return out

    pipe = make_pipeline(mesh, stage_fn, pipe_axis)

    def stack_blocks(variables):
        root = variables["params"]
        mp = root[model._name] if model._name in root \
            else next(iter(root.values()))
        subs = [mp[blk._name] for blk in model.blocks]
        stacked = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *subs)
        return {block0._name: stacked}

    def apply_fn(variables, ids, positions=None):
        h = model.apply(variables, ids, positions=positions, method="embed")
        B = h.shape[0]
        assert B % microbatches == 0, \
            f"batch {B} must divide by microbatches {microbatches}"
        x_mb = h.reshape(microbatches, B // microbatches, *h.shape[1:])
        out = pipe(stack_blocks(variables), x_mb)
        out = out.reshape(B, *h.shape[1:])
        return model.apply(variables, out, method="head")

    return apply_fn


__all__ += ["make_pipeline_lm_apply"]
