"""Attention seq2seq NMT — the reference's flagship recurrent workload.

Reference: encoder-decoder with ``simple_attention`` inside a recurrent group
(``/root/reference/python/paddle/trainer_config_helpers/networks.py:1320``;
demo ``v1_api_demo/seqToseq`` equivalent; the decoder unroll + beam-search
generation is ``RecurrentGradientMachine::generateSequence`` /
``beamSearch``, ``paddle/gserver/gradientmachines/RecurrentGradientMachine.cpp:539``).

TPU-native: the encoder is a BiRNN scan; the decoder trains teacher-forced under
one scan (no per-step Python); generation is a fixed-width beam search inside
``lax.scan`` over decode steps — fully jittable, static shapes, runs on-device
(the reference's dynamic ``Path`` expansion becomes tensor-shaped beam state).

Token conventions: 0 = pad, 1 = <s> (bos), 2 = <e> (eos), matching the
reference's seqToseq data convention.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.module import Module
from ..core.sequence import length_mask
from .. import nn

__all__ = ["Seq2SeqAttention", "PAD", "BOS", "EOS"]

PAD, BOS, EOS = 0, 1, 2


class Seq2SeqAttention(Module):
    """GRU encoder-decoder with additive attention.

    forward(batch) -> per-example loss (teacher forcing).
    ``generate`` -> beam-search decode (jittable).
    """

    def __init__(self, src_vocab: int, tgt_vocab: int, emb_dim: int = 128,
                 hidden: int = 256, name=None):
        super().__init__(name=name)
        self.src_vocab = src_vocab
        self.tgt_vocab = tgt_vocab
        self.hidden = hidden
        self.src_emb = nn.Embedding(src_vocab, emb_dim, name="src_emb")
        self.tgt_emb = nn.Embedding(tgt_vocab, emb_dim, name="tgt_emb")
        self.encoder = nn.BiRNN(nn.GRUCell(hidden), nn.GRUCell(hidden),
                                name="encoder")
        self.dec_cell = nn.GRUCell(hidden, name="dec_cell")
        self.att = nn.AdditiveAttention(hidden, name="att")
        self.boot = nn.Linear(hidden, act="tanh", name="boot")
        self.readout = nn.Linear(tgt_vocab, name="readout")

    # -- shared pieces --------------------------------------------------------

    def encode(self, src_ids, src_len):
        mask = length_mask(src_len, src_ids.shape[1])
        enc = self.encoder(self.src_emb(src_ids), mask=mask)   # [B, T, 2H]
        # boot state from the backward encoder's first output (the reference
        # boots the decoder from backward_first, networks.py simple_attention
        # usage in seqToseq)
        back_first = enc[:, 0, self.hidden:]
        dec0 = self.boot(back_first)
        return enc, mask, dec0

    def _dec_cell_step(self, state, y_emb, enc, enc_mask, enc_proj):
        """One decoder step WITHOUT the vocab readout — the readout is 83%
        of decoder FLOPs (2*h*V per token) and, run per scan step as a tiny
        [B, h] @ [h, V] matmul, dominated the step at single-digit MXU
        efficiency (experiments/PERF.md "Round 5: seq2seq"); training
        hoists it out of the scan and applies it once over [B, T, h]."""
        ctx, _ = self.att(state, enc, enc_mask, enc_proj=enc_proj)
        x = jnp.concatenate([y_emb, ctx], axis=-1)
        new_state, out = self.dec_cell.step(state, x)
        return new_state, out

    def _dec_step(self, state, y_emb, enc, enc_mask, enc_proj):
        new_state, out = self._dec_cell_step(state, y_emb, enc, enc_mask,
                                             enc_proj)
        logits = self.readout(out)
        return new_state, logits

    # -- training -------------------------------------------------------------

    def forward(self, batch, train: bool = False):
        """batch: src [B,Ts], src_len [B], tgt [B,Tt] (bos-prefixed),
        tgt_len [B]. Returns per-example summed CE loss (masked)."""
        src, src_len = batch["src"], batch["src_len"]
        tgt, tgt_len = batch["tgt"], batch["tgt_len"]
        enc, enc_mask, dec0 = self.encode(src, src_len)
        with self.att.scope():
            enc_proj = self.att.proj_e(enc)
        tgt_in = tgt[:, :-1]
        tgt_out = tgt[:, 1:]
        y_embs = self.tgt_emb(tgt_in)                       # [B, Tt-1, E]

        # materialize decoder params before scan
        _ = self._dec_step(dec0, y_embs[:, 0], enc, enc_mask, enc_proj)

        def body(state, y_emb_t):
            new_state, out = self._dec_cell_step(state, y_emb_t, enc,
                                                 enc_mask, enc_proj)
            return new_state, out

        _, outs = lax.scan(body, dec0, jnp.swapaxes(y_embs, 0, 1))
        # one big [B*(Tt-1), h] @ [h, V] readout instead of Tt-1 tiny ones
        # inside the scan: same math, MXU-shaped (PERF.md "Round 5")
        logits = self.readout(jnp.swapaxes(outs, 0, 1))      # [B, Tt-1, V]
        losses = nn.costs.softmax_cross_entropy(logits, tgt_out)
        out_mask = length_mask(jnp.maximum(tgt_len - 1, 0), tgt_out.shape[1])
        return (losses * out_mask).sum(-1)

    def init_variables(self, rng, batch):
        return self.init(rng, batch)

    # -- generation (beam search) --------------------------------------------

    def generate(self, variables, src, src_len, beam_size: int = 4,
                 max_len: int = 32, length_penalty: float = 0.0):
        """Beam-search decode. Returns (tokens [B, beam, max_len],
        scores [B, beam]) sorted best-first. Jittable; the analog of
        ``RecurrentGradientMachine::generateSequence`` with ``--beam_size``."""
        return self.apply(variables, src, src_len, beam_size, max_len,
                          length_penalty, method="_beam_search")

    def _beam_search(self, src, src_len, K, max_len, length_penalty):
        B = src.shape[0]
        V = self.tgt_vocab
        enc, enc_mask, dec0 = self.encode(src, src_len)
        with self.att.scope():
            enc_proj = self.att.proj_e(enc)

        # expand to beams: [B*K, ...]
        def tile(x):
            return jnp.repeat(x, K, axis=0)

        enc_b, mask_b, proj_b = tile(enc), tile(enc_mask), tile(enc_proj)
        state = tile(dec0)

        neg_inf = -1e9
        # beam scores: beam 0 active, others dead (standard first-step trick)
        scores = jnp.tile(jnp.array([0.0] + [neg_inf] * (K - 1)), (B,))  # [B*K]
        tokens = jnp.full((B * K, max_len), PAD, jnp.int32)
        cur = jnp.full((B * K,), BOS, jnp.int32)
        finished = jnp.zeros((B * K,), bool)

        # materialize params (already created in encode/att) for the step
        _ = self._dec_step(state, self.tgt_emb(cur), enc_b, mask_b, proj_b)

        def body(carry, t):
            state, scores, tokens, cur, finished = carry
            new_state, logits = self._dec_step(state, self.tgt_emb(cur),
                                               enc_b, mask_b, proj_b)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)  # [B*K,V]
            # finished beams: only PAD continuation, score unchanged
            cont = jnp.where(finished[:, None],
                             jnp.where(jnp.arange(V)[None, :] == PAD, 0.0,
                                       neg_inf),
                             logp)
            cand = scores[:, None] + cont                   # [B*K, V]
            cand = cand.reshape(B, K * V)
            top_s, top_i = lax.top_k(cand, K)               # [B, K]
            beam_idx = top_i // V                           # which source beam
            tok = (top_i % V).astype(jnp.int32)
            flat_src = (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)
            new_state = jax.tree_util.tree_map(
                lambda s: jnp.take(s, flat_src, axis=0), new_state)
            tokens2 = jnp.take(tokens, flat_src, axis=0)
            tokens2 = tokens2.at[:, t].set(tok.reshape(-1))
            fin2 = jnp.take(finished, flat_src) | (tok.reshape(-1) == EOS)
            return (new_state, top_s.reshape(-1), tokens2, tok.reshape(-1),
                    fin2), None

        (state, scores, tokens, cur, finished), _ = lax.scan(
            body, (state, scores, tokens, cur, finished),
            jnp.arange(max_len))

        tokens = tokens.reshape(B, K, max_len)
        scores = scores.reshape(B, K)
        if length_penalty > 0:
            lengths = (tokens != PAD).sum(-1)
            scores = scores / ((5.0 + lengths) / 6.0) ** length_penalty
        order = jnp.argsort(-scores, axis=1)
        tokens = jnp.take_along_axis(tokens, order[..., None], axis=1)
        scores = jnp.take_along_axis(scores, order, axis=1)
        return tokens, scores
