"""ResNet family — the reference's model-zoo flagship and benchmark workload.

Reference: ``/root/reference/v1_api_demo/model_zoo/resnet/resnet.py:171-253``
(conv_bn_layer / shortcut / basicblock / bottleneck; depth 18/34/50/101/152)
and ``benchmark/paddle/image/resnet.py``. TPU-native: NHWC layout, bf16 compute
policy, BN running stats as module state; the residual topology maps 1:1.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from ..core.module import Module
from .. import nn

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "resnet_cifar"]


class ConvBN(Module):
    """conv + batchnorm + activation (reference: conv_bn_layer,
    resnet.py:171)."""

    def __init__(self, features, kernel, stride=1, act="relu", name=None):
        super().__init__(name=name)
        self.conv = nn.Conv2D(features, kernel, stride=stride, padding="SAME",
                              act="", use_bias=False, name="conv")
        self.bn = nn.BatchNorm(name="bn")
        self.act = nn.activations.get(act)

    def forward(self, x, train=False):
        return self.act(self.bn(self.conv(x), train=train))


class BasicBlock(Module):
    """3x3+3x3 residual block (reference: basicblock, resnet.py:205)."""

    expansion = 1

    def __init__(self, features, stride=1, name=None):
        super().__init__(name=name)
        self.c1 = ConvBN(features, 3, stride=stride, name="c1")
        self.c2 = ConvBN(features, 3, act="", name="c2")
        # Declared here (like every other submodule); only called — and thus
        # only parameterized — when the block actually changes shape.
        self.shortcut = ConvBN(features, 1, stride=stride, act="",
                               name="shortcut")
        self.stride = stride
        self.features = features

    def forward(self, x, train=False):
        h = self.c2(self.c1(x, train=train), train=train)
        if self.stride != 1 or x.shape[-1] != self.features:
            x = self.shortcut(x, train=train)
        return jnp.maximum(h + x, 0.0)


class Bottleneck(Module):
    """1x1-3x3-1x1 bottleneck (reference: bottleneck, resnet.py:219)."""

    expansion = 4

    def __init__(self, features, stride=1, name=None):
        super().__init__(name=name)
        self.c1 = ConvBN(features, 1, name="c1")
        self.c2 = ConvBN(features, 3, stride=stride, name="c2")
        self.c3 = ConvBN(features * 4, 1, act="", name="c3")
        self.shortcut = ConvBN(features * 4, 1, stride=stride, act="",
                               name="shortcut")
        self.stride = stride
        self.features = features

    def forward(self, x, train=False):
        h = self.c3(self.c2(self.c1(x, train=train), train=train), train=train)
        if self.stride != 1 or x.shape[-1] != self.features * 4:
            x = self.shortcut(x, train=train)
        return jnp.maximum(h + x, 0.0)


class SpaceToDepthStem(Module):
    """MLPerf-style space-to-depth stem: rearrange 2x2 input patches into
    channels ([B, 224, 224, 3] -> [B, 112, 112, 12]) and apply a 4x4
    stride-1 conv instead of the canonical 7x7 stride-2. Functionally the
    same receptive-field family (4x4x12 = 192 taps covers the 7x7x3 = 147),
    but the MXU sees 12 input channels instead of 3 — the tiny-C_in conv is
    the single least-efficient op in the ResNet step on TPU."""

    def __init__(self, features=64, name=None):
        super().__init__(name=name)
        self.conv = ConvBN(features, 4, stride=1, name="conv")

    def forward(self, x, train=False):
        n, h, w, c = x.shape
        x = x.reshape(n, h // 2, 2, w // 2, 2, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
        return self.conv(x, train=train)


class ResNet(Module):
    """ImageNet-shape ResNet (reference: resnet.py:232 ``deep_res_net``).

    ``stem``: "conv7" (canonical 7x7/2, weight-compatible with the
    reference) or "s2d" (space-to-depth 4x4 stem — same accuracy family,
    much better MXU utilization; the benchmark default)."""

    def __init__(self, block, layers: Sequence[int], num_classes: int = 1000,
                 stem: str = "conv7", name=None):
        super().__init__(name=name)
        if stem == "s2d":
            self.stem = SpaceToDepthStem(64, name="stem")
        else:
            self.stem = ConvBN(64, 7, stride=2, name="stem")
        self.pool = nn.Pool2D("max", 3, stride=2, padding="SAME")
        self.stages = []
        feats = [64, 128, 256, 512]
        for si, (f, n) in enumerate(zip(feats, layers)):
            blocks = []
            for bi in range(n):
                stride = 2 if (si > 0 and bi == 0) else 1
                blocks.append(block(f, stride=stride,
                                    name=f"stage{si}_block{bi}"))
            self.stages.append(blocks)
        # register for naming
        self.all_blocks = [b for s in self.stages for b in s]
        self.head = nn.Linear(num_classes, name="fc")

    def forward(self, x, train=False):
        h = self.pool(self.stem(x, train=train))
        for stage in self.stages:
            for blk in stage:
                h = blk(h, train=train)
        h = jnp.mean(h, axis=(1, 2))
        return self.head(h)


def resnet18(num_classes=1000):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes)


def resnet34(num_classes=1000):
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes)


def resnet50(num_classes=1000, stem="conv7"):
    return ResNet(Bottleneck, [3, 4, 6, 3], num_classes, stem=stem)


def resnet101(num_classes=1000):
    return ResNet(Bottleneck, [3, 4, 23, 3], num_classes)


def resnet152(num_classes=1000):
    return ResNet(Bottleneck, [3, 8, 36, 3], num_classes)


class ResNetCifar(Module):
    """CIFAR-shape ResNet (3 stages, 32x32 stem) — the benchmark SmallNet
    analog (``benchmark/paddle/image/smallnet_mnist_cifar.py`` scale)."""

    def __init__(self, depth_n: int = 3, num_classes: int = 10, name=None):
        super().__init__(name=name)
        self.stem = ConvBN(16, 3, name="stem")
        self.blocks = []
        for si, f in enumerate([16, 32, 64]):
            for bi in range(depth_n):
                stride = 2 if (si > 0 and bi == 0) else 1
                self.blocks.append(BasicBlock(f, stride=stride,
                                              name=f"s{si}_b{bi}"))
        self.head = nn.Linear(num_classes, name="fc")

    def forward(self, x, train=False):
        h = self.stem(x, train=train)
        for blk in self.blocks:
            h = blk(h, train=train)
        return self.head(jnp.mean(h, axis=(1, 2)))


def resnet_cifar(depth_n=3, num_classes=10):
    return ResNetCifar(depth_n, num_classes)
