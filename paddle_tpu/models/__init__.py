"""Model zoo — the acceptance workloads from BASELINE.json (MNIST LeNet,
ResNet, seq2seq attention NMT, sequence tagging, CTR) built on paddle_tpu.nn."""

from .mnist import LeNet, MnistMLP
from .seq2seq import Seq2SeqAttention
from .tagging import LinearCrfTagger, RnnCrfTagger
