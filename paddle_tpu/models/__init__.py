"""Model zoo — the acceptance workloads from BASELINE.json (MNIST LeNet,
ResNet, seq2seq attention NMT, sequence tagging, CTR) built on paddle_tpu.nn."""

from .ctr import CTR_SHARDING_RULES, SparseLR, WideDeepCTR
from .gan import Discriminator, Generator, gan_step_fn
from .image_zoo import AlexNet, GoogLeNet, VGG, vgg16, vgg19
from .mnist import LeNet, MnistMLP
from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152, resnet_cifar)
from .seq2seq import Seq2SeqAttention
from .ssd import SSDHead
from .vae import VAE, elbo_loss
from .tagging import LinearCrfTagger, RnnCrfTagger
from .text_cls import LSTMTextClassifier
from .traffic import TrafficPredictor
from .transformer import TransformerBlock, TransformerLM
