"""SSD detection head — the model-side wiring for the detection layer family.

Reference: the SSD configuration the reference's detection layers serve
(``gserver/layers/PriorBox.cpp``, ``MultiBoxLossLayer.cpp``,
``DetectionOutputLayer.cpp``; demo config ``v1_api_demo`` SSD-style nets).

TPU-first: priors for all feature maps are concatenated host-side into one
static [P, 4] constant; the per-map loc/conf convolutions stay NHWC 3x3 convs
(MXU-friendly), reshaped and concatenated into the fixed [B, P, ...] tensors
that :class:`~paddle_tpu.nn.detection.MultiBoxLoss` /
:class:`~paddle_tpu.nn.detection.DetectionOutput` consume.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.nn.layers import Conv2D
from paddle_tpu.nn.detection import (DetectionOutput, MultiBoxLoss, prior_box)

__all__ = ["SSDHead"]


class SSDHead(Module):
    """Multi-scale loc/conf heads + static priors.

    ``feature_shapes[i]`` is the (H, W) of the i-th backbone feature map;
    ``min_sizes[i]`` / ``max_sizes[i]`` size the priors of that map (SSD's
    per-scale assignment). ``forward(features)`` takes the list of NHWC
    feature maps and returns ``(loc [B, P, 4], conf [B, P, num_classes])``.
    """

    def __init__(self, num_classes: int,
                 feature_shapes: Sequence[Tuple[int, int]],
                 image_shape: Tuple[int, int],
                 min_sizes: Sequence[float],
                 max_sizes: Sequence[float] = (),
                 aspect_ratios: Sequence[float] = (2.0,),
                 variance: Sequence[float] = (0.1, 0.1, 0.2, 0.2),
                 name: str = "ssd_head"):
        super().__init__(name=name)
        self.num_classes = num_classes
        self.feature_shapes = [tuple(s) for s in feature_shapes]
        self.image_shape = tuple(image_shape)
        self.min_sizes = list(min_sizes)
        self.max_sizes = list(max_sizes)
        self.aspect_ratios = list(aspect_ratios)
        self.variance = tuple(variance)

        priors, variances = [], []
        self._num_priors_per_cell = []
        for i, fs in enumerate(self.feature_shapes):
            mx = [self.max_sizes[i]] if self.max_sizes else []
            b, v = prior_box(fs, self.image_shape, [self.min_sizes[i]], mx,
                             self.aspect_ratios, self.variance)
            priors.append(b)
            variances.append(v)
            self._num_priors_per_cell.append(b.shape[0] // (fs[0] * fs[1]))
        self.priors = jnp.concatenate(priors, 0)
        self.variances = jnp.concatenate(variances, 0)

        self.loc_convs = [Conv2D(n * 4, kernel=3, padding="SAME",
                                 name=f"loc{i}")
                          for i, n in enumerate(self._num_priors_per_cell)]
        self.conf_convs = [Conv2D(n * num_classes, kernel=3, padding="SAME",
                                  name=f"conf{i}")
                           for i, n in enumerate(self._num_priors_per_cell)]

    def forward(self, features):
        assert len(features) == len(self.feature_shapes)
        locs, confs = [], []
        for i, feat in enumerate(features):
            B = feat.shape[0]
            loc = self.loc_convs[i](feat).reshape(B, -1, 4)
            conf = self.conf_convs[i](feat).reshape(B, -1, self.num_classes)
            locs.append(loc)
            confs.append(conf)
        return jnp.concatenate(locs, 1), jnp.concatenate(confs, 1)

    def multibox_loss(self, **kw) -> MultiBoxLoss:
        return MultiBoxLoss(self.priors, self.variances, self.num_classes,
                            **kw)

    def detection_output(self, **kw) -> DetectionOutput:
        return DetectionOutput(self.priors, self.variances, self.num_classes,
                               **kw)
