"""CTR models — the reference's sparse quick-start demos, TPU-native.

Reference: ``/root/reference/v1_api_demo/quick_start/trainer_config.lr.py``
(wide logistic regression over sparse ids) and ``trainer_config.emb.py``
(embedding + fc). The reference trains these with row-sharded embedding
tables on parameter servers, prefetching only the rows present in each batch
(``trainer/RemoteParameterUpdater.h:265`` SparseRemoteParameterUpdater,
``math/SparseRowMatrix.h:31``, ``pserver/SparseParameterDistribution.cpp``).

TPU-native, the entire sparse-distribution tier collapses into a *sharding*:
the table rows are laid out over the ``model`` mesh axis
(:data:`CTR_SHARDING_RULES`), lookups become XLA gathers with the collective
traffic inserted by SPMD, and the scatter-add gradient of ``jnp.take`` is the
SelectedRows analog — only touched rows produce updates.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.module import Module, Sequential
from .. import nn
from ..parallel import ShardingRules

__all__ = ["WideDeepCTR", "SparseLR", "CTR_SHARDING_RULES",
           "SparseRowsWideDeepCTR", "make_sparse_ctr_step"]

# Row-shard every embedding table over the `model` axis — the pserver
# row-sharding analog. First match wins; everything else replicated.
CTR_SHARDING_RULES = ShardingRules([
    ("*/wide/w", P("model", None)),
    ("*/deep/w", P("model", None)),
])


def _global_field_ids(ids, num_fields: int, vocab_per_field: int):
    """Map field-local ids [B, F] into one table's row space: field f owns
    rows [f*vocab, (f+1)*vocab). Padding (-1) is preserved."""
    offs = jnp.arange(num_fields, dtype=ids.dtype) * vocab_per_field
    return jnp.where(ids >= 0, ids + offs[None, :], -1)


class SparseLR(Module):
    """Wide logistic regression over sparse categorical fields
    (reference: ``trainer_config.lr.py`` — sparse_binary_vector -> fc).

    ``ids [B, F]`` carry field-local ids; each field f gets its own row range
    ``[f*vocab, (f+1)*vocab)`` of one big weight table. Returns logits [B].

    ``weights [B, F]`` (optional) makes the input a *sparse float-value*
    vector — ``x[id_f] = w_f`` instead of 1.0 — the PyDataProvider2
    ``sparse_float_vector`` slot (reference:
    ``python/paddle/trainer/PyDataProvider2.py:116-248`` converter,
    input-type system ``:365``); the logit is then exactly the dense
    matmul ``x @ W`` of that weighted multi-hot vector.
    """

    def __init__(self, num_fields: int, vocab_per_field: int, name=None):
        super().__init__(name=name)
        self.num_fields = num_fields
        self.vocab = vocab_per_field
        self.wide = nn.Embedding(num_fields * vocab_per_field, 1,
                                 name="wide")

    def forward(self, ids, weights=None, train=False):
        g = _global_field_ids(ids, self.num_fields, self.vocab)
        per_field = self.wide(g)[..., 0]                # [B, F]
        if weights is not None:
            per_field = per_field * weights
        logit = per_field.sum(-1)                       # [B]
        b = self.param("b", lambda r, s, d: jnp.zeros(s, d), ())
        return logit + b


class WideDeepCTR(Module):
    """Wide (sparse LR) + deep (embedding -> MLP) click model
    (reference: ``trainer_config.emb.py`` embedding path combined with the
    ``lr`` wide path; the 2016 wide&deep recipe the demo family approximates).
    Returns logits [B]."""

    def __init__(self, num_fields: int, vocab_per_field: int,
                 emb_dim: int = 16, hidden: Sequence[int] = (64, 32),
                 name=None):
        super().__init__(name=name)
        self.num_fields = num_fields
        self.vocab = vocab_per_field
        self.emb_dim = emb_dim
        self.wide = nn.Embedding(num_fields * vocab_per_field, 1, name="wide")
        self.deep = nn.Embedding(num_fields * vocab_per_field, emb_dim,
                                 name="deep")
        self.mlp = Sequential(
            *[nn.Linear(h, act="relu", name=f"fc{i}")
              for i, h in enumerate(hidden)],
            nn.Linear(1, name="out"), name="mlp")

    def forward(self, ids, weights=None, train=False):
        """``weights [B, F]`` (optional) = sparse float-value slot: both the
        wide term and the deep field embeddings scale by the id's value
        (the dense equivalent feeds the weighted multi-hot vector)."""
        g = _global_field_ids(ids, self.num_fields, self.vocab)
        wide_per_field = self.wide(g)[..., 0]                       # [B, F]
        e = self.deep(g)                                            # [B,F,D]
        if weights is not None:
            wide_per_field = wide_per_field * weights
            e = e * weights[..., None]
        wide_logit = wide_per_field.sum(-1)                         # [B]
        flat = e.reshape(e.shape[0], self.num_fields * self.emb_dim)
        deep_logit = self.mlp(flat)[:, 0]                           # [B]
        return wide_logit + deep_logit


class SparseRowsWideDeepCTR(Module):
    """Wide&deep CTR over *prefetched rows* — the sparse-update twin of
    :class:`WideDeepCTR` for tables that must never see a dense gradient
    (reference: the sparse remote tier, ``RemoteParameterUpdater.h:265``).

    The embedding tables are NOT parameters of this module: they live in
    :class:`paddle_tpu.optim.sparse.SparseTable` buffers outside autodiff;
    the step (see :func:`make_sparse_ctr_step`) gathers each batch's unique
    rows and differentiates w.r.t. the gathered [U, D] slices only. The
    dense MLP trains normally.
    """

    def __init__(self, num_fields: int, vocab_per_field: int,
                 emb_dim: int = 16, hidden: Sequence[int] = (64, 32),
                 name=None):
        super().__init__(name=name)
        self.num_fields = num_fields
        self.vocab = vocab_per_field
        self.emb_dim = emb_dim
        self.mlp = Sequential(
            *[nn.Linear(h, act="relu", name=f"fc{i}")
              for i, h in enumerate(hidden)],
            nn.Linear(1, name="out"), name="mlp")

    def global_ids(self, ids):
        return _global_field_ids(ids, self.num_fields, self.vocab)

    def forward(self, ids, wide_rows, wide_gather, deep_rows, deep_gather,
                weights=None, train=False):
        """``*_rows`` [U, D] gathered table rows; ``*_gather`` [B, F] index
        of each field's row within them (padding already zeroed in rows).
        ``weights [B, F]`` (optional) = sparse float-value slot."""
        valid = (ids >= 0)[..., None]
        wide_e = jnp.where(valid, wide_rows[wide_gather], 0.0)     # [B,F,1]
        deep_e = jnp.where(valid, deep_rows[deep_gather], 0.0)     # [B,F,D]
        if weights is not None:
            wide_e = wide_e * weights[..., None]
            deep_e = deep_e * weights[..., None]
        wide_logit = wide_e[..., 0].sum(-1)
        flat = deep_e.reshape(deep_e.shape[0], self.num_fields * self.emb_dim)
        return wide_logit + self.mlp(flat)[:, 0]


def make_sparse_ctr_step(model: "SparseRowsWideDeepCTR", dense_optimizer,
                         row_optimizer, loss_fn, catchup=None):
    """Build the jitted sparse train step.

    Signature: ``step(dense_params, dense_opt_state, wide_table, deep_table,
    step_no, batch) -> (dense_params, dense_opt_state, wide_table,
    deep_table, loss)`` with the tables donated — commits lower to in-place
    scatters and **nothing [vocab, D]-shaped enters the autodiff graph**
    (asserted structurally by ``tests/test_sparse_rows.py``).
    """
    import jax

    from ..optim import sparse as sp
    from ..optim.optimizers import apply_updates

    def step_fn(params, opt_state, wide_tbl, deep_tbl, step_no, batch):
        ids = batch["ids"]
        weights = batch.get("weights")      # sparse float-value slot
        g = model.global_ids(ids)
        wide_pre = sp.sparse_prefetch(wide_tbl, g, step_no, catchup=catchup)
        deep_pre = sp.sparse_prefetch(deep_tbl, g, step_no, catchup=catchup)

        def compute_loss(p, wide_rows, deep_rows):
            out = model.apply(
                {"params": p}, ids, wide_rows, wide_pre.gather_idx,
                deep_rows, deep_pre.gather_idx, weights=weights, train=True)
            return loss_fn(out, batch)

        (loss), grads = jax.value_and_grad(compute_loss, argnums=(0, 1, 2))(
            params, wide_pre.rows, deep_pre.rows)
        gdense, gwide, gdeep = grads

        upd, new_opt = dense_optimizer.update(gdense, opt_state, params,
                                              step_no)
        new_params = apply_updates(params, upd)

        new_tables = []
        for tbl, pre, grows in ((wide_tbl, wide_pre, gwide),
                                (deep_tbl, deep_pre, gdeep)):
            rupd, rslots = row_optimizer.update(grows, pre.slots, pre.rows,
                                                step_no)
            new_tables.append(sp.sparse_commit(
                tbl, pre, pre.rows + rupd, rslots, step_no))
        return (new_params, new_opt, new_tables[0], new_tables[1],
                loss)

    jitted = jax.jit(step_fn, donate_argnums=(2, 3))
    jitted._raw = step_fn          # for structural jaxpr inspection in tests
    return jitted
