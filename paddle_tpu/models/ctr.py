"""CTR models — the reference's sparse quick-start demos, TPU-native.

Reference: ``/root/reference/v1_api_demo/quick_start/trainer_config.lr.py``
(wide logistic regression over sparse ids) and ``trainer_config.emb.py``
(embedding + fc). The reference trains these with row-sharded embedding
tables on parameter servers, prefetching only the rows present in each batch
(``trainer/RemoteParameterUpdater.h:265`` SparseRemoteParameterUpdater,
``math/SparseRowMatrix.h:31``, ``pserver/SparseParameterDistribution.cpp``).

TPU-native, the entire sparse-distribution tier collapses into a *sharding*:
the table rows are laid out over the ``model`` mesh axis
(:data:`CTR_SHARDING_RULES`), lookups become XLA gathers with the collective
traffic inserted by SPMD, and the scatter-add gradient of ``jnp.take`` is the
SelectedRows analog — only touched rows produce updates.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.module import Module, Sequential
from .. import nn
from ..parallel import ShardingRules

__all__ = ["WideDeepCTR", "SparseLR", "CTR_SHARDING_RULES"]

# Row-shard every embedding table over the `model` axis — the pserver
# row-sharding analog. First match wins; everything else replicated.
CTR_SHARDING_RULES = ShardingRules([
    ("*/wide/w", P("model", None)),
    ("*/deep/w", P("model", None)),
])


def _global_field_ids(ids, num_fields: int, vocab_per_field: int):
    """Map field-local ids [B, F] into one table's row space: field f owns
    rows [f*vocab, (f+1)*vocab). Padding (-1) is preserved."""
    offs = jnp.arange(num_fields, dtype=ids.dtype) * vocab_per_field
    return jnp.where(ids >= 0, ids + offs[None, :], -1)


class SparseLR(Module):
    """Wide logistic regression over sparse categorical fields
    (reference: ``trainer_config.lr.py`` — sparse_binary_vector -> fc).

    ``ids [B, F]`` carry field-local ids; each field f gets its own row range
    ``[f*vocab, (f+1)*vocab)`` of one big weight table. Returns logits [B].
    """

    def __init__(self, num_fields: int, vocab_per_field: int, name=None):
        super().__init__(name=name)
        self.num_fields = num_fields
        self.vocab = vocab_per_field
        self.wide = nn.Embedding(num_fields * vocab_per_field, 1,
                                 name="wide")

    def forward(self, ids, train=False):
        g = _global_field_ids(ids, self.num_fields, self.vocab)
        logit = self.wide(g)[..., 0].sum(-1)            # [B]
        b = self.param("b", lambda r, s, d: jnp.zeros(s, d), ())
        return logit + b


class WideDeepCTR(Module):
    """Wide (sparse LR) + deep (embedding -> MLP) click model
    (reference: ``trainer_config.emb.py`` embedding path combined with the
    ``lr`` wide path; the 2016 wide&deep recipe the demo family approximates).
    Returns logits [B]."""

    def __init__(self, num_fields: int, vocab_per_field: int,
                 emb_dim: int = 16, hidden: Sequence[int] = (64, 32),
                 name=None):
        super().__init__(name=name)
        self.num_fields = num_fields
        self.vocab = vocab_per_field
        self.emb_dim = emb_dim
        self.wide = nn.Embedding(num_fields * vocab_per_field, 1, name="wide")
        self.deep = nn.Embedding(num_fields * vocab_per_field, emb_dim,
                                 name="deep")
        self.mlp = Sequential(
            *[nn.Linear(h, act="relu", name=f"fc{i}")
              for i, h in enumerate(hidden)],
            nn.Linear(1, name="out"), name="mlp")

    def forward(self, ids, train=False):
        g = _global_field_ids(ids, self.num_fields, self.vocab)
        wide_logit = self.wide(g)[..., 0].sum(-1)                   # [B]
        e = self.deep(g)                                            # [B,F,D]
        flat = e.reshape(e.shape[0], self.num_fields * self.emb_dim)
        deep_logit = self.mlp(flat)[:, 0]                           # [B]
        return wide_logit + deep_logit
