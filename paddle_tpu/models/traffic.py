"""Traffic speed-category forecasting (reference:
``v1_api_demo/traffic_prediction/trainer_config.py`` — a shared link
embedding feeding FORECASTING_NUM 4-way softmax heads, trained multi-task).

TPU-native: the per-horizon heads are one Linear producing
``[B, horizons, 4]`` (identical math to separate heads; one MXU matmul
instead of 24 small ones), with the shared embedding exactly as the
reference's shared ``_link_vec.w``.
"""

from __future__ import annotations

from paddle_tpu.core.module import Module
from paddle_tpu.nn.layers import Linear

__all__ = ["TrafficPredictor"]


class TrafficPredictor(Module):
    def __init__(self, term_num: int = 24, forecasting_num: int = 24,
                 emb_size: int = 16, num_classes: int = 4,
                 name="traffic"):
        super().__init__(name=name)
        self.term_num = term_num
        self.forecasting_num = forecasting_num
        self.num_classes = num_classes
        # the shared _link_vec.w; tanh is the v1 fc_layer default activation
        self.link_vec = Linear(emb_size, act="tanh")
        self.heads = Linear(forecasting_num * num_classes)

    def forward(self, encode, train: bool = False):
        assert encode.shape[1] == self.term_num, \
            f"expected {self.term_num} readings, got {encode.shape[1]}"
        h = self.link_vec(encode)
        logits = self.heads(h)
        return logits.reshape(encode.shape[0], self.forecasting_num,
                              self.num_classes)
