"""GAN demo family (reference: ``v1_api_demo/gan/gan_conf.py`` — MLP
generator/discriminator over 2-D synthetic samples; ``gan_conf_image.py`` —
conv MNIST variant; trainer loop ``gan_trainer.py``).

TPU-native: generator and discriminator are ordinary Modules; the
alternating two-optimizer loop is ONE jit-compiled step that performs the
discriminator update then the generator update back-to-back (both phases in
a single XLA program — no host round-trip between the half-steps, unlike
the reference's two GradientMachines driven from Python).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module
from paddle_tpu.nn.layers import BatchNorm, Linear
from paddle_tpu.optim.optimizers import Optimizer

__all__ = ["Generator", "Discriminator", "gan_step_fn"]


class Generator(Module):
    """noise [B, Z] -> sample [B, D] (reference ``generator``,
    ``gan_conf.py:90`` — two hidden relu/bn layers, linear output)."""

    def __init__(self, sample_dim: int, hidden: int = 64,
                 use_bn: bool = True, name="generator"):
        super().__init__(name=name)
        self.h1 = Linear(hidden, act="relu")
        self.bn = BatchNorm() if use_bn else None
        self.h2 = Linear(hidden, act="relu")
        self.out = Linear(sample_dim)

    def forward(self, z, train: bool = True):
        h = self.h1(z)
        if self.bn is not None:
            h = self.bn(h, train=train)
        return self.out(self.h2(h))


class Discriminator(Module):
    """sample [B, D] -> logit [B, 1] (reference ``discriminator``,
    ``gan_conf.py:43``)."""

    def __init__(self, hidden: int = 64, name="discriminator"):
        super().__init__(name=name)
        self.h1 = Linear(hidden, act="relu")
        self.h2 = Linear(hidden, act="relu")
        self.out = Linear(1)

    def forward(self, x, train: bool = True):
        return self.out(self.h2(self.h1(x)))


def gan_step_fn(gen: Generator, disc: Discriminator,
                g_opt: Optimizer, d_opt: Optimizer):
    """Build the jit-able alternating step.

    Returns ``step(g_vars, d_vars, g_opt_state, d_opt_state, step_no, real,
    noise) -> (g_vars, d_vars, g_opt_state, d_opt_state, d_loss, g_loss)``.
    Non-saturating BCE objectives; the discriminator update sees the
    generator through ``stop_gradient`` and vice versa.
    """

    def bce_logits(logits, target):
        # -[t log s + (1-t) log (1-s)] in the stable softplus form
        return jnp.mean(jax.nn.softplus(logits) - target * logits)

    def step(g_vars, d_vars, g_opt_state, d_opt_state, step_no, real, noise):
        # --- discriminator phase: train-mode generator output, but the BN
        # running-stat update is discarded here — the generator phase below
        # recomputes and keeps it, so stats advance once per step.
        fake, _ = gen.apply(g_vars, noise, train=True, mutable=("state",))
        fake_sg = jax.lax.stop_gradient(fake)

        def d_loss_fn(dp):
            dv = {"params": dp, "state": d_vars.get("state", {})}
            real_logit = disc.apply(dv, real)
            fake_logit = disc.apply(dv, fake_sg)
            return bce_logits(real_logit, 1.0) + bce_logits(fake_logit, 0.0)

        d_loss, d_grads = jax.value_and_grad(d_loss_fn)(d_vars["params"])
        d_params, d_opt_state = d_opt.apply(d_grads, d_opt_state,
                                            d_vars["params"], step_no)
        d_vars = {"params": d_params, "state": d_vars.get("state", {})}

        # --- generator phase (non-saturating: maximize log D(G(z)))
        def g_loss_fn(gp):
            gv = {"params": gp, "state": g_vars.get("state", {})}
            out, new = gen.apply(gv, noise, train=True, mutable=("state",))
            logit = disc.apply(d_vars, out)
            return bce_logits(logit, 1.0), new["state"]

        (g_loss, g_state), g_grads = jax.value_and_grad(
            g_loss_fn, has_aux=True)(g_vars["params"])
        g_params, g_opt_state = g_opt.apply(g_grads, g_opt_state,
                                            g_vars["params"], step_no)
        g_vars = {"params": g_params, "state": g_state}
        return (g_vars, d_vars, g_opt_state, d_opt_state, d_loss, g_loss)

    return jax.jit(step)
