"""Sequence tagging models — linear-CRF and RNN-CRF.

Reference: ``/root/reference/v1_api_demo/sequence_tagging/linear_crf.py`` (sparse
feature projections + CRF) and ``rnn_crf.py`` (embedding + RNN + CRF), evaluated
with the chunk evaluator (``paddle/gserver/evaluators/ChunkEvaluator.cpp``).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.module import Module
from ..core.sequence import length_mask
from .. import nn

__all__ = ["RnnCrfTagger", "LinearCrfTagger"]


class RnnCrfTagger(Module):
    """Embedding -> BiLSTM -> Linear emissions -> CRF (rnn_crf.py analog).

    forward(batch) -> per-sequence CRF negative log-likelihood.
    ``decode`` -> viterbi tags (use via apply(..., method="decode")).
    """

    def __init__(self, vocab: int, num_tags: int, emb_dim: int = 64,
                 hidden: int = 128, name=None):
        super().__init__(name=name)
        self.emb = nn.Embedding(vocab, emb_dim, name="emb")
        self.rnn = nn.BiRNN(nn.LSTMCell(hidden), nn.LSTMCell(hidden),
                            name="birnn")
        self.proj = nn.Linear(num_tags, name="emissions")
        self.crf = nn.CRF(num_tags, name="crf")

    def emissions(self, batch):
        toks, lengths = batch["tokens"], batch["length"]
        mask = length_mask(lengths, toks.shape[1])
        h = self.rnn(self.emb(toks), mask=mask)
        return self.proj(h), lengths

    def forward(self, batch, train: bool = False):
        em, lengths = self.emissions(batch)
        return self.crf(em, batch["label"], lengths)

    def decode(self, batch):
        em, lengths = self.emissions(batch)
        return self.crf.decode(em, lengths)

    def init_variables(self, rng, batch):
        return self.init(rng, batch)


class LinearCrfTagger(Module):
    """Sparse-feature linear emissions -> CRF (linear_crf.py analog): token
    (and optional context) ids project straight to tag scores via embedding
    tables — the TPU-native form of the reference's sparse full-matrix
    projections over one-hot features."""

    def __init__(self, vocab: int, num_tags: int, context: int = 2, name=None):
        super().__init__(name=name)
        self.context = context
        self.tables = [nn.Embedding(vocab, num_tags, name=f"feat_{i}")
                       for i in range(2 * context + 1)]
        self.crf = nn.CRF(num_tags, name="crf")

    def emissions(self, batch):
        toks, lengths = batch["tokens"], batch["length"]
        em = None
        for off in range(-self.context, self.context + 1):
            shifted = jnp.roll(toks, -off, axis=1)
            t = toks.shape[1]
            idx = jnp.arange(t)
            valid = (idx + off >= 0) & (idx + off < t)
            shifted = jnp.where(valid[None, :], shifted, -1)  # -1 -> zero emb
            e = self.tables[off + self.context](shifted)
            em = e if em is None else em + e
        return em, lengths

    def forward(self, batch, train: bool = False):
        em, lengths = self.emissions(batch)
        return self.crf(em, batch["label"], lengths)

    def decode(self, batch):
        em, lengths = self.emissions(batch)
        return self.crf.decode(em, lengths)

    def init_variables(self, rng, batch):
        return self.init(rng, batch)
