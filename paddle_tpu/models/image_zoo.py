"""VGG, AlexNet, GoogLeNet — the reference's image benchmark set.

Reference configs: ``/root/reference/benchmark/paddle/image/vgg.py``,
``alexnet.py``, ``googlenet.py``; the v1 DSL composite
``trainer_config_helpers/networks.py:468`` (``vgg_16_network``,
``img_conv_group``).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from ..core.module import Module
from .. import nn

__all__ = ["VGG", "vgg16", "vgg19", "AlexNet", "GoogLeNet"]


class ConvGroup(Module):
    """n × (conv3x3 + relu) [+ BN] + maxpool (reference: img_conv_group,
    networks.py:376)."""

    def __init__(self, features: int, n: int, use_bn: bool = True,
                 dropout: float = 0.0, name=None):
        super().__init__(name=name)
        self.convs = [nn.Conv2D(features, 3, act="" if use_bn else "relu",
                                use_bias=not use_bn, name=f"conv{i}")
                      for i in range(n)]
        self.bns = ([nn.BatchNorm(name=f"bn{i}") for i in range(n)]
                    if use_bn else None)
        self.dropout = nn.Dropout(dropout) if dropout > 0 else None
        self.pool = nn.Pool2D("max", 2)

    def forward(self, x, train=False):
        for i, conv in enumerate(self.convs):
            x = conv(x)
            if self.bns is not None:
                x = jnp.maximum(self.bns[i](x, train=train), 0.0)
            if self.dropout is not None:
                x = self.dropout(x, train=train)
        return self.pool(x)


class VGG(Module):
    """VGG-16/19 (reference: benchmark/paddle/image/vgg.py; vgg_16_network)."""

    def __init__(self, cfg: Sequence[int], num_classes: int = 1000,
                 use_bn: bool = True, name=None):
        super().__init__(name=name)
        feats = [64, 128, 256, 512, 512]
        self.groups = [ConvGroup(f, n, use_bn=use_bn, name=f"group{i}")
                       for i, (f, n) in enumerate(zip(feats, cfg))]
        self.do1 = nn.Dropout(0.5)
        self.fc1 = nn.Linear(4096, act="relu", name="fc1")
        self.do2 = nn.Dropout(0.5)
        self.fc2 = nn.Linear(4096, act="relu", name="fc2")
        self.out = nn.Linear(num_classes, name="out")

    def forward(self, x, train=False):
        for g in self.groups:
            x = g(x, train=train)
        x = x.reshape(x.shape[0], -1)
        x = self.do1(self.fc1(x), train=train)
        x = self.do2(self.fc2(x), train=train)
        return self.out(x)


def vgg16(num_classes=1000, use_bn=True):
    return VGG([2, 2, 3, 3, 3], num_classes, use_bn)


def vgg19(num_classes=1000, use_bn=True):
    return VGG([2, 2, 4, 4, 4], num_classes, use_bn)


class AlexNet(Module):
    """AlexNet (reference: benchmark/paddle/image/alexnet.py)."""

    def __init__(self, num_classes: int = 1000, name=None):
        super().__init__(name=name)
        self.c1 = nn.Conv2D(96, 11, stride=4, padding="VALID", act="relu",
                            name="c1")
        self.c2 = nn.Conv2D(256, 5, act="relu", groups=1, name="c2")
        self.c3 = nn.Conv2D(384, 3, act="relu", name="c3")
        self.c4 = nn.Conv2D(384, 3, act="relu", name="c4")
        self.c5 = nn.Conv2D(256, 3, act="relu", name="c5")
        self.pool = nn.Pool2D("max", 3, stride=2, padding="VALID")
        self.do1 = nn.Dropout(0.5)
        self.fc1 = nn.Linear(4096, act="relu", name="fc1")
        self.do2 = nn.Dropout(0.5)
        self.fc2 = nn.Linear(4096, act="relu", name="fc2")
        self.out = nn.Linear(num_classes, name="out")

    def forward(self, x, train=False):
        h = self.pool(self.c1(x))
        h = self.pool(self.c2(h))
        h = self.c4(self.c3(h))
        h = self.pool(self.c5(h))
        h = h.reshape(h.shape[0], -1)
        h = self.do1(self.fc1(h), train=train)
        h = self.do2(self.fc2(h), train=train)
        return self.out(h)


class Inception(Module):
    """GoogLeNet inception block (reference: benchmark/paddle/image/
    googlenet.py ``inception``): 1x1 / 3x3 / 5x5 / pool-proj branches."""

    def __init__(self, c1, c3r, c3, c5r, c5, proj, name=None):
        super().__init__(name=name)
        self.b1 = nn.Conv2D(c1, 1, act="relu", name="b1")
        self.b3r = nn.Conv2D(c3r, 1, act="relu", name="b3r")
        self.b3 = nn.Conv2D(c3, 3, act="relu", name="b3")
        self.b5r = nn.Conv2D(c5r, 1, act="relu", name="b5r")
        self.b5 = nn.Conv2D(c5, 5, act="relu", name="b5")
        self.pool = nn.Pool2D("max", 3, stride=1, padding="SAME")
        self.bp = nn.Conv2D(proj, 1, act="relu", name="bp")

    def forward(self, x):
        return jnp.concatenate([
            self.b1(x), self.b3(self.b3r(x)), self.b5(self.b5r(x)),
            self.bp(self.pool(x))], axis=-1)


class GoogLeNet(Module):
    """GoogLeNet v1 (reference: benchmark/paddle/image/googlenet.py), without
    the auxiliary towers (benchmark config also drops them)."""

    def __init__(self, num_classes: int = 1000, name=None):
        super().__init__(name=name)
        self.stem1 = nn.Conv2D(64, 7, stride=2, act="relu", name="stem1")
        self.stem2 = nn.Conv2D(64, 1, act="relu", name="stem2")
        self.stem3 = nn.Conv2D(192, 3, act="relu", name="stem3")
        self.pool = nn.Pool2D("max", 3, stride=2, padding="SAME")
        cfg = [
            (64, 96, 128, 16, 32, 32), (128, 128, 192, 32, 96, 64),  # 3a 3b
            (192, 96, 208, 16, 48, 64), (160, 112, 224, 24, 64, 64),  # 4a 4b
            (128, 128, 256, 24, 64, 64), (112, 144, 288, 32, 64, 64),  # 4c 4d
            (256, 160, 320, 32, 128, 128),                             # 4e
            (256, 160, 320, 32, 128, 128), (384, 192, 384, 48, 128, 128),  # 5
        ]
        self.inc = [Inception(*c, name=f"inc{i}") for i, c in enumerate(cfg)]
        self.dropout = nn.Dropout(0.4)
        self.out = nn.Linear(num_classes, name="out")

    def forward(self, x, train=False):
        h = self.pool(self.stem1(x))
        h = self.pool(self.stem3(self.stem2(h)))
        for i, blk in enumerate(self.inc):
            h = blk(h)
            if i in (1, 6):
                h = self.pool(h)
        h = jnp.mean(h, axis=(1, 2))
        h = self.dropout(h, train=train)
        return self.out(h)
