"""v1-style declarative layer DSL — the second frontend over the model IR.

Reference: ``python/paddle/trainer_config_helpers/layers.py`` (~100 wrapper
functions returning ``LayerOutput``, ``layers.py:312``) and ``networks.py``
composites. The v1 API's essence: a config script *describes* a graph as
data; the engine builds it. Here each helper appends a node to a small DAG
and ``build_network`` compiles the DAG into ONE serializable
:class:`NetworkModule` — so the declarative script and the imperative Module
API meet in the same IR (``core/config.py``), the "one IR, two frontends"
design SURVEY §7 calls for (the reference solved it the same way:
``v2/layer.py:263`` reuses the v1 config generator).

Example::

    img  = data_layer("image")
    h    = fc_layer(img, size=128, act="relu")
    prob = fc_layer(h, size=10)
    net  = build_network(prob)          # a Module; init/apply/export as usual
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, List, Optional, Sequence, Tuple, Union

from paddle_tpu.core.module import Module
from paddle_tpu.nn import layers as L
from paddle_tpu.nn import recurrent as R
from paddle_tpu.nn import sequence_ops as S
from paddle_tpu.nn.attention import AdditiveAttention

__all__ = [
    "data_layer", "fc_layer", "embedding_layer", "img_conv_layer",
    "img_pool_layer", "batch_norm_layer", "dropout_layer", "concat_layer",
    "addto_layer", "cos_sim", "pooling_layer", "last_seq", "first_seq",
    "simple_rnn", "lstmemory", "grumemory", "bidirectional_lstm",
    "simple_img_conv_pool", "build_network", "NetworkModule", "LayerOut",
    "reset_graph", "graph_scope",
    # run-config surface (v1 settings()/outputs(), Flags.cpp analog)
    "settings", "outputs", "get_run_config", "RunConfig",
    # acceptance-set cost/composite layers
    "classification_cost", "mse_cost", "crf_tagging_cost",
    "simple_attention_seq2seq", "ssd_cost",
]


@dataclasses.dataclass(frozen=True)
class LayerOut:
    """Handle to a DAG node (the reference's ``LayerOutput``)."""
    graph: "_Graph"
    idx: int


class _Graph:
    def __init__(self):
        # node = (module_or_None, input_idxs, data_name_or_None, call_kwargs)
        self.nodes: List[Tuple[Optional[Module], List[int],
                               Optional[str], dict]] = []

    def add_data(self, name: str) -> LayerOut:
        self.nodes.append((None, [], name, {}))
        return LayerOut(self, len(self.nodes) - 1)

    def add(self, module: Module, inputs: Sequence[LayerOut],
            **call_kwargs) -> LayerOut:
        for i in inputs:
            if i.graph is not self:
                raise ValueError("layers from different graphs cannot mix")
        self.nodes.append((module, [i.idx for i in inputs], None,
                           dict(call_kwargs)))
        return LayerOut(self, len(self.nodes) - 1)


def _graph_of(inputs: Sequence[LayerOut]) -> _Graph:
    return inputs[0].graph


_tls = __import__("threading").local()


def _stack() -> List[_Graph]:
    # Thread-local: concurrent config builders (e.g. tests) don't share the
    # implicit graph.
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


def _ensure_graph() -> _Graph:
    stack = _stack()
    if not stack:
        stack.append(_Graph())
    return stack[-1]


def reset_graph() -> None:
    """Drop any in-progress config graph (for abandoned scripts / REPLs;
    ``build_network`` resets automatically)."""
    _stack().clear()


@contextlib.contextmanager
def graph_scope():
    """Isolated config-graph scope: pushes a fresh implicit graph and always
    pops it, so a script that raises mid-build cannot leak half-built nodes
    into the next ``data_layer()`` call (the failure mode of the module-level
    implicit graph). Use around any config script whose exceptions you
    catch::

        with config_helpers.graph_scope():
            net = build_my_network()
    """
    stack = _stack()
    g = _Graph()
    stack.append(g)
    try:
        yield g
    finally:
        # Remove this scope's graph wherever it is (build_network may have
        # already consumed it).
        if g in stack:
            stack.remove(g)


def data_layer(name: str) -> LayerOut:
    """Declare a network input (reference: ``data_layer``). Inputs feed
    ``NetworkModule.forward`` positionally in declaration order."""
    return _ensure_graph().add_data(name)


def fc_layer(input: LayerOut, size: int, act: str = "",
             bias_attr: bool = True, name=None) -> LayerOut:
    return input.graph.add(L.Linear(size, act=act, use_bias=bias_attr,
                                    name=name), [input])


def embedding_layer(input: LayerOut, size: int, vocab: int,
                    name=None) -> LayerOut:
    return input.graph.add(L.Embedding(vocab, size, name=name), [input])


def img_conv_layer(input: LayerOut, filter_size, num_filters: int,
                   stride=1, padding="SAME", act: str = "",
                   name=None) -> LayerOut:
    return input.graph.add(
        L.Conv2D(num_filters, kernel=filter_size, stride=stride,
                 padding=padding, act=act, name=name), [input])


def img_pool_layer(input: LayerOut, pool_size, stride=None,
                   pool_type: str = "max", name=None) -> LayerOut:
    return input.graph.add(L.Pool2D(pool_type, window=pool_size,
                                    stride=stride, name=name), [input])


def batch_norm_layer(input: LayerOut, act: str = "", name=None) -> LayerOut:
    out = input.graph.add(L.BatchNorm(name=name), [input])
    if act:
        out = out.graph.add(_Activation(act), [out])
    return out


def dropout_layer(input: LayerOut, dropout_rate: float,
                  name=None) -> LayerOut:
    return input.graph.add(L.Dropout(dropout_rate, name=name), [input])


def concat_layer(inputs: Sequence[LayerOut], name=None) -> LayerOut:
    return _graph_of(inputs).add(L.Concat(name=name), list(inputs))


def addto_layer(inputs: Sequence[LayerOut], act: str = "",
                name=None) -> LayerOut:
    return _graph_of(inputs).add(L.Addto(act=act, name=name), list(inputs))


def cos_sim(a: LayerOut, b: LayerOut, name=None) -> LayerOut:
    return a.graph.add(L.CosSim(name=name), [a, b])


def pooling_layer(input: LayerOut, lengths: LayerOut,
                  pooling_type: str = "average", name=None) -> LayerOut:
    return input.graph.add(_SeqPool(pooling_type, name=name),
                           [input, lengths])


def last_seq(input: LayerOut, lengths: LayerOut, name=None) -> LayerOut:
    return input.graph.add(_SeqLast(name=name), [input, lengths])


def first_seq(input: LayerOut, lengths: LayerOut, name=None) -> LayerOut:
    return input.graph.add(_SeqFirst(name=name), [input, lengths])


def simple_rnn(input: LayerOut, size: int, reverse: bool = False,
               name=None) -> LayerOut:
    return input.graph.add(R.RNN(R.SimpleRNNCell(size), reverse=reverse,
                                 name=name), [input], _take=0)


def lstmemory(input: LayerOut, size: int, reverse: bool = False,
              name=None) -> LayerOut:
    return input.graph.add(R.RNN(R.LSTMCell(size), reverse=reverse,
                                 name=name), [input], _take=0)


def grumemory(input: LayerOut, size: int, reverse: bool = False,
              name=None) -> LayerOut:
    return input.graph.add(R.RNN(R.GRUCell(size), reverse=reverse,
                                 name=name), [input], _take=0)


def bidirectional_lstm(input: LayerOut, size: int, name=None) -> LayerOut:
    return input.graph.add(
        R.BiRNN(R.LSTMCell(size), R.LSTMCell(size), name=name), [input])


def simple_img_conv_pool(input: LayerOut, filter_size, num_filters: int,
                         pool_size, act: str = "relu") -> LayerOut:
    """Composite (reference: ``networks.py`` ``simple_img_conv_pool``)."""
    conv = img_conv_layer(input, filter_size, num_filters, act=act)
    return img_pool_layer(conv, pool_size)


class _Activation(Module):
    def __init__(self, act: str, name=None):
        super().__init__(name=name)
        self.act = act

    def forward(self, x):
        from paddle_tpu.nn import activations
        return activations.get(self.act)(x)


class _SeqPool(Module):
    def __init__(self, kind: str = "average", name=None):
        super().__init__(name=name)
        self.kind = kind

    def forward(self, x, lengths):
        return S.seq_pool(x, lengths, self.kind)


class _SeqLast(Module):
    def forward(self, x, lengths):
        return S.seq_last(x, lengths)


class _SeqFirst(Module):
    def forward(self, x, lengths):
        return S.seq_first(x, lengths)


class NetworkModule(Module):
    """The compiled DAG: one serializable Module whose constructor args are
    the node list itself (modules serialize through the IR's module refs).

    ``forward(*inputs)`` feeds ``data_layer`` nodes in declaration order and
    evaluates nodes topologically (nodes are appended post-order, so list
    order IS a topological order).
    """

    def __init__(self, modules: Sequence[Optional[Module]],
                 edges: Sequence[Sequence[int]],
                 data_names: Sequence[Optional[str]],
                 takes: Sequence[int],
                 outputs: Sequence[int], name="network"):
        super().__init__(name=name)
        self.modules = list(modules)
        self.edges = [list(e) for e in edges]
        self.data_names = list(data_names)
        self.takes = list(takes)
        self.outputs = list(outputs)

    @staticmethod
    def _accepted_kwargs(mod, kwargs):
        """Pass through only the kwargs a node's forward accepts (the graph
        driver broadcasts e.g. ``train=`` but plain layers don't take it)."""
        if not kwargs:
            return kwargs
        import inspect
        try:
            sig = inspect.signature(mod.forward)
        except (TypeError, ValueError):
            return kwargs
        if any(p.kind is inspect.Parameter.VAR_KEYWORD
               for p in sig.parameters.values()):
            return kwargs
        return {k: v for k, v in kwargs.items() if k in sig.parameters}

    def input_names(self) -> List[str]:
        return [n for n in self.data_names if n is not None]

    def init_variables(self, rng, batch):
        """Initialize from a dict batch keyed by data-layer names (the CLI
        config-script contract); falls back to the single-input ``x``
        convention when names don't match."""
        names = self.input_names()
        if isinstance(batch, dict) and all(n in batch for n in names):
            return self.init(rng, *[batch[n] for n in names], train=True)
        return self.init(rng, batch["x"], train=True)

    def forward(self, *inputs, **kwargs):
        feed = list(inputs)
        values: List[Any] = []
        for mod, ins, dname, take in zip(self.modules, self.edges,
                                         self.data_names, self.takes):
            if mod is None:
                if not feed:
                    raise ValueError(
                        f"missing input for data layer {dname!r}")
                values.append(feed.pop(0))
            else:
                out = mod(*[values[i] for i in ins],
                          **self._accepted_kwargs(mod, kwargs))
                if take >= 0 and isinstance(out, tuple):
                    out = out[take]
                values.append(out)
        if feed:
            raise ValueError(
                f"{len(feed)} surplus input(s): the network declares "
                f"{sum(m is None for m in self.modules)} data layer(s)")
        outs = [values[i] for i in self.outputs]
        return outs[0] if len(outs) == 1 else tuple(outs)


def build_network(*outputs: LayerOut, name: str = "network") -> NetworkModule:
    """Freeze the current graph into a :class:`NetworkModule` and reset the
    implicit builder (each config script builds one network, like a v1
    config file)."""
    if not outputs:
        raise ValueError("build_network needs at least one output")
    g = outputs[0].graph
    for o in outputs:
        if o.graph is not g:
            raise ValueError("outputs from different graphs")
    # Remove the consumed graph; under graph_scope outer scopes survive, and
    # an abandoned implicit graph below this one is dropped too so it can't
    # leak into the next script.
    stack = _stack()
    if g in stack:
        del stack[stack.index(g):]   # g and any abandoned graphs above it
    else:
        stack.clear()
    mods = [n[0] for n in g.nodes]
    edges = [n[1] for n in g.nodes]
    names = [n[2] for n in g.nodes]
    takes = [n[3].get("_take", -1) for n in g.nodes]
    return NetworkModule(mods, edges, names, takes,
                         [o.idx for o in outputs], name=name)


# -- run-config surface (the v1 config-script workflow) -----------------------
#
# A v1 config script is a COMPLETE run description: `settings(...)` for the
# optimizer/batch knobs (reference: trainer_config_helpers/optimizers.py
# `settings`), the DSL graph for the model, and `outputs(cost)` to mark the
# cost node (reference: config_parser.py `Outputs`). The CLI
# (`python -m paddle_tpu.train.cli --config script.py`) executes the script
# and trains it with no user code — the `paddle_trainer --config=` workflow.

@dataclasses.dataclass
class RunConfig:
    network: "NetworkModule" = None
    settings: dict = dataclasses.field(default_factory=dict)
    train_reader: Any = None
    test_reader: Any = None


def _run_cfg() -> RunConfig:
    if not hasattr(_tls, "run_cfg") or _tls.run_cfg is None:
        _tls.run_cfg = RunConfig()
    return _tls.run_cfg


def settings(**kw) -> None:
    """Record run settings (reference: ``settings(batch_size=...,
    learning_rate=..., ...)`` in every v1 config script). Recognised keys:
    batch_size, learning_rate, optimizer (name in paddle_tpu.optim),
    num_passes, evaluator, plus free-form extras the CLI flags can read."""
    _run_cfg().settings.update(kw)


def outputs(*outs: LayerOut, name: str = "network") -> "NetworkModule":
    """Freeze the graph (like :func:`build_network`) AND record it as the
    run's network (reference: ``outputs(...)`` in config scripts)."""
    net = build_network(*outs, name=name)
    _run_cfg().network = net
    return net


def get_run_config(reset: bool = True) -> RunConfig:
    """Collect what the config script declared (CLI entry point). The
    script's reader callables are picked off the returned object by the CLI
    (scripts set ``cfg = get_run_config`` indirection is NOT needed — the
    CLI assigns script-level ``train_reader``/``test_reader`` itself)."""
    cfg = _run_cfg()
    if reset:
        _tls.run_cfg = None
    return cfg


# -- acceptance-set cost & composite layers -----------------------------------

class _FnCost(Module):
    """Generic (out, label) -> per-example cost node."""

    def __init__(self, kind: str, name=None):
        super().__init__(name=name)
        self.kind = kind

    def forward(self, out, label):
        from paddle_tpu.nn import costs as C
        return {"softmax_ce": C.softmax_cross_entropy,
                "mse": C.mse}[self.kind](out, label)


def classification_cost(input: LayerOut, label: LayerOut,
                        name=None) -> LayerOut:
    """Per-example softmax cross-entropy (reference: ``classification_cost``,
    trainer_config_helpers/layers.py)."""
    return input.graph.add(_FnCost("softmax_ce", name=name), [input, label])


def mse_cost(input: LayerOut, label: LayerOut, name=None) -> LayerOut:
    return input.graph.add(_FnCost("mse", name=name), [input, label])


class _CrfTaggingCost(Module):
    """Sparse linear-CRF tagger cost over (tokens, length, label)
    (reference: ``v1_api_demo/sequence_tagging/linear_crf.py`` —
    crf_layer + sparse feature projections)."""

    def __init__(self, vocab: int, num_tags: int, context: int = 2,
                 name=None):
        super().__init__(name=name)
        from paddle_tpu.models.tagging import LinearCrfTagger
        self.tagger = LinearCrfTagger(vocab, num_tags, context=context,
                                      name="tagger")

    def forward(self, tokens, length, label, train: bool = False):
        return self.tagger({"tokens": tokens, "length": length,
                            "label": label}, train=train)

    def decode(self, tokens, length):
        return self.tagger.decode({"tokens": tokens, "length": length})


def crf_tagging_cost(tokens: LayerOut, length: LayerOut, label: LayerOut,
                     vocab: int, num_tags: int, context: int = 2,
                     name=None) -> LayerOut:
    """Linear-chain CRF sequence-tagging cost (reference: ``crf_layer``,
    trainer_config_helpers/layers.py + linear_crf.py demo)."""
    return tokens.graph.add(
        _CrfTaggingCost(vocab, num_tags, context=context, name=name),
        [tokens, length, label])


class _Seq2SeqCost(Module):
    """Attention seq2seq teacher-forcing cost over (src, src_len, tgt,
    tgt_len) (reference: ``simple_attention``, networks.py:1320, as used by
    the seqToseq demo)."""

    def __init__(self, src_vocab: int, tgt_vocab: int, emb_dim: int = 128,
                 hidden: int = 256, name=None):
        super().__init__(name=name)
        from paddle_tpu.models.seq2seq import Seq2SeqAttention
        self.model = Seq2SeqAttention(src_vocab, tgt_vocab, emb_dim=emb_dim,
                                      hidden=hidden, name="seq2seq")

    def forward(self, src, src_len, tgt, tgt_len, train: bool = False):
        return self.model({"src": src, "src_len": src_len, "tgt": tgt,
                           "tgt_len": tgt_len}, train=train)


def simple_attention_seq2seq(src: LayerOut, src_len: LayerOut,
                             tgt: LayerOut, tgt_len: LayerOut,
                             src_vocab: int, tgt_vocab: int,
                             emb_dim: int = 128, hidden: int = 256,
                             name=None) -> LayerOut:
    """Attention encoder-decoder cost (reference: ``simple_attention``
    recurrent group, networks.py:1320)."""
    return src.graph.add(
        _Seq2SeqCost(src_vocab, tgt_vocab, emb_dim=emb_dim, hidden=hidden,
                     name=name), [src, src_len, tgt, tgt_len])


class _SSDCost(Module):
    """SSD heads + multibox loss over backbone feature maps
    (reference: ``MultiBoxLossLayer`` + the SSD config family)."""

    def __init__(self, num_classes, feature_shapes, image_shape, min_sizes,
                 max_sizes=(), name=None):
        super().__init__(name=name)
        from paddle_tpu.models.ssd import SSDHead
        self.head = SSDHead(num_classes, feature_shapes, image_shape,
                            min_sizes, max_sizes, name="head")
        self.loss = self.head.multibox_loss()

    def forward(self, *args):
        feats, (gt_boxes, gt_labels) = list(args[:-2]), args[-2:]
        loc, conf = self.head(feats)
        return self.loss(loc, conf, gt_boxes, gt_labels)


def ssd_cost(features: Sequence[LayerOut], gt_boxes: LayerOut,
             gt_labels: LayerOut, num_classes: int,
             feature_shapes: Sequence[Tuple[int, int]],
             image_shape: Tuple[int, int], min_sizes: Sequence[float],
             max_sizes: Sequence[float] = (), name=None) -> LayerOut:
    """Multi-scale SSD loc/conf heads + multibox training loss (reference:
    the SSD detection config; ``MultiBoxLossLayer.cpp``)."""
    return _graph_of(list(features)).add(
        _SSDCost(num_classes, feature_shapes, image_shape, min_sizes,
                 max_sizes, name=name),
        list(features) + [gt_boxes, gt_labels])


# -- thin wrappers widening the v1 DSL surface --------------------------------
# (reference: trainer_config_helpers/layers.py — ~100 one-module wrappers;
# each maps 1:1 onto a library Module, so the DSL name set keeps growing at
# near-zero cost. All follow the same shape: append one node.)

def maxout_layer(input: LayerOut, groups: int, name=None) -> LayerOut:
    return input.graph.add(L.Maxout(groups, name=name), [input])


def bias_layer(input: LayerOut, name=None) -> LayerOut:
    return input.graph.add(L.Bias(name=name), [input])


def scale_shift_layer(input: LayerOut, name=None) -> LayerOut:
    return input.graph.add(L.ScaleShift(name=name), [input])


def interpolation_layer(a: LayerOut, b: LayerOut, w: LayerOut,
                        name=None) -> LayerOut:
    return a.graph.add(L.Interpolation(name=name), [a, b, w])


def power_layer(input: LayerOut, p: LayerOut, name=None) -> LayerOut:
    return input.graph.add(L.Power(name=name), [input, p])


def scaling_layer(input: LayerOut, s: LayerOut, name=None) -> LayerOut:
    return input.graph.add(L.Scaling(name=name), [input, s])


def slope_intercept_layer(input: LayerOut, slope: float = 1.0,
                          intercept: float = 0.0, name=None) -> LayerOut:
    return input.graph.add(L.SlopeIntercept(slope, intercept, name=name),
                           [input])


def sum_to_one_norm_layer(input: LayerOut, name=None) -> LayerOut:
    return input.graph.add(L.SumToOneNorm(name=name), [input])


def row_l2_norm_layer(input: LayerOut, name=None) -> LayerOut:
    return input.graph.add(L.RowL2Norm(name=name), [input])


def l2_distance_layer(a: LayerOut, b: LayerOut, name=None) -> LayerOut:
    return a.graph.add(L.L2Distance(name=name), [a, b])


def outer_prod_layer(a: LayerOut, b: LayerOut, name=None) -> LayerOut:
    return a.graph.add(L.OuterProd(name=name), [a, b])


def conv_shift_layer(a: LayerOut, b: LayerOut, name=None) -> LayerOut:
    return a.graph.add(L.ConvShift(name=name), [a, b])


def pad_layer(input: LayerOut, pad, name=None) -> LayerOut:
    return input.graph.add(L.Pad2D(pad, name=name), [input])


def crop_layer(input: LayerOut, offsets, shape, name=None) -> LayerOut:
    return input.graph.add(L.Crop2D(offsets, shape, name=name), [input])


def resize_layer(input: LayerOut, size, name=None) -> LayerOut:
    return input.graph.add(L.Resize(size, name=name), [input])


def rotate_layer(input: LayerOut, name=None) -> LayerOut:
    return input.graph.add(L.Rotate(name=name), [input])


def multiplex_layer(index: LayerOut, inputs: Sequence[LayerOut],
                    name=None) -> LayerOut:
    return index.graph.add(L.Multiplex(name=name), [index] + list(inputs))


def featuremap_expand_layer(input: LayerOut, num: int, name=None) -> LayerOut:
    return input.graph.add(L.FeatureMapExpand(num, name=name), [input])


def block_expand_layer(input: LayerOut, block, stride=None,
                       name=None) -> LayerOut:
    return input.graph.add(L.BlockExpand(block, stride, name=name), [input])


def spp_layer(input: LayerOut, levels: int = 3, pool_type: str = "max",
              name=None) -> LayerOut:
    """Pyramid levels are powers of two: level l pools a 2^l x 2^l grid
    (reference: SpatialPyramidPoolLayer pyramid_height)."""
    return input.graph.add(L.SpatialPyramidPool(levels, pool_type,
                                                name=name), [input])


def img_cmrnorm_layer(input: LayerOut, size: int = 5, name=None) -> LayerOut:
    return input.graph.add(L.CrossMapNormal(size, name=name), [input])


def row_conv_layer(input: LayerOut, future: int, name=None) -> LayerOut:
    return input.graph.add(L.RowConv(future, name=name), [input])


def depthwise_conv_layer(input: LayerOut, filter_size, multiplier: int = 1,
                         stride=1, act: str = "", name=None) -> LayerOut:
    return input.graph.add(
        L.DepthwiseConv2D(multiplier, kernel=filter_size, stride=stride,
                          act=act, name=name), [input])


def img_conv_transpose_layer(input: LayerOut, filter_size, num_filters: int,
                             stride=1, act: str = "", name=None) -> LayerOut:
    return input.graph.add(
        L.Conv2DTranspose(num_filters, kernel=filter_size, stride=stride,
                          act=act, name=name), [input])


def layer_norm_layer(input: LayerOut, name=None) -> LayerOut:
    return input.graph.add(L.LayerNorm(name=name), [input])


def global_pool_layer(input: LayerOut, pool_type: str = "avg",
                      name=None) -> LayerOut:
    return input.graph.add(L.GlobalPool(pool_type, name=name), [input])


def sampling_id_layer(input: LayerOut, name=None) -> LayerOut:
    return input.graph.add(L.SamplingId(name=name), [input])


__all__ += [
    "maxout_layer", "bias_layer", "scale_shift_layer", "interpolation_layer",
    "power_layer", "scaling_layer", "slope_intercept_layer",
    "sum_to_one_norm_layer", "row_l2_norm_layer", "l2_distance_layer",
    "outer_prod_layer", "conv_shift_layer", "pad_layer", "crop_layer",
    "resize_layer", "rotate_layer", "multiplex_layer",
    "featuremap_expand_layer", "block_expand_layer", "spp_layer",
    "img_cmrnorm_layer", "row_conv_layer", "depthwise_conv_layer",
    "img_conv_transpose_layer", "layer_norm_layer", "global_pool_layer",
    "sampling_id_layer",
]


# -- composite networks (the networks.py tier) --------------------------------

def img_conv_group(input: LayerOut, num_filters: Sequence[int],
                   filter_size=3, pool_size=2, act: str = "relu",
                   with_bn: bool = False) -> LayerOut:
    """Conv(xN) -> [BN] -> pool block (reference: ``img_conv_group``,
    networks.py)."""
    h = input
    for nf in num_filters:
        h = img_conv_layer(h, filter_size, nf,
                           act="" if with_bn else act)
        if with_bn:
            h = batch_norm_layer(h, act=act)
    return img_pool_layer(h, pool_size)


class _Flatten(Module):
    def forward(self, x):
        return x.reshape(x.shape[0], -1)


def flatten_layer(input: LayerOut, name=None) -> LayerOut:
    return input.graph.add(_Flatten(name=name), [input])


def vgg_16_network(input_image: LayerOut, num_classes: int = 1000,
                   with_bn: bool = True) -> LayerOut:
    """VGG-16 head-to-logits composite (reference: ``vgg_16_network``,
    networks.py:468)."""
    h = input_image
    for filters, reps in ((64, 2), (128, 2), (256, 3), (512, 3), (512, 3)):
        h = img_conv_group(h, [filters] * reps, with_bn=with_bn)
    h = flatten_layer(h)
    h = fc_layer(h, size=4096, act="relu")
    h = dropout_layer(h, 0.5)
    h = fc_layer(h, size=4096, act="relu")
    h = dropout_layer(h, 0.5)
    return fc_layer(h, size=num_classes)


def simple_lstm(input: LayerOut, size: int, reverse: bool = False) -> LayerOut:
    """fc -> lstm composite (reference: ``simple_lstm``, networks.py:553 —
    the input projection lives outside the recurrence)."""
    return lstmemory(fc_layer(input, size=size * 4), size, reverse=reverse)


def simple_gru(input: LayerOut, size: int, reverse: bool = False) -> LayerOut:
    """fc -> gru composite (reference: ``simple_gru``, networks.py:997)."""
    return grumemory(fc_layer(input, size=size * 3), size, reverse=reverse)


def sequence_conv_pool(input: LayerOut, lengths: LayerOut,
                       context_len: int, hidden_size: int,
                       pooling_type: str = "max") -> LayerOut:
    """Context-window conv over a sequence then pool (reference:
    ``sequence_conv_pool``, networks.py — the text-classification block)."""
    ctx = input.graph.add(L.ContextProjection(context_len), [input])
    h = fc_layer(ctx, size=hidden_size, act="tanh")
    return pooling_layer(h, lengths, pooling_type=pooling_type)


__all__ += ["img_conv_group", "vgg_16_network", "simple_lstm", "simple_gru",
            "sequence_conv_pool", "flatten_layer"]
