"""Record-file data format — the RecordIO role in the reference's cloud
data path (Go master partitions datasets over RecordIO chunks,
``go/master/service.go:30,59,253``; wire schema ``proto/DataFormat.proto``).

Format: a stream of ``[u32 length][u32 crc32][payload]`` records plus a JSON
sidecar index (``path + '.idx'``) holding every record's byte offset. The
index is what makes the format *shardable*: hosts partition records
deterministically without reading each other's bytes — the task-queue role
collapsed into static sharding (see DESIGN_DECISIONS.md, Go-master row).

Payloads are bytes; :func:`write_samples` / :func:`read_samples` layer a
numpy (npz) codec on top for dict-of-array samples.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

import numpy as np

__all__ = ["RecordWriter", "read_records", "write_samples", "read_samples",
           "sharded_records", "num_records", "recover_index"]

_HEADER = struct.Struct("<II")           # length, crc32


class RecordWriter:
    """Append-only record writer; writes the index sidecar on close."""

    def __init__(self, path: str):
        self.path = path
        # opening a writer invalidates the file NOW: a stale index from an
        # earlier write must not outlive the data it described
        try:
            os.remove(path + ".idx")
        except FileNotFoundError:
            pass
        self._f = open(path, "wb")
        self._offsets: List[int] = []

    def write(self, payload: bytes) -> None:
        self._offsets.append(self._f.tell())
        self._f.write(_HEADER.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)

    def close(self) -> None:
        self._f.close()
        with open(self.path + ".idx", "w") as f:
            json.dump({"offsets": self._offsets}, f)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            # failed write: close the data file but do NOT publish an index —
            # a possibly-truncated file must look incomplete, not valid
            self._f.close()
            return
        self.close()


def _read_at(f, offset: int) -> bytes:
    f.seek(offset)
    head = f.read(_HEADER.size)
    if len(head) < _HEADER.size:
        raise IOError("truncated record header")
    length, crc = _HEADER.unpack(head)
    payload = f.read(length)
    if len(payload) < length:
        raise IOError("truncated record payload")
    if zlib.crc32(payload) != crc:
        raise IOError(f"record crc mismatch at offset {offset}")
    return payload


def recover_index(path: str, write: bool = True) -> List[int]:
    """Rebuild the offset index by scanning the raw record stream with CRC
    verification — the sidecar is a cache, not the source of truth (the Go
    master rebuilt its chunk index the same way,
    ``go/master/service.go:253``). Hot loop is native
    (``native/packer.cpp:ptn_recordio_scan``) with a tested-equal Python
    fallback. Raises on the first corrupt/truncated record."""
    with open(path, "rb") as f:
        data = f.read()

    from .. import native
    L = native.lib()
    offsets: List[int] = []
    if L is not None:
        import ctypes

        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) \
            if data else (ctypes.c_uint8 * 1)()
        max_records = len(data) // _HEADER.size + 1
        out = (ctypes.c_int64 * max_records)()
        n = L.ptn_recordio_scan(buf, len(data), max_records, out)
        if n < 0:
            raise IOError(f"corrupt record stream in {path} at byte "
                          f"{-(n + 1)}")
        offsets = list(out[:n])
    else:
        off = 0
        while off < len(data):
            if off + _HEADER.size > len(data):
                raise IOError(f"corrupt record stream in {path} at byte "
                              f"{off}")
            length, crc = _HEADER.unpack_from(data, off)
            payload = data[off + _HEADER.size: off + _HEADER.size + length]
            if len(payload) < length or zlib.crc32(payload) != crc:
                raise IOError(f"corrupt record stream in {path} at byte "
                              f"{off}")
            offsets.append(off)
            off += _HEADER.size + length
    if write:
        with open(path + ".idx", "w") as f:
            json.dump({"offsets": offsets}, f)
    return offsets


def _offsets(path: str) -> List[int]:
    try:
        with open(path + ".idx") as f:
            return json.load(f)["offsets"]
    except FileNotFoundError:
        # lost sidecar: recover by scanning (never fatal for intact data)
        return recover_index(path)


def num_records(path: str) -> int:
    return len(_offsets(path))


def read_records(path: str) -> Iterator[bytes]:
    """Sequential CRC-checked record stream."""
    offs = _offsets(path)
    with open(path, "rb") as f:
        for o in offs:
            yield _read_at(f, o)


def sharded_records(path: str, num_shards: int,
                    shard_id: int) -> Iterator[bytes]:
    """This shard's records (index-based seek — no scan over other shards'
    bytes; the Go master's chunk partitioning done statically)."""
    offs = _offsets(path)
    with open(path, "rb") as f:
        for i in range(shard_id, len(offs), num_shards):
            yield _read_at(f, offs[i])


def _encode_sample(sample: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in sample.items()})
    return buf.getvalue()


def _decode_sample(payload: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(payload), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def write_samples(path: str, samples: Iterable[Dict[str, Any]]) -> int:
    """Write dict-of-array samples; returns the record count."""
    n = 0
    with RecordWriter(path) as w:
        for s in samples:
            w.write(_encode_sample(s))
            n += 1
    return n


def read_samples(path: str, num_shards: int = 1, shard_id: int = 0):
    """Reader-combinator-style callable yielding dict samples (drop straight
    into ``data.batched``/``data.map_readers``)."""
    def reader():
        it = (read_records(path) if num_shards == 1
              else sharded_records(path, num_shards, shard_id))
        for payload in it:
            yield _decode_sample(payload)
    reader.num_samples = (num_records(path) + num_shards - 1 - shard_id) \
        // num_shards if num_shards > 1 else num_records(path)
    return reader
