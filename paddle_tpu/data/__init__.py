"""Data pipeline: reader combinators + dataset loaders (successor of
paddle.v2.reader / paddle.v2.dataset / PyDataProvider2)."""

from . import datasets, image, recordio
from .reader import (batched, buffered, chain, compose, cycle, firstn,
                     map_readers, prefetch, sharded, shuffle, xmap)
