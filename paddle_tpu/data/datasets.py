"""Dataset loaders — the v2 ``paddle.v2.dataset`` surface.

Reference: ``/root/reference/python/paddle/v2/dataset/`` (mnist, cifar, imdb,
uci_housing, wmt14, movielens, conll05, imikolov, sentiment, voc2012 …) with
auto-download & cache (``dataset/common.py``). This environment has zero egress,
so every loader first checks the local cache dir (``~/.cache/paddle_tpu``, or
``PADDLE_TPU_DATA``) for the standard files and otherwise falls back to a
*deterministic synthetic* dataset with the same shapes/vocab so every demo,
test, and benchmark runs anywhere. Synthetic data is clearly flagged via
``is_synthetic``.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Callable, Iterator, Optional, Tuple

import numpy as np

__all__ = ["data_home", "mnist", "cifar10", "cifar100", "uci_housing",
           "imdb", "synthetic_nmt",
           "synthetic_tagging", "synthetic_ctr", "movielens", "conll05",
           "imikolov", "wmt14", "voc2012", "mq2007", "sentiment", "flowers",
           "traffic"]


def data_home() -> str:
    return os.environ.get(
        "PADDLE_TPU_DATA",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu"))


def _synth_images(n: int, classes: int, hw: Tuple[int, int], channels: int,
                  seed: int, proto_seed: int = 1234,
                  label_noise: float = 0.1):
    """Synthetic image set: class-dependent blob pattern + pixel noise +
    ``label_noise`` fraction of labels resampled uniformly over the OTHER
    classes. The label noise gives the task an irreducible Bayes error of
    about ``label_noise`` on held-out splits, so a model that reports 0
    test error on it is broken, not good (VERDICT r4 weak #4). The class
    prototypes come from ``proto_seed`` so train/test splits (which differ
    only in ``seed``) are draws from the SAME task."""
    rng = np.random.RandomState(seed)
    h, w = hw
    protos = np.random.RandomState(proto_seed).uniform(
        -1, 1, size=(classes, h, w, channels)).astype(np.float32)
    labels = rng.randint(0, classes, size=n).astype(np.int32)
    noise = rng.normal(0, 0.7, size=(n, h, w, channels)).astype(np.float32)
    images = protos[labels] + noise
    if label_noise > 0 and classes > 1:
        flip = rng.uniform(size=n) < label_noise
        shift = rng.randint(1, classes, size=n)       # uniform other class
        labels = np.where(flip, (labels + shift) % classes,
                          labels).astype(np.int32)
    return images, labels


def _mnist_files(split):
    base = os.path.join(data_home(), "mnist")
    if split == "train":
        return (os.path.join(base, "train-images-idx3-ubyte.gz"),
                os.path.join(base, "train-labels-idx1-ubyte.gz"))
    return (os.path.join(base, "t10k-images-idx3-ubyte.gz"),
            os.path.join(base, "t10k-labels-idx1-ubyte.gz"))


def _read_idx_images(path):
    with gzip.open(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(n, rows, cols, 1).astype(np.float32) / 127.5 - 1.0


def _read_idx_labels(path):
    with gzip.open(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        return np.frombuffer(f.read(), dtype=np.uint8).astype(np.int32)


# Public MNIST idx files (stable S3 mirror) + their well-known md5s — the
# URL/md5 table the reference keeps per dataset module (common.py pattern).
_MNIST_URLS = {
    "train-images-idx3-ubyte.gz": "f68b3c2dcbeaaa9fbdd348bbdeb94873",
    "train-labels-idx1-ubyte.gz": "d53e105ee54ea40749a09fcbcd1e9432",
    "t10k-images-idx3-ubyte.gz": "9fb629c4189551a2d022fa330f9573f3",
    "t10k-labels-idx1-ubyte.gz": "ec29112dd5afa0611ce80d1b7f02629c",
}
_MNIST_BASE = "https://ossci-datasets.s3.amazonaws.com/mnist/"


def _try_download_mnist(split):
    from .download import DownloadDisabled, download, downloads_enabled
    if not downloads_enabled():
        return
    names = (["train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"]
             if split == "train" else
             ["t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"])
    try:
        for n in names:
            download(_MNIST_BASE + n, "mnist", _MNIST_URLS[n])
    except (DownloadDisabled, IOError):
        pass                            # loader falls back to synthetic


def mnist(split: str = "train", synthetic_n: Optional[int] = None):
    """MNIST reader (reference: ``v2/dataset/mnist.py``) yielding
    ``(image [28,28,1] float32 in [-1,1], label int)``. Auto-downloads into
    the cache when ``PADDLE_TPU_AUTO_DOWNLOAD=1`` (``data/download.py``, the
    common.py analog); otherwise falls back to a deterministic synthetic set
    when the idx files aren't cached locally."""
    imgs_p, lbls_p = _mnist_files(split)
    if not (os.path.exists(imgs_p) and os.path.exists(lbls_p)):
        _try_download_mnist(split)
    if os.path.exists(imgs_p) and os.path.exists(lbls_p):
        images = _read_idx_images(imgs_p)
        labels = _read_idx_labels(lbls_p)
        is_synthetic = False
    else:
        n = synthetic_n or (8192 if split == "train" else 2048)
        images, labels = _synth_images(n, 10, (28, 28), 1,
                                       seed=0 if split == "train" else 1)
        is_synthetic = True

    def reader():
        for i in range(len(labels)):
            yield images[i], labels[i]
    reader.is_synthetic = is_synthetic
    reader.num_samples = len(labels)
    return reader


_CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
_CIFAR10_MD5 = "c58f30108f718f92721af3b95e74349a"
_CIFAR100_URL = "https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz"
_CIFAR100_MD5 = "eb9058c3a382ffc7106e4002c42a8d85"


def _try_download_cifar(url, md5):
    from .download import DownloadDisabled, download, downloads_enabled
    if not downloads_enabled():
        return
    try:
        tar = download(url, "cifar", md5)
    except (DownloadDisabled, IOError):
        return
    import tarfile
    with tarfile.open(tar, "r:gz") as tf:
        tf.extractall(data_home(), filter="data")


def _cifar_reader(base, files, label_key, num_classes, url, md5, split,
                  synthetic_n, synth_seeds):
    """Shared CIFAR-10/100 loader (both splits live in one pickle format;
    reference ``v2/dataset/cifar.py`` serves the two sets from one
    ``reader_creator``)."""
    paths = [os.path.join(base, f) for f in files]
    if not all(os.path.exists(p) for p in paths):
        _try_download_cifar(url, md5)
    if all(os.path.exists(p) for p in paths):
        import pickle
        xs, ys = [], []
        for p in paths:
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.float32))
            ys.extend(d[label_key])
        images = (np.concatenate(xs).reshape(-1, 3, 32, 32)
                  .transpose(0, 2, 3, 1) / 127.5 - 1.0).astype(np.float32)
        labels = np.asarray(ys, np.int32)
        is_synthetic = False
    else:
        n = synthetic_n or (8192 if split == "train" else 2048)
        images, labels = _synth_images(
            n, num_classes, (32, 32), 3,
            seed=synth_seeds[0] if split == "train" else synth_seeds[1],
            proto_seed=synth_seeds[2])
        is_synthetic = True

    def reader():
        for i in range(len(labels)):
            yield images[i], labels[i]
    reader.is_synthetic = is_synthetic
    reader.num_samples = len(labels)
    return reader


def cifar10(split: str = "train", synthetic_n: Optional[int] = None):
    """CIFAR-10 reader (reference: ``v2/dataset/cifar.py``) yielding
    ``(image [32,32,3], label)``; auto-download via ``data/download.py``
    when enabled, synthetic fallback otherwise."""
    files = ([f"data_batch_{i}" for i in range(1, 6)] if split == "train"
             else ["test_batch"])
    return _cifar_reader(os.path.join(data_home(), "cifar-10-batches-py"),
                         files, b"labels", 10, _CIFAR10_URL, _CIFAR10_MD5,
                         split, synthetic_n, (2, 3, 4321))


def cifar100(split: str = "train", synthetic_n: Optional[int] = None,
             label_kind: str = "fine"):
    """CIFAR-100 reader (reference: ``v2/dataset/cifar.py`` serves 10 and
    100 from the same pickle format) yielding ``(image [32,32,3], label)``
    with fine (100-way) or coarse (20-way) labels."""
    assert label_kind in ("fine", "coarse")
    key = (b"fine_labels" if label_kind == "fine" else b"coarse_labels")
    return _cifar_reader(os.path.join(data_home(), "cifar-100-python"),
                         ["train" if split == "train" else "test"],
                         key, 100 if label_kind == "fine" else 20,
                         _CIFAR100_URL, _CIFAR100_MD5,
                         split, synthetic_n, (5, 6, 8765))


def uci_housing(split: str = "train"):
    """UCI housing regression (reference: ``v2/dataset/uci_housing.py``):
    13 features -> price. Synthetic linear+noise fallback with fixed weights."""
    path = os.path.join(data_home(), "housing.data")
    if os.path.exists(path):
        data = np.loadtxt(path).astype(np.float32)
        feats, target = data[:, :-1], data[:, -1:]
        feats = (feats - feats.mean(0)) / (feats.std(0) + 1e-6)
        n_train = int(len(data) * 0.8)
        sl = slice(0, n_train) if split == "train" else slice(n_train, None)
        feats, target = feats[sl], target[sl]
        is_synthetic = False
    else:
        rng = np.random.RandomState(4 if split == "train" else 5)
        n = 4096 if split == "train" else 512
        w = np.linspace(-2, 2, 13).astype(np.float32)
        feats = rng.normal(size=(n, 13)).astype(np.float32)
        target = (feats @ w + 3.0 + rng.normal(0, 0.1, n)).astype(
            np.float32)[:, None]
        is_synthetic = True

    def reader():
        for i in range(len(target)):
            yield feats[i], target[i]
    reader.is_synthetic = is_synthetic
    reader.num_samples = len(target)
    return reader


_IMDB_URL = "https://ai.stanford.edu/~amaas/data/sentiment/aclImdb_v1.tar.gz"
_IMDB_MD5 = "7c2ac02c03563afcf9b574c7e56c153a"


def _imdb_tar_path():
    from .download import DownloadDisabled, download, downloads_enabled
    path = os.path.join(data_home(), "imdb", "aclImdb_v1.tar.gz")
    if os.path.exists(path):
        return path
    if downloads_enabled():
        try:
            return download(_IMDB_URL, "imdb", _IMDB_MD5)
        except (DownloadDisabled, IOError):
            pass
    return None


def _imdb_real(split, vocab_size, max_len):
    """Parse the aclImdb tarball (reference: ``v2/dataset/imdb.py`` —
    tokenize, build the frequency word dict from train, map to ids).
    Returns (samples, labels) lists or None when no tarball is cached."""
    tar_path = _imdb_tar_path()
    if tar_path is None:
        return None
    import collections
    import re
    import tarfile
    token_re = re.compile(r"[a-z']+")

    def docs(section):
        with tarfile.open(tar_path, "r:gz") as tf:
            for m in tf:
                parts = m.name.split("/")
                if len(parts) == 4 and parts[1] == section and \
                        parts[2] in ("pos", "neg") and m.isfile():
                    text = tf.extractfile(m).read().decode(
                        "utf-8", errors="replace").lower()
                    yield token_re.findall(text), int(parts[2] == "pos")

    freq = collections.Counter()
    for toks, _ in docs("train"):
        freq.update(toks)
    # id 0 = <unk>; 1..vocab_size-1 = most frequent words (dict order of the
    # reference's build_dict: frequency desc, word asc for ties)
    vocab = {w: i + 1 for i, (w, _) in enumerate(
        sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        [:vocab_size - 1])}
    samples, labels = [], []
    for toks, lab in docs("train" if split == "train" else "test"):
        ids = np.asarray([vocab.get(t, 0) for t in toks[:max_len]], np.int32)
        samples.append(ids)
        labels.append(lab)
    return samples, labels


def imdb(split: str = "train", vocab_size: int = 5000, max_len: int = 100,
         synthetic_n: Optional[int] = None):
    """IMDB sentiment (reference: ``v2/dataset/imdb.py``) yielding
    ``(token_ids varying-length, label 0/1)``. Uses the real aclImdb corpus
    when cached or downloadable (``PADDLE_TPU_AUTO_DOWNLOAD=1``); synthetic
    fallback generates label-correlated token distributions (positive
    reviews draw from the upper vocab half more often) so models actually
    learn."""
    real = _imdb_real(split, vocab_size, max_len)
    if real is not None:
        samples, labels = real

        def reader():
            for ids, lab in zip(samples, labels):
                yield ids, lab
        reader.is_synthetic = False
        reader.num_samples = len(labels)
        return reader

    n = synthetic_n or (4096 if split == "train" else 1024)

    def reader():
        # 5% label flips make the task's Bayes error ~0.05 (a model scoring
        # 0 error on held-out data is broken, not good); synthetic_tagging
        # and synthetic_ctr are already stochastic by construction
        rng = np.random.RandomState(6 if split == "train" else 7)
        for i in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(max_len // 4, max_len))
            # class-dependent token bias
            if label:
                ids = rng.zipf(1.3, size=length) % (vocab_size // 2) \
                    + vocab_size // 2
            else:
                ids = rng.zipf(1.3, size=length) % (vocab_size // 2)
            if rng.rand() < 0.05:
                label = 1 - label
            yield ids.astype(np.int32), label
    reader.is_synthetic = True
    reader.num_samples = n
    return reader


def synthetic_nmt(split: str = "train", src_vocab: int = 1000,
                  tgt_vocab: int = 1000, max_len: int = 30,
                  n: Optional[int] = None):
    """Synthetic translation pairs with a learnable structure (target =
    reversed source mapped through a fixed permutation) — stands in for
    ``v2/dataset/wmt14.py`` in the zero-egress environment. ids 0/1/2 reserved
    for pad/bos/eos."""
    n = n or (4096 if split == "train" else 512)
    perm = np.random.RandomState(42).permutation(src_vocab)

    def reader():
        rng = np.random.RandomState(8 if split == "train" else 9)
        for i in range(n):
            length = int(rng.randint(3, max_len - 2))
            src = rng.randint(3, src_vocab, size=length).astype(np.int32)
            tgt = (perm[src[::-1]] % (tgt_vocab - 3) + 3).astype(np.int32)
            yield src, tgt
    reader.is_synthetic = True
    reader.num_samples = n
    return reader


def synthetic_tagging(split: str = "train", vocab: int = 2000, n_tags: int = 9,
                      max_len: int = 40, n: Optional[int] = None):
    """Synthetic sequence-tagging set (stands in for the reference's
    sequence_tagging demo data, ``v1_api_demo/sequence_tagging``): tag depends
    on token range + previous tag, so CRF transitions matter."""
    n = n or (4096 if split == "train" else 512)

    def reader():
        rng = np.random.RandomState(10 if split == "train" else 11)
        for i in range(n):
            length = int(rng.randint(5, max_len))
            toks = rng.randint(0, vocab, size=length).astype(np.int32)
            tags = np.zeros(length, np.int32)
            for t in range(length):
                base = (toks[t] * n_tags) // vocab
                if t and rng.rand() < 0.3:
                    tags[t] = tags[t - 1]  # sticky transitions
                else:
                    tags[t] = base
            yield toks, tags
    reader.is_synthetic = True
    reader.num_samples = n
    return reader


def synthetic_ctr(split: str = "train", num_fields: int = 8,
                  vocab_per_field: int = 10000, n: Optional[int] = None):
    """Synthetic CTR set (stands in for the reference's quick_start sparse demo,
    ``v1_api_demo/quick_start/trainer_config.lr.py``): sparse categorical ids
    per field; click prob from a hidden per-field weight table."""
    n = n or (16384 if split == "train" else 2048)
    hidden = np.random.RandomState(43).normal(
        0, 1.0, size=(num_fields, vocab_per_field)).astype(np.float32)

    def reader():
        rng = np.random.RandomState(12 if split == "train" else 13)
        for i in range(n):
            ids = np.array([rng.randint(0, vocab_per_field)
                            for _ in range(num_fields)], np.int32)
            score = sum(hidden[f, ids[f]] for f in range(num_fields))
            p = 1.0 / (1.0 + np.exp(-score))
            label = np.int32(rng.rand() < p)
            yield ids, label
    reader.is_synthetic = True
    reader.num_samples = n
    return reader


_ML1M_URL = "https://files.grouplens.org/datasets/movielens/ml-1m.zip"
_ML1M_MD5 = "c4d9eecfca2ab87c1945afe126590906"
_ML_GENRES = ["Action", "Adventure", "Animation", "Children's", "Comedy",
              "Crime"]          # first 6 kept (fixed [6] feature contract)


def _movielens_real(split):
    """Parse the real ml-1m archive (reference: ``v2/dataset/movielens.py``
    — users.dat/movies.dat/ratings.dat '::'-separated). Deterministic 90/10
    train/test split by rating index."""
    from .download import DownloadDisabled, download, downloads_enabled
    path = os.path.join(data_home(), "movielens", "ml-1m.zip")
    if not os.path.exists(path):
        if not downloads_enabled():
            return None
        try:
            path = download(_ML1M_URL, "movielens", _ML1M_MD5)
        except (DownloadDisabled, IOError):
            return None
    import zipfile

    def rows(zf, name):
        with zf.open(name) as f:
            for raw in f.read().decode("latin-1").splitlines():
                if raw.strip():
                    yield raw.split("::")

    with zipfile.ZipFile(path) as zf:
        users = {}
        for uid, gender, age, occ, _zip in rows(zf, "ml-1m/users.dat"):
            users[int(uid)] = np.asarray(
                [int(gender == "M"), int(age) // 10, int(occ), 0], np.int32)
        movies = {}
        for mid, _title, genres in rows(zf, "ml-1m/movies.dat"):
            gset = set(genres.split("|"))
            movies[int(mid)] = np.asarray(
                [int(g in gset) for g in _ML_GENRES], np.int32)
        samples = []
        for i, (uid, mid, rating, _ts) in enumerate(
                rows(zf, "ml-1m/ratings.dat")):
            if (i % 10 == 9) != (split != "train"):
                continue
            uid, mid = int(uid), int(mid)
            samples.append((np.int32(uid), np.int32(mid), users[uid],
                            movies.get(mid, np.zeros(6, np.int32)),
                            np.float32(rating)))
    return samples


def movielens(split: str = "train", n_users: int = 500, n_movies: int = 300,
              n: Optional[int] = None):
    """MovieLens rating samples (reference: ``v2/dataset/movielens.py``)
    yielding ``(user_id, movie_id, user_features [4], movie_genres [6],
    rating)``. Real ml-1m when cached/downloadable. Synthetic fallback:
    ratings from a hidden low-rank user x movie factor model plus genre
    affinity, so matrix-factorisation recommenders actually learn."""
    real = _movielens_real(split)
    if real is not None:
        def reader():
            yield from real
        reader.is_synthetic = False
        reader.num_samples = len(real)
        return reader

    n = n or (16384 if split == "train" else 2048)
    g = np.random.RandomState(44)
    u_fac = g.normal(0, 1, (n_users, 6)).astype(np.float32)
    m_fac = g.normal(0, 1, (n_movies, 6)).astype(np.float32)
    m_genre = (g.uniform(size=(n_movies, 6)) > 0.7).astype(np.int32)
    # per-user genre taste: makes the yielded genre features predictive
    u_taste = g.normal(0, 0.5, (n_users, 6)).astype(np.float32)
    u_feat = np.stack([g.randint(0, 2, n_users), g.randint(0, 7, n_users),
                       g.randint(0, 21, n_users), g.randint(18, 60, n_users)],
                      axis=1).astype(np.int32)

    def reader():
        rng = np.random.RandomState(14 if split == "train" else 15)
        for i in range(n):
            u = int(rng.randint(0, n_users))
            m = int(rng.randint(0, n_movies))
            score = float(u_fac[u] @ m_fac[m]) / 3.0 \
                + float(u_taste[u] @ m_genre[m]) / 3.0 + 3.0
            rating = np.float32(np.clip(score + rng.normal(0, 0.3), 1.0, 5.0))
            yield (np.int32(u), np.int32(m), u_feat[u], m_genre[m], rating)
    reader.is_synthetic = True
    reader.num_samples = n
    return reader


def _conll05_real(split, vocab_size):
    """Parse the real CoNLL-05 words/props pair (reference:
    ``v2/dataset/conll05.py`` — test.wsj.words.gz + test.wsj.props.gz,
    span-bracket notation per predicate column). Yields one sample per
    (sentence, predicate): (word_ids, predicate_index, iob_label_ids)."""
    base = os.path.join(data_home(), "conll05")
    words_p = os.path.join(base, f"{split}.wsj.words.gz")
    props_p = os.path.join(base, f"{split}.wsj.props.gz")
    if not (os.path.exists(words_p) and os.path.exists(props_p)):
        return None

    def sentences(path, ncols=None):
        out, cur = [], []
        with gzip.open(path, "rt") as f:
            for line in f:
                line = line.strip()
                if not line:
                    if cur:
                        out.append(cur)
                        cur = []
                else:
                    cur.append(line.split())
        if cur:
            out.append(cur)
        return out

    word_sents = sentences(words_p)
    prop_sents = sentences(props_p)
    # word dict (frequency desc, word asc) — 0 = <unk>
    import collections
    freq = collections.Counter(w[0] for s in word_sents for w in s)
    wdict = {w: i + 1 for i, (w, _) in enumerate(
        sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        [:vocab_size - 1])}

    samples = []
    roles = set()
    parsed = []
    for ws, ps in zip(word_sents, prop_sents):
        ids = np.asarray([wdict.get(w[0], 0) for w in ws], np.int32)
        ncols = len(ps[0]) - 1            # col 0 = predicate lemma
        for c in range(ncols):
            spans = []                    # (role, start, end) inclusive
            open_role, start = None, 0
            pred_idx = 0
            for t, row in enumerate(ps):
                cell = row[1 + c]
                if cell.startswith("("):
                    open_role = cell[1:].split("*")[0].rstrip(")")
                    start = t
                if open_role == "V" and cell.startswith("("):
                    pred_idx = t
                if cell.endswith(")"):
                    spans.append((open_role, start, t))
                    open_role = None
            roles.update(r for r, _, _ in spans if r != "V")
            parsed.append((ids, pred_idx, spans))
    role_ids = {r: i for i, r in enumerate(sorted(roles))}
    for ids, pred_idx, spans in parsed:
        labels = np.zeros(len(ids), np.int32)          # 0 = O
        for r, s, e in spans:
            if r == "V":
                continue
            rid = role_ids[r]
            labels[s] = 1 + 2 * rid                    # B-
            labels[s + 1:e + 1] = 2 + 2 * rid          # I-
        samples.append((ids, np.int32(pred_idx), labels))
    return samples, 1 + 2 * len(role_ids)


def conll05(split: str = "train", vocab: int = 3000, n_labels: int = 13,
            max_len: int = 40, n: Optional[int] = None):
    """CoNLL-05 semantic-role-labeling data (reference:
    ``v2/dataset/conll05.py``) yielding ``(words, predicate_index,
    labels)`` with IOB-coded labels. Parses real cached
    ``{split}.wsj.words.gz`` + ``{split}.wsj.props.gz`` pairs; synthetic
    fallback: arguments cluster around the predicate so position features
    matter."""
    real = _conll05_real(split, vocab)
    if real is not None:
        samples, real_n_labels = real

        def reader():
            yield from samples
        reader.is_synthetic = False
        reader.num_samples = len(samples)
        reader.num_labels = real_n_labels
        return reader

    n = n or (4096 if split == "train" else 512)

    def reader():
        rng = np.random.RandomState(16 if split == "train" else 17)
        for i in range(n):
            length = int(rng.randint(5, max_len))
            words = rng.randint(0, vocab, size=length).astype(np.int32)
            pred = int(rng.randint(0, length))
            labels = np.zeros(length, np.int32)    # 0 = O
            # mark an ARG span adjacent to the predicate with B-/I- codes
            span_len = int(rng.randint(1, 4))
            start = max(0, pred - span_len)
            typ = int(rng.randint(0, (n_labels - 1) // 2))
            for t in range(start, min(length, start + span_len)):
                labels[t] = 1 + 2 * typ + (0 if t == start else 1)
            yield words, np.int32(pred), labels
    reader.is_synthetic = True
    reader.num_samples = n
    return reader


_IMIKOLOV_URL = ("http://www.fit.vutbr.cz/~imikolov/rnnlm/"
                 "simple-examples.tgz")
_IMIKOLOV_MD5 = "30177ea32e27c525793142b6bf2c8e2d"


def _imikolov_real(split, vocab_size, ngram):
    """Parse the real PTB tarball (simple-examples.tgz) into n-gram windows
    (reference: ``v2/dataset/imikolov.py`` build_dict + reader)."""
    from .download import DownloadDisabled, download, downloads_enabled
    path = os.path.join(data_home(), "imikolov", "simple-examples.tgz")
    if not os.path.exists(path):
        if not downloads_enabled():
            return None
        try:
            path = download(_IMIKOLOV_URL, "imikolov", _IMIKOLOV_MD5)
        except (DownloadDisabled, IOError):
            return None
    import collections
    import tarfile
    member = {"train": "./simple-examples/data/ptb.train.txt",
              "test": "./simple-examples/data/ptb.test.txt"}

    def lines(name):
        with tarfile.open(path) as tf:
            for m in tf:
                if m.name.lstrip("./") == name.lstrip("./") and m.isfile():
                    for raw in tf.extractfile(m).read().decode(
                            "utf-8", errors="replace").splitlines():
                        yield raw.split()
                    return

    freq = collections.Counter()
    for toks in lines(member["train"]):
        freq.update(toks)
    # id 0 = <unk>; frequency-desc, word-asc tie-break (build_dict order)
    vocab = {w: i + 1 for i, (w, _) in enumerate(
        sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        [:vocab_size - 1])}
    windows = []
    for toks in lines(member["train" if split == "train" else "test"]):
        ids = [vocab.get(t, 0) for t in toks]
        for i in range(len(ids) - ngram + 1):
            windows.append(np.asarray(ids[i:i + ngram], np.int32))
    return windows


def imikolov(split: str = "train", vocab: int = 2000, ngram: int = 5,
             n: Optional[int] = None):
    """PTB n-gram language-model windows (reference:
    ``v2/dataset/imikolov.py``) yielding ``(context [ngram-1], next_word)``.
    Real PTB when cached/downloadable; synthetic fallback: a first-order
    Markov chain over the vocab so context genuinely predicts the next
    word."""
    real = _imikolov_real(split, vocab, ngram)
    if real is not None:
        def reader():
            for w in real:
                yield w[:-1], w[-1]
        reader.is_synthetic = False
        reader.num_samples = len(real)
        return reader

    n = n or (16384 if split == "train" else 2048)
    g = np.random.RandomState(45)
    # sparse-ish transition preferences: each word has 4 likely successors
    succ = g.randint(0, vocab, size=(vocab, 4)).astype(np.int32)

    def reader():
        rng = np.random.RandomState(18 if split == "train" else 19)
        w = int(rng.randint(0, vocab))
        for i in range(n):
            ctx = []
            for _ in range(ngram - 1):
                ctx.append(w)
                w = int(succ[w, rng.randint(0, 4)]) if rng.rand() < 0.9 \
                    else int(rng.randint(0, vocab))
            yield np.asarray(ctx, np.int32), np.int32(
                succ[ctx[-1], rng.randint(0, 4)] if rng.rand() < 0.9
                else rng.randint(0, vocab))
    reader.is_synthetic = True
    reader.num_samples = n
    return reader


_WMT14_RESERVED = 3        # <s>=0, <e>=1, <unk>=2 (the reference's layout)


def _wmt14_real(split, dict_size, max_len):
    """Parse the real shrunk-WMT14 tarball (reference ``v2/dataset/
    wmt14.py``: src.dict/trg.dict member files = one word per line, id =
    line number; train/test members = tab-separated parallel lines; the
    <s>/<e>/<unk> convention and the >80-token filter)."""
    path = os.path.join(data_home(), "wmt14", "wmt14.tgz")
    if not os.path.exists(path):
        return None
    import tarfile

    def to_dict(fd, size):
        out = {}
        for i, line in enumerate(fd):
            if i >= size:
                break
            out[line.strip().decode("utf-8", errors="replace")] = i
        return out

    samples = []
    with tarfile.open(path) as tf:
        src_name = [m.name for m in tf if m.name.endswith("src.dict")][0]
        trg_name = [m.name for m in tf if m.name.endswith("trg.dict")][0]
        src_dict = to_dict(tf.extractfile(src_name), dict_size)
        trg_dict = to_dict(tf.extractfile(trg_name), dict_size)
        member = "train/train" if split == "train" else "test/test"
        names = [m.name for m in tf if m.name.endswith(member)]
        for name in names:
            for raw in tf.extractfile(name):
                parts = raw.decode("utf-8", errors="replace").strip() \
                    .split("\t")
                if len(parts) != 2:
                    continue
                src = [src_dict.get(w, 2) for w in
                       ["<s>"] + parts[0].split() + ["<e>"]]
                trg = [trg_dict.get(w, 2) for w in parts[1].split()]
                if len(src) > 80 or len(trg) > 80:
                    continue
                tgt = [trg_dict.get("<s>", 0)] + trg + [trg_dict.get("<e>", 1)]
                samples.append((np.asarray(src[:max_len], np.int32),
                                np.asarray(tgt[:max_len + 1], np.int32)))
    return samples


def wmt14(split: str = "train", src_vocab: int = 1000, tgt_vocab: int = 1000,
          max_len: int = 30, n: Optional[int] = None):
    """WMT14 en-fr translation surface (reference: ``v2/dataset/wmt14.py``)
    yielding ``(src_ids, tgt_ids)`` (tgt bos-prefixed/eos-suffixed). Real
    shrunk-WMT14 tarball when cached; otherwise delegates to
    :func:`synthetic_nmt` (same structure and reserved ids) under the
    reference's dataset name."""
    real = _wmt14_real(split, max(src_vocab, tgt_vocab), max_len)
    if real is not None:
        def reader():
            yield from real
        reader.is_synthetic = False
        reader.num_samples = len(real)
        return reader
    return synthetic_nmt(split, src_vocab, tgt_vocab, max_len, n)


VOC_CLASSES = ["aeroplane", "bicycle", "bird", "boat", "bottle", "bus",
               "car", "cat", "chair", "cow", "diningtable", "dog", "horse",
               "motorbike", "person", "pottedplant", "sheep", "sofa",
               "train", "tvmonitor"]


def _voc2012_real(split, hw, max_boxes):
    """Parse a real VOCdevkit layout (reference: ``v2/dataset/voc2012.py``):
    ``VOCdevkit/VOC2012/{JPEGImages,Annotations,ImageSets/Main}``; labels =
    1 + index into the 20 VOC classes (0 = background)."""
    root = os.path.join(data_home(), "voc2012", "VOCdevkit", "VOC2012")
    setfile = os.path.join(root, "ImageSets", "Main",
                           "train.txt" if split == "train" else "val.txt")
    if not os.path.exists(setfile):
        return None
    import xml.etree.ElementTree as ET

    from PIL import Image
    H, W = hw
    cls_id = {c: i + 1 for i, c in enumerate(VOC_CLASSES)}
    with open(setfile) as f:
        names = [ln.strip() for ln in f if ln.strip()]

    def load(name):
        img = Image.open(os.path.join(root, "JPEGImages",
                                      name + ".jpg")).convert("RGB")
        iw, ih = img.size
        arr = np.asarray(img.resize((W, H)), np.float32) / 127.5 - 1.0
        boxes = np.zeros((max_boxes, 4), np.float32)
        labels = np.full((max_boxes,), -1, np.int32)
        tree = ET.parse(os.path.join(root, "Annotations", name + ".xml"))
        k = 0
        for obj in tree.findall("object"):
            if k >= max_boxes:
                break
            cname = obj.findtext("name")
            bb = obj.find("bndbox")
            if cname not in cls_id or bb is None:
                continue
            boxes[k] = [float(bb.findtext("xmin")) / iw,
                        float(bb.findtext("ymin")) / ih,
                        float(bb.findtext("xmax")) / iw,
                        float(bb.findtext("ymax")) / ih]
            labels[k] = cls_id[cname]
            k += 1
        return arr, boxes, labels

    return names, load


def voc2012(split: str = "train", hw: Tuple[int, int] = (96, 96),
            num_classes: int = 5, max_boxes: int = 4,
            n: Optional[int] = None):
    """VOC-style detection data (reference: ``v2/dataset/voc2012.py``)
    yielding ``(image [H,W,3], gt_boxes [max_boxes,4] normalized xyxy,
    gt_labels [max_boxes] with -1 padding)``. Real VOCdevkit when cached
    (labels then span the 20 VOC classes); synthetic fallback: colored
    rectangles on noise — class = dominant channel, so detectors learn."""
    real = _voc2012_real(split, hw, max_boxes)
    if real is not None:
        names, load = real

        def reader():
            for name in names:
                yield load(name)
        reader.is_synthetic = False
        reader.num_samples = len(names)
        return reader
    n = n or (2048 if split == "train" else 256)
    H, W = hw

    def reader():
        rng = np.random.RandomState(20 if split == "train" else 21)
        for i in range(n):
            img = rng.uniform(0, 0.3, size=(H, W, 3)).astype(np.float32)
            k = int(rng.randint(1, max_boxes + 1))
            boxes = np.zeros((max_boxes, 4), np.float32)
            labels = np.full((max_boxes,), -1, np.int32)
            for b in range(k):
                x1, y1 = rng.uniform(0, 0.6, 2)
                w, h = rng.uniform(0.2, 0.35, 2)
                x2, y2 = min(x1 + w, 1.0), min(y1 + h, 1.0)
                cls = int(rng.randint(1, num_classes))
                ch = (cls - 1) % 3
                img[int(y1 * H):int(y2 * H), int(x1 * W):int(x2 * W), ch] = \
                    0.8 + 0.2 * rng.rand()
                boxes[b] = [x1, y1, x2, y2]
                labels[b] = cls
            yield img, boxes, labels
    reader.is_synthetic = True
    reader.num_samples = n
    return reader


def _mq2007_real(split):
    """Parse real LETOR-format files (reference: ``v2/dataset/mq2007.py``;
    line = ``rel qid:Q 1:v 2:v ... #docid``), grouping docs per query."""
    path = os.path.join(data_home(), "mq2007", f"{split}.txt")
    if not os.path.exists(path):
        return None
    import collections
    by_query = collections.OrderedDict()
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            rel = int(parts[0])
            qid = parts[1].split(":")[1]
            feats = [float(p.split(":")[1]) for p in parts[2:]]
            by_query.setdefault(qid, []).append((rel, feats))
    samples = []
    for qid, docs in by_query.items():
        f = np.asarray([d[1] for d in docs], np.float32)
        rel = np.asarray([d[0] for d in docs], np.int32)
        samples.append((f, rel))
    return samples


def mq2007(split: str = "train", n_queries: int = 400, docs_per_query: int = 8,
           n_features: int = 16):
    """MQ2007 learning-to-rank surface (reference: ``v2/dataset/mq2007.py``)
    yielding per-query groups ``(features [D, F], relevance [D])``. Real
    LETOR files when cached (``mq2007/{split}.txt``); synthetic fallback
    with graded relevance 0-2 from a hidden linear model."""
    real = _mq2007_real(split)
    if real is not None:
        def reader():
            yield from real
        reader.is_synthetic = False
        reader.num_samples = len(real)
        return reader

    nq = n_queries if split == "train" else max(1, n_queries // 8)
    g = np.random.RandomState(46)
    w_hidden = g.normal(0, 1, n_features).astype(np.float32)

    def reader():
        rng = np.random.RandomState(22 if split == "train" else 23)
        for q in range(nq):
            f = rng.normal(0, 1, (docs_per_query, n_features)).astype(
                np.float32)
            score = f @ w_hidden + rng.normal(0, 0.5, docs_per_query)
            rel = np.digitize(score, [-0.5, 1.0]).astype(np.int32)  # 0/1/2
            yield f, rel
    reader.is_synthetic = True
    reader.num_samples = nq
    return reader


def sentiment(split: str = "train", **kw):
    """Movie-review sentiment surface (reference:
    ``v2/dataset/sentiment.py``) — same shape as :func:`imdb`."""
    return imdb(split, **kw)


def _flowers_real(split, hw):
    """Parse the real Flowers-102 layout (reference:
    ``v2/dataset/flowers.py``: ``102flowers/jpg`` images +
    ``imagelabels.mat`` + ``setid.mat`` split ids)."""
    base = os.path.join(data_home(), "flowers")
    labels_p = os.path.join(base, "imagelabels.mat")
    setid_p = os.path.join(base, "setid.mat")
    jpg_dir = os.path.join(base, "jpg")
    if not (os.path.exists(labels_p) and os.path.exists(setid_p)
            and os.path.isdir(jpg_dir)):
        return None
    from PIL import Image
    from scipy.io import loadmat
    H, W = hw
    labels = loadmat(labels_p)["labels"].ravel().astype(np.int32) - 1
    sets = loadmat(setid_p)
    # reference uses trnid for train, tstid for test
    ids = sets["trnid" if split == "train" else "tstid"].ravel()

    def load(i):
        img = Image.open(os.path.join(
            jpg_dir, f"image_{int(i):05d}.jpg")).convert("RGB")
        arr = np.asarray(img.resize((W, H)), np.float32) / 127.5 - 1.0
        return arr, labels[int(i) - 1]

    return ids, load


def flowers(split: str = "train", hw: Tuple[int, int] = (64, 64),
            num_classes: int = 102, synthetic_n: Optional[int] = None):
    """Flowers-102 classification surface (reference:
    ``v2/dataset/flowers.py``) yielding ``(image [H,W,3], label)``. Real
    102flowers layout when cached; synthetic separable fallback."""
    real = _flowers_real(split, hw)
    if real is not None:
        ids, load = real

        def reader():
            for i in ids:
                yield load(i)
        reader.is_synthetic = False
        reader.num_samples = len(ids)
        return reader

    n = synthetic_n or (2048 if split == "train" else 256)
    seed = 24 if split == "train" else 25
    images, labels = _synth_images(n, num_classes, hw, 3, seed)

    def reader():
        for i in range(n):
            yield images[i], labels[i]
    reader.is_synthetic = True
    reader.num_samples = n
    return reader


def traffic(split: str = "train", term_num: int = 24,
            forecasting_num: int = 24, n: Optional[int] = None):
    """Traffic speed-category prediction (reference:
    ``v1_api_demo/traffic_prediction/`` — encode the last ``term_num``
    5-minute readings of a road link, predict a 4-class speed category for
    each of the next ``forecasting_num`` intervals; multi-task heads share
    the link embedding).

    Synthetic fallback: speeds follow a smooth daily sinusoid + link offset
    + noise, so the future is genuinely predictable from the recent past.
    Yields ``(encode [term_num], labels [forecasting_num] in 0..3)``.
    """
    n = n or (8192 if split == "train" else 1024)

    def speed_at(phase, t):
        return 2.0 + 1.5 * np.sin(2 * np.pi * (t + phase) / 288.0)

    def reader():
        rng = np.random.RandomState(26 if split == "train" else 27)
        for i in range(n):
            phase = rng.uniform(0, 288)
            t0 = rng.uniform(0, 288)
            ts = t0 + np.arange(term_num + forecasting_num)
            speeds = speed_at(phase, ts) + rng.normal(0, 0.15,
                                                      ts.shape)
            encode = speeds[:term_num].astype(np.float32)
            future = speeds[term_num:]
            labels = np.clip(future, 0, 3.999).astype(np.int32)
            yield encode, labels
    reader.is_synthetic = True
    reader.num_samples = n
    return reader
