"""Composable reader combinators — the v2 data-pipeline surface.

Reference: ``/root/reference/python/paddle/v2/reader/decorator.py`` (map_readers,
shuffle, buffered, compose, chain, firstn, xmap) and ``minibatch.py``. A *reader*
is a zero-arg callable returning an iterator over samples; combinators wrap
readers into new readers. Batching adds TPU-specific care: fixed batch shapes
(drop/pad last partial batch) so jit never re-traces, and host-side prefetch into
a background thread (the analog of the reference's ``DoubleBuffer`` async layer,
``paddle/gserver/dataproviders/DataProvider.h:249``).
"""

from __future__ import annotations

import contextlib
import itertools
import multiprocessing as _mp
import queue
import random as _random
import threading
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

__all__ = ["map_readers", "shuffle", "buffered", "compose", "chain", "firstn",
           "batched", "prefetch", "cycle", "sharded", "xmap"]

Reader = Callable[[], Iterable]


def map_readers(func: Callable, *readers: Reader) -> Reader:
    """Apply func elementwise over zipped readers (reference: map_readers)."""
    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)
    return reader


def shuffle(reader_fn: Reader, buf_size: int, seed: Optional[int] = None) -> Reader:
    """Windowed shuffle (reference: shuffle decorator). Deterministic when
    ``seed`` is given — required for resumable/elastic data order."""
    def reader():
        rng = _random.Random(seed)
        buf: List[Any] = []
        for item in reader_fn():
            buf.append(item)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rng.shuffle(buf)
            yield from buf
    return reader


_NULL_CTX = contextlib.nullcontext()


def _fill_span(tracer, name: str):
    """Null-safe tracer span (duck-typed against ``obs.trace.Tracer`` so
    this module never imports jax-adjacent packages)."""
    if tracer is None:
        return _NULL_CTX
    return tracer.span(name)


def buffered(reader_fn: Reader, size: int, tracer=None) -> Reader:
    """Decouple producer/consumer with a bounded queue on a thread
    (reference: buffered decorator).

    Shutdown contract: when the consumer abandons the generator early
    (``break`` mid-pass, :func:`firstn`, generator ``close()``), the fill
    thread terminates instead of blocking forever on ``q.put`` into the
    full queue — the generator's ``finally`` sets a stop event every
    producer-side ``put`` polls. Producer exceptions surface PROMPTLY:
    the consumer re-raises as soon as the error is recorded, without
    first draining the items already buffered ahead of it.

    ``tracer``: optional :class:`paddle_tpu.obs.Tracer` — records one
    ``data.fill`` span on the fill thread per item produced, so the
    reader's own cost (parse/augment/collate upstream of this queue)
    shows up on its thread in the hot-loop timeline next to the stager
    and main-loop spans (ISSUE 4)."""
    def reader():
        q: queue.Queue = queue.Queue(maxsize=size)
        end = object()
        err: List[BaseException] = []
        stop = threading.Event()

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False                       # consumer gone

        def fill():
            try:
                it = iter(reader_fn())
                while True:
                    with _fill_span(tracer, "data.fill"):
                        item = next(it, end)
                    if item is end:
                        return
                    if not _put(item):
                        return
            except BaseException as e:  # propagate into consumer
                err.append(e)
            finally:
                _put(end)

        t = threading.Thread(target=fill, daemon=True,
                             name="paddle_tpu.data.buffered.fill")
        t.start()
        try:
            while True:
                if err:                        # prompt: don't drain first
                    raise err[0]
                item = q.get()
                if item is end:
                    break
                yield item
            if err:
                raise err[0]
        finally:
            stop.set()                         # unblock + end the producer
    return reader


def compose(*readers: Reader) -> Reader:
    """Zip readers into tuple samples (reference: compose)."""
    def reader():
        for items in zip(*[r() for r in readers]):
            out = []
            for it in items:
                if isinstance(it, tuple):
                    out.extend(it)
                else:
                    out.append(it)
            yield tuple(out)
    return reader


def chain(*readers: Reader) -> Reader:
    """Concatenate readers (reference: chain)."""
    def reader():
        for r in readers:
            yield from r()
    return reader


def firstn(reader_fn: Reader, n: int) -> Reader:
    def reader():
        return itertools.islice(reader_fn(), n)
    return reader


def cycle(reader_fn: Reader) -> Reader:
    def reader():
        while True:
            it = iter(reader_fn())
            empty = True
            for x in it:
                empty = False
                yield x
            if empty:
                return
    return reader


def sharded(reader_fn: Reader, num_shards: int, shard_id: int) -> Reader:
    """Deterministic per-host data sharding — the TPU-native replacement for the
    Go master's task queue (``/root/reference/go/master/service.go:368``): every
    host reads the same stream and keeps items where idx % num_shards == id."""
    def reader():
        for i, item in enumerate(reader_fn()):
            if i % num_shards == shard_id:
                yield item
    return reader


def batched(reader_fn: Reader, batch_size: int, drop_last: bool = True,
            collate: Optional[Callable] = None) -> Reader:
    """Group samples into fixed-size batches of stacked numpy arrays.

    Fixed shapes keep one XLA compilation alive (the reference re-traces nothing
    either — its batches are dynamic but C++-side). ``collate`` overrides the
    default stack-per-field behavior (tuples -> tuple of arrays, dicts -> dict).
    """
    def default_collate(samples):
        first = samples[0]
        if isinstance(first, dict):
            return {k: np.stack([np.asarray(s[k]) for s in samples])
                    for k in first}
        if isinstance(first, (tuple, list)):
            return tuple(np.stack([np.asarray(s[i]) for s in samples])
                         for i in range(len(first)))
        return np.stack([np.asarray(s) for s in samples])

    coll = collate or default_collate

    def reader():
        buf = []
        for item in reader_fn():
            buf.append(item)
            if len(buf) == batch_size:
                yield coll(buf)
                buf = []
        if buf and not drop_last:
            yield coll(buf)
    return reader


def prefetch(reader_fn: Reader, depth: int = 2, tracer=None) -> Reader:
    """Async host-side prefetch (DoubleBuffer analog) — overlap input pipeline
    with device compute. ``tracer`` forwards to :func:`buffered`'s
    fill-thread spans."""
    return buffered(reader_fn, depth, tracer=tracer)


def _xmap_worker(func, in_q, out_q):
    """Worker-process loop for :func:`xmap` (top-level so the spawn context
    can pickle it)."""
    while True:
        task = in_q.get()
        if task is None:
            out_q.put(("done", -1, None))
            return
        idx, sample = task
        try:
            out_q.put(("ok", idx, func(sample)))
        except BaseException as e:  # surface in the consumer, then die
            out_q.put(("err", idx, f"{type(e).__name__}: {e}"))
            return


def xmap(func: Callable, reader_fn: Reader, processes: int = 2,
         buffer: int = 8, ordered: bool = True,
         mp_context: str = "spawn") -> Reader:
    """Parallel map over a reader in worker PROCESSES — real parallelism
    for CPU-bound mappers that the GIL serializes under :func:`buffered`
    (the reference's ``xmap_readers``, ``v2/reader/decorator.py:233-292``,
    and its image loader ``utils/image_multiproc.py``).

    ``func`` and the samples must be picklable; the default ``spawn``
    context is used because forking after jax/XLA threads exist is unsafe.
    Workers should not touch jax devices. ``buffer`` bounds in-flight
    samples in each direction (backpressure — the reader is consumed at
    the pace of the mappers, never slurped whole). ``ordered=True``
    preserves input order at the cost of head-of-line blocking. Workers
    shut down cleanly both when the reader is exhausted and when the
    consumer abandons the iterator early (``break`` / ``close()``)."""
    assert processes >= 1

    def reader():
        ctx = _mp.get_context(mp_context)
        in_q = ctx.Queue(buffer)
        out_q = ctx.Queue(buffer)
        workers = [ctx.Process(target=_xmap_worker,
                               args=(func, in_q, out_q), daemon=True)
                   for _ in range(processes)]
        for w in workers:
            w.start()
        stop = threading.Event()
        feeder_err: List[BaseException] = []

        def _put(task) -> bool:
            while not stop.is_set():
                try:
                    in_q.put(task, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False                               # consumer gone

        def feed():
            try:
                for i, s in enumerate(reader_fn()):
                    if not _put((i, s)):
                        return
            except BaseException as e:     # surface in the consumer
                feeder_err.append(e)
            finally:
                # ALWAYS deliver the per-worker sentinels — a source-reader
                # error must end the workers, not strand the consumer on
                # out_q.get() forever
                for _ in workers:
                    if not _put(None):
                        return

        feeder = threading.Thread(target=feed, daemon=True)
        feeder.start()

        def _drain(q):
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass

        try:
            done, pending, nxt, silent = 0, {}, 0, 0
            while done < len(workers):
                try:
                    kind, idx, payload = out_q.get(timeout=1.0)
                except queue.Empty:
                    # a worker killed by SIGKILL/segfault/OOM never posts
                    # its sentinel — detect the corpse instead of hanging
                    dead = [w for w in workers
                            if w.exitcode not in (None, 0)]
                    if dead:
                        raise RuntimeError(
                            f"xmap worker died with exitcode "
                            f"{dead[0].exitcode} (segfault/OOM-kill?)")
                    # exit-code-0 corpse (func called os._exit(0)): all
                    # workers gone, queue stayed empty across TWO timeouts
                    # (margin for an in-flight pipe flush), sentinels short
                    if all(not w.is_alive() for w in workers):
                        silent += 1
                        if silent >= 2:
                            raise RuntimeError(
                                "xmap workers exited without completing "
                                "(mapped func called os._exit?)")
                    else:
                        silent = 0
                    continue
                silent = 0
                if kind == "done":
                    done += 1
                elif kind == "err":
                    raise RuntimeError(f"xmap worker failed: {payload}")
                elif not ordered:
                    yield payload
                else:
                    pending[idx] = payload
                    while nxt in pending:
                        yield pending.pop(nxt)
                        nxt += 1
            if feeder_err:
                raise feeder_err[0]
        finally:
            stop.set()
            # fast shutdown without SIGTERM: free workers blocked on a full
            # out_q, clear pending tasks, then hand every worker a sentinel
            # with a short blocking put (a get_nowait-to-make-room scheme
            # can evict sentinels it just placed when buffer < processes)
            _drain(out_q)
            _drain(in_q)
            for _ in workers:
                try:
                    in_q.put(None, timeout=0.2)
                except queue.Full:
                    break
            _drain(out_q)
            for w in workers:
                w.join(timeout=2.0)
                if w.is_alive():
                    w.terminate()
    return reader
