"""Dataset download + cache machinery — the ``v2/dataset/common.py`` analog.

Reference behavior (``/root/reference/python/paddle/v2/dataset/common.py``):
``download(url, module_name, md5sum)`` fetches into
``~/.cache/paddle/dataset/<module>/``, verifies md5, retries a bounded
number of times, and every loader calls it transparently.

TPU-native build differences:
- the cache root is :func:`paddle_tpu.data.datasets.data_home`
  (``PADDLE_TPU_DATA`` overrides);
- downloads are **env-gated**: network fetches only happen when
  ``PADDLE_TPU_AUTO_DOWNLOAD=1`` — in air-gapped environments (like this
  build sandbox) loaders skip straight to their labelled synthetic
  fallback instead of hanging on a dead socket;
- writes are atomic (tmp file + rename) so a killed download never
  poisons the cache, and an md5 mismatch retries then raises.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import urllib.request
from typing import Optional

__all__ = ["download", "md5file", "downloads_enabled", "DownloadDisabled"]

_RETRIES = 3
_ENV_GATE = "PADDLE_TPU_AUTO_DOWNLOAD"


class DownloadDisabled(RuntimeError):
    """Raised when a fetch would be needed but downloads are not enabled."""


def downloads_enabled() -> bool:
    return os.environ.get(_ENV_GATE, "0").lower() in ("1", "true", "yes")


def md5file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def download(url: str, module_name: str, md5sum: Optional[str] = None,
             filename: Optional[str] = None) -> str:
    """Fetch ``url`` into the cache dir and return the local path.

    A cached file with a matching md5 (or any cached file when ``md5sum`` is
    None) is returned without touching the network. Otherwise requires
    ``PADDLE_TPU_AUTO_DOWNLOAD=1`` (else :class:`DownloadDisabled`), retries
    up to 3 times on md5 mismatch, and writes atomically.
    """
    from .datasets import data_home

    directory = os.path.join(data_home(), module_name)
    os.makedirs(directory, exist_ok=True)
    fname = filename or url.rstrip("/").split("/")[-1]
    path = os.path.join(directory, fname)

    if os.path.exists(path) and (md5sum is None or md5file(path) == md5sum):
        return path

    if not downloads_enabled():
        raise DownloadDisabled(
            f"{fname} is not cached under {directory} and automatic "
            f"downloads are disabled; set {_ENV_GATE}=1 (network required) "
            f"or place the file there manually")

    last_err: Optional[str] = None
    for _ in range(_RETRIES):
        tmp = path + ".part"
        try:
            with urllib.request.urlopen(url) as resp, open(tmp, "wb") as out:
                shutil.copyfileobj(resp, out)
        except OSError as e:
            last_err = f"fetch failed: {e}"
            continue
        if md5sum is not None and md5file(tmp) != md5sum:
            last_err = f"md5 mismatch for {fname}"
            os.remove(tmp)
            continue
        os.replace(tmp, path)          # atomic publish
        return path
    raise IOError(f"download of {url} failed after {_RETRIES} attempts: "
                  f"{last_err}")
