"""Host-side image preprocessing (reference:
``python/paddle/utils/image_util.py`` + ``preprocess_img.py`` + the
multi-process loader ``image_multiproc.py``; v2 ``paddle.v2.image``).

All numpy, all HWC float32 (the package's NHWC convention — the reference is
CHW and converts at the edge). Compose transforms with :func:`pipeline` and
lift onto a reader with ``data.map_readers``; heavy pipelines parallelize
across worker processes with ``data.xmap`` (the analog of the reference's
multiprocess loader — ``TrainAugment``/``EvalTransform`` are picklable for
exactly this), or across threads with ``data.buffered`` for IO-bound work.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import numpy as np

__all__ = ["resize", "center_crop", "random_crop", "random_flip",
           "normalize", "to_chw", "to_hwc", "pipeline", "train_augment",
           "eval_transform", "TrainAugment", "EvalTransform"]


def resize(img: np.ndarray, hw: Tuple[int, int]) -> np.ndarray:
    """Bilinear resize, HWC (the reference uses PIL's default bilinear)."""
    H, W = img.shape[:2]
    h, w = hw
    if (H, W) == (h, w):
        return img
    # sample grid (align-corners=False convention)
    ys = (np.arange(h) + 0.5) * H / h - 0.5
    xs = (np.arange(w) + 0.5) * W / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, H - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, W - 1)
    y1 = np.clip(y0 + 1, 0, H - 1)
    x1 = np.clip(x0 + 1, 0, W - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    img = img.astype(np.float32)
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


def center_crop(img: np.ndarray, hw: Tuple[int, int]) -> np.ndarray:
    h, w = hw
    H, W = img.shape[:2]
    y = max(0, (H - h) // 2)
    x = max(0, (W - w) // 2)
    return img[y:y + h, x:x + w]


def random_crop(img: np.ndarray, hw: Tuple[int, int],
                rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    rng = rng or np.random
    h, w = hw
    H, W = img.shape[:2]
    y = int(rng.randint(0, max(1, H - h + 1)))
    x = int(rng.randint(0, max(1, W - w + 1)))
    return img[y:y + h, x:x + w]


def random_flip(img: np.ndarray,
                rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    rng = rng or np.random
    return img[:, ::-1] if rng.rand() < 0.5 else img


def normalize(img: np.ndarray, mean: Sequence[float],
              std: Sequence[float] = (1.0, 1.0, 1.0)) -> np.ndarray:
    """Per-channel (x - mean) / std — the reference's mean-image/mean-value
    subtraction (``image_util.py`` ``ImageTransformer.set_mean``)."""
    return ((img.astype(np.float32) - np.asarray(mean, np.float32))
            / np.asarray(std, np.float32))


def to_chw(img: np.ndarray) -> np.ndarray:
    """HWC -> CHW (only at interop edges; the package itself stays NHWC)."""
    return np.transpose(img, (2, 0, 1))


def to_hwc(img: np.ndarray) -> np.ndarray:
    return np.transpose(img, (1, 2, 0))


def pipeline(*fns: Callable) -> Callable:
    """Left-to-right composition of image transforms."""
    def run(img):
        for f in fns:
            img = f(img)
        return img
    return run


class TrainAugment:
    """Train-time augmentation of ``preprocess_img.py``: resize -> random
    crop -> random flip -> normalize.

    PICKLABLE (plain attributes, no closures) so it can cross process
    boundaries in ``data.xmap`` — the analog of the reference's
    multi-process image loader (``utils/image_multiproc.py``). Randomness
    is derived per SAMPLE from ``(seed, epoch, crc32(image bytes))``, so
    the augmentation is deterministic and independent of worker count and
    of which worker gets which sample. For fresh crops/flips each epoch,
    call :meth:`set_epoch` before the pass (e.g. in a ``BeginPass``
    handler); readers embedding the instance see the new value because the
    object is shared, and ``data.xmap`` re-pickles it at each ``reader()``
    call, so workers pick it up too."""

    def __init__(self, crop_hw: Tuple[int, int], resize_hw: Tuple[int, int],
                 mean: Sequence[float], std: Sequence[float] = (1, 1, 1),
                 seed: int = 0):
        self.crop_hw = tuple(crop_hw)
        self.resize_hw = tuple(resize_hw)
        self.mean = tuple(mean)
        self.std = tuple(std)
        self.seed = seed
        self.epoch = 0

    def set_epoch(self, epoch: int) -> "TrainAugment":
        self.epoch = int(epoch)
        return self

    def _rng(self, img: np.ndarray) -> np.random.RandomState:
        import zlib
        h = zlib.crc32(np.ascontiguousarray(img).tobytes())
        return np.random.RandomState(
            (self.seed * 2654435761 + self.epoch * 40503 + h) & 0xFFFFFFFF)

    def __call__(self, img: np.ndarray) -> np.ndarray:
        rng = self._rng(img)
        img = resize(img, self.resize_hw)
        img = random_crop(img, self.crop_hw, rng)
        img = random_flip(img, rng)
        return normalize(img, self.mean, self.std)


class EvalTransform:
    """Eval-time: resize -> center crop -> normalize (picklable for
    ``data.xmap``)."""

    def __init__(self, crop_hw: Tuple[int, int], resize_hw: Tuple[int, int],
                 mean: Sequence[float], std: Sequence[float] = (1, 1, 1)):
        self.crop_hw = tuple(crop_hw)
        self.resize_hw = tuple(resize_hw)
        self.mean = tuple(mean)
        self.std = tuple(std)

    def __call__(self, img: np.ndarray) -> np.ndarray:
        img = resize(img, self.resize_hw)
        img = center_crop(img, self.crop_hw)
        return normalize(img, self.mean, self.std)


def train_augment(crop_hw: Tuple[int, int], resize_hw: Tuple[int, int],
                  mean: Sequence[float], std: Sequence[float] = (1, 1, 1),
                  seed: int = 0) -> Callable:
    """See :class:`TrainAugment` (kept as the factory-style API).

    SEMANTICS CHANGE vs the pre-xmap closure: randomness is now a pure
    function of ``(seed, epoch, image bytes)`` — reproducible and
    worker-assignment-independent — so repeated passes re-apply IDENTICAL
    crops/flips unless you call ``.set_epoch(pass_id)`` between passes
    (e.g. from a ``BeginPass`` event handler). The old version drew from
    one advancing RandomState and varied per epoch but was irreproducible
    under multi-process mapping."""
    return TrainAugment(crop_hw, resize_hw, mean, std, seed)


def eval_transform(crop_hw: Tuple[int, int], resize_hw: Tuple[int, int],
                   mean: Sequence[float],
                   std: Sequence[float] = (1, 1, 1)) -> Callable:
    """See :class:`EvalTransform` (kept as the factory-style API)."""
    return EvalTransform(crop_hw, resize_hw, mean, std)
