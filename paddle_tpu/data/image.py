"""Host-side image preprocessing (reference:
``python/paddle/utils/image_util.py`` + ``preprocess_img.py`` + the
multi-process loader ``image_multiproc.py``; v2 ``paddle.v2.image``).

All numpy, all HWC float32 (the package's NHWC convention — the reference is
CHW and converts at the edge). Compose transforms with :func:`pipeline` and
lift onto a reader with ``data.map_readers``; heavy pipelines parallelize
with the threaded prefetch reader (``data.buffered``), the analog of the
reference's multiprocess loader.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import numpy as np

__all__ = ["resize", "center_crop", "random_crop", "random_flip",
           "normalize", "to_chw", "to_hwc", "pipeline", "train_augment",
           "eval_transform"]


def resize(img: np.ndarray, hw: Tuple[int, int]) -> np.ndarray:
    """Bilinear resize, HWC (the reference uses PIL's default bilinear)."""
    H, W = img.shape[:2]
    h, w = hw
    if (H, W) == (h, w):
        return img
    # sample grid (align-corners=False convention)
    ys = (np.arange(h) + 0.5) * H / h - 0.5
    xs = (np.arange(w) + 0.5) * W / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, H - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, W - 1)
    y1 = np.clip(y0 + 1, 0, H - 1)
    x1 = np.clip(x0 + 1, 0, W - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    img = img.astype(np.float32)
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


def center_crop(img: np.ndarray, hw: Tuple[int, int]) -> np.ndarray:
    h, w = hw
    H, W = img.shape[:2]
    y = max(0, (H - h) // 2)
    x = max(0, (W - w) // 2)
    return img[y:y + h, x:x + w]


def random_crop(img: np.ndarray, hw: Tuple[int, int],
                rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    rng = rng or np.random
    h, w = hw
    H, W = img.shape[:2]
    y = int(rng.randint(0, max(1, H - h + 1)))
    x = int(rng.randint(0, max(1, W - w + 1)))
    return img[y:y + h, x:x + w]


def random_flip(img: np.ndarray,
                rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    rng = rng or np.random
    return img[:, ::-1] if rng.rand() < 0.5 else img


def normalize(img: np.ndarray, mean: Sequence[float],
              std: Sequence[float] = (1.0, 1.0, 1.0)) -> np.ndarray:
    """Per-channel (x - mean) / std — the reference's mean-image/mean-value
    subtraction (``image_util.py`` ``ImageTransformer.set_mean``)."""
    return ((img.astype(np.float32) - np.asarray(mean, np.float32))
            / np.asarray(std, np.float32))


def to_chw(img: np.ndarray) -> np.ndarray:
    """HWC -> CHW (only at interop edges; the package itself stays NHWC)."""
    return np.transpose(img, (2, 0, 1))


def to_hwc(img: np.ndarray) -> np.ndarray:
    return np.transpose(img, (1, 2, 0))


def pipeline(*fns: Callable) -> Callable:
    """Left-to-right composition of image transforms."""
    def run(img):
        for f in fns:
            img = f(img)
        return img
    return run


def train_augment(crop_hw: Tuple[int, int], resize_hw: Tuple[int, int],
                  mean: Sequence[float], std: Sequence[float] = (1, 1, 1),
                  seed: int = 0) -> Callable:
    """The standard train-time augmentation of ``preprocess_img.py``:
    resize -> random crop -> random flip -> normalize."""
    rng = np.random.RandomState(seed)
    return pipeline(lambda im: resize(im, resize_hw),
                    lambda im: random_crop(im, crop_hw, rng),
                    lambda im: random_flip(im, rng),
                    lambda im: normalize(im, mean, std))


def eval_transform(crop_hw: Tuple[int, int], resize_hw: Tuple[int, int],
                   mean: Sequence[float],
                   std: Sequence[float] = (1, 1, 1)) -> Callable:
    """Eval-time: resize -> center crop -> normalize."""
    return pipeline(lambda im: resize(im, resize_hw),
                    lambda im: center_crop(im, crop_hw),
                    lambda im: normalize(im, mean, std))
