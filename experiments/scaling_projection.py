"""Analytic multi-chip scaling projection from the sharded step's HLO.

VERDICT r3 item 6 built the method; VERDICT r4 item 6 asked for the
POSITIVE tp/pp story (r4's only tp datapoint was a config tp should lose
at). The projection:

1. For each workload config and device count n, compile the REAL sharded
   training step on a forced n-device virtual CPU platform and parse the
   optimized (post-SPMD) HLO for the collectives XLA actually inserted
   (all-reduce / all-gather / reduce-scatter / all-to-all /
   collective-permute) with their buffer sizes. Transformer workloads
   lower ABSTRACTLY (ShapeDtypeStruct args carrying NamedShardings — no
   host buffers), so big-model big-mesh compiles fit in host RAM.
2. Convert buffers to per-device wire bytes with the standard ring-algorithm
   factors over each op's replica group (all-reduce 2B(n-1)/n,
   gather/all-to-all B(n-1)/n, reduce-scatter B(n-1) of the shard,
   permute B). Pipeline ppermutes inside the wavefront loop are scaled by
   the tick count (static-op parse x dynamic executions).
3. Combine with public per-chip ICI bandwidth and the measured single-chip
   step time into projected scaling efficiency, with no comm/compute
   overlap (pessimistic) and perfect overlap (optimistic bound). Pipeline
   workloads also charge the GPipe bubble (S-1)/M as a compute overhead
   factor, so their efficiency is vs ideal linear scaling, not vs an
   already-bubbled baseline.

Workload matrix (the tp/pp story):
  - d512 tp=4            — the r4 NEGATIVE result, kept for contrast
  - d512 tp=4 + sp       — EXPLICIT Megatron sequence-parallel residuals
    (parallel/megatron.py): AG+RS at all-reduce-equal wire, loss inside
    the shard_map so nothing [*,vocab]-shaped is gathered
  - d1024 dp x pp=8      — GPipe block pipeline via make_pipeline_loss
    (scalar-psum loss form; ppermute hops), M=32
  - d2048 tp=4 + sp      — the dim where tp=4 SHOULD win (tp comm scales
    with d, compute with d^2)
plus analytic dp-only baselines per model, so the final ``recommended``
section names the best config per (model, n) against dp, not in a vacuum.

Cross-checks: (a) at n=8 the parsed resnet all-reduce buffer bytes must
match the analytic f32 gradient size within 10%; (b) a MEASURED virtual-
CPU-mesh transformer dp point at n=8 anchors the transformer projection to
an executed (not just compiled) sharded step.

Output: ``SCALING_r05.json`` at the repo root (run from repo root:
``python experiments/scaling_projection.py``).

Reference anchor: the 3.85x-at-4-GPUs table,
``/root/reference/benchmark/README.md:70-93``.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ISSUE 6: the collective-parsing machinery this experiment pioneered is
# now the library's (paddle_tpu/obs/hloprof.py — the same regexes, shape
# rules, and ring factors, verbatim). tests/test_hloprof.py pins the
# aggregate's variadic/iota-group/async-start/ring-factor behaviors and
# its totals against the structured inventory, so the committed
# SCALING_* numbers cannot drift. Loaded by FILE PATH, not through the
# paddle_tpu package: hloprof.py is deliberately stdlib-only, and this
# driver does all jax work in env-controlled subprocesses — importing
# the package here would eagerly initialize jax in the parent.
import importlib.util as _ilu  # noqa: E402

_spec = _ilu.spec_from_file_location(
    "_hloprof", os.path.join(REPO, "paddle_tpu", "obs", "hloprof.py"))
_hloprof = _ilu.module_from_spec(_spec)
sys.modules["_hloprof"] = _hloprof      # dataclasses resolve via sys.modules
_spec.loader.exec_module(_hloprof)
parse_collectives = _hloprof.parse_collectives

# Public per-chip interconnect specs (cloud.google.com/tpu/docs spec
# sheets): v5e ICI 1,600 Gbit/s per chip aggregate -> 200 GB/s; one-way
# usable per direction ~100 GB/s. DCN (inter-slice) ~ 25 GB/s per host.
ICI_BYTES_PER_S = 100e9          # one-way per chip, v5e
DCN_BYTES_PER_S = 25e9 / 8      # per chip when 8 chips share a host NIC
ICI_POD_LIMIT = 256              # v5e pod: 256 chips on one ICI fabric

# Measured single-chip step times (experiments/PERF.md protocol; this
# round's numbers) and the transformer model zoo. t_comp is the IDEAL
# per-chip step time at that parallelism (single-chip time / model-split
# factor); pipeline bubble is charged separately via overhead_factor.
WORKLOADS = {
    "resnet50_dp": {
        "t_comp_ms": 47.1,           # measured (PERF.md r5 stem fix, bs128)
        "mode": "resnet", "all_ar_is_grad": True,
        "note": "ResNet-50 bs128/chip bf16, pure data parallel",
    },
    "transformer_dp_tp": {
        "t_comp_ms": None,           # filled from MEASURED_MS at load
        "mode": "tp", "d": 512, "L": 6, "H": 4, "ffn": 2048,
        "tp": 4, "sp": False, "bs_group": 8,
        "note": "TransformerLM d512 L6 seq2048, dp x tp=4 (the r4 NEGATIVE "
                "kept for contrast: at d512 the Megatron activation "
                "all-reduces make tp=4 ICI-heavy; see the _sp and d2048 "
                "rows for the configs that fix it)",
    },
    "transformer_dp_tp_sp": {
        "t_comp_ms": None,
        "mode": "tp", "d": 512, "L": 6, "H": 4, "ffn": 2048,
        "tp": 4, "sp": True, "bs_group": 8, "all_ar_is_grad": True,
        "note": "d512 tp=4 with EXPLICIT Megatron sequence-parallel "
                "residuals (parallel.make_megatron_sp_lm_apply, bf16 "
                "comm compression): AG+RS pairs replace the all-reduces, "
                "wire halved by comm_dtype=bf16, residuals/"
                "LayerNorms/activation memory shard T/tp per device; loss "
                "computed inside the shard_map so nothing [*,vocab]-"
                "shaped is ever gathered",
    },
    "transformer_d1024_dp_pp": {
        "t_comp_ms": None,
        "mode": "pp", "d": 1024, "L": 8, "H": 8, "ffn": 4096,
        "pp": 8, "microbatches": 32, "mb_rows_group": 4,
        "all_ar_is_grad": True,
        "note": "TransformerLM d1024 L8 seq2048, dp x GPipe pipe=8 (one "
                "block per stage, M=32 microbatches of 4 rows per dp "
                "group) via parallel.make_pipeline_loss — loss closes on "
                "the last stage (scalar psum; the naive replicated-output "
                "form pays a 1.07 GB/step pipe-axis broadcast, measured "
                "r5); activations hop via ppermute; efficiency charges "
                "the (S-1)/M bubble as compute overhead",
    },
    "transformer_d2048_dp_tp_sp": {
        "t_comp_ms": None,
        "mode": "tp", "d": 2048, "L": 8, "H": 16, "ffn": 8192,
        "tp": 4, "sp": True, "bs_group": 8, "all_ar_is_grad": True,
        "note": "TransformerLM d2048 L8 seq2048, dp x tp=4 + seq-parallel "
                "residuals (bf16 comm compression) — the dim where tp=4 "
                "should win: tp wire scales with d, compute with d^2",
    },
}

# Measured single-chip ms/step anchors (real v5e chip, interleaved
# differential; experiments/PERF.md "Round 5", dh=128 geometry). d512 and
# d1024 are at the bench shapes; d2048's bs8 group batch is anchored to
# the measured bs4 step (see _fill_t_comp).
MEASURED_MS = {
    "d512_bs8": 51.3,            # H4, 40.4% MFU
    "d1024_bs16": 339.1,         # H8, 44.2% MFU
    "d2048_bs4": 247.3,          # H16, 50.6% MFU (bs8 full-step OOMs the
                                 # 16 GB chip with adam states resident —
                                 # the tp group's whole point is that 4
                                 # chips share this model)
}

# per-model totals for the analytic dp-only baseline rows (params from
# model.init leaf sizes: blocks 12*d^2*L + tied emb V*d + pos T*d)
PARAM_COUNTS = {
    "d512": 12 * 512 * 512 * 6 + 32000 * 512 + 2048 * 512,
    "d1024": 12 * 1024 * 1024 * 8 + 32000 * 1024 + 2048 * 1024,
    "d2048": 12 * 2048 * 2048 * 8 + 32000 * 2048 + 2048 * 2048,
}


def _fill_t_comp():
    w = WORKLOADS
    w["transformer_dp_tp"]["t_comp_ms"] = \
        round(MEASURED_MS["d512_bs8"] / 4, 2)
    w["transformer_dp_tp_sp"]["t_comp_ms"] = \
        round(MEASURED_MS["d512_bs8"] / 4, 2)
    # one full pipeline group of 8 chips processes 8x the single-chip
    # batch: ideal per-chip time == the single-chip bs16 step time
    w["transformer_d1024_dp_pp"]["t_comp_ms"] = MEASURED_MS["d1024_bs16"]
    S = w["transformer_d1024_dp_pp"]["pp"]
    M = w["transformer_d1024_dp_pp"]["microbatches"]
    w["transformer_d1024_dp_pp"]["overhead_factor"] = (S - 1) / M
    # bs8 anchor = 2x the measured bs4 step: compute-bound at 50.6% MFU,
    # so batch scaling is ~linear (sub-linearity would only raise MFU and
    # efficiency; recorded as t_comp_basis on the workload)
    w["transformer_d2048_dp_tp_sp"]["t_comp_ms"] = \
        round(2 * MEASURED_MS["d2048_bs4"] / 4, 2)
    w["transformer_d2048_dp_tp_sp"]["t_comp_basis"] = \
        "2x measured bs4 single-chip step (247.3 ms, 50.6% MFU)"


_RESNET_CODE = """
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import paddle_tpu as pt
from paddle_tpu import optim
from paddle_tpu.nn import costs
from paddle_tpu.train import Trainer

import json
n = %(n)d
devices = jax.devices()[:n]
# small image: conv activations shrink (fast CPU compile) while the
# gradient all-reduce — the thing we are counting — is unchanged
from paddle_tpu.models import resnet50
mesh = pt.make_mesh({"data": n}, devices=devices)
trainer = Trainer(model=resnet50(num_classes=1000),
                  loss_fn=lambda out, b: costs.softmax_cross_entropy(
                      out, b["label"]),
                  optimizer=optim.momentum(0.1, 0.9), mesh=mesh)
rng = np.random.RandomState(0)
batch = {"x": rng.normal(size=(2 * n, 64, 64, 3)).astype(np.float32),
         "label": rng.randint(0, 1000, size=2 * n).astype(np.int32)}
trainer.init(jax.random.PRNGKey(0), batch)
trainer._build_train_step()
ts = trainer.train_state
sharded = trainer._shard(batch)
lowered = trainer._train_step.lower(ts.params, ts.state, ts.opt_state,
                                    ts.step, sharded,
                                    jax.random.PRNGKey(1))
print("=====HLO=====")
print(lowered.compile().as_text())
"""

_TRANSFORMER_CODE = """
import json, sys
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import paddle_tpu as pt
from paddle_tpu import optim, parallel
from paddle_tpu.nn import costs
from paddle_tpu.models import TransformerLM
from paddle_tpu.optim.optimizers import apply_updates

cfg = json.loads(%(cfg)r)
n = %(n)d
devices = jax.devices()[:n]
D, L, H, FFN = cfg["d"], cfg["L"], cfg["H"], cfg["ffn"]
V, SEQ = 32000, 2048
opt = optim.adam(1e-4)


def sds(shape, dtype, mesh, spec):
    return jax.ShapeDtypeStruct(shape, dtype,
                                sharding=NamedSharding(mesh, spec))


def abstract_params(model, mesh, ids_shape, spec_fn):
    \"\"\"eval_shape the init (no host buffers) and attach NamedShardings
    chosen by spec_fn(path-matched rules).\"\"\"
    var_sds = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                             jax.ShapeDtypeStruct(ids_shape, jnp.int32))
    params = var_sds["params"]
    specs = spec_fn(params)
    return jax.tree_util.tree_map(
        lambda s, sp: sds(s.shape, s.dtype, mesh, sp), params, specs)


if cfg["mode"] == "tp":
    tp = cfg["tp"]
    dp = n // tp
    B = cfg["bs_group"] * dp
    mesh = pt.make_mesh({"data": dp, "model": tp}, devices=devices)
    model = TransformerLM(vocab=V, dim=D, num_layers=L, num_heads=H,
                          ffn_hidden=FFN, max_len=SEQ)
    rules = parallel.megatron_sp_rules()
    p_sds = abstract_params(model, mesh, (B, SEQ), rules)
    inp_sds = sds((B, SEQ), jnp.int32, mesh, P("data", None))
    tgt_sds = sds((B, SEQ), jnp.int32, mesh, P("data", None))
    if cfg["sp"]:
        # EXPLICIT Megatron tp + sequence-parallel residuals: shard_map
        # with hand-written all_gather / psum_scatter pairs and the CE
        # loss computed inside (parallel/megatron.py) — the pjit
        # partitioner does not produce this lowering (it keeps
        # all-reduces, or splits the residual reshard into all-reduce +
        # all-gather, measured WORSE)
        lm_loss = parallel.make_megatron_sp_lm_apply(
            model, mesh, with_loss=True, comm_dtype=jnp.bfloat16)

        def ce_of(p, inp, tgt):
            return lm_loss({"params": p}, inp, tgt)
    else:
        def ce_of(p, inp, tgt):
            logits = model.apply({"params": p}, inp)
            return jnp.mean(costs.softmax_cross_entropy(
                logits.reshape(-1, V), tgt.reshape(-1)))

    def step(p, inp, tgt):
        def loss_fn(p):
            return ce_of(p, inp, tgt)
        loss, g = jax.value_and_grad(loss_fn)(p)
        # fresh opt state inside the step: zeros-init adds no collectives
        # and the abstract lowering then needs no opt-state shardings
        upd, _ = opt.update(g, opt.init(p), p, jnp.zeros((), jnp.int32))
        return loss, apply_updates(p, upd)

    lowered = jax.jit(step).lower(p_sds, inp_sds, tgt_sds)

else:                                  # mode == "pp": dp x GPipe blocks
    from paddle_tpu.parallel import make_pipeline_loss
    S = cfg["pp"]
    M = cfg["microbatches"]
    dp = n // S
    mbg = cfg["mb_rows_group"] * dp     # global rows per microbatch
    mesh = pt.make_mesh({"data": dp, "pipe": S}, devices=devices)
    model = TransformerLM(vocab=V, dim=D, num_layers=L, num_heads=H,
                          ffn_hidden=FFN, max_len=SEQ)
    assert len(model.blocks) == S
    block0 = model.blocks[0]
    var_sds = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                             jax.ShapeDtypeStruct((2, SEQ), jnp.int32))
    root_name = next(iter(var_sds["params"]))
    root = var_sds["params"][root_name]
    # [S, ...]-stacked block params sharded over pipe (keyed by block0 --
    # Module.apply scoping, the shape make_pipeline_lm_apply's
    # stack_blocks produces); embeddings/head/ln_f replicated, their
    # grads psum over the mesh like any replicated param
    blocks = [root["block%%d" %% i] for i in range(S)]
    stacked_sds = {"block0": jax.tree_util.tree_map(
        lambda *ls: sds((S,) + ls[0].shape, ls[0].dtype, mesh,
                        P(*(("pipe",) + (None,) * ls[0].ndim))), *blocks)}
    emb_sds = jax.tree_util.tree_map(
        lambda s: sds(s.shape, s.dtype, mesh, P()),
        {k: v for k, v in root.items() if not k.startswith("block")})

    def stage_fn(p_stage, act):
        out, _aux = block0.apply({"params": p_stage}, act)
        return out

    def _ln(x, p, eps=1e-6):
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - mu) * jax.lax.rsqrt(var + eps)
        return (y * p["scale"] + p["bias"]).astype(x.dtype)

    def final_fn(fp, outbuf, tgt):
        # per-microbatch CE scan keeps the [mb, T, V] logits transient
        emb_w = fp["emb"]["w"]

        def mb_ce(carry, zt):
            zz, tt = zt
            lg = (_ln(zz, fp["ln_f"]) @ emb_w.T).astype(jnp.float32)
            lse = jax.nn.logsumexp(lg, axis=-1)
            picked = jnp.take_along_axis(lg, tt[..., None],
                                         axis=-1)[..., 0]
            return carry + jnp.sum(lse - picked), None

        # derive the carry from a device-varying value (shard_map
        # varying-axes rule — same trick as pipeline_apply's buffers)
        carry0 = (outbuf.ravel()[0] * 0.0).astype(jnp.float32)
        tot, _ = jax.lax.scan(mb_ce, carry0, (outbuf, tgt))
        return tot

    pipe_loss = make_pipeline_loss(
        mesh, stage_fn, final_fn, pipe_axis="pipe",
        x_spec=P(None, "data", None, None),
        extra_specs=(P(None, "data", None),), reduce_axes=("data",),
        comm_dtype=jnp.bfloat16)

    ids_sds = sds((M, mbg, SEQ), jnp.int32, mesh, P(None, "data", None))
    tgt_sds = sds((M, mbg, SEQ), jnp.int32, mesh, P(None, "data", None))

    def train(stacked, emb_p, ids, tgt):
        def loss_of(stacked, emb_p):
            vars_embed = {"params": {root_name: dict(emb_p)}}
            # embed the 3-D [M, mbg, T] ids DIRECTLY (Embedding takes any
            # int shape; positions passed explicitly so the pos table
            # broadcasts over [M, mbg]) — reshaping [M, mbg(sharded), T]
            # to [M*mbg, T] merges a replicated dim into the dp-sharded
            # one and makes XLA all-gather the whole stack (33 GB/step at
            # n=256, measured)
            h = model.apply(vars_embed, ids,
                            positions=jnp.arange(SEQ)[None, None],
                            method="embed")
            # same emb leaf feeds embed (here) and the head (final_fn):
            # autodiff sums the tied-weight contributions
            return pipe_loss(stacked, emb_p, h, tgt) / (M * mbg * SEQ)
        loss, (gs, ge) = jax.value_and_grad(loss_of, argnums=(0, 1))(
            stacked, emb_p)
        u1, _ = opt.update(gs, opt.init(gs), gs, jnp.zeros((), jnp.int32))
        u2, _ = opt.update(ge, opt.init(ge), ge, jnp.zeros((), jnp.int32))
        return loss, apply_updates(stacked, u1), apply_updates(emb_p, u2)

    lowered = jax.jit(train).lower(stacked_sds, emb_sds, ids_sds, tgt_sds)

import re as _re
pre = lowered.as_text()
# bf16 collective detection in the pre-optimization StableHLO. all_gather
# and collective_permute print on one (long) line with the type at the
# end — match within the line (replica_groups literals grow with the mesh
# and overran a bounded window). reduce_scatter carries a multi-line
# reduction region, so take a wide DOTALL window to its type; our
# programs use a uniform comm dtype, so over-matching is not a concern.
pre_counts = {
    "bf16_all_gather": len(_re.findall(
        r"all_gather.*?bf16", pre)),           # '.' stops at the newline
    "bf16_reduce_scatter": len(_re.findall(
        r"reduce_scatter.{0,100000}?bf16", pre, _re.S)),
    "bf16_collective_permute": len(_re.findall(
        r"collective_permute.*?bf16", pre)),
}
print("=====PREOPT=====")
print(json.dumps(pre_counts))
print("=====HLO=====")
print(lowered.compile().as_text())
"""


def _collect_hlo(n_devices: int, workload: str):
    """Compile the sharded step on a forced n-device CPU platform in a
    subprocess. Returns ``(pre_counts, hlo_text)``: the pre-optimization
    bf16-collective counts (for the comm-compression correction) and the
    optimized post-SPMD HLO."""
    cfg = WORKLOADS[workload]
    if cfg["mode"] == "resnet":
        code = _RESNET_CODE % {"n": n_devices}
    else:
        code = _TRANSFORMER_CODE % {"n": n_devices, "cfg": json.dumps(cfg)}
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=3000)
    if res.returncode != 0:
        raise RuntimeError(f"HLO collection failed (n={n_devices}, "
                           f"{workload}): {res.stderr[-2000:]}")
    pre_counts = {}
    body = res.stdout
    if "=====PREOPT=====" in body:
        pre, body = body.split("=====PREOPT=====", 1)[1].split(
            "=====HLO=====", 1)
        pre_counts = json.loads(pre.strip().splitlines()[0])
    else:
        body = body.split("=====HLO=====", 1)[1]
    return pre_counts, body


def _row(cfg, n, wire, colls=None, extrapolated_from=None,
         grad_wire=None):
    bw = ICI_BYTES_PER_S if n <= ICI_POD_LIMIT else DCN_BYTES_PER_S
    t_comm_ms = wire / bw * 1e3
    t_comp = cfg["t_comp_ms"]
    ovh = cfg.get("overhead_factor", 0.0)
    t_step = t_comp * (1.0 + ovh)
    row = {
        "n_devices": n,
        "wire_bytes_per_device": round(wire),
        "link": "ICI" if n <= ICI_POD_LIMIT else "DCN",
        "t_comp_ms": t_comp,
        "t_comm_ms": round(t_comm_ms, 3),
        "efficiency_no_overlap": round(t_comp / (t_step + t_comm_ms), 4),
        "efficiency_full_overlap": round(
            t_comp / max(t_step, t_comm_ms), 4),
    }
    if grad_wire is not None:
        # middle column: only the GRAD all-reduce overlaps with backward
        # compute (the universally-implemented bucketed grad-sync overlap
        # — XLA's async collective scheduling does this automatically);
        # activation syncs stay on the critical path. Grad sync that
        # exceeds the step can't fully hide — charge the excess.
        t_act = (wire - grad_wire) / bw * 1e3
        t_grad = grad_wire / bw * 1e3
        hidden_excess = max(0.0, t_grad - t_step)
        row["efficiency_grad_overlap"] = round(
            t_comp / (t_step + t_act + hidden_excess), 4)
        row["grad_sync_hides_under_compute"] = bool(t_grad <= t_step)
    if ovh:
        row["compute_overhead_factor"] = round(ovh, 4)
    if colls is not None:
        row["collectives"] = colls
    if extrapolated_from is not None:
        row["extrapolated_from_n"] = extrapolated_from
        row["note"] = ("UPPER BOUND on wire bytes (ring factor taken to "
                       "its g->inf limit: 2B per all-reduce, B otherwise) "
                       "from the largest compiled mesh — fixed-size "
                       "replica groups (e.g. tp) keep constant per-device "
                       "wire, growing groups approach the bound; the XLA "
                       "compile at this mesh size exceeded the harness "
                       "budget. Efficiency is therefore a LOWER bound.")
    return row


def _wire_upper_bound(colls):
    """g->inf limit of the ring factors: 2B for all-reduce, B otherwise.
    >= the true wire at ANY group layout, so efficiencies computed from it
    are lower bounds."""
    total = 0.0
    for kind, e in colls.items():
        total += (2.0 if kind == "all-reduce" else 1.0) * e["buffer_bytes"]
    return total


def project(workload: str, counts=(8, 64, 256)):
    cfg = WORKLOADS[workload]
    rows = []
    last_colls = None
    for n in counts:
        try:
            pre_counts, hlo = _collect_hlo(n, workload)
        except (RuntimeError, subprocess.TimeoutExpired):
            if last_colls is None:
                raise
            colls, nn = last_colls
            rows.append(_row(cfg, n, _wire_upper_bound(colls),
                             extrapolated_from=nn))
            continue
        colls = parse_collectives(hlo, n)
        for kind, pre_key in (
                ("all-gather", "bf16_all_gather"),
                ("reduce-scatter", "bf16_reduce_scatter"),
                ("collective-permute", "bf16_collective_permute")):
            # bf16 comm compression: the jax-level program casts these
            # collectives' operands to bf16 (verified in the
            # pre-optimization StableHLO), but the CPU backend's float
            # normalization upcasts bf16 collectives to f32 in the
            # compiled HLO we parse — on TPU they run native bf16, so
            # halve the parsed wire and record the correction
            if kind in colls and pre_counts.get(pre_key, 0) > 0:
                colls[kind]["wire_bytes_per_device"] *= 0.5
                colls[kind]["bf16_comm_corrected"] = True
        if "collective-permute" in colls and cfg["mode"] == "pp":
            # the ppermute ops live inside the M+S-1-tick wavefront loop:
            # the static HLO op executes once per tick (fwd scan) and once
            # per tick in the transposed bwd scan — scale the parsed
            # static bytes by the tick count
            mult = cfg["microbatches"] + cfg["pp"] - 1
            e = colls["collective-permute"]
            e["wire_bytes_per_device"] *= mult
            e["loop_multiplier"] = mult
        wire = sum(e["wire_bytes_per_device"] for e in colls.values())
        last_colls = (colls, n)
        grad_wire = None
        if cfg.get("all_ar_is_grad") and "all-reduce" in colls:
            # in these workloads the activation syncs are AG/RS/ppermute
            # (explicit shard_map collectives); every all-reduce is a
            # grad/loss sync
            grad_wire = colls["all-reduce"]["wire_bytes_per_device"]
        rows.append(_row(cfg, n, wire, colls=colls, grad_wire=grad_wire))
    return {"workload": workload, "note": cfg["note"], "projection": rows}


def measured_transformer_proxy_n8():
    """MEASURED (executed, not just compiled) dp-sharded transformer step
    on the virtual 8-device CPU mesh vs the same step on 1 device — the
    anchor tying the transformer projection to a real sharded execution.
    Virtual devices share host cores, so the efficiency is a pessimistic
    floor; its value is that the collectives RUN and the sharded step's
    numerics/overheads are real."""
    code = """
import time, json
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import paddle_tpu as pt
from paddle_tpu import optim
from paddle_tpu.nn import costs
from paddle_tpu.models import TransformerLM
from paddle_tpu.optim.optimizers import apply_updates

n = int(jax.device_count())
V, D, L, H, FFN, SEQ, BPD = 8000, 256, 4, 2, 1024, 512, 2
model = TransformerLM(vocab=V, dim=D, num_layers=L, num_heads=H,
                      ffn_hidden=FFN, max_len=SEQ)
rng = np.random.RandomState(0)
B = BPD * n
ids = jnp.asarray(rng.randint(0, V, (B, SEQ + 1)), jnp.int32)
mesh = pt.make_mesh({"data": n}, devices=jax.devices()[:n])
inp = jax.device_put(ids[:, :-1], NamedSharding(mesh, P("data", None)))
tgt = jax.device_put(ids[:, 1:], NamedSharding(mesh, P("data", None)))
params = model.init(jax.random.PRNGKey(0), ids[:2, :-1])["params"]
opt = optim.adam(1e-4)
ostate = opt.init(params)

@jax.jit
def step(p, o, inp, tgt):
    def loss_fn(p):
        logits = model.apply({"params": p}, inp)
        return jnp.mean(costs.softmax_cross_entropy(
            logits.reshape(-1, V), tgt.reshape(-1)))
    l, g = jax.value_and_grad(loss_fn)(p)
    u, o2 = opt.update(g, o, p, jnp.zeros((), jnp.int32))
    return apply_updates(p, u), o2, l

params, ostate, l = step(params, ostate, inp, tgt)   # compile+warm
float(l)
iters = 6
t0 = time.perf_counter()
for _ in range(iters):
    params, ostate, l = step(params, ostate, inp, tgt)
float(l)
dt = (time.perf_counter() - t0) / iters
print(json.dumps({"n": n, "ms_per_step": round(dt * 1e3, 1),
                  "tokens_per_s": round(B * SEQ / dt)}))
"""
    out = {}
    for n in (1, 8):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={n}")
        env["XLA_FLAGS"] = " ".join(flags)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        t0 = time.time()
        res = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                             env=env, capture_output=True, text=True,
                             timeout=1800)
        if res.returncode != 0:
            return {"error": res.stderr[-1000:]}
        out[n] = json.loads(res.stdout.strip().splitlines()[-1])
        out[n]["wall_s"] = round(time.time() - t0, 1)
    # per-token throughput ratio: 8-dev tokens/s vs 8x the 1-dev rate
    eff = out[8]["tokens_per_s"] / (8 * out[1]["tokens_per_s"])
    return {
        "model": "TransformerLM d256 L4 seq512, dp=8, bs2/device",
        "n1": out[1], "n8": out[8],
        "efficiency_vs_linear": round(eff, 3),
        "environment": "virtual-cpu-mesh (devices share host cores: "
                       "pessimistic floor; validates the sharded step "
                       "EXECUTES, complements the analytic ICI projection)",
    }


def _dp_only_rows(model_key, t_comp_ms, counts=(8, 64, 256),
                  feasible=True, feasibility_note=""):
    """Analytic dp-only baseline: wire = f32 grad all-reduce only
    (2*P*4*(n-1)/n per device). Same arithmetic the resnet50_dp HLO parse
    is cross-checked against, so no per-model compile is needed.
    ``feasible=False`` keeps the row for context but excludes it from the
    recommendation (e.g. the model + optimizer states + training
    activations exceed single-chip HBM at the comparison batch)."""
    P_count = PARAM_COUNTS[model_key]
    rows = []
    for n in counts:
        wire = 2.0 * P_count * 4 * (n - 1) / n
        rows.append(_row({"t_comp_ms": t_comp_ms}, n, wire,
                         grad_wire=wire))
    note = (f"pure data parallel {model_key} (analytic grad "
            "all-reduce bytes; method cross-checked against the "
            "parsed resnet50_dp HLO)")
    if feasibility_note:
        note += ". " + feasibility_note
    return {"workload": f"{model_key}_dp_only_analytic",
            "feasible": feasible,
            "note": note,
            "projection": rows}


def _recommend(workloads_out):
    """Best config per (model, n) — dp-only baselines included, so tp/pp
    must actually beat dp to be named. Ranked by efficiency_grad_overlap
    (grad syncs hidden under backward — the standard and XLA-automatic
    overlap) with efficiency_no_overlap reported alongside as the
    pessimistic floor."""
    by_model = {
        "transformer_d512": ["transformer_dp_tp", "transformer_dp_tp_sp",
                             "d512_dp_only_analytic"],
        "transformer_d1024": ["transformer_d1024_dp_pp",
                              "d1024_dp_only_analytic"],
        "transformer_d2048": ["transformer_d2048_dp_tp_sp",
                              "d2048_dp_only_analytic"],
    }
    rec = {}
    table = {w["workload"]: w for w in workloads_out}
    for model, names in by_model.items():
        rec[model] = {}
        for n in (8, 64, 256):
            best = None
            for name in names:
                if name not in table:
                    continue
                if not table[name].get("feasible", True):
                    continue
                for row in table[name]["projection"]:
                    if row["n_devices"] == n:
                        eff = row.get("efficiency_grad_overlap",
                                      row["efficiency_no_overlap"])
                        cand = (eff, name, row["efficiency_no_overlap"])
                        if best is None or cand > best:
                            best = cand
            if best:
                rec[model][str(n)] = {
                    "config": best[1],
                    "efficiency_grad_overlap": best[0],
                    "efficiency_no_overlap": best[2]}
    return rec


def main(counts=(8, 64, 256)):
    _fill_t_comp()
    out = {
        "metric": "scaling_efficiency_projection",
        "method": (
            "per-step collective wire bytes parsed from the post-SPMD "
            "optimized HLO of the real sharded train step, compiled on a "
            "forced n-device virtual CPU platform (transformers lower "
            "abstractly — ShapeDtypeStruct args with NamedShardings); "
            "ring-algorithm wire factors; public v5e ICI bandwidth; "
            "measured single-chip step time as t_comp; GPipe bubble "
            "charged as compute overhead; in-loop ppermutes scaled by the "
            "tick count. Numeric correctness of the same collectives is "
            "pinned by __graft_entry__ dryrun (steps 2/4/7) + the "
            "megatron/pipeline-loss oracle tests + the measured proxy "
            "below."),
        "constants": {
            "ici_bytes_per_s_per_chip_oneway": ICI_BYTES_PER_S,
            "dcn_bytes_per_s_per_chip": DCN_BYTES_PER_S,
            "ici_pod_limit_chips": ICI_POD_LIMIT,
            "source": "public TPU v5e spec (1600 Gbit/s ICI per chip)",
        },
        "measured_single_chip_ms": {k: v for k, v in MEASURED_MS.items()},
        "workloads": [],
        "reference_anchor": "3.85x at 4 GPUs, reference benchmark/README.md",
    }
    for w in WORKLOADS:
        out["workloads"].append(project(w, counts=counts))
    out["workloads"].append(
        _dp_only_rows("d512", MEASURED_MS["d512_bs8"], counts))
    out["workloads"].append(
        _dp_only_rows("d1024", MEASURED_MS["d1024_bs16"], counts))
    out["workloads"].append(_dp_only_rows(
        "d2048", 2 * MEASURED_MS["d2048_bs4"], counts,
        feasible=False,
        feasibility_note=(
            "INFEASIBLE at the comparison batch: the bs8 full training "
            "step (params + adam states + activations) OOMs the 16 GB "
            "chip — measured, experiments/profile_transformer.py "
            "PROF_DIM=2048 PROF_BS=8; bs4 runs AT the memory cliff with "
            "no headroom for longer sequences. Kept for wire context; "
            "excluded from the recommendation — d2048-class training "
            "needs the model sharded (tp+sp)")))

    out["recommended"] = _recommend(out["workloads"])

    # cross-check 1: n=8 resnet all-reduce buffer bytes ~= f32 grad size
    rn = out["workloads"][0]["projection"][0]
    ar = rn["collectives"].get("all-reduce", {"buffer_bytes": 0})
    expect = 25.6e6 * 4            # ~25.6M params, f32 grads
    ratio = ar["buffer_bytes"] / expect
    out["cross_check"] = {
        "resnet50_allreduce_buffer_bytes": ar["buffer_bytes"],
        "expected_f32_grad_bytes": expect,
        "ratio": round(ratio, 3),
        "pass": bool(0.8 < ratio < 1.3),
    }
    # cross-check 2: measured virtual-mesh transformer execution at n=8
    out["measured_proxy_transformer_n8"] = measured_transformer_proxy_n8()
    return out


if __name__ == "__main__":
    quick = "--quick" in sys.argv
    result = main(counts=(8,) if quick else (8, 64, 256))
    path = os.path.join(REPO, "SCALING_r05.json")
    # keep the honest virtual-mesh proxy alongside the projection
    prev = os.path.join(REPO, "SCALING_r03.json")
    if os.path.exists(prev):
        with open(prev) as f:
            result["virtual_mesh_proxy_r03"] = json.load(f)
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({"metric": result["metric"],
                      "cross_check_pass": result["cross_check"]["pass"],
                      "recommended": result.get("recommended"),
                      "written": path}))
