"""Analytic multi-chip scaling projection from the sharded step's HLO.

VERDICT r3 item 6: the virtual-CPU-mesh proxy (``bench.py --metric
scaling``) measures 8 virtual devices sharing one host's cores — it
validates collective CORRECTNESS but says nothing about TPU-mesh scaling.
This script supplies the missing analytic complement:

1. For each workload config and device count n in {8, 64, 256}, compile the
   REAL sharded training step on a forced n-device virtual CPU platform and
   parse the optimized (post-SPMD) HLO for the collectives XLA actually
   inserted (all-reduce / all-gather / reduce-scatter / all-to-all /
   collective-permute) with their buffer sizes.
2. Convert buffers to per-device wire bytes with the standard ring-algorithm
   factors (all-reduce 2B(n-1)/n, gather/scatter/all-to-all B(n-1)/n,
   permute B).
3. Combine with public per-chip ICI bandwidth and the measured single-chip
   step time into projected scaling efficiency, both with no comm/compute
   overlap (pessimistic) and perfect overlap (optimistic bound).

Cross-check: at n=8 the parsed all-reduce bytes must match the analytic
expectation (the f32 gradient size of the model) within 10% — tying the HLO
parse to ground truth. The numeric correctness of the same collectives is
pinned by the virtual-mesh dryrun (`__graft_entry__._dryrun_impl`) and the
proxy bench.

Output: ``SCALING_r04.json`` at the repo root (run from repo root:
``python experiments/scaling_projection.py``).

Reference anchor: the 3.85x-at-4-GPUs table,
``/root/reference/benchmark/README.md:70-93``.
"""

import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Public per-chip interconnect specs (cloud.google.com/tpu/docs spec
# sheets): v5e ICI 1,600 Gbit/s per chip aggregate -> 200 GB/s; one-way
# usable per direction ~100 GB/s. DCN (inter-slice) ~ 25 GB/s per host.
ICI_BYTES_PER_S = 100e9          # one-way per chip, v5e
DCN_BYTES_PER_S = 25e9 / 8      # per chip when 8 chips share a host NIC
ICI_POD_LIMIT = 256              # v5e pod: 256 chips on one ICI fabric

# Measured single-chip step times (experiments/PERF.md protocol / BENCH_r04)
# and per-step FLOPs for the projected workloads.
WORKLOADS = {
    "resnet50_dp": {
        "t_comp_ms": 47.5,           # measured (PERF.md r4, bs128/chip)
        "note": "ResNet-50 bs128/chip bf16, pure data parallel",
    },
    "transformer_dp_tp": {
        # per-chip compute = measured single-chip 65.6 ms (bs8 seq2048,
        # post flash-block fix) split ideally over the tp=4 group that
        # shares those tokens
        "t_comp_ms": 65.6 / 4,
        "note": "TransformerLM d512 L6 seq2048, dp x tp=4, bs8 per "
                "tp-group (HLO compiled at the real token count; t_comp = "
                "measured single-chip 65.6 ms / tp). TAKEAWAY: at d512 the "
                "Megatron-style activation all-reduces (~2.4 GB/step/chip) "
                "make tp=4 ICI-bound — TP comm scales with d while compute "
                "scales with d^2, so small models should shard dp-only "
                "(96%+ projected) and reserve tp for larger dims",
    },
}


def _collect_hlo(n_devices: int, workload: str) -> str:
    """Compile the sharded step on a forced n-device CPU platform in a
    subprocess; print the optimized HLO."""
    code = f"""
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import paddle_tpu as pt
from paddle_tpu import optim, parallel
from paddle_tpu.nn import costs
from paddle_tpu.train import Trainer

n = {n_devices}
devices = jax.devices()[:n]
if "{workload}" == "resnet50_dp":
    # small image: conv activations shrink (fast CPU compile) while the
    # gradient all-reduce — the thing we are counting — is unchanged
    from paddle_tpu.models import resnet50
    mesh = pt.make_mesh({{"data": n}}, devices=devices)
    trainer = Trainer(model=resnet50(num_classes=1000),
                      loss_fn=lambda out, b: costs.softmax_cross_entropy(
                          out, b["label"]),
                      optimizer=optim.momentum(0.1, 0.9), mesh=mesh)
    rng = np.random.RandomState(0)
    batch = {{"x": rng.normal(size=(2 * n, 64, 64, 3)).astype(np.float32),
             "label": rng.randint(0, 1000, size=2 * n).astype(np.int32)}}
    trainer.init(jax.random.PRNGKey(0), batch)
    trainer._build_train_step()
    ts = trainer.train_state
    sharded = trainer._shard(batch)
    lowered = trainer._train_step.lower(ts.params, ts.state, ts.opt_state,
                                        ts.step, sharded,
                                        jax.random.PRNGKey(1))
else:
    # TransformerLM dp x tp: batch over data, FFN/attn weights over model.
    # Compiled at the REAL bench token count (bs8 per tp-group, seq 2048):
    # the Megatron-style TP activation all-reduces scale with B*seq*dim,
    # so a shrunk compile shape would undercount them.
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.optim.optimizers import apply_updates
    tp = 4
    mesh = pt.make_mesh({{"data": n // tp, "model": tp}}, devices=devices)
    SEQ = 2048
    model = TransformerLM(vocab=32000, dim=512, num_layers=6, num_heads=8,
                          ffn_hidden=2048, max_len=SEQ)
    rng = np.random.RandomState(0)
    B = 8 * (n // tp)
    ids = jnp.asarray(rng.randint(0, 32000, (B, SEQ + 1)), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids[:, :-1])
    rules = parallel.ShardingRules([
        ("*/attn/wq", P(None, "model")), ("*/attn/wk", P(None, "model")),
        ("*/attn/wv", P(None, "model")), ("*/attn/wo", P("model", None)),
        ("*/ffn1/w", P(None, "model")), ("*/ffn1/b", P("model")),
        ("*/ffn2/w", P("model", None)),
    ])
    params = parallel.shard_tree(mesh, variables["params"],
                                 rules(variables["params"]))
    inp = jax.device_put(ids[:, :-1], NamedSharding(mesh, P("data", None)))
    tgt = jax.device_put(ids[:, 1:], NamedSharding(mesh, P("data", None)))
    opt = optim.adam(1e-4)
    opt_state = opt.init(params)

    def step(p, opt_state, sno, inp, tgt):
        def loss_fn(p):
            logits = model.apply({{"params": p}}, inp)
            return jnp.mean(costs.softmax_cross_entropy(
                logits.reshape(-1, 32000), tgt.reshape(-1)))
        loss, g = jax.value_and_grad(loss_fn)(p)
        upd, o2 = opt.update(g, opt_state, p, sno)
        return loss, apply_updates(p, upd), o2

    lowered = jax.jit(step).lower(params, opt_state, jnp.zeros((), jnp.int32),
                                  inp, tgt)
print("=====HLO=====")
print(lowered.compile().as_text())
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=3000)
    if res.returncode != 0:
        raise RuntimeError(f"HLO collection failed (n={n_devices}, "
                           f"{workload}): {res.stderr[-2000:]}")
    return res.stdout.split("=====HLO=====", 1)[1]


_DTYPE_BYTES = {"f64": 8, "f32": 4, "s32": 4, "u32": 4, "bf16": 2,
                "f16": 2, "s8": 1, "u8": 1, "pred": 1, "s64": 8, "u64": 8}

# XLA aggregates gradients into VARIADIC collectives whose result is a
# tuple: `(f32[64]{0}, f32[128,3]{1,0}) all-reduce(...)` — the shape group
# must accept both single shapes and tuples.
_SHAPE = r"\w+\[[\d,]*\](?:\{[^}]*\})?"
_COLL_RE = re.compile(
    r"((?:" + _SHAPE + r")|\((?:" + _SHAPE + r")(?:,\s*(?:" + _SHAPE +
    r"))*\))\s+"
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(-start)?\(")


def _shape_bytes(shape_s: str, kind: str = "", is_start: bool = False) -> int:
    """Total bytes of a shape or tuple-of-shapes string, counting only the
    RESULT buffers for async '*-start' forms. Per-kind, per XLA's HLO:
    all-gather-start and collective-permute-start carry
    ``(operand..., result..., [u32 contexts])`` tuples (count the trailing
    result half after dropping the dimensionless context scalars);
    all-reduce/reduce-scatter/all-to-all '-start' shapes are already
    results-only (count everything). The n=8 sync-HLO cross-check in this
    experiment guards this assumption against XLA lowering drift."""
    shapes = list(re.finditer(r"(\w+)\[([\d,]*)\]", shape_s))
    if is_start:
        shapes = [m for m in shapes
                  if not (m.group(1) in ("u32", "s32") and not m.group(2))]
        if kind in ("all-gather", "collective-permute") \
                and len(shapes) >= 2 and len(shapes) % 2 == 0:
            shapes = shapes[len(shapes) // 2:]
    total = 0
    for m in shapes:
        dt, dims = m.group(1), m.group(2)
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dt, 4)
    return total


def _group_size(op_line: str, default: int) -> int:
    """Replica-group size of one collective op: the ring factor must use
    the GROUP the op actually spans (a tp=4 activation all-reduce on a
    dp x tp mesh rings over 4 devices, not the whole mesh)."""
    m = re.search(r"replica_groups=\{\{([\d,]+)\}", op_line)
    if m:                          # explicit form {{0,1,2,3},{4,...}}
        return len(m.group(1).split(","))
    m = re.search(r"replica_groups=\[(\d+),(\d+)\]<=", op_line)
    if m:                          # iota form [groups, group_size]<=[...]
        return int(m.group(2))
    return default


def parse_collectives(hlo: str, n_devices: int):
    """Per-device wire bytes by collective kind (ring-algorithm factors
    over each op's replica group)."""
    # XLA interleaves /*index=N*/ comments inside big variadic tuples —
    # strip them or the tuple regex stops at the first comment
    hlo = re.sub(r"/\*.*?\*/", "", hlo)
    by_kind = {}
    for line in hlo.splitlines():
        m = _COLL_RE.search(line)
        if not m:
            continue
        shape_s, kind = m.group(1), m.group(2)
        b = _shape_bytes(shape_s, kind=kind, is_start=bool(m.group(3)))
        g = _group_size(line, n_devices)
        if g <= 1:                 # degenerate 1-device group moves nothing
            continue
        if kind == "all-reduce":
            wire = 2.0 * b * (g - 1) / g
        elif kind == "reduce-scatter":
            wire = 1.0 * b * (g - 1)     # result is the 1/g shard
        elif kind in ("all-gather", "all-to-all"):
            wire = 1.0 * b * (g - 1) / g
        else:                      # collective-permute
            wire = float(b)
        e = by_kind.setdefault(kind, {"ops": 0, "buffer_bytes": 0,
                                      "wire_bytes_per_device": 0.0,
                                      "group_sizes": []})
        e["ops"] += 1
        e["buffer_bytes"] += b
        e["wire_bytes_per_device"] += wire
        if g not in e["group_sizes"]:
            e["group_sizes"].append(g)
    return by_kind


def _row(cfg, n, wire, colls=None, extrapolated_from=None):
    bw = ICI_BYTES_PER_S if n <= ICI_POD_LIMIT else DCN_BYTES_PER_S
    t_comm_ms = wire / bw * 1e3
    t_comp = cfg["t_comp_ms"]
    row = {
        "n_devices": n,
        "wire_bytes_per_device": round(wire),
        "link": "ICI" if n <= ICI_POD_LIMIT else "DCN",
        "t_comp_ms": t_comp,
        "t_comm_ms": round(t_comm_ms, 3),
        "efficiency_no_overlap": round(t_comp / (t_comp + t_comm_ms), 4),
        "efficiency_full_overlap": round(t_comp / max(t_comp, t_comm_ms), 4),
    }
    if colls is not None:
        row["collectives"] = colls
    if extrapolated_from is not None:
        row["extrapolated_from_n"] = extrapolated_from
        row["note"] = ("UPPER BOUND on wire bytes (ring factor taken to "
                       "its g->inf limit: 2B per all-reduce, B otherwise) "
                       "from the largest compiled mesh — fixed-size "
                       "replica groups (e.g. tp) keep constant per-device "
                       "wire, growing groups approach the bound; the XLA "
                       "compile at this mesh size exceeded the harness "
                       "budget. Efficiency is therefore a LOWER bound.")
    return row


def _wire_upper_bound(colls):
    """g->inf limit of the ring factors: 2B for all-reduce, B otherwise.
    >= the true wire at ANY group layout, so efficiencies computed from it
    are lower bounds."""
    total = 0.0
    for kind, e in colls.items():
        total += (2.0 if kind == "all-reduce" else 1.0) * e["buffer_bytes"]
    return total


def project(workload: str, counts=(8, 64, 256)):
    cfg = WORKLOADS[workload]
    rows = []
    last_colls = None
    for n in counts:
        try:
            hlo = _collect_hlo(n, workload)
        except (RuntimeError, subprocess.TimeoutExpired):
            if last_colls is None:
                raise
            colls, nn = last_colls
            rows.append(_row(cfg, n, _wire_upper_bound(colls),
                             extrapolated_from=nn))
            continue
        colls = parse_collectives(hlo, n)
        wire = sum(e["wire_bytes_per_device"] for e in colls.values())
        last_colls = (colls, n)
        rows.append(_row(cfg, n, wire, colls=colls))
    return {"workload": workload, "note": cfg["note"], "projection": rows}


def main():
    out = {
        "metric": "scaling_efficiency_projection",
        "method": (
            "per-step collective wire bytes parsed from the post-SPMD "
            "optimized HLO of the real sharded train step, compiled on a "
            "forced n-device virtual CPU platform; ring-algorithm wire "
            "factors; public v5e ICI bandwidth; measured single-chip step "
            "time as t_comp. Numeric correctness of the same collectives "
            "is pinned by __graft_entry__ dryrun + the virtual-mesh proxy."),
        "constants": {
            "ici_bytes_per_s_per_chip_oneway": ICI_BYTES_PER_S,
            "dcn_bytes_per_s_per_chip": DCN_BYTES_PER_S,
            "ici_pod_limit_chips": ICI_POD_LIMIT,
            "source": "public TPU v5e spec (1600 Gbit/s ICI per chip)",
        },
        "workloads": [],
        "reference_anchor": "3.85x at 4 GPUs, reference benchmark/README.md",
    }
    for w in WORKLOADS:
        out["workloads"].append(project(w))

    # cross-check: n=8 resnet all-reduce buffer bytes ~= f32 grad size
    rn = out["workloads"][0]["projection"][0]
    ar = rn["collectives"].get("all-reduce", {"buffer_bytes": 0})
    import numpy as np
    expect = 25.6e6 * 4            # ~25.6M params, f32 grads
    ratio = ar["buffer_bytes"] / expect
    out["cross_check"] = {
        "resnet50_allreduce_buffer_bytes": ar["buffer_bytes"],
        "expected_f32_grad_bytes": expect,
        "ratio": round(ratio, 3),
        "pass": bool(0.8 < ratio < 1.3),
    }
    return out


if __name__ == "__main__":
    result = main()
    path = os.path.join(REPO, "SCALING_r04.json")
    # keep the honest virtual-mesh proxy alongside the projection
    prev = os.path.join(REPO, "SCALING_r03.json")
    if os.path.exists(prev):
        with open(prev) as f:
            result["virtual_mesh_proxy_r03"] = json.load(f)
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({"metric": result["metric"],
                      "cross_check_pass": result["cross_check"]["pass"],
                      "written": path}))
