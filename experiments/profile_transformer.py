"""Where do the compute-bound transformer's ms go? (round-4 MFU work)

bench transformer_big (d1024 L8 bs16 seq2048 bf16 flash) measured
0.95 s/step = 15.8% MFU — low for a GEMM-dominated config. This script
ablates the step on the real chip with the r4 interleaved-differential
protocol (no fetch inside timed regions):

  - full train step (fwd+bwd+adam)
  - value_and_grad only
  - forward only
  - attention isolated: flash fwd / flash fwd+bwd vs the dense reference
    at the bench shape, over the block_q/block_k grid
  - GEMM floor: the step's matmuls alone (QKVO + FFN + head as plain
    jnp.dot chains at identical shapes/dtypes)

Usage: PYTHONPATH=/root/repo:/root/.axon_site python
       experiments/profile_transformer.py [--quick]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from _timing import diff_time

# PROF_* env overrides re-point the script at other transformer shapes
# (d512 bench config, d2048 scaling anchor). PROF_HEADS at fixed D is the
# dh=128 vs dh=64 MXU geometry experiment; attention FLOPs are
# H-independent.
B = int(os.environ.get("PROF_BS", "16"))
T = 2048
D = int(os.environ.get("PROF_DIM", "1024"))
L = int(os.environ.get("PROF_LAYERS", "8"))
H = int(os.environ.get("PROF_HEADS", "8"))
V = 32000
FFN = 4 * D
PEAK = 197e12


def main():
    from paddle_tpu import optim
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn import costs
    from paddle_tpu.nn.pallas_attention import (flash_attention,
                                                reference_attention)
    from paddle_tpu.optim.optimizers import apply_updates

    quick = "--quick" in sys.argv
    # --only fwd,att,ref,gemm,grad,full,dh128 runs a subset (crash recovery:
    # the remote tunnel can RESOURCE_EXHAUST mid-script; rerun the rest in a
    # fresh process)
    only = None
    for a in sys.argv[1:]:
        if a.startswith("--only="):
            only = set(a.split("=", 1)[1].split(","))

    def want(sec):
        return only is None or sec in only

    out = {"config": f"d{D} L{L} bs{B} seq{T} bf16"}
    rng = np.random.RandomState(0)

    with use_policy(bfloat16_compute):
        model = TransformerLM(vocab=V, dim=D, num_layers=L, num_heads=H,
                              ffn_hidden=FFN, max_len=T, use_flash=True)
        ids = jnp.asarray(rng.randint(0, V, (B, T + 1)), jnp.int32)
        inp, tgt = ids[:, :-1], ids[:, 1:]
        variables = model.init(jax.random.PRNGKey(0), inp)
        opt = optim.adam(1e-4)
        params = variables["params"]
        opt_state = opt.init(params)

        def loss_of(p):
            logits = model.apply({"params": p}, inp)
            return jnp.mean(costs.softmax_cross_entropy(
                logits.reshape(-1, V), tgt.reshape(-1)))

        # Params must be STATE, never closure: a ~0.5 GB closure constant
        # blows up the remote-compile payload (reproducible broken pipe),
        # while the same program with params as donated arguments compiles
        # fine. Each section re-inits its own (donated) copy.

        # -- forward only ----------------------------------------------------
        def fwd_body(s):  # noqa: E306
            # folding 1e-20*loss into the params keeps them loop-variant
            # (no cross-call caching games) at far-below-bf16 resolution
            p, acc = s
            l = loss_of(p)
            p2 = jax.tree_util.tree_map(
                lambda a: a + (l * 1e-20).astype(a.dtype), p)
            return (p2, acc + l)

        if want("fwd"):
            out["fwd_only_ms"] = round(
                diff_time(fwd_body, (params, jnp.zeros((), jnp.float32)),
                          k=4), 1)
            print("partial:", json.dumps(out), file=sys.stderr, flush=True)

        # -- attention isolated ---------------------------------------------
        q_host = rng.normal(size=(B, H, T, D // H))

        def fresh_q():       # each diff_time donates its state
            return (jnp.asarray(q_host, jnp.bfloat16),
                    jnp.zeros((), jnp.float32))

        def att_cfg(bq, bk, with_bwd):
            def body(s):
                qq, acc = s
                if with_bwd:
                    def f(qq):
                        o = flash_attention(qq, qq, qq, causal=True,
                                            block_q=bq, block_k=bk)
                        return jnp.sum(o.astype(jnp.float32) ** 2)
                    l, dq = jax.value_and_grad(f)(qq)
                    return (qq + 1e-6 * dq.astype(qq.dtype), acc + l)
                o = flash_attention(qq, qq, qq, causal=True,
                                    block_q=bq, block_k=bk)
                return (qq + 1e-6 * o, acc + jnp.sum(o.astype(jnp.float32)))
            return body

        if want("att"):
            grid = [(128, 128)] if quick else [(128, 128), (256, 256),
                                               (512, 512), (256, 1024),
                                               (512, 1024), (1024, 1024)]
            att = {}
            for bq, bk in grid:
                att[f"fwd_bq{bq}_bk{bk}"] = round(
                    diff_time(att_cfg(bq, bk, False), fresh_q(), k=30,
                              use_fori=True), 2)
                att[f"fwdbwd_bq{bq}_bk{bk}"] = round(
                    diff_time(att_cfg(bq, bk, True), fresh_q(), k=30,
                              use_fori=True), 2)
            out["attention_per_layer_ms"] = att
            print("partial:", json.dumps(out), file=sys.stderr, flush=True)

        # -- dh=128 head-geometry probe (same total D = H*dh, same FLOPs):
        # at dh=64 both attention matmuls run half-width MXU tiles
        # (contraction / output dim 64 vs the 128x128 array) --------------
        if want("dh128") and D // H == 64:
            # only meaningful from the dh=64 geometry (PROF_HEADS=16 at
            # d1024); from the dh=128 default it would probe dh=256
            q128 = rng.normal(size=(B, H // 2, T, 2 * (D // H)))
            dh = {}
            for bq, bk in [(512, 1024), (1024, 1024)]:
                def cfg(with_bwd, bq=bq, bk=bk):
                    def body(s):
                        qq, acc = s
                        if with_bwd:
                            def f(qq):
                                o = flash_attention(qq, qq, qq, causal=True,
                                                    block_q=bq, block_k=bk)
                                return jnp.sum(o.astype(jnp.float32) ** 2)
                            l, dq = jax.value_and_grad(f)(qq)
                            return (qq + 1e-6 * dq.astype(qq.dtype), acc + l)
                        o = flash_attention(qq, qq, qq, causal=True,
                                            block_q=bq, block_k=bk)
                        return (qq + 1e-6 * o,
                                acc + jnp.sum(o.astype(jnp.float32)))
                    return body
                st = (jnp.asarray(q128, jnp.bfloat16),
                      jnp.zeros((), jnp.float32))
                dh[f"fwd_bq{bq}_bk{bk}"] = round(
                    diff_time(cfg(False), st, k=30, use_fori=True), 2)
                st = (jnp.asarray(q128, jnp.bfloat16),
                      jnp.zeros((), jnp.float32))
                dh[f"fwdbwd_bq{bq}_bk{bk}"] = round(
                    diff_time(cfg(True), st, k=30, use_fori=True), 2)
            out["attention_dh128_per_layer_ms"] = dh
            print("partial:", json.dumps(out), file=sys.stderr, flush=True)

        # dense reference attention (materialises [T,T]) for context
        def ref_body(s):
            qq, acc = s
            o = reference_attention(
                qq.astype(jnp.float32), qq.astype(jnp.float32),
                qq.astype(jnp.float32), causal=True)
            return (qq + 1e-6 * o.astype(qq.dtype),
                    acc + jnp.sum(o))
        if not quick and want("ref"):
            out["attention_ref_fwd_ms"] = round(
                diff_time(ref_body, fresh_q(), k=6,
                          use_fori=True), 2)

        # -- GEMM floor ------------------------------------------------------
        x2 = jnp.asarray(rng.normal(size=(B * T, D)), jnp.bfloat16)
        wq = jnp.asarray(rng.normal(size=(D, 3 * D)) * .02, jnp.bfloat16)
        wo = jnp.asarray(rng.normal(size=(D, D)) * .02, jnp.bfloat16)
        w1 = jnp.asarray(rng.normal(size=(D, FFN)) * .02, jnp.bfloat16)
        w2 = jnp.asarray(rng.normal(size=(FFN, D)) * .02, jnp.bfloat16)
        wh = jnp.asarray(rng.normal(size=(D, V)) * .02, jnp.bfloat16)

        def gemm_body(s):
            # weights ride in the state (donated): big closures break the
            # remote-compile payload
            x, acc, wq, wo, w1, w2, wh = s
            h = x
            for _ in range(L):
                h = (h @ wq)[:, :D]
                h = h @ wo
                h = jnp.maximum(h @ w1, 0) @ w2
            lg = h @ wh
            return (x + 1e-6 * h, acc + jnp.sum(lg.astype(jnp.float32)),
                    wq, wo, w1, w2, wh)

        if want("gemm"):
            out["gemm_fwd_floor_ms"] = round(
                diff_time(gemm_body,
                          (x2, jnp.zeros((), jnp.float32), wq, wo, w1, w2,
                           wh),
                          k=10, use_fori=True), 1)
            print("partial:", json.dumps(out), file=sys.stderr, flush=True)

        # -- grad only (fresh params, donated; SGD-like fold keeps every
        # grad leaf live) -----------------------------------------------------
        def grad_body(s):
            p, acc = s
            l, g = jax.value_and_grad(loss_of)(p)
            p2 = jax.tree_util.tree_map(
                lambda a, b: a - 1e-12 * b.astype(a.dtype), p, g)
            return (p2, acc + l)

        if want("grad"):
            params = model.init(jax.random.PRNGKey(0), inp)["params"]
            out["grad_only_ms"] = round(
                diff_time(grad_body, (params, jnp.zeros((), jnp.float32)),
                          k=4), 1)
            print("partial:", json.dumps(out), file=sys.stderr, flush=True)

        # -- full step (params were donated above: fresh init) ---------------
        def full_body(s):
            p, o, i, _ = s
            l, g = jax.value_and_grad(loss_of)(p)
            u, o2 = opt.update(g, o, p, i)
            return (apply_updates(p, u), o2, i + 1, l)

        if want("full"):
            params = model.init(jax.random.PRNGKey(0), inp)["params"]
            opt_state = opt.init(params)
            st = (params, opt_state, jnp.zeros((), jnp.int32),
                  jnp.zeros((), jnp.float32))
            out["full_step_ms"] = round(diff_time(full_body, st, k=4), 1)

            import bench
            flops = bench.transformer_train_flops(B, T, D, L, V, FFN)
            out["flops_per_step"] = flops
            out["mfu_from_full_step"] = round(
                100 * flops / (out["full_step_ms"] / 1e3) / PEAK, 1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
