"""Summarize a jax.profiler trace: per-op-category and top-op device time.

Usage: python experiments/trace_summary.py <tracedir> [n_steps]
"""

import collections
import glob
import gzip
import json
import re
import sys


def main():
    tracedir = sys.argv[1]
    nsteps = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    paths = sorted(glob.glob(f"{tracedir}/plugins/profile/*/*.trace.json.gz"))
    path = paths[-1]
    with gzip.open(path) as f:
        data = json.load(f)
    pids = {e["pid"]: e["args"].get("name")
            for e in data["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    dev_pid = next(p for p, n in pids.items() if "TPU" in (n or ""))
    events = [e for e in data["traceEvents"]
              if e.get("ph") == "X" and e.get("pid") == dev_pid
              and not e["name"].startswith("jit_")
              and not re.fullmatch(r"\d+", e["name"])]
    cat = collections.Counter()
    flops = collections.Counter()
    total = 0.0
    for e in events:
        a = e.get("args") or {}
        c = a.get("hlo_category", "?")
        cat[c] += e["dur"]
        total += e["dur"]
        flops[c] += int(a.get("model_flops", 0) or 0)
    print(f"[{path}]")
    print(f"per-step device total: {total/nsteps/1e3:.2f} ms")
    for n, d in cat.most_common(12):
        print(f"{d/nsteps/1e3:9.2f} ms/step  {100*d/total:5.1f}%  "
              f"flops={flops[n]/nsteps/1e9:8.1f}G  {n}")
    print()
    agg = collections.defaultdict(float)
    names = {}
    for e in events:
        a = e.get("args") or {}
        agg[e["name"]] += e["dur"]
        names[e["name"]] = (a.get("long_name") or "")[:150]
    for n, d in sorted(agg.items(), key=lambda kv: -kv[1])[:30]:
        print(f"{d/nsteps/1e3:8.2f} ms/step {n[:36]:36s} {names[n][:110]}")


if __name__ == "__main__":
    main()
