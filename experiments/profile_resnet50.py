"""ResNet-50 step profiling + ablations (round-3 MFU work).

Measures where the non-MXU time goes in the flagship bench step:
  - full train step (fwd+bwd+momentum, donation, bf16 policy) [the bench path]
  - value_and_grad only (no optimizer)
  - forward only (train=True, BN batch stats)
  - forward only (train=False, running stats)
and captures a jax.profiler trace of the full step, plus XLA's own
cost analysis (FLOPs / bytes) of the compiled executable.

NOTE: the dtype policy is consulted at *trace* time, so every first call of a
jitted function must happen inside ``use_policy(bfloat16_compute)``.

Usage: PYTHONPATH=.:$PYTHONPATH python experiments/profile_resnet50.py --trace
"""

import argparse
import json
import time

import numpy as np
import jax
import jax.numpy as jnp


def timeit(fn, fence, warmup=3, iters=20):
    for _ in range(warmup):
        out = fn()
    fence(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    fence(out)
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", action="store_true")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--ablate", action="store_true",
                    help="also time grad-only / fwd-only variants")
    args = ap.parse_args()

    from paddle_tpu import optim
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import resnet50
    from paddle_tpu.nn import costs
    from paddle_tpu.train import Trainer

    trainer = Trainer(
        model=resnet50(num_classes=1000),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.momentum(0.1, 0.9))
    rng = np.random.RandomState(0)
    host_batch = {
        "x": rng.normal(size=(args.batch, 224, 224, 3)).astype(np.float32),
        "label": rng.randint(0, 1000, size=args.batch).astype(np.int32),
    }
    results = {"batch": args.batch, "device": jax.devices()[0].device_kind}

    with use_policy(bfloat16_compute):
        trainer.init(jax.random.PRNGKey(0), host_batch)
        trainer._build_train_step()
        model, loss_fn = trainer.model, trainer.loss_fn
        ts = trainer.train_state
        batch = trainer._shard(host_batch)
        key = jax.random.PRNGKey(1)

        # --- full step (bench path, donation) --------------------------------
        def run_steps(n, p, st, os_, step):
            for _ in range(n):
                p, st, os_, step, loss, stats = trainer._train_step(
                    p, st, os_, step, batch, key)
            return p, st, os_, step, loss

        p, st, os_, step, loss = run_steps(
            3, ts.params, ts.state, ts.opt_state, ts.step)
        float(loss)
        t0 = time.perf_counter()
        p, st, os_, step, loss = run_steps(args.iters, p, st, os_, step)
        float(loss)
        results["full_step_ms"] = round(
            (time.perf_counter() - t0) / args.iters * 1e3, 2)

        if args.ablate:
            p0, st0 = ts.params, ts.state  # donated away? donate invalidates
            # Re-init small trees for the ablations (params were donated).
            trainer2 = Trainer(
                model=model,
                loss_fn=loss_fn,
                optimizer=optim.momentum(0.1, 0.9), donate=False)
            trainer2.init(jax.random.PRNGKey(0), host_batch)
            p2, st2 = trainer2.train_state.params, trainer2.train_state.state

            @jax.jit
            def grad_only(p, st, batch, rng):
                def compute_loss(pp):
                    out, new = model.apply(
                        {"params": pp, "state": st}, batch["x"], train=True,
                        mutable=("state",), rngs={"dropout": rng})
                    return jnp.mean(loss_fn(out, batch))
                loss, g = jax.value_and_grad(compute_loss)(p)
                return loss, g

            results["grad_only_ms"] = round(timeit(
                lambda: grad_only(p2, st2, batch, key),
                lambda o: float(o[0]), iters=args.iters), 2)

            @jax.jit
            def fwd_train(p, st, batch, rng):
                out, new = model.apply({"params": p, "state": st}, batch["x"],
                                       train=True, mutable=("state",),
                                       rngs={"dropout": rng})
                return jnp.mean(loss_fn(out, batch))
            results["fwd_train_ms"] = round(timeit(
                lambda: fwd_train(p2, st2, batch, key), lambda o: float(o),
                iters=args.iters), 2)

            @jax.jit
            def fwd_infer(p, st, batch):
                out = model.apply({"params": p, "state": st}, batch["x"])
                return jnp.mean(loss_fn(out, batch))
            results["fwd_infer_ms"] = round(timeit(
                lambda: fwd_infer(p2, st2, batch), lambda o: float(o),
                iters=args.iters), 2)

        # --- XLA cost analysis ------------------------------------------------
        try:
            lowered = trainer._train_step.lower(p, st, os_, step, batch, key)
            compiled = lowered.compile()
            ca = compiled.cost_analysis()
            if isinstance(ca, list):
                ca = ca[0]
            results["xla_flops"] = float(ca.get("flops", -1))
            results["xla_bytes_accessed"] = float(ca.get("bytes accessed", -1))
        except Exception as e:  # noqa
            results["cost_analysis_error"] = repr(e)

        # --- trace ------------------------------------------------------------
        if args.trace:
            tracedir = "experiments/trace_resnet50"
            with jax.profiler.trace(tracedir):
                p, st, os_, step, loss = run_steps(5, p, st, os_, step)
                float(loss)
            results["trace_dir"] = tracedir

    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()
