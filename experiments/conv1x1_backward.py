"""1x1-bottleneck conv backward: XLA conv path vs matmul form vs Pallas.

PERF.md (round 3) measured the ResNet-50 residual ceiling at XLA's conv
kernels: dW for [1,1,Cin,Cout] shapes at ~13% MXU, dx/BN-backward
mega-fusions at 5-11%. A 1x1 stride-1 conv IS a matmul
([B*H*W, Cin] @ [Cin, Cout]), and XLA's *matmul* path tiles these shapes
very differently from its conv path — so before hand-writing Pallas, this
experiment measures, per bottleneck shape of the bs128 step, the full
train-relevant cost (forward + dx + dW via jax.vjp) of:

  a. ``lax.conv_general_dilated`` (the shipped form);
  b. reshape + ``lax.dot_general`` (matmul form — its VJP is two matmuls);
  c. (when available) the Pallas dW kernel in
     ``paddle_tpu.nn.pallas_conv``.

Protocol: bf16 operands, fori_loop(K) chained inside ONE jit call so the
tunnel dispatch cost amortises; a single scalar fetch closes the timing
(the r4 no-fetch-inside-timing rule). Run on the real chip:
``python experiments/conv1x1_backward.py``.
"""

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

# the 1x1 convs of ResNet-50 bs128 @224 (NHWC): (H, Cin, Cout)
SHAPES = [
    (56, 64, 256),     # stage0 c3
    (56, 256, 64),     # stage0 c1 (later blocks)
    (28, 128, 512),    # stage1 c3
    (28, 512, 128),    # stage1 c1
    (14, 256, 1024),   # stage2 c3
    (14, 1024, 256),   # stage2 c1
    (7, 512, 2048),    # stage3 c3
    (7, 2048, 512),    # stage3 c1
]
B = 128
K = 200         # differential pair is (K, 3K) chained passes per jit call


def conv_form(x, w):
    return lax.conv_general_dilated(
        x, w.reshape(1, 1, w.shape[0], w.shape[1]),
        window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def matmul_form(x, w):
    b, h, ww, c = x.shape
    y = x.reshape(b * h * ww, c) @ w
    return y.reshape(b, h, ww, w.shape[1])


def timed(fn, x, w, dy):
    """ms per fwd+vjp pass, differential: time (dispatch + fetch) at K and
    3K chained passes inside one jit call each and difference — the ~1 s
    tunnel fetch/dispatch constant cancels (same rule as bench.py r4).

    NOTE: bench.py's run_timed_child is the CANONICAL implementation of
    the interleaved-differential protocol; protocol fixes land there
    first — keep this experiment copy in sync when touching either."""

    @partial(jax.jit, static_argnames=("k",))
    def run(x, w, dy, k):
        def body(i, carry):
            # EVERY product must be loop-variant or XLA hoists it: y
            # feeds dy (keeps the forward alive and dx varying — dx of a
            # linear op does not depend on x!), dx feeds x, dw feeds acc.
            acc, x, dy = carry
            y, vjp = jax.vjp(fn, x, w)
            dx, dw = vjp(dy)
            return (acc + jnp.sum(dw.astype(jnp.float32)),
                    x + 1e-12 * dx.astype(x.dtype),
                    dy + 1e-12 * y.astype(dy.dtype))
        acc, _, _ = lax.fori_loop(
            0, k, body, (jnp.zeros((), jnp.float32), x, dy))
        return acc

    def once(k):
        t0 = time.perf_counter()
        float(jax.device_get(run(x, w, dy, k)))
        return time.perf_counter() - t0

    for k in (K, 3 * K):
        run(x, w, dy, k).block_until_ready()   # compile both variants
    once(K)                                    # warm
    t1, t2 = once(K), once(3 * K)
    if t2 <= t1:
        return None        # drift swamped the signal: say so, don't clamp
    return (t2 - t1) / (2 * K) * 1e3


def main():
    rows = []
    forms = {"conv": conv_form, "matmul": matmul_form}
    try:
        from paddle_tpu.nn import pallas_conv
        forms["pallas"] = pallas_conv.conv1x1
    except (ImportError, AttributeError):
        pass
    for (h, cin, cout) in SHAPES:
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.normal(size=(B, h, h, cin)), jnp.bfloat16)
        w = jnp.asarray(rng.normal(size=(cin, cout)) * 0.05, jnp.bfloat16)
        dy = jnp.asarray(rng.normal(size=(B, h, h, cout)), jnp.bfloat16)
        row = {"shape": f"{h}x{h}x{cin}->{cout}"}
        flops = 3 * 2.0 * B * h * h * cin * cout      # fwd+dx+dW
        for name, fn in forms.items():
            ms = timed(fn, x, w, dy)
            if ms is None:             # degenerate differential (drift)
                row[name + "_ms"] = None
                row[name + "_mxu_pct"] = None
                continue
            row[name + "_ms"] = round(ms, 3)
            row[name + "_mxu_pct"] = round(
                100 * flops / (ms * 1e-3) / 197e12, 1)
        rows.append(row)
        print(json.dumps(row))
    tot = {f: (round(sum(r[f + "_ms"] for r in rows), 3)
               if all(r[f + "_ms"] is not None for r in rows) else None)
           for f in forms}
    print(json.dumps({"total_ms_per_step_equivalent": tot}))


if __name__ == "__main__":
    main()
