"""ResNet-50 step ablations: dispatch amortization (fori_loop) and batch size.

Compares wall-clock per train step for:
  - per-call dispatch (one jit call per step, chained donated state)
  - k steps per jit call via lax.fori_loop (amortizes the remote-tunnel
    dispatch overhead measured at ~5-6 ms/call)

Usage: PYTHONPATH=.:$PYTHONPATH python experiments/ablate_resnet.py
"""

import json
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)


def build(batch_size, stem="conv7", barrier=False):
    from paddle_tpu import optim
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.models import resnet50
    from paddle_tpu.nn import costs
    from paddle_tpu.optim.optimizers import apply_updates
    from paddle_tpu.train import Trainer

    if barrier:
        # experiment: stop XLA from fusing BN stat reductions into convs
        from paddle_tpu.models import resnet as resnet_mod

        def barrier_forward(self, x, train=False):
            y = jax.lax.optimization_barrier(self.conv(x))
            return self.act(self.bn(y, train=train))
        resnet_mod.ConvBN.forward = barrier_forward

    trainer = Trainer(
        model=resnet50(num_classes=1000, stem=stem),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.momentum(0.1, 0.9))
    rng = np.random.RandomState(0)
    host_batch = {
        "x": rng.normal(size=(batch_size, 224, 224, 3)).astype(np.float32),
        "label": rng.randint(0, 1000, size=batch_size).astype(np.int32),
    }
    with use_policy(bfloat16_compute):
        trainer.init(jax.random.PRNGKey(0), host_batch)
        trainer._build_train_step()

        model, loss_fn, opt = trainer.model, trainer.loss_fn, trainer.optimizer
        mesh = trainer.mesh

        def one_step(carry, batch, rng):
            params, state, opt_state, step = carry
            rngs = {"dropout": jax.random.fold_in(rng, step)}

            def compute_loss(p):
                out, new = model.apply({"params": p, "state": state},
                                       batch["x"], train=True,
                                       mutable=("state",), rngs=rngs)
                return jnp.mean(loss_fn(out, batch)), new["state"]

            (loss, new_state), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(params)
            updates, new_opt = opt.update(grads, opt_state, params, step)
            return (apply_updates(params, updates), new_state, new_opt,
                    step + 1), loss

        def multi(carry, batch, rng, k):
            def body(i, c_l):
                c, _ = c_l
                return one_step(c, batch, rng)
            return jax.lax.fori_loop(0, k, body, (carry, jnp.zeros(())))

        repl = NamedSharding(mesh, P())
        data = NamedSharding(mesh, P(mesh_lib.DATA_AXIS))
        multi_jit = jax.jit(
            multi,
            in_shardings=((repl,) * 4, data, repl),
            static_argnums=(3,), donate_argnums=(0,))
    return trainer, host_batch, multi_jit


def main():
    import argparse
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy

    ap = argparse.ArgumentParser()
    ap.add_argument("--stem", default="conv7")
    ap.add_argument("--barrier", action="store_true")
    ap.add_argument("--batches", default="128")
    args = ap.parse_args()

    out = {"stem": args.stem, "barrier": args.barrier}
    for bs in [int(b) for b in args.batches.split(",")]:
        trainer, host_batch, multi_jit = build(bs, stem=args.stem,
                                               barrier=args.barrier)
        ts = trainer.train_state
        batch = trainer._shard(host_batch)
        key = jax.random.PRNGKey(1)

        with use_policy(bfloat16_compute):
            # --- per-call ----------------------------------------------------
            p, st, os_, step = ts.params, ts.state, ts.opt_state, ts.step
            for _ in range(3):
                p, st, os_, step, loss, _ = trainer._train_step(
                    p, st, os_, step, batch, key)
            float(loss)
            t0 = time.perf_counter()
            for _ in range(20):
                p, st, os_, step, loss, _ = trainer._train_step(
                    p, st, os_, step, batch, key)
            float(loss)
            ms1 = (time.perf_counter() - t0) / 20 * 1e3
            out[f"bs{bs}_per_call_ms"] = round(ms1, 2)
            print("partial:", json.dumps(out), flush=True)

            # --- fori_loop k=10 ---------------------------------------------
            carry = (p, st, os_, step)
            k = 10
            carry, loss = multi_jit(carry, batch, key, k)   # compile+warm
            float(loss)
            t0 = time.perf_counter()
            for _ in range(4):
                carry, loss = multi_jit(carry, batch, key, k)
            float(loss)
            ms2 = (time.perf_counter() - t0) / (4 * k) * 1e3
            out[f"bs{bs}_fori10_ms"] = round(ms2, 2)
            out[f"bs{bs}_img_s_fori"] = round(bs / ms2 * 1e3, 1)
            out[f"bs{bs}_mfu_fori"] = round(
                bs / ms2 * 1e3 * 4.089e9 * 6 / 197e12 * 100, 1)
            print("partial:", json.dumps(out), flush=True)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
