"""Shared interleaved-differential timing for experiment scripts.

bench.py's ``run_timed_child`` is the CANONICAL implementation of the
protocol (warmup fence, degenerate-sample sentinel, fallback labelling) —
protocol fixes land there first. This module is the experiment-side
k-parameterized form so the profiling scripts stop carrying divergent
inline copies (code-review r5: conv1x1_backward / profile_transformer /
conv3x3_shapes each had one).
"""

import time

import jax
from jax import lax


def fence_state(state):
    """Block until the device work producing ``state`` is done (fetch one
    scalar — never fetch big buffers inside a timed region)."""
    float(jax.device_get(jax.tree_util.tree_leaves(state)[0].ravel()[0]))


def diff_time(make_body, state, k=8, reps=2, use_fori=False):
    """Interleaved differential of a state->state body: median ms/pass.

    Times regions of k and 3k passes back to back and reports
    ``(t_3k - t_k) / 2k`` — per-call dispatch and the closing fetch cancel.

    ``use_fori=False`` dispatches the jitted body k / 3k times per region
    (the proven bench-child pattern — the remote compile service
    reproducibly breaks on fori-wrapped FULL-model programs, while k=1
    programs and fori-wrapped small ops compile fine). Use
    ``use_fori=True`` only for cheap ops where the ~5 ms/call dispatch
    would swamp the signal."""
    if use_fori:
        stepc = jax.jit(lambda s: lax.fori_loop(
            0, k, lambda i, t: make_body(t), s), donate_argnums=0)
        stepc3 = jax.jit(lambda s: lax.fori_loop(
            0, 3 * k, lambda i, t: make_body(t), s), donate_argnums=0)

        def region(which, state):
            t0 = time.perf_counter()
            state = (stepc if which == 0 else stepc3)(state)
            fence_state(state)
            return time.perf_counter() - t0, state
    else:
        stepc1 = jax.jit(make_body, donate_argnums=0)

        def region(which, state):
            ncalls = k if which == 0 else 3 * k
            t0 = time.perf_counter()
            for _ in range(ncalls):
                state = stepc1(state)
            fence_state(state)
            return time.perf_counter() - t0, state

    _, state = region(0, state)          # compile + warm both variants
    _, state = region(1, state)
    fence_state(state)
    samples = []
    for _ in range(reps):
        ta, state = region(0, state)
        tb, state = region(1, state)
        samples.append((tb - ta) / (2 * k))
    return sorted(samples)[len(samples) // 2] * 1e3
