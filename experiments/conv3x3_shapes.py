"""3x3-conv campaign (VERDICT r4 #3): per-shape fwd/dx/dW cost + roofline.

The round-3 trace put the ResNet-50 bs128 step's 3x3 convs at 41-47% MXU
— never examined per shape. This experiment measures, for every 3x3 conv
of the bs128 step (and the 7x7 stem), the train-relevant triple
(forward + dx + dW via jax.vjp) under the interleaved-differential
protocol, and compares each against its compute/bandwidth ROOFLINE:
  t_floor = max(flops / bf16_peak, hbm_bytes / hbm_bw)
with hbm_bytes the compulsory traffic (x, w, y read+write once per pass
as touched by the fwd/dx/dW triple). measured/floor tells us whether a
hand kernel could exist; a ratio near 1 closes the door the way
conv1x1_backward.py closed the 1x1 one.

Run on the real chip:
  PYTHONPATH=/root/repo:/root/.axon_site python experiments/conv3x3_shapes.py
"""

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

B = 128
K = 60
PEAK = 197e12          # v5e bf16
HBM_BW = 819e9         # v5e HBM GB/s

# (H_in, Cin, Cout, kernel, stride, count_in_model) — ResNet-50 bs128,
# stride lives in the 3x3 (models/resnet.py Bottleneck.c2)
SHAPES = [
    (224, 3, 64, 7, 2, 1),        # stem
    (56, 64, 64, 3, 1, 3),        # stage0 c2
    (56, 128, 128, 3, 2, 1),      # stage1 first c2
    (28, 128, 128, 3, 1, 3),      # stage1 c2
    (28, 256, 256, 3, 2, 1),      # stage2 first c2
    (14, 256, 256, 3, 1, 5),      # stage2 c2
    (14, 512, 512, 3, 2, 1),      # stage3 first c2
    (7, 512, 512, 3, 1, 2),       # stage3 c2
]


def conv(x, w, stride):
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def timed(fn, x, w, dy):
    """ms per fwd+vjp pass, interleaved differential.

    NOTE: bench.py's run_timed_child is the CANONICAL implementation of
    this protocol (conv1x1_backward.py carries the same copy) — protocol
    fixes land there first; keep the experiment copies in sync."""

    @partial(jax.jit, static_argnames=("k",))
    def run(x, w, dy, k):
        def body(i, carry):
            acc, x, dy = carry
            y, vjp = jax.vjp(fn, x, w)
            dx, dw = vjp(dy)
            return (acc + jnp.sum(dw.astype(jnp.float32)),
                    x + 1e-12 * dx.astype(x.dtype),
                    dy + 1e-12 * y.astype(dy.dtype))
        acc, _, _ = lax.fori_loop(
            0, k, body, (jnp.zeros((), jnp.float32), x, dy))
        return acc

    for k in (K, 3 * K):
        run(x, w, dy, k).block_until_ready()

    def once(k):
        t0 = time.perf_counter()
        float(jax.device_get(run(x, w, dy, k)))
        return time.perf_counter() - t0

    once(K)
    t1, t2 = once(K), once(3 * K)
    if t2 <= t1:
        return None
    return (t2 - t1) / (2 * K) * 1e3


def main():
    rows = []
    for (h, cin, cout, kk, stride, count) in SHAPES:
        rng = np.random.RandomState(0)
        ho = h // stride
        x = jnp.asarray(rng.normal(size=(B, h, h, cin)), jnp.bfloat16)
        w = jnp.asarray(rng.normal(size=(kk, kk, cin, cout)) * 0.05,
                        jnp.bfloat16)
        dy = jnp.asarray(rng.normal(size=(B, ho, ho, cout)), jnp.bfloat16)
        fn = partial(conv, stride=stride)
        ms = timed(fn, x, w, dy)
        # fwd + dx + dW each do ~2*B*Ho*Wo*K*K*Cin*Cout FLOPs
        flops = 3 * 2.0 * B * ho * ho * kk * kk * cin * cout
        # compulsory HBM traffic over the triple (bf16=2B):
        #   fwd reads x,w writes y; dx reads dy,w writes dx(x-sized);
        #   dW reads x,dy writes dw  ->  3 x-sized + 3 y-sized + ~3 w
        bx = 2.0 * B * h * h * cin
        by = 2.0 * B * ho * ho * cout
        bw_ = 2.0 * kk * kk * cin * cout
        bytes_ = 3 * bx + 3 * by + 3 * bw_
        t_mxu = flops / PEAK * 1e3
        t_hbm = bytes_ / HBM_BW * 1e3
        floor = max(t_mxu, t_hbm)
        row = {"shape": f"{h}x{h}x{cin}->{cout} k{kk} s{stride}",
               "count": count,
               "ms": None if ms is None else round(ms, 3),
               "mxu_pct": None if ms is None else round(
                   100 * flops / (ms * 1e-3) / PEAK, 1),
               "floor_ms": round(floor, 3),
               "bound": "mxu" if t_mxu >= t_hbm else "hbm",
               "measured_over_floor": None if ms is None else round(
                   ms / floor, 2)}
        rows.append(row)
        print(json.dumps(row))
    ok = [r for r in rows if r["ms"] is not None]
    print(json.dumps({
        "total_step_ms": round(sum(r["ms"] * r["count"] for r in ok), 2),
        "total_floor_ms": round(
            sum(r["floor_ms"] * r["count"] for r in ok), 2),
        "device": jax.devices()[0].device_kind}))


if __name__ == "__main__":
    main()
