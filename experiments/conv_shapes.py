"""Standalone conv fwd/bwd efficiency at ResNet-50 shapes (v5e, bf16).

Separates "XLA convs are slow at these shapes" from "our fusion structure
hurts" — each conv is timed alone (fwd, and grad wrt both operands).
"""

import json
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

PEAK = 197e12

SHAPES = [
    # (N, H, W, Cin, KH, KW, Cout, stride)
    (128, 224, 224, 3, 7, 7, 64, 2),      # stem
    (128, 56, 56, 64, 1, 1, 256, 1),      # bottleneck expand
    (128, 56, 56, 256, 1, 1, 64, 1),      # bottleneck reduce
    (128, 56, 56, 64, 3, 3, 64, 1),       # bottleneck 3x3
    (128, 56, 56, 256, 1, 1, 512, 2),     # stage2 shortcut
    (128, 28, 28, 128, 3, 3, 128, 1),
    (128, 28, 28, 512, 1, 1, 128, 1),
    (128, 14, 14, 256, 3, 3, 256, 1),
    (128, 14, 14, 1024, 1, 1, 256, 1),
    (128, 7, 7, 512, 3, 3, 512, 1),
    (128, 7, 7, 2048, 1, 1, 512, 1),
]


def bench_one(n, h, w, cin, kh, kw, cout, stride, iters=30):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.normal(size=(n, h, w, cin)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(kh, kw, cin, cout)), jnp.bfloat16)

    def conv(x, k):
        return lax.conv_general_dilated(
            x, k, window_strides=(stride, stride), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    fwd = jax.jit(conv)

    @jax.jit
    def bwd(x, k):
        def f(x, k):
            return jnp.sum(conv(x, k).astype(jnp.float32))
        return jax.grad(f, argnums=(0, 1))(x, k)

    out = fwd(x, k)
    ho, wo = out.shape[1], out.shape[2]
    flops = 2 * n * ho * wo * kh * kw * cin * cout

    def timeit(fn, fence):
        o = fn()
        fence(o)
        t0 = time.perf_counter()
        for _ in range(iters):
            o = fn()
        fence(o)
        return (time.perf_counter() - t0) / iters

    # Fence via host transfer: on the remote-TPU plugin block_until_ready can
    # report buffers ready before execution completes (see bench.py).
    tf = timeit(lambda: fwd(x, k), lambda o: float(o[0, 0, 0, 0]))
    tb = timeit(lambda: bwd(x, k), lambda o: float(o[0][0, 0, 0, 0]))
    return flops, tf, tb


def main():
    print(f"{'shape':44s} {'fwd ms':>8s} {'fwd%':>6s} {'bwd ms':>8s} {'bwd%':>6s}")
    for s in SHAPES:
        flops, tf, tb = bench_one(*s)
        name = f"{s[0]}x{s[1]}x{s[2]}x{s[3]} k{s[4]}x{s[5]} -> {s[6]} s{s[7]}"
        print(f"{name:44s} {tf*1e3:8.3f} {flops/tf/PEAK*100:6.1f} "
              f"{tb*1e3:8.3f} {2*flops/tb/PEAK*100:6.1f}")


if __name__ == "__main__":
    main()
