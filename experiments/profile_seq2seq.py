"""Where do the seq2seq bench's ms go? (round-5 MFU campaign, VERDICT #2)

bench seq2seq (B=64, src=tgt=30, h=512, e=256, V=30k, bf16) measured
10.37 ms/step = 12.0% MFU in round 4 and had never been profiled. This
script ablates the exact bench step on the real chip: full step, grad-only,
forward-only, encoder / decoder-scan / readout in isolation, the bare
scan-iteration overhead floor, and the batched-GEMM floor of the same
FLOPs. Results + conclusions land in experiments/PERF.md "Round 5".

Usage: PYTHONPATH=/root/repo:/root/.axon_site python
       experiments/profile_seq2seq.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

B, TS, TT, H, E, V = 64, 30, 30, 512, 256, 30000
PEAK = 197e12
K = 20          # steps per timed call


def timeit(fn, state, reps=3):
    """Interleaved-differential per-step seconds: alternate fori_loop
    regions of K and 3K steps; (T_3K - T_K)/(2K) cancels the tunnel's
    per-dispatch constant (~2.5 ms/call here), which otherwise floors
    every ablation identically (bench.py's protocol, measured necessary
    in the first run of this script)."""
    stepk = jax.jit(lambda s: lax.fori_loop(0, K, lambda i, t: fn(t), s))
    step3k = jax.jit(lambda s: lax.fori_loop(0, 3 * K,
                                             lambda i, t: fn(t), s))

    def fence(s):
        # the tunnel's block_until_ready is unreliable; a real FETCH of the
        # scalar accumulator (every ablation carries it LAST, computed from
        # the FULL result so DCE cannot hollow the ablation out) is the
        # only trustworthy region close
        return float(np.asarray(
            jax.device_get(jax.tree_util.tree_leaves(s)[-1])))

    s = step3k(stepk(state))                      # compile both + warm
    fence(s)
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        s = stepk(s)
        fence(s)
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        s = step3k(s)
        fence(s)
        t3 = time.perf_counter() - t0
        samples.append((t3 - t1) / (2 * K))
    return sorted(samples)[len(samples) // 2]


def main():
    from paddle_tpu import optim
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import Seq2SeqAttention
    from paddle_tpu.nn import costs
    from paddle_tpu.optim.optimizers import apply_updates

    rng = np.random.RandomState(0)
    batch = {
        "src": jnp.asarray(rng.randint(3, V, (B, TS)), jnp.int32),
        "src_len": jnp.full((B,), TS, jnp.int32),
        "tgt": jnp.asarray(rng.randint(3, V, (B, TT + 1)), jnp.int32),
        "tgt_len": jnp.full((B,), TT, jnp.int32),
    }
    model = Seq2SeqAttention(V, V, emb_dim=E, hidden=H)
    results = {}
    with use_policy(bfloat16_compute):
        variables = model.init(jax.random.PRNGKey(0), batch)
        opt = optim.adam(1e-3)
        opt_state = opt.init(variables["params"])
        p0 = variables["params"]

        def loss_of(p):
            return jnp.sum(model.apply({"params": p}, batch,
                                       train=True)) / (B * TT)

        # Component ablations keep params CONSTANT, so the iteration input
        # must change or XLA hoists the whole body out of the fori_loop
        # (loop-invariant code motion — caught in this script's second
        # run: forward "took" 5 us). A batch-axis roll by the running
        # shift is cheap and defeats hoisting.
        def loss_rolled(p, shift):
            b2 = dict(batch,
                      src=jnp.roll(batch["src"], shift, 0),
                      tgt=jnp.roll(batch["tgt"], shift, 0))
            return jnp.sum(model.apply({"params": p}, b2,
                                       train=True)) / (B * TT)

        # 1. full train step
        def full(s):
            p, o, n, _ = s
            l, g = jax.value_and_grad(loss_of)(p)
            u, o2 = opt.update(g, o, p, n)
            return (apply_updates(p, u), o2, n + 1, l)
        results["full_step"] = timeit(
            full, (p0, opt_state, jnp.zeros((), jnp.int32),
                   jnp.zeros((), jnp.float32)))

        # 2. value_and_grad only (no optimizer) — the grads must feed the
        # accumulator or XLA dead-code-eliminates the whole backward
        def vg(s):
            sh, acc = s
            l, g = jax.value_and_grad(loss_rolled)(p0, sh)
            gsum = sum(jnp.sum(x.astype(jnp.float32))
                       for x in jax.tree_util.tree_leaves(g))
            return (sh + 1, acc + l + 1e-12 * gsum)
        results["value_and_grad"] = timeit(
            vg, (jnp.zeros((), jnp.int32), jnp.zeros(())))

        # 3. forward only
        def fwd(s):
            sh, acc = s
            return (sh + 1, acc + loss_rolled(p0, sh))
        results["forward"] = timeit(
            fwd, (jnp.zeros((), jnp.int32), jnp.zeros(())))

        # 4. encoder only (BiGRU + masks + boot)
        def enc_only(s):
            sh, acc = s
            enc, m, d0 = model.apply({"params": p0},
                                     jnp.roll(batch["src"], sh, 0),
                                     batch["src_len"], method="encode")
            return (sh + 1, acc + jnp.sum(enc.astype(jnp.float32))
                    + jnp.sum(d0.astype(jnp.float32)))
        results["encoder_fwd"] = timeit(
            enc_only, (jnp.zeros((), jnp.int32), jnp.zeros(())))

        # 5. readout GEMM alone at the hoisted shape [B*TT, H] @ [H, V]
        w = jnp.asarray(rng.normal(size=(H, V)).astype(np.float32) * 0.02,
                        jnp.bfloat16)
        xro = jnp.asarray(rng.normal(size=(B * TT, H)), jnp.bfloat16)

        def ro(s):
            x, acc = s
            y = x @ w
            # fold a hash of the output back into x: chains iterations
            # (x stays bf16 — the bench-shape dtype; an f32 x measured
            # the wrong GEMM in this script's first committed run)
            x2 = x + (jnp.sum(y.astype(jnp.float32)) * 1e-24).astype(x.dtype)
            return (x2, acc + jnp.sum(y.astype(jnp.float32)))
        results["readout_gemm_fwd"] = timeit(ro, (xro, jnp.zeros(())))

        # 6. bare scan-iteration floor: TT iterations, one [B,H]@[H,H]
        wloop = jnp.asarray(rng.normal(size=(H, H)).astype(np.float32) * 0.02,
                            jnp.bfloat16)

        def bare(s):
            h, acc = s

            def body(c, _):
                return jnp.tanh(c @ wloop), ()
            h2, _ = lax.scan(body, h, None, length=TT)
            return (h2, acc + jnp.sum(h2.astype(jnp.float32)))
        results["bare_scan_30x_512gemm"] = timeit(
            bare, (jnp.asarray(rng.normal(size=(B, H)), jnp.bfloat16),
                   jnp.zeros(())))

    from bench import seq2seq_train_flops
    flops = seq2seq_train_flops(B, TS, TT, E, H, V)
    out = {k: round(v * 1e3, 3) for k, v in results.items()}
    out["train_flops"] = flops
    out["mfu_pct_full"] = round(100 * flops / results["full_step"] / PEAK, 2)
    out["device"] = jax.devices()[0].device_kind
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
