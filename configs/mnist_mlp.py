"""MNIST MLP config script — the light_mnist acceptance config
(reference: ``v1_api_demo/mnist/light_mnist.py``: the canonical
config-script workflow with ``classification_cost`` + the
classification-error evaluator).

Run:  python -m paddle_tpu.train.cli --config configs/mnist_mlp.py
"""

import numpy as np

from paddle_tpu.config_helpers import (classification_cost, data_layer,
                                       fc_layer, outputs, settings)

settings(batch_size=64, learning_rate=0.05, optimizer="momentum",
         num_passes=2, evaluator="classification_error")

img = data_layer("image")
label = data_layer("label")
h = fc_layer(img, size=128, act="relu")
h = fc_layer(h, size=64, act="relu")
logits = fc_layer(h, size=10)
cost = classification_cost(logits, label)
# outputs[0] = the training cost; outputs[1] feeds the evaluator (the v1
# evaluator-layer attachment, here: classification error over the logits)
outputs(cost, logits, name="mnist_mlp")


def train_reader(batch_size, n_batches=24, seed=0):
    """Synthetic-MNIST provider (the dataprovider.py analog): the dataset
    module's labelled synthetic fallback, flattened to vectors."""
    from paddle_tpu.data import datasets

    base = datasets.mnist("train", synthetic_n=batch_size * n_batches)

    def reader():
        xs, ys = [], []
        for x, y in base():
            xs.append(np.asarray(x).reshape(-1))
            ys.append(y)
            if len(xs) == batch_size:
                yield {"image": np.stack(xs).astype(np.float32),
                       "label": np.asarray(ys, np.int32)}
                xs, ys = [], []
    return reader


def test_reader(batch_size, n_batches=4, seed=1):
    from paddle_tpu.data import datasets

    base = datasets.mnist("test", synthetic_n=batch_size * n_batches)

    def reader():
        xs, ys = [], []
        for x, y in base():
            xs.append(np.asarray(x).reshape(-1))
            ys.append(y)
            if len(xs) == batch_size:
                yield {"image": np.stack(xs).astype(np.float32),
                       "label": np.asarray(ys, np.int32)}
                xs, ys = [], []
    return reader
