"""SSD detection config script — the acceptance detection config from
``BASELINE.json`` (reference: the SSD config family over
``MultiBoxLossLayer`` / ``PriorBoxLayer`` / ``DetectionOutputLayer``).

A small conv backbone produces two feature scales; ``ssd_cost`` attaches
the multi-scale loc/conf heads, static priors, and the multibox training
loss (hard negative mining included).

Run:  python -m paddle_tpu.train.cli --config configs/ssd_detection.py
"""

import numpy as np

from paddle_tpu.config_helpers import (data_layer, img_conv_layer,
                                       img_pool_layer, outputs, settings,
                                       ssd_cost)

IMAGE = 32
NUM_CLASSES = 4      # background=0 + 3 object classes
MAX_BOXES = 3

settings(batch_size=16, learning_rate=1e-3, optimizer="adam", num_passes=2)

image = data_layer("image")
gt_box = data_layer("gt_box")
gt_label = data_layer("gt_label")

c1 = img_conv_layer(image, 3, 16, act="relu")
p1 = img_pool_layer(c1, 2)                      # 16x16
c2 = img_conv_layer(p1, 3, 32, act="relu")
f1 = img_pool_layer(c2, 2)                      # 8x8   — first SSD scale
c3 = img_conv_layer(f1, 3, 32, act="relu")
f2 = img_pool_layer(c3, 2)                      # 4x4   — second SSD scale

cost = ssd_cost([f1, f2], gt_box, gt_label, num_classes=NUM_CLASSES,
                feature_shapes=[(8, 8), (4, 4)], image_shape=(IMAGE, IMAGE),
                min_sizes=[8.0, 16.0], max_sizes=[16.0, 28.0])
outputs(cost, name="ssd_detection")


def train_reader(batch_size, n_batches=12, seed=0):
    """Synthetic boxes (the pascal-voc provider analog): each image has 1-3
    axis-aligned boxes with class = quadrant-derived label."""

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n_batches):
            img = rng.normal(size=(batch_size, IMAGE, IMAGE, 3))
            boxes = np.zeros((batch_size, MAX_BOXES, 4), np.float32)
            labels = np.full((batch_size, MAX_BOXES), -1, np.int64)
            for b in range(batch_size):
                k = rng.randint(1, MAX_BOXES + 1)
                for i in range(k):
                    x0, y0 = rng.uniform(0, 0.6, size=2)
                    w, h = rng.uniform(0.2, 0.4, size=2)
                    boxes[b, i] = [x0, y0, min(x0 + w, 1.0), min(y0 + h, 1.0)]
                    labels[b, i] = 1 + rng.randint(0, NUM_CLASSES - 1)
            yield {"image": img.astype(np.float32),
                   "gt_box": boxes,
                   "gt_label": labels.astype(np.int32)}
    return reader
