"""Sequence-tagging linear-CRF config script — the acceptance config from
``BASELINE.json`` (reference: ``v1_api_demo/sequence_tagging/linear_crf.py``:
sparse feature projections -> crf_layer, trained by paddle_trainer from the
config alone).

Run:  python -m paddle_tpu.train.cli --config configs/sequence_tagging_crf.py
"""

import numpy as np

from paddle_tpu.config_helpers import (crf_tagging_cost, data_layer,
                                       outputs, settings)

VOCAB = 200
NUM_TAGS = 5
SEQ_LEN = 16

settings(batch_size=32, learning_rate=0.2, optimizer="adagrad",
         num_passes=3)

tokens = data_layer("tokens")
length = data_layer("length")
label = data_layer("label")
cost = crf_tagging_cost(tokens, length, label, vocab=VOCAB,
                        num_tags=NUM_TAGS, context=2)
outputs(cost, name="sequence_tagging_crf")


def train_reader(batch_size, n_batches=20, seed=0):
    """Synthetic tagging stream (the dataprovider analog,
    ``sequence_tagging/dataprovider.py``): tag is a deterministic function
    of the token id — learnable by the linear CRF emissions."""

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n_batches):
            toks = rng.randint(0, VOCAB, size=(batch_size, SEQ_LEN))
            lens = rng.randint(4, SEQ_LEN + 1, size=batch_size)
            labs = toks % NUM_TAGS
            pos = np.arange(SEQ_LEN)[None, :]
            labs = np.where(pos < lens[:, None], labs, -1)
            yield {"tokens": toks.astype(np.int32),
                   "length": lens.astype(np.int32),
                   "label": labs.astype(np.int32)}
    return reader
