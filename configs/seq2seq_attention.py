"""Attention seq2seq config script — the acceptance NMT config from
``BASELINE.json`` (reference: the seqToseq demo over
``trainer_config_helpers/networks.py:1320`` ``simple_attention``).

Run:  python -m paddle_tpu.train.cli --config configs/seq2seq_attention.py
"""

import numpy as np

from paddle_tpu.config_helpers import (data_layer, outputs, settings,
                                       simple_attention_seq2seq)

VOCAB = 120          # ids 0=pad, 1=bos, 2=eos, 3.. tokens
SRC_LEN = 12
TGT_LEN = 12

settings(batch_size=32, learning_rate=1e-3, optimizer="adam", num_passes=2)

src = data_layer("src")
src_len = data_layer("src_len")
tgt = data_layer("tgt")
tgt_len = data_layer("tgt_len")
cost = simple_attention_seq2seq(src, src_len, tgt, tgt_len,
                                src_vocab=VOCAB, tgt_vocab=VOCAB,
                                emb_dim=32, hidden=64)
outputs(cost, name="seq2seq_attention")


def train_reader(batch_size, n_batches=16, seed=0):
    """Synthetic copy task (the wmt14 dataprovider analog): target = bos +
    source — learnable by the attention decoder."""

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n_batches):
            lens = rng.randint(4, SRC_LEN + 1, size=batch_size)
            src = rng.randint(3, VOCAB, size=(batch_size, SRC_LEN))
            pos = np.arange(SRC_LEN)[None, :]
            src = np.where(pos < lens[:, None], src, 0)
            tgt = np.zeros((batch_size, TGT_LEN + 1), np.int64)
            tgt[:, 0] = 1                                  # bos
            tgt[:, 1:] = src[:, :TGT_LEN]
            yield {"src": src.astype(np.int32),
                   "src_len": lens.astype(np.int32),
                   "tgt": tgt.astype(np.int32),
                   "tgt_len": (lens + 1).astype(np.int32)}
    return reader
