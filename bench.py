"""Benchmark harness — prints ONE JSON line for the driver.

Flagship metric: ResNet-50 training throughput (images/sec/chip), the
reference's own north-star workload (``/root/reference/benchmark/paddle/image/
resnet.py`` + ``run.sh`` protocol: fixed batch, warmup, timed batches). Runs
NHWC bfloat16-compute (the TPU MXU path) on device-resident synthetic
224x224 data, reporting img/s, ms/step and an MFU estimate. ``vs_baseline``
is the honest same-model ratio against the reference's strongest published
ResNet-50 figure: 82.35 img/s bs128 on 2xXeon 6148 (BASELINE.md; the
reference publishes no ResNet-50 GPU number).
"""

import json
import time

import numpy as np
import jax

# Reference's published ResNet-50 bs128 throughput (BASELINE.md:21).
BASELINE_RESNET50_IMG_S = 82.35

# Forward multiply-accumulates for ResNet-50 at 224x224 (the standard 4.09
# GMACs figure); x2 for mul+add, x3 for forward + backward.
RESNET50_TRAIN_FLOPS_PER_IMAGE = 4.089e9 * 2 * 3

# Peak dense bf16 FLOP/s per chip by device kind (public spec sheets).
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v3": 123e12,
    "TPU v2": 46e12,
}



def _time_trainer_steps(trainer, batch, warmup, iters):
    """Shared harness: init'd Trainer + host batch -> (seconds/iter, loss,
    n_devices). Fences via host transfer of the loss (on the remote-TPU
    plugin block_until_ready can report buffers ready before execution
    completes, which would time dispatch instead of compute)."""
    trainer._build_train_step()
    ts = trainer.train_state
    sharded = trainer._shard(batch)       # device-resident for all iters
    key = jax.random.PRNGKey(1)
    params, state, opt_state, step = (ts.params, ts.state, ts.opt_state,
                                      ts.step)
    for _ in range(warmup):
        params, state, opt_state, step, loss, stats = trainer._train_step(
            params, state, opt_state, step, sharded, key)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, state, opt_state, step, loss, stats = trainer._train_step(
            params, state, opt_state, step, sharded, key)
    loss = float(loss)
    dt = (time.perf_counter() - t0) / iters
    return dt, loss, int(trainer.mesh.devices.size)

def bench_resnet50(batch_size=128, warmup=3, iters=20):
    from paddle_tpu import optim
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import resnet50
    from paddle_tpu.nn import costs
    from paddle_tpu.train import Trainer

    trainer = Trainer(
        model=resnet50(num_classes=1000),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.momentum(0.1, 0.9))
    rng = np.random.RandomState(0)
    batch = {
        "x": rng.normal(size=(batch_size, 224, 224, 3)).astype(np.float32),
        "label": rng.randint(0, 1000, size=batch_size).astype(np.int32),
    }
    with use_policy(bfloat16_compute):
        trainer.init(jax.random.PRNGKey(0), batch)
        dt, loss, n_dev = _time_trainer_steps(trainer, batch, warmup, iters)
    # The default mesh spans every visible device (batch sharded over the
    # data axis), so normalize whole-mesh throughput to per-chip.
    img_s = batch_size / dt / n_dev
    ms_step = dt * 1e3
    peak = PEAK_FLOPS.get(jax.devices()[0].device_kind)
    mfu = (img_s * RESNET50_TRAIN_FLOPS_PER_IMAGE / peak) if peak else None
    return img_s, ms_step, mfu, loss


def bench_lstm(batch_size=64, seq_len=100, hidden=512, vocab=30000,
               warmup=3, iters=20):
    """LSTM text classification (2 x lstm + fc) — the reference's RNN
    benchmark protocol (``benchmark/paddle/rnn/rnn.py``; published anchor:
    184 ms/batch at bs64 h512 seq100 vocab30k on 1xK40m, BASELINE.md)."""
    import jax.numpy as jnp
    from paddle_tpu import optim
    from paddle_tpu.core.module import Module
    from paddle_tpu.nn import costs
    from paddle_tpu.nn.layers import Embedding, Linear
    from paddle_tpu.nn.recurrent import LSTMCell, RNN
    from paddle_tpu.train import Trainer

    class TextLstm(Module):
        def __init__(self):
            super().__init__()
            self.emb = Embedding(vocab, hidden)
            self.l1 = RNN(LSTMCell(hidden))
            self.l2 = RNN(LSTMCell(hidden))
            self.fc = Linear(2)

        def forward(self, ids, train: bool = False):
            h = self.emb(ids)
            h, _ = self.l1(h)
            h, _ = self.l2(h)
            return self.fc(h[:, -1])

    trainer = Trainer(
        model=TextLstm(),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.adam(1e-3))
    rng = np.random.RandomState(0)
    batch = {"x": rng.randint(0, vocab, (batch_size, seq_len)).astype(np.int32),
             "label": rng.randint(0, 2, batch_size).astype(np.int32)}
    trainer.init(jax.random.PRNGKey(0), batch)
    dt, loss, n_dev = _time_trainer_steps(trainer, batch, warmup, iters)
    return dt * 1e3, loss, n_dev


# Reference's published LSTM text-cls figure for this exact config
# (bs64, h512, seq100, vocab30k): 184 ms/batch on 1xK40m (BASELINE.md).
BASELINE_LSTM_MS = 184.0


def bench_transformer(batch_size=8, seq_len=2048, dim=512, layers=6,
                      heads=8, vocab=32000, warmup=1, iters=10):
    """Long-context transformer LM training tokens/s through the Pallas
    flash-attention path (no reference anchor — the 2017 reference predates
    transformers; this measures the framework's modern flagship)."""
    import jax.numpy as jnp
    from paddle_tpu import optim
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn import costs
    from paddle_tpu.optim.optimizers import apply_updates

    model = TransformerLM(vocab=vocab, dim=dim, num_layers=layers,
                          num_heads=heads, ffn_hidden=4 * dim,
                          max_len=seq_len, use_flash=True)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, vocab, (batch_size, seq_len + 1)),
                      jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids[:, :-1])
    opt = optim.adam(1e-4)
    opt_state = opt.init(variables["params"])

    @jax.jit
    def step(p, opt_state, sno, inp, tgt):
        def loss_fn(p):
            logits = model.apply({"params": p}, inp)
            return jnp.mean(costs.softmax_cross_entropy(
                logits.reshape(-1, vocab), tgt.reshape(-1)))
        loss, g = jax.value_and_grad(loss_fn)(p)
        updates, opt_state = opt.update(g, opt_state, p, sno)
        return loss, apply_updates(p, updates), opt_state

    p = variables["params"]
    inp, tgt = ids[:, :-1], ids[:, 1:]
    sno = 0
    for _ in range(max(1, warmup)):    # >=1: the fence below needs a loss
        loss, p, opt_state = step(p, opt_state, jnp.asarray(sno), inp, tgt)
        sno += 1
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, p, opt_state = step(p, opt_state, jnp.asarray(sno), inp, tgt)
        sno += 1
    loss = float(loss)
    dt = time.perf_counter() - t0
    cfg = {"seq_len": seq_len, "dim": dim, "layers": layers,
           "batch_size": batch_size}
    return batch_size * seq_len * iters / dt, dt / iters * 1e3, loss, cfg


def bench_seq2seq(batch_size=64, src_len=30, tgt_len=30, vocab=30000,
                  hidden=512, warmup=3, iters=20):
    """Attention seq2seq training tokens/s. The reference never published a
    seq2seq number ("will be added later", benchmark/README.md Seq2Seq
    section) so there is no vs_baseline anchor — this measures the
    simple_attention-equivalent model (models/seq2seq.py)."""
    import jax.numpy as jnp
    from paddle_tpu import optim
    from paddle_tpu.models import Seq2SeqAttention
    from paddle_tpu.optim.optimizers import apply_updates

    model = Seq2SeqAttention(vocab, vocab, emb_dim=hidden // 2, hidden=hidden)
    rng = np.random.RandomState(0)
    batch = {
        "src": jnp.asarray(rng.randint(3, vocab, (batch_size, src_len)),
                           jnp.int32),
        "src_len": jnp.full((batch_size,), src_len, jnp.int32),
        "tgt": jnp.asarray(rng.randint(3, vocab, (batch_size, tgt_len + 1)),
                           jnp.int32),
        "tgt_len": jnp.full((batch_size,), tgt_len, jnp.int32),
    }
    variables = model.init(jax.random.PRNGKey(0), batch)
    opt = optim.adam(1e-3)
    opt_state = opt.init(variables["params"])

    @jax.jit
    def step(p, opt_state, sno, batch):
        def loss_fn(p):
            return jnp.mean(model.apply({"params": p}, batch, train=True))
        loss, g = jax.value_and_grad(loss_fn)(p)
        updates, opt_state = opt.update(g, opt_state, p, sno)
        return loss, apply_updates(p, updates), opt_state

    p = variables["params"]
    sno = 0
    for _ in range(warmup):
        loss, p, opt_state = step(p, opt_state, jnp.asarray(sno), batch)
        sno += 1
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, p, opt_state = step(p, opt_state, jnp.asarray(sno), batch)
        sno += 1
    loss = float(loss)
    dt = time.perf_counter() - t0
    tokens = batch_size * (src_len + tgt_len)
    return tokens * iters / dt, dt / iters * 1e3, loss


def main():
    import dataclasses
    import sys
    from paddle_tpu.utils.flags import TrainerFlags, parse_flags

    @dataclasses.dataclass
    class BenchFlags(TrainerFlags):
        batch_size: int = 128
        warmup: int = 3
        iters: int = 20
        metric: str = "resnet50"      # resnet50 | lstm | seq2seq | transformer

    flags = parse_flags(BenchFlags, sys.argv[1:])
    if flags.metric == "transformer":
        tok_s, ms, loss, cfg = bench_transformer(warmup=flags.warmup,
                                                 iters=flags.iters)
        print(json.dumps({
            "metric": "transformer_lm_flash_train_tokens_per_sec",
            "value": round(tok_s, 1),
            "unit": "tokens/sec",
            "vs_baseline": None,   # the 2017 reference predates transformers
            "ms_per_step": round(ms, 2),
            **cfg,
            "device": jax.devices()[0].device_kind,
            "final_loss": round(loss, 4),
        }))
        return
    if flags.metric == "seq2seq":
        tok_s, ms, loss = bench_seq2seq(warmup=flags.warmup,
                                        iters=flags.iters)
        print(json.dumps({
            "metric": "seq2seq_attn_train_tokens_per_sec",
            "value": round(tok_s, 1),
            "unit": "tokens/sec",
            "vs_baseline": None,     # the reference published no seq2seq number
            "ms_per_step": round(ms, 2),
            "device": jax.devices()[0].device_kind,
            "final_loss": round(loss, 4),
        }))
        return
    if flags.metric == "lstm":
        ms, loss, n_dev = bench_lstm(warmup=flags.warmup, iters=flags.iters)
        print(json.dumps({
            "metric": "lstm_textcls_ms_per_batch",
            "value": round(ms, 2),
            "unit": "ms/batch",
            "vs_baseline": round(BASELINE_LSTM_MS / ms, 2),
            "n_devices": n_dev,
            "batch_size": 64, "hidden": 512, "seq_len": 100,
            "device": jax.devices()[0].device_kind,
            "final_loss": round(loss, 4),
        }))
        return
    batch_size = flags.batch_size
    img_s, ms_step, mfu, loss = bench_resnet50(
        batch_size=batch_size, warmup=flags.warmup, iters=flags.iters)
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / BASELINE_RESNET50_IMG_S, 2),
        "batch_size": batch_size,
        "ms_per_step": round(ms_step, 2),
        "mfu_pct": round(100 * mfu, 2) if mfu is not None else None,
        "device": jax.devices()[0].device_kind,
        "final_loss": round(loss, 4),
    }))


if __name__ == "__main__":
    main()
