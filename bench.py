"""Benchmark harness — prints ONE JSON line for the driver.

Flagship metric: ResNet-50 training throughput (images/sec/chip), the
reference's own north-star workload (``/root/reference/benchmark/paddle/image/
resnet.py`` + ``run.sh`` protocol: fixed batch, warmup, timed batches). Runs
NHWC bfloat16-compute (the TPU MXU path) on device-resident synthetic
224x224 data, reporting img/s, ms/step and an MFU estimate. ``vs_baseline``
is the honest same-model ratio against the reference's strongest published
ResNet-50 figure: 82.35 img/s bs128 on 2xXeon 6148 (BASELINE.md; the
reference publishes no ResNet-50 GPU number).
"""

import json
import time

import numpy as np
import jax

# Reference's published ResNet-50 bs128 throughput (BASELINE.md:21).
BASELINE_RESNET50_IMG_S = 82.35

# Forward multiply-accumulates for ResNet-50 at 224x224 (the standard 4.09
# GMACs figure); x2 for mul+add, x3 for forward + backward.
RESNET50_TRAIN_FLOPS_PER_IMAGE = 4.089e9 * 2 * 3

# Peak dense bf16 FLOP/s per chip by device kind (public spec sheets).
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v3": 123e12,
    "TPU v2": 46e12,
}


def bench_resnet50(batch_size=128, warmup=3, iters=20):
    from paddle_tpu import optim
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import resnet50
    from paddle_tpu.nn import costs
    from paddle_tpu.train import Trainer

    trainer = Trainer(
        model=resnet50(num_classes=1000),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.momentum(0.1, 0.9))
    rng = np.random.RandomState(0)
    batch = {
        "x": rng.normal(size=(batch_size, 224, 224, 3)).astype(np.float32),
        "label": rng.randint(0, 1000, size=batch_size).astype(np.int32),
    }
    with use_policy(bfloat16_compute):
        trainer.init(jax.random.PRNGKey(0), batch)
        trainer._build_train_step()
        ts = trainer.train_state
        sharded = trainer._shard(batch)       # device-resident for all iters
        key = jax.random.PRNGKey(1)
        params, state, opt_state, step = (ts.params, ts.state, ts.opt_state,
                                          ts.step)
        for _ in range(warmup):
            params, state, opt_state, step, loss, stats = trainer._train_step(
                params, state, opt_state, step, sharded, key)
        # Fence via host transfer of a value at the end of the dependency
        # chain: on the remote-TPU plugin block_until_ready can report
        # buffers ready before execution completes, which would time dispatch
        # instead of compute.
        float(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            params, state, opt_state, step, loss, stats = trainer._train_step(
                params, state, opt_state, step, sharded, key)
        loss = float(loss)
    dt = time.perf_counter() - t0
    # The default mesh spans every visible device (batch sharded over the
    # data axis), so normalize whole-mesh throughput to per-chip.
    n_dev = int(trainer.mesh.devices.size)
    img_s = batch_size * iters / dt / n_dev
    ms_step = dt / iters * 1e3
    peak = PEAK_FLOPS.get(jax.devices()[0].device_kind)
    mfu = (img_s * RESNET50_TRAIN_FLOPS_PER_IMAGE / peak) if peak else None
    return img_s, ms_step, mfu, loss


def main():
    import dataclasses
    import sys
    from paddle_tpu.utils.flags import TrainerFlags, parse_flags

    @dataclasses.dataclass
    class BenchFlags(TrainerFlags):
        batch_size: int = 128
        warmup: int = 3
        iters: int = 20

    flags = parse_flags(BenchFlags, sys.argv[1:])
    batch_size = flags.batch_size
    img_s, ms_step, mfu, loss = bench_resnet50(
        batch_size=batch_size, warmup=flags.warmup, iters=flags.iters)
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / BASELINE_RESNET50_IMG_S, 2),
        "batch_size": batch_size,
        "ms_per_step": round(ms_step, 2),
        "mfu_pct": round(100 * mfu, 2) if mfu is not None else None,
        "device": jax.devices()[0].device_kind,
        "final_loss": round(loss, 4),
    }))


if __name__ == "__main__":
    main()
