"""Benchmark harness — prints ONE JSON line for the driver.

Default mode runs EVERY north-star metric (`BASELINE.json`) in one process
and prints a single JSON object: ResNet-50 img/s/chip (the headline fields,
for driver continuity), seq2seq-attention tokens/s, long-context transformer
tokens/s, LSTM text-classification ms/batch, and a scaling-efficiency
probe — all under the bf16 compute policy (the TPU MXU path).

Protocols mirror the reference's own benchmarks: fixed batch, warmup, timed
steps (``/root/reference/benchmark/paddle/image/run.sh``; RNN grid
``benchmark/paddle/rnn/rnn.py``; the seq2seq section the reference left
"will be added later" is measured here). ``vs_baseline`` is the honest
same-model ratio against the reference's strongest published number where
one exists (BASELINE.md).

Timing fences ride a host transfer of the loss: on the remote-TPU plugin
``block_until_ready`` can report buffers ready before execution completes.
Steps are dispatched ``steps_per_call`` at a time through ``lax.fori_loop``
(measured ~5 ms/call dispatch overhead through the remote tunnel;
amortising it is part of the framework's own trainer design space, not a
bench trick — real training loops batch dispatch the same way).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

# NOTE: do NOT enable jax's persistent compilation cache here — executables
# deserialized from the cache hang at execution time under the remote-TPU
# (axon) plugin (observed round 3: cache-hit runs block forever in
# device_get while fresh compiles of the same HLO run fine).

# Reference's published numbers (BASELINE.md).
BASELINE_RESNET50_IMG_S = 82.35     # ResNet-50 bs128, 2xXeon 6148 MKL-DNN
BASELINE_LSTM_MS = 184.0            # LSTM text-cls bs64 h512 seq100, 1xK40m

# Forward multiply-accumulates for ResNet-50 at 224x224 (the standard 4.09
# GMACs figure); x2 for mul+add, x3 for forward + backward.
RESNET50_TRAIN_FLOPS_PER_IMAGE = 4.089e9 * 2 * 3

# Peak dense bf16 FLOP/s per chip by device kind (public spec sheets).
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v3": 123e12,
    "TPU v2": 46e12,
}


def _fence(x):
    return float(np.asarray(jax.device_get(x)).ravel()[0])


def _build_resnet_trainer(batch_size, model=None, image=224, classes=1000):
    from paddle_tpu import optim
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import resnet50
    from paddle_tpu.nn import costs
    from paddle_tpu.train import Trainer

    trainer = Trainer(
        model=model or resnet50(num_classes=classes),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.momentum(0.1, 0.9))
    rng = np.random.RandomState(0)
    batch = {
        "x": rng.normal(size=(batch_size, image, image, 3)).astype(np.float32),
        "label": rng.randint(0, classes, size=batch_size).astype(np.int32),
    }
    with use_policy(bfloat16_compute):
        trainer.init(jax.random.PRNGKey(0), batch)
    return trainer, batch


def _time_steps(trainer, batch, warmup, iters, mesh=None):
    """Chained per-call train steps (donated state; each step's inputs are
    the previous step's outputs, so dispatch pipelines). NOTE: a
    lax.fori_loop multi-step harness measured faster when first built
    (dispatch amortisation, experiments/PERF.md exp 2) but the remote-TPU
    tunnel later regressed to re-dispatching every loop iteration
    host-side (~35x slowdown on large carries, measured round 3) — the
    portable per-call protocol is the shipped harness."""
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    with use_policy(bfloat16_compute):
        trainer._build_train_step()
        ts = trainer.train_state
        sharded = trainer._shard(batch)
        key = jax.random.PRNGKey(1)
        params, state, opt_state, step = (ts.params, ts.state, ts.opt_state,
                                          ts.step)
        for _ in range(max(1, warmup)):
            params, state, opt_state, step, loss, _ = trainer._train_step(
                params, state, opt_state, step, sharded, key)
        _fence(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            params, state, opt_state, step, loss, _ = trainer._train_step(
                params, state, opt_state, step, sharded, key)
        loss = _fence(loss)
        dt = (time.perf_counter() - t0) / iters
    n_dev = int((mesh or trainer.mesh).devices.size)
    return dt, loss, n_dev


def bench_resnet50(batch_size=128, warmup=3, iters=20):
    """ResNet-50 NHWC bf16 training throughput (img/s/chip) — the flagship
    (``benchmark/paddle/image/resnet.py`` protocol)."""
    trainer, batch = _build_resnet_trainer(batch_size)
    dt, loss, n_dev = _time_steps(trainer, batch, warmup, iters)
    img_s = batch_size / dt / n_dev
    peak = PEAK_FLOPS.get(jax.devices()[0].device_kind)
    mfu = (img_s * RESNET50_TRAIN_FLOPS_PER_IMAGE / peak) if peak else None
    return {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / BASELINE_RESNET50_IMG_S, 2),
        "batch_size": batch_size,
        "ms_per_step": round(dt * 1e3, 2),
        "mfu_pct": round(100 * mfu, 2) if mfu is not None else None,
        "device": jax.devices()[0].device_kind,
        "final_loss": round(loss, 4),
    }


def bench_lstm(batch_size=64, seq_len=100, hidden=512, vocab=30000,
               warmup=3, iters=20):
    """LSTM text classification (2 x lstm + fc), bf16 compute — the
    reference's RNN protocol (``benchmark/paddle/rnn/rnn.py``; anchor 184
    ms/batch at bs64 h512 seq100 vocab30k on 1xK40m). Library model
    (:class:`paddle_tpu.models.LSTMTextClassifier`)."""
    from paddle_tpu import optim
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import LSTMTextClassifier
    from paddle_tpu.nn import costs
    from paddle_tpu.train import Trainer

    trainer = Trainer(
        model=LSTMTextClassifier(vocab, hidden),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.adam(1e-3))
    rng = np.random.RandomState(0)
    batch = {"x": rng.randint(0, vocab, (batch_size, seq_len)).astype(np.int32),
             "label": rng.randint(0, 2, batch_size).astype(np.int32)}
    with use_policy(bfloat16_compute):
        trainer.init(jax.random.PRNGKey(0), batch)
    dt, loss, n_dev = _time_steps(trainer, batch, 3, iters)
    ms = dt * 1e3
    return {
        "metric": "lstm_textcls_ms_per_batch",
        "value": round(ms, 2),
        "unit": "ms/batch",
        "vs_baseline": round(BASELINE_LSTM_MS / ms, 2),
        "n_devices": n_dev,
        "batch_size": batch_size, "hidden": hidden, "seq_len": seq_len,
        "device": jax.devices()[0].device_kind,
        "final_loss": round(loss, 4),
    }


def bench_transformer(batch_size=8, seq_len=2048, dim=512, layers=6,
                      heads=8, vocab=32000, warmup=1, iters=10):
    """Long-context transformer LM training tokens/s through the Pallas
    flash-attention path, bf16 compute (no reference anchor — the 2017
    reference predates transformers; this measures the framework's modern
    flagship)."""
    from paddle_tpu import optim
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn import costs
    from paddle_tpu.optim.optimizers import apply_updates

    model = TransformerLM(vocab=vocab, dim=dim, num_layers=layers,
                          num_heads=heads, ffn_hidden=4 * dim,
                          max_len=seq_len, use_flash=True)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, vocab, (batch_size, seq_len + 1)),
                      jnp.int32)
    with use_policy(bfloat16_compute):
        variables = model.init(jax.random.PRNGKey(0), ids[:, :-1])
        opt = optim.adam(1e-4)
        opt_state = opt.init(variables["params"])

        @jax.jit
        def step(p, opt_state, sno, inp, tgt):
            def loss_fn(p):
                logits = model.apply({"params": p}, inp)
                return jnp.mean(costs.softmax_cross_entropy(
                    logits.reshape(-1, vocab), tgt.reshape(-1)))
            loss, g = jax.value_and_grad(loss_fn)(p)
            updates, opt_state2 = opt.update(g, opt_state, p, sno)
            return loss, apply_updates(p, updates), opt_state2

        p = variables["params"]
        inp, tgt = ids[:, :-1], ids[:, 1:]
        sno = 0
        for _ in range(max(1, warmup)):
            loss, p, opt_state = step(p, opt_state, jnp.asarray(sno), inp, tgt)
            sno += 1
        _fence(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, p, opt_state = step(p, opt_state, jnp.asarray(sno), inp, tgt)
            sno += 1
        loss = _fence(loss)
        dt = time.perf_counter() - t0
    return {
        "metric": "transformer_lm_flash_train_tokens_per_sec",
        "value": round(batch_size * seq_len * iters / dt, 1),
        "unit": "tokens/sec",
        "vs_baseline": None,     # the 2017 reference predates transformers
        "ms_per_step": round(dt / iters * 1e3, 2),
        "seq_len": seq_len, "dim": dim, "layers": layers,
        "batch_size": batch_size,
        "device": jax.devices()[0].device_kind,
        "final_loss": round(loss, 4),
    }


def bench_seq2seq(batch_size=64, src_len=30, tgt_len=30, vocab=30000,
                  hidden=512, warmup=3, iters=20):
    """Attention seq2seq training tokens/s, bf16 compute. The reference
    never published a seq2seq number ("will be added later",
    benchmark/README.md Seq2Seq section) so there is no vs_baseline anchor —
    this measures the simple_attention-equivalent model
    (models/seq2seq.py)."""
    from paddle_tpu import optim
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import Seq2SeqAttention
    from paddle_tpu.optim.optimizers import apply_updates

    model = Seq2SeqAttention(vocab, vocab, emb_dim=hidden // 2, hidden=hidden)
    rng = np.random.RandomState(0)
    batch = {
        "src": jnp.asarray(rng.randint(3, vocab, (batch_size, src_len)),
                           jnp.int32),
        "src_len": jnp.full((batch_size,), src_len, jnp.int32),
        "tgt": jnp.asarray(rng.randint(3, vocab, (batch_size, tgt_len + 1)),
                           jnp.int32),
        "tgt_len": jnp.full((batch_size,), tgt_len, jnp.int32),
    }
    with use_policy(bfloat16_compute):
        variables = model.init(jax.random.PRNGKey(0), batch)
        opt = optim.adam(1e-3)
        opt_state = opt.init(variables["params"])

        @jax.jit
        def step(p, opt_state, sno, batch):
            def loss_fn(p):
                return jnp.mean(model.apply({"params": p}, batch, train=True))
            loss, g = jax.value_and_grad(loss_fn)(p)
            updates, opt_state2 = opt.update(g, opt_state, p, sno)
            return loss, apply_updates(p, updates), opt_state2

        p = variables["params"]
        sno = 0
        for _ in range(warmup):
            loss, p, opt_state = step(p, opt_state, jnp.asarray(sno), batch)
            sno += 1
        _fence(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, p, opt_state = step(p, opt_state, jnp.asarray(sno), batch)
            sno += 1
        loss = _fence(loss)
        dt = time.perf_counter() - t0
    tokens = batch_size * (src_len + tgt_len)
    return {
        "metric": "seq2seq_attn_train_tokens_per_sec",
        "value": round(tokens * iters / dt, 1),
        "unit": "tokens/sec",
        "vs_baseline": None,     # the reference published no seq2seq number
        "ms_per_step": round(dt / iters * 1e3, 2),
        "batch_size": batch_size, "hidden": hidden,
        "src_len": src_len, "tgt_len": tgt_len,
        "device": jax.devices()[0].device_kind,
        "final_loss": round(loss, 4),
    }


def bench_scaling(per_device_batch=32, iters=2, steps_per_call=4):
    """Throughput vs device count at fixed per-device batch — the third
    north-star metric (reference anchor: 3.85x at 4 GPUs,
    ``benchmark/README.md:70-93``).

    With one real chip (the normal driver environment) this re-launches
    itself on a virtual 8-device CPU mesh — a correctness/overhead proxy
    (virtual devices share host cores, so absolute efficiency is
    pessimistic), clearly labelled in ``environment``. On a real multi-chip
    slice it runs in place over ICI.
    """
    import paddle_tpu as pt
    from paddle_tpu.models import resnet_cifar

    devices = jax.devices()
    if len(devices) < 8:
        # re-launch on the virtual CPU mesh (env must be set pre-jax-import)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append("--xla_force_host_platform_device_count=8")
        env["XLA_FLAGS"] = " ".join(flags)
        repo = os.path.dirname(os.path.abspath(__file__))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        code = ("import jax; jax.config.update('jax_platforms','cpu'); "
                "import bench; import json; "
                f"print(json.dumps(bench.bench_scaling({per_device_batch},"
                f"{iters},{steps_per_call})))")
        res = subprocess.run([sys.executable, "-c", code], cwd=repo, env=env,
                             capture_output=True, text=True, timeout=1500)
        if res.returncode != 0:
            return {"metric": "scaling_efficiency",
                    "error": res.stderr[-2000:]}
        return json.loads(res.stdout.strip().splitlines()[-1])

    counts = [n for n in (1, 2, 4, 8) if n <= len(devices)]
    throughput = {}
    for n in counts:
        mesh = pt.make_mesh({"data": n}, devices=devices[:n])
        bs = per_device_batch * n
        trainer, batch = _build_resnet_trainer(
            bs, model=resnet_cifar(depth_n=2), image=32, classes=10)
        trainer.mesh = mesh
        dt, loss, _ = _time_steps(trainer, batch, 1,
                                  max(2, iters * steps_per_call // 2),
                                  mesh=mesh)
        throughput[n] = bs / dt
    base = throughput[counts[0]]
    eff = {str(n): round(throughput[n] / (n * base), 3) for n in counts}
    platform = jax.devices()[0].platform
    return {
        "metric": "scaling_efficiency",
        "value": eff[str(counts[-1])],
        "unit": f"fraction of linear at {counts[-1]} devices",
        "vs_baseline": round(
            (eff[str(4)] if "4" in eff else eff[str(counts[-1])]) /
            (3.85 / 4), 2),   # reference: 3.85x at 4 GPUs
        "throughput_img_s": {str(n): round(t, 1)
                             for n, t in throughput.items()},
        "efficiency_vs_linear": eff,
        "per_device_batch": per_device_batch,
        "model": "resnet_cifar(depth_n=2) bs/device=%d" % per_device_batch,
        "environment": ("real-%s-mesh" % platform) if platform == "tpu"
                       else "virtual-cpu-mesh (correctness/overhead proxy; "
                            "virtual devices share host cores)",
        "n_devices": counts[-1],
    }


def main():
    import dataclasses
    from paddle_tpu.utils.flags import TrainerFlags, parse_flags

    @dataclasses.dataclass
    class BenchFlags(TrainerFlags):
        batch_size: int = 128
        warmup: int = 1
        iters: int = 4
        # all | resnet50 | lstm | seq2seq | transformer | scaling
        metric: str = "all"

    flags = parse_flags(BenchFlags, sys.argv[1:])
    single = {
        "resnet50": lambda: bench_resnet50(batch_size=flags.batch_size,
                                           warmup=flags.warmup,
                                           iters=flags.iters),
        "lstm": bench_lstm,
        "seq2seq": bench_seq2seq,
        "transformer": bench_transformer,
        "scaling": bench_scaling,
    }
    if flags.metric in single:
        print(json.dumps(single[flags.metric]()))
        return

    # Default: every north-star metric, each in its OWN subprocess with a
    # hard timeout and one retry. Process isolation is deliberate: the
    # remote-TPU tunnel occasionally wedges mid-session (a blocked compile/
    # execute RPC never returns — observed round 3), and a fresh process =
    # a fresh tunnel connection; a hung sub-bench must not sink the rest.
    # Output: ONE JSON object, headline = the flagship ResNet-50 fields
    # (driver/judge continuity), `all_metrics` carrying everything.
    repo = os.path.dirname(os.path.abspath(__file__))
    results = {}
    errors = {}
    # The scaling probe is NOT in the default plan: with one real chip it
    # runs on the virtual-CPU mesh and its 4 CPU compiles cost ~20 min —
    # run it explicitly (`--metric scaling`); the committed artifact is
    # SCALING_r03.json.
    plan = [("resnet50", 2400), ("seq2seq", 1800), ("transformer", 2400),
            ("lstm", 1800)]
    for name, budget in plan:
        for attempt in (1, 2):
            # Own session per sub-bench: on timeout the WHOLE process group
            # dies (bench_scaling spawns a grandchild for the virtual-CPU
            # mesh; a plain subprocess timeout would orphan it, leaving it
            # burning host cores under later sub-benches).
            proc = subprocess.Popen(
                [sys.executable, os.path.join(repo, "bench.py"),
                 "--metric", name],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                cwd=repo, start_new_session=True)
            try:
                out_s, err_s = proc.communicate(timeout=budget)
                res = subprocess.CompletedProcess(proc.args, proc.returncode,
                                                  out_s, err_s)
            except subprocess.TimeoutExpired:
                import signal
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except OSError:
                    pass
                proc.wait()
                errors[name] = f"attempt {attempt}: timeout after {budget}s"
                continue
            if res.returncode == 0:
                try:
                    results[name] = json.loads(
                        res.stdout.strip().splitlines()[-1])
                    errors.pop(name, None)
                    break
                except (ValueError, IndexError):
                    errors[name] = (f"attempt {attempt}: unparseable output "
                                    f"{res.stdout[-300:]!r}")
            else:
                errors[name] = (f"attempt {attempt}: rc={res.returncode} "
                                f"{res.stderr[-400:]}")
    headline = results.get("resnet50", {})
    out = {**headline,
           "all_metrics": {r["metric"]: r for r in results.values()
                           if "metric" in r}}
    if errors:
        out["bench_errors"] = errors
    print(json.dumps(out))


if __name__ == "__main__":
    main()
