"""Benchmark harness — prints ONE JSON line for the driver.

Default mode runs every north-star metric (`BASELINE.json`) and prints a
single JSON object: ResNet-50 img/s/chip (the headline fields, for driver
continuity), seq2seq-attention tokens/s, long-context transformer tokens/s
(a latency-bound continuity point AND a compute-bound config), an LSTM
text-classification size sweep (hidden 256/512/1280, the reference's RNN
grid `benchmark/README.md` RNN section) — every training metric carries an
``mfu_pct`` computed from analytically counted model FLOPs.

Measurement protocol (round 4, degradation-proof):

The remote-TPU tunnel has two observed failure modes (experiments/PERF.md
"Incident"): (a) any device->host fetch can flip the session into a
non-resident mode where every later dispatch pays ~1 ms/MB of carried
state, and (b) ``block_until_ready`` does not actually fence on this
plugin (r3: it produced a physically impossible 352% MFU). Therefore:

1. **No device_get ever happens between warmup and the end of timing.**
   A timed region is: dispatch K jitted calls, then ONE final fetch of the
   scalar loss that closes it.
2. **Interleaved differential timing.** Within one fresh subprocess the
   metric alternates timed regions of N and 3N steps (each: dispatch-only
   calls + ONE closing fetch), ``reps`` times: per-step time =
   median over pairs of (T_3N - T_N) / (2N). The fetch/dispatch constant
   cancels pairwise, and the interleaving + median make the estimate
   robust to the tunnel's minute-scale transfer-latency drift (measured
   r4: the closing fetch of identical regions varied 12 s -> 40 s between
   sessions, which breaks a two-subprocess differential). If the median is
   degenerate (<= 0, pure noise) the harness falls back to the best
   absolute rate and labels the result ``protocol:
   "absolute-fallback-includes-fetch-constant"`` (the 3N-region wall time
   divided there includes the single closing fetch, whose constant can
   dominate in the degraded-tunnel mode that triggers this path).
3. **A health probe runs first** (own subprocess): small put/get
   round-trip, chained-jit residency on a 100 MB carried state before and
   after a scalar fetch, 100 MB download bandwidth. The verdict and raw
   measurements are in the output JSON under ``environment`` so a poisoned
   record is visibly poisoned.
4. Steps are optionally batched ``steps_per_call`` at a time through
   ``lax.fori_loop`` (amortises the ~5 ms/call tunnel dispatch,
   experiments/PERF.md exp 2 — in healthy mode the fastest protocol).

Protocols mirror the reference's own benchmarks: fixed batch, warmup,
timed steps (``/root/reference/benchmark/paddle/image/run.sh``; RNN grid
``benchmark/paddle/rnn/rnn.py``). ``vs_baseline`` is the honest same-model
ratio against the reference's strongest published number where one exists
(BASELINE.md).
"""

import json
import math
import os
import statistics
import subprocess
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

# NOTE: do NOT enable jax's persistent compilation cache here — executables
# deserialized from the cache hang at execution time under the remote-TPU
# (axon) plugin (observed round 3: cache-hit runs block forever in
# device_get while fresh compiles of the same HLO run fine).

# Reference's published numbers (BASELINE.md) — strongest in-tree anchor
# per model.
BASELINE_RESNET50_IMG_S = 82.35     # ResNet-50 bs128, 2xXeon 6148 MKL-DNN
BASELINE_LSTM_MS = 184.0            # LSTM text-cls bs64 h512 seq100, 1xK40m
BASELINE_LSTM_H256_MS = 83.0        # bs64 h256, 1xK40m (README RNN grid)
BASELINE_LSTM_H1280_BS128_MS = 1007.0   # bs128 h1280, 1xK40m
BASELINE_ALEXNET_IMG_S = 128 / 0.334    # 334 ms/batch bs128, 1xK40m
BASELINE_GOOGLENET_IMG_S = 264.83   # bs128, 2xXeon 6148 MKL-DNN
BASELINE_VGG19_IMG_S = 29.83        # bs128, 2xXeon 6148 MKL-DNN

# Forward multiply-accumulates for ResNet-50 at 224x224 (the standard 4.09
# GMACs figure); x2 for mul+add, x3 for forward + backward.
RESNET50_TRAIN_FLOPS_PER_IMAGE = 4.089e9 * 2 * 3

# Peak dense bf16 FLOP/s per chip by device kind (public spec sheets).
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v3": 123e12,
    "TPU v2": 46e12,
}


def _fence(x):
    return float(np.asarray(jax.device_get(x)).ravel()[0])


# ---------------------------------------------------------------------------
# analytic model FLOPs (training = 3x forward; mul+add = 2 FLOPs)
# ---------------------------------------------------------------------------

def transformer_train_flops(bs, seq, dim, layers, vocab, ffn):
    """Per-step FLOPs for a causal LM: matmul params (attn 4d^2, ffn 2*d*ffn
    per layer, tied head vocab*d) at 6 FLOPs/param/token + causal attention
    (QK^T and AV at ~2*seq*dim each fwd, halved by causality, x3 train)."""
    per_tok = (6.0 * (4 * dim * dim * layers + 2 * dim * ffn * layers
                      + vocab * dim)
               + 6.0 * layers * seq * dim)
    return per_tok * bs * seq


def lstm_textcls_train_flops(bs, seq, hidden, layers=2):
    """Per-step FLOPs: each LSTM layer's gate matmul [2h -> 4h] is 16h^2
    fwd per token; embedding lookup and the 2-class head are negligible."""
    return 3.0 * 16.0 * hidden * hidden * layers * bs * seq


def seq2seq_train_flops(bs, src_len, tgt_len, emb, hidden, vocab):
    """Per-step FLOPs for the GRU encoder-decoder with additive attention
    (models/seq2seq.py): BiGRU encoder 2x3 gates [e+h -> h] per src token,
    attention key projection [2h -> h] per src token, decoder GRU with
    [e+2h] input + query proj + additive scores + readout [h -> V] per tgt
    token."""
    h, e, V = hidden, emb, vocab
    enc = src_len * (12.0 * h * (e + h) + 4.0 * h * h)
    dec = tgt_len * (2.0 * h * h + 6.0 * src_len * h
                     + 6.0 * h * (e + 3 * h) + 2.0 * h * V)
    return 3.0 * bs * (enc + dec)


# ---------------------------------------------------------------------------
# metric preps: each returns (step_body, state0, meta).
# step_body: state -> state, pure, un-jitted (harness jits it, optionally
# wrapped in a steps_per_call fori_loop, with the state donated). state[-1]
# is the scalar loss that closes the timed region.
# ---------------------------------------------------------------------------

def _build_resnet_trainer(batch_size, model=None, image=224, classes=1000,
                          lr=0.1):
    from paddle_tpu import optim
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import resnet50
    from paddle_tpu.nn import costs
    from paddle_tpu.train import Trainer

    trainer = Trainer(
        model=model or resnet50(num_classes=classes),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.momentum(lr, 0.9))
    # Conflicting-pair construction (VERDICT r4 #4): each image appears
    # TWICE with two different labels, so the batch loss has an exact
    # irreducible floor of ln 2 (optimal prediction is 0.5/0.5 on the pair's
    # labels) that memorization cannot beat — final_loss is a real
    # convergence sentinel instead of the 0.0 a separable fixed batch decays
    # to.
    rng = np.random.RandomState(0)
    half = batch_size // 2
    x_u = rng.normal(size=(half, image, image, 3)).astype(np.float32)
    la = rng.randint(0, classes, size=half).astype(np.int32)
    # uniform over the OTHER classes: guaranteed lb != la
    lb = ((la + 1 + rng.randint(0, classes - 1, size=half))
          % classes).astype(np.int32)
    batch = {
        "x": np.concatenate([x_u, x_u], axis=0),
        "label": np.concatenate([la, lb]),
    }
    with use_policy(bfloat16_compute):
        trainer.init(jax.random.PRNGKey(0), batch)
    return trainer, batch


def _trainer_step_body(trainer, batch):
    """Adapt a Trainer's jitted step to the harness state protocol (the jit
    inlines when the harness re-jits around it)."""
    trainer._build_train_step()
    sharded = trainer._shard(batch)
    key = jax.random.PRNGKey(1)
    ts = trainer.train_state
    state0 = (ts.params, ts.state, ts.opt_state, ts.step,
              jnp.zeros((), jnp.float32))

    def step_body(s):
        params, st, opt, stepno, _ = s
        params, st, opt, stepno, loss, _ = trainer._train_step(
            params, st, opt, stepno, sharded, key)
        return (params, st, opt, stepno, loss)
    return step_body, state0


def prep_resnet50(batch_size=128, model_name="resnet50", image=224,
                  classes=1000):
    """The flagship (``benchmark/paddle/image/resnet.py`` protocol); also
    serves alexnet/googlenet/vgg16 from the image zoo (the reference's
    image grid, ``benchmark/paddle/image/``)."""
    model = None
    if model_name != "resnet50":
        from paddle_tpu.models import image_zoo
        model = {"alexnet": image_zoo.AlexNet,
                 "googlenet": image_zoo.GoogLeNet,
                 "vgg16": image_zoo.vgg16,
                 "vgg19": image_zoo.vgg19}[model_name](num_classes=classes)
    # alexnet/googlenet have no batchnorm: the resnet lr diverges on them
    lr = 0.01 if model_name in ("alexnet", "googlenet") else 0.1
    trainer, batch = _build_resnet_trainer(batch_size, model=model,
                                           image=image, classes=classes,
                                           lr=lr)
    step_body, state0 = _trainer_step_body(trainer, batch)
    flops = (RESNET50_TRAIN_FLOPS_PER_IMAGE * batch_size
             if model_name == "resnet50" else None)
    anchors = {"resnet50": BASELINE_RESNET50_IMG_S,
               "alexnet": BASELINE_ALEXNET_IMG_S,
               "googlenet": BASELINE_GOOGLENET_IMG_S,
               "vgg19": BASELINE_VGG19_IMG_S}
    meta = {
        "metric": f"{model_name}_train_images_per_sec_per_chip",
        "unit": "images/sec",
        "units_per_step": batch_size,
        "flops_per_step": flops,
        "batch_size": batch_size,
        # Trainer data-parallelizes over the default (all-device) mesh;
        # per-chip normalisation divides by this
        "n_devices": int(trainer.mesh.devices.size),
        "baseline": anchors.get(model_name),
        "baseline_kind": "higher",      # units/s: higher is better
        # every example is one arm of an identical-image conflicting pair
        "loss_floor": round(math.log(2.0), 4),
    }
    return step_body, state0, meta


def prep_lstm(batch_size=64, seq_len=100, hidden=512, vocab=30000):
    """LSTM text classification (2 x lstm + fc) — the reference's RNN
    protocol (``benchmark/paddle/rnn/rnn.py``; anchor 184 ms/batch at bs64
    h512 seq100 vocab30k on 1xK40m). The hidden-size sweep mirrors the
    reference's RNN grid (hidden 256->1280)."""
    from paddle_tpu import optim
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import LSTMTextClassifier
    from paddle_tpu.nn import costs
    from paddle_tpu.train import Trainer

    trainer = Trainer(
        model=LSTMTextClassifier(vocab, hidden),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.adam(1e-3))
    # Half the batch sits in conflicting identical-sequence pairs (labels 0
    # AND 1), half is free: exact loss floor 0.5*ln2, while a broken model
    # stays at the balanced-binary initial ~ln2 — the two are
    # distinguishable (VERDICT r4 #4).
    rng = np.random.RandomState(0)
    q = batch_size // 4
    x_u = rng.randint(0, vocab, (batch_size - q, seq_len)).astype(np.int32)
    lab_u = rng.randint(0, 2, batch_size - q).astype(np.int32)
    batch = {"x": np.concatenate([x_u, x_u[:q]], axis=0),
             "label": np.concatenate([lab_u, 1 - lab_u[:q]])}
    with use_policy(bfloat16_compute):
        trainer.init(jax.random.PRNGKey(0), batch)
    step_body, state0 = _trainer_step_body(trainer, batch)
    meta = {
        # the h512 anchor keeps its r1-r3 record key; sweep points suffix
        "metric": ("lstm_textcls_ms_per_batch" if hidden == 512
                   else f"lstm_textcls_h{hidden}_ms_per_batch"),
        "unit": "ms/batch",
        "units_per_step": batch_size,
        "flops_per_step": lstm_textcls_train_flops(batch_size, seq_len,
                                                   hidden),
        "batch_size": batch_size, "hidden": hidden, "seq_len": seq_len,
        "n_devices": int(trainer.mesh.devices.size),
        # same-config anchors from the reference's RNN grid (BASELINE.md)
        "baseline": {(512, 64): BASELINE_LSTM_MS,
                     (256, 64): BASELINE_LSTM_H256_MS,
                     (1280, 128): BASELINE_LSTM_H1280_BS128_MS,
                     }.get((hidden, batch_size)),
        "baseline_kind": "lower",       # ms/batch: lower is better
        # 2q of batch_size examples are conflicting pairs at ln2 each
        "loss_floor": round(2 * q / batch_size * math.log(2.0), 4),
    }
    return step_body, state0, meta


def prep_transformer(batch_size=8, seq_len=2048, dim=512, layers=6,
                     heads=4, vocab=32000):
    """Long-context transformer LM through the Pallas flash-attention path
    (no reference anchor — the 2017 reference predates transformers). The
    default dim-512 point is latency-bound (kept for record continuity);
    ``prep_transformer_big`` is the compute-bound config.

    Head geometry: dh=128 (d512 H4 / d1024 H8) as of round 5 — at dh=64
    both flash matmuls run half-width MXU tiles (contraction / output dim
    64 vs the 128x128 array): measured 14.49 -> 6.96 ms per d1024 layer
    fwd+bwd, full step 436 -> 339 ms (34.4 -> 44.2% MFU). Same dim/layers/
    FLOPs — heads never enter ``transformer_train_flops``; dh=128 is the
    TPU-canonical choice (pallas guide; PaLM/LLaMA-class models).
    PROF_HEADS=16 experiments/profile_transformer.py --only=dh128 (the probe needs the dh=64 start point), PERF.md r5."""
    from paddle_tpu import optim
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn import costs
    from paddle_tpu.optim.optimizers import apply_updates

    ffn = 4 * dim
    model = TransformerLM(vocab=vocab, dim=dim, num_layers=layers,
                          num_heads=heads, ffn_hidden=ffn,
                          max_len=seq_len, use_flash=True)
    # Decoupled input/target with conflicting pairs (VERDICT r4 #4): the
    # input rows come in identical pairs while the targets are independent
    # random rows, so at every position the causal model sees the same
    # prefix for both pair members and must split probability between two
    # targets — exact floor ln2 * P(targets differ), computed from the
    # arrays. A shifted-same-array LM task has near-zero achievable loss on
    # a fixed batch (memorization), which is what round 4 measured.
    rng = np.random.RandomState(0)
    half = batch_size // 2
    inp_u = rng.randint(0, vocab, (half, seq_len))
    inp = jnp.asarray(np.concatenate([inp_u, inp_u], axis=0), jnp.int32)
    tgt_np = rng.randint(0, vocab, (batch_size, seq_len))
    tgt = jnp.asarray(tgt_np, jnp.int32)
    conflict_frac = float(np.mean(tgt_np[:half] != tgt_np[half:]))
    loss_floor = round(conflict_frac * math.log(2.0), 4)
    with use_policy(bfloat16_compute):
        variables = model.init(jax.random.PRNGKey(0), inp)
        opt = optim.adam(1e-4)
        opt_state = opt.init(variables["params"])

    def loss_of(p):
        logits = model.apply({"params": p}, inp)
        return jnp.mean(costs.softmax_cross_entropy(
            logits.reshape(-1, vocab), tgt.reshape(-1)))

    def step_body(s):
        p, opt_state, sno, _ = s
        loss, g = jax.value_and_grad(loss_of)(p)
        updates, opt_state2 = opt.update(g, opt_state, p, sno)
        return (apply_updates(p, updates), opt_state2, sno + 1, loss)

    state0 = (variables["params"], opt_state, jnp.zeros((), jnp.int32),
              jnp.zeros((), jnp.float32))
    meta = {
        # the d512 point keeps its r1-r3 record key; other sizes suffix
        "metric": ("transformer_lm_flash_train_tokens_per_sec" if dim == 512
                   else f"transformer_lm_flash_d{dim}_train_tokens_per_sec"),
        "unit": "tokens/sec",
        "units_per_step": batch_size * seq_len,
        "flops_per_step": transformer_train_flops(batch_size, seq_len, dim,
                                                  layers, vocab, ffn),
        "seq_len": seq_len, "dim": dim, "layers": layers,
        "batch_size": batch_size,
        "n_devices": 1,      # raw jit step, single-device placement
        "baseline": None, "baseline_kind": "higher",
        "loss_floor": loss_floor,
    }
    return step_body, state0, meta


def prep_transformer_big(batch_size=16, seq_len=2048, dim=1024, layers=8,
                         heads=8, vocab=32000):
    """Compute-bound transformer config (VERDICT r3 item 3: dim >= 1024 at
    seq 2048, so the modern-flagship number measures the MXU, not dispatch
    latency)."""
    return prep_transformer(batch_size=batch_size, seq_len=seq_len, dim=dim,
                            layers=layers, heads=heads, vocab=vocab)


def prep_transformer_fused(batch_size=8, seq_len=2048, dim=512, layers=6,
                           heads=4, vocab=32000, k_steps=8, remat=None,
                           grad_sync=None, bucket_mb=4.0,
                           metric_tag="fused"):
    """Trainer-level fused dispatch (steps_per_call=K): ONE device call runs
    K optimizer steps as a donated lax.scan over K stacked batches. Against
    the same-shape `transformer` metric this is the fused-vs-plain
    per-step differential — it isolates the multi-step dispatch
    amortisation (the ~5 ms/call tunnel constant, experiments/PERF.md
    exp 2) from the compute, through the REAL Trainer pipeline rather than
    the harness's own fori_loop.

    ``remat``/``grad_sync``/``bucket_mb`` parameterize the same harness
    for the gradient-sync overlap metric (``prep_transformer_dp_overlap``)
    so the two preps cannot drift apart; ``metric_tag`` names the
    variant."""
    from paddle_tpu import optim
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn import costs
    from paddle_tpu.train import Trainer

    ffn = 4 * dim
    model = TransformerLM(vocab=vocab, dim=dim, num_layers=layers,
                          num_heads=heads, ffn_hidden=ffn,
                          max_len=seq_len, use_flash=True, remat=remat)
    # identical conflicting-pair task to prep_transformer (same floor)
    rng = np.random.RandomState(0)
    half = batch_size // 2
    inp_u = rng.randint(0, vocab, (half, seq_len))
    inp = np.concatenate([inp_u, inp_u], axis=0).astype(np.int32)
    tgt_np = rng.randint(0, vocab, (batch_size, seq_len)).astype(np.int32)
    conflict_frac = float(np.mean(tgt_np[:half] != tgt_np[half:]))
    host_batch = {"x": inp, "y": tgt_np}

    trainer = Trainer(
        model=model,
        loss_fn=lambda out, b: costs.softmax_cross_entropy(
            out.reshape(-1, vocab), b["y"].reshape(-1)),
        optimizer=optim.adam(1e-4), steps_per_call=k_steps,
        grad_sync=grad_sync, bucket_mb=bucket_mb)
    trainer.init(jax.random.PRNGKey(0), host_batch)
    fused_step, batches = trainer.compile_fused([host_batch] * k_steps)
    key = jax.random.PRNGKey(1)
    ts = trainer.train_state
    state0 = (ts.params, ts.state, ts.opt_state, ts.step,
              jnp.zeros((), jnp.float32))

    def step_body(s):
        params, st, opt_state, stepno, _ = s
        params, st, opt_state, stepno, losses, _ = fused_step(
            params, st, opt_state, stepno, batches, key)
        return (params, st, opt_state, stepno, losses[-1])

    meta = {
        "metric": f"transformer_lm_{metric_tag}_k{k_steps}"
                  f"_train_tokens_per_sec",
        "unit": "tokens/sec",
        # one step_body call = k_steps real optimizer steps
        "units_per_step": k_steps * batch_size * seq_len,
        "flops_per_step": k_steps * transformer_train_flops(
            batch_size, seq_len, dim, layers, vocab, ffn),
        "seq_len": seq_len, "dim": dim, "layers": layers,
        "batch_size": batch_size, "k_steps": k_steps,
        "n_devices": int(trainer.mesh.devices.size),
        "baseline": None, "baseline_kind": "higher",
        "loss_floor": round(conflict_frac * math.log(2.0), 4),
    }
    if remat is not None:
        meta["remat"] = remat
    if grad_sync is not None:
        meta["bucket_mb"] = bucket_mb
        meta["grad_sync_active"] = trainer._resolve_grad_sync()
    return step_body, state0, meta


def prep_transformer_dp_overlap(batch_size=8, seq_len=2048, dim=512,
                                layers=6, heads=4, vocab=32000, k_steps=8,
                                bucket_mb=4.0):
    """The bucketed gradient-sync overlap metric (ISSUE 8): the
    ``transformer_fused`` harness with ``Trainer(grad_sync="bucketed")``
    AND ``remat="dots"`` — explicit per-bucket grad all-reduces anchored
    inside the backward, with the per-layer in-scan sync engaged (the
    remat'd scan stack is what the in-scan path exists for, so the
    metric exercises it; the remat recompute delta vs the non-remat
    ``transformer_fused`` is therefore part of any cross-metric
    comparison — ``meta['remat']`` records it). On a single-device mesh
    grad_sync degrades (one warning) and the metric measures the
    implicit-sync remat'd baseline — ``meta['grad_sync_active']``
    records which program actually ran."""
    return prep_transformer_fused(
        batch_size=batch_size, seq_len=seq_len, dim=dim, layers=layers,
        heads=heads, vocab=vocab, k_steps=k_steps, remat="dots",
        grad_sync="bucketed", bucket_mb=bucket_mb,
        metric_tag="dp_overlap")


def prep_seq2seq(batch_size=64, src_len=30, tgt_len=30, vocab=30000,
                 hidden=512):
    """Attention seq2seq training tokens/s. The reference never published a
    seq2seq number ("will be added later", benchmark/README.md Seq2Seq
    section) so there is no vs_baseline anchor. ``final_loss`` is the mean
    per-TOKEN cross entropy (the model returns per-example masked sums)."""
    from paddle_tpu import optim
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import Seq2SeqAttention
    from paddle_tpu.optim.optimizers import apply_updates

    emb = hidden // 2
    model = Seq2SeqAttention(vocab, vocab, emb_dim=emb, hidden=hidden)
    # Conflicting pairs (VERDICT r4 #4): pair members share the SOURCE row
    # and the first target token, then diverge — the teacher-forced decoder
    # sees identical inputs up to the pair's first target divergence, where
    # it must split probability two ways (ln2 for that one token; later
    # positions see different forced inputs and are free). The floor is
    # computed exactly from the arrays under the loss's own mask.
    rng = np.random.RandomState(0)
    half = batch_size // 2
    src_u = rng.randint(3, vocab, (half, src_len))
    t0 = rng.randint(3, vocab, (half, 1))
    ta = np.concatenate([t0, rng.randint(3, vocab, (half, tgt_len))], axis=1)
    tb = np.concatenate([t0, rng.randint(3, vocab, (half, tgt_len))], axis=1)
    batch = {
        "src": jnp.asarray(np.concatenate([src_u, src_u]), jnp.int32),
        "src_len": jnp.full((batch_size,), src_len, jnp.int32),
        "tgt": jnp.asarray(np.concatenate([ta, tb]), jnp.int32),
        "tgt_len": jnp.full((batch_size,), tgt_len, jnp.int32),
    }
    n_out_tokens = batch_size * tgt_len
    # one conflicted output token per pair MEMBER at the first column where
    # ta != tb (output index = column - 1; both rows pay ln2 there since
    # they share the decoder's visible state), counted only if the loss
    # mask (length tgt_len - 1) covers it
    neq = ta != tb
    diverged = neq.any(axis=1)
    first_col = np.argmax(neq, axis=1)
    n_conflicts = 2 * int(np.sum(diverged & (first_col - 1 < tgt_len - 1)))
    loss_floor = round(n_conflicts * math.log(2.0) / n_out_tokens, 4)
    with use_policy(bfloat16_compute):
        variables = model.init(jax.random.PRNGKey(0), batch)
        opt = optim.adam(1e-3)
        opt_state = opt.init(variables["params"])

    def loss_of(p):
        # mean per-token CE: per-example masked sums / total target tokens
        return jnp.sum(model.apply({"params": p}, batch,
                                   train=True)) / n_out_tokens

    def step_body(s):
        p, opt_state, sno, _ = s
        loss, g = jax.value_and_grad(loss_of)(p)
        updates, opt_state2 = opt.update(g, opt_state, p, sno)
        return (apply_updates(p, updates), opt_state2, sno + 1, loss)

    state0 = (variables["params"], opt_state, jnp.zeros((), jnp.int32),
              jnp.zeros((), jnp.float32))
    meta = {
        "metric": "seq2seq_attn_train_tokens_per_sec",
        "unit": "tokens/sec",
        "units_per_step": batch_size * (src_len + tgt_len),
        "flops_per_step": seq2seq_train_flops(batch_size, src_len, tgt_len,
                                              emb, hidden, vocab),
        "batch_size": batch_size, "hidden": hidden,
        "src_len": src_len, "tgt_len": tgt_len,
        "n_devices": 1,      # raw jit step, single-device placement
        "baseline": None, "baseline_kind": "higher",
        "loss_floor": loss_floor,
    }
    return step_body, state0, meta


PREPS = {
    "resnet50": prep_resnet50,
    "alexnet": lambda: prep_resnet50(model_name="alexnet"),
    "googlenet": lambda: prep_resnet50(model_name="googlenet"),
    "vgg16": lambda: prep_resnet50(model_name="vgg16"),
    "vgg19": lambda: prep_resnet50(model_name="vgg19"),
    "lstm": prep_lstm,
    "lstm_h256": lambda: prep_lstm(hidden=256),
    # bs128 matches the reference grid's h1280 row (1007 ms/batch anchor)
    "lstm_h1280": lambda: prep_lstm(hidden=1280, batch_size=128),
    "seq2seq": prep_seq2seq,
    "transformer": prep_transformer,
    "transformer_big": prep_transformer_big,
    "transformer_fused": prep_transformer_fused,
    "transformer_dp_overlap": prep_transformer_dp_overlap,
}

# per-metric timed-step counts (N; the pair is N and 3N) and inner-loop k.
# N is sized so the differential gap is >= ~5 s of device time.
PLANS = {
    "resnet50":        dict(n=200, k=10, budget=2400),
    "alexnet":         dict(n=200, k=10, budget=2400),
    "googlenet":       dict(n=200, k=10, budget=2400),
    "vgg16":           dict(n=100, k=10, budget=2400),
    "vgg19":           dict(n=100, k=10, budget=2400),
    "lstm":            dict(n=400, k=10, budget=1800),
    "lstm_h256":       dict(n=400, k=10, budget=1800),
    "lstm_h1280":      dict(n=300, k=10, budget=1800),
    "seq2seq":         dict(n=300, k=10, budget=1800),
    "transformer":     dict(n=60,  k=2,  budget=2400),
    "transformer_big": dict(n=30,  k=1,  budget=2400),
    # one step_body call = 8 fused optimizer steps; k stays 1 (the fusion
    # under test is the Trainer's, not the harness fori_loop's)
    "transformer_fused": dict(n=8, k=1, budget=2400),
    # same shape as transformer_fused, explicit bucketed grad sync — the
    # pair is the overlap differential on a dp mesh
    "transformer_dp_overlap": dict(n=8, k=1, budget=2400),
    # Trainer-loop-level overlap differential (own child protocol:
    # run_pipelined_child; n/k unused)
    "transformer_pipelined": dict(n=0, k=1, budget=2400),
    # serving decode throughput (own child protocol:
    # run_serving_bench_child; n/k unused)
    "transformer_decode": dict(n=0, k=1, budget=2400),
    # same tick over an int8-quantized KV pool (ISSUE 14): the
    # memory-bound decode's bytes-vs-throughput differential
    "transformer_decode_int8": dict(n=0, k=1, budget=2400),
    # speculative-vs-plain decode differential (own child protocol:
    # run_serving_spec_bench_child; n/k unused)
    "transformer_decode_spec": dict(n=0, k=1, budget=2400),
    # tensor-parallel sharded tick over a 2-device mesh (ISSUE 15; own
    # child protocol: run_serving_tp_bench_child; n/k unused)
    "transformer_decode_tp": dict(n=0, k=1, budget=2400),
    # cold-vs-warm fresh-process spawn TTFT (ISSUE 16; own child
    # protocol: run_replica_spawn_child; n/k unused)
    "replica_spawn": dict(n=0, k=1, budget=2400),
}


# ---------------------------------------------------------------------------
# timed child: one fresh process = one tunnel session = one timed region
# ---------------------------------------------------------------------------

def run_timed_child(name, timed_steps, steps_per_call, warmup_calls=2,
                    reps=3):
    """Interleaved differential inside ONE process: alternate timed regions
    of N and 3N steps (each dispatch-only, closed by ONE fetch), ``reps``
    times; report median (T_3N - T_N)/(2N) plus the raw samples. Prints a
    JSON line for the parent.

    ``BENCH_CONV1X1_IMPL=conv|matmul|pallas`` selects the 1x1-conv lowering
    (experiments/conv1x1_backward.py A/B hook)."""
    impl = os.environ.get("BENCH_CONV1X1_IMPL")
    if impl:
        from paddle_tpu.nn.layers import set_conv1x1_impl
        set_conv1x1_impl(impl)
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    n = timed_steps
    with use_policy(bfloat16_compute):
        step_body, state, meta = PREPS[name]()
        k = max(1, steps_per_call)
        if k > 1:
            def body(s):
                return lax.fori_loop(0, k, lambda i, t: step_body(t), s)
        else:
            body = step_body
        stepc = jax.jit(body, donate_argnums=0)
        for _ in range(max(1, warmup_calls)):
            state = stepc(state)           # compile + warmup
        # fence the warmup so its async tail can't leak into the first
        # timed region (it would bias sample 1 low)
        _fence(state[-1])

        def region(nsteps, state):
            ncalls = max(1, nsteps // k)
            t0 = time.perf_counter()
            for _ in range(ncalls):
                state = stepc(state)
            loss = _fence(state[-1])       # the single fetch closes timing
            return time.perf_counter() - t0, ncalls * k, loss, state

        samples, pairs, raw_tb, loss = [], [], [], float("nan")
        sa = sb = 1
        for _ in range(max(1, reps)):
            ta, sa, _, state = region(n, state)
            tb, sb, loss, state = region(3 * n, state)
            # sb == sa iff steps_per_call swallowed the whole region
            # (k >= 3n): no differential signal, force the fallback
            samples.append((tb - ta) / (sb - sa) if sb > sa else -1.0)
            pairs.append([round(ta, 3), round(tb, 3)])   # reporting only
            raw_tb.append(tb)                            # computation
        med = sorted(samples)[len(samples) // 2]
        if med <= 0:
            # drift swamped the signal: report the best absolute rate
            # (sb = steps actually executed in a 3N region). NOTE: this
            # includes the one closing fetch, whose constant can dominate
            # in the degraded-tunnel mode that triggers this path — the
            # JSON carries the caveat.
            med = min(raw_tb) / sb
            protocol = "absolute-fallback-includes-fetch-constant"
        else:
            protocol = "differential-interleaved"
    print(json.dumps({"child": name, "per_step_s": med,
                      "protocol": protocol,
                      "samples_s_per_step": [round(s, 6) for s in samples],
                      "region_totals_s": pairs,
                      "timed_steps_pair": [sa, sb],
                      "steps_per_call": k,
                      "final_loss": round(loss, 4),
                      "device": jax.devices()[0].device_kind,
                      "meta": {m: v for m, v in meta.items()
                               if not callable(v)}}))


def _force_cpu_devices(env, n):
    """A copy of ``env`` pinned to the virtual ``n``-device CPU platform
    (must land before the child's jax initializes); scrubs any existing
    device-count flag first so forcing is idempotent."""
    env = dict(env, JAX_PLATFORMS="cpu")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env


def _spawn_child(name, timed_steps, steps_per_call, budget, env=None):
    repo = os.path.dirname(os.path.abspath(__file__))
    cmd = [sys.executable, os.path.join(repo, "bench.py"),
           "--metric", name, "--child", "1",
           "--timed-steps", str(timed_steps),
           "--steps-per-call", str(steps_per_call)]
    res = subprocess.run(cmd, capture_output=True, text=True, cwd=repo,
                         timeout=budget, env=env)
    if res.returncode != 0:
        raise RuntimeError(f"child {name}/{timed_steps} rc={res.returncode}: "
                           f"{res.stderr[-600:]}")
    return json.loads(res.stdout.strip().splitlines()[-1])


def bench_differential(name, n=None, k=None, budget=None):
    """The degradation-proof protocol: one fresh-session child running the
    interleaved N/3N differential (see run_timed_child)."""
    plan = PLANS[name]
    n = n or plan["n"]
    k = k or plan["k"]
    budget = budget or plan["budget"]
    r2 = _spawn_child(name, n, k, budget)
    per_step = r2["per_step_s"]
    protocol = r2["protocol"]
    meta = r2["meta"]
    n_dev = max(1, int(meta.get("n_devices", 1)))
    units = meta["units_per_step"]
    rate = units / per_step / n_dev     # per-chip normalisation
    out = {
        "metric": meta["metric"],
        "unit": meta["unit"],
        "ms_per_step": round(per_step * 1e3, 2),
        "final_loss": r2["final_loss"],
        "device": r2["device"],
        "protocol": protocol,
        "protocol_detail": {
            "timed_steps_pair": r2["timed_steps_pair"],
            "samples_s_per_step": r2["samples_s_per_step"],
            "region_totals_s": r2["region_totals_s"],
            "steps_per_call": r2["steps_per_call"],
        },
    }
    out["n_devices"] = n_dev
    if meta["unit"] == "ms/batch":
        out["value"] = round(per_step * 1e3, 2)
    else:
        out["value"] = round(rate, 2)
    peak = PEAK_FLOPS.get(r2["device"])
    if meta.get("flops_per_step") and peak:
        out["mfu_pct"] = round(
            100 * meta["flops_per_step"] / per_step / (peak * n_dev), 2)
    floor = meta.get("loss_floor")
    if floor is not None:
        out["loss_floor"] = floor
        fl = out["final_loss"]
        # the conflicting-pair floor is an exact lower bound: a batch loss
        # below it means the task went degenerate or the model is broken
        if not math.isfinite(fl) or fl < floor * 0.98 - 5e-4:
            raise RuntimeError(
                f"{name}: final_loss {fl} is below the analytic floor "
                f"{floor} of the conflicting-pair task — degenerate data "
                f"or broken model")
    base = meta.get("baseline")
    if base:
        if meta.get("baseline_kind") == "lower":
            out["vs_baseline"] = round(base / (per_step * 1e3), 2)
        else:
            out["vs_baseline"] = round(rate / base, 2)
    else:
        out["vs_baseline"] = None
    for key in ("batch_size", "hidden", "seq_len", "dim", "layers",
                "src_len", "tgt_len"):
        if key in meta:
            out[key] = meta[key]
    return out


# ---------------------------------------------------------------------------
# CPU smoke gate: fused-vs-plain differential (ISSUE 1; runs in CI tier-1)
# ---------------------------------------------------------------------------

# Keys every telemetry JSONL step record must carry (the smoke gate and
# tests/test_bench_smoke.py both enforce this schema — BENCH_* snapshots
# carry the telemetry block going forward).
TELEMETRY_STEP_KEYS = frozenset((
    "kind", "ts", "pass", "step", "k_steps", "m", "loss",
    "host_stack_ms", "shard_ms", "dispatch_ms", "device_ms", "replay_ms",
    "stage_ms", "drain_wait_ms", "overlap_frac",
    "compile_count", "retrace_count", "grad_norm", "param_norm",
    "update_ratio", "nonfinite_count", "bytes_in_use", "peak_bytes",
    "fenced"))


def run_smoke(K=4, M=2, timing_passes=3):
    """Tiny-model fused-vs-plain gate, CPU-sized for CI: train the SAME
    batch stream through ``Trainer(steps_per_call=K, grad_accum=M)`` (one
    dispatch per K steps, with the remat scan-over-layers block stack) and
    through the unfused ``Trainer(grad_accum=M)`` (one dispatch per step),
    assert bit-identical f32 params and per-step losses, then time both hot
    loops post-compile and print ONE JSON line with the per-optimizer-step
    differential. Non-equal params exit non-zero — the fused path cannot
    silently rot.

    ISSUE 2 extension: a third, telemetry-on fused run emits JSONL through
    ``obs.Telemetry(sinks=[JsonlSink])``; the gate asserts the file parses
    and every step record carries the required schema keys
    (``TELEMETRY_STEP_KEYS``), and the output JSON carries the telemetry
    summary (step breakdown, retrace count, est. MFU) so BENCH_* snapshots
    record them going forward."""
    import jax.numpy as jnp   # noqa: F811 (module-level import is fine too)
    from paddle_tpu import optim
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn import costs
    from paddle_tpu.train import Trainer, events as ev

    V, T, bs, n_batches = 64, 16, 8, K * M * 2
    rng = np.random.RandomState(0)
    batches = [{"x": rng.randint(0, V, (bs, T)).astype(np.int32),
                "y": rng.randint(0, V, (bs, T)).astype(np.int32)}
               for _ in range(n_batches)]

    def make(k_steps, telemetry=None, pipeline_depth=1, tracer=None):
        tr = Trainer(
            model=TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                                ffn_hidden=64, max_len=T, remat="dots"),
            loss_fn=lambda out, b: costs.softmax_cross_entropy(
                out.reshape(-1, V), b["y"].reshape(-1)),
            optimizer=optim.adam(1e-3), steps_per_call=k_steps,
            grad_accum=M, pipeline_depth=pipeline_depth, telemetry=telemetry,
            tracer=tracer)
        tr.init(jax.random.PRNGKey(0), batches[0])
        return tr

    def run(tr):
        losses = []

        def handler(e):
            if isinstance(e, ev.EndIteration):
                losses.append(e.cost)

        tr.train(lambda: iter(batches), num_passes=1, event_handler=handler,
                 log_period=0)
        return losses

    def timed(tr):
        t0 = time.perf_counter()
        for _ in range(timing_passes):
            tr.train(lambda: iter(batches), num_passes=1, log_period=0)
        steps = timing_passes * (n_batches // M)
        return (time.perf_counter() - t0) / steps

    tr_fused, tr_plain = make(K), make(1)
    l_fused, l_plain = run(tr_fused), run(tr_plain)
    eq_losses = l_fused == l_plain
    eq_params = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(
                tr_fused.train_state.params)),
            jax.tree_util.tree_leaves(jax.device_get(
                tr_plain.train_state.params))))
    fused_ms = timed(tr_fused) * 1e3      # post-compile hot-loop timing
    plain_ms = timed(tr_plain) * 1e3

    # -- telemetry gate: short telemetry-on fused run, JSONL must parse and
    # carry the required keys (ISSUE 2 satellite) -------------------------
    import tempfile
    from paddle_tpu.obs import InMemorySink, JsonlSink, Telemetry
    jsonl_path = os.path.join(tempfile.mkdtemp(prefix="paddle_tpu_tel_"),
                              "telemetry.jsonl")
    tel = Telemetry(
        sinks=[InMemorySink(), JsonlSink(jsonl_path)],
        tokens_per_step=bs * T * M,
        flops_per_step=M * transformer_train_flops(bs, T, 32, 2, V, 64))
    tr_tel = make(K, telemetry=tel)
    l_tel = run(tr_tel)
    tel.close()
    tel_records = []
    jsonl_ok, missing = False, []
    try:
        with open(jsonl_path) as f:
            tel_records = [json.loads(line) for line in f if line.strip()]
        steps = [r for r in tel_records if r.get("kind") == "step"]
        missing = sorted(TELEMETRY_STEP_KEYS
                         - set(steps[0] if steps else {}))
        jsonl_ok = (bool(steps) and not missing
                    and all(r.get("device_ms") is not None for r in steps)
                    and tel.compile_count >= 1)
    except (OSError, json.JSONDecodeError) as e:
        missing = [f"parse-error: {e}"]
    telemetry = {"jsonl_records": len(tel_records), "jsonl_ok": jsonl_ok,
                 # telemetry must not perturb the math: same loss stream
                 "losses_equal_with_telemetry": l_tel == l_plain,
                 **tel.summary()}
    if missing:
        telemetry["missing_keys"] = missing

    # -- async host pipeline gate (ISSUE 3): a pipeline_depth=2 fused run
    # must reproduce the serial loss stream bit-exact and its telemetry
    # must carry the overlap keys (stage_ms / drain_wait_ms / overlap_frac
    # non-None). The steps/s delta is recorded but informational — on a
    # shared-core CPU CI box the stager thread competes with XLA for the
    # same cores, so the overlap win is only reliably visible on device.
    tel_pipe = Telemetry(sinks=[InMemorySink()])
    tr_pipe = make(K, telemetry=tel_pipe, pipeline_depth=2)
    l_pipe = run(tr_pipe)
    pipe_steps = [r for r in tel_pipe.sinks[0].by_kind("step")]
    overlap_ok = bool(pipe_steps) and all(
        r.get("stage_ms") is not None and r.get("drain_wait_ms") is not None
        and r.get("overlap_frac") is not None for r in pipe_steps)
    tr_pipe_t = make(K, pipeline_depth=2)              # untelemetered timing
    run(tr_pipe_t)                                     # compile warmup pass
    pipe_ms = timed(tr_pipe_t) * 1e3
    pipeline = {
        "losses_equal": l_pipe == l_fused,
        "overlap_keys_ok": overlap_ok,
        "pipelined_ms_per_opt_step": round(pipe_ms, 3),
        "serial_ms_per_opt_step": round(fused_ms, 3),
        "pipelined_vs_serial_speedup": round(fused_ms / pipe_ms, 3),
        "mean_stage_ms": tel_pipe.summary().get("mean_stage_ms"),
        "mean_drain_wait_ms": tel_pipe.summary().get("mean_drain_wait_ms"),
        "mean_overlap_frac": tel_pipe.summary().get("mean_overlap_frac"),
        # the serial host cost the pipeline hides (acceptance comparator)
        "serial_host_stack_plus_shard_ms": round(
            (telemetry.get("mean_host_stack_ms") or 0.0)
            + (telemetry.get("mean_shard_ms") or 0.0), 4),
    }

    # -- structured-trace gate (ISSUE 4): a traced pipelined run must
    # serialize to valid Chrome Trace Event JSON carrying spans from BOTH
    # the main thread and the stager thread, with every flow event paired
    # (each staging "s" finds its drain "f"), sane monotonic timestamps,
    # and at least one stager-thread staging span TIME-INTERSECTING an
    # individual main-thread span — the two threads provably active at
    # once, the host/device overlap the trace exists to make auditable
    # (a union-window check would pass even for fully serialized staging).
    # Tracing must not perturb the math either (same loss stream as the
    # serial fused run).
    from paddle_tpu.obs import Tracer
    tr_traced = make(K, telemetry=Telemetry(sinks=[InMemorySink()]),
                     pipeline_depth=2, tracer=Tracer())
    l_traced = run(tr_traced)
    # gate on a FRESH tracer over a post-compile pass: in pass 1 the tiny
    # stream stages every group before the compile-dominated first
    # dispatch even starts, so the steady-state interleaving the
    # concurrency gate checks only exists from pass 2 on. The
    # stage-concurrent-with-main property is real but SCHEDULING-
    # dependent on a fast host (the stager can finish staging between
    # two main-thread spans in any one pass), so the gate takes up to
    # `attempts` post-compile passes and passes when ANY exhibits the
    # concurrency — the format/flow/clock invariants are re-checked on
    # every attempt and must hold on the last one regardless.
    trace_path = os.path.join(os.path.dirname(jsonl_path), "trace.json")
    trace_ok, trace = False, {"path": trace_path,
                              "losses_equal_with_tracer": l_traced == l_fused}
    attempts = 6
    for attempt in range(attempts):
        tracer = Tracer()
        tr_traced.tracer = tracer
        run(tr_traced)
        tracer.save(trace_path)
        try:
            with open(trace_path) as f:
                tdata = json.load(f)
            evs = tdata["traceEvents"]
            xs = [e for e in evs if e.get("ph") == "X"]
            s_ids = {e["id"] for e in evs if e.get("ph") == "s"}
            f_ids = {e["id"] for e in evs if e.get("ph") == "f"}
            ts_list = [e.get("ts", -1.0) for e in evs]
            # ts_monotonic alone only validates the serializer's sort;
            # the clock invariant is every span ts >= 0 (relative to
            # tracer construction) with a positive duration
            ts_valid = all(e["ts"] >= 0 and e["dur"] > 0 for e in xs)
            disp = [e for e in xs if e["name"] == "dispatch"]
            stage = [e for e in xs if e["name"] == "stage"]
            stage_tids = {e["tid"] for e in stage}
            cross_thread = bool(stage and disp and
                                not (stage_tids & {e["tid"] for e in disp}))
            main = [e for e in xs if e["tid"] not in stage_tids]
            stage_concurrent_with_main = any(
                s["ts"] < m["ts"] + m["dur"] and s["ts"] + s["dur"] > m["ts"]
                for s in stage for m in main)
            trace_ok = (len({e["tid"] for e in xs}) >= 2 and cross_thread
                        and bool(s_ids) and s_ids == f_ids
                        and ts_list == sorted(ts_list) and ts_valid
                        and stage_concurrent_with_main)
            trace.update({
                "trace_ok": trace_ok, "spans": len(xs),
                "threads": len({e["tid"] for e in xs}),
                "flows": len(s_ids), "flows_paired": s_ids == f_ids,
                "ts_monotonic": ts_list == sorted(ts_list),
                "ts_valid": ts_valid,
                "stage_concurrent_with_main": stage_concurrent_with_main,
                "concurrency_attempts": attempt + 1,
            })
        except Exception as e:                   # malformed file IS the bug
            trace.update({"trace_ok": False,
                          "error": f"{type(e).__name__}: {e}"})
            break
        if trace_ok:
            break

    # -- simulated-dp gate children: each gate runs in its own subprocess
    # (the forced 2-device platform must exist before jax initializes).
    # The child prints its full verdict JSON (which acceptance criterion
    # failed) even when it exits 1 — keep that diagnosis; synthesize an
    # error dict only when there is no parseable line (a crash before
    # printing), and then carry the stderr tail so the traceback isn't
    # lost.
    env = _force_cpu_devices(os.environ, 2)
    repo = os.path.dirname(os.path.abspath(__file__))

    def run_gate_child(flag):
        try:
            res = subprocess.run(
                [sys.executable, os.path.join(repo, "bench.py"), flag, "1"],
                cwd=repo, env=env, capture_output=True, text=True,
                timeout=600)
        except (subprocess.TimeoutExpired, OSError) as e:
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        try:
            verdict = json.loads(res.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            verdict = {"ok": False,
                       "error": f"no verdict on stdout; "
                                f"stderr: {res.stderr[-400:]}"}
        if res.returncode != 0:
            verdict["ok"] = False
            verdict.setdefault("rc", res.returncode)
        return verdict

    # attribution gate (ISSUE 6): static HLO analyzer over the CPU fused
    # transformer step — >=4 named scopes with nonzero FLOPs, parsed
    # total FLOPs within 5% of cost_analysis(), an exposed-communication
    # estimate for the grad all-reduce.
    attribution = run_gate_child("--attribution-child")
    attribution_ok = attribution.get("ok") is True

    # gradient-sync overlap gate (ISSUE 8): bucketed-vs-fused explicit dp
    # sync — bit-equal losses and params, >= 2 gradient all-reduces in
    # the bucketed HLO (incl. the per-layer in-scan sync) vs exactly 1
    # fused, per-bucket comm rows with the sched_distance field in the
    # attribution record.
    overlap = run_gate_child("--overlap-child")
    overlap_ok = overlap.get("ok") is True

    # serving gate (ISSUE 9): 8 ragged requests through the continuous-
    # batching engine — all complete, zero retraces after warmup,
    # per-request TTFT/TPOT records, continuous beats gang-static
    # tokens/sec, decode tick classified memory-bound.
    serving = run_gate_child("--serving-child")
    serving_ok = serving.get("ok") is True

    # fault-tolerance gate (ISSUE 10): supervised crash/corrupt/preempt
    # recovery — the supervisor resumes an injected crash, quarantines a
    # corrupted latest pass and falls back one pass, and a preemption
    # quiesces mid-pass then resumes, each bit-equal to the
    # uninterrupted run.
    faults = run_gate_child("--faults-child")
    faults_ok = faults.get("ok") is True

    # serving-fleet gate (ISSUE 11): seeded bursty loadgen over 3
    # replicas with one injected kill + one drain — every request
    # terminal with clean lineage, no survivor leaks/retraces, bounded
    # shedding, and the SJF-vs-FCFS goodput-under-deadline differential.
    fleet = run_gate_child("--fleet-child")
    fleet_ok = fleet.get("ok") is True

    # cold-vs-warm spawn gate (ISSUE 16): two fresh replica children
    # against one cache root — the cold one pays autotune trials + XLA
    # compiles and misses both persistent caches, the warm one runs zero
    # trials and hits both, compile_counts stay {prefill:1, tick:1}
    # through real traffic, and the two emit identical tokens.
    spawn = run_gate_child("--spawn-child")
    spawn_ok = spawn.get("ok") is True

    # perf-regression sentinel self-check (ISSUE 19): a 2-entry
    # synthetic ledger must pass an in-family NEW record and fail one
    # with injected regressions in BOTH directions (ms metric up, rate
    # metric down) — the --compare-history gate, exercised end to end
    # without a real bench run.
    hdir = tempfile.mkdtemp(prefix="bench_hist_")
    ledger = os.path.join(hdir, "LEDGER.jsonl")
    for ms, rate in ((10.0, 90.0), (10.4, 88.0)):
        append_history(ledger, {"all_metrics": {
            "step": {"metric": "step", "value": ms, "unit": "ms/step"},
            "tput": {"metric": "tput", "value": rate,
                     "unit": "steps/s"}}})
    good_p = os.path.join(hdir, "good.json")
    bad_p = os.path.join(hdir, "bad.json")
    with open(good_p, "w") as f:
        json.dump({"all_metrics": {
            "step": {"metric": "step", "value": 10.3, "unit": "ms/step"},
            "tput": {"metric": "tput", "value": 89.5,
                     "unit": "steps/s"}}}, f)
    with open(bad_p, "w") as f:
        json.dump({"all_metrics": {
            "step": {"metric": "step", "value": 13.0, "unit": "ms/step"},
            "tput": {"metric": "tput", "value": 70.0,
                     "unit": "steps/s"}}}, f)
    try:
        gate_good = compare_history(ledger, good_p, 5.0, window=5)
        gate_bad = compare_history(ledger, bad_p, 5.0, window=5)
        history = {
            "ok": bool(gate_good["ok"] and not gate_bad["ok"]
                       and set(gate_bad["regressions"])
                       == {"step", "tput"}
                       and gate_good["baseline_entries"] == 2),
            "good_passes": bool(gate_good["ok"]),
            "bad_regressions": gate_bad["regressions"],
            "baseline_entries": gate_good["baseline_entries"],
        }
    except (OSError, ValueError, KeyError) as e:
        history = {"ok": False, "error": f"{type(e).__name__}: {e}"}
    history_ok = history.get("ok") is True

    out = {
        "metric": "fused_vs_plain_smoke",
        "equal": bool(eq_params and eq_losses),
        "params_equal": bool(eq_params), "losses_equal": bool(eq_losses),
        "K": K, "M": M, "opt_steps": len(l_fused),
        "fused_ms_per_opt_step": round(fused_ms, 3),
        "plain_ms_per_opt_step": round(plain_ms, 3),
        "fused_vs_plain_speedup": round(plain_ms / fused_ms, 3),
        "final_loss": round(l_fused[-1], 4) if l_fused else None,
        "device": jax.devices()[0].device_kind,
        "telemetry": telemetry,
        "pipeline": pipeline,
        "trace": trace,
        "attribution": attribution,
        "overlap": overlap,
        "serving": serving,
        "faults": faults,
        "fleet": fleet,
        "spawn": spawn,
        "history": history,
    }
    print(json.dumps(out))
    ok = (out["equal"] and jsonl_ok
          and telemetry["losses_equal_with_telemetry"]
          and pipeline["losses_equal"] and pipeline["overlap_keys_ok"]
          and trace_ok and trace["losses_equal_with_tracer"]
          and attribution_ok and overlap_ok and serving_ok and faults_ok
          and fleet_ok and spawn_ok and history_ok)
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# MFU-gap attribution gate child (ISSUE 6): static HLO analyzer on the
# fused transformer step over a simulated dp mesh
# ---------------------------------------------------------------------------

def run_attribution_child(K=2, M=2):
    """Build the same tiny fused transformer trainer run_smoke gates, on
    the dp mesh this process was forced onto
    (xla_force_host_platform_device_count), run
    ``Trainer.attribution_report`` over it, and print the gate verdict as
    one JSON line: >=4 named scopes with nonzero FLOPs, parsed-vs-
    cost_analysis FLOPs agreement within 5%, a collective inventory with
    an exposed-communication estimate for the grad all-reduce, and the
    ``kind="attribution"`` telemetry record landing in the sink."""
    from paddle_tpu import optim
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn import costs
    from paddle_tpu.obs import InMemorySink, Telemetry
    from paddle_tpu.train import Trainer

    V, T, bs = 64, 16, 8
    rng = np.random.RandomState(0)
    batches = [{"x": rng.randint(0, V, (bs, T)).astype(np.int32),
                "y": rng.randint(0, V, (bs, T)).astype(np.int32)}
               for _ in range(K * M)]
    mem = InMemorySink()
    tr = Trainer(
        model=TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                            ffn_hidden=64, max_len=T, remat="dots"),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(
            out.reshape(-1, V), b["y"].reshape(-1)),
        optimizer=optim.adam(1e-3), steps_per_call=K, grad_accum=M,
        telemetry=Telemetry(sinks=[mem]))
    tr.init(jax.random.PRNGKey(0), batches[0])
    report = tr.attribution_report(batches)
    named = sorted(k for k, v in report["scope_rollup"].items()
                   if v > 0 and k != "(unscoped)")
    agree = report["flops_vs_cost_analysis_pct"]
    gar = (report.get("comm") or {}).get("grad_allreduce")
    emitted = len(mem.by_kind("attribution"))
    ok = (len(named) >= 4
          and agree is not None and abs(agree) <= 5.0
          and bool(report["collectives"])
          and gar is not None
          and gar.get("exposed_ms_if_overlapped") is not None
          and emitted == 1)
    print(json.dumps({
        "child": "attribution", "ok": bool(ok),
        "n_devices": int(jax.device_count()),
        "scopes_nonzero": len(named), "scopes": named[:16],
        "flops_vs_cost_analysis_pct": agree,
        "flops_static": report["flops_static"],
        "cost_analysis_flops": report["cost_analysis_flops"],
        "collectives": len(report["collectives"]),
        "grad_allreduce": gar,
        "exposed_comm_ms": report["comm"]["exposed_ms"],
        "est_mfu_pct": report["est_mfu_pct"],
        "emitted_records": emitted,
        "mfu_gap_top": (report["mfu_gap_rank"][0]["scope"]
                        if report["mfu_gap_rank"] else None),
    }))
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# gradient-sync overlap gate child (ISSUE 8): bucketed-vs-fused on a
# simulated dp mesh
# ---------------------------------------------------------------------------

def run_overlap_child(K=2):
    """Bucketed-vs-fused gradient sync on the 2-device dp mesh this
    process was forced onto: train the tiny remat'd transformer one pass
    under ``Trainer(grad_sync="bucketed", bucket_mb=tiny)`` and
    ``grad_sync="fused"``, assert bit-identical f32 params and per-step
    losses, then gate the compiled HLO through the attribution report —
    bucketed yields >= 2 gradient all-reduces (including the per-layer
    in-scan sync, whose loop multiplier exceeds the K-step scan's,
    proving it sits INSIDE the backward scan) where fused yields exactly
    1, and every per-bucket ``comm.grad_allreduce`` row carries the
    ``sched_distance`` field. Prints the verdict as one JSON line."""
    from paddle_tpu import optim
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn import costs
    from paddle_tpu.obs import InMemorySink, Telemetry
    from paddle_tpu.train import Trainer, events as ev

    V, T, bs, L = 64, 16, 8, 2
    rng = np.random.RandomState(0)
    batches = [{"x": rng.randint(0, V, (bs, T)).astype(np.int32),
                "y": rng.randint(0, V, (bs, T)).astype(np.int32)}
               for _ in range(2 * K)]

    def make(grad_sync, bucket_mb=4.0, telemetry=None):
        tr = Trainer(
            model=TransformerLM(vocab=V, dim=32, num_layers=L, num_heads=4,
                                ffn_hidden=64, max_len=T, remat="dots"),
            loss_fn=lambda out, b: costs.softmax_cross_entropy(
                out.reshape(-1, V), b["y"].reshape(-1)),
            optimizer=optim.adam(1e-3), steps_per_call=K,
            grad_sync=grad_sync, bucket_mb=bucket_mb, telemetry=telemetry)
        tr.init(jax.random.PRNGKey(0), batches[0])
        return tr

    def run(tr):
        losses = []

        def handler(e):
            if isinstance(e, ev.EndIteration):
                losses.append(e.cost)

        tr.train(lambda: iter(batches), num_passes=1, event_handler=handler,
                 log_period=0)
        return losses

    mem = InMemorySink()
    tr_b = make("bucketed", bucket_mb=0.0005,
                telemetry=Telemetry(sinks=[mem]))
    tr_f = make("fused")
    l_b, l_f = run(tr_b), run(tr_f)
    losses_equal = l_b == l_f
    params_equal = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(
                tr_b.train_state.params)),
            jax.tree_util.tree_leaves(jax.device_get(
                tr_f.train_state.params))))

    def gar_of(tr):
        rep = tr.attribution_report(batches[:K], emit=tr is tr_b)
        return (rep["comm"] or {}).get("grad_allreduce") or {}

    gar_b, gar_f = gar_of(tr_b), gar_of(tr_f)
    rows_b = gar_b.get("buckets") or []
    rows_f = gar_f.get("buckets") or []
    # the in-scan row executes K * L times per dispatch; a row whose
    # multiplier exceeds K can only live inside the backward layer scan
    in_scan_rows = [r for r in rows_b if r["multiplier"] > K]
    sched_field_ok = all("sched_distance" in r for r in rows_b + rows_f)
    emitted = len(mem.by_kind("attribution"))
    ok = (losses_equal and params_equal
          and len(rows_b) >= 2 and len(rows_f) == 1
          and bool(in_scan_rows) and sched_field_ok and emitted == 1)
    print(json.dumps({
        "child": "overlap", "ok": bool(ok),
        "n_devices": int(jax.device_count()),
        "losses_equal": losses_equal, "params_equal": params_equal,
        "final_loss": round(l_b[-1], 4) if l_b else None,
        "bucketed_grad_allreduces": len(rows_b),
        "fused_grad_allreduces": len(rows_f),
        "in_scan_rows": len(in_scan_rows),
        "sched_distance_field": sched_field_ok,
        "bucket_rows": rows_b,
        "bucketed_exposed_ms_today": gar_b.get("exposed_ms_today"),
        "bucketed_exposed_ms_if_overlapped":
            gar_b.get("exposed_ms_if_overlapped"),
        "emitted_records": emitted,
    }))
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# serving gate child (ISSUE 9): continuous batching + paged KV on CPU
# ---------------------------------------------------------------------------

def run_serving_child():
    """The serving runtime's CI gate: 8 ragged requests through a
    4-slot engine (``paddle_tpu.serve``), once under continuous batching
    and once under the gang-static baseline. Asserts: every request
    completes; ZERO retraces after warmup (one compiled program per
    entry point across all admission/eviction churn); one per-request
    telemetry record each with the TTFT/TPOT SLO fields; continuous
    beats static on ragged-length tokens/sec; and the decode tick's
    attribution report classifies ``decode/*`` as memory-bound. Prints
    the verdict as one JSON line."""
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.obs import InMemorySink, Telemetry
    from paddle_tpu.serve import ContinuousBatchingScheduler, DecodeEngine

    V, W = 64, 32
    model = TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                          ffn_hidden=64, max_len=W)
    vs = model.init(jax.random.PRNGKey(0), jnp.zeros((1, W), jnp.int32))
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(0, V, rng.randint(2, 8)))
               for _ in range(8)]
    # stragglers dominate their gang: exactly the raggedness
    # iteration-level scheduling exists to absorb
    maxnew = [2, 16, 2, 16, 2, 16, 2, 2]

    def run_policy(policy):
        mem = InMemorySink()
        eng = DecodeEngine(model, vs, max_slots=4, block_size=4,
                           telemetry=Telemetry(sinks=[mem]))

        def one_run():
            sched = ContinuousBatchingScheduler(eng, policy=policy)
            for p, m in zip(prompts, maxnew):
                sched.submit(p, m)
            t0 = time.perf_counter()
            done = sched.run()
            return done, time.perf_counter() - t0

        one_run()                          # warmup: compiles + first churn
        warm_ticks = eng.ticks
        done, wall = one_run()             # timed, fully warm
        toks = sum(len(r.tokens) for r in done)
        return {
            "completed": len(done), "tokens": toks,
            "ticks": eng.ticks - warm_ticks,
            "tokens_per_sec": round(toks / wall, 2),
            "compile_counts": eng.compile_counts(),
            "request_records": len(mem.by_kind("request")),
            "tick_records": len(mem.by_kind("decode_tick")),
            "sample_request": next(
                (r for r in mem.by_kind("request")
                 if r.get("tpot_ms") is not None), None),
        }, eng

    cont, eng_c = run_policy("continuous")
    stat, _ = run_policy("static")
    report = eng_c.attribution_report(emit=False)
    decode_block = report.get("decode") or {}

    no_retrace = (cont["compile_counts"] == {"prefill": 1, "tick": 1}
                  and stat["compile_counts"] == {"prefill": 1, "tick": 1})
    records_ok = (cont["request_records"] == 16     # warmup + timed runs
                  and cont["sample_request"] is not None
                  and cont["sample_request"].get("ttft_ms") is not None)

    # --- ISSUE 12 leg (a): copy-on-write prefix sharing — a shared-
    # prefix workload admits with FEWER fresh block allocations than
    # sharing-off, produces bit-identical tokens, and leaks nothing
    pre = list(rng.randint(0, V, 9))
    shared_prompts = [pre + list(rng.randint(0, V, 3)) for _ in range(6)]

    def run_shared(share):
        eng = DecodeEngine(model, vs, max_slots=4, block_size=4,
                           share_prefix=share)
        sched = ContinuousBatchingScheduler(eng)
        reqs = [sched.submit(p, 4) for p in shared_prompts]
        sched.run()
        return eng, [r.tokens for r in reqs]

    eng_on, toks_on = run_shared(True)
    eng_off, toks_off = run_shared(False)
    share_leg = {
        "tokens_identical": toks_on == toks_off,
        "fresh_allocs_shared": eng_on.cache.allocator.total_allocs,
        "fresh_allocs_unshared": eng_off.cache.allocator.total_allocs,
        "prefix_hit_blocks": eng_on.cache.prefix_hit_blocks,
        "leak_free": eng_on.cache.free_blocks
        == eng_on.cache.num_blocks - 1,
        "compile_counts": eng_on.compile_counts(),
    }
    share_ok = (share_leg["tokens_identical"] and share_leg["leak_free"]
                and share_leg["fresh_allocs_shared"]
                < share_leg["fresh_allocs_unshared"]
                and share_leg["compile_counts"]
                == {"prefill": 1, "tick": 1})

    # --- ISSUE 12 leg (b): lossless speculative decoding — token-
    # identical to the plain greedy engine with STRICTLY fewer ticks
    def run_spec(k):
        eng = DecodeEngine(model, vs, max_slots=4, block_size=4,
                           speculative=k)
        sched = ContinuousBatchingScheduler(eng)
        reqs = [sched.submit(p, m) for p, m in zip(prompts, maxnew)]
        sched.run()
        return eng, [r.tokens for r in reqs]

    eng_b, toks_b = run_spec(0)
    eng_s, toks_s = run_spec(3)
    spec_leg = {
        "tokens_identical": toks_s == toks_b,
        "ticks_baseline": eng_b.ticks,
        "ticks_speculative": eng_s.ticks,
        "draft_accept_rate": round(
            eng_s.draft_accepted / eng_s.draft_proposed, 4)
        if eng_s.draft_proposed else None,
        "compile_counts": eng_s.compile_counts(),
    }
    spec_ok = (spec_leg["tokens_identical"]
               and spec_leg["ticks_speculative"]
               < spec_leg["ticks_baseline"]
               and spec_leg["compile_counts"]
               == {"prefill": 1, "tick": 1})

    # --- ISSUE 12 leg (c): chunked prefill — a long admission
    # interleaves with running slots' decode ticks (TPOT keeps flowing)
    # instead of stalling them behind one monolithic prefill
    long_prompt = list(rng.randint(0, V, 24))
    short_prompt = list(rng.randint(0, V, 4))

    def run_chunk(chunk):
        eng = DecodeEngine(model, vs, max_slots=2, block_size=4,
                           prefill_chunk=chunk)
        sched = ContinuousBatchingScheduler(eng)
        short = sched.submit(list(short_prompt), 24)
        for _ in range(3):
            sched.step()
        before = len(short.tokens)
        long_req = sched.submit(long_prompt, 2)
        while long_req.first_token_ts is None and sched.step():
            pass
        interleaved = len(short.tokens) - before
        sched.run()
        return interleaved, short.tokens, long_req.tokens, eng

    il_chunk, short_c, long_c, eng_ck = run_chunk(6)
    il_full, short_f, long_f, _ = run_chunk(None)
    chunk_leg = {
        "interleaved_tokens_chunked": il_chunk,
        "interleaved_tokens_monolithic": il_full,
        "tokens_identical": short_c == short_f and long_c == long_f,
        "prefill_chunks": eng_ck.prefill_chunks,
        "compile_counts": eng_ck.compile_counts(),
    }
    chunk_ok = (chunk_leg["tokens_identical"]
                and chunk_leg["interleaved_tokens_chunked"]
                > chunk_leg["interleaved_tokens_monolithic"]
                and chunk_leg["compile_counts"]
                == {"prefill": 1, "tick": 1})

    # --- ISSUE 14 leg (d): int8 KV quantization — at EQUAL pool bytes
    # the int8 pool serves >= 1.8x the resident sequences, a saturated
    # workload still completes every request, and greedy tokens agree
    # >= 99% with the f32 pool on the gate set (bounded drift)
    res_len, res_reserve = 5, 12            # 3 blocks per sequence
    from paddle_tpu.serve import PagedKVCache

    def pool_blocks(kv_dtype, budget_bytes):
        probe = PagedKVCache(num_layers=2, num_heads=4, head_dim=8,
                             num_blocks=2, block_size=4, max_slots=1,
                             max_blocks_per_seq=8, kv_dtype=kv_dtype)
        return budget_bytes // probe.bytes_per_block, \
            probe.kv_bytes_per_token

    budget = pool_blocks(None, 0)[1] * 4 * (6 * 3)   # 6 f32 sequences

    def count_resident(kv_dtype):
        nb, bpt = pool_blocks(kv_dtype, budget)
        eng = DecodeEngine(model, vs, max_slots=16, block_size=4,
                           num_blocks=nb + 1, kv_dtype=kv_dtype)
        resident = 0
        while (eng.free_slots()
               and eng.can_admit(res_reserve)):
            slot = eng.free_slots()[0]
            eng.admit(slot, list(rng.randint(0, V, res_len)),
                      reserve_len=res_reserve)
            resident += 1
        return resident, nb, bpt

    res_f32, nb_f32, bpt_f32 = count_resident(None)
    res_i8, nb_i8, bpt_i8 = count_resident("int8")

    def run_quant(kv_dtype):
        eng = DecodeEngine(model, vs, max_slots=4, block_size=4,
                           kv_dtype=kv_dtype)
        sched = ContinuousBatchingScheduler(eng)
        reqs = [sched.submit(p, m) for p, m in zip(prompts, maxnew)]
        sched.run()
        return [r.tokens for r in reqs], eng

    toks_f32, _ = run_quant(None)
    toks_i8, eng_i8 = run_quant("int8")
    agree = sum(a == b for x, y in zip(toks_f32, toks_i8)
                for a, b in zip(x, y))
    total = sum(len(x) for x in toks_f32)
    quant_leg = {
        "pool_budget_bytes": int(budget),
        "resident_f32": res_f32, "resident_int8": res_i8,
        "capacity_ratio": round(res_i8 / res_f32, 3) if res_f32 else None,
        "kv_bytes_per_token_f32": int(bpt_f32),
        "kv_bytes_per_token_int8": int(bpt_i8),
        "completed": sum(1 for t in toks_i8 if t),
        "token_agreement": round(agree / total, 4) if total else None,
        "compile_counts": eng_i8.compile_counts(),
    }
    quant_ok = (quant_leg["capacity_ratio"] is not None
                and quant_leg["capacity_ratio"] >= 1.8
                and quant_leg["completed"] == 8
                and quant_leg["token_agreement"] >= 0.99
                and quant_leg["compile_counts"]
                == {"prefill": 1, "tick": 1})

    # --- ISSUE 14 leg (e): radix retention — a SECOND wave of
    # same-prefix sessions (no live sharer) hits retained blocks and
    # allocates fewer fresh blocks than a retention-off engine; the
    # pool stays leak-free with retained counted reclaimable
    ret_pre = list(rng.randint(0, V, 8))
    ret_tails = [list(rng.randint(0, V, 3)) for _ in range(4)]

    def run_retention(retain):
        eng = DecodeEngine(model, vs, max_slots=2, block_size=4,
                           retain_prefix=retain)
        allocs = []
        for i in range(2):               # two sequential waves
            sched = ContinuousBatchingScheduler(eng)
            for t in ret_tails[2 * i:2 * i + 2]:
                sched.submit(ret_pre + list(t), 4)
            sched.run()
            allocs.append(eng.cache.allocator.total_allocs)
        return eng, allocs[1] - allocs[0]      # wave-2 fresh allocs

    eng_ret, wave2_on = run_retention(True)
    eng_off2, wave2_off = run_retention(False)
    ret_leg = {
        "retained_hits": eng_ret.cache.retained_hits,
        "wave2_fresh_allocs_retained": wave2_on,
        "wave2_fresh_allocs_unretained": wave2_off,
        "retained_blocks_now": eng_ret.cache.retained_blocks,
        "leak_free": eng_ret.cache.free_blocks
        == eng_ret.cache.num_blocks - 1,
        "compile_counts": eng_ret.compile_counts(),
    }
    ret_ok = (ret_leg["retained_hits"] >= 1
              and ret_leg["wave2_fresh_allocs_retained"]
              < ret_leg["wave2_fresh_allocs_unretained"]
              and ret_leg["leak_free"]
              and ret_leg["compile_counts"] == {"prefill": 1, "tick": 1})

    # --- ISSUE 15 leg (f): tensor-parallel sharded tick — the tp=2
    # engine (2 forced host devices) is token-identical to the
    # single-device engine on the ragged churn workload across TWO
    # waves on one engine (wave 2 pins zero retraces), per-shard KV
    # bytes halve (capacity at equal per-device pool bytes doubles),
    # and the tick's tp collectives classify into the serving comm
    # table of the attribution report.
    from jax.sharding import Mesh
    tp_mesh = Mesh(np.asarray(jax.devices()[:2]), ("model",))

    def run_tp(mesh):
        eng = DecodeEngine(model, vs, max_slots=4, block_size=4,
                           mesh=mesh)
        toks = []
        for _ in range(2):
            sched = ContinuousBatchingScheduler(eng)
            reqs = [sched.submit(p, m) for p, m in zip(prompts, maxnew)]
            sched.run()
            toks.append([r.tokens for r in reqs])
        return toks, eng

    toks_tp, eng_tp = run_tp(tp_mesh)
    toks_1d, eng_1d = run_tp(None)
    tp_comm = (eng_tp.attribution_report(emit=False).get("decode")
               or {}).get("comm") or {}
    tp_leg = {
        "tokens_identical": toks_tp == toks_1d,
        "tp_degree": eng_tp.tp_degree,
        "compile_counts": eng_tp.compile_counts(),
        "kv_bytes_per_token_tp": eng_tp.cache.kv_bytes_per_token,
        "kv_bytes_per_token_1dev": eng_1d.cache.kv_bytes_per_token,
        # per-shard capacity ratio: blocks a device's HBM budget holds
        # under tp vs alone (the head split's whole capacity story)
        "per_shard_capacity_ratio": round(
            eng_1d.cache.kv_bytes_per_token
            / eng_tp.cache.kv_bytes_per_token, 3),
        "decode_comm_ops": tp_comm.get("ops", 0),
        "decode_comm_kinds": tp_comm.get("kinds"),
        "leak_free": eng_tp.cache.free_blocks
        == eng_tp.cache.num_blocks - 1,
    }
    tp_ok = (tp_leg["tokens_identical"] and tp_leg["tp_degree"] == 2
             and tp_leg["compile_counts"] == {"prefill": 1, "tick": 1}
             and tp_leg["per_shard_capacity_ratio"] >= 2.0
             and tp_leg["decode_comm_ops"] >= 1
             and tp_leg["leak_free"])

    ok = (cont["completed"] == 8 and stat["completed"] == 8
          and no_retrace and records_ok
          and cont["tokens_per_sec"] > stat["tokens_per_sec"]
          and cont["ticks"] < stat["ticks"]
          and decode_block.get("bound") == "memory"
          and share_ok and spec_ok and chunk_ok and quant_ok and ret_ok
          and tp_ok)
    print(json.dumps({
        "child": "serving", "ok": bool(ok),
        "requests": 8, "max_slots": 4, "block_size": 4,
        "continuous": cont, "static": stat,
        "continuous_vs_static": round(
            cont["tokens_per_sec"] / stat["tokens_per_sec"], 3)
        if stat["tokens_per_sec"] else None,
        "zero_retraces_after_warmup": bool(no_retrace),
        "decode_bound": decode_block.get("bound"),
        "decode_intensity_flops_per_byte":
            decode_block.get("intensity_flops_per_byte"),
        "prefix_sharing": {**share_leg, "ok": bool(share_ok)},
        "speculative": {**spec_leg, "ok": bool(spec_ok)},
        "chunked_prefill": {**chunk_leg, "ok": bool(chunk_ok)},
        "quantization": {**quant_leg, "ok": bool(quant_ok)},
        "retention": {**ret_leg, "ok": bool(ret_ok)},
        "tp": {**tp_leg, "ok": bool(tp_ok)},
        "device": jax.devices()[0].device_kind,
    }))
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# elastic fault-tolerance gate child (ISSUE 10): supervised crash/corrupt/
# preempt recovery on CPU, bit-equal to the uninterrupted run
# ---------------------------------------------------------------------------

def run_faults_child():
    """The resilience layer's CI gate: a tiny fused transformer training
    run under ``run_resilient`` with a seeded :class:`FaultSchedule`,
    three legs —

    - **crash+resume**: an injected crash mid pass 2; the supervisor
      restarts, ``resume=True`` picks up the newest checkpoint, and the
      final params are BIT-EQUAL (f32) to the uninterrupted 3-pass run.
    - **corrupt latest pass**: pass 1's landed checkpoint gets a byte
      flipped (CRC now stale), then a crash in pass 2; the resume
      quarantines ``pass-00001`` to ``pass-00001.corrupt`` (never
      deletes), falls back to pass 0, replays, and still finishes
      bit-equal.
    - **preempt mid-pass**: an injected preemption quiesces at the next
      group boundary, writes a mid-pass checkpoint, and exits with the
      distinct ``"preempted"`` status; a second supervised run resumes
      from it and finishes bit-equal.

    Prints the verdict as one JSON line."""
    import glob
    import tempfile
    from paddle_tpu import optim
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn import costs
    from paddle_tpu.train import FaultSchedule, Trainer, run_resilient

    V, T, bs, n_batches = 64, 16, 8, 8
    rng = np.random.RandomState(0)
    batches = [{"x": rng.randint(0, V, (bs, T)).astype(np.int32),
                "y": rng.randint(0, V, (bs, T)).astype(np.int32)}
               for _ in range(n_batches)]
    reader = lambda: iter(batches)       # noqa: E731 - deterministic replay

    def make_tr(faults=None):
        tr = Trainer(
            model=TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                                ffn_hidden=64, max_len=T),
            loss_fn=lambda out, b: costs.softmax_cross_entropy(
                out.reshape(-1, V), b["y"].reshape(-1)),
            optimizer=optim.adam(1e-3), steps_per_call=2, faults=faults)
        tr.init(jax.random.PRNGKey(0), batches[0])
        return tr

    def leaves(state):
        return jax.tree_util.tree_leaves(jax.device_get(state.params))

    def equal(a, b):
        return all(np.array_equal(np.asarray(x), np.asarray(y))
                   for x, y in zip(a, b))

    root = tempfile.mkdtemp(prefix="paddle_tpu_faults_")
    passes, steps_per_pass = 3, n_batches          # M=1: one step per batch

    base = make_tr()
    base.train(reader, num_passes=passes,
               checkpoint_dir=os.path.join(root, "base"), log_period=0)
    p0 = leaves(base.train_state)

    # leg A: crash mid pass 2 -> restart -> resume -> bit-equal. ONE
    # schedule instance shared across attempts: the one-shot disarm is
    # what makes the fault transient (a fresh schedule per attempt would
    # model a deterministic bug — give-up-loud territory).
    crash_step = 2 * steps_per_pass + 3
    fs_a = FaultSchedule(crash_at_step=crash_step)
    res_a = run_resilient(
        lambda: make_tr(fs_a), reader,
        checkpoint_dir=os.path.join(root, "crash"), num_passes=passes,
        log_period=0, backoff_s=0.01)
    leg_a = {"status": res_a.status, "restarts": res_a.restarts,
             "params_equal": equal(p0, leaves(res_a.state))}

    # leg B: corrupt pass-1's checkpoint (save idx 1), crash in pass 2 ->
    # quarantine + fall back one pass -> bit-equal
    ck_b = os.path.join(root, "corrupt")
    fs_b = FaultSchedule(corrupt_checkpoint_file=1,
                         crash_at_step=crash_step)
    res_b = run_resilient(
        lambda: make_tr(fs_b), reader,
        checkpoint_dir=ck_b, num_passes=passes, log_period=0,
        backoff_s=0.01)
    leg_b = {"status": res_b.status, "restarts": res_b.restarts,
             "fallbacks": len(res_b.fallbacks),
             "corrupt_dirs": len(glob.glob(os.path.join(ck_b,
                                                        "*.corrupt*"))),
             "params_equal": equal(p0, leaves(res_b.state))}

    # leg C: preempt mid pass 1 (graceful stop at the group boundary,
    # quiesced mid-pass checkpoint) -> distinct status -> resume finishes
    ck_c = os.path.join(root, "preempt")
    fs_c = FaultSchedule(preempt_at_step=steps_per_pass + 3)
    res_c1 = run_resilient(
        lambda: make_tr(fs_c),
        reader, checkpoint_dir=ck_c, num_passes=passes, saving_period=4,
        log_period=0, backoff_s=0.01)
    res_c2 = run_resilient(
        make_tr, reader, checkpoint_dir=ck_c, num_passes=passes,
        saving_period=4, log_period=0, backoff_s=0.01)
    leg_c = {"first_status": res_c1.status,
             "preempt_next_batch": (res_c1.preempted.next_batch
                                    if res_c1.preempted else None),
             "second_status": res_c2.status,
             "params_equal": equal(p0, leaves(res_c2.state))}

    ok = (leg_a["status"] == "completed" and leg_a["restarts"] == 1
          and leg_a["params_equal"]
          and leg_b["status"] == "completed" and leg_b["restarts"] == 1
          and leg_b["fallbacks"] >= 1 and leg_b["corrupt_dirs"] >= 1
          and leg_b["params_equal"]
          and leg_c["first_status"] == "preempted"
          and leg_c["second_status"] == "completed"
          and leg_c["params_equal"])
    print(json.dumps({
        "child": "faults", "ok": bool(ok),
        "passes": passes, "steps_per_pass": steps_per_pass,
        "crash": leg_a, "corrupt": leg_b, "preempt": leg_c,
        "device": jax.devices()[0].device_kind,
    }))
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# serving-fleet resilience gate child (ISSUE 11): loadgen burst over 3
# in-process replicas with one injected kill + one drain, plus the
# SJF-vs-FCFS goodput differential under a deterministic clock
# ---------------------------------------------------------------------------

def run_fleet_child():
    """The serving fleet's CI gate, five legs on a SimClock —

    - **fault drill**: a seeded bursty loadgen trace (sessions with
      shared prefixes, ragged lengths, deadlines) over 3 replicas; a
      FaultSchedule kills replica 0 mid-decode and replica 1 is drained
      mid-traffic. Asserts: every request reaches a terminal
      finish_reason with exactly one terminal record per rid (retried
      lineage for the killed replica's requests), p99 TTFT finite, the
      shed count bounded, zero retraces and zero leaked KV blocks on
      every surviving replica.
    - **SLO policy differential**: the same overload (2 long jobs ahead
      of 4 short deadline-carrying jobs, one engine, fixed 1s ticks)
      under order="fcfs" vs order="sjf" — SJF's goodput-under-deadline
      must beat FCFS's, reported through the new percentile metrics.
    - **process-isolation drill** (ISSUE 13): two replicas as REAL
      child processes behind the submit/complete transport; the
      schedule hangs one transport reply (per-message timeout +
      retransmit recovers the cached reply), garbles another
      (classified corrupt, recovered), then SIGKILLs replica 0
      mid-decode — the router never crashes, death is observed via
      heartbeat staleness, every request stays terminal with one
      terminal record per rid and oracle-identical tokens, the live
      survivors are leak- and retrace-free (evidence from each child's
      own stats probe), and the autoscaler cold-spawns a replacement
      within its restart budget.
    - **observability drill** (ISSUE 17): the SAME process-mode
      SIGKILL-resubmit shape run twice — once fully instrumented
      (tracing + SLO + serving anomaly detection + child telemetry
      JSONL sinks), once with everything off. Asserts the merged fleet
      trace JSON-round-trips with ≥2 replica lanes plus the router
      lane and the killed-and-resubmitted rid renders as ONE connected
      s→t→f flow across processes; the streaming SLO report has finite
      percentiles and a burn rate in ``stats()``; an injected stall
      fires the ``tick_stall`` anomaly and dumps a forensic bundle;
      the killed child's JSONL telemetry survives its SIGKILL; and the
      instrumented run's tokens and finish reasons are IDENTICAL to
      the dark run's — observability changes nothing it observes.
    - **disaggregation drill** (ISSUE 18): 1 prefill + 2 decode
      replicas as SOCKET children on loopback — every request prefills
      on the prefill replica, streams its KV pages over TCP and decodes
      the greedy oracle's exact tokens, with the handoff wire bytes
      matching the analytic blocks x bytes-per-block accounting; then
      in-process role fleets measure the disaggregation CLAIM (decode
      tokens/tick within 25% when heavy prefill-only load is added) and
      the int8 path (identical tokens to colocated int8, ~2.7x fewer
      wire bytes per block than f32).
    - **chaos drill** (ISSUE 20): the disagg socket fleet again, under
      a seeded :class:`NetworkChaos` plane — an asymmetric partition
      cuts the prefill replica's reply direction (false death → fence
      by epoch → disagg degrades to colocated prefill on the decoders)
      and a one-shot link flap fences a decode replica. Asserts every
      request terminal with oracle tokens and a single lineage, zero
      tokens from any fenced epoch, both zombies re-admitted on heal,
      the degradation engaged AND released, survivors leak-free, and
      the chaos fleet's ``stats()`` keyset differing from the chaos-off
      socket fleet's (leg 5a — the dark twin) by exactly ``{"chaos"}``.

    Prints the verdict as one JSON line."""
    import collections
    import tempfile
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.obs import InMemorySink, Telemetry, summarize_requests
    from paddle_tpu.serve import (Autoscaler, ContinuousBatchingScheduler,
                                  DecodeEngine, ServingFleet, SimClock)
    from paddle_tpu.serve.loadgen import make_workload, workload_stats
    from paddle_tpu.train import FaultSchedule

    V, W = 64, 32
    model = TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                          ffn_hidden=64, max_len=W)
    vs = model.init(jax.random.PRNGKey(0), jnp.zeros((1, W), jnp.int32))

    # -- leg 1: the fleet fault drill
    mem = InMemorySink()
    clock = SimClock()
    faults = FaultSchedule(kill_replica_at_tick=(6, 0))
    fleet = ServingFleet.from_model(
        model, vs, 3, engine_kwargs=dict(max_slots=2, block_size=4),
        telemetry=Telemetry(sinks=[mem]), clock=clock,
        heartbeat_timeout_s=0.25, est_tick_s=0.1, faults=faults,
        root=tempfile.mkdtemp(prefix="paddle_tpu_fleet_gate_"))
    wl = make_workload(14, V, seed=3, rate_rps=30.0, arrival="bursty",
                       prompt_len=(2, 8), max_new=(2, 10), n_sessions=3,
                       session_prefix_len=4, p_session=0.5,
                       deadline_s=(2.0, 6.0), p_deadline=0.5,
                       max_total=W)
    frs = fleet.play(wl, dt_s=0.1, drain_at_tick={10: 1})
    stats = fleet.stats()
    summary = summarize_requests(mem.records)

    all_terminal = all(fr.record is not None for fr in frs)
    terminal_per_rid = collections.Counter(
        r["rid"] for r in mem.by_kind("request")
        if r["finish_reason"] != "retried")
    lineage_ok = (set(terminal_per_rid) == {fr.rid for fr in frs}
                  and all(v == 1 for v in terminal_per_rid.values()))
    survivors = [w for w in fleet.workers if not w.killed
                 and w.state != "dead"]
    no_leak = all(w.engine.cache.free_blocks
                  == w.engine.cache.num_blocks - 1 for w in survivors)
    no_retrace = all(
        w.engine.compile_counts() == {"prefill": 1, "tick": 1}
        for w in survivors if w.engine.ticks > 0)
    p99_finite = (summary["ttft_ms_p99"] is not None
                  and np.isfinite(summary["ttft_ms_p99"]))
    shed_bounded = 0 <= stats["shed"] <= len(frs) // 2

    # -- leg 2: SJF vs FCFS goodput differential (single engine, 1s ticks)
    def run_order(order):
        mem2 = InMemorySink()
        eng = DecodeEngine(model, vs, max_slots=2, block_size=4,
                           telemetry=Telemetry(sinks=[mem2]))
        clk = SimClock()
        sched = ContinuousBatchingScheduler(eng, order=order, clock=clk,
                                            est_tick_s=1.0)
        rng = np.random.RandomState(0)
        for _ in range(2):                         # stragglers first
            sched.submit(list(rng.randint(1, V, 4)), 12)
        for _ in range(4):                         # tight-deadline shorts
            sched.submit(list(rng.randint(1, V, 4)), 2, deadline_s=8.0)
        while sched.step():
            clk.advance(1.0)
        return summarize_requests(mem2.records)

    fcfs = run_order("fcfs")
    sjf = run_order("sjf")
    sjf_wins = (fcfs["goodput_pct"] is not None
                and sjf["goodput_pct"] is not None
                and sjf["goodput_pct"] > fcfs["goodput_pct"])

    # -- leg 3: process-isolated replicas + supervised autoscaler
    # (ISSUE 13). Transport faults first (hang -> timeout+retransmit,
    # corrupt -> classified+retransmit), then SIGKILL replica 0
    # mid-decode; min_replicas=2 makes the autoscaler cold-spawn a
    # replacement child when the death is observed.
    oracle_fwd = jax.jit(lambda v, i: model.apply(v, i))

    def greedy_oracle(prompt, n_new):
        seq, out = list(prompt), []
        for _ in range(n_new):
            pad = np.zeros((1, W), np.int32)
            pad[0, :len(seq)] = seq
            logits = oracle_fwd(vs, jnp.asarray(pad))
            tok = int(np.argmax(np.asarray(logits[0, len(seq) - 1])))
            out.append(tok)
            seq.append(tok)
        return out

    mem3 = InMemorySink()
    clock3 = SimClock()
    faults3 = FaultSchedule(sigkill_replica_at_tick=(6, 0),
                            transport_hang_at=(3, 1),
                            corrupt_reply_at=(4, 1))
    scaler = Autoscaler(min_replicas=2, max_replicas=3, up_delay_s=60.0,
                        idle_grace_ticks=1000, cooldown_ticks=5,
                        max_replacements=1)
    fleet3 = ServingFleet.from_model(
        model, vs, 2, engine_kwargs=dict(max_slots=2, block_size=4),
        replica_mode="process", telemetry=Telemetry(sinks=[mem3]),
        clock=clock3, heartbeat_timeout_s=0.25, est_tick_s=0.1,
        # generous per-message budget: a child's FIRST tick includes
        # its jit compiles, and a slow CI host must not turn that into
        # a false transport_down (only the injected hang pays it)
        faults=faults3, transport_timeout_s=5.0, autoscaler=scaler,
        root=tempfile.mkdtemp(prefix="paddle_tpu_fleet_proc_"))
    wl3 = make_workload(8, V, seed=7, rate_rps=30.0, prompt_len=(2, 6),
                        max_new=(3, 8), max_total=W)
    try:
        frs3 = fleet3.play(wl3, dt_s=0.1)
        stats3 = fleet3.stats()
        term3 = collections.Counter(
            r["rid"] for r in mem3.by_kind("request")
            if r["finish_reason"] != "retried")
        proc_all_terminal = all(fr.record is not None for fr in frs3)
        proc_lineage = (set(term3) == {fr.rid for fr in frs3}
                        and all(v == 1 for v in term3.values()))
        retried3 = [fr for fr in frs3 if fr.retries > 0]
        # re-homed requests regenerate the oracle's exact tokens —
        # process isolation is semantically invisible
        oracle_ok = all(
            fr.tokens == greedy_oracle(fr.prompt, fr.max_new_tokens)
            for fr in (retried3[:2] or frs3[:2]))
        probes = {w.replica_id: w.stats_probe(clock3())
                  for w in fleet3.workers
                  if w.state == "live" and not w.killed}
        proc_no_leak = bool(probes) and all(
            p is not None and p["free_blocks"] == p["num_blocks"] - 1
            for p in probes.values())
        proc_no_retrace = all(
            p["compile_counts"] == {"prefill": 1, "tick": 1}
            for p in probes.values()
            if p is not None and p["ticks"] > 0)
        transports = {w.replica_id: w.transport_stats()
                      for w in fleet3.workers
                      if w.transport_stats() is not None}
        hang_recovered = any(t["timeouts"] >= 1 and t["retransmits"] >= 1
                             for t in transports.values())
        corrupt_classified = any(t["corrupt_replies"] >= 1
                                 for t in transports.values())
        replaced = any(e["action"] == "replace" for e in scaler.events)
        proc = {
            "ok": bool(proc_all_terminal and proc_lineage and oracle_ok
                       and proc_no_leak and proc_no_retrace
                       and hang_recovered and corrupt_classified
                       and replaced
                       and stats3["stale_completions"] == 0
                       and stats3["resubmits"] >= 1
                       and scaler.replacements <= 1),
            "all_terminal": bool(proc_all_terminal),
            "lineage_ok": bool(proc_lineage),
            "oracle_tokens_ok": bool(oracle_ok),
            "no_leak_on_survivors": bool(proc_no_leak),
            "zero_retraces_on_survivors": bool(proc_no_retrace),
            "transport_hang_recovered": bool(hang_recovered),
            "corrupt_reply_classified": bool(corrupt_classified),
            "replacement_spawned": bool(replaced),
            "replacements_within_budget": scaler.replacements,
            "retried_requests": len(retried3),
            "transports": transports,
            "scale_events": [{k: e[k] for k in
                              ("action", "reason", "tick",
                               "replicas_before", "replicas_after")}
                             for e in scaler.events],
            "stats": stats3,
            "faults_fired": [p for p, _ in faults3.fired],
        }
    finally:
        fleet3.shutdown()

    # -- leg 4: fleet observability drill (ISSUE 17) — the same
    # SIGKILL-resubmit shape traced and dark, compared
    from paddle_tpu.obs import ServingAnomalyDetector
    from paddle_tpu.obs.fleet_trace import flow_connected, lane_monotonic

    def run_obs_drill(instrumented):
        mem4 = InMemorySink()
        clock4 = SimClock()
        faults4 = FaultSchedule(sigkill_replica_at_tick=(6, 0),
                                stall_replica_at_tick=(8, 1, 3))
        root4 = tempfile.mkdtemp(prefix="paddle_tpu_fleet_obs_")
        anom = (ServingAnomalyDetector(
                    out_dir=os.path.join(root4, "anomalies"),
                    stall_ticks=2)
                if instrumented else None)
        # heartbeat timeout ABOVE the injected stall (3 ticks = 0.3s
        # plus the wake tick): the stall must fire the tick_stall
        # anomaly, not the death verdict — replica 1 is the sole
        # survivor once replica 0 is SIGKILLed
        f = ServingFleet.from_model(
            model, vs, 2, engine_kwargs=dict(max_slots=2, block_size=4),
            replica_mode="socket", telemetry=Telemetry(sinks=[mem4]),
            clock=clock4, heartbeat_timeout_s=0.55, est_tick_s=0.1,
            faults=faults4, transport_timeout_s=5.0, root=root4,
            trace=instrumented, slo=instrumented, anomaly=anom,
            metrics=instrumented,
            telemetry_dir=(os.path.join(root4, "child_telemetry")
                           if instrumented else None))
        wl4 = make_workload(8, V, seed=7, rate_rps=30.0,
                            prompt_len=(2, 6), max_new=(3, 8),
                            max_total=W)
        scrape = None
        try:
            frs4 = f.play(wl4, dt_s=0.1)
            if instrumented:
                # remote scrape over the live socket: the survivor
                # (replica 0 was SIGKILLed) serves its own registry as
                # text exposition via the `metrics` transport op
                scrape = f.workers[1].scrape_metrics(clock4())
        finally:
            f.shutdown()
        return f, frs4, anom, root4, scrape

    fleet_tr, frs_tr, anom4, root_tr, scrape4 = run_obs_drill(True)
    fleet_dk, frs_dk, _, _, _ = run_obs_drill(False)

    trace4 = fleet_tr.fleet_trace()
    trace4 = json.loads(json.dumps(trace4))      # Chrome-parseable
    lanes = sorted({e.get("pid") for e in trace4["traceEvents"]
                    if e.get("ph") != "M"})
    lanes_ok = 0 in lanes and len([p for p in lanes if p > 0]) >= 2
    retried4 = [fr.rid for fr in frs_tr if fr.retries > 0]
    resub_flow_ok = bool(retried4) and all(
        flow_connected(trace4, r) for r in retried4)
    slo4 = fleet_tr.slo_report()
    stats4 = fleet_tr.stats()
    slo_ok = (slo4["wall_ms_p99"] is not None
              and np.isfinite(slo4["wall_ms_p99"])
              and "burn_rate" in stats4.get("slo", {}))
    stall_fired = any(v.kind == "tick_stall" for v in anom4.verdicts)
    bundle_ok = stall_fired and any(
        "tick_stall" in d for d in (
            os.listdir(os.path.join(root_tr, "anomalies"))
            if os.path.isdir(os.path.join(root_tr, "anomalies"))
            else []))
    # the SIGKILLed child's line-flushed JSONL outlives its process
    killed_jsonl = os.path.join(root_tr, "child_telemetry",
                                "replica_0.jsonl")
    jsonl_ok = (os.path.isfile(killed_jsonl)
                and os.path.getsize(killed_jsonl) > 0)
    # instrumentation must not change the work: identical tokens and
    # finish reasons per rid against the dark run
    tok_tr = {fr.rid: (fr.finish_reason, list(fr.tokens))
              for fr in frs_tr}
    tok_dk = {fr.rid: (fr.finish_reason, list(fr.tokens))
              for fr in frs_dk}
    dark_identical = tok_tr == tok_dk
    # metrics backbone (ISSUE 19): the instrumented socket drill's
    # merged registry must hold per-link RTT histograms with nonzero
    # counts for every link (parent-side wire health), per-replica
    # engine tick histograms absorbed from the children's piggybacked
    # deltas, and a parseable Prometheus exposition; the dark twin must
    # carry no registry and — beyond the slo/anomaly blocks the
    # instrumented run opts into — no new stats keys.
    from paddle_tpu.obs.metrics import parse_exposition
    snapm = fleet_tr.metrics.snapshot()

    def _hist_count(name, lkey, lval):
        return sum(r.get("count") or 0 for r in snapm
                   if r["name"] == name
                   and r["labels"].get(lkey) == lval)

    links_ok = all(_hist_count("transport_rtt_ms", "link", l) > 0
                   for l in ("0", "1"))
    ticks_ok = all(_hist_count("engine_tick_ms", "replica", r) > 0
                   for r in ("0", "1"))
    expo4 = parse_exposition(fleet_tr.metrics.render())
    expo_ok = (len(expo4["samples"]) > 0
               and expo4["types"].get("transport_rtt_ms") == "histogram"
               and expo4["types"].get("fleet_ticks") == "counter")
    scraped = parse_exposition(scrape4 or "")
    scrape_ok = (len(scraped["samples"]) > 0
                 and scraped["types"].get("engine_ticks") == "counter")
    new_keys = set(stats4) - set(fleet_dk.stats())
    keys_ok = (new_keys == {"slo", "anomalies"}
               and fleet_dk.metrics is None)
    metrics4 = {
        "ok": bool(links_ok and ticks_ok and expo_ok and scrape_ok
                   and keys_ok),
        "remote_scrape_samples": len(scraped["samples"]),
        "per_link_rtt_counts": {
            l: _hist_count("transport_rtt_ms", "link", l)
            for l in ("0", "1")},
        "per_replica_tick_counts": {
            r: _hist_count("engine_tick_ms", "replica", r)
            for r in ("0", "1")},
        "exposition_samples": len(expo4["samples"]),
        "new_stats_keys": sorted(new_keys),
        "registry_rows": len(snapm),
    }
    tracing = {
        "ok": bool(lanes_ok and resub_flow_ok and slo_ok and bundle_ok
                   and jsonl_ok and dark_identical and metrics4["ok"]
                   and lane_monotonic(trace4)),
        "metrics": metrics4,
        "lanes": lanes,
        "resubmitted_rids": retried4,
        "resubmit_flow_connected": bool(resub_flow_ok),
        "lane_monotonic": bool(lane_monotonic(trace4)),
        "trace_events": len(trace4["traceEvents"]),
        "slo": {k: slo4[k] for k in
                ("requests", "goodput_pct", "burn_rate", "ttft_ms_p99",
                 "wall_ms_p99")},
        "tick_stall_fired": bool(stall_fired),
        "anomaly_bundle": bool(bundle_ok),
        "killed_child_jsonl_survives": bool(jsonl_ok),
        "identical_to_uninstrumented": bool(dark_identical),
    }

    # -- leg 5: prefill/decode disaggregation (ISSUE 18) — sockets on
    # loopback for the real cross-host shape, in-process fleets for the
    # cheap differential measurements.
    #
    # 5a: 1 prefill + 2 decode replicas as socket children. Every
    # request must prefill on the prefill replica, stream its KV pages
    # over TCP, and decode to the greedy oracle's EXACT tokens; the
    # wire bytes must equal blocks x the analytic per-block size.
    f32_block = 2 * 2 * 4 * 4 * 8 * 4       # 2(kv) L H BS hd f32
    int8_block = 2 * 2 * 4 * 4 * (8 + 4)    # int8 values + f32 scales
    sock_fleet = ServingFleet.from_model(
        model, vs, 3, engine_kwargs=dict(max_slots=2, block_size=4),
        replica_mode="socket", roles=["prefill", "decode", "decode"],
        clock=SimClock(), heartbeat_timeout_s=0.25, est_tick_s=0.1,
        transport_timeout_s=10.0,
        root=tempfile.mkdtemp(prefix="paddle_tpu_fleet_sock_"))
    rng5 = np.random.RandomState(5)
    try:
        frs5 = [sock_fleet.submit(list(rng5.randint(1, V, int(p))), 5)
                for p in rng5.randint(2, 8, 6)]
        for _ in range(300):
            if not sock_fleet.outstanding():
                break
            sock_fleet.tick()
            sock_fleet.clock.advance(0.1)
        stats5 = sock_fleet.stats()
        sock_terminal = all(fr.record is not None for fr in frs5)
        sock_oracle = all(
            fr.finish_reason == "length"
            and fr.tokens == greedy_oracle(fr.prompt, fr.max_new_tokens)
            for fr in frs5)
        sock_roles = all(fr.attempts[0] == 0 and fr.replica in (1, 2)
                         for fr in frs5)
        sock_wire_exact = (
            stats5["handoffs"] == len(frs5)
            and stats5["handoff_wire_bytes"]
            == stats5["handoff_blocks"] * f32_block)
    finally:
        sock_fleet.shutdown()

    # 5b: decode isolation under prefill load — the disaggregation
    # claim, measured. The same decode jobs run twice on in-process
    # role fleets; run B adds heavy prefill-only jobs (long prompts,
    # max_new=1 finishes at prefill, no handoff). Decode throughput —
    # ticks until the decode jobs all finish — must hold within 25%.
    def run_disagg(extra_prefill, kv_dtype=None):
        ek = dict(max_slots=2, block_size=4)
        if kv_dtype:
            ek["kv_dtype"] = kv_dtype
        f5 = ServingFleet.from_model(
            model, vs, 3, engine_kwargs=ek,
            roles=["prefill", "decode", "decode"], clock=SimClock(),
            heartbeat_timeout_s=0.25, est_tick_s=0.1,
            root=tempfile.mkdtemp(prefix="paddle_tpu_fleet_disagg_"))
        r = np.random.RandomState(9)
        decode_jobs = [f5.submit(list(r.randint(1, V, 4)), 6)
                       for _ in range(6)]
        if extra_prefill:
            for _ in range(8):
                f5.submit(list(r.randint(1, V, 20)), 1)
        done_at = None
        for _ in range(400):
            if done_at is None and all(fr.record is not None
                                       for fr in decode_jobs):
                done_at = f5.ticks
            if not f5.outstanding():
                break
            f5.tick()
            f5.clock.advance(0.1)
        if done_at is None and all(fr.record is not None
                                   for fr in decode_jobs):
            done_at = f5.ticks
        st = f5.stats()
        toks = sum(len(fr.tokens) for fr in decode_jobs)
        return {"fleet": f5, "stats": st, "decode_jobs": decode_jobs,
                "decode_done_tick": done_at,
                "decode_tok_per_tick": (toks / done_at
                                        if done_at else None)}

    base = run_disagg(extra_prefill=False)
    loaded = run_disagg(extra_prefill=True)
    iso_ratio = (loaded["decode_tok_per_tick"]
                 / base["decode_tok_per_tick"]
                 if base["decode_tok_per_tick"]
                 and loaded["decode_tok_per_tick"] else None)
    iso_ok = (iso_ratio is not None and iso_ratio >= 0.75
              and all(fr.tokens == base["decode_jobs"][i].tokens
                      for i, fr in enumerate(loaded["decode_jobs"])))

    # 5c: int8 KV crosses the wire quantized — identical tokens to the
    # colocated int8 fleet, ~2.7x fewer bytes per block than f32
    q5 = run_disagg(extra_prefill=False, kv_dtype="int8")
    colo5 = ServingFleet.from_model(
        model, vs, 2,
        engine_kwargs=dict(max_slots=2, block_size=4, kv_dtype="int8"),
        clock=SimClock(), heartbeat_timeout_s=0.25, est_tick_s=0.1,
        root=tempfile.mkdtemp(prefix="paddle_tpu_fleet_colo8_"))
    rq = np.random.RandomState(9)
    colo_jobs = [colo5.submit(list(rq.randint(1, V, 4)), 6)
                 for _ in range(6)]
    for _ in range(400):
        if not colo5.outstanding():
            break
        colo5.tick()
        colo5.clock.advance(0.1)
    q_stats = q5["stats"]
    quant_identical = all(
        a.tokens == b.tokens and a.finish_reason == b.finish_reason
        for a, b in zip(colo_jobs, q5["decode_jobs"]))
    q_wire_exact = (q_stats["handoffs"] >= 6
                    and q_stats["handoff_wire_bytes"]
                    == q_stats["handoff_blocks"] * int8_block)
    quant_wire_ratio = f32_block / int8_block    # 2.67x for hd=8
    disagg = {
        "ok": bool(sock_terminal and sock_oracle and sock_roles
                   and sock_wire_exact and iso_ok and quant_identical
                   and q_wire_exact
                   and stats5["router_ms"]["total"] > 0.0),
        "socket_all_terminal": bool(sock_terminal),
        "socket_oracle_tokens": bool(sock_oracle),
        "socket_role_placement": bool(sock_roles),
        "socket_wire_bytes_exact": bool(sock_wire_exact),
        "socket_handoffs": stats5["handoffs"],
        "socket_wire_bytes": stats5["handoff_wire_bytes"],
        "router_ms": stats5["router_ms"],
        "decode_tok_per_tick_base": base["decode_tok_per_tick"],
        "decode_tok_per_tick_loaded": loaded["decode_tok_per_tick"],
        "decode_isolation_ratio": iso_ratio,
        "decode_isolated_under_prefill_load": bool(iso_ok),
        "int8_tokens_identical_to_colocated": bool(quant_identical),
        "int8_wire_bytes_exact": bool(q_wire_exact),
        "int8_wire_ratio_vs_f32": quant_wire_ratio,
    }

    # -- leg 6: partition + flap chaos gate (ISSUE 20). The leg-5a
    # disagg socket fleet re-run under a seeded NetworkChaos plane:
    # link 0 (the only prefill) loses its REPLY direction for two fleet
    # seconds — the asymmetric partition: the child hears every frame,
    # the parent hears nothing — which manufactures a false death,
    # an epoch fence, and the disagg→colocated degradation; link 2
    # takes a single flap window that drops one tick exchange outright
    # and fences a decode replica the same way. Both zombies must be
    # re-admitted on heal having generated ZERO tokens under their
    # fenced epochs, every rid must keep exactly one terminal record
    # with oracle tokens, and the chaos-off leg-5a fleet is the dark
    # twin: same stats schema plus exactly the "chaos" ledger.
    from paddle_tpu.serve import LinkChaos, NetworkChaos
    chaos_plane = NetworkChaos(20, links={
        0: LinkChaos(partitions=[(0.25, 2.5, "recv")]),
        2: LinkChaos(flap=(50.0, 0.12, 0.9))})
    mem6 = InMemorySink()
    fleet6 = ServingFleet.from_model(
        model, vs, 3, engine_kwargs=dict(max_slots=2, block_size=4),
        replica_mode="socket", roles=["prefill", "decode", "decode"],
        chaos=chaos_plane, clock=SimClock(),
        heartbeat_timeout_s=0.25, est_tick_s=0.1, warmup=True,
        transport_timeout_s=0.75, readmit_grace_s=100.0,
        telemetry=Telemetry(sinks=[mem6]),
        root=tempfile.mkdtemp(prefix="paddle_tpu_fleet_chaos_"))
    rng6 = np.random.RandomState(6)
    try:
        frs6 = [fleet6.submit(list(rng6.randint(1, V, int(p))), 8)
                for p in rng6.randint(2, 8, 6)]
        late6 = []
        for _ in range(400):
            if not late6 and fleet6.clock() >= 1.5:
                # mid-degradation arrivals: routed straight to the
                # colocated decode path, no prefill replica alive
                late6 = [fleet6.submit(list(rng6.randint(1, V, 4)), 6)
                         for _ in range(2)]
            if (not fleet6.outstanding()
                    and fleet6.readmitted >= fleet6.fences
                    and not fleet6.degraded):
                break
            fleet6.tick()
            fleet6.clock.advance(0.1)
        frs6 += late6
        stats6 = fleet6.stats()
        mb6 = stats6["membership"]
        ch6 = stats6["chaos"]
        chaos_terminal = all(fr.record is not None for fr in frs6)
        chaos_oracle = all(
            fr.finish_reason == "length"
            and fr.tokens == greedy_oracle(fr.prompt, fr.max_new_tokens)
            for fr in frs6)
        term6 = collections.Counter(
            r["rid"] for r in mem6.by_kind("request")
            if r["finish_reason"] != "retried")
        chaos_lineage = (set(term6) == {fr.rid for fr in frs6}
                         and all(v == 1 for v in term6.values()))
        fenced6 = [w for w in fleet6.workers if w.readmit_info]
        zero_zombie_tokens = (
            len(fenced6) == fleet6.fences
            and all(w.readmit_info["tokens_while_fenced"] == 0
                    for w in fenced6))
        live6 = [w for w in fleet6.workers if w.state == "live"]
        chaos_no_leak = (len(live6) == 3 and all(
            w.engine.free_blocks == w.engine.num_blocks - 1
            for w in live6))
        degrade_cycle = (mb6["degradations"] >= 1
                         and mb6["degrade_releases"] >= 1
                         and not mb6["degraded"])
        chaos_evidence = (
            ch6["frames_dropped"] > 0
            and ch6["drop_reasons"].get("partition", 0) > 0
            and ch6["drop_reasons"].get("flap", 0) > 0)
        dark_twin_keys = set(stats6) - set(stats5) == {"chaos"}
    finally:
        fleet6.shutdown()
    chaos6 = {
        "ok": bool(chaos_terminal and chaos_oracle and chaos_lineage
                   and zero_zombie_tokens and chaos_no_leak
                   and degrade_cycle and chaos_evidence
                   and dark_twin_keys and fleet6.fences >= 2
                   and fleet6.readmitted >= fleet6.fences),
        "all_terminal": bool(chaos_terminal),
        "oracle_tokens": bool(chaos_oracle),
        "single_lineage": bool(chaos_lineage),
        "fences": fleet6.fences,
        "readmitted": fleet6.readmitted,
        "zero_tokens_while_fenced": bool(zero_zombie_tokens),
        "survivors_leak_free": bool(chaos_no_leak),
        "degradation_engaged_and_released": bool(degrade_cycle),
        "membership": mb6,
        "network": ch6,
        "stats_keys_vs_dark_twin": sorted(set(stats6) - set(stats5)),
    }

    ok = (all_terminal and lineage_ok and no_leak and no_retrace
          and p99_finite and shed_bounded and stats["resubmits"] >= 1
          and stats["stale_completions"] == 0 and sjf_wins
          and proc["ok"] and tracing["ok"] and disagg["ok"]
          and chaos6["ok"])
    print(json.dumps({
        "child": "fleet", "ok": bool(ok),
        "workload": workload_stats(wl),
        "all_terminal": bool(all_terminal),
        "lineage_ok": bool(lineage_ok),
        "no_leak_on_survivors": bool(no_leak),
        "zero_retraces_on_survivors": bool(no_retrace),
        "p99_ttft_finite": bool(p99_finite),
        "shed_bounded": bool(shed_bounded),
        "sjf_beats_fcfs_goodput": bool(sjf_wins),
        "goodput_fcfs_pct": fcfs["goodput_pct"],
        "goodput_sjf_pct": sjf["goodput_pct"],
        "stats": stats, "requests": summary,
        "faults_fired": [p for p, _ in faults.fired],
        "process": proc,
        "tracing": tracing,
        "disagg": disagg,
        "chaos": chaos6,
        "device": jax.devices()[0].device_kind,
    }))
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# serving decode throughput metric (ISSUE 9): steady-state tokens/sec
# through the compiled decode tick
# ---------------------------------------------------------------------------

def run_serving_bench_child(max_slots=8, block_size=16, seq_len=1024,
                            dim=512, layers=6, heads=8, vocab=32000,
                            prompt_len=128, warmup_ticks=8,
                            timed_ticks=64, kv_dtype=None):
    """The ``transformer_decode`` device metric: fill every slot with a
    long-running request, warm the tick, then time ``timed_ticks``
    compiled decode steps — steady-state serving throughput with the
    paged KV gather on the hot path (the decode-shaped attention auto-
    selects Pallas on TPU, the XLA gather path elsewhere).
    ``kv_dtype="int8"`` is the ``transformer_decode_int8`` variant
    (ISSUE 14): the same tick over a quantized pool, so the metric pair
    answers "what does halving-to-quartering KV HBM traffic buy the
    memory-bound tick". Prints one JSON line for the parent."""
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn.autotune import time_kernel
    from paddle_tpu.serve import DecodeEngine

    ffn = 4 * dim
    model = TransformerLM(vocab=vocab, dim=dim, num_layers=layers,
                          num_heads=heads, ffn_hidden=ffn, max_len=seq_len)
    vs = model.init(jax.random.PRNGKey(0),
                    jnp.zeros((1, seq_len), jnp.int32))
    eng = DecodeEngine(model, vs, max_slots=max_slots,
                       block_size=block_size, kv_dtype=kv_dtype)
    rng = np.random.RandomState(0)
    target = prompt_len + warmup_ticks + timed_ticks + 2
    assert target <= eng.context_width
    for slot in range(max_slots):
        eng.admit(slot, list(rng.randint(0, vocab, prompt_len)),
                  reserve_len=target)
    # decode_tick drains to host internally, so no extra fence is needed
    wall, _ = time_kernel(eng.decode_tick, warmup=warmup_ticks,
                          iters=timed_ticks, fence=None)
    tokens = timed_ticks * max_slots
    print(json.dumps({
        "child": ("transformer_decode" if kv_dtype is None
                  else "transformer_decode_int8"),
        "decode_tokens_per_sec": round(tokens / wall, 2),
        "ms_per_tick": round(wall / timed_ticks * 1e3, 3),
        "max_slots": max_slots, "block_size": block_size,
        "context_width": eng.context_width, "prompt_len": prompt_len,
        "timed_ticks": timed_ticks, "dim": dim, "layers": layers,
        "vocab": vocab, "attention": eng.attention,
        "kv_dtype": eng.cache.quant_dtype,
        "kv_bytes_per_token": eng.cache.kv_bytes_per_token,
        "compile_counts": eng.compile_counts(),
        "device": jax.devices()[0].device_kind,
    }))


def bench_serving(budget=None, kv_dtype=None):
    """Fresh-subprocess wrapper for run_serving_bench_child (one child =
    one tunnel session, like every other metric). ``kv_dtype="int8"``
    runs the quantized-pool variant."""
    metric = ("transformer_decode" if kv_dtype is None
              else "transformer_decode_int8")
    budget = budget or PLANS[metric]["budget"]
    r = _spawn_child(metric, 0, 1, budget)
    return {
        "metric": f"{metric}_tokens_per_sec",
        "unit": "tokens/sec",
        "value": r["decode_tokens_per_sec"],
        "ms_per_tick": r["ms_per_tick"],
        "max_slots": r["max_slots"], "block_size": r["block_size"],
        "context_width": r["context_width"],
        "prompt_len": r["prompt_len"], "dim": r["dim"],
        "layers": r["layers"], "attention": r["attention"],
        "kv_dtype": r["kv_dtype"],
        "kv_bytes_per_token": r["kv_bytes_per_token"],
        "device": r["device"],
        "baseline": None, "vs_baseline": None,
    }


def run_serving_tp_bench_child(max_slots=8, block_size=16, seq_len=1024,
                               dim=512, layers=6, heads=8, vocab=32000,
                               prompt_len=128, warmup_ticks=8,
                               timed_ticks=64):
    """The ``transformer_decode_tp`` metric (ISSUE 15): steady-state
    decode tokens/sec through the TENSOR-PARALLEL tick — the same
    full-slot workload as ``transformer_decode`` but with params
    megatron-placed and the KV pools head-sharded over a 2-device mesh.
    On real TPU the interesting number is the tick time at HALF the
    per-device KV/weight bytes (the capacity-latency trade tp buys); on
    the forced-CPU proxy it is a correctness/overhead gate. Prints one
    JSON line for the parent."""
    from jax.sharding import Mesh
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn.autotune import time_kernel
    from paddle_tpu.serve import DecodeEngine

    devs = jax.devices()
    if len(devs) < 2:
        raise RuntimeError(
            "transformer_decode_tp needs >= 2 devices (force with "
            "--xla_force_host_platform_device_count=2)")
    mesh = Mesh(np.asarray(devs[:2]), ("model",))
    ffn = 4 * dim
    model = TransformerLM(vocab=vocab, dim=dim, num_layers=layers,
                          num_heads=heads, ffn_hidden=ffn, max_len=seq_len)
    vs = model.init(jax.random.PRNGKey(0),
                    jnp.zeros((1, seq_len), jnp.int32))
    eng = DecodeEngine(model, vs, max_slots=max_slots,
                       block_size=block_size, mesh=mesh)
    rng = np.random.RandomState(0)
    target = prompt_len + warmup_ticks + timed_ticks + 2
    assert target <= eng.context_width
    for slot in range(max_slots):
        eng.admit(slot, list(rng.randint(0, vocab, prompt_len)),
                  reserve_len=target)
    wall, _ = time_kernel(eng.decode_tick, warmup=warmup_ticks,
                          iters=timed_ticks, fence=None)
    tokens = timed_ticks * max_slots
    print(json.dumps({
        "child": "transformer_decode_tp",
        "decode_tokens_per_sec": round(tokens / wall, 2),
        "ms_per_tick": round(wall / timed_ticks * 1e3, 3),
        "tp_degree": eng.tp_degree,
        "max_slots": max_slots, "block_size": block_size,
        "context_width": eng.context_width, "prompt_len": prompt_len,
        "timed_ticks": timed_ticks, "dim": dim, "layers": layers,
        "vocab": vocab, "attention": eng.attention,
        "kv_bytes_per_token_per_shard": eng.cache.kv_bytes_per_token,
        "compile_counts": eng.compile_counts(),
        "device": jax.devices()[0].device_kind,
        "n_devices": len(devs),
    }))


def bench_serving_tp(budget=None):
    """Fresh-subprocess wrapper for run_serving_tp_bench_child. The
    child needs >= 2 devices; when the driver environment has fewer
    (one real chip) it is spawned on a forced 2-virtual-device CPU
    platform — a correctness/overhead proxy, labelled in the record."""
    budget = budget or PLANS["transformer_decode_tp"]["budget"]
    forced = len(jax.devices()) < 2
    r = _spawn_child("transformer_decode_tp", 0, 1, budget,
                     env=_force_cpu_devices(os.environ, 2)
                     if forced else None)
    return {
        "metric": "transformer_decode_tp_tokens_per_sec",
        "unit": "tokens/sec",
        "value": r["decode_tokens_per_sec"],
        "ms_per_tick": r["ms_per_tick"],
        "tp_degree": r["tp_degree"],
        "max_slots": r["max_slots"], "block_size": r["block_size"],
        "context_width": r["context_width"],
        "prompt_len": r["prompt_len"], "dim": r["dim"],
        "layers": r["layers"], "attention": r["attention"],
        "kv_bytes_per_token_per_shard":
            r["kv_bytes_per_token_per_shard"],
        "device": r["device"],
        "environment_note": "forced-2-virtual-cpu-devices (shared host "
                            "cores; correctness/overhead proxy)"
        if forced else None,
        "baseline": None, "vs_baseline": None,
    }


def run_serving_spec_bench_child(max_slots=4, block_size=16, seq_len=256,
                                 dim=256, layers=4, heads=8, vocab=8000,
                                 prompt_len=32, speculative=4,
                                 warmup_ticks=4, timed_ticks=24):
    """The ``transformer_decode_spec`` metric: steady-state ACCEPTED
    tokens/sec through the speculative verify tick vs the plain q_len=1
    tick on the SAME engine shape and a repetitive (draft-friendly)
    workload — the measured answer to "how much does n-gram
    self-drafting buy on a memory-bound decode". Periodic prompts make
    the self-drafter's lookup hit, so the accept rate reflects the
    mechanism, not a random-token worst case. Prints one JSON line."""
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.serve import DecodeEngine

    ffn = 4 * dim
    model = TransformerLM(vocab=vocab, dim=dim, num_layers=layers,
                          num_heads=heads, ffn_hidden=ffn, max_len=seq_len)
    vs = model.init(jax.random.PRNGKey(0),
                    jnp.zeros((1, seq_len), jnp.int32))
    rng = np.random.RandomState(0)
    # periodic prompts: the n-gram drafter exists for exactly this shape
    period = rng.randint(1, vocab, 4)
    prompts = [list(np.tile(period, prompt_len // 4 + 1)[:prompt_len])
               for _ in range(max_slots)]

    def timed(k):
        eng = DecodeEngine(model, vs, max_slots=max_slots,
                           block_size=block_size, speculative=k)
        target = eng.context_width
        for slot in range(max_slots):
            eng.admit(slot, prompts[slot], reserve_len=target)
        for _ in range(warmup_ticks):
            eng.decode_tick()
        tok0 = eng.tokens_generated
        t0 = time.perf_counter()
        for _ in range(timed_ticks):
            eng.decode_tick()
        wall = time.perf_counter() - t0
        toks = eng.tokens_generated - tok0
        return {"tokens": toks, "wall_s": round(wall, 4),
                "tokens_per_sec": round(toks / wall, 2),
                "ms_per_tick": round(wall / timed_ticks * 1e3, 3),
                "draft_accept_rate": round(
                    eng.draft_accepted / eng.draft_proposed, 4)
                if eng.draft_proposed else None,
                "compile_counts": eng.compile_counts()}

    base = timed(0)
    spec = timed(speculative)
    print(json.dumps({
        "child": "transformer_decode_spec",
        "decode_spec_tokens_per_sec": spec["tokens_per_sec"],
        "baseline_tokens_per_sec": base["tokens_per_sec"],
        "speedup": round(spec["tokens_per_sec"]
                         / base["tokens_per_sec"], 3)
        if base["tokens_per_sec"] else None,
        "draft_accept_rate": spec["draft_accept_rate"],
        "speculative": speculative, "max_slots": max_slots,
        "block_size": block_size, "prompt_len": prompt_len,
        "timed_ticks": timed_ticks, "dim": dim, "layers": layers,
        "vocab": vocab, "base": base, "spec": spec,
        "device": jax.devices()[0].device_kind,
    }))


def bench_serving_spec(budget=None):
    """Fresh-subprocess wrapper for run_serving_spec_bench_child."""
    budget = budget or PLANS["transformer_decode_spec"]["budget"]
    r = _spawn_child("transformer_decode_spec", 0, 1, budget)
    return {
        "metric": "transformer_decode_spec_tokens_per_sec",
        "unit": "tokens/sec",
        "value": r["decode_spec_tokens_per_sec"],
        "baseline_tokens_per_sec": r["baseline_tokens_per_sec"],
        "speedup": r["speedup"],
        "draft_accept_rate": r["draft_accept_rate"],
        "speculative": r["speculative"],
        "max_slots": r["max_slots"], "block_size": r["block_size"],
        "prompt_len": r["prompt_len"], "dim": r["dim"],
        "layers": r["layers"], "device": r["device"],
        "baseline": None, "vs_baseline": None,
    }


# ---------------------------------------------------------------------------
# replica cold-start metric (ISSUE 16): TTFT of a FRESH child process,
# cold caches vs populated persistent caches
# ---------------------------------------------------------------------------

def _replica_spawn_once(spec, replica_id, prompt, new_tokens, env):
    """Spawn ONE fresh replica child against ``spec``, drive a single
    request to completion over the stdio transport, and return the
    end-to-end walls (hello = process start -> engine ready, ttft =
    process start -> first completed request) plus the child's own
    ``startup_ms`` breakdown and the generated tokens."""
    from paddle_tpu.serve import transport as tp
    t0 = time.perf_counter()
    proc = tp.spawn_replica_process(dict(spec, replica_id=replica_id),
                                    stderr=subprocess.DEVNULL, env=env)
    trans = tp.ReplicaTransport(proc.stdout, proc.stdin, proc=proc,
                                timeout_s=300.0)
    try:
        hello = trans.request("hello", now=0.0, timeout_s=300.0)
        hello_s = time.perf_counter() - t0
        trans.request("submit", rid=1, prompt=list(prompt),
                      max_new_tokens=new_tokens, now=0.0)
        tokens, ttft_s, load = None, None, {}
        for i in range(16 + 4 * new_tokens):
            rep = trans.request("tick", now=0.05 * (i + 1), timeout_s=120.0)
            load = rep.get("load") or load
            if rep.get("completed"):
                tokens = rep["completed"][0]["tokens"]
                ttft_s = time.perf_counter() - t0
                break
        trans.request("stop", now=9.0)
    finally:
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
    return {"hello_s": hello_s, "ttft_s": ttft_s, "tokens": tokens,
            "startup_ms": hello.get("startup_ms") or {},
            "hello_compile_counts": (hello.get("load") or {}).get(
                "compile_counts"),
            "final_compile_counts": load.get("compile_counts")}


def run_replica_spawn_child(dim=128, layers=2, heads=4, vocab=512,
                            max_len=128, prompt_len=16, new_tokens=4,
                            max_slots=2, block_size=8):
    """The ``replica_spawn`` metric (ISSUE 16): time-to-first-token of a
    FRESH serving child process, cold vs warm. Two spawns share one
    cache root: the first pays the XLA compiles and the autotune trials
    and populates the persistent caches; the second deserializes its
    executables and reads the tuner's stored configs. The delta is the
    cold-start cost the warmup+cache stack removes from autoscaler
    cold-spawns and supervisor replacements — the fleet's effective
    scale-up latency. Children are pinned to CPU: the cold-vs-warm
    ratio is backend-portable, and the remote-TPU (axon) plugin cannot
    execute cache-deserialized executables (the caveat at the top of
    this file), so the persistent cache stays CPU/local-TPU-only.
    Prints one JSON line for the parent."""
    import tempfile
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.serve import fleet as fleet_lib

    model = TransformerLM(vocab=vocab, dim=dim, num_layers=layers,
                          num_heads=heads, ffn_hidden=4 * dim,
                          max_len=max_len)
    vs = model.init(jax.random.PRNGKey(0),
                    jnp.zeros((1, max_len), jnp.int32))
    root = tempfile.mkdtemp(prefix="paddle_tpu_replica_spawn_")
    spec = fleet_lib.build_proc_spec(
        model, vs, root,
        engine_kwargs=dict(max_slots=max_slots, block_size=block_size),
        warmup=True,
        compile_cache_dir=os.path.join(root, "xla-cache"),
        autotune_cache_dir=os.path.join(root, "autotune"))
    env = _force_cpu_devices(os.environ, 1)
    rng = np.random.RandomState(0)
    prompt = list(rng.randint(2, vocab, prompt_len))
    cold = _replica_spawn_once(spec, 0, prompt, new_tokens, env)
    warm = _replica_spawn_once(spec, 1, prompt, new_tokens, env)
    su_c, su_w = cold["startup_ms"], warm["startup_ms"]
    rec = {
        "child": "replica_spawn",
        "cold_ttft_s": round(cold["ttft_s"], 3),
        "warm_ttft_s": round(warm["ttft_s"], 3),
        "cold_hello_s": round(cold["hello_s"], 3),
        "warm_hello_s": round(warm["hello_s"], 3),
        "spawn_speedup": round(cold["ttft_s"] / warm["ttft_s"], 3),
        "cold_startup_ms": su_c, "warm_startup_ms": su_w,
        "cold_autotune_trials": su_c.get("autotune_trials"),
        "warm_autotune_trials": su_w.get("autotune_trials"),
        "cold_autotune_cache_hit": su_c.get("autotune_cache_hit"),
        "warm_autotune_cache_hit": su_w.get("autotune_cache_hit"),
        "cold_xla_cache_hit": su_c.get("xla_cache_hit"),
        "warm_xla_cache_hit": su_w.get("xla_cache_hit"),
        "token_identical": cold["tokens"] == warm["tokens"]
        and cold["tokens"] is not None,
        "cold_compile_counts": cold["final_compile_counts"],
        "warm_compile_counts": warm["final_compile_counts"],
        "hello_compile_counts": warm["hello_compile_counts"],
        "prompt_len": prompt_len, "new_tokens": new_tokens,
        "max_slots": max_slots, "block_size": block_size,
        "dim": dim, "layers": layers, "vocab": vocab,
        "device": "cpu (pinned; see docstring)",
    }
    print(json.dumps(rec))
    return rec


def bench_replica_spawn(budget=None):
    """Fresh-subprocess wrapper for run_replica_spawn_child (one child =
    one tunnel session; that child then spawns the two measured replica
    processes itself)."""
    budget = budget or PLANS["replica_spawn"]["budget"]
    r = _spawn_child("replica_spawn", 0, 1, budget)
    return {
        "metric": "replica_spawn_cold_vs_warm",
        "unit": "x ttft speedup",
        "value": r["spawn_speedup"],
        "cold_ttft_s": r["cold_ttft_s"], "warm_ttft_s": r["warm_ttft_s"],
        "cold_hello_s": r["cold_hello_s"],
        "warm_hello_s": r["warm_hello_s"],
        "cold_startup_ms": r["cold_startup_ms"],
        "warm_startup_ms": r["warm_startup_ms"],
        "warm_autotune_trials": r["warm_autotune_trials"],
        "warm_autotune_cache_hit": r["warm_autotune_cache_hit"],
        "warm_xla_cache_hit": r["warm_xla_cache_hit"],
        "token_identical": r["token_identical"],
        "prompt_len": r["prompt_len"], "new_tokens": r["new_tokens"],
        "dim": r["dim"], "layers": r["layers"],
        "device": r["device"],
        "baseline": None, "vs_baseline": None,
    }


def run_spawn_child():
    """Cold-vs-warm spawn SMOKE GATE (ISSUE 16; tiny config): asserts
    the warmup/cache contract rather than reporting a perf number —
    the cold child runs >= 1 autotune trial and misses both caches, the
    warm child runs ZERO trials and hits both, both children keep
    ``compile_counts == {prefill: 1, tick: 1}`` through real traffic
    (warmup adds no variants), and the two children emit identical
    tokens (warmup + caches are semantically invisible). Prints the
    verdict as one JSON line; exit 0 iff every check holds."""
    r = run_replica_spawn_child(dim=32, layers=1, heads=2, vocab=64,
                                max_len=64, prompt_len=4, new_tokens=2,
                                max_slots=2, block_size=4)
    pinned = {"prefill": 1, "tick": 1}
    checks = {
        "cold_tuned": (r["cold_autotune_trials"] or 0) >= 1,
        "cold_autotune_miss": r["cold_autotune_cache_hit"] is False,
        "cold_xla_miss": r["cold_xla_cache_hit"] is False,
        "warm_zero_trials": r["warm_autotune_trials"] == 0,
        "warm_autotune_hit": r["warm_autotune_cache_hit"] is True,
        "warm_xla_hit": r["warm_xla_cache_hit"] is True,
        "token_identical": r["token_identical"] is True,
        "compile_counts_pinned":
            r["cold_compile_counts"] == pinned
            and r["warm_compile_counts"] == pinned
            and r["hello_compile_counts"] == pinned,
        "warm_faster_hello": r["warm_hello_s"] < r["cold_hello_s"],
    }
    ok = all(checks.values())
    print(json.dumps({
        "child": "spawn_gate", "ok": bool(ok), **checks,
        "cold_ttft_s": r["cold_ttft_s"], "warm_ttft_s": r["warm_ttft_s"],
        "cold_startup_ms": r["cold_startup_ms"],
        "warm_startup_ms": r["warm_startup_ms"],
        "spawn_speedup": r["spawn_speedup"],
    }))
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# bench regression diff (ISSUE 6 satellite): gate perf on the BENCH
# trajectory in CI
# ---------------------------------------------------------------------------

def _bench_rows(doc):
    """Per-metric {value, unit, mfu_pct} rows from any bench record
    shape: the full/sidecar format (``all_metrics``), the compact
    final-line record (``metrics`` rows with v/u/mfu), or the driver's
    committed BENCH_r*.json wrapper (compact record under ``parsed``)."""
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    rows = {}
    for m, r in (doc.get("all_metrics") or {}).items():
        rows[m] = {"value": r.get("value"), "unit": r.get("unit"),
                   "mfu_pct": r.get("mfu_pct")}
    if not rows:
        for m, r in (doc.get("metrics") or {}).items():
            rows[m] = {"value": r.get("v"), "unit": r.get("u"),
                       "mfu_pct": r.get("mfu")}
    if not rows and doc.get("metric"):
        rows[doc["metric"]] = {"value": doc.get("value"),
                               "unit": doc.get("unit"),
                               "mfu_pct": doc.get("mfu_pct")}
    return rows


def compare_bench(old_path, new_path, threshold_pct=5.0):
    """Per-metric regression diff between two bench JSON records
    (``bench.py --compare OLD NEW``). Direction comes from the unit
    (``ms``-denominated metrics: lower is better; rates: higher is
    better); a metric whose value worsened by more than
    ``threshold_pct`` lands in ``regressions`` and the CLI exits
    non-zero, so CI can gate on the BENCH_r* trajectory."""
    with open(old_path) as f:
        old = json.load(f)
    with open(new_path) as f:
        new = json.load(f)
    rows, regressions = _compare_rows(_bench_rows(old), _bench_rows(new),
                                      threshold_pct)
    return {"metric": "bench_compare", "threshold_pct": threshold_pct,
            "old": old_path, "new": new_path, "rows": rows,
            "regressions": regressions, "ok": not regressions}


def _compare_rows(o_rows, n_rows, threshold_pct=5.0):
    """The shared old-vs-new diff behind ``--compare`` (two records)
    and ``--compare-history`` (rolling-median baseline vs one record):
    unit-derived direction, vanished-metric-is-a-regression."""
    rows, regressions = {}, []
    for m in sorted(set(o_rows) | set(n_rows)):
        o, n = o_rows.get(m), n_rows.get(m)
        if o is None:
            rows[m] = {"status": "new", "new": n.get("value")}
            continue
        if n is None:
            rows[m] = {"status": "missing", "old": o.get("value")}
            regressions.append(m)          # a vanished metric IS a regression
            continue
        if not o.get("value") or n.get("value") is None:
            rows[m] = {"status": "incomparable", "old": o.get("value"),
                       "new": n.get("value")}
            continue
        unit = n.get("unit") or o.get("unit") or ""
        lower_better = "ms" in unit
        delta = 100.0 * (n["value"] - o["value"]) / o["value"]
        worsened = (delta > threshold_pct if lower_better
                    else delta < -threshold_pct)
        improved = (delta < -threshold_pct if lower_better
                    else delta > threshold_pct)
        rows[m] = {"old": o["value"], "new": n["value"], "unit": unit,
                   "delta_pct": round(delta, 2),
                   "direction": "lower-better" if lower_better
                   else "higher-better",
                   "status": ("regressed" if worsened
                              else "improved" if improved else "ok")}
        if worsened:
            regressions.append(m)
    return rows, regressions


def append_history(ledger_path, doc):
    """Append one bench record's metric rows to the JSONL perf ledger
    (``bench.py ... --history LEDGER.jsonl``) — the rolling baseline
    ``--compare-history`` gates against. One line per run: timestamp +
    ``{metric: {v, u}}``; any record shape ``_bench_rows`` reads works
    (full, compact, driver wrapper)."""
    rows = _bench_rows(doc)
    rec = {"ts": time.time(),
           "metrics": {m: {"v": r.get("value"), "u": r.get("unit")}
                       for m, r in rows.items()
                       if r.get("value") is not None}}
    with open(ledger_path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec


def history_baseline(ledger_path, window=5):
    """The ledger's rolling baseline: per-metric MEDIAN of the last
    ``window`` entries (median, not mean — one noisy CI run must not
    drag the gate), with each metric's most recent unit."""
    entries = []
    with open(ledger_path) as f:
        for line in f:
            line = line.strip()
            if line:
                entries.append(json.loads(line))
    if not entries:
        raise ValueError(f"empty perf ledger {ledger_path!r}")
    tail = entries[-int(window):]
    rows = {}
    names = sorted({m for e in tail for m in (e.get("metrics") or {})})
    for m in names:
        vals = [e["metrics"][m].get("v") for e in tail
                if m in (e.get("metrics") or {})
                and e["metrics"][m].get("v") is not None]
        if not vals:
            continue
        unit = next((e["metrics"][m].get("u") for e in reversed(tail)
                     if m in (e.get("metrics") or {})), "")
        rows[m] = {"value": float(statistics.median(vals)),
                   "unit": unit, "mfu_pct": None}
    return rows, len(tail)


def compare_history(ledger_path, new_path, threshold_pct=5.0, window=5):
    """The perf-regression sentinel (``bench.py --compare-history
    LEDGER.jsonl NEW.json``): gate NEW against the ledger's rolling
    median-of-last-``window`` baseline with the same direction logic as
    ``--compare``. Nonzero exit on any regression; pass ``--history
    LEDGER.jsonl`` on the same invocation to append NEW to the ledger
    after the verdict (gate first, so a regressing run never pollutes
    its own baseline)."""
    base_rows, n_hist = history_baseline(ledger_path, window=window)
    with open(new_path) as f:
        new = json.load(f)
    rows, regressions = _compare_rows(base_rows, _bench_rows(new),
                                      threshold_pct)
    return {"metric": "bench_compare_history",
            "threshold_pct": threshold_pct, "window": int(window),
            "baseline_entries": n_hist, "ledger": ledger_path,
            "new": new_path, "rows": rows,
            "regressions": regressions, "ok": not regressions}


# ---------------------------------------------------------------------------
# async host pipeline differential (ISSUE 3): overlap-on vs overlap-off
# steps/s through the REAL Trainer host loop (reader -> stager -> window),
# not the harness fori_loop — the serialization under test is the host's.
# ---------------------------------------------------------------------------

def run_pipelined_child(k_steps=8, depth=3, timed_passes=2,
                        groups_per_pass=3, batch_size=8, seq_len=2048,
                        dim=512, layers=6, heads=4, vocab=32000):
    """Train the same batch stream through ``Trainer(steps_per_call=K)``
    with ``pipeline_depth=1`` (serial) and ``pipeline_depth=depth``
    (async host pipeline), timing the post-compile hot loop of each, and
    report the steps/s delta plus the overlap telemetry (stage_ms /
    drain_wait_ms / overlap_frac vs the serial host_stack+shard baseline).
    Prints one JSON line for the parent."""
    from paddle_tpu import optim
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import TransformerLM
    from paddle_tpu.nn import costs
    from paddle_tpu.obs import InMemorySink, Telemetry
    from paddle_tpu.train import Trainer

    ffn = 4 * dim
    rng = np.random.RandomState(0)
    n_batches = groups_per_pass * k_steps
    batches = [{"x": rng.randint(0, vocab, (batch_size, seq_len))
                .astype(np.int32),
                "y": rng.randint(0, vocab, (batch_size, seq_len))
                .astype(np.int32)}
               for _ in range(n_batches)]

    def make(W, telemetry=None):
        tr = Trainer(
            model=TransformerLM(vocab=vocab, dim=dim, num_layers=layers,
                                num_heads=heads, ffn_hidden=ffn,
                                max_len=seq_len, use_flash=True),
            loss_fn=lambda out, b: costs.softmax_cross_entropy(
                out.reshape(-1, vocab), b["y"].reshape(-1)),
            optimizer=optim.adam(1e-4), steps_per_call=k_steps,
            pipeline_depth=W, telemetry=telemetry)
        tr.init(jax.random.PRNGKey(0), batches[0])
        return tr

    def measure(W):
        # fence=False: the serial run must not pay the telemetry fence the
        # pipelined run structurally avoids — both record host timings only
        tel = Telemetry(sinks=[InMemorySink()], health=False, fence=False)
        with use_policy(bfloat16_compute):
            tr = make(W, telemetry=tel)
            tr.train(lambda: iter(batches), num_passes=1,
                     log_period=0)             # compile + warmup pass
            t0 = time.perf_counter()
            tr.train(lambda: iter(batches), num_passes=timed_passes,
                     log_period=0)
            wall = time.perf_counter() - t0
        steps = timed_passes * n_batches
        return steps / wall, tel.summary()

    serial_rate, serial_tel = measure(1)
    pipe_rate, pipe_tel = measure(depth)
    out = {
        "child": "transformer_pipelined",
        "pipelined_steps_per_sec": round(pipe_rate, 4),
        "serial_steps_per_sec": round(serial_rate, 4),
        "pipelined_vs_serial": round(pipe_rate / serial_rate, 4),
        "tokens_per_sec": round(pipe_rate * batch_size * seq_len, 1),
        "pipeline_depth": depth, "k_steps": k_steps,
        "batch_size": batch_size, "seq_len": seq_len, "dim": dim,
        "mean_stage_ms": pipe_tel.get("mean_stage_ms"),
        "mean_drain_wait_ms": pipe_tel.get("mean_drain_wait_ms"),
        "mean_overlap_frac": pipe_tel.get("mean_overlap_frac"),
        "serial_host_stack_plus_shard_ms": round(
            (serial_tel.get("mean_host_stack_ms") or 0.0)
            + (serial_tel.get("mean_shard_ms") or 0.0), 4),
        "device": jax.devices()[0].device_kind,
    }
    print(json.dumps(out))


def bench_pipelined(budget=None):
    """Fresh-subprocess wrapper for run_pipelined_child (one child = one
    tunnel session, like every other metric)."""
    budget = budget or PLANS["transformer_pipelined"]["budget"]
    r = _spawn_child("transformer_pipelined", 0, 1, budget)
    return {
        "metric": "transformer_pipelined_train_steps_per_sec",
        "unit": "steps/sec",
        "value": r["pipelined_steps_per_sec"],
        "serial_steps_per_sec": r["serial_steps_per_sec"],
        "pipelined_vs_serial": r["pipelined_vs_serial"],
        "tokens_per_sec": r["tokens_per_sec"],
        "ms_per_step": round(1e3 / r["pipelined_steps_per_sec"], 2)
        if r["pipelined_steps_per_sec"] else None,
        "mean_stage_ms": r["mean_stage_ms"],
        "mean_drain_wait_ms": r["mean_drain_wait_ms"],
        "mean_overlap_frac": r["mean_overlap_frac"],
        "serial_host_stack_plus_shard_ms":
            r["serial_host_stack_plus_shard_ms"],
        "pipeline_depth": r["pipeline_depth"], "k_steps": r["k_steps"],
        "batch_size": r["batch_size"], "seq_len": r["seq_len"],
        "dim": r["dim"], "device": r["device"],
        "baseline": None, "vs_baseline": None,
    }


# ---------------------------------------------------------------------------
# environment health probe
# ---------------------------------------------------------------------------

def run_probe_child():
    """Measures the tunnel's two failure axes (experiments/PERF.md
    "Incident"): transfer bandwidth/latency and buffer residency across a
    device->host fetch. Prints one JSON line."""
    out = {}
    t0 = time.perf_counter()
    x = jax.device_put(np.ones((8,), np.float32))
    _ = jax.device_get(x)
    out["small_roundtrip_s"] = round(time.perf_counter() - t0, 3)

    state = jax.device_put(np.zeros((25_000_000,), np.float32))   # 100 MB

    @jax.jit
    def stepf(s):
        return s * 1.000001 + 0.000001

    s = stepf(state)                       # compile
    t0 = time.perf_counter()
    for _ in range(20):
        s = stepf(s)
    pre = (time.perf_counter() - t0) / 20
    _ = float(jax.device_get(s[0]))        # the poison trigger, if any
    t0 = time.perf_counter()
    for _ in range(20):
        s = stepf(s)
    post = (time.perf_counter() - t0) / 20
    out["chained_100mb_ms_per_step_prefetch"] = round(pre * 1e3, 3)
    out["chained_100mb_ms_per_step_postfetch"] = round(post * 1e3, 3)
    t0 = time.perf_counter()
    _ = jax.device_get(s)
    out["get_100mb_s"] = round(time.perf_counter() - t0, 2)
    out["device"] = jax.devices()[0].device_kind
    # green = buffers stay device-resident after a fetch (the non-resident
    # mode costs ~1 ms/MB => ~100 ms/step here; threshold 10 ms is 50x the
    # healthy reading with margin).
    resident = post < 10e-3
    out["verdict"] = "green" if resident else "red"
    if not resident:
        out["reason"] = ("non-resident mode: chained dispatch pays per-MB "
                         "transfer after a fetch; throughput numbers from "
                         "this session understate the framework")
    print(json.dumps(out))


def probe_environment(budget=600):
    repo = os.path.dirname(os.path.abspath(__file__))
    cmd = [sys.executable, os.path.join(repo, "bench.py"), "--probe", "1"]
    try:
        res = subprocess.run(cmd, capture_output=True, text=True, cwd=repo,
                             timeout=budget)
        if res.returncode != 0:
            return {"verdict": "red",
                    "reason": f"probe failed rc={res.returncode}: "
                              f"{res.stderr[-400:]}"}
        return json.loads(res.stdout.strip().splitlines()[-1])
    except subprocess.TimeoutExpired:
        return {"verdict": "red", "reason": f"probe timeout after {budget}s"}


# ---------------------------------------------------------------------------
# scaling probe (unchanged protocol: virtual-CPU-mesh proxy, run explicitly;
# the analytic ICI projection lives in experiments/scaling_projection.py and
# SCALING_r05.json)
# ---------------------------------------------------------------------------

def bench_scaling(per_device_batch=32, iters=2, steps_per_call=4):
    """Throughput vs device count at fixed per-device batch — the third
    north-star metric (reference anchor: 3.85x at 4 GPUs,
    ``benchmark/README.md:70-93``).

    With one real chip (the normal driver environment) this re-launches
    itself on a virtual 8-device CPU mesh — a correctness/overhead proxy
    (virtual devices share host cores, so absolute efficiency is
    pessimistic), clearly labelled in ``environment``. On a real multi-chip
    slice it runs in place over ICI.
    """
    import paddle_tpu as pt
    from paddle_tpu.core.dtypes import bfloat16_compute, use_policy
    from paddle_tpu.models import resnet_cifar

    devices = jax.devices()
    if len(devices) < 8:
        # re-launch on the virtual CPU mesh (env must be set pre-jax-import)
        env = _force_cpu_devices(os.environ, 8)
        repo = os.path.dirname(os.path.abspath(__file__))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        code = ("import jax; jax.config.update('jax_platforms','cpu'); "
                "import bench; import json; "
                f"print(json.dumps(bench.bench_scaling({per_device_batch},"
                f"{iters},{steps_per_call})))")
        res = subprocess.run([sys.executable, "-c", code], cwd=repo, env=env,
                             capture_output=True, text=True, timeout=1500)
        if res.returncode != 0:
            return {"metric": "scaling_efficiency",
                    "error": res.stderr[-2000:]}
        return json.loads(res.stdout.strip().splitlines()[-1])

    counts = [n for n in (1, 2, 4, 8) if n <= len(devices)]
    throughput = {}
    for n in counts:
        mesh = pt.make_mesh({"data": n}, devices=devices[:n])
        bs = per_device_batch * n
        trainer, batch = _build_resnet_trainer(
            bs, model=resnet_cifar(depth_n=2), image=32, classes=10)
        trainer.mesh = mesh
        with use_policy(bfloat16_compute):
            step_body, state = _trainer_step_body(trainer, batch)
            stepc = jax.jit(step_body, donate_argnums=0)
            state = stepc(state)
            _fence(state[-1])      # warmup must not leak into the window
            iters_n = max(2, iters * steps_per_call // 2)
            t0 = time.perf_counter()
            for _ in range(iters_n):
                state = stepc(state)
            _fence(state[-1])
            dt = (time.perf_counter() - t0) / iters_n
        throughput[n] = bs / dt
    base = throughput[counts[0]]
    eff = {str(n): round(throughput[n] / (n * base), 3) for n in counts}
    platform = jax.devices()[0].platform
    return {
        "metric": "scaling_efficiency",
        "value": eff[str(counts[-1])],
        "unit": f"fraction of linear at {counts[-1]} devices",
        "vs_baseline": round(
            (eff[str(4)] if "4" in eff else eff[str(counts[-1])]) /
            (3.85 / 4), 2),   # reference: 3.85x at 4 GPUs
        "throughput_img_s": {str(n): round(t, 1)
                             for n, t in throughput.items()},
        "efficiency_vs_linear": eff,
        "per_device_batch": per_device_batch,
        "model": "resnet_cifar(depth_n=2) bs/device=%d" % per_device_batch,
        "environment": ("real-%s-mesh" % platform) if platform == "tpu"
                       else "virtual-cpu-mesh (correctness/overhead proxy; "
                            "virtual devices share host cores)",
        "n_devices": counts[-1],
    }


# ---------------------------------------------------------------------------
# driver entry
# ---------------------------------------------------------------------------

# Default plan: every north-star metric. The scaling probe is NOT in the
# default plan: with one real chip it runs on the virtual-CPU mesh and its
# CPU compiles cost ~20 min — run it explicitly (`--metric scaling`); the
# committed artifacts are SCALING_r05.json (proxy + analytic projection).
DEFAULT_PLAN = ["resnet50", "seq2seq", "transformer", "transformer_fused",
                "transformer_dp_overlap", "transformer_pipelined",
                "transformer_decode", "transformer_decode_int8",
                "transformer_decode_spec", "transformer_decode_tp",
                "replica_spawn",
                "transformer_big", "lstm", "lstm_h256", "lstm_h1280"]


_KNOWN_FLAGS = ("--metric", "--child", "--probe", "--n", "--k",
                "--timed-steps", "--steps-per-call", "--smoke",
                "--attribution-child", "--overlap-child",
                "--serving-child", "--faults-child", "--fleet-child",
                "--spawn-child",
                "--compare",
                "--threshold",
                "--history", "--compare-history", "--window")


def main():
    args = sys.argv[1:]

    def flag(name, default=None, cast=str):
        # accepts both "--name value" and "--name=value"
        for i, a in enumerate(args):
            if a == name and i + 1 < len(args):
                return cast(args[i + 1])
            if a.startswith(name + "="):
                return cast(a.split("=", 1)[1])
        return default

    unknown = [a for a in args if a.startswith("--")
               and a.split("=", 1)[0] not in _KNOWN_FLAGS]
    if unknown:
        print(json.dumps({"error": f"unknown flags {unknown}; "
                                   f"known: {list(_KNOWN_FLAGS)}"}))
        sys.exit(2)

    def maybe_append_history(doc):
        # --history LEDGER.jsonl on any measuring run: append this
        # run's metric rows to the rolling perf ledger (ISSUE 19)
        hist = flag("--history")
        if hist:
            try:
                append_history(hist, doc)
            except OSError as e:
                sys.stderr.write(f"history append failed: {e}\n")

    if "--compare" in args:
        # bench.py --compare OLD.json NEW.json [--threshold PCT]
        i = args.index("--compare")
        if len(args) < i + 3 or args[i + 1].startswith("--") \
                or args[i + 2].startswith("--"):
            print(json.dumps({"error": "--compare needs OLD.json NEW.json"}))
            sys.exit(2)
        try:
            out = compare_bench(args[i + 1], args[i + 2],
                                flag("--threshold", 5.0, float))
        except (OSError, ValueError) as e:
            print(json.dumps({"metric": "bench_compare",
                              "error": f"{type(e).__name__}: {e}"}))
            sys.exit(2)
        print(json.dumps(out))
        sys.exit(0 if out["ok"] else 1)

    if "--compare-history" in args:
        # bench.py --compare-history LEDGER.jsonl NEW.json
        #          [--threshold PCT] [--window K] [--history LEDGER]
        # the perf-regression sentinel: NEW vs the ledger's rolling
        # median-of-last-K baseline; exit 1 on regression. --history
        # appends NEW to the ledger AFTER the verdict (a regressing run
        # never pollutes its own baseline).
        i = args.index("--compare-history")
        if len(args) < i + 3 or args[i + 1].startswith("--") \
                or args[i + 2].startswith("--"):
            print(json.dumps({"error": "--compare-history needs "
                                       "LEDGER.jsonl NEW.json"}))
            sys.exit(2)
        try:
            out = compare_history(args[i + 1], args[i + 2],
                                  flag("--threshold", 5.0, float),
                                  flag("--window", 5, int))
            hist = flag("--history")
            if hist:
                with open(args[i + 2]) as f:
                    append_history(hist, json.load(f))
        except (OSError, ValueError, KeyError) as e:
            print(json.dumps({"metric": "bench_compare_history",
                              "error": f"{type(e).__name__}: {e}"}))
            sys.exit(2)
        print(json.dumps(out))
        sys.exit(0 if out["ok"] else 1)

    if flag("--attribution-child", cast=int):
        sys.exit(run_attribution_child())

    if flag("--overlap-child", cast=int):
        sys.exit(run_overlap_child())

    if flag("--serving-child", cast=int):
        sys.exit(run_serving_child())

    if flag("--faults-child", cast=int):
        sys.exit(run_faults_child())

    if flag("--fleet-child", cast=int):
        sys.exit(run_fleet_child())

    if flag("--spawn-child", cast=int):
        sys.exit(run_spawn_child())

    if "--smoke" in args or flag("--smoke", cast=int):
        # CPU mode: the gate must be deterministic and CI-runnable — on any
        # other backend re-launch pinned to CPU (JAX_PLATFORMS must be set
        # before jax initializes, hence the subprocess).
        if jax.default_backend() != "cpu":
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            repo = os.path.dirname(os.path.abspath(__file__))
            res = subprocess.run(
                [sys.executable, os.path.join(repo, "bench.py"), "--smoke"],
                cwd=repo, env=env, capture_output=True, text=True,
                timeout=900)
            sys.stdout.write(res.stdout.strip().splitlines()[-1] + "\n"
                             if res.stdout.strip() else res.stderr[-500:])
            sys.exit(res.returncode)
        sys.exit(run_smoke())

    if flag("--probe", cast=int):
        run_probe_child()
        return

    metric = flag("--metric")
    if metric == "all":                 # legacy alias for the full plan
        metric = None
    if flag("--child", cast=int):
        if metric == "transformer_pipelined":
            run_pipelined_child()
        elif metric == "transformer_decode":
            run_serving_bench_child()
        elif metric == "transformer_decode_int8":
            run_serving_bench_child(kv_dtype="int8")
        elif metric == "transformer_decode_spec":
            run_serving_spec_bench_child()
        elif metric == "transformer_decode_tp":
            run_serving_tp_bench_child()
        elif metric == "replica_spawn":
            run_replica_spawn_child()
        else:
            run_timed_child(metric, flag("--timed-steps", 100, int),
                            flag("--steps-per-call", 1, int))
        return

    if metric == "scaling":
        print(json.dumps(bench_scaling()))
        return
    if metric in ("transformer_pipelined", "transformer_decode",
                  "transformer_decode_int8", "transformer_decode_spec",
                  "transformer_decode_tp", "replica_spawn"):
        try:
            out = (bench_pipelined() if metric == "transformer_pipelined"
                   else bench_serving() if metric == "transformer_decode"
                   else bench_serving(kv_dtype="int8")
                   if metric == "transformer_decode_int8"
                   else bench_serving_tp()
                   if metric == "transformer_decode_tp"
                   else bench_replica_spawn()
                   if metric == "replica_spawn"
                   else bench_serving_spec())
        except (RuntimeError, subprocess.TimeoutExpired, ValueError,
                IndexError, KeyError) as e:
            print(json.dumps({"metric": metric, "error": str(e)[-800:],
                              "environment": probe_environment()}))
            sys.exit(1)
        out["environment"] = probe_environment()
        print(json.dumps(out))
        maybe_append_history(out)
        return
    if metric is not None and metric not in PREPS:
        print(json.dumps(
            {"error": f"unknown metric {metric!r}; choose from "
                      f"{sorted(PREPS) + ['scaling', 'transformer_pipelined', 'transformer_decode', 'transformer_decode_int8', 'transformer_decode_spec', 'transformer_decode_tp', 'replica_spawn']}"
             }))
        sys.exit(2)
    if metric in PREPS:
        try:
            out = bench_differential(metric, n=flag("--n", None, int),
                                     k=flag("--k", None, int))
        except (RuntimeError, subprocess.TimeoutExpired, ValueError,
                IndexError) as e:
            # the one-JSON-line contract holds even when the child dies
            print(json.dumps({"metric": metric, "error": str(e)[-800:],
                              "environment": probe_environment()}))
            sys.exit(1)
        out["environment"] = probe_environment()
        print(json.dumps(out))
        maybe_append_history(out)
        return

    # Full driver run: health probe first, then every metric, each via the
    # interleaved-differential child (one subprocess per metric) with one
    # retry.
    environment = probe_environment()
    results, errors = {}, {}
    for name in DEFAULT_PLAN:
        for attempt in (1, 2):
            try:
                if name == "transformer_pipelined":
                    results[name] = bench_pipelined()
                elif name == "transformer_decode":
                    results[name] = bench_serving()
                elif name == "transformer_decode_int8":
                    # own child protocol — bench_differential would ask
                    # the serving child for per_step_s it never prints
                    results[name] = bench_serving(kv_dtype="int8")
                elif name == "transformer_decode_spec":
                    results[name] = bench_serving_spec()
                elif name == "transformer_decode_tp":
                    results[name] = bench_serving_tp()
                elif name == "replica_spawn":
                    results[name] = bench_replica_spawn()
                else:
                    results[name] = bench_differential(name)
                errors.pop(name, None)
                break
            except (RuntimeError, subprocess.TimeoutExpired,
                    ValueError, IndexError, KeyError) as e:
                errors[name] = f"attempt {attempt}: {e}"
    headline = dict(results.get("resnet50", {}))
    full = {**headline,
            "environment": environment,
            "all_metrics": {r["metric"]: r for r in results.values()
                            if "metric" in r}}
    # ISSUE 2: the telemetry gate's summary (step breakdown, retrace count,
    # est. MFU) rides every full BENCH_* snapshot going forward. Runs in
    # the pinned-CPU smoke subprocess; a failure is recorded, not fatal.
    try:
        repo = os.path.dirname(os.path.abspath(__file__))
        res = subprocess.run(
            [sys.executable, os.path.join(repo, "bench.py"), "--smoke"],
            cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=900)
        smoke = json.loads(res.stdout.strip().splitlines()[-1])
        full["telemetry_smoke"] = smoke.get("telemetry",
                                            {"error": "no telemetry block"})
    except (subprocess.TimeoutExpired, ValueError, IndexError,
            OSError) as e:
        full["telemetry_smoke"] = {"error": str(e)[-300:]}
    if errors:
        full["bench_errors"] = errors
    # Full protocol detail goes to a committed sidecar and is printed BEFORE
    # the final line; the FINAL stdout line is a compact record that must fit
    # the driver's 2,000-char tail capture (round 4 lost its headline numbers
    # to truncation — VERDICT r4 weak #1).
    sidecar = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           SIDECAR_NAME)
    sidecar_ok = True
    try:
        with open(sidecar, "w") as f:
            json.dump(full, f, indent=1)
    except OSError:
        sidecar_ok = False
    print(json.dumps(full))
    print(json.dumps(compact_record(results, errors, environment,
                                    sidecar_ok=sidecar_ok)))
    maybe_append_history(full)


SIDECAR_NAME = "BENCH_FULL_r05.json"


def compact_record(results, errors, environment, cap=1500, sidecar_ok=True):
    """Final-line record: headline at top level (driver contract: metric/
    value/unit/vs_baseline) plus one short row per metric. Hard-capped at
    ``cap`` chars by progressively dropping optional detail."""
    rows = {}
    for r in results.values():
        if "metric" not in r:
            continue
        row = {"v": r.get("value"), "u": r.get("unit"),
               "ms": r.get("ms_per_step")}
        if r.get("mfu_pct") is not None:
            row["mfu"] = r["mfu_pct"]
        if r.get("vs_baseline") is not None:
            row["vs"] = r["vs_baseline"]
        if r.get("final_loss") is not None:
            row["loss"] = r["final_loss"]
        if r.get("loss_floor") is not None:
            row["floor"] = r["loss_floor"]
        rows[r["metric"]] = row
    head = results.get("resnet50", {})
    out = {"metric": head.get("metric"), "value": head.get("value"),
           "unit": head.get("unit"), "vs_baseline": head.get("vs_baseline"),
           "ms_per_step": head.get("ms_per_step"),
           "mfu_pct": head.get("mfu_pct"),
           "env": environment.get("verdict"),
           "device": head.get("device"),
           "full_record": SIDECAR_NAME if sidecar_ok else None,
           "metrics": rows}
    if errors:
        out["errors"] = {k: str(v)[-100:] for k, v in errors.items()}
    # degrade to fit: each stage strips one tier of optional detail; the
    # last two guarantee the cap no matter how many metrics/errors exist
    for strip in ("loss", "vs", "errors", "rows",
                  "drop_errors", "drop_metrics"):
        if len(json.dumps(out)) <= cap:
            return out
        if strip == "errors":
            out["errors"] = {k: str(v)[-40:] for k, v in errors.items()}
        elif strip == "rows":
            out["metrics"] = {m: {"v": r["v"], "u": r["u"]}
                              for m, r in rows.items()}
        elif strip == "drop_errors":
            out.pop("errors", None)
        elif strip == "drop_metrics":
            out["metrics"] = {}
        else:
            for r in rows.values():
                r.pop(strip, None)
                if strip == "loss":
                    r.pop("floor", None)
    return out


if __name__ == "__main__":
    main()
