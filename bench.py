"""Benchmark harness — prints ONE JSON line for the driver.

Mirrors the reference's benchmark protocol (``/root/reference/benchmark/
paddle/image/run.sh``: fixed batch size, warmup, timed batches, img/s). Current
flagship metric: MNIST-LeNet training images/sec on one chip (placeholder until
the ResNet-50 milestone lands; baseline anchor is the reference's ResNet-50
CPU number in BASELINE.md until then).
"""

import json
import time

import numpy as np
import jax
import jax.numpy as jnp


def bench_lenet(batch_size=128, warmup=5, iters=30):
    import paddle_tpu as pt
    from paddle_tpu import optim
    from paddle_tpu.models import LeNet
    from paddle_tpu.nn import costs
    from paddle_tpu.train import Trainer

    trainer = Trainer(
        model=LeNet(),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.momentum(0.01, 0.9))
    rng = np.random.RandomState(0)
    batch = {
        "x": rng.normal(size=(batch_size, 28, 28, 1)).astype(np.float32),
        "label": rng.randint(0, 10, size=batch_size).astype(np.int32),
    }
    trainer.init(jax.random.PRNGKey(0), batch)
    trainer._build_train_step()
    ts = trainer.train_state
    sharded = trainer._shard(batch)
    key = jax.random.PRNGKey(1)
    params, state, opt_state, step = ts.params, ts.state, ts.opt_state, ts.step
    for _ in range(warmup):
        params, state, opt_state, step, loss, stats = trainer._train_step(
            params, state, opt_state, step, sharded, key)
    jax.block_until_ready(params)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, state, opt_state, step, loss, stats = trainer._train_step(
            params, state, opt_state, step, sharded, key)
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0
    return batch_size * iters / dt


def main():
    img_s = bench_lenet()
    # Anchor: no in-tree MNIST-LeNet throughput number exists in the reference;
    # vs_baseline compares against the reference's strongest CPU ResNet-50
    # figure (82.35 img/s, BASELINE.md) only as a sanity scale until the
    # ResNet-50 benchmark replaces this metric.
    print(json.dumps({
        "metric": "mnist_lenet_train_images_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / 82.35, 2),
    }))


if __name__ == "__main__":
    main()
