"""Shared training routine for the real two-process jax.distributed test.

Imported both by the pytest parent (single-process oracle over its local
8-device CPU mesh) and by ``_multiproc_worker.py`` (two cooperating
processes, 4 forced CPU devices each, global 8-device mesh) — the same
function must produce bitwise-comparable losses either way, the
local-vs-remote oracle of the reference's ``test_CompareSparse.cpp:144``.
"""

from __future__ import annotations

import numpy as np


def run_training(mesh, ckpt_dir=None, steps=4, batch=64, dim=16, classes=10):
    """Deterministic tiny-MLP data-parallel training over ``mesh``.

    Returns {"losses": [...], "final_loss": float, "ckpt_loaded_ok": bool}.
    Uses jax.make_array_from_callback for batches so the identical code
    works single-process and multi-controller.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu import optim
    from paddle_tpu.core.module import Sequential
    from paddle_tpu.nn import costs
    from paddle_tpu.nn.layers import Linear
    from paddle_tpu.optim.optimizers import apply_updates

    model = Sequential(Linear(32, act="relu", name="fc1"),
                       Linear(classes, name="fc2"), name="mlp")
    rng = np.random.RandomState(0)
    X = rng.normal(size=(steps, batch, dim)).astype(np.float32)
    Y = rng.randint(0, classes, size=(steps, batch)).astype(np.int32)

    repl = NamedSharding(mesh, P())
    data_sh = NamedSharding(mesh, P("data"))

    def put(host, sharding):
        host = np.asarray(host)
        return jax.make_array_from_callback(host.shape, sharding,
                                            lambda idx: host[idx])

    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, dim)))
    params = jax.tree_util.tree_map(
        lambda x: put(np.asarray(x), repl), variables["params"])
    opt = optim.momentum(0.1, 0.9)
    opt_state = jax.tree_util.tree_map(
        lambda x: put(np.asarray(x), repl), opt.init(variables["params"]))

    @jax.jit
    def step_fn(params, opt_state, sno, x, y):
        def loss_fn(p):
            out = model.apply({"params": p}, x)
            return jnp.mean(costs.softmax_cross_entropy(out, y))
        loss, g = jax.value_and_grad(loss_fn)(params)
        upd, new_opt = opt.update(g, opt_state, params, sno)
        return apply_updates(params, upd), new_opt, loss

    losses = []
    for i in range(steps):
        x = put(X[i], data_sh)
        y = put(Y[i], data_sh)
        params, opt_state, loss = step_fn(params, opt_state,
                                          jnp.asarray(i), x, y)
        losses.append(float(loss))       # replicated scalar: safe everywhere

    result = {"losses": losses, "final_loss": losses[-1],
              "ckpt_loaded_ok": None}

    if ckpt_dir is not None:
        from jax.experimental import multihost_utils
        from paddle_tpu.train import checkpoint as ckpt_lib

        host_params = jax.tree_util.tree_map(np.asarray, params)
        ckpt_lib.save_checkpoint(ckpt_dir, 0, {"params": host_params,
                                               "step": np.asarray(steps)})
        if jax.process_count() > 1:
            multihost_utils.sync_global_devices("ckpt_written")
        loaded = ckpt_lib.load_checkpoint(ckpt_dir, 0)
        ok = True
        for a, b in zip(jax.tree_util.tree_leaves(loaded["params"]),
                        jax.tree_util.tree_leaves(host_params)):
            ok = ok and np.allclose(a, b)
        result["ckpt_loaded_ok"] = bool(ok)
    return result
