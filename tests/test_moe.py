"""Mixture-of-experts FFN: routing correctness, learnability, and
expert-parallel equivalence over the ``expert`` mesh axis."""

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.nn.moe import MoEFFN, moe_sharding_rules
from paddle_tpu.parallel import ShardingRules, shard_tree


def test_moe_single_expert_equals_dense_ffn():
    """With one expert and ample capacity, MoE is exactly a dense FFN scaled
    by the (softmax-of-one = 1) gate."""
    moe = MoEFFN(num_experts=1, hidden=16, capacity_factor=2.0)
    x = jnp.asarray(np.random.RandomState(0).normal(
        size=(2, 8, 4)).astype(np.float32))
    p = moe.init(jax.random.PRNGKey(0), x)
    out = moe.apply(p, x)
    tree = p["params"][next(iter(p["params"]))]
    h = jax.nn.gelu(jnp.einsum("btd,dh->bth", x, tree["w1"][0]) + tree["b1"][0])
    want = jnp.einsum("bth,hd->btd", h, tree["w2"][0]) + tree["b2"][0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_moe_capacity_drops_overflow():
    """Tokens past an expert's capacity contribute zero output."""
    moe = MoEFFN(num_experts=2, hidden=8, capacity_factor=0.25)
    # 1 * 8 tokens, E=2, C = ceil(8/2*0.25) = 1: at most 1 token per expert
    x = jnp.ones((1, 8, 4))
    p = moe.init(jax.random.PRNGKey(0), x)
    out = np.asarray(moe.apply(p, x))
    # identical tokens route identically; only the first per expert is kept
    nonzero_rows = (np.abs(out[0]).sum(-1) > 1e-9).sum()
    assert nonzero_rows <= 2


def test_moe_learns_expert_specialization():
    """Two token populations needing opposite transforms: a 2-expert MoE must
    fit both (a single linear map cannot), and routing must separate them."""
    rng = np.random.RandomState(0)
    D = 8

    def batch():
        kind = rng.randint(0, 2, (4, 16))
        base = rng.normal(size=(4, 16, D)).astype(np.float32)
        # population 0 wants y = +x ; population 1 wants y = -x, and the
        # population is marked in the first feature
        base[..., 0] = np.where(kind, 3.0, -3.0)
        y = np.where(kind[..., None], -base, base).astype(np.float32)
        return jnp.asarray(base), jnp.asarray(y)

    moe = MoEFFN(num_experts=2, hidden=32, capacity_factor=2.0, act="tanh")
    x0, _ = batch()
    p = moe.init(jax.random.PRNGKey(0), x0)["params"]
    from paddle_tpu.optim.optimizers import adam
    opt = adam(3e-3)
    st = opt.init(p)

    @jax.jit
    def step(p, st, sno, x, y):
        def loss_fn(p):
            out, aux = moe.apply({"params": p}, x, return_aux=True)
            return jnp.mean((out - y) ** 2) + 0.01 * aux
        loss, g = jax.value_and_grad(loss_fn)(p)
        p, st = opt.apply(g, st, p, sno)
        return loss, p, st

    first = None
    for i in range(400):
        x, y = batch()
        loss, p, st = step(p, st, jnp.asarray(i), x, y)
        if first is None:
            first = float(loss)
    assert float(loss) < 0.3 * first, (first, float(loss))


def test_moe_expert_sharded_matches_replicated():
    """Sharding the expert weights over an ``expert`` mesh axis must not
    change the math (XLA inserts the collectives)."""
    mesh = pt.make_mesh({"data": 2, "expert": 4})
    moe = MoEFFN(num_experts=4, hidden=16, capacity_factor=2.0)
    x = jnp.asarray(np.random.RandomState(1).normal(
        size=(4, 8, 8)).astype(np.float32))
    variables = moe.init(jax.random.PRNGKey(0), x)
    want = np.asarray(moe.apply(variables, x))

    rules = ShardingRules(moe_sharding_rules("expert"))
    with mesh:
        sharded = shard_tree(mesh, variables["params"], rules(variables["params"]))
        got = jax.jit(lambda p, x: moe.apply({"params": p}, x))(sharded, x)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_topk_dense_oracle(nprng):
    """top-2 routing with ample capacity == dense weighted mixture of the
    two chosen experts' FFNs per token."""
    import math

    from paddle_tpu.nn import activations

    B, T, D, E, H = 2, 6, 8, 4, 16
    x = jnp.asarray(nprng.normal(size=(B, T, D)).astype(np.float32))
    moe = MoEFFN(E, H, capacity_factor=8.0, top_k=2, renormalize=True)
    vs = moe.init(jax.random.PRNGKey(0), x)
    out, aux, stats = moe.apply(vs, x, return_aux=True, return_stats=True)
    assert float(stats["drop_rate"]) == 0.0     # ample capacity

    p = next(iter(vs["params"].values()))
    xf = np.asarray(x).reshape(-1, D)
    probs = np.asarray(jax.nn.softmax(xf @ np.asarray(p["wg"]), axis=-1))
    gelu = activations.get("gelu")
    want = np.zeros_like(xf)
    for n in range(xf.shape[0]):
        idx = np.argsort(-probs[n])[:2]
        g = probs[n][idx]
        g = g / g.sum()
        for e, ge in zip(idx, g):
            h = np.asarray(gelu(jnp.asarray(
                xf[n] @ np.asarray(p["w1"][e]) + np.asarray(p["b1"][e]))))
            want[n] += ge * (h @ np.asarray(p["w2"][e])
                             + np.asarray(p["b2"][e]))
    np.testing.assert_allclose(np.asarray(out).reshape(-1, D), want,
                               rtol=2e-4, atol=2e-5)


def test_topk1_matches_legacy_top1(nprng):
    """top_k=1 must reproduce the original Switch top-1 path bit-for-bit in
    routing decisions (same params, same dispatch)."""
    B, T, D, E, H = 2, 8, 8, 4, 16
    x = jnp.asarray(nprng.normal(size=(B, T, D)).astype(np.float32))
    m1 = MoEFFN(E, H, capacity_factor=1.25, top_k=1)
    vs = m1.init(jax.random.PRNGKey(0), x)
    out1 = m1.apply(vs, x)
    # a second instance with identical params and the same k
    m2 = MoEFFN(E, H, capacity_factor=1.25, top_k=1, renormalize=False)
    out2 = m2.apply(vs, x)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               rtol=1e-6, atol=1e-7)


def test_drop_rate_reported_under_pressure(nprng):
    """With capacity_factor << 1 the layer must report a nonzero drop rate
    instead of silently zeroing tokens (VERDICT r2 weak 6)."""
    B, T, D, E, H = 2, 32, 8, 4, 8
    x = jnp.asarray(nprng.normal(size=(B, T, D)).astype(np.float32))
    moe = MoEFFN(E, H, capacity_factor=0.25, top_k=2)
    vs = moe.init(jax.random.PRNGKey(0), x)
    out, stats = moe.apply(vs, x, return_stats=True)
    assert float(stats["drop_rate"]) > 0.0
    assert np.isclose(float(jnp.sum(stats["expert_fraction"])), 1.0)
