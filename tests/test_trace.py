"""Structured tracing + anomaly flight recorder (ISSUE 4): Chrome Trace
Event emission (thread-aware spans, flow linking, ring bound), the traced
pipelined Trainer run, every anomaly-detector trigger kind (one bundle
each, off-by-default none), the stager-leak close() contract, and the
fill-thread spans in data.buffered."""

import json
import logging
import os
import threading
import time

import numpy as np
import jax
import pytest

from paddle_tpu import optim
from paddle_tpu.data import reader as data
from paddle_tpu.models import MnistMLP
from paddle_tpu.nn import costs
from paddle_tpu.obs import (AnomalyDetector, InMemorySink, Telemetry,
                            Tracer, tspan)
from paddle_tpu.obs.anomaly import Verdict
from paddle_tpu.train import Trainer
from paddle_tpu.train.host_pipeline import GroupStager

BS, DIM = 16, 12


def make_batches(n, bs=BS, dim=DIM, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.normal(size=(bs, dim)).astype(np.float32),
             "label": rng.randint(0, 4, size=bs).astype(np.int32)}
            for _ in range(n)]


def make_trainer(K=2, M=2, **kw):
    return Trainer(
        model=MnistMLP(num_classes=4, hidden=(8,)),
        loss_fn=lambda out, b: costs.softmax_cross_entropy(out, b["label"]),
        optimizer=optim.adam(1e-3),
        steps_per_call=K, grad_accum=M, **kw)


def step_rec(step, *, wall=10.0, retrace=0, drain=None, mem=None,
             nonfinite=0, loss=0.5):
    """A synthetic telemetry step record with a controllable wall time."""
    return {"kind": "step", "ts": time.time(), "step": step, "k_steps": 1,
            "m": 1, "loss": loss, "host_stack_ms": None, "shard_ms": wall / 2,
            "dispatch_ms": wall / 2, "device_ms": None, "replay_ms": None,
            "drain_wait_ms": drain, "bytes_in_use": mem,
            "retrace_count": retrace, "nonfinite_count": nonfinite}


# ---------------------------------------------------------------------------
# Tracer: Chrome Trace Event format
# ---------------------------------------------------------------------------

def test_tracer_spans_flows_and_chrome_format(tmp_path):
    tracer = Tracer()
    fid = tracer.new_flow()
    with tracer.span("stage", flow_start=fid, group=0):
        time.sleep(0.001)

    def other_thread():
        with tracer.span("dispatch", flow_step=fid):
            time.sleep(0.001)

    t = threading.Thread(target=other_thread, name="worker")
    t.start()
    t.join()
    with tracer.span("drain", flow_end=fid):
        pass
    tracer.instant("marker", step=3)

    path = tracer.save(str(tmp_path / "trace.json"))
    doc = json.load(open(path))                   # valid JSON by parse
    evs = doc["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"stage", "dispatch", "drain"}
    assert len({e["tid"] for e in xs}) == 2       # two threads recorded
    # every span has a positive duration and args survived
    assert all(e["dur"] > 0 for e in xs)
    assert [e for e in xs if e["name"] == "stage"][0]["args"]["group"] == 0
    # flow events: s/t/f share the id; the "f" binds to its enclosing slice
    flows = {e["ph"]: e for e in evs if e.get("cat") == "flow"}
    assert set(flows) == {"s", "t", "f"}
    assert len({e["id"] for e in flows.values()}) == 1
    assert flows["f"]["bp"] == "e"
    # thread metadata names both threads; instant marker present
    names = [e for e in evs if e.get("ph") == "M"
             and e["name"] == "thread_name"]
    assert len(names) == 2
    assert any(e["ph"] == "i" and e["name"] == "marker" for e in evs)
    # serialized traceEvents are timestamp-sorted (the bench gate's rule)
    ts = [e.get("ts", -1.0) for e in evs]
    assert ts == sorted(ts)


def test_tracer_ring_bound_and_tspan_null():
    tracer = Tracer(max_events=10)
    for i in range(50):
        with tracer.span("s", i=i):
            pass
    evs = [e for e in tracer.events() if e["ph"] == "X"]
    assert len(evs) == 10                         # ring kept the tail
    assert evs[-1]["args"]["i"] == 49
    assert tracer.dropped_events == 40
    # tspan with tracer=None is a shared no-op context
    with tspan(None, "anything", junk=1) as v:
        assert v is None


def test_tracer_concurrent_span_emission():
    """Spans finishing on many threads concurrently must all land (the
    lock contract the stager/fill threads rely on)."""
    tracer = Tracer()

    def worker(n):
        for i in range(50):
            with tracer.span("w", n=n):
                pass

    threads = [threading.Thread(target=worker, args=(n,)) for n in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    xs = [e for e in tracer.events() if e["ph"] == "X"]
    assert len(xs) == 200


# ---------------------------------------------------------------------------
# traced pipelined Trainer run
# ---------------------------------------------------------------------------

def test_traced_pipelined_run_two_threads_flows_pair(tmp_path):
    """pipeline_depth=2 with a tracer: staging spans come from the stager
    thread, dispatch/drain spans from the main thread, and every staging
    flow pairs with a drain flow."""
    tracer = Tracer()
    tel = Telemetry(sinks=[InMemorySink()])
    tr = make_trainer(telemetry=tel, tracer=tracer, pipeline_depth=2)
    batches = make_batches(2 * 2 * 3)
    tr.init(jax.random.PRNGKey(0), batches[0])
    tr.train(lambda: iter(batches), num_passes=1, log_period=0)
    evs = tracer.events()
    xs = [e for e in evs if e["ph"] == "X"]
    by_name = {}
    for e in xs:
        by_name.setdefault(e["name"], []).append(e)
    for required in ("stage", "stack", "shard", "dispatch", "drain",
                     "drain_wait", "events_replay"):
        assert required in by_name, f"no {required!r} spans"
    stage_tids = {e["tid"] for e in by_name["stage"]}
    main_tids = {e["tid"] for e in by_name["dispatch"]}
    assert stage_tids and main_tids and not (stage_tids & main_tids)
    s_ids = {e["id"] for e in evs if e.get("ph") == "s"}
    f_ids = {e["id"] for e in evs if e.get("ph") == "f"}
    assert s_ids and s_ids == f_ids               # every flow pairs up
    # the whole document serializes as valid Chrome trace JSON
    tracer.save(str(tmp_path / "t.json"))
    json.load(open(str(tmp_path / "t.json")))


def test_tracer_off_is_byte_identical_params_and_dispatches():
    """ISSUE 4 acceptance: tracer=None, anomaly=None is the pre-PR-4 hot
    loop — same dispatch count and bit-identical params vs a fully
    instrumented run (tracing/anomaly must not perturb the math)."""
    batches = make_batches(2 * 2 * 3)

    def run(**kw):
        tr = make_trainer(**kw)
        tr.init(jax.random.PRNGKey(0), batches[0])
        calls = {"n": 0}
        orig = tr._dispatch_fused

        def counting(stacked, rng, **k):
            calls["n"] += 1
            return orig(stacked, rng, **k)

        tr._dispatch_fused = counting
        tr.train(lambda: iter(batches), num_passes=1, log_period=0)
        return tr, calls["n"]

    tr_off, n_off = run(telemetry=None)
    import tempfile
    tr_on, n_on = run(
        telemetry=Telemetry(sinks=[InMemorySink()]), tracer=Tracer(),
        anomaly=AnomalyDetector(out_dir=tempfile.mkdtemp()))
    assert n_on == n_off
    for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(
                tr_off.train_state.params)),
            jax.tree_util.tree_leaves(jax.device_get(
                tr_on.train_state.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_anomaly_without_telemetry_rejected():
    with pytest.raises(ValueError, match="telemetry"):
        make_trainer(anomaly=AnomalyDetector(out_dir="/tmp/x"))


# ---------------------------------------------------------------------------
# anomaly detector: every trigger kind, one bundle each
# ---------------------------------------------------------------------------

def _bundle_dirs(root):
    return sorted(d for d in os.listdir(root) if d.startswith("anomaly_"))


def test_anomaly_slow_step_outlier(tmp_path):
    det = AnomalyDetector(out_dir=str(tmp_path), warmup=8)
    for i in range(20):
        assert det.observe(step_rec(i, wall=10.0 + 0.01 * i)) == []
    v = det.observe(step_rec(99, wall=500.0))     # 50x the median
    assert [x.kind for x in v] == ["slow_step"]
    det.observe(step_rec(100, wall=500.0))        # one-shot: no 2nd bundle
    assert _bundle_dirs(str(tmp_path)) == ["anomaly_000_slow_step"]
    verdict = json.load(open(
        tmp_path / "anomaly_000_slow_step" / "verdict.json"))
    assert verdict["verdict"]["kind"] == "slow_step"
    assert verdict["trigger_record"]["step"] == 99


def test_anomaly_retrace_burst(tmp_path):
    det = AnomalyDetector(out_dir=str(tmp_path), retrace_burst=3)
    for i in range(5):
        assert det.observe(step_rec(i, retrace=0)) == []
    for i, rc in enumerate((1, 2, 2)):
        det.observe(step_rec(5 + i, retrace=rc))
    v = det.observe(step_rec(9, retrace=3))       # +3 within the window
    assert [x.kind for x in v] == ["retrace_burst"]
    assert _bundle_dirs(str(tmp_path)) == ["anomaly_000_retrace_burst"]


def test_anomaly_drain_stall_and_memory(tmp_path):
    det = AnomalyDetector(out_dir=str(tmp_path), drain_stall_ms=100.0,
                          memory_frac=0.9, memory_bytes_limit=1000)
    for i in range(4):                        # baseline: healthy ~50ms drains
        assert det.observe(step_rec(i, drain=50.0, mem=500)) == []
    # above the floor but only 2.4x the median: a big healthy group, not a
    # stall (the device-bound steady state drains ~group time every call)
    assert det.observe(step_rec(4, drain=120.0)) == []
    v = det.observe(step_rec(5, drain=400.0))   # floor AND >3x median
    assert [x.kind for x in v] == ["drain_stall"]
    v = det.observe(step_rec(6, mem=950))
    assert [x.kind for x in v] == ["memory_high_water"]
    assert _bundle_dirs(str(tmp_path)) == [
        "anomaly_000_drain_stall", "anomaly_001_memory_high_water"]


def test_anomaly_nonfinite_and_ring_content(tmp_path):
    det = AnomalyDetector(out_dir=str(tmp_path), ring_size=4)
    for i in range(6):
        det.observe(step_rec(i))
    v = det.observe(step_rec(6, nonfinite=3, loss=None))
    assert [x.kind for x in v] == ["nonfinite"]
    bundle = tmp_path / "anomaly_000_nonfinite"
    ring = [json.loads(l) for l in
            open(bundle / "telemetry_ring.jsonl") if l.strip()]
    assert len(ring) == 4                          # bounded ring
    assert ring[-1]["step"] == 6                   # trigger record included
    # healthy records never trigger; nothing else fired
    assert _bundle_dirs(str(tmp_path)) == ["anomaly_000_nonfinite"]


def test_anomaly_staged_wall_excludes_stager_time(tmp_path):
    """Stager-staged records (stage_ms present) measure host_stack/shard
    on the STAGER thread (hidden cost); the slow-step wall must count
    only dispatch + drain_wait there — a hidden staging spike is not a
    slow step. A genuinely exposed drain stall still is."""
    det = AnomalyDetector(out_dir=str(tmp_path), warmup=8)

    def staged(step, shard=1.0, drain=10.0):
        r = step_rec(step, wall=2.0, drain=drain)   # dispatch_ms = 1.0
        r["shard_ms"], r["host_stack_ms"], r["stage_ms"] = shard, 1.0, 2.0
        return r

    for i in range(16):
        assert det.observe(staged(i)) == []
    assert det.observe(staged(99, shard=800.0)) == []   # hidden: no verdict
    assert _bundle_dirs(str(tmp_path)) == []
    stall = staged(100, drain=500.0)                    # exposed: real
    assert [v.kind for v in det.observe(stall)] == ["slow_step"]


def test_anomaly_plain_deferred_wall_counts_main_thread_shard(tmp_path):
    """The plain deferred-fetch loop (drain_wait_ms set, NO stage_ms)
    shards on the MAIN thread — a device_put spike there is critical-path
    and must still trigger slow_step."""
    det = AnomalyDetector(out_dir=str(tmp_path), warmup=8)
    for i in range(16):
        r = step_rec(i, wall=2.0, drain=1.0)
        assert det.observe(r) == []
    spike = step_rec(99, wall=2.0, drain=1.0)
    spike["shard_ms"] = 500.0                  # main-thread device_put stall
    assert [v.kind for v in det.observe(spike)] == ["slow_step"]


def test_anomaly_profiled_record_skipped(tmp_path):
    """An anomaly-armed profiler capture fences inside its dispatch window
    — that record must not feed slow_step (the flight recorder must not
    trigger the detector that armed it)."""
    det = AnomalyDetector(out_dir=str(tmp_path), warmup=8)
    for i in range(16):
        det.observe(step_rec(i))
    prof = step_rec(99, wall=5000.0)
    prof["profiled"] = True
    assert det.observe(prof) == []
    assert _bundle_dirs(str(tmp_path)) == []


def test_tracer_tail_zero_and_profile_window_lazy(tmp_path):
    tracer = Tracer()
    with tracer.span("a"):
        pass
    assert [e for e in tracer.tail(0) if e["ph"] == "X"] == []
    assert len([e for e in tracer.tail(5) if e["ph"] == "X"]) == 1
    # profile_window is lazy: constructing it must record nothing (and
    # must not start the device profiler) until `with` entry
    cm = tracer.profile_window(str(tmp_path / "prof"))
    assert len([e for e in tracer.events() if e["ph"] == "X"]) == 1
    with cm:
        pass
    spans = [e for e in tracer.events() if e["ph"] == "X"]
    assert [e["name"] for e in spans].count("jax_profile") == 1


def test_anomaly_profiler_arming(tmp_path):
    det = AnomalyDetector(out_dir=str(tmp_path), arm_profiler=True)
    assert det.take_profiler_request() is None
    det.observe(step_rec(0, nonfinite=1))
    req = det.take_profiler_request()
    assert req is not None and req.startswith(str(tmp_path))
    assert det.take_profiler_request() is None     # one-shot pop


def test_anomaly_injected_nan_run_leaves_one_bundle(tmp_path):
    """ISSUE 4 acceptance: an injected-NaN pipelined run leaves exactly ONE
    forensics bundle on disk with the nonfinite verdict, the telemetry
    ring, the config snapshot, and the trace tail."""
    out = str(tmp_path / "forensics")
    os.makedirs(out)
    tracer = Tracer()
    tel = Telemetry(sinks=[InMemorySink()])
    tr = make_trainer(K=2, M=1, telemetry=tel, tracer=tracer,
                      anomaly=AnomalyDetector(out_dir=out),
                      pipeline_depth=2)
    batches = make_batches(8)
    batches[4]["x"][0, 0] = np.nan
    tr.init(jax.random.PRNGKey(0), batches[0])
    tr.train(lambda: iter(batches), num_passes=1, log_period=0)
    assert _bundle_dirs(out) == ["anomaly_000_nonfinite"]
    bundle = os.path.join(out, "anomaly_000_nonfinite")
    assert sorted(os.listdir(bundle)) == [
        "snapshot.json", "telemetry_ring.jsonl", "trace_tail.json",
        "verdict.json"]
    snap = json.load(open(os.path.join(bundle, "snapshot.json")))
    assert snap["steps_per_call"] == 2 and snap["pipeline_depth"] == 2
    assert snap["model"] == "MnistMLP" and "mesh_axes" in snap
    tail = json.load(open(os.path.join(bundle, "trace_tail.json")))
    assert any(e.get("ph") == "X" for e in tail["traceEvents"])


def test_anomaly_bundle_written_even_when_nan_check_raises(tmp_path):
    """Fused mode + nan_check=True: the FloatingPointError trap unwinds
    the replay, but the flight recorder must still have written its
    nonfinite bundle first — a poisoned run is exactly when the
    forensics matter (the plain loop observes before raising; fused must
    match)."""
    out = str(tmp_path / "forensics")
    os.makedirs(out)
    tr = make_trainer(K=2, M=2, telemetry=Telemetry(sinks=[InMemorySink()]),
                      anomaly=AnomalyDetector(out_dir=out), nan_check=True)
    batches = make_batches(8)
    batches[2]["x"][0, 0] = np.nan
    tr.init(jax.random.PRNGKey(0), batches[0])
    with pytest.raises(FloatingPointError, match="non-finite loss"):
        tr.train(lambda: iter(batches), num_passes=1, log_period=0)
    assert _bundle_dirs(out) == ["anomaly_000_nonfinite"]


def test_nan_check_error_not_masked_by_raising_handler(tmp_path):
    """A handler that raises on TelemetryRecord during the nan_check
    unwind must not mask the original FloatingPointError (whose message
    carries the nonfinite-leaves postmortem)."""
    out = str(tmp_path / "forensics")
    os.makedirs(out)
    tr = make_trainer(K=2, M=2, telemetry=Telemetry(sinks=[InMemorySink()]),
                      anomaly=AnomalyDetector(out_dir=out), nan_check=True)
    batches = make_batches(8)
    batches[2]["x"][0, 0] = np.nan
    tr.init(jax.random.PRNGKey(0), batches[0])

    def bad_handler(e):
        if type(e).__name__ == "TelemetryRecord":
            raise RuntimeError("handler bug")

    with pytest.raises(FloatingPointError, match="non-finite loss"):
        tr.train(lambda: iter(batches), num_passes=1, log_period=0,
                 event_handler=bad_handler)
    # the healthy path still propagates handler bugs (no silent eating)
    tr2 = make_trainer(K=2, M=2, telemetry=Telemetry(sinks=[InMemorySink()]))
    clean = make_batches(4)
    tr2.init(jax.random.PRNGKey(0), clean[0])
    with pytest.raises(RuntimeError, match="handler bug"):
        tr2.train(lambda: iter(clean), num_passes=1, log_period=0,
                  event_handler=bad_handler)


def test_plain_loop_profiler_arming(tmp_path, monkeypatch):
    """arm_profiler must capture in the plain (K=1, M=1) loop too, not
    only the fused path — every dispatch path polls the armed request."""
    import contextlib
    from paddle_tpu.obs import trace as trace_mod
    captured = []

    @contextlib.contextmanager
    def fake_profile(log_dir):
        captured.append(log_dir)
        yield

    monkeypatch.setattr(trace_mod, "jax_profile", fake_profile)
    tel = Telemetry(sinks=[InMemorySink()])
    tr = make_trainer(K=1, M=1, telemetry=tel,
                      anomaly=AnomalyDetector(out_dir=str(tmp_path),
                                              arm_profiler=True))
    batches = make_batches(6)
    batches[2]["x"][0, 0] = np.nan          # trigger at record 2
    tr.init(jax.random.PRNGKey(0), batches[0])
    tr.train(lambda: iter(batches), num_passes=1, log_period=0)
    assert len(captured) == 1               # next dispatch was captured
    assert captured[0].endswith("jax_profile")
    recs = tel.sinks[0].by_kind("step")
    assert [r["profiled"] for r in recs].count(True) == 1


def test_no_anomaly_attached_no_bundles(tmp_path):
    """Off by default: the same poisoned run without a detector writes
    nothing anywhere."""
    before = set(os.listdir(str(tmp_path)))
    tr = make_trainer(K=2, M=1, telemetry=Telemetry(sinks=[InMemorySink()]))
    batches = make_batches(4)
    batches[2]["x"][0, 0] = np.nan
    tr.init(jax.random.PRNGKey(0), batches[0])
    tr.train(lambda: iter(batches), num_passes=1, log_period=0)
    assert set(os.listdir(str(tmp_path))) == before


def test_anomaly_detector_crash_never_kills_training(tmp_path, caplog):
    class Boom(AnomalyDetector):
        def observe(self, rec):
            raise RuntimeError("detector died")

    tr = make_trainer(telemetry=Telemetry(sinks=[InMemorySink()]),
                      anomaly=Boom(out_dir=str(tmp_path)))
    batches = make_batches(2 * 2 * 2)
    tr.init(jax.random.PRNGKey(0), batches[0])
    with caplog.at_level(logging.ERROR, logger="paddle_tpu.trainer"):
        tr.train(lambda: iter(batches), num_passes=1, log_period=0)
    assert "anomaly detector failed" in caplog.text


def test_anomaly_reset_rearms(tmp_path):
    det = AnomalyDetector(out_dir=str(tmp_path))
    det.observe(step_rec(0, nonfinite=1))
    det.observe(step_rec(1, nonfinite=1))
    assert len(det.bundles) == 1
    det.reset()
    det.observe(step_rec(2, nonfinite=1))
    assert len(det.bundles) == 2


def test_anomaly_rearm_true_fires_every_onset(tmp_path):
    """ISSUE 6 satellite: rearm=True makes every trigger of the same kind
    dump its own bundle — no reset() needed between onsets — and the
    bundle sequence numbers stay distinct."""
    det = AnomalyDetector(out_dir=str(tmp_path), rearm=True)
    det.observe(step_rec(0, nonfinite=1))
    det.observe(step_rec(1, nonfinite=1))
    det.observe(step_rec(2, nonfinite=1))
    assert len(det.bundles) == 3
    assert len(set(det.bundles)) == 3
    assert [v.kind for v in det.verdicts] == ["nonfinite"] * 3
    # the default (rearm=False) under the identical stream fires once
    det2 = AnomalyDetector(out_dir=str(tmp_path / "oneshot"))
    for i in range(3):
        det2.observe(step_rec(i, nonfinite=1))
    assert len(det2.bundles) == 1


def test_anomaly_reset_clears_one_shot_and_rolling_state(tmp_path):
    """ISSUE 6 satellite: reset() re-arms every kind AND clears the
    rolling windows + any pending armed-profiler request; bundles on
    disk stay."""
    det = AnomalyDetector(out_dir=str(tmp_path), arm_profiler=True)
    for i in range(6):
        det.observe(step_rec(i, wall=10.0))
    assert len(det._walls) == 6
    det.observe(step_rec(6, nonfinite=1))
    assert det._fired == {"nonfinite"}
    assert det._profiler_request is not None       # armed by the trigger
    bundles_before = list(det.bundles)
    det.reset()
    assert det._fired == set()
    assert det.take_profiler_request() is None     # request cleared
    assert len(det._walls) == 0 and len(det._ring) == 0
    assert det.bundles == bundles_before           # evidence persists
    det.observe(step_rec(7, nonfinite=1))          # fires again post-reset
    assert len(det.bundles) == len(bundles_before) + 1


# ---------------------------------------------------------------------------
# stager-leak close() contract (ISSUE 4 satellite)
# ---------------------------------------------------------------------------

def test_group_stager_close_flags_stuck_thread(caplog):
    release = threading.Event()

    def wedge(work):
        release.wait(20.0)                        # simulates a wedged put
        return work

    stager = GroupStager(wedge, join_timeout=0.3)
    stager.submit(("work", 0, False))
    time.sleep(0.05)                              # let the worker pick it up
    with caplog.at_level(logging.WARNING,
                         logger="paddle_tpu.host_pipeline"):
        leaked = stager.close()
    assert leaked is True
    assert "did not exit" in caplog.text
    assert "paddle_tpu.host_pipeline.stager" in caplog.text
    release.set()                                 # let the thread die (and
    stager._thread.join(timeout=5.0)              # don't leak it into later
    assert not stager._thread.is_alive()          # tests' thread scans)

    clean = GroupStager(lambda w: w, join_timeout=5.0)
    assert clean.close() is False


def test_stager_leak_surfaces_in_telemetry_summary(monkeypatch):
    tel = Telemetry(sinks=[InMemorySink()])
    tr = make_trainer(telemetry=tel, pipeline_depth=2)
    batches = make_batches(2 * 2 * 2)
    tr.init(jax.random.PRNGKey(0), batches[0])
    orig_close, stagers = GroupStager.close, []

    def fake_close(self):
        stagers.append(self)
        return True                               # report "missed deadline"

    monkeypatch.setattr(GroupStager, "close", fake_close)
    try:
        tr.train(lambda: iter(batches), num_passes=1, log_period=0)
        assert tel.summary()["stager_leaked"] is True
        # and the close-time summary record carries the flag into the JSONL
        tel.close()
        summaries = tel.sinks[0].by_kind("summary")
        assert len(summaries) == 1 and summaries[0]["stager_leaked"] is True
    finally:
        for s in stagers:                         # actually stop the thread
            orig_close(s)                         # (don't leak it into
    assert all(not s._thread.is_alive() for s in stagers)  # later tests)


# ---------------------------------------------------------------------------
# data.buffered fill-thread spans (ISSUE 4 satellite)
# ---------------------------------------------------------------------------

def test_buffered_fill_thread_spans():
    tracer = Tracer()

    def src():
        yield from range(5)

    out = list(data.buffered(src, 2, tracer=tracer)())
    assert out == [0, 1, 2, 3, 4]
    fills = [e for e in tracer.events()
             if e["ph"] == "X" and e["name"] == "data.fill"]
    assert len(fills) >= 5                        # one span per item (+ end)
    assert {e["tid"] for e in fills} != {threading.get_ident()}
    names = {e["args"]["name"] for e in tracer.events()
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert "paddle_tpu.data.buffered.fill" in names
