"""A REAL two-process ``jax.distributed`` bring-up test — VERDICT r2 item 5.

The reference tests its distributed tier with real in-process servers
(``gserver/tests/test_CompareSparse.cpp:64-72`` spins ParameterServer2 on
localhost ports) and real etcd (``go/pserver/client_test.go``). The TPU-native
analog: spawn two actual OS processes, each contributing 4 virtual CPU
devices, joined through ``parallel.multihost.initialize`` (a localhost
coordinator), train data-parallel over the global 8-device mesh, and require
the losses to equal a single-process 8-device run of the same code —
plus single-writer/all-readers checkpoint behavior across the processes.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt

_HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_distributed_matches_single_process(tmp_path):
    port = _free_port()
    nproc = 2
    outs = [str(tmp_path / f"out{i}.json") for i in range(nproc)]
    ckpt_dir = str(tmp_path / "ckpt")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(_HERE)] + env.get("PYTHONPATH", "").split(os.pathsep))
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(_HERE, "_multiproc_worker.py"),
             "--coordinator", f"localhost:{port}",
             "--num-processes", str(nproc), "--process-id", str(i),
             "--ckpt-dir", ckpt_dir, "--out", outs[i]],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(nproc)
    ]
    logs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        logs.append(out.decode(errors="replace"))
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"worker failed:\n{log[-4000:]}"

    results = []
    for o in outs:
        with open(o) as f:
            results.append(json.load(f))

    # both processes saw the global topology
    for r in results:
        assert r["process_count"] == nproc
        assert r["local_devices"] == 4
        assert r["ckpt_loaded_ok"] is True   # all-readers works
    assert {r["process_id"] for r in results} == {0, 1}

    # replicated loss is identical across processes
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=0, atol=0)

    # the checkpoint was written exactly once (single writer, process 0)
    from paddle_tpu.train import checkpoint as ckpt_lib
    assert ckpt_lib.latest_pass(ckpt_dir) == 0

    # and the two-process run equals this process's single-process 8-device
    # oracle (the local-vs-remote comparison of test_CompareSparse.cpp:144)
    sys.path.insert(0, _HERE)
    from _multiproc_common import run_training
    oracle = run_training(pt.make_mesh({"data": 8}))
    np.testing.assert_allclose(results[0]["losses"], oracle["losses"],
                               rtol=1e-6, atol=1e-7)
